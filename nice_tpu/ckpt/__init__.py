"""Crash-safe field-scan checkpointing.

snapshot.py — the on-disk format (versioned, CRC-guarded, atomic-rename
manifest + payload files); manager.py — the per-field lifecycle (plan
signature validation, resume-state packing, startup resume scan). The engine
knows nothing about files: it takes a checkpoint_cb and a resume state
(ops/engine.py); this package is where those become durable.
"""

from nice_tpu.ckpt.manager import (
    FieldCheckpointer,
    find_resumable,
    plan_signature,
)
from nice_tpu.ckpt.snapshot import (
    FORMAT_VERSION,
    SnapshotError,
    read_snapshot,
    write_snapshot,
)

__all__ = [
    "FORMAT_VERSION",
    "FieldCheckpointer",
    "SnapshotError",
    "find_resumable",
    "plan_signature",
    "read_snapshot",
    "write_snapshot",
]
