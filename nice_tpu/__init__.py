"""nice-tpu: a TPU-native distributed search framework for square-cube pandigitals.

A brand-new framework with the capabilities of wasabipesto/nice: the per-number
niceness check (big-int square+cube, base-b digit extraction, digit-set
uniqueness, filter cascade) is a batched fixed-width integer JAX/Pallas kernel,
vmapped over a whole field range and sharded across TPU chips, beside the same
checkout -> process -> submit control plane (HTTP API, field ledger DB, claim
queues, submission verification, consensus).

Layer map (mirrors reference SURVEY.md section 1):
  L0 core/      domain types, base-range math, stats, consensus
  L1 ops/       compute engines: scalar oracle, jnp vector engine, Pallas TPU
                kernels, filter cascade (residue / LSD / stride / MSD-prefix)
  L2 client/    HTTP transport with retry/backoff
  L3 server/    field ledger DB + claim engine + queues
  L4 client/server/jobs/daemon binaries
  parallel/     device mesh, collectives, host pipeline
"""

__version__ = "0.1.0"

CLIENT_VERSION = __version__
