from nice_tpu.client.main import main

if __name__ == "__main__":
    raise SystemExit(main())
