"""nice-tpu search client CLI.

The L4 binary: claim -> process -> submit against a coordination server, plus
offline --benchmark and --validate modes. Mirrors the reference CLI surface
(client/src/main.rs:64-116): every option is also settable via a NICE_* env
var, CLI > env > default.

Run modes (reference client/src/main.rs:295-562):
  * single iteration: claim, process, submit
  * --repeat: 3-stage pipeline — claim N+1 and submit N-1 overlap processing N
  * --benchmark <mode>: offline timing on the built-in benchmark fields
  * --validate: fetch a double-checked field + canonical results from the
    server and diff a local recomputation against them
"""

from __future__ import annotations

import argparse
import hashlib
import json
import logging
import os
import sys
import time
from typing import Optional

from nice_tpu import CLIENT_VERSION, ckpt, obs
from nice_tpu.client import api_client
from nice_tpu.faults import spool as spool_mod
from nice_tpu.obs.series import (
    CKPT_RENEWALS,
    CLIENT_FIELD_SECONDS,
    CLIENT_FIELDS,
    CLIENT_NUMBERS,
)
from nice_tpu.core import number_stats
from nice_tpu.core.benchmark import BenchmarkMode, get_benchmark_field
from nice_tpu.core.types import (
    DataToClient,
    DataToServer,
    FieldResults,
    SearchMode,
)
from nice_tpu.ops import engine
from nice_tpu.ops.stride_filter import get_stride_table
from nice_tpu.utils import fsio, knobs, lockdep

log = logging.getLogger("nice_tpu.client")

DEFAULT_LSD_K_VALUE = 2  # reference client/src/main.rs:19


def _env(name: str, default):
    return os.environ.get(f"NICE_{name}", default)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="nice-tpu-client",
        description="Distributed search client for square-cube pandigitals (TPU-native)",
    )
    p.add_argument(
        "mode",
        nargs="?",
        default=_env("MODE", "detailed"),
        choices=["detailed", "niceonly"],
        help="search mode (env NICE_MODE)",
    )
    p.add_argument(
        "--api-base",
        default=_env("API_BASE", "https://api.nicenumbers.net"),
        help="API base URL; may be a comma-separated list for failover "
        "(env NICE_API_BASE)",
    )
    p.add_argument(
        "--servers",
        default=knobs.SERVERS.get(),
        help="additional comma-separated server endpoints merged into "
        "--api-base for multi-server failover (env NICE_TPU_SERVERS)",
    )
    p.add_argument(
        "--username",
        default=_env("USERNAME", "anonymous"),
        help="username credited with submissions (env NICE_USERNAME)",
    )
    p.add_argument(
        "--max-retries",
        type=int,
        default=int(_env("MAX_RETRIES", 10)),
        help="HTTP retry ceiling (env NICE_MAX_RETRIES)",
    )
    p.add_argument(
        "--repeat",
        action="store_true",
        default=bool(int(_env("REPEAT", 0))),
        help="run forever with the 3-stage pipeline (env NICE_REPEAT)",
    )
    p.add_argument(
        "--backend",
        default=_env("BACKEND", "jax"),
        choices=["jax", "jnp", "pallas", "native", "scalar"],
        help="compute backend: jax auto-selects Pallas kernels on TPU; "
        "native is the multithreaded C++ host engine (env NICE_BACKEND)",
    )
    p.add_argument(
        "--batch-size",
        type=lambda v: int(v) or None,
        default=int(_env("BATCH_SIZE", 0)) or None,
        help="device lanes per dispatch; 0 = resolved by the autotuner "
        "(tuned winners table, falling back to "
        f"{engine.DEFAULT_BATCH_SIZE}) (env NICE_BATCH_SIZE)",
    )
    p.add_argument(
        "--threads",
        type=int,
        default=int(_env("THREADS", 0)),
        help="host threads for the native backend; 0 = all cores "
        "(env NICE_THREADS; reference client/src/main.rs:64-116)",
    )
    p.add_argument(
        "--progress-secs",
        type=float,
        default=float(_env("PROGRESS_SECS", 5)),
        help="seconds between in-field progress lines; 0 disables "
        "(env NICE_PROGRESS_SECS)",
    )
    p.add_argument(
        "--checkpoint-dir",
        default=_env("CHECKPOINT_DIR", None),
        help="directory for crash-safe field-scan snapshots; enables "
        "periodic checkpointing and auto-resume of an interrupted claim on "
        "startup (env NICE_CHECKPOINT_DIR)",
    )
    p.add_argument(
        "--spool-dir",
        default=_env("SPOOL_DIR", None),
        help="directory journaling submissions whose HTTP retries were "
        "exhausted, for replay at the next loop iteration / startup; "
        "defaults to <checkpoint-dir>/spool when checkpointing is on "
        "(env NICE_SPOOL_DIR)",
    )
    p.add_argument(
        "--checkpoint-secs",
        type=float,
        default=float(_env("CHECKPOINT_SECS", 30)),
        help="seconds between snapshots while scanning (env "
        "NICE_CHECKPOINT_SECS; a batch-count trigger also fires every "
        "NICE_TPU_CKPT_BATCHES dispatches)",
    )
    p.add_argument(
        "--claim-block",
        type=int,
        default=knobs.CLAIM_BLOCK.get(),
        help="fields per claim round-trip: >1 claims through the block-lease "
        "endpoints (/claim_block, /submit_block) with ONE lease covering the "
        "whole block; 1 = per-field compatibility path. Falls back to "
        "per-field automatically against servers without block support "
        "(env NICE_TPU_CLAIM_BLOCK)",
    )
    p.add_argument(
        "--tenants",
        default=knobs.TENANTS.raw(),
        help="run the multi-tenant scheduler instead of the single-workload "
        "loop: semicolon-separated name:mode:base[:opt...] tenant specs "
        "(opts prio=N, slo=SECS, bases=LO-HI, batch=N, backend=NAME; modes "
        "also near-miss / hi-base) — see README 'Multi-tenant scheduling' "
        "(env NICE_TPU_TENANTS)",
    )
    p.add_argument(
        "--renew-secs",
        type=float,
        default=float(_env("RENEW_SECS", 900)),
        help="seconds between claim-lease renewal heartbeats to "
        "/renew_claim; 0 disables (env NICE_RENEW_SECS)",
    )
    p.add_argument(
        "--telemetry-secs",
        type=float,
        default=float(_env("TELEMETRY_SECS", 60)),
        help="seconds between fleet-telemetry heartbeats to /telemetry "
        "(throughput, backend mix, downgrades, spool depth); 0 disables "
        "(env NICE_TELEMETRY_SECS)",
    )
    p.add_argument(
        "--benchmark",
        default=_env("BENCHMARK", None),
        choices=[m.value for m in BenchmarkMode],
        help="run an offline benchmark field instead of the server loop "
        "(env NICE_BENCHMARK)",
    )
    p.add_argument(
        "--validate",
        action="store_true",
        help="self-check against a canonical double-checked field",
    )
    p.add_argument(
        "--base",
        type=int,
        default=None,
        help="restrict --validate to a specific base",
    )
    p.add_argument(
        "--log-level",
        default=_env("LOG_LEVEL", "info"),
        choices=["trace", "debug", "info", "warn", "error"],
        help="log verbosity (env NICE_LOG_LEVEL)",
    )
    return p


def _progress_logger(every_secs: float):
    """Throttled in-field progress callback: % done, live n/s, ETA (the
    reference's tqdm progress bar, client/src/main.rs:183-196, as log lines
    — adaptive-unit rendering without a TTY dependency). Thread-safe: the
    engine may call it from a pipeline worker thread."""
    if not every_secs or every_secs <= 0:
        return None
    t0 = time.monotonic()
    state = {"last": t0}
    lock = lockdep.make_lock("client.main.progress_cb.lock")

    def cb(done: int, total: int) -> None:
        now = time.monotonic()
        with lock:
            if now - state["last"] < every_secs or done <= 0 or done >= total:
                return
            state["last"] = now
        rate = done / max(now - t0, 1e-9)
        eta = (total - done) / rate if rate > 0 else float("inf")
        log.info(
            "progress %5.1f%% (%s / %s) %s numbers/sec, ETA %.0fs",
            100.0 * done / total, f"{done:,}", f"{total:,}", f"{rate:,.0f}", eta,
        )

    return cb


def process_field(
    data: DataToClient, mode: SearchMode, backend: str, batch_size: int | None,
    progress_secs: float = 0.0, *,
    checkpointer=None, resume=None, checkpoint_secs=None,
) -> tuple[FieldResults, float]:
    """Process one field, returning results and elapsed seconds, logging the
    reference's throughput line (client/src/main.rs:361-371).

    checkpointer: optional ckpt.FieldCheckpointer whose save() becomes the
    engine's checkpoint_cb; resume: a validated state from its load() (or
    find_resumable) to continue from instead of restarting the scan."""
    if mode == SearchMode.DETAILED:
        # Pre-build this base's batch executables OUTSIDE the measured
        # window; after the first field per (base, batch, backend) this is a
        # pure executable-cache hit.
        engine.warm_detailed(data.base, batch_size=batch_size, backend=backend)
    t0 = time.monotonic()
    rng = data.to_field_size()
    progress = _progress_logger(progress_secs)
    checkpoint_cb = checkpointer.save if checkpointer is not None else None
    mode_label = "detailed" if mode == SearchMode.DETAILED else "niceonly"
    with obs.span(
        "client.process_field", base=data.base, size=data.range_size,
        mode=mode_label, backend=backend,
    ), obs.profiler("process_field"):
        if mode == SearchMode.DETAILED:
            results = engine.process_range_detailed(
                rng, data.base, backend=backend, batch_size=batch_size,
                progress=progress, checkpoint_cb=checkpoint_cb,
                resume=resume, checkpoint_secs=checkpoint_secs,
            )
        else:
            stride = get_stride_table(data.base, DEFAULT_LSD_K_VALUE)
            results = engine.process_range_niceonly(
                rng, data.base, stride_table=stride, backend=backend,
                batch_size=batch_size, progress=progress,
                checkpoint_cb=checkpoint_cb, resume=resume,
                checkpoint_secs=checkpoint_secs,
            )
    elapsed = time.monotonic() - t0
    CLIENT_FIELD_SECONDS.labels(mode_label).observe(elapsed)
    CLIENT_FIELDS.labels(mode_label).inc()
    CLIENT_NUMBERS.inc(data.range_size)
    # Critical-path stamp: this field's stepprof phase breakdown, keyed to
    # its claim so the server folds h2d_feed/device_compute/readback into
    # the field's waterfall. Only when the profiler ran (NICE_TPU_STEPPROF=1
    # — off means no breakdown exists and the waterfall reports that time
    # as unaccounted rather than inventing segments).
    if obs.stepprof.enabled():
        lb = dict(obs.stepprof.LAST_BREAKDOWN)
        if lb and lb.get("base") == data.base:
            phases = {
                p: round(float(lb.get(p, 0.0) or 0.0), 6)
                for p in obs.stepprof.PHASES
            }
            obs.journal.record_client_event(
                "phases", claim_id=data.claim_id,
                wall=round(float(lb.get("wall", elapsed) or elapsed), 6),
                **phases,
            )
    rate = data.range_size / elapsed if elapsed > 0 else float("inf")
    log.info(
        "processed %s numbers in %.2fs (%s numbers/sec)",
        f"{data.range_size:,}",
        elapsed,
        f"{rate:,.0f}",
    )
    return results, elapsed


def compile_results(
    data: DataToClient, results: FieldResults, mode: SearchMode, username: str
) -> DataToServer:
    """Build the submission payload (reference client/src/main.rs:212-254),
    stamped with the exactly-once submit_id: claim id + a content hash, so a
    retried request the server already accepted is recognized as the SAME
    submission (idempotent replay), while a different result set for the
    same claim (recomputation after a lost checkpoint) is not."""
    payload = DataToServer(
        claim_id=data.claim_id,
        username=username,
        client_version=CLIENT_VERSION,
        unique_distribution=(
            list(results.distribution) if mode == SearchMode.DETAILED else None
        ),
        nice_numbers=list(results.nice_numbers),
        backend_downgrades=list(results.backend_downgrades) or None,
    )
    if results.backend_downgrades:
        # Client-side journal event: the engine downgrade site has no claim
        # context, so the claim<->downgrade join happens here.
        obs.journal.record_client_event(
            "downgrade", claim_id=data.claim_id,
            downgrades=list(results.backend_downgrades),
        )
    content = json.dumps(payload.to_json(), sort_keys=True).encode()
    payload.submit_id = (
        f"{data.claim_id}-{hashlib.sha256(content).hexdigest()[:16]}"
    )
    return payload


def _prefetch_enabled() -> bool:
    return knobs.PREFETCH.get_bool()


def _warm_field(data: DataToClient, mode: SearchMode, backend: str,
                batch_size: int | None) -> None:
    try:
        if mode == SearchMode.DETAILED:
            engine.warm_detailed(
                data.base, batch_size=batch_size, backend=backend
            )
        else:
            engine.warm_niceonly(
                data.base, field_size=data.range_size,
                field_start=data.range_start,
            )
    except Exception:
        # Best-effort: the field dispatch compiles on demand anyway.
        log.debug("prefetch warm failed for base %d", data.base, exc_info=True)


def _prefetch_on_claim(future, mode: SearchMode, backend: str,
                       batch_size: int | None) -> None:
    """NICE_TPU_PREFETCH hook: when the next claim resolves — typically while
    the current field is still on-device — AOT-warm the executables that
    field will dispatch on a background thread, so a base change at the field
    boundary costs a cache hit instead of a foreground compile."""
    if not _prefetch_enabled():
        return
    import threading

    def _cb(fut) -> None:
        try:
            resolved = fut.result()
        except BaseException:
            return  # the loop's own .result() owns the failure
        # claim_async yields one field; claim_block_async (block_id, fields).
        fields = resolved[1] if isinstance(resolved, tuple) else [resolved]
        seen: set[tuple[int, int]] = set()
        todo = []
        for data in fields:
            key = (data.base, data.range_size if mode != SearchMode.DETAILED else 0)
            if key not in seen:
                seen.add(key)
                todo.append(data)

        def _warm_all() -> None:
            for data in todo:
                _warm_field(data, mode, backend, batch_size)

        threading.Thread(
            target=_warm_all, name="nice-prefetch", daemon=True
        ).start()

    future.add_done_callback(_cb)


def run_benchmark(args) -> int:
    mode = SearchMode.DETAILED if args.mode == "detailed" else SearchMode.NICEONLY
    bench = BenchmarkMode(args.benchmark)
    data = get_benchmark_field(bench)
    log.info(
        "benchmark %s: base %d, range [%d, %d) (%s numbers), mode %s, backend %s",
        bench.value,
        data.base,
        data.range_start,
        data.range_end,
        f"{data.range_size:,}",
        mode,
        args.backend,
    )
    results, elapsed = process_field(data, mode, args.backend, args.batch_size, args.progress_secs)
    nm_cutoff = number_stats.get_near_miss_cutoff(data.base)
    summary = {
        "benchmark": bench.value,
        "base": data.base,
        "range_size": data.range_size,
        "mode": args.mode,
        "backend": args.backend,
        "elapsed_secs": round(elapsed, 4),
        "numbers_per_sec": round(data.range_size / elapsed, 1),
        "nice_count": sum(
            1 for n in results.nice_numbers if n.num_uniques == data.base
        ),
        "near_miss_cutoff": nm_cutoff,
        "near_misses": len(results.nice_numbers),
    }
    print(json.dumps(summary))
    return 0


def run_validate(args) -> int:
    """Diff local recomputation against server-canonical results
    (reference client/src/main.rs:256-292)."""
    vdata = api_client.get_validation_data_from_server(
        args.api_base, args.username, args.base, args.max_retries
    )
    log.info(
        "validating field %d: base %d, range [%d, %d)",
        vdata.field_id,
        vdata.base,
        vdata.range_start,
        vdata.range_end,
    )
    data = DataToClient(
        claim_id=0,
        base=vdata.base,
        range_start=vdata.range_start,
        range_end=vdata.range_end,
        range_size=vdata.range_size,
    )
    results, _ = process_field(data, SearchMode.DETAILED, args.backend, args.batch_size, args.progress_secs)
    ok = True
    canon_dist = {d.num_uniques: d.count for d in vdata.unique_distribution}
    local_dist = {d.num_uniques: d.count for d in results.distribution}
    if canon_dist != local_dist:
        ok = False
        for k in sorted(set(canon_dist) | set(local_dist)):
            if canon_dist.get(k) != local_dist.get(k):
                log.error(
                    "distribution mismatch at %d uniques: canon=%s local=%s",
                    k,
                    canon_dist.get(k),
                    local_dist.get(k),
                )
    canon_nums = {(n.number, n.num_uniques) for n in vdata.nice_numbers}
    local_nums = {(n.number, n.num_uniques) for n in results.nice_numbers}
    if canon_nums != local_nums:
        ok = False
        log.error(
            "nice-number mismatch: only-canon=%s only-local=%s",
            sorted(canon_nums - local_nums),
            sorted(local_nums - canon_nums),
        )
    if ok:
        log.info("validation passed: local results match canonical submission")
        return 0
    log.error("validation FAILED")
    return 1


def _known_servers_path(checkpoint_dir: str) -> str:
    return os.path.join(checkpoint_dir, "servers.json")


def _load_known_servers(checkpoint_dir: Optional[str]) -> list[str]:
    """Server endpoints learned from /status by a previous run — merged
    into the failover list at startup so a restarted client can still fail
    over when its CONFIGURED primary is the server that died."""
    if not checkpoint_dir:
        return []
    try:
        with open(_known_servers_path(checkpoint_dir)) as f:
            data = json.load(f)
    except (OSError, ValueError):
        return []
    if not isinstance(data, list):
        return []
    return [s.rstrip("/") for s in data if isinstance(s, str) and s.strip()]


def _save_known_servers(checkpoint_dir: Optional[str],
                        servers: list[str]) -> None:
    if not checkpoint_dir or not servers:
        return
    try:
        os.makedirs(checkpoint_dir, exist_ok=True)
        fsio.atomic_write_json(
            _known_servers_path(checkpoint_dir),
            list(dict.fromkeys(s.rstrip("/") for s in servers)),
        )
    except OSError as e:
        log.debug("failed to persist known servers: %s", e)


def _fleet_snapshot(args, spool) -> dict:
    """This client's current obs.telemetry snapshot, spool depth included."""
    depth = 0
    if spool is not None:
        try:
            depth = len(spool.pending())
        except OSError:
            pass
    return obs.telemetry.snapshot(
        username=args.username, backend=args.backend, spool_depth=depth,
        client_version=CLIENT_VERSION,
    )


class _TelemetryReporter:
    """Background fleet-visibility heartbeat: POSTs /telemetry immediately
    on entry and then every every_secs, so long-scanning clients show up on
    the server's fleet dashboard before their first submission. Failures
    are logged and swallowed — telemetry must never hurt the scan."""

    def __init__(self, args, spool):
        import threading

        self.args = args
        self.spool = spool
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="telemetry-report", daemon=True
        )

    def _report_once(self) -> None:
        try:
            api_client.post_telemetry(
                self.args.api_base, _fleet_snapshot(self.args, self.spool)
            )
        except Exception as e:
            log.debug("telemetry heartbeat failed: %s", e)
        self._learn_servers()

    def _learn_servers(self) -> None:
        """Persist the server list /status advertises (primary + live
        standbys) beside the checkpoints, so the NEXT run's failover list
        covers servers this run only learned about at runtime."""
        if not self.args.checkpoint_dir:
            return
        try:
            status = api_client.failover_request(
                self.args.api_base, "/status", max_retries=0,
                endpoint="telemetry",
            )
            servers = (status.get("repl") or {}).get("servers") or []
            _save_known_servers(self.args.checkpoint_dir, servers)
        except Exception as e:
            log.debug("server-list learn failed: %s", e)

    def _run(self) -> None:
        self._report_once()
        while not self._stop.wait(self.args.telemetry_secs):
            self._report_once()

    def __enter__(self) -> "_TelemetryReporter":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def _maybe_telemetry(args, spool):
    from contextlib import nullcontext

    if args.telemetry_secs and args.telemetry_secs > 0:
        return _TelemetryReporter(args, spool)
    return nullcontext()


class _ClaimRenewer:
    """Background lease heartbeat for one claim: POSTs /renew_claim
    immediately on entry (a resumed claim may be near expiry) and then every
    every_secs. Failures are logged and swallowed — a missed heartbeat is
    recoverable, killing the scan over one is not."""

    def __init__(self, api_base: str, claim_id: int, every_secs: float):
        import threading

        self.api_base = api_base
        self.claim_id = claim_id
        self.every_secs = every_secs
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="claim-renew", daemon=True
        )

    def _renew_once(self) -> None:
        try:
            api_client.renew_claim(self.api_base, self.claim_id)
            CKPT_RENEWALS.inc()
            log.debug("renewed claim %d lease", self.claim_id)
        except Exception as e:
            log.warning("claim %d lease renewal failed: %s", self.claim_id, e)

    def _run(self) -> None:
        self._renew_once()
        while not self._stop.wait(self.every_secs):
            self._renew_once()

    def __enter__(self) -> "_ClaimRenewer":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def _maybe_renewer(args, claim_id: int):
    from contextlib import nullcontext

    if args.renew_secs and args.renew_secs > 0 and claim_id > 0:
        return _ClaimRenewer(args.api_base, claim_id, args.renew_secs)
    return nullcontext()


class _BlockRenewer:
    """Lease heartbeat for a block claim: one POST /renew_claim {block_id}
    re-arms every member field's lease (same immediately-then-periodically
    cadence and swallow-failures policy as _ClaimRenewer)."""

    def __init__(self, api_base: str, block_id: str, every_secs: float):
        import threading

        self.api_base = api_base
        self.block_id = block_id
        self.every_secs = every_secs
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="block-renew", daemon=True
        )

    def _renew_once(self) -> None:
        try:
            api_client.renew_block(self.api_base, self.block_id)
            CKPT_RENEWALS.inc()
            log.debug("renewed block %s lease", self.block_id)
        except Exception as e:
            log.warning("block %s lease renewal failed: %s", self.block_id, e)

    def _run(self) -> None:
        self._renew_once()
        while not self._stop.wait(self.every_secs):
            self._renew_once()

    def __enter__(self) -> "_BlockRenewer":
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def _maybe_block_renewer(args, block_id: str):
    from contextlib import nullcontext

    if args.renew_secs and args.renew_secs > 0 and block_id:
        return _BlockRenewer(args.api_base, block_id, args.renew_secs)
    return nullcontext()


def _new_checkpointer(args, data: DataToClient, mode: SearchMode):
    if not args.checkpoint_dir:
        return None
    return ckpt.FieldCheckpointer(
        args.checkpoint_dir, data, mode, args.backend, args.batch_size
    )


def _resume_or_claim(args, api: api_client.AsyncApi, mode: SearchMode):
    """(data, resume_state, checkpointer): the newest matching snapshot in
    --checkpoint-dir if one exists (same claim, no re-claim round-trip), else
    a fresh server claim."""
    if args.checkpoint_dir:
        found = ckpt.find_resumable(
            args.checkpoint_dir, mode, args.backend, args.batch_size
        )
        if found is not None:
            data, state, ckptr = found
            log.info(
                "resuming claim %d from checkpoint: base %d, range [%d, %d), "
                "cursor %d",
                data.claim_id, data.base, data.range_start, data.range_end,
                state["cursor"],
            )
            return data, state, ckptr
    data = api.claim_async(mode).result()
    log.info(
        "claimed field (claim %d): base %d, range [%d, %d)",
        data.claim_id,
        data.base,
        data.range_start,
        data.range_end,
    )
    return data, None, _new_checkpointer(args, data, mode)


def _await_submit(future, submission: DataToServer, spool) -> None:
    """Confirm a submit, journaling to the spool when the server stayed
    unreachable past the retry budget. A 4xx rejection always raises — a
    replay of a rejected payload can never succeed. Once this returns,
    delivery is OWNED (accepted, already-accepted duplicate, or spooled), so
    the field's snapshot may be retired."""
    try:
        future.result()
        log.info("submitted claim %d", submission.claim_id)
    except api_client.ApiError as e:
        if spool is None or (e.status is not None and 400 <= e.status < 500):
            raise
        spool.add(submission)


def run_single_iteration(
    args, api: api_client.AsyncApi, mode: SearchMode, spool=None
) -> None:
    data, resume, ckptr = _resume_or_claim(args, api, mode)
    # One distributed trace per claim lifecycle: the id is derived from the
    # claim id, so the server's handler spans (continued from the request's
    # traceparent header) and the engine's scan spans share it.
    with obs.trace_context(obs.claim_trace_id(data.claim_id)):
        obs.trace_event(
            "client.claim", claim=data.claim_id, base=data.base,
            range_start=str(data.range_start), size=data.range_size,
            resumed=resume is not None,
        )
        obs.flight.record("claim", claim=data.claim_id, base=data.base)
        with _maybe_renewer(args, data.claim_id):
            results, _ = process_field(
                data, mode, args.backend, args.batch_size, args.progress_secs,
                checkpointer=ckptr, resume=resume,
                checkpoint_secs=args.checkpoint_secs,
            )
    submission = compile_results(data, results, mode, args.username)
    # Telemetry rides along AFTER submit_id is stamped: it must not perturb
    # the content hash that makes replays idempotent.
    submission.telemetry = _fleet_snapshot(args, spool)
    _await_submit(api.submit_async(submission), submission, spool)
    # Only an owned submit (confirmed or spooled) retires the snapshot; any
    # failure before this point leaves it on disk for the next startup.
    if ckptr is not None:
        ckptr.delete()


def run_pipelined_loop(
    args, api: api_client.AsyncApi, mode: SearchMode, spool=None
) -> None:
    """claim N+1 || process N || submit N-1 (reference client/src/main.rs:411-562)."""
    # (future, checkpointer, submission) awaiting confirmation
    pending_submit = None
    data, resume, ckptr = _resume_or_claim(args, api, mode)
    stats_every = float(_env("STATS_SECS", 60))
    t_start = time.monotonic()
    last_stats = t_start
    fields = 0
    numbers = 0
    while True:
        if spool is not None:
            # Loop-boundary replay: a no-op when empty, and the natural
            # moment to drain journaled submissions once the server is back.
            spool.replay(args.api_base)
        next_claim = api.claim_async(mode)  # overlap with processing
        _prefetch_on_claim(next_claim, mode, args.backend, args.batch_size)
        with obs.trace_context(obs.claim_trace_id(data.claim_id)):
            obs.trace_event(
                "client.claim", claim=data.claim_id, base=data.base,
                range_start=str(data.range_start), size=data.range_size,
                resumed=resume is not None,
            )
            obs.flight.record("claim", claim=data.claim_id, base=data.base)
            with _maybe_renewer(args, data.claim_id):
                results, _ = process_field(
                    data, mode, args.backend, args.batch_size,
                    args.progress_secs, checkpointer=ckptr, resume=resume,
                    checkpoint_secs=args.checkpoint_secs,
                )
        if pending_submit is not None:
            # Settle the previous submit before queueing the next one; only
            # an owned submit (confirmed or spooled) retires its snapshot.
            prev_future, prev_ckptr, prev_sub = pending_submit
            _await_submit(prev_future, prev_sub, spool)
            if prev_ckptr is not None:
                prev_ckptr.delete()
        submission = compile_results(data, results, mode, args.username)
        submission.telemetry = _fleet_snapshot(args, spool)
        pending_submit = (api.submit_async(submission), ckptr, submission)
        fields += 1
        numbers += data.range_size
        now = time.monotonic()
        if stats_every > 0 and now - last_stats >= stats_every:
            last_stats = now
            up = now - t_start
            log.info(
                "session stats: %d fields, %s numbers in %.0fs "
                "(%s numbers/sec average)",
                fields, f"{numbers:,}", up, f"{numbers / up:,.0f}",
            )
        data = next_claim.result()
        resume = None
        ckptr = _new_checkpointer(args, data, mode)
        log.info(
            "claimed field (claim %d): base %d, size %s",
            data.claim_id,
            data.base,
            f"{data.range_size:,}",
        )


def _process_block(args, mode: SearchMode, block_id: str, fields, spool):
    """Process every field of a block sequentially under ONE block-lease
    renewer; returns [(submission, checkpointer), ...] in field order."""
    submissions = []
    with _maybe_block_renewer(args, block_id):
        for data in fields:
            ckptr = _new_checkpointer(args, data, mode)
            with obs.trace_context(obs.claim_trace_id(data.claim_id)):
                obs.trace_event(
                    "client.claim", claim=data.claim_id, base=data.base,
                    range_start=str(data.range_start), size=data.range_size,
                    resumed=False, block=block_id,
                )
                obs.flight.record(
                    "claim", claim=data.claim_id, base=data.base,
                    block=block_id,
                )
                results, _ = process_field(
                    data, mode, args.backend, args.batch_size,
                    args.progress_secs, checkpointer=ckptr,
                    checkpoint_secs=args.checkpoint_secs,
                )
            submissions.append(
                (compile_results(data, results, mode, args.username), ckptr)
            )
    return submissions


def _await_block_submit(future, submissions, spool) -> None:
    """Settle one /submit_block: per-item rejections are logged (a replay of
    a rejected payload can never succeed, so they still retire their
    snapshots); retry exhaustion spools every member for per-field replay.
    Once this returns, delivery of every member is owned."""
    resp = None
    try:
        resp = future.result()
        for (sub, _ck), result in zip(submissions, resp.get("results", [])):
            if result.get("status") == "error":
                log.error(
                    "block submission for claim %d rejected (%s): %s",
                    sub.claim_id, result.get("code"), result.get("message"),
                )
            else:
                log.info(
                    "submitted claim %d%s", sub.claim_id,
                    " (duplicate)" if result.get("duplicate") else "",
                )
    except api_client.ApiError as e:
        if spool is None or (e.status is not None and 400 <= e.status < 500):
            raise
        # The spool replays through the per-field /submit path, which the
        # server keeps for exactly this kind of compatibility traffic.
        for sub, _ck in submissions:
            spool.add(sub)
    for _sub, ck in submissions:
        if ck is not None:
            ck.delete()


def _drain_resumable(args, api: api_client.AsyncApi, mode: SearchMode, spool):
    """Block mode can't resume a lone per-field snapshot into a block, so a
    crash-recovered scan finishes through the per-field path first."""
    if not args.checkpoint_dir:
        return
    while ckpt.find_resumable(
        args.checkpoint_dir, mode, args.backend, args.batch_size
    ):
        run_single_iteration(args, api, mode, spool=spool)


def run_block_iteration(
    args, api: api_client.AsyncApi, mode: SearchMode, spool=None
) -> bool:
    """Claim one block, process all members, submit batched. False means the
    server predates block leases (404) and the caller should fall back."""
    _drain_resumable(args, api, mode, spool)
    try:
        block_id, fields = api.claim_block_async(
            mode, args.claim_block
        ).result()
    except api_client.ApiError as e:
        if e.status == 404:
            log.warning(
                "server has no /claim_block; falling back to per-field claims"
            )
            return False
        raise
    log.info("claimed block %s: %d fields", block_id, len(fields))
    submissions = _process_block(args, mode, block_id, fields, spool)
    future = api.submit_block_async(
        block_id, [s for s, _ in submissions], _fleet_snapshot(args, spool)
    )
    _await_block_submit(future, submissions, spool)
    return True


def run_block_pipelined_loop(
    args, api: api_client.AsyncApi, mode: SearchMode, spool=None
) -> bool:
    """claim block N+1 || process block N || settle submit block N-1: the
    3-stage pipeline over block leases — one HTTP round-trip per
    --claim-block fields at each stage. False = server has no block support."""
    _drain_resumable(args, api, mode, spool)
    pending_submit = None  # (future, submissions) awaiting confirmation
    try:
        block_id, fields = api.claim_block_async(
            mode, args.claim_block
        ).result()
    except api_client.ApiError as e:
        if e.status == 404:
            log.warning(
                "server has no /claim_block; falling back to per-field claims"
            )
            return False
        raise
    stats_every = float(_env("STATS_SECS", 60))
    t_start = time.monotonic()
    last_stats = t_start
    fields_done = 0
    numbers = 0
    while True:
        if spool is not None:
            spool.replay(args.api_base)
        log.info("claimed block %s: %d fields", block_id, len(fields))
        next_block = api.claim_block_async(mode, args.claim_block)
        _prefetch_on_claim(next_block, mode, args.backend, args.batch_size)
        submissions = _process_block(args, mode, block_id, fields, spool)
        if pending_submit is not None:
            _await_block_submit(*pending_submit, spool)
        pending_submit = (
            api.submit_block_async(
                block_id,
                [s for s, _ in submissions],
                _fleet_snapshot(args, spool),
            ),
            submissions,
        )
        fields_done += len(fields)
        numbers += sum(d.range_size for d in fields)
        now = time.monotonic()
        if stats_every > 0 and now - last_stats >= stats_every:
            last_stats = now
            up = now - t_start
            log.info(
                "session stats: %d fields, %s numbers in %.0fs "
                "(%s numbers/sec average)",
                fields_done, f"{numbers:,}", up, f"{numbers / up:,.0f}",
            )
        block_id, fields = next_block.result()


def run_tenants(args) -> int:
    """Multi-tenant scheduler mode (--tenants / NICE_TPU_TENANTS): parse
    the tenant specs, claim with tenant routing, and interleave every
    tenant's pages on this process's mesh. --repeat keeps each tenant
    claiming until the server runs dry; otherwise each tenant runs one
    field (the smoke-friendly bound)."""
    from nice_tpu import sched

    registry = sched.TenantRegistry(sched.parse_tenants(args.tenants))
    if not len(registry):
        log.error("--tenants parsed to zero tenants")
        return 2
    source = sched.ServerSource(
        args.api_base, args.username,
        fields_per_tenant=None if args.repeat else 1,
        max_retries=args.max_retries,
    )
    scheduler = sched.MultiTenantScheduler(registry, source)
    from nice_tpu.ops import autotune

    for row in autotune.tenant_report(
        [(s.name, s.mode, s.base, s.backend) for s in registry]
    ):
        log.info(
            "tenant %s: %s tuned=%s batch=%d megaloop=%d page_quantum=%d",
            row["tenant"], row["key"], row["tuned"], row["batch_size"],
            row["megaloop"], row["page_quantum"],
        )
    scheduler.start_slo_thread()
    try:
        stats = scheduler.run()
    finally:
        scheduler.stop_slo_thread()
    log.info(
        "scheduler done: %d rounds, occupancy %.2f; per-tenant %s",
        stats["rounds"], stats["occupancy"],
        {t: (v["fields"], v["pages"]) for t, v in stats["tenants"].items()},
    )
    return 0


def main(argv: Optional[list[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    # Unified JSON-line sink (trace_id injection; NICE_TPU_LOG_LEVEL /
    # NICE_TPU_LOG_FILE override the CLI flag).
    obs.logsink.install(default_level=args.log_level)
    # Local /metrics endpoint (NICE_TPU_METRICS_PORT): exposes the client's
    # field/latency series plus the engine pipeline registry.
    obs.maybe_serve_metrics()
    # Crash/SIGUSR2 flight-recorder dumps (NICE_TPU_FLIGHT_DIR).
    obs.flight.install()
    # Time-series history sampler behind the same local port's GET /history
    # (NICE_TPU_HISTORY_SECS; 0 disables).
    obs.history.maybe_start_sampler()
    # Resource observatory: memory/footprint sampler (NICE_TPU_MEMWATCH_SECS)
    # and the statistical wall-clock profiler (NICE_TPU_PYPROF_HZ). Either
    # knob at 0 means no thread is created at all.
    obs.memwatch.maybe_start_sampler()
    obs.pyprof.maybe_start()
    if args.threads > 0:
        # The native backend sizes its pools from NICE_THREADS (engine
        # _native_threads); the flag is the CLI face of the same knob
        # (reference --threads, client/src/main.rs:64-116, 183-196).
        os.environ["NICE_THREADS"] = str(args.threads)
    # Make JAX_PLATFORMS authoritative: some PJRT plugins override the env
    # var at import time, so a user's JAX_PLATFORMS=cpu would otherwise
    # still grab (or hang on) an accelerator (see nice_tpu/utils/platform.py).
    platform = os.environ.get("JAX_PLATFORMS")
    if platform and args.backend in ("jax", "jnp", "pallas"):
        import jax

        jax.config.update("jax_platforms", platform)
    if args.checkpoint_dir and args.backend == "native":
        # The native engine's thread fan-out has no consistent cursor to
        # snapshot; disable rather than write unresumable state.
        log.warning(
            "--checkpoint-dir is not supported with backend='native'; "
            "checkpointing disabled"
        )
        args.checkpoint_dir = None
    # Multi-server failover list: --api-base (may itself be a comma list)
    # + --servers/NICE_TPU_SERVERS + endpoints a previous run learned from
    # /status. The joined list IS the api_base from here on — every
    # api_client call (spool replay included) rotates across it.
    server_list = api_client.split_servers(args.api_base)
    if args.servers:
        server_list += api_client.split_servers(args.servers)
    server_list += _load_known_servers(args.checkpoint_dir)
    args.api_base = ",".join(dict.fromkeys(server_list))
    if args.benchmark:
        return run_benchmark(args)
    if args.validate:
        return run_validate(args)
    if args.tenants:
        return run_tenants(args)
    mode = SearchMode.DETAILED if args.mode == "detailed" else SearchMode.NICEONLY
    api = api_client.AsyncApi(args.api_base, args.username, args.max_retries)
    spool = spool_mod.maybe_spool(args.spool_dir, args.checkpoint_dir)
    # Register on-disk footprints with the resource sampler so leak-trend /
    # exhaustion forecasting covers what this client writes.
    if spool is not None:
        obs.memwatch.watch_path("spool", spool.dir)
    obs.memwatch.watch_path("ckpt", args.checkpoint_dir)
    trace_sink = knobs.TRACE.raw() or ""
    if trace_sink and trace_sink not in ("1", "stderr"):
        obs.memwatch.watch_path("trace", trace_sink)
    if spool is not None:
        # Startup replay: deliver anything journaled by a previous run (the
        # kill-during-outage case) before claiming new work.
        spool.replay(args.api_base)
    try:
        with _maybe_telemetry(args, spool):
            handled = False
            if args.claim_block > 1:
                # Block-lease path: N fields per round-trip; a False return
                # means the server predates /claim_block, so fall through to
                # the per-field compatibility loop below.
                if args.repeat:
                    handled = run_block_pipelined_loop(
                        args, api, mode, spool=spool
                    )
                else:
                    handled = run_block_iteration(args, api, mode, spool=spool)
            if not handled:
                if args.repeat:
                    run_pipelined_loop(args, api, mode, spool=spool)
                else:
                    run_single_iteration(args, api, mode, spool=spool)
    except KeyboardInterrupt:
        log.info("interrupted; shutting down")
    finally:
        api.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
