"""Client HTTP transport: claim / submit / validate with retry + backoff.

Stdlib-only (urllib) equivalent of the reference's reqwest wrappers
(client_api_sync.rs:37-206): full-jitter exponential backoff (AWS
architecture-blog style: uniform(0, min(2^attempt, cap)) so a fleet of
clients knocked over by one server restart doesn't reconverge in lockstep),
retrying network errors and 5xx responses; 4xx errors surface immediately
with the server's message; a server-sent Retry-After (the 503 overload
shed) overrides the computed backoff. A thread-pool async facade gives the
overlap the reference gets from tokio (client_api_async.rs) without extra
dependencies.

Fault injection: every attempt passes through the http.<endpoint> site
(nice_tpu.faults), so NICE_TPU_FAULTS can synthesize 5xx responses,
connection errors, or — the nasty one — drop_response: the request REACHES
the server and is processed, but the client sees a network error and
retries, exercising the exactly-once submit path.
"""

from __future__ import annotations

import contextlib
import http.client
import io
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from email.message import Message
from typing import Any, Optional
from urllib.parse import urlsplit

from nice_tpu import faults, obs
from nice_tpu.core.constants import CLIENT_REQUEST_TIMEOUT_SECS
from nice_tpu.core.types import DataToClient, DataToServer, SearchMode, ValidationData
from nice_tpu.utils import lockdep
from nice_tpu.obs.series import (
    CLIENT_FAILOVERS,
    CLIENT_REQUEST_SECONDS,
    CLIENT_RETRIES,
)

log = logging.getLogger(__name__)

DEFAULT_MAX_RETRIES = 10
MAX_BACKOFF_SECS = 512

# Backoff jitter source; module-level so tests can reseed for determinism.
_backoff_rng = random.Random()

# Replication fencing: the highest epoch this process has seen in any
# server response. Stamped on every request as X-Nice-Epoch so a deposed
# primary learns it has been fenced the moment a post-failover client
# talks to it (claim GETs mutate server state too, so ALL requests stamp).
_epoch_lock = lockdep.make_lock("client.api_client._epoch_lock")
_last_epoch = 0


def _note_epoch(parsed: Any) -> None:
    """Learn the fencing epoch from a response body: top-level "epoch"
    (write replies, /status) or the nested /status repl block."""
    global _last_epoch
    if not isinstance(parsed, dict):
        return
    epoch = parsed.get("epoch")
    if epoch is None and isinstance(parsed.get("repl"), dict):
        epoch = parsed["repl"].get("epoch")
    try:
        epoch = int(epoch)
    except (TypeError, ValueError):
        return
    with _epoch_lock:
        if epoch > _last_epoch:
            _last_epoch = epoch


def last_seen_epoch() -> int:
    with _epoch_lock:
        return _last_epoch


class ApiError(Exception):
    """Non-retryable API failure.

    status: the HTTP status code when the server definitively answered
    (4xx — the request is rejected, retrying cannot help), or None when
    retries were exhausted on transient errors (the request MAY still
    succeed later; the submission spool uses the distinction)."""

    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


def _inject_http_fault(
    action: str, url: str, body: Optional[dict], timeout: float
) -> Any:
    """Apply an http.<endpoint> fault action. Raises for every action except
    an unknown one (which degrades to the real request)."""
    if action == "drop_response":
        # The server processes the request; the client never learns.
        _request_json(url, body, timeout)
        raise urllib.error.URLError(f"injected fault: response dropped for {url}")
    if action in ("conn_error", "raise"):
        raise urllib.error.URLError(f"injected fault: connection error for {url}")
    try:
        code = int(action)
    except ValueError:
        log.warning("unknown http fault action %r; passing through", action)
        return _request_json(url, body, timeout)
    raise urllib.error.HTTPError(
        url, code, f"injected fault: HTTP {code}", Message(),
        io.BytesIO(b"injected fault"),
    )


def _retry_after_secs(err: Exception) -> Optional[float]:
    """Delay-seconds from a server-sent Retry-After header, if any (the
    HTTP-date form is ignored — this server only emits delta-seconds)."""
    headers = getattr(err, "headers", None)
    if headers is None:
        return None
    value = headers.get("Retry-After")
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        return None


# Per-thread keep-alive connection pool, keyed by (scheme, host:port). One
# persistent socket per server per thread replaces the fresh TCP handshake
# urllib.request paid on EVERY call; the server speaks HTTP/1.1 keep-alive
# on both cores, so a pipelined client reuses one connection for its whole
# lifetime (the load harness measures the RTT delta). Thread-local because
# http.client connections are not thread-safe and the AsyncApi pool plus the
# renew/telemetry threads each need their own.
_conn_local = threading.local()

# Errors that mean the REUSED socket went stale (server closed an idle
# keep-alive connection): safe to transparently retry once on a fresh
# socket. On a brand-new connection the same errors are real failures and
# propagate to retry_request's backoff (which is also where the
# exactly-once submit_id story absorbs any ambiguous resend).
_STALE_ERRORS = (
    http.client.BadStatusLine,
    http.client.RemoteDisconnected,
    BrokenPipeError,
    ConnectionResetError,
    ConnectionAbortedError,
)


# Dead-endpoint registry, shared across ALL threads' pools: when one thread
# hits a connection error against an endpoint, every pooled keep-alive
# socket to that endpoint born BEFORE the failure is evicted on next use
# instead of each thread re-probing its own stale socket and eating its own
# timeout. Keyed like the pools: (scheme, host:port) -> monotonic mark.
_dead_hosts_lock = lockdep.make_lock("client.api_client._dead_hosts_lock")
_dead_hosts: dict = {}


def _mark_host_dead(key) -> None:
    with _dead_hosts_lock:
        _dead_hosts[key] = time.monotonic()


def _conn_pool() -> dict:
    pool = getattr(_conn_local, "pool", None)
    if pool is None:
        pool = _conn_local.pool = {}
    return pool


def _drop_connection(key) -> None:
    conn = _conn_pool().pop(key, None)
    if conn is not None:
        with contextlib.suppress(Exception):
            conn.close()


def close_connections(netloc: Optional[str] = None) -> None:
    """Close this thread's pooled connections — all of them (tests / clean
    shutdown), or only those to one host:port when netloc is given (a dead
    endpoint's sockets go without disturbing live servers' keep-alives)."""
    pool = _conn_pool()
    for key in list(pool):
        if netloc is not None and key[1] != netloc:
            continue
        conn = pool.pop(key)
        with contextlib.suppress(Exception):
            conn.close()


def _request_json(
    url: str,
    body: Optional[dict] = None,
    timeout: float = CLIENT_REQUEST_TIMEOUT_SECS,
) -> Any:
    parts = urlsplit(url)
    if parts.scheme not in ("http", "https"):
        raise urllib.error.URLError(f"unsupported scheme in {url!r}")
    target = parts.path or "/"
    if parts.query:
        target += "?" + parts.query
    data = None
    headers = {"Accept": "application/json"}
    if body is not None:
        data = json.dumps(body).encode()
        headers["Content-Type"] = "application/json"
    # Resolved here (not threaded through the retry loop) so the thread's
    # ambient trace context alone decides the header.
    traceparent = obs.current_traceparent()
    if traceparent:
        headers["traceparent"] = traceparent
    epoch = last_seen_epoch()
    if epoch > 0:
        headers["X-Nice-Epoch"] = str(epoch)
    method = "GET" if body is None else "POST"
    key = (parts.scheme, parts.netloc)
    pool = _conn_pool()
    for fresh_retry in (False, True):
        conn = pool.get(key)
        if conn is not None:
            # Cross-thread dead-host eviction: a socket born before another
            # thread marked this endpoint dead is stale by fiat — drop it
            # rather than re-probe it through its own timeout.
            with _dead_hosts_lock:
                dead_mark = _dead_hosts.get(key)
            if (
                dead_mark is not None
                and getattr(conn, "_nice_born", 0.0) <= dead_mark
            ):
                _drop_connection(key)
                conn = None
        reused = conn is not None
        if conn is None:
            cls = (
                http.client.HTTPSConnection
                if parts.scheme == "https"
                else http.client.HTTPConnection
            )
            conn = cls(parts.netloc, timeout=timeout)
            conn._nice_born = time.monotonic()
            pool[key] = conn
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        try:
            conn.request(method, target, body=data, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
        except _STALE_ERRORS as e:
            _drop_connection(key)
            if reused and not fresh_retry:
                continue
            # A FRESH connection failing the same way means the endpoint
            # itself is down, not just an idle keep-alive reaped.
            _mark_host_dead(key)
            raise urllib.error.URLError(f"{e.__class__.__name__}: {e}") from e
        except OSError:
            # Connect/socket failure: state unknown, never silently resend.
            _drop_connection(key)
            _mark_host_dead(key)
            raise
        if resp.will_close:
            _drop_connection(key)
        if resp.status >= 400:
            raise urllib.error.HTTPError(
                url, resp.status, resp.reason, resp.headers, io.BytesIO(payload)
            )
        parsed = json.loads(payload) if payload else None
        _note_epoch(parsed)
        return parsed


def retry_request(
    url: str,
    body: Optional[dict] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
    timeout: float = CLIENT_REQUEST_TIMEOUT_SECS,
    endpoint: str = "other",
) -> Any:
    """GET/POST with full-jitter exponential backoff on 5xx, 429, and
    network errors: each retry sleeps uniform(0, min(2^attempt, cap))
    seconds, unless the response carried Retry-After (server overload shed
    or per-client rate limit), which wins.

    endpoint labels the per-attempt latency histogram and retry counter
    (claim / submit / validate / renew / other). Every attempt carries a
    W3C traceparent header from the thread's ambient trace context (wrap the
    call in obs.trace_context to set it) so the server's handler span joins
    the field's distributed trace."""
    attempt = 0
    while True:
        t0 = time.monotonic()
        try:
            act = faults.fire(f"http.{endpoint}", url=url, attempt=attempt)
            if act is not None:
                result = _inject_http_fault(act, url, body, timeout)
            else:
                result = _request_json(url, body, timeout)
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            return result
        except urllib.error.HTTPError as e:
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            if e.code < 500 and e.code != 429:
                # 4xx = our request is wrong, retrying won't fix it — except
                # 429 (per-client rate limit): that clears with time, so it
                # backs off like a 5xx, honoring the server's Retry-After.
                detail = ""
                try:
                    detail = e.read().decode(errors="replace")
                except Exception:
                    pass
                raise ApiError(
                    f"HTTP {e.code} from {url}: {detail}", status=e.code
                ) from e
            err: Exception = e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            err = e
        if attempt >= max_retries:
            # Preserve the HTTP status when the last failure was a definite
            # server answer (429/5xx), so callers can distinguish "rate
            # limited until I slow down" from a dead transport.
            raise ApiError(
                f"request to {url} failed after {attempt} retries: {err}",
                status=getattr(err, "code", None),
            )
        CLIENT_RETRIES.labels(endpoint).inc()
        obs.flight.record("retry", endpoint=endpoint, attempt=attempt,
                          error=str(err)[:200])
        hinted = _retry_after_secs(err)
        if hinted is not None:
            delay = min(hinted, MAX_BACKOFF_SECS)
        else:
            delay = _backoff_rng.uniform(0, min(2**attempt, MAX_BACKOFF_SECS))
        log.warning(
            "request failed (%s); retry %d in %.2fs%s",
            err, attempt + 1, delay,
            " (server Retry-After)" if hinted is not None else "",
        )
        time.sleep(delay)
        attempt += 1


# Multi-server failover (--servers / NICE_TPU_SERVERS): api_base may be a
# comma-separated endpoint list. Sticky per-list cursor: all threads start
# from the last server that worked, so one failover reroutes the whole
# process instead of every thread rediscovering the dead primary.
_failover_lock = lockdep.make_lock("client.api_client._failover_lock")
_failover_idx: dict = {}
# Generation per server-list key, bumped on every cursor store: a store
# computed before a concurrent rotation must not clobber it (same
# discipline as the status cache's _status_cache_gen).
_failover_gen: dict = {}

# Statuses that rotate to the next server (on top of None = transport
# failure and 5xx): timeouts and rate/overload shed clear elsewhere, and
# 410/421 are the epoch fence saying "not me — ask the promoted server".
_ROTATE_STATUSES = frozenset({408, 410, 421, 429})


def split_servers(api_base: str) -> list:
    """Endpoint list from an api_base that may be comma-separated."""
    return [s.strip().rstrip("/") for s in api_base.split(",") if s.strip()]


def failover_request(
    api_base: str,
    path: str,
    body: Optional[dict] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
    timeout: float = CLIENT_REQUEST_TIMEOUT_SECS,
    endpoint: str = "other",
) -> Any:
    """retry_request over one OR many servers.

    Single server: byte-identical to retry_request (same backoff budget).
    Multiple: each cycle tries every server once (no per-server backoff),
    rotating on transport errors, 5xx, and _ROTATE_STATUSES; other 4xx
    raise immediately — a definite answer from a live primary. A full
    failed cycle sleeps the usual full-jitter backoff; cycles are capped at
    max_retries + 1 and the last ApiError re-raises (status=None preserved
    so the submission spool still distinguishes dead transport)."""
    servers = split_servers(api_base)
    if len(servers) <= 1:
        base = servers[0] if servers else api_base.rstrip("/")
        return retry_request(
            base + path, body, max_retries=max_retries, timeout=timeout,
            endpoint=endpoint,
        )
    key = ",".join(servers)
    with _failover_lock:
        start = _failover_idx.get(key, 0) % len(servers)
        gen = _failover_gen.get(key, 0)
    last_err: Optional[ApiError] = None
    for cycle in range(max_retries + 1):
        for off in range(len(servers)):
            i = (start + off) % len(servers)
            try:
                result = retry_request(
                    servers[i] + path, body, max_retries=0,
                    timeout=timeout, endpoint=endpoint,
                )
            except ApiError as e:
                last_err = e
                if (
                    e.status is not None
                    and e.status < 500
                    and e.status not in _ROTATE_STATUSES
                ):
                    raise
                CLIENT_FAILOVERS.labels(endpoint).inc()
                obs.flight.record(
                    "failover", endpoint=endpoint, server=servers[i],
                    status=e.status, cycle=cycle,
                )
                log.warning(
                    "server %s failed %s (%s); rotating to next endpoint",
                    servers[i], path,
                    e.status if e.status is not None else f"transport: {e}",
                )
                continue
            with _failover_lock:
                # Store only if no other thread moved the cursor while this
                # request ran outside the lock — a concurrent rotation away
                # from a dead server must win over our older success.
                if _failover_gen.get(key, 0) == gen:  # nicelint: allow R5 (generation-checked store; schedex scenario failover_cursor_rotate_vs_store replays the window)
                    _failover_idx[key], _failover_gen[key] = i, gen + 1
            return result
        if cycle >= max_retries:
            break
        delay = _backoff_rng.uniform(0, min(2 ** cycle, MAX_BACKOFF_SECS))
        log.warning(
            "all %d servers failed %s; cycle %d backoff %.2fs",
            len(servers), path, cycle + 1, delay,
        )
        time.sleep(delay)
    assert last_err is not None
    raise last_err


def get_field_from_server(
    mode: SearchMode, api_base: str, username: str,
    max_retries: int = DEFAULT_MAX_RETRIES,
    tenant: Optional[str] = None,
    base_min: Optional[int] = None,
    base_max: Optional[int] = None,
) -> DataToClient:
    """GET /claim/{detailed|niceonly} (reference client_api_sync.rs:104-129).

    tenant / base_min / base_max are the multi-tenant scheduler's claim
    routing: the claim row is stamped with the tenant name and the field is
    drawn from the tenant's base window. Pre-sched servers ignore the extra
    query params, so the scheduler degrades to unrouted claims."""
    endpoint = "detailed" if mode == SearchMode.DETAILED else "niceonly"
    path = f"/claim/{endpoint}?username={urllib.request.quote(username)}"
    if tenant is not None:
        path += f"&tenant={urllib.request.quote(tenant)}"
    if base_min is not None:
        path += f"&base_min={int(base_min)}"
    if base_max is not None:
        path += f"&base_max={int(base_max)}"
    t0 = time.monotonic()
    data = DataToClient.from_json(
        failover_request(api_base, path, max_retries=max_retries,
                         endpoint="claim")
    )
    # Critical-path stamp: the claim round-trip as the CLIENT experienced it
    # (retries and backoff included — that wait is real end-to-end latency).
    # Rides the next telemetry snapshot into this field's journal timeline.
    obs.journal.record_client_event(
        "claim_rtt", claim_id=data.claim_id,
        secs=round(time.monotonic() - t0, 6),
    )
    return data


def submit_field_to_server(
    api_base: str, submit_data: DataToServer, max_retries: int = DEFAULT_MAX_RETRIES
) -> dict:
    """POST /submit (reference client_api_sync.rs:144-172). Returns the
    server's response dict; {"duplicate": true} means a retried submit was
    already accepted (exactly-once via submit_id) — success, not an error."""
    # Derived (not ambient) trace id: AsyncApi runs submits on pool threads
    # where the field's trace_context isn't set, but the claim id is in the
    # payload, so the submit span still joins the field's trace.
    trace_id = obs.claim_trace_id(submit_data.claim_id)
    t0 = time.monotonic()
    with obs.trace_context(trace_id), obs.span(
        "client.submit", claim=submit_data.claim_id
    ):
        resp = failover_request(
            api_base, "/submit", submit_data.to_json(),
            max_retries=max_retries, endpoint="submit",
        )
    # Critical-path stamp (see get_field_from_server): delivered by the
    # NEXT telemetry snapshot, after the server already journaled
    # submit_accepted — the waterfall composes both at read time.
    obs.journal.record_client_event(
        "submit_rtt", claim_id=submit_data.claim_id,
        secs=round(time.monotonic() - t0, 6),
    )
    if isinstance(resp, dict) and resp.get("duplicate"):
        log.info(
            "submit for claim %d was a duplicate: a retried request had "
            "already been accepted", submit_data.claim_id,
        )
    return resp if isinstance(resp, dict) else {"status": "OK"}


def renew_claim(
    api_base: str, claim_id: int, max_retries: int = 1
) -> None:
    """POST /renew_claim — lease heartbeat while a long field scans.

    Low default retry budget on purpose: a missed heartbeat is harmless (the
    next one, or the submit itself, lands well inside the expiry window), so
    the renewer thread must never sit in a 10-deep backoff while the scan it
    protects finishes."""
    # The renewer runs on its own thread, so re-derive the field's trace
    # context from the claim id rather than relying on an ambient one.
    with obs.trace_context(obs.claim_trace_id(claim_id)):
        failover_request(
            api_base, "/renew_claim", {"claim_id": claim_id},
            max_retries=max_retries, endpoint="renew",
        )


def claim_block_from_server(
    mode: SearchMode,
    api_base: str,
    username: str,
    count: int,
    max_retries: int = DEFAULT_MAX_RETRIES,
    tenant: Optional[str] = None,
    base_min: Optional[int] = None,
    base_max: Optional[int] = None,
) -> tuple[str, list[DataToClient]]:
    """POST /claim_block — N fields per round-trip under one block lease.

    Returns (block_id, fields). A server that predates block leases answers
    404; callers treat that ApiError as "fall back to per-field claims".
    tenant / base_min / base_max route the whole block for a scheduler
    tenant (see get_field_from_server)."""
    mode_arg = "detailed" if mode == SearchMode.DETAILED else "niceonly"
    payload = {"mode": mode_arg, "count": count, "username": username}
    if tenant is not None:
        payload["tenant"] = tenant
    if base_min is not None:
        payload["base_min"] = int(base_min)
    if base_max is not None:
        payload["base_max"] = int(base_max)
    resp = failover_request(
        api_base, "/claim_block", payload,
        max_retries=max_retries, endpoint="claim_block",
    )
    return resp["block_id"], [
        DataToClient.from_json(f) for f in resp["fields"]
    ]


def submit_block_to_server(
    api_base: str,
    block_id: str,
    submissions: list[DataToServer],
    telemetry: Optional[dict] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
) -> dict:
    """POST /submit_block — batched results for a block claim. The reply has
    one result per submission (in order) plus accepted/duplicates/rejected
    counts; duplicates are exactly-once replays, success not failure."""
    body: dict = {
        "block_id": block_id,
        "submissions": [s.to_json() for s in submissions],
    }
    if telemetry is not None:
        body["telemetry"] = telemetry
    with obs.span("client.submit_block", block=block_id, n=len(submissions)):
        resp = failover_request(
            api_base, "/submit_block", body,
            max_retries=max_retries, endpoint="submit_block",
        )
    if isinstance(resp, dict) and resp.get("duplicates"):
        log.info(
            "submit_block %s: %d of %d results were duplicates (retried "
            "requests already accepted)",
            block_id, resp["duplicates"], len(submissions),
        )
    return resp if isinstance(resp, dict) else {"status": "OK"}


def renew_block(api_base: str, block_id: str, max_retries: int = 1) -> None:
    """POST /renew_claim {block_id} — one heartbeat re-arms every member of
    the block lease (same low retry budget rationale as renew_claim)."""
    failover_request(
        api_base, "/renew_claim", {"block_id": block_id},
        max_retries=max_retries, endpoint="renew",
    )


def post_telemetry(
    api_base: str, snap: dict, max_retries: int = 1
) -> None:
    """POST /telemetry — lightweight fleet-visibility heartbeat.

    Best-effort by design (low retry budget, like renew_claim): a dropped
    heartbeat only delays the fleet dashboard by one period, and the
    reporter thread must never back off for minutes while the scan runs."""
    failover_request(
        api_base, "/telemetry", snap, max_retries=max_retries,
        endpoint="telemetry",
    )


def get_validation_data_from_server(
    api_base: str, username: str, base: Optional[int] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
) -> ValidationData:
    """GET /claim/validate (reference client_api_sync.rs:188-206)."""
    path = f"/claim/validate?username={urllib.request.quote(username)}"
    if base is not None:
        path += f"&base={base}"
    return ValidationData.from_json(
        failover_request(api_base, path, max_retries=max_retries,
                         endpoint="validate")
    )


class AsyncApi:
    """Thread-backed async facade so claim N+1 / submit N-1 overlap compute
    (the reference's 3-stage tokio pipeline, client/src/main.rs:411-562)."""

    def __init__(self, api_base: str, username: str, max_retries: int = DEFAULT_MAX_RETRIES):
        self.api_base = api_base
        self.username = username
        self.max_retries = max_retries
        self._pool = ThreadPoolExecutor(max_workers=2, thread_name_prefix="nice-api")

    def claim_async(self, mode: SearchMode):
        return self._pool.submit(
            get_field_from_server, mode, self.api_base, self.username, self.max_retries
        )

    def submit_async(self, data: DataToServer):
        return self._pool.submit(
            submit_field_to_server, self.api_base, data, self.max_retries
        )

    def claim_block_async(self, mode: SearchMode, count: int):
        return self._pool.submit(
            claim_block_from_server, mode, self.api_base, self.username,
            count, self.max_retries,
        )

    def submit_block_async(
        self,
        block_id: str,
        submissions: list[DataToServer],
        telemetry: Optional[dict] = None,
    ):
        return self._pool.submit(
            submit_block_to_server, self.api_base, block_id, submissions,
            telemetry, self.max_retries,
        )

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)
