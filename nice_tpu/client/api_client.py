"""Client HTTP transport: claim / submit / validate with retry + backoff.

Stdlib-only (urllib) equivalent of the reference's reqwest wrappers
(client_api_sync.rs:37-206): full-jitter exponential backoff (AWS
architecture-blog style: uniform(0, min(2^attempt, cap)) so a fleet of
clients knocked over by one server restart doesn't reconverge in lockstep),
retrying network errors and 5xx responses; 4xx errors surface immediately
with the server's message; a server-sent Retry-After (the 503 overload
shed) overrides the computed backoff. A thread-pool async facade gives the
overlap the reference gets from tokio (client_api_async.rs) without extra
dependencies.

Fault injection: every attempt passes through the http.<endpoint> site
(nice_tpu.faults), so NICE_TPU_FAULTS can synthesize 5xx responses,
connection errors, or — the nasty one — drop_response: the request REACHES
the server and is processed, but the client sees a network error and
retries, exercising the exactly-once submit path.
"""

from __future__ import annotations

import io
import json
import logging
import random
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from email.message import Message
from typing import Any, Optional

from nice_tpu import faults, obs
from nice_tpu.core.constants import CLIENT_REQUEST_TIMEOUT_SECS
from nice_tpu.core.types import DataToClient, DataToServer, SearchMode, ValidationData
from nice_tpu.obs.series import CLIENT_REQUEST_SECONDS, CLIENT_RETRIES

log = logging.getLogger(__name__)

DEFAULT_MAX_RETRIES = 10
MAX_BACKOFF_SECS = 512

# Backoff jitter source; module-level so tests can reseed for determinism.
_backoff_rng = random.Random()


class ApiError(Exception):
    """Non-retryable API failure.

    status: the HTTP status code when the server definitively answered
    (4xx — the request is rejected, retrying cannot help), or None when
    retries were exhausted on transient errors (the request MAY still
    succeed later; the submission spool uses the distinction)."""

    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


def _inject_http_fault(
    action: str, url: str, body: Optional[dict], timeout: float
) -> Any:
    """Apply an http.<endpoint> fault action. Raises for every action except
    an unknown one (which degrades to the real request)."""
    if action == "drop_response":
        # The server processes the request; the client never learns.
        _request_json(url, body, timeout)
        raise urllib.error.URLError(f"injected fault: response dropped for {url}")
    if action in ("conn_error", "raise"):
        raise urllib.error.URLError(f"injected fault: connection error for {url}")
    try:
        code = int(action)
    except ValueError:
        log.warning("unknown http fault action %r; passing through", action)
        return _request_json(url, body, timeout)
    raise urllib.error.HTTPError(
        url, code, f"injected fault: HTTP {code}", Message(),
        io.BytesIO(b"injected fault"),
    )


def _retry_after_secs(err: Exception) -> Optional[float]:
    """Delay-seconds from a server-sent Retry-After header, if any (the
    HTTP-date form is ignored — this server only emits delta-seconds)."""
    headers = getattr(err, "headers", None)
    if headers is None:
        return None
    value = headers.get("Retry-After")
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        return None


def _request_json(
    url: str,
    body: Optional[dict] = None,
    timeout: float = CLIENT_REQUEST_TIMEOUT_SECS,
) -> Any:
    data = None
    headers = {"Accept": "application/json"}
    if body is not None:
        data = json.dumps(body).encode()
        headers["Content-Type"] = "application/json"
    # Resolved here (not threaded through the retry loop) so the thread's
    # ambient trace context alone decides the header.
    traceparent = obs.current_traceparent()
    if traceparent:
        headers["traceparent"] = traceparent
    req = urllib.request.Request(url, data=data, headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        payload = resp.read()
    return json.loads(payload) if payload else None


def retry_request(
    url: str,
    body: Optional[dict] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
    timeout: float = CLIENT_REQUEST_TIMEOUT_SECS,
    endpoint: str = "other",
) -> Any:
    """GET/POST with full-jitter exponential backoff on 5xx and network
    errors: each retry sleeps uniform(0, min(2^attempt, cap)) seconds, unless
    the response carried Retry-After (server overload shed), which wins.

    endpoint labels the per-attempt latency histogram and retry counter
    (claim / submit / validate / renew / other). Every attempt carries a
    W3C traceparent header from the thread's ambient trace context (wrap the
    call in obs.trace_context to set it) so the server's handler span joins
    the field's distributed trace."""
    attempt = 0
    while True:
        t0 = time.monotonic()
        try:
            act = faults.fire(f"http.{endpoint}", url=url, attempt=attempt)
            if act is not None:
                result = _inject_http_fault(act, url, body, timeout)
            else:
                result = _request_json(url, body, timeout)
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            return result
        except urllib.error.HTTPError as e:
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            if e.code < 500:
                detail = ""
                try:
                    detail = e.read().decode(errors="replace")
                except Exception:
                    pass
                raise ApiError(
                    f"HTTP {e.code} from {url}: {detail}", status=e.code
                ) from e
            err: Exception = e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            err = e
        if attempt >= max_retries:
            raise ApiError(f"request to {url} failed after {attempt} retries: {err}")
        CLIENT_RETRIES.labels(endpoint).inc()
        obs.flight.record("retry", endpoint=endpoint, attempt=attempt,
                          error=str(err)[:200])
        hinted = _retry_after_secs(err)
        if hinted is not None:
            delay = min(hinted, MAX_BACKOFF_SECS)
        else:
            delay = _backoff_rng.uniform(0, min(2**attempt, MAX_BACKOFF_SECS))
        log.warning(
            "request failed (%s); retry %d in %.2fs%s",
            err, attempt + 1, delay,
            " (server Retry-After)" if hinted is not None else "",
        )
        time.sleep(delay)
        attempt += 1


def get_field_from_server(
    mode: SearchMode, api_base: str, username: str, max_retries: int = DEFAULT_MAX_RETRIES
) -> DataToClient:
    """GET /claim/{detailed|niceonly} (reference client_api_sync.rs:104-129)."""
    endpoint = "detailed" if mode == SearchMode.DETAILED else "niceonly"
    url = f"{api_base}/claim/{endpoint}?username={urllib.request.quote(username)}"
    return DataToClient.from_json(
        retry_request(url, max_retries=max_retries, endpoint="claim")
    )


def submit_field_to_server(
    api_base: str, submit_data: DataToServer, max_retries: int = DEFAULT_MAX_RETRIES
) -> dict:
    """POST /submit (reference client_api_sync.rs:144-172). Returns the
    server's response dict; {"duplicate": true} means a retried submit was
    already accepted (exactly-once via submit_id) — success, not an error."""
    # Derived (not ambient) trace id: AsyncApi runs submits on pool threads
    # where the field's trace_context isn't set, but the claim id is in the
    # payload, so the submit span still joins the field's trace.
    trace_id = obs.claim_trace_id(submit_data.claim_id)
    with obs.trace_context(trace_id), obs.span(
        "client.submit", claim=submit_data.claim_id
    ):
        resp = retry_request(
            f"{api_base}/submit", submit_data.to_json(),
            max_retries=max_retries, endpoint="submit",
        )
    if isinstance(resp, dict) and resp.get("duplicate"):
        log.info(
            "submit for claim %d was a duplicate: a retried request had "
            "already been accepted", submit_data.claim_id,
        )
    return resp if isinstance(resp, dict) else {"status": "OK"}


def renew_claim(
    api_base: str, claim_id: int, max_retries: int = 1
) -> None:
    """POST /renew_claim — lease heartbeat while a long field scans.

    Low default retry budget on purpose: a missed heartbeat is harmless (the
    next one, or the submit itself, lands well inside the expiry window), so
    the renewer thread must never sit in a 10-deep backoff while the scan it
    protects finishes."""
    # The renewer runs on its own thread, so re-derive the field's trace
    # context from the claim id rather than relying on an ambient one.
    with obs.trace_context(obs.claim_trace_id(claim_id)):
        retry_request(
            f"{api_base}/renew_claim", {"claim_id": claim_id},
            max_retries=max_retries, endpoint="renew",
        )


def post_telemetry(
    api_base: str, snap: dict, max_retries: int = 1
) -> None:
    """POST /telemetry — lightweight fleet-visibility heartbeat.

    Best-effort by design (low retry budget, like renew_claim): a dropped
    heartbeat only delays the fleet dashboard by one period, and the
    reporter thread must never back off for minutes while the scan runs."""
    retry_request(
        f"{api_base}/telemetry", snap, max_retries=max_retries,
        endpoint="telemetry",
    )


def get_validation_data_from_server(
    api_base: str, username: str, base: Optional[int] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
) -> ValidationData:
    """GET /claim/validate (reference client_api_sync.rs:188-206)."""
    url = f"{api_base}/claim/validate?username={urllib.request.quote(username)}"
    if base is not None:
        url += f"&base={base}"
    return ValidationData.from_json(
        retry_request(url, max_retries=max_retries, endpoint="validate")
    )


class AsyncApi:
    """Thread-backed async facade so claim N+1 / submit N-1 overlap compute
    (the reference's 3-stage tokio pipeline, client/src/main.rs:411-562)."""

    def __init__(self, api_base: str, username: str, max_retries: int = DEFAULT_MAX_RETRIES):
        self.api_base = api_base
        self.username = username
        self.max_retries = max_retries
        self._pool = ThreadPoolExecutor(max_workers=2, thread_name_prefix="nice-api")

    def claim_async(self, mode: SearchMode):
        return self._pool.submit(
            get_field_from_server, mode, self.api_base, self.username, self.max_retries
        )

    def submit_async(self, data: DataToServer):
        return self._pool.submit(
            submit_field_to_server, self.api_base, data, self.max_retries
        )

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)
