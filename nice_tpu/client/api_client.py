"""Client HTTP transport: claim / submit / validate with retry + backoff.

Stdlib-only (urllib) equivalent of the reference's reqwest wrappers
(client_api_sync.rs:37-206): full-jitter exponential backoff (AWS
architecture-blog style: uniform(0, min(2^attempt, cap)) so a fleet of
clients knocked over by one server restart doesn't reconverge in lockstep),
retrying network errors and 5xx responses; 4xx errors surface immediately
with the server's message; a server-sent Retry-After (the 503 overload
shed) overrides the computed backoff. A thread-pool async facade gives the
overlap the reference gets from tokio (client_api_async.rs) without extra
dependencies.

Fault injection: every attempt passes through the http.<endpoint> site
(nice_tpu.faults), so NICE_TPU_FAULTS can synthesize 5xx responses,
connection errors, or — the nasty one — drop_response: the request REACHES
the server and is processed, but the client sees a network error and
retries, exercising the exactly-once submit path.
"""

from __future__ import annotations

import contextlib
import http.client
import io
import json
import logging
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from email.message import Message
from typing import Any, Optional
from urllib.parse import urlsplit

from nice_tpu import faults, obs
from nice_tpu.core.constants import CLIENT_REQUEST_TIMEOUT_SECS
from nice_tpu.core.types import DataToClient, DataToServer, SearchMode, ValidationData
from nice_tpu.obs.series import CLIENT_REQUEST_SECONDS, CLIENT_RETRIES

log = logging.getLogger(__name__)

DEFAULT_MAX_RETRIES = 10
MAX_BACKOFF_SECS = 512

# Backoff jitter source; module-level so tests can reseed for determinism.
_backoff_rng = random.Random()


class ApiError(Exception):
    """Non-retryable API failure.

    status: the HTTP status code when the server definitively answered
    (4xx — the request is rejected, retrying cannot help), or None when
    retries were exhausted on transient errors (the request MAY still
    succeed later; the submission spool uses the distinction)."""

    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


def _inject_http_fault(
    action: str, url: str, body: Optional[dict], timeout: float
) -> Any:
    """Apply an http.<endpoint> fault action. Raises for every action except
    an unknown one (which degrades to the real request)."""
    if action == "drop_response":
        # The server processes the request; the client never learns.
        _request_json(url, body, timeout)
        raise urllib.error.URLError(f"injected fault: response dropped for {url}")
    if action in ("conn_error", "raise"):
        raise urllib.error.URLError(f"injected fault: connection error for {url}")
    try:
        code = int(action)
    except ValueError:
        log.warning("unknown http fault action %r; passing through", action)
        return _request_json(url, body, timeout)
    raise urllib.error.HTTPError(
        url, code, f"injected fault: HTTP {code}", Message(),
        io.BytesIO(b"injected fault"),
    )


def _retry_after_secs(err: Exception) -> Optional[float]:
    """Delay-seconds from a server-sent Retry-After header, if any (the
    HTTP-date form is ignored — this server only emits delta-seconds)."""
    headers = getattr(err, "headers", None)
    if headers is None:
        return None
    value = headers.get("Retry-After")
    if value is None:
        return None
    try:
        return max(0.0, float(value))
    except ValueError:
        return None


# Per-thread keep-alive connection pool, keyed by (scheme, host:port). One
# persistent socket per server per thread replaces the fresh TCP handshake
# urllib.request paid on EVERY call; the server speaks HTTP/1.1 keep-alive
# on both cores, so a pipelined client reuses one connection for its whole
# lifetime (the load harness measures the RTT delta). Thread-local because
# http.client connections are not thread-safe and the AsyncApi pool plus the
# renew/telemetry threads each need their own.
_conn_local = threading.local()

# Errors that mean the REUSED socket went stale (server closed an idle
# keep-alive connection): safe to transparently retry once on a fresh
# socket. On a brand-new connection the same errors are real failures and
# propagate to retry_request's backoff (which is also where the
# exactly-once submit_id story absorbs any ambiguous resend).
_STALE_ERRORS = (
    http.client.BadStatusLine,
    http.client.RemoteDisconnected,
    BrokenPipeError,
    ConnectionResetError,
    ConnectionAbortedError,
)


def _conn_pool() -> dict:
    pool = getattr(_conn_local, "pool", None)
    if pool is None:
        pool = _conn_local.pool = {}
    return pool


def _drop_connection(key) -> None:
    conn = _conn_pool().pop(key, None)
    if conn is not None:
        with contextlib.suppress(Exception):
            conn.close()


def close_connections() -> None:
    """Close this thread's pooled connections (tests / clean shutdown)."""
    pool = _conn_pool()
    for conn in pool.values():
        with contextlib.suppress(Exception):
            conn.close()
    pool.clear()


def _request_json(
    url: str,
    body: Optional[dict] = None,
    timeout: float = CLIENT_REQUEST_TIMEOUT_SECS,
) -> Any:
    parts = urlsplit(url)
    if parts.scheme not in ("http", "https"):
        raise urllib.error.URLError(f"unsupported scheme in {url!r}")
    target = parts.path or "/"
    if parts.query:
        target += "?" + parts.query
    data = None
    headers = {"Accept": "application/json"}
    if body is not None:
        data = json.dumps(body).encode()
        headers["Content-Type"] = "application/json"
    # Resolved here (not threaded through the retry loop) so the thread's
    # ambient trace context alone decides the header.
    traceparent = obs.current_traceparent()
    if traceparent:
        headers["traceparent"] = traceparent
    method = "GET" if body is None else "POST"
    key = (parts.scheme, parts.netloc)
    pool = _conn_pool()
    for fresh_retry in (False, True):
        conn = pool.get(key)
        reused = conn is not None
        if conn is None:
            cls = (
                http.client.HTTPSConnection
                if parts.scheme == "https"
                else http.client.HTTPConnection
            )
            conn = cls(parts.netloc, timeout=timeout)
            pool[key] = conn
        conn.timeout = timeout
        if conn.sock is not None:
            conn.sock.settimeout(timeout)
        try:
            conn.request(method, target, body=data, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
        except _STALE_ERRORS as e:
            _drop_connection(key)
            if reused and not fresh_retry:
                continue
            raise urllib.error.URLError(f"{e.__class__.__name__}: {e}") from e
        except OSError:
            # Connect/socket failure: state unknown, never silently resend.
            _drop_connection(key)
            raise
        if resp.will_close:
            _drop_connection(key)
        if resp.status >= 400:
            raise urllib.error.HTTPError(
                url, resp.status, resp.reason, resp.headers, io.BytesIO(payload)
            )
        return json.loads(payload) if payload else None


def retry_request(
    url: str,
    body: Optional[dict] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
    timeout: float = CLIENT_REQUEST_TIMEOUT_SECS,
    endpoint: str = "other",
) -> Any:
    """GET/POST with full-jitter exponential backoff on 5xx, 429, and
    network errors: each retry sleeps uniform(0, min(2^attempt, cap))
    seconds, unless the response carried Retry-After (server overload shed
    or per-client rate limit), which wins.

    endpoint labels the per-attempt latency histogram and retry counter
    (claim / submit / validate / renew / other). Every attempt carries a
    W3C traceparent header from the thread's ambient trace context (wrap the
    call in obs.trace_context to set it) so the server's handler span joins
    the field's distributed trace."""
    attempt = 0
    while True:
        t0 = time.monotonic()
        try:
            act = faults.fire(f"http.{endpoint}", url=url, attempt=attempt)
            if act is not None:
                result = _inject_http_fault(act, url, body, timeout)
            else:
                result = _request_json(url, body, timeout)
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            return result
        except urllib.error.HTTPError as e:
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            if e.code < 500 and e.code != 429:
                # 4xx = our request is wrong, retrying won't fix it — except
                # 429 (per-client rate limit): that clears with time, so it
                # backs off like a 5xx, honoring the server's Retry-After.
                detail = ""
                try:
                    detail = e.read().decode(errors="replace")
                except Exception:
                    pass
                raise ApiError(
                    f"HTTP {e.code} from {url}: {detail}", status=e.code
                ) from e
            err: Exception = e
        except (urllib.error.URLError, TimeoutError, OSError) as e:
            CLIENT_REQUEST_SECONDS.labels(endpoint).observe(
                time.monotonic() - t0
            )
            err = e
        if attempt >= max_retries:
            # Preserve the HTTP status when the last failure was a definite
            # server answer (429/5xx), so callers can distinguish "rate
            # limited until I slow down" from a dead transport.
            raise ApiError(
                f"request to {url} failed after {attempt} retries: {err}",
                status=getattr(err, "code", None),
            )
        CLIENT_RETRIES.labels(endpoint).inc()
        obs.flight.record("retry", endpoint=endpoint, attempt=attempt,
                          error=str(err)[:200])
        hinted = _retry_after_secs(err)
        if hinted is not None:
            delay = min(hinted, MAX_BACKOFF_SECS)
        else:
            delay = _backoff_rng.uniform(0, min(2**attempt, MAX_BACKOFF_SECS))
        log.warning(
            "request failed (%s); retry %d in %.2fs%s",
            err, attempt + 1, delay,
            " (server Retry-After)" if hinted is not None else "",
        )
        time.sleep(delay)
        attempt += 1


def get_field_from_server(
    mode: SearchMode, api_base: str, username: str,
    max_retries: int = DEFAULT_MAX_RETRIES,
    tenant: Optional[str] = None,
    base_min: Optional[int] = None,
    base_max: Optional[int] = None,
) -> DataToClient:
    """GET /claim/{detailed|niceonly} (reference client_api_sync.rs:104-129).

    tenant / base_min / base_max are the multi-tenant scheduler's claim
    routing: the claim row is stamped with the tenant name and the field is
    drawn from the tenant's base window. Pre-sched servers ignore the extra
    query params, so the scheduler degrades to unrouted claims."""
    endpoint = "detailed" if mode == SearchMode.DETAILED else "niceonly"
    url = f"{api_base}/claim/{endpoint}?username={urllib.request.quote(username)}"
    if tenant is not None:
        url += f"&tenant={urllib.request.quote(tenant)}"
    if base_min is not None:
        url += f"&base_min={int(base_min)}"
    if base_max is not None:
        url += f"&base_max={int(base_max)}"
    t0 = time.monotonic()
    data = DataToClient.from_json(
        retry_request(url, max_retries=max_retries, endpoint="claim")
    )
    # Critical-path stamp: the claim round-trip as the CLIENT experienced it
    # (retries and backoff included — that wait is real end-to-end latency).
    # Rides the next telemetry snapshot into this field's journal timeline.
    obs.journal.record_client_event(
        "claim_rtt", claim_id=data.claim_id,
        secs=round(time.monotonic() - t0, 6),
    )
    return data


def submit_field_to_server(
    api_base: str, submit_data: DataToServer, max_retries: int = DEFAULT_MAX_RETRIES
) -> dict:
    """POST /submit (reference client_api_sync.rs:144-172). Returns the
    server's response dict; {"duplicate": true} means a retried submit was
    already accepted (exactly-once via submit_id) — success, not an error."""
    # Derived (not ambient) trace id: AsyncApi runs submits on pool threads
    # where the field's trace_context isn't set, but the claim id is in the
    # payload, so the submit span still joins the field's trace.
    trace_id = obs.claim_trace_id(submit_data.claim_id)
    t0 = time.monotonic()
    with obs.trace_context(trace_id), obs.span(
        "client.submit", claim=submit_data.claim_id
    ):
        resp = retry_request(
            f"{api_base}/submit", submit_data.to_json(),
            max_retries=max_retries, endpoint="submit",
        )
    # Critical-path stamp (see get_field_from_server): delivered by the
    # NEXT telemetry snapshot, after the server already journaled
    # submit_accepted — the waterfall composes both at read time.
    obs.journal.record_client_event(
        "submit_rtt", claim_id=submit_data.claim_id,
        secs=round(time.monotonic() - t0, 6),
    )
    if isinstance(resp, dict) and resp.get("duplicate"):
        log.info(
            "submit for claim %d was a duplicate: a retried request had "
            "already been accepted", submit_data.claim_id,
        )
    return resp if isinstance(resp, dict) else {"status": "OK"}


def renew_claim(
    api_base: str, claim_id: int, max_retries: int = 1
) -> None:
    """POST /renew_claim — lease heartbeat while a long field scans.

    Low default retry budget on purpose: a missed heartbeat is harmless (the
    next one, or the submit itself, lands well inside the expiry window), so
    the renewer thread must never sit in a 10-deep backoff while the scan it
    protects finishes."""
    # The renewer runs on its own thread, so re-derive the field's trace
    # context from the claim id rather than relying on an ambient one.
    with obs.trace_context(obs.claim_trace_id(claim_id)):
        retry_request(
            f"{api_base}/renew_claim", {"claim_id": claim_id},
            max_retries=max_retries, endpoint="renew",
        )


def claim_block_from_server(
    mode: SearchMode,
    api_base: str,
    username: str,
    count: int,
    max_retries: int = DEFAULT_MAX_RETRIES,
    tenant: Optional[str] = None,
    base_min: Optional[int] = None,
    base_max: Optional[int] = None,
) -> tuple[str, list[DataToClient]]:
    """POST /claim_block — N fields per round-trip under one block lease.

    Returns (block_id, fields). A server that predates block leases answers
    404; callers treat that ApiError as "fall back to per-field claims".
    tenant / base_min / base_max route the whole block for a scheduler
    tenant (see get_field_from_server)."""
    mode_arg = "detailed" if mode == SearchMode.DETAILED else "niceonly"
    payload = {"mode": mode_arg, "count": count, "username": username}
    if tenant is not None:
        payload["tenant"] = tenant
    if base_min is not None:
        payload["base_min"] = int(base_min)
    if base_max is not None:
        payload["base_max"] = int(base_max)
    resp = retry_request(
        f"{api_base}/claim_block",
        payload,
        max_retries=max_retries,
        endpoint="claim_block",
    )
    return resp["block_id"], [
        DataToClient.from_json(f) for f in resp["fields"]
    ]


def submit_block_to_server(
    api_base: str,
    block_id: str,
    submissions: list[DataToServer],
    telemetry: Optional[dict] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
) -> dict:
    """POST /submit_block — batched results for a block claim. The reply has
    one result per submission (in order) plus accepted/duplicates/rejected
    counts; duplicates are exactly-once replays, success not failure."""
    body: dict = {
        "block_id": block_id,
        "submissions": [s.to_json() for s in submissions],
    }
    if telemetry is not None:
        body["telemetry"] = telemetry
    with obs.span("client.submit_block", block=block_id, n=len(submissions)):
        resp = retry_request(
            f"{api_base}/submit_block", body,
            max_retries=max_retries, endpoint="submit_block",
        )
    if isinstance(resp, dict) and resp.get("duplicates"):
        log.info(
            "submit_block %s: %d of %d results were duplicates (retried "
            "requests already accepted)",
            block_id, resp["duplicates"], len(submissions),
        )
    return resp if isinstance(resp, dict) else {"status": "OK"}


def renew_block(api_base: str, block_id: str, max_retries: int = 1) -> None:
    """POST /renew_claim {block_id} — one heartbeat re-arms every member of
    the block lease (same low retry budget rationale as renew_claim)."""
    retry_request(
        f"{api_base}/renew_claim", {"block_id": block_id},
        max_retries=max_retries, endpoint="renew",
    )


def post_telemetry(
    api_base: str, snap: dict, max_retries: int = 1
) -> None:
    """POST /telemetry — lightweight fleet-visibility heartbeat.

    Best-effort by design (low retry budget, like renew_claim): a dropped
    heartbeat only delays the fleet dashboard by one period, and the
    reporter thread must never back off for minutes while the scan runs."""
    retry_request(
        f"{api_base}/telemetry", snap, max_retries=max_retries,
        endpoint="telemetry",
    )


def get_validation_data_from_server(
    api_base: str, username: str, base: Optional[int] = None,
    max_retries: int = DEFAULT_MAX_RETRIES,
) -> ValidationData:
    """GET /claim/validate (reference client_api_sync.rs:188-206)."""
    url = f"{api_base}/claim/validate?username={urllib.request.quote(username)}"
    if base is not None:
        url += f"&base={base}"
    return ValidationData.from_json(
        retry_request(url, max_retries=max_retries, endpoint="validate")
    )


class AsyncApi:
    """Thread-backed async facade so claim N+1 / submit N-1 overlap compute
    (the reference's 3-stage tokio pipeline, client/src/main.rs:411-562)."""

    def __init__(self, api_base: str, username: str, max_retries: int = DEFAULT_MAX_RETRIES):
        self.api_base = api_base
        self.username = username
        self.max_retries = max_retries
        self._pool = ThreadPoolExecutor(max_workers=2, thread_name_prefix="nice-api")

    def claim_async(self, mode: SearchMode):
        return self._pool.submit(
            get_field_from_server, mode, self.api_base, self.username, self.max_retries
        )

    def submit_async(self, data: DataToServer):
        return self._pool.submit(
            submit_field_to_server, self.api_base, data, self.max_retries
        )

    def claim_block_async(self, mode: SearchMode, count: int):
        return self._pool.submit(
            claim_block_from_server, mode, self.api_base, self.username,
            count, self.max_retries,
        )

    def submit_block_async(
        self,
        block_id: str,
        submissions: list[DataToServer],
        telemetry: Optional[dict] = None,
    ):
        return self._pool.submit(
            submit_block_to_server, self.api_base, block_id, submissions,
            telemetry, self.max_retries,
        )

    def shutdown(self) -> None:
        self._pool.shutdown(wait=True)
