"""Search client: HTTP transport (L2) and the CLI binary (L4)."""
