"""Multi-chip scaling: device mesh, shard_map field processing, ICI collectives."""
