"""Device mesh and sharded field processing.

The system's one long axis is the number line (reference SURVEY.md section 5:
base range -> chunks -> fields -> processing chunks -> lanes). Multi-chip
scaling is sequence-parallelism over that axis: a field batch is sharded
across the mesh's "field" axis, every device derives its candidates from its
axis index (zero input transfer), and the per-device digit-histograms are
reduced with a psum over ICI (the TPU analog of the reference's warp -> block
-> global -> host reduction chain, nice_kernels.cu:496-530 / P8).

The control plane (HTTP checkout/submit) stays on DCN, exactly as the
reference keeps its coordination on HTTP while compute scales on-device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from nice_tpu.ops import vector_engine as ve
from nice_tpu.ops.limbs import BasePlan

FIELD_AXIS = "field"


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or given) devices; the axis shards the number line."""
    devices = devices if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (FIELD_AXIS,))


from nice_tpu.ops.vector_engine import histogram_lanes  # re-export (shared)


def make_sharded_detailed_step(plan: BasePlan, per_device_batch: int, mesh: Mesh):
    """Jitted multi-chip detailed step.

    Each device processes per_device_batch consecutive candidates starting at
    start + axis_index * per_device_batch; histograms are psum-reduced over
    ICI so every device returns the full-field histogram.

    Returns fn(start_limbs u32[limbs_n], valid_count i32) ->
    (histogram i32[base+2], near_miss_count i32), both replicated.
    """

    def device_step(start_limbs, valid_count):
        dev = jax.lax.axis_index(FIELD_AXIS)
        offset = dev.astype(jnp.uint32) * np.uint32(per_device_batch)
        idx = jnp.arange(per_device_batch, dtype=jnp.uint32) + offset
        base_limbs = [
            jnp.broadcast_to(start_limbs[i], (per_device_batch,))
            for i in range(plan.limbs_n)
        ]
        n = ve.add_u32(base_limbs, idx)
        uniques = ve.num_uniques_lanes(plan, n)
        valid = idx.astype(jnp.int32) < valid_count
        hist, nm = ve.detailed_from_uniques(plan, uniques, valid)
        hist = jax.lax.psum(hist, FIELD_AXIS)
        nm = jax.lax.psum(nm, FIELD_AXIS)
        return hist, nm

    sharded = jax.shard_map(
        device_step,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=(P(), P()),
        check_vma=False,
    )
    return jax.jit(sharded)


def make_sharded_niceonly_step(plan: BasePlan, per_device_batch: int, mesh: Mesh):
    """Jitted multi-chip niceonly (dense) step: psum'd count of fully nice
    lanes across the mesh."""

    def device_step(start_limbs, valid_count):
        dev = jax.lax.axis_index(FIELD_AXIS)
        offset = dev.astype(jnp.uint32) * np.uint32(per_device_batch)
        idx = jnp.arange(per_device_batch, dtype=jnp.uint32) + offset
        base_limbs = [
            jnp.broadcast_to(start_limbs[i], (per_device_batch,))
            for i in range(plan.limbs_n)
        ]
        n = ve.add_u32(base_limbs, idx)
        uniques = ve.num_uniques_lanes(plan, n)
        valid = idx.astype(jnp.int32) < valid_count
        count = jnp.sum((valid & (uniques == plan.base)).astype(jnp.int32))
        return jax.lax.psum(count, FIELD_AXIS)

    sharded = jax.shard_map(
        device_step,
        mesh=mesh,
        in_specs=(P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    return jax.jit(sharded)
