"""Device mesh and sharded field processing.

The system's one long axis is the number line (reference SURVEY.md section 5:
base range -> chunks -> fields -> processing chunks -> lanes). Multi-chip
scaling is sequence-parallelism over that axis: a field batch is sharded
across the mesh's "field" axis, every device derives its candidates from its
axis index (zero input transfer), and the per-device digit-histograms are
reduced with a psum over ICI (the TPU analog of the reference's warp -> block
-> global -> host reduction chain, nice_kernels.cu:496-530 / P8).

The control plane (HTTP checkout/submit) stays on DCN, exactly as the
reference keeps its coordination on HTTP while compute scales on-device.
"""

from __future__ import annotations

import logging
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from nice_tpu.obs.series import MESH_DEVICES, MESH_DISPATCH_SECONDS
from nice_tpu.ops import vector_engine as ve
from nice_tpu.ops.limbs import BasePlan
from nice_tpu.utils import lockdep

log = logging.getLogger(__name__)

FIELD_AXIS = "field"


class MeshDeviceLost(RuntimeError):
    """A mesh dispatch failed because one or more devices dropped.

    lost: positions along the mesh's field axis (NOT device ids) of the
    devices believed dead. Raised by the chaos hook (faults site
    mesh.dispatch, action dead[:i[+j...]]) and available for real device-loss
    detection; ops/engine.py catches it at the elastic downshift boundary."""

    def __init__(self, lost, cause: BaseException | None = None):
        self.lost = tuple(sorted(set(int(i) for i in lost)))
        self.cause = cause
        super().__init__(f"mesh device(s) lost at axis position(s) {self.lost}")


# --- device liveness (real probes + simulated loss for chaos tests) -------

_dead_lock = lockdep.make_lock("parallel.mesh._dead_lock")
_simulated_dead: set[int] = set()


def simulate_device_loss(device_ids) -> None:
    """Mark device ids as dead for probe_devices/live_devices. Lets chaos
    tests (and the fault injector's dead:<i> action) drive the elastic
    downshift path on hardware that cannot actually lose a device."""
    with _dead_lock:
        _simulated_dead.update(int(i) for i in device_ids)


def heal_devices() -> None:
    """Clear every simulated device loss (test teardown)."""
    with _dead_lock:
        _simulated_dead.clear()


def live_devices(devices) -> list:
    """Filter out simulated-dead devices (cheap; no probe dispatch)."""
    with _dead_lock:
        dead = set(_simulated_dead)
    return [d for d in devices if int(d.id) not in dead]


def probe_devices(devices) -> tuple[list, list]:
    """Partition devices into (alive, lost) by running a trivial transfer +
    add on each. Simulated-dead devices always count as lost."""
    with _dead_lock:
        dead = set(_simulated_dead)
    alive, lost = [], []
    for d in devices:
        if int(d.id) in dead:
            lost.append(d)
            continue
        try:
            x = jax.device_put(np.ones((), dtype=np.int32), d) + 1
            # nicelint: fence (probe readback proves the device computes)
            if int(np.asarray(x)) != 2:
                raise RuntimeError("device probe computed garbage")
            alive.append(d)
        except Exception:  # noqa: BLE001 — any failure means "not usable"
            lost.append(d)
    return alive, lost


def mesh_device_ids(mesh: Mesh) -> tuple[int, ...]:
    """The cache identity of a mesh: its device ids in axis order."""
    return tuple(int(d.id) for d in mesh.devices.flat)


# --- sharded-step cache ----------------------------------------------------
# Jitted sharded steps are cached per (kind, device ids, shape key) instead
# of per Mesh object: two Mesh objects over the same devices share entries,
# and — the part lru_cache got wrong — entries for a mesh that lost a device
# can be evicted on downshift instead of pinning the dead Mesh (and its
# compiled executables) for the life of the process.

_step_lock = lockdep.make_lock("parallel.mesh._step_lock")
_STEP_CACHE: dict = {}


def _step_cached(kind: str, mesh: Mesh, extra_key, build):
    key = (kind, mesh_device_ids(mesh), extra_key)
    with _step_lock:
        step = _STEP_CACHE.get(key)
    if step is not None:
        return step
    step = build()
    with _step_lock:
        return _STEP_CACHE.setdefault(key, step)


def clear_step_cache(device_ids=None) -> int:
    """Evict cached sharded steps. device_ids (an iterable of ids, order-
    insensitive) evicts every entry whose mesh contains ANY of those devices
    — the downshift calls this with the dead mesh's ids so no stale Mesh
    stays reachable. None clears everything. Returns entries dropped."""
    with _step_lock:
        if device_ids is None:
            n = len(_STEP_CACHE)
            _STEP_CACHE.clear()
            return n
        ids = set(int(i) for i in device_ids)
        doomed = [k for k in _STEP_CACHE if ids.intersection(k[1])]
        for k in doomed:
            del _STEP_CACHE[k]
        return len(doomed)


def partition_segments(segments, n_slices: int, batch_size: int) -> list[list]:
    """Split ascending, disjoint [start, end) segments into n_slices work
    queues (lists of segments) of near-equal total size, cut points aligned
    to batch_size so every slice dispatches whole batches until its tail.

    This is the pod-slicing primitive: a field's (remaining) cursor range
    becomes one queue per device, and a downshift re-runs it over the
    survivors' count — slices may span several segments after a reshard."""
    segs = [(int(s), int(e)) for s, e in segments if int(e) > int(s)]
    n_slices = max(1, int(n_slices))
    if not segs:
        return [[] for _ in range(n_slices)]
    total = sum(e - s for s, e in segs)
    per = -(-total // n_slices)
    per = -(-per // batch_size) * batch_size
    out: list[list] = []
    cur: list = []
    room = per
    i = 0
    while i < len(segs):
        s, e = segs[i]
        if len(out) >= n_slices - 1:
            cur.append((s, e))
            i += 1
            continue
        take = min(room, e - s)
        cur.append((s, s + take))
        room -= take
        if take < e - s:
            segs[i] = (s + take, e)
        else:
            i += 1
        if room == 0:
            out.append(cur)
            cur = []
            room = per
    out.append(cur)
    while len(out) < n_slices:
        out.append([])
    return out


def _shard_map(f, mesh: Mesh, in_specs, out_specs):
    """shard_map across jax versions: `jax.shard_map(..., check_vma=)` on
    current jax, `jax.experimental.shard_map.shard_map(..., check_rep=)` on
    0.4.x. Replication checking is off either way — every step here returns
    explicitly psum'd (or deliberately sharded) outputs."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


# Serializes the ENQUEUE of every sharded executable. Two threads dispatching
# collective programs concurrently (the feed loop's step and the collector's
# histogram fold) can enqueue them in a different order on different devices;
# per-device queues then each wait on the other program's replicas — a
# classic collective deadlock (observed on the 8-virtual-device CPU mesh).
# Holding the lock across the jit call makes the cross-device enqueue order
# consistent; execution itself stays async and overlapped.
_DISPATCH_LOCK = lockdep.make_rlock("parallel.mesh._DISPATCH_LOCK")


def _timed_step(fn, mode: str):
    """Wrap a jitted sharded step so each dispatch lands in
    nice_mesh_dispatch_seconds{mode=...} (async enqueue cost under jit)."""
    import time as _time

    import functools as _functools

    @_functools.wraps(fn)
    def timed(*args, **kwargs):
        t0 = _time.perf_counter()
        try:
            with _DISPATCH_LOCK:
                return fn(*args, **kwargs)
        finally:
            MESH_DISPATCH_SECONDS.labels(mode).observe(
                _time.perf_counter() - t0
            )

    return timed


class OccupancyMeter:
    """Accumulates device-busy wall time against an observation window so
    the multi-tenant scheduler can report mesh occupancy (busy/wall) per
    tenant and overall. Busy intervals are attributed by tenant label;
    thread-safe because the scheduler's page loop and its SLO periodic
    both read it."""

    def __init__(self):
        self._lock = lockdep.make_lock("parallel.mesh.OccupancyMeter._lock")
        self._busy: dict[str, float] = {}
        self._started: float | None = None
        self._stopped: float | None = None

    def start(self, now: float) -> None:
        with self._lock:
            if self._started is None:
                self._started = now
            self._stopped = None

    def stop(self, now: float) -> None:
        with self._lock:
            self._stopped = now

    def add_busy(self, tenant: str, secs: float) -> None:
        if secs <= 0:
            return
        with self._lock:
            self._busy[tenant] = self._busy.get(tenant, 0.0) + secs

    def busy_secs(self, tenant: str | None = None) -> float:
        with self._lock:
            if tenant is not None:
                return self._busy.get(tenant, 0.0)
            return sum(self._busy.values())

    def wall_secs(self, now: float | None = None) -> float:
        with self._lock:
            if self._started is None:
                return 0.0
            end = self._stopped if self._stopped is not None else now
            if end is None:
                return 0.0
            return max(0.0, end - self._started)

    def occupancy(self, now: float | None = None) -> float:
        """Overall busy/wall in [0, 1]; 0 before the window opens."""
        wall = self.wall_secs(now)
        if wall <= 0:
            return 0.0
        return min(1.0, self.busy_secs() / wall)

    def shares(self) -> dict[str, float]:
        """Each tenant's fraction of total busy time (sums to ~1)."""
        with self._lock:
            total = sum(self._busy.values())
            if total <= 0:
                return {t: 0.0 for t in self._busy}
            return {t: b / total for t, b in self._busy.items()}


def make_mesh(devices=None) -> Mesh:
    """1-D mesh over all (or given) devices; the axis shards the number line."""
    devices = devices if devices is not None else jax.devices()
    MESH_DEVICES.set(len(devices))
    # nicelint: allow D1 (host-side device list, no transfer)
    return Mesh(np.asarray(devices), (FIELD_AXIS,))


from nice_tpu.ops.vector_engine import histogram_lanes  # re-export (shared)


def make_sharded_detailed_step(plan: BasePlan, per_device_batch: int, mesh: Mesh):
    """Jitted multi-chip detailed step.

    Each device processes per_device_batch consecutive candidates starting at
    start + axis_index * per_device_batch; histograms are psum-reduced over
    ICI so every device returns the full-field histogram.

    Returns fn(start_limbs u32[limbs_n], valid_count i32) ->
    (histogram i32[base+2], near_miss_count i32), both replicated.
    """

    def device_step(start_limbs, valid_count):
        dev = jax.lax.axis_index(FIELD_AXIS)
        offset = dev.astype(jnp.uint32) * np.uint32(per_device_batch)
        idx = jnp.arange(per_device_batch, dtype=jnp.uint32) + offset
        base_limbs = [
            jnp.broadcast_to(start_limbs[i], (per_device_batch,))
            for i in range(plan.limbs_n)
        ]
        n = ve.add_u32(base_limbs, idx)
        uniques = ve.num_uniques_lanes(plan, n)
        valid = idx.astype(jnp.int32) < valid_count
        hist, nm = ve.detailed_from_uniques(plan, uniques, valid)
        hist = jax.lax.psum(hist, FIELD_AXIS)
        nm = jax.lax.psum(nm, FIELD_AXIS)
        return hist, nm

    sharded = _shard_map(
        device_step, mesh, in_specs=(P(), P()), out_specs=(P(), P())
    )
    return jax.jit(sharded)


def make_sharded_stats_step(
    plan: BasePlan,
    per_device_batch: int,
    mesh: Mesh,
    mode: str,
    kernel: str = "auto",
):
    """Production multi-chip stats step: every device runs the SINGLE-CHIP
    batch engine — the Mosaic/Pallas stats kernel on TPU, the jnp graph
    elsewhere — on its own (start, valid) slice, and the stats are psum-reduced
    over ICI. This is the step ops/engine.py dispatches when more than one
    device is visible, so the multi-chip path exercises the exact same kernels
    as single-chip (ref reduction chain P8, nice_kernels.cu:496-530).

    mode: "detailed" | "niceonly".
    kernel: "pallas" | "jnp" | "auto" (pallas iff it would be picked
    single-chip: TPU backend + base fits the stats tile + whole blocks).

    Returns fn(starts u32[n_dev, limbs_n], valids i32[n_dev]) with per-device
    start limbs / valid counts computed exactly on the host (no in-graph
    offset arithmetic -> no u32 overflow concerns at any field size):
      detailed -> (histogram i32[>=base+2], near_miss_count i32), replicated
      niceonly -> nice count i32, replicated
    """
    return _step_cached(
        "stats", mesh, (plan, per_device_batch, mode, kernel),
        lambda: _build_stats_step(plan, per_device_batch, mesh, mode, kernel),
    )


def _build_stats_step(plan, per_device_batch, mesh, mode, kernel):
    from nice_tpu.ops import pallas_engine as pe

    kernel = _resolve_kernel(plan, per_device_batch, kernel)
    mod = pe if kernel == "pallas" else ve
    if mode == "detailed":
        run = lambda start, valid: mod.detailed_batch(  # noqa: E731
            plan, per_device_batch, start, valid
        )
    else:
        run = lambda start, valid: (  # noqa: E731
            None,
            mod.niceonly_dense_batch(plan, per_device_batch, start, valid),
        )

    def device_step(start_row, valid_row):
        hist, count = run(start_row[0], valid_row[0])
        count = jax.lax.psum(count, FIELD_AXIS)
        if mode == "detailed":
            return jax.lax.psum(hist, FIELD_AXIS), count
        return count

    sharded = _shard_map(
        device_step,
        mesh,
        in_specs=(P(FIELD_AXIS, None), P(FIELD_AXIS)),
        out_specs=(P(), P()) if mode == "detailed" else P(),
    )
    return _timed_step(jax.jit(sharded), mode)


def _resolve_kernel(plan: BasePlan, per_device_batch: int, kernel: str):
    """Shared "auto" resolution: pallas iff it would be picked single-chip."""
    from nice_tpu.ops import pallas_engine as pe

    if kernel != "auto":
        return kernel
    return (
        "pallas"
        if (
            jax.default_backend() == "tpu"
            and pe.supports_base(plan)
            and per_device_batch % 128 == 0
        )
        else "jnp"
    )


def make_sharded_stats_accum_step(
    plan: BasePlan,
    per_device_batch: int,
    mesh: Mesh,
    kernel: str = "auto",
):
    """Detailed step with a DEVICE-RESIDENT per-device histogram accumulator.

    Each device folds its batch histogram into its own row of a sharded
    accumulator (donated, so the buffer is carried across batches in place);
    only the psum'd near-miss scalar is replicated per batch. The accumulator
    rows stay un-reduced until make_sharded_stats_fold performs the single
    per-field psum — one collective per field for the histogram instead of
    one per batch (ISSUE 2 tentpole part 2).

    Returns fn(hist_acc i32[n_dev, base+2] sharded on FIELD_AXIS,
               starts u32[n_dev, limbs_n], valids i32[n_dev])
      -> (new_hist_acc, sharded; near_miss_count i32, replicated)
    """
    return _step_cached(
        "stats-accum", mesh, (plan, per_device_batch, kernel),
        lambda: _build_stats_accum_step(plan, per_device_batch, mesh, kernel),
    )


def _build_stats_accum_step(plan, per_device_batch, mesh, kernel):
    from nice_tpu.ops import pallas_engine as pe

    kernel = _resolve_kernel(plan, per_device_batch, kernel)
    mod = pe if kernel == "pallas" else ve
    width = plan.base + 2

    def device_step(hist_row, start_row, valid_row):
        hist, nm = mod.detailed_batch(
            plan, per_device_batch, start_row[0], valid_row[0]
        )
        return hist_row + hist[None, :width], jax.lax.psum(nm, FIELD_AXIS)

    sharded = _shard_map(
        device_step,
        mesh,
        in_specs=(P(FIELD_AXIS, None), P(FIELD_AXIS, None), P(FIELD_AXIS)),
        out_specs=(P(FIELD_AXIS, None), P()),
    )
    return _timed_step(jax.jit(sharded, donate_argnums=(0,)), "detailed-accum")


def make_sharded_megaloop_accum_step(
    plan: BasePlan,
    per_device_batch: int,
    seg: int,
    mesh: Mesh,
    kernel: str = "auto",
):
    """Megaloop variant of make_sharded_stats_accum_step: each device runs a
    `seg`-iteration lax.scan that advances its own cursor in-program and folds
    every batch histogram into its row of the donated sharded accumulator —
    one collective dispatch per SEGMENT instead of per batch, with a single
    psum'd near-miss total per segment. The per-device valid count is the
    device's whole-segment lane budget (up to per_device_batch * seg); a
    short tail masks exactly as the per-batch step does.

    Returns fn(hist_acc i32[n_dev, base+2] sharded on FIELD_AXIS,
               starts u32[n_dev, limbs_n], valids i32[n_dev])
      -> (new_hist_acc, sharded; near_miss_total i32, replicated)
    """
    return _step_cached(
        "stats-accum-mega", mesh, (plan, per_device_batch, seg, kernel),
        lambda: _build_megaloop_accum_step(plan, per_device_batch, seg, mesh,
                                           kernel),
    )


def _build_megaloop_accum_step(plan, per_device_batch, seg, mesh, kernel):
    from nice_tpu.ops import pallas_engine as pe

    kernel = _resolve_kernel(plan, per_device_batch, kernel)
    mod = pe if kernel == "pallas" else ve
    width = plan.base + 2

    def device_step(hist_row, start_row, valid_row):
        def body(carry, _):
            cursor, rem, acc, nm_acc = carry
            valid = jnp.minimum(rem, jnp.int32(per_device_batch))
            hist, nm = mod.detailed_batch(
                plan, per_device_batch, cursor, valid
            )
            return (ve._advance_cursor(plan, cursor, per_device_batch),
                    rem - valid, acc + hist[:width], nm_acc + nm), None

        init = (start_row[0].astype(jnp.uint32),
                valid_row[0].astype(jnp.int32), hist_row[0], jnp.int32(0))
        (_c, _r, acc, nm), _ = jax.lax.scan(body, init, None, length=seg)
        return acc[None, :], jax.lax.psum(nm, FIELD_AXIS)

    sharded = _shard_map(
        device_step,
        mesh,
        in_specs=(P(FIELD_AXIS, None), P(FIELD_AXIS, None), P(FIELD_AXIS)),
        out_specs=(P(FIELD_AXIS, None), P()),
    )
    return _timed_step(jax.jit(sharded, donate_argnums=(0,)), "detailed-accum")


def make_sharded_megaloop_count_step(
    plan: BasePlan,
    per_device_batch: int,
    seg: int,
    mesh: Mesh,
):
    """Megaloop variant of the sharded niceonly step: each device scans `seg`
    batches of the dense jnp count kernel over its own in-program cursor; the
    segment totals are psum-reduced once. Returns fn(starts u32[n_dev,
    limbs_n], valids i32[n_dev]) -> nice count i32, replicated."""
    return _step_cached(
        "stats-mega", mesh, (plan, per_device_batch, seg),
        lambda: _build_megaloop_count_step(plan, per_device_batch, seg, mesh),
    )


def _build_megaloop_count_step(plan, per_device_batch, seg, mesh):
    def device_step(start_row, valid_row):
        def body(carry, _):
            cursor, rem, count = carry
            valid = jnp.minimum(rem, jnp.int32(per_device_batch))
            c = ve.niceonly_dense_batch(plan, per_device_batch, cursor, valid)
            return (ve._advance_cursor(plan, cursor, per_device_batch),
                    rem - valid, count + c), None

        init = (start_row[0].astype(jnp.uint32),
                valid_row[0].astype(jnp.int32), jnp.int32(0))
        (_c, _r, count), _ = jax.lax.scan(body, init, None, length=seg)
        return jax.lax.psum(count, FIELD_AXIS)

    sharded = _shard_map(
        device_step,
        mesh,
        in_specs=(P(FIELD_AXIS, None), P(FIELD_AXIS)),
        out_specs=P(),
    )
    return _timed_step(jax.jit(sharded), "niceonly")


def make_sharded_stats_fold(mesh: Mesh):
    """The field-end reduction paired with make_sharded_stats_accum_step:
    ONE psum of the per-device accumulator rows over ICI, returning the
    replicated full-field histogram."""
    return _step_cached("stats-fold", mesh, None,
                        lambda: _build_stats_fold(mesh))


def _build_stats_fold(mesh):
    def device_fold(hist_row):
        return jax.lax.psum(hist_row[0], FIELD_AXIS)

    sharded = _shard_map(
        device_fold, mesh, in_specs=(P(FIELD_AXIS, None),), out_specs=P()
    )
    return _timed_step(jax.jit(sharded), "stats-fold")


def make_sharded_strided_step(plan: BasePlan, spec, per_device_desc: int,
                              periods: int, mesh: Mesh):
    """Multi-chip stride-compacted niceonly step: the descriptor table is
    sharded across the mesh (each device counts nice candidates for its own
    descriptor rows with the strided Pallas kernel) and the per-descriptor
    count tiles are stacked, NOT reduced — the host needs every descriptor's
    count to decide which sub-ranges to re-scan.

    Returns fn(desc u32[n_dev * per_device_desc, 12], n_real i32[n_dev]) ->
    i32[n_dev * 8, 128]; descriptor (dev d, local i) count lands at
    [d * 8 + i // 128, i % 128]. n_real[d] is the count of real (non-padding)
    rows in device d's shard; padded rows skip all lane compute.
    """
    return _step_cached(
        "strided", mesh, (plan, spec, per_device_desc, periods),
        lambda: _build_strided_step(plan, spec, per_device_desc, periods, mesh),
    )


def _build_strided_step(plan, spec, per_device_desc, periods, mesh):
    from nice_tpu.ops import pallas_engine as pe

    def device_step(desc, n_real):
        return pe._strided_callable(plan, spec, per_device_desc, periods)(
            desc, n_real[0]
        )

    sharded = _shard_map(
        device_step,
        mesh,
        in_specs=(P(FIELD_AXIS, None), P(FIELD_AXIS)),
        out_specs=P(FIELD_AXIS, None),
    )
    return _timed_step(jax.jit(sharded), "strided")


def make_sharded_niceonly_step(plan: BasePlan, per_device_batch: int, mesh: Mesh):
    """Jitted multi-chip niceonly (dense) step: psum'd count of fully nice
    lanes across the mesh."""

    def device_step(start_limbs, valid_count):
        dev = jax.lax.axis_index(FIELD_AXIS)
        offset = dev.astype(jnp.uint32) * np.uint32(per_device_batch)
        idx = jnp.arange(per_device_batch, dtype=jnp.uint32) + offset
        base_limbs = [
            jnp.broadcast_to(start_limbs[i], (per_device_batch,))
            for i in range(plan.limbs_n)
        ]
        n = ve.add_u32(base_limbs, idx)
        uniques = ve.num_uniques_lanes(plan, n)
        valid = idx.astype(jnp.int32) < valid_count
        count = jnp.sum((valid & (uniques == plan.base)).astype(jnp.int32))
        return jax.lax.psum(count, FIELD_AXIS)

    sharded = _shard_map(
        device_step, mesh, in_specs=(P(), P()), out_specs=P()
    )
    return jax.jit(sharded)
