"""On-disk submission spool: graceful degradation for the submit path.

When a submit exhausts its HTTP retries (server down for longer than the
backoff budget), the client journals the full DataToServer payload here —
one JSON file per submission, written atomically — and moves on. At the
next loop iteration or startup, replay() re-sends every spooled entry:

  * accepted (or {"duplicate": true} — the original request had landed
    after all): the entry is deleted; exactly-once is the server's job via
    submit_id, the spool just has to keep trying;
  * definitively rejected (4xx, e.g. the claim lease expired and the field
    was re-issued): the entry is renamed to <name>.rejected and kept for
    post-mortem — replaying it again can never succeed;
  * still unreachable: the entry stays for the next replay.

Entries are keyed by submit_id, so re-journaling the same submission (crash
between journal and replay) overwrites rather than duplicates.

This module imports the client transport, so it is NOT re-exported from
nice_tpu.faults (which the transport itself imports).
"""

from __future__ import annotations

import json
import logging
import os
import re
import time
from typing import Optional

from nice_tpu.client import api_client
from nice_tpu.core.types import DataToServer
from nice_tpu.obs import flight, journal
from nice_tpu.obs.series import (
    SPOOL_JOURNALED,
    SPOOL_QUARANTINE_PRUNED,
    SPOOL_REPLAYS,
)
from nice_tpu.utils import fsio, knobs

log = logging.getLogger(__name__)

_SUFFIX = ".json"
_UNSAFE = re.compile(r"[^A-Za-z0-9._-]")


class SubmissionSpool:
    """A directory of journaled submissions awaiting delivery."""

    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)

    def _path_for(self, data: DataToServer) -> str:
        key = data.submit_id or f"claim-{data.claim_id}"
        return os.path.join(self.dir, _UNSAFE.sub("_", key) + _SUFFIX)

    def add(self, data: DataToServer) -> str:
        """Atomically journal a submission; returns the entry path."""
        path = self._path_for(data)
        fsio.atomic_write_json(path, data.to_json(), sort_keys=True)
        SPOOL_JOURNALED.inc()
        flight.record("spool", claim=data.claim_id, path=path)
        log.warning(
            "journaled undeliverable submission for claim %d to %s "
            "(will replay)", data.claim_id, path,
        )
        return path

    def pending(self) -> list[str]:
        """Journaled entry paths, oldest first (stable mtime-then-name)."""
        try:
            names = [
                n for n in os.listdir(self.dir) if n.endswith(_SUFFIX)
            ]
        except FileNotFoundError:
            return []
        paths = [os.path.join(self.dir, n) for n in names]
        return sorted(paths, key=lambda p: (os.path.getmtime(p), p))

    def replay(
        self, api_base: str, max_retries: int = 2
    ) -> dict[str, int]:
        """Attempt delivery of every pending entry; returns outcome counts
        {"delivered": n, "rejected": n, "deferred": n}.

        max_retries is deliberately small: the spool is itself the retry
        mechanism, so each replay pass should fail fast and yield to the
        caller's main loop rather than sit in a deep backoff."""
        counts = {"delivered": 0, "rejected": 0, "deferred": 0}
        # Age-based quarantine retention keeps sweeping even when nothing
        # new gets rejected (long-lived clients would otherwise only prune
        # on the next quarantine).
        self.prune_quarantine()
        for path in self.pending():
            outcome = self._replay_one(path, api_base, max_retries)
            counts[outcome] += 1
            SPOOL_REPLAYS.labels(outcome).inc()
        if sum(counts.values()):
            log.info(
                "spool replay: %d delivered, %d rejected, %d deferred",
                counts["delivered"], counts["rejected"], counts["deferred"],
            )
        return counts

    def _replay_one(
        self, path: str, api_base: str, max_retries: int
    ) -> str:
        t0 = time.monotonic()
        try:
            with open(path, "r", encoding="utf-8") as f:
                data = DataToServer.from_json(json.load(f))
        except (OSError, ValueError, KeyError) as e:
            log.error("unreadable spool entry %s: %s", path, e)
            self._quarantine(path)
            return "rejected"
        try:
            resp = api_client.submit_field_to_server(
                api_base, data, max_retries=max_retries
            )
        except api_client.ApiError as e:
            if e.status is not None and 400 <= e.status < 500:
                log.error(
                    "spooled submission for claim %d rejected by the server "
                    "(%s); keeping %s.rejected for post-mortem",
                    data.claim_id, e, path,
                )
                self._quarantine(path)
                journal.record_client_event(
                    "spool_replay", claim_id=data.claim_id,
                    outcome="rejected", status=e.status,
                    secs=round(time.monotonic() - t0, 6),
                )
                return "rejected"
            log.warning(
                "spooled submission for claim %d still undeliverable (%s); "
                "will retry next replay", data.claim_id, e,
            )
            return "deferred"
        log.info(
            "delivered spooled submission for claim %d%s", data.claim_id,
            " (duplicate: the original had landed)"
            if resp.get("duplicate") else "",
        )
        self._remove(path)
        # secs is the replay round-trip only; the time the submission sat
        # spooled on disk is already visible in the journal as the gap
        # before this event's timestamp.
        journal.record_client_event(
            "spool_replay", claim_id=data.claim_id, outcome="delivered",
            duplicate=bool(resp.get("duplicate")),
            secs=round(time.monotonic() - t0, 6),
        )
        return "delivered"

    @staticmethod
    def _remove(path: str) -> None:
        try:
            os.remove(path)
        except FileNotFoundError:
            pass

    def _quarantine(self, path: str) -> None:
        try:
            os.replace(path, path + ".rejected")
        except OSError:
            pass
        # A definitively-rejected submission is exactly when the preceding
        # event history matters: dump the flight ring next to the wreckage.
        flight.record("quarantine", path=path + ".rejected")
        flight.dump(reason="quarantine")
        self.prune_quarantine()

    def prune_quarantine(self) -> dict:
        """Retention sweep over quarantined (.rejected) entries, which
        would otherwise accumulate forever: delete entries older than
        NICE_TPU_SPOOL_QUARANTINE_MAX_AGE_SECS, then oldest-first until the
        survivors fit NICE_TPU_SPOOL_QUARANTINE_MAX_BYTES (either knob at 0
        disables that bound). Returns {"entries": n, "bytes": n} pruned."""
        try:
            max_bytes = int(knobs.SPOOL_QUARANTINE_MAX_BYTES.get())
        except (TypeError, ValueError):
            max_bytes = 0
        try:
            max_age = float(knobs.SPOOL_QUARANTINE_MAX_AGE_SECS.get())
        except (TypeError, ValueError):
            max_age = 0.0
        if max_bytes <= 0 and max_age <= 0:
            return {"entries": 0, "bytes": 0}
        try:
            names = [
                n for n in os.listdir(self.dir) if n.endswith(".rejected")
            ]
        except OSError:
            return {"entries": 0, "bytes": 0}
        entries = []  # (mtime, path, size), oldest first
        for name in names:
            path = os.path.join(self.dir, name)
            try:
                st = os.lstat(path)
            except OSError:
                continue
            entries.append((st.st_mtime, path, st.st_size))
        entries.sort()
        now = time.time()
        doomed = []
        kept = []
        for mtime, path, size in entries:
            if max_age > 0 and now - mtime > max_age:
                doomed.append((path, size))
            else:
                kept.append((path, size))
        if max_bytes > 0:
            total = sum(size for _p, size in kept)
            while kept and total > max_bytes:
                path, size = kept.pop(0)  # oldest survivor goes first
                doomed.append((path, size))
                total -= size
        pruned_entries = 0
        pruned_bytes = 0
        for path, size in doomed:
            try:
                os.remove(path)
            except OSError:
                continue
            pruned_entries += 1
            pruned_bytes += size
        if pruned_entries:
            SPOOL_QUARANTINE_PRUNED.inc(pruned_bytes)
            flight.record(
                "quarantine_pruned", dir=self.dir,
                entries=pruned_entries, bytes=pruned_bytes,
            )
            log.info(
                "pruned %d quarantined spool entries (%d bytes) under the"
                " retention bounds", pruned_entries, pruned_bytes,
            )
        return {"entries": pruned_entries, "bytes": pruned_bytes}


def maybe_spool(
    spool_dir: Optional[str], checkpoint_dir: Optional[str] = None
) -> Optional[SubmissionSpool]:
    """Spool for the client: an explicit dir wins; otherwise co-locate with
    the checkpoint dir (both are 'survive a crash' state); no dir, no spool."""
    if spool_dir:
        return SubmissionSpool(spool_dir)
    if checkpoint_dir:
        return SubmissionSpool(os.path.join(checkpoint_dir, "spool"))
    return None
