"""Deterministic fault injection + graceful-degradation helpers.

`fire(site, **ctx)` is the single hook production code threads through; the
NICE_TPU_FAULTS env var (see injector.py for the grammar) decides what, if
anything, happens there. The submission spool lives in
nice_tpu.faults.spool (imported lazily — it pulls in the client transport).
"""

from nice_tpu.faults.injector import (  # noqa: F401
    ENV_SEED,
    ENV_SPEC,
    FaultSpecError,
    active_sites,
    configure,
    fire,
    parse_spec,
    reset,
)
