"""Deterministic, seeded fault injection.

One env var drives every chaos hook in the system:

    NICE_TPU_FAULTS="http.submit:drop_response@0.3,engine.dispatch:raise@batch=7"
    NICE_TPU_FAULTS_SEED=42

Grammar: comma-separated rules, each `site:action[@selector]`.

  site      dotted injection-point name (http.submit, server.claim,
            engine.dispatch, ckpt.write, ...). A site only exists where a
            fire() call is threaded through the production code; unknown
            sites parse fine and simply never match.
  action    opaque string the call site interprets (500, conn_error,
            drop_response, raise, truncate, ...).
  selector  when the rule fires:
              @0.3       float -> independent per-call probability, drawn
                         from a per-site RNG seeded by NICE_TPU_FAULTS_SEED
                         (same seed + same call sequence = same faults, and
                         one site's draws never perturb another's)
              @2         bare int -> the Nth eligible call at the site,
                         exactly once
              @key=val   fires once, on the first call whose ctx has
                         str(ctx[key]) == val (e.g. engine.dispatch with
                         batch=7)
              (omitted)  every eligible call

The module costs one dict lookup per fire() when no spec is configured, so
production code can leave the hooks permanently threaded through.
"""

from __future__ import annotations

import logging
import random
from dataclasses import dataclass, field
from typing import Optional

from nice_tpu.obs import flight
from nice_tpu.obs.series import FAULTS_INJECTED
from nice_tpu.utils import knobs, lockdep

log = logging.getLogger("nice_tpu.faults")

ENV_SPEC = "NICE_TPU_FAULTS"
ENV_SEED = "NICE_TPU_FAULTS_SEED"
DEFAULT_SEED = 0


class FaultSpecError(ValueError):
    """Malformed NICE_TPU_FAULTS spec string."""


@dataclass
class _Rule:
    site: str
    action: str
    # Exactly one selector kind is set:
    probability: Optional[float] = None
    nth: Optional[int] = None
    match: Optional[tuple[str, str]] = None  # (ctx key, value as str)
    always: bool = False
    # Mutable firing state:
    calls: int = 0
    fired: bool = False
    rng: random.Random = field(default_factory=random.Random)

    def should_fire(self, ctx: dict) -> bool:
        self.calls += 1
        if self.probability is not None:
            return self.rng.random() < self.probability
        if self.nth is not None:
            if self.fired or self.calls != self.nth:
                return False
            self.fired = True
            return True
        if self.match is not None:
            if self.fired:
                return False
            key, want = self.match
            if key not in ctx or str(ctx[key]) != want:
                return False
            self.fired = True
            return True
        return self.always


def parse_spec(spec: str, seed: int = DEFAULT_SEED) -> list[_Rule]:
    """Parse a NICE_TPU_FAULTS string into rules (see module docstring)."""
    rules: list[_Rule] = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if ":" not in part:
            raise FaultSpecError(
                f"fault rule {part!r} has no action (want site:action[@selector])"
            )
        site, rest = part.split(":", 1)
        site = site.strip()
        selector = None
        if "@" in rest:
            action, selector = rest.split("@", 1)
        else:
            action = rest
        action = action.strip()
        if not site or not action:
            raise FaultSpecError(f"fault rule {part!r} has an empty site or action")
        rule = _Rule(site=site, action=action)
        # Per-(site, rule-ordinal) RNG stream: probability draws are
        # reproducible per site regardless of interleaving with other sites.
        rule.rng = random.Random(f"{seed}:{site}:{len(rules)}")
        if selector is not None:
            selector = selector.strip()
            if "=" in selector:
                key, val = selector.split("=", 1)
                rule.match = (key.strip(), val.strip())
            elif "." in selector or "e" in selector.lower():
                try:
                    rule.probability = float(selector)
                except ValueError:
                    raise FaultSpecError(
                        f"fault rule {part!r}: bad probability {selector!r}"
                    )
                if not 0.0 <= rule.probability <= 1.0:
                    raise FaultSpecError(
                        f"fault rule {part!r}: probability must be in [0, 1]"
                    )
            else:
                try:
                    rule.nth = int(selector)
                except ValueError:
                    raise FaultSpecError(
                        f"fault rule {part!r}: bad selector {selector!r}"
                    )
                if rule.nth < 1:
                    raise FaultSpecError(
                        f"fault rule {part!r}: Nth-call selector must be >= 1"
                    )
        else:
            rule.always = True
        rules.append(rule)
    return rules


class FaultPlan:
    """Active rule set, indexed by site. Thread-safe: fire() may be called
    concurrently from dispatch, collector, renewer, and server threads."""

    def __init__(self, rules: list[_Rule]):
        self._lock = lockdep.make_lock("faults.injector.FaultPlan._lock")
        self.by_site: dict[str, list[_Rule]] = {}
        for r in rules:
            self.by_site.setdefault(r.site, []).append(r)

    def fire(self, site: str, ctx: dict) -> Optional[str]:
        rules = self.by_site.get(site)
        if not rules:
            return None
        with self._lock:
            for rule in rules:
                if rule.should_fire(ctx):
                    FAULTS_INJECTED.labels(site, rule.action).inc()
                    flight.record("fault", site=site, action=rule.action)
                    log.warning(
                        "injected fault at %s: action=%s ctx=%s (call %d)",
                        site, rule.action, ctx, rule.calls,
                    )
                    return rule.action
        return None


_EMPTY = FaultPlan([])
_plan: Optional[FaultPlan] = None
_plan_lock = lockdep.make_lock("faults.injector._plan_lock")


def configure(spec: Optional[str] = None, seed: Optional[int] = None) -> None:
    """Install a fault plan explicitly (tests / tools). spec=None or ""
    clears every rule."""
    global _plan
    with _plan_lock:
        if not spec:
            _plan = _EMPTY
        else:
            _plan = FaultPlan(
                parse_spec(spec, DEFAULT_SEED if seed is None else int(seed))
            )


def reset() -> None:
    """Drop the active plan; the next fire() re-reads the environment."""
    global _plan
    with _plan_lock:
        _plan = None


def _active() -> FaultPlan:
    global _plan
    plan = _plan
    if plan is None:
        with _plan_lock:
            if _plan is None:
                spec = knobs.FAULTS.get() or ""
                seed = knobs.FAULTS_SEED.get(default=DEFAULT_SEED)
                _plan = (
                    FaultPlan(parse_spec(spec, seed)) if spec.strip() else _EMPTY
                )
                if _plan.by_site:
                    log.warning(
                        "fault injection ACTIVE (%s=%r seed=%d)",
                        ENV_SPEC, spec, seed,
                    )
            plan = _plan
    return plan


def fire(site: str, **ctx) -> Optional[str]:
    """The injection hook: returns the action string when a rule fires at
    this site for this call, else None. Near-free when no faults are
    configured."""
    plan = _active()
    if not plan.by_site:
        return None
    return plan.fire(site, ctx)


def active_sites() -> tuple[str, ...]:
    """Sites with at least one configured rule (diagnostics)."""
    return tuple(sorted(_active().by_site))
