"""Unified JSON-line log sink for every nice-tpu entry point.

Before this module, each main() called logging.basicConfig with its own
format string and the 18 modules' ``logging.getLogger`` loggers emitted
free-text lines that grep could not join with the structured trace/journal
sinks. install() configures the root logger once with a JSON formatter
that stamps every record with the ambient ``trace_id`` (obs/trace.py
context), so a server handler's log lines group with the same request's
spans and journal events on the one id.

Knobs (typed registry, K1-clean):
  NICE_TPU_LOG_LEVEL — root level (trace/debug/info/warn/error); unset
      falls back to the installing main's default (e.g. the server's
      --log-level flag).
  NICE_TPU_LOG_FILE  — additionally append JSON lines to this file.

install() is idempotent-by-force: it replaces root handlers
(basicConfig(force=True)), so calling it from a main that already
configured logging simply re-points the sink.
"""

from __future__ import annotations

import json
import logging
import sys
import time
from typing import Optional

from . import trace
from nice_tpu.utils import knobs

__all__ = ["JsonFormatter", "install", "resolve_level"]

# "trace" is a client-CLI convention (extra-verbose debug), not a stdlib
# level — map it onto DEBUG.
_LEVELS = {
    "trace": logging.DEBUG,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warn": logging.WARNING,
    "warning": logging.WARNING,
    "error": logging.ERROR,
}


def resolve_level(default: str = "info") -> int:
    """Root level: NICE_TPU_LOG_LEVEL wins, else the caller's default."""
    name = (knobs.LOG_LEVEL.get() or default or "info").strip().lower()
    return _LEVELS.get(name, logging.INFO)


class JsonFormatter(logging.Formatter):
    """One JSON object per line: ts/level/logger/msg, the ambient trace_id
    when a trace context is active, and a formatted traceback under "exc"
    for records carrying exc_info."""

    def format(self, record: logging.LogRecord) -> str:
        out = {
            "ts": round(record.created, 6),
            "level": record.levelname.lower(),
            "logger": record.name,
            "msg": record.getMessage(),
        }
        tid = trace.current_trace_id()
        if tid:
            out["trace_id"] = tid
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out, default=repr, separators=(",", ":"))


def install(default_level: str = "info") -> None:
    """Point the root logger at the JSON sink (stderr + optional file)."""
    formatter = JsonFormatter()
    handlers: list[logging.Handler] = [logging.StreamHandler(sys.stderr)]
    log_file: Optional[str] = knobs.LOG_FILE.get()
    if log_file:
        try:
            handlers.append(logging.FileHandler(log_file, encoding="utf-8"))
        except OSError as exc:
            print(
                f"nice_tpu.obs: cannot open log sink {log_file!r}: {exc}",
                file=sys.stderr,
            )
    for h in handlers:
        h.setFormatter(formatter)
    logging.basicConfig(
        level=resolve_level(default_level), handlers=handlers, force=True
    )
    # UTC everywhere, matching the trace sink and the ledger's timestamps.
    logging.Formatter.converter = time.gmtime
