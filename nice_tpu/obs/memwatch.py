"""Resource watch: device memory, host RSS, and on-disk footprints.

The observability stack's memory/footprint axis (the wall-time axis is
stepprof + critpath). A periodic sample reads

* **device memory** — ``jax.live_arrays()`` population/nbytes plus
  ``device.memory_stats()`` bytes-in-use / peak / limit per device. Only
  when jax is *already imported*: the sampler never forces a backend init,
  so the jax-free server and a cold client pay nothing;
* **host RSS** — the shared ``utils/resources.py`` backend ladder
  (/proc -> psutil -> rusage peak);
* **compile-cache footprint** — executable count + best-effort per-
  (mode, base) AOT code size from ``ops/compile_cache.footprint()``;
* **disk** — recursive footprints of every path registered with
  :func:`watch_path` (spool, quarantined spool entries, checkpoint dir,
  trace sink, the SQLite ledger + its repl_ops journal) and the free bytes
  of the filesystem holding them.

Samples land in the ``nice_mem_*`` / ``nice_disk_*`` series, so they flow
into the history store on the next sampler beat and feed the
``mem_leak_trend`` / ``resource_exhaustion`` anomaly detectors
(obs/anomaly.py), whose slope/forecast math lives HERE (:func:`trend`,
:func:`forecast`) so the memprof smoke can cross-check it against an
injected leak rate.

Cadence: ``NICE_TPU_MEMWATCH_SECS`` (0 = off: zero threads, zero samples —
``nice_mem_samples_total`` staying 0 is the proof, stepprof-style). The
client and daemon run a "nice-memwatch" daemon thread via
:func:`maybe_start_sampler`; the server calls :func:`maybe_sample` on its
writer-actor observatory beat instead (no extra thread), throttled to the
same knob.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from .series import (
    DISK_FREE_BYTES,
    DISK_USAGE_BYTES,
    MEM_CACHED_EXECUTABLES,
    MEM_DEVICE_BYTES,
    MEM_DEVICE_LIMIT_BYTES,
    MEM_DEVICE_PEAK_BYTES,
    MEM_EXECUTABLE_BYTES,
    MEM_LIVE_ARRAY_BYTES,
    MEM_LIVE_ARRAYS,
    MEM_RSS_BYTES,
    MEM_RSS_PEAK_BYTES,
    MEM_SAMPLES,
)
from nice_tpu.utils import knobs, lockdep, resources

log = logging.getLogger("nice_tpu.obs")

__all__ = [
    "interval_secs",
    "watch_path",
    "watched",
    "sample",
    "maybe_sample",
    "summary",
    "maybe_start_sampler",
    "slope_per_sec",
    "trend",
    "forecast",
    "reset_for_tests",
]

_lock = lockdep.make_lock("obs.memwatch._lock")
_watched: Dict[str, str] = {}
_last_summary: Dict[str, object] = {}
_last_sample_mono: List[float] = [0.0]

_sampler_lock = lockdep.make_lock("obs.memwatch._sampler_lock")
_sampler_started = False


def interval_secs() -> float:
    """The sampling cadence; <= 0 means memwatch is off everywhere."""
    try:
        return float(knobs.MEMWATCH_SECS.get())
    except (TypeError, ValueError):
        return 0.0


def watch_path(what: str, path: Optional[str]) -> None:
    """Register a directory/file under a stable label ("spool", "ckpt",
    "trace", "ledger", ...). None/empty paths are ignored so call sites can
    pass their maybe-configured dirs unconditionally."""
    if not path:
        return
    with _lock:
        _watched[what] = path


def watched() -> Dict[str, str]:
    with _lock:
        return dict(_watched)


# --- one sample -----------------------------------------------------------


def _device_memory() -> dict:
    """Device-memory view, strictly opportunistic: if jax is not already in
    sys.modules (jax-free server, pre-init client) this reports nothing and
    imports nothing."""
    out: dict = {"devices": {}, "live_arrays": None, "live_array_bytes": None}
    jax = sys.modules.get("jax")
    if jax is None:
        return out
    try:
        arrays = jax.live_arrays()
        out["live_arrays"] = len(arrays)
        out["live_array_bytes"] = int(
            sum(getattr(a, "nbytes", 0) or 0 for a in arrays)
        )
    except Exception:  # noqa: BLE001 — backend not initialized yet
        return out
    try:
        devices = jax.devices()
    except Exception:  # noqa: BLE001
        return out
    for d in devices:
        stats = None
        try:
            stats = d.memory_stats()
        except Exception:  # noqa: BLE001 — CPU backends often lack stats
            stats = None
        entry = {}
        if stats:
            for src, dst in (("bytes_in_use", "in_use"),
                             ("peak_bytes_in_use", "peak"),
                             ("bytes_limit", "limit")):
                if src in stats:
                    entry[dst] = int(stats[src])
        out["devices"][str(getattr(d, "id", len(out["devices"])))] = entry
    return out


def _executable_footprint() -> dict:
    from nice_tpu.ops import compile_cache

    try:
        return compile_cache.footprint()
    except Exception:  # noqa: BLE001 — footprint is best-effort
        return {"count": 0, "groups": {}}


def _quarantine_bytes(spool_dir: str) -> Optional[int]:
    """Footprint of .rejected entries inside the spool dir (they are
    excluded from the spool's own pending() listing, so they get their own
    watermark)."""
    try:
        names = os.listdir(spool_dir)
    except OSError:
        return None
    total = 0
    for n in names:
        if not n.endswith(".rejected"):
            continue
        try:
            total += os.lstat(os.path.join(spool_dir, n)).st_size
        except OSError:
            continue
    return total


def sample() -> dict:
    """Take one resource sample: refresh every nice_mem_* / nice_disk_*
    gauge and return (and retain, see summary()) a compact dict."""
    now = time.time()
    out: dict = {"ts": now}

    rss = resources.rss_bytes()
    if rss is not None:
        MEM_RSS_BYTES.set(rss)
        out["rss_bytes"] = rss
    peak = resources.peak_rss_bytes()
    if peak is not None:
        MEM_RSS_PEAK_BYTES.set(peak)
        out["rss_peak_bytes"] = peak

    dev = _device_memory()
    if dev["live_arrays"] is not None:
        MEM_LIVE_ARRAYS.set(dev["live_arrays"])
        MEM_LIVE_ARRAY_BYTES.set(dev["live_array_bytes"])
        out["live_arrays"] = dev["live_arrays"]
        out["live_array_bytes"] = dev["live_array_bytes"]
    if dev["devices"]:
        out["devices"] = dev["devices"]
        for dev_id, entry in dev["devices"].items():
            # Backends without memory_stats still show their live-array
            # bytes in the aggregate gauges above; per-device gauges only
            # carry what the runtime actually reports.
            if "in_use" in entry:
                MEM_DEVICE_BYTES.labels(dev_id).set(entry["in_use"])
            if "peak" in entry:
                MEM_DEVICE_PEAK_BYTES.labels(dev_id).set(entry["peak"])
            if "limit" in entry:
                MEM_DEVICE_LIMIT_BYTES.labels(dev_id).set(entry["limit"])

    fp = _executable_footprint()
    MEM_CACHED_EXECUTABLES.set(fp.get("count", 0))
    for key, nbytes in (fp.get("groups") or {}).items():
        MEM_EXECUTABLE_BYTES.labels(key).set(nbytes)
    out["cached_executables"] = fp.get("count", 0)

    disk: Dict[str, int] = {}
    free: Optional[int] = None
    for what, path in sorted(watched().items()):
        nbytes = resources.dir_bytes(path)
        if nbytes is not None:
            DISK_USAGE_BYTES.labels(what).set(nbytes)
            disk[what] = nbytes
        if what == "spool":
            q = _quarantine_bytes(path)
            if q is not None:
                DISK_USAGE_BYTES.labels("quarantine").set(q)
                disk["quarantine"] = q
        if free is None:
            free = resources.fs_free_bytes(path)
    if disk:
        out["disk_bytes"] = disk
    if free is not None:
        DISK_FREE_BYTES.set(free)
        out["disk_free_bytes"] = free

    MEM_SAMPLES.inc()
    with _lock:
        _last_summary.clear()
        _last_summary.update(out)
    _last_sample_mono[0] = time.monotonic()
    return out


def maybe_sample() -> Optional[dict]:
    """Piggyback entry point for hosts with their own periodic (the server's
    observatory beat): sample iff memwatch is on and a full interval has
    elapsed since the last sample."""
    secs = interval_secs()
    if secs <= 0:
        return None
    if time.monotonic() - _last_sample_mono[0] < secs:
        return None
    try:
        return sample()
    except Exception:  # noqa: BLE001 — sampling must never hurt the host
        log.exception("memwatch sample failed")
        return None


def summary() -> dict:
    """The most recent sample (empty before the first one) — telemetry
    piggybacks this, /status and the resource stream kind serve it."""
    with _lock:
        return dict(_last_summary)


def maybe_start_sampler(interval: Optional[float] = None) -> bool:
    """Start the background sampling thread once per process (client +
    daemon; the server samples on the writer periodic instead). Returns
    True when the sampler is running. NICE_TPU_MEMWATCH_SECS=0 disables —
    no thread is created at all."""
    global _sampler_started
    secs = interval_secs() if interval is None else interval
    if not secs or secs <= 0:
        return False
    with _sampler_lock:
        if _sampler_started:
            return True
        _sampler_started = True

    def _run():
        while True:
            time.sleep(secs)
            try:
                sample()
            except Exception:  # noqa: BLE001 — keep sampling
                log.exception("memwatch sample failed")

    threading.Thread(target=_run, name="nice-memwatch", daemon=True).start()
    log.info("memwatch sampler started (every %.1fs)", secs)
    return True


# --- leak trend + exhaustion forecast -------------------------------------

# A slope needs this many points before it is evidence rather than jitter.
MIN_TREND_POINTS = 4

# Series the trend/forecast math watches, with how each maps to a resource.
_RSS_SERIES = "nice_mem_rss_bytes"
_DISK_SERIES = "nice_disk_usage_bytes"
_DISK_FREE_SERIES = "nice_disk_free_bytes"
_HBM_SERIES = "nice_mem_device_bytes"
_HBM_LIMIT_SERIES = "nice_mem_device_limit_bytes"


def slope_per_sec(points: List[Tuple[float, float]]) -> Optional[float]:
    """Least-squares growth rate (units/sec) of [(unix_ts, value), ...];
    None when the window can't support a fit."""
    n = len(points)
    if n < 2:
        return None
    t0 = points[0][0]
    xs = [t - t0 for t, _v in points]
    ys = [v for _t, v in points]
    mx = sum(xs) / n
    my = sum(ys) / n
    den = sum((x - mx) ** 2 for x in xs)
    if den <= 0:
        return None
    num = sum((x - mx) * (y - my) for x, y in zip(xs, ys))
    return num / den


def _series_points(store, name: str, since: float) -> List[Tuple[float, float]]:
    """Timestamped points for one history series over the window, coarse
    tiers first so a window longer than the raw ring still has a spine."""
    snap = store.query(name, since=since, tiers=("15m", "1m", "raw"))
    if not snap:
        return []
    merged: Dict[float, float] = {}
    for tier in ("15m", "1m", "raw"):
        # raw points are [ts, value]; coarse tiers carry
        # [bucket_ts, mean, min, max, last, n] — take (ts, mean).
        for p in snap.get(tier, []) or []:
            merged[p[0]] = p[1]
    return sorted(merged.items())


def _last_value(store, name: str, since: float) -> Optional[float]:
    pts = _series_points(store, name, since)
    return pts[-1][1] if pts else None


def trend(store, since: float) -> Dict[str, float]:
    """Per-series growth slope (bytes/sec) over the window for every
    resident-set and watched-disk series with enough points."""
    out: Dict[str, float] = {}
    for name in store.series_names():
        if not (name.startswith(_RSS_SERIES)
                or name.startswith(_DISK_SERIES)):
            continue
        pts = _series_points(store, name, since)
        if len(pts) < MIN_TREND_POINTS:
            continue
        s = slope_per_sec(pts)
        if s is not None:
            out[name] = s
    return out


def forecast(store, since: float,
             horizon_secs: Optional[float] = None) -> Dict[str, dict]:
    """Time-to-exhaustion forecast per resource. For each of rss / disk /
    hbm with a fitted growth slope and known headroom:

    ``ratio``    = slope * horizon / headroom — the detector value; >= 1
                   means the resource runs out inside the horizon;
    ``tte_secs`` = headroom / slope (None when not growing).
    """
    horizon = (
        float(knobs.MEMWATCH_HORIZON_SECS.get())
        if horizon_secs is None else float(horizon_secs)
    )
    out: Dict[str, dict] = {}

    def _emit(resource: str, pts, headroom: Optional[float]) -> None:
        if len(pts) < MIN_TREND_POINTS or headroom is None or headroom <= 0:
            return
        s = slope_per_sec(pts)
        if s is None:
            return
        entry = {
            "slope_bytes_per_sec": s,
            "headroom_bytes": headroom,
            "horizon_secs": horizon,
        }
        if s > 0:
            entry["tte_secs"] = headroom / s
            entry["ratio"] = s * horizon / headroom
        else:
            entry["tte_secs"] = None
            entry["ratio"] = 0.0
        out[resource] = entry

    rss_pts = _series_points(store, _RSS_SERIES, since)
    total = resources.host_memory_total_bytes()
    if rss_pts and total:
        _emit("rss", rss_pts, max(0.0, float(total) - rss_pts[-1][1]))

    # Disk: the aggregate usage series (sum over watched paths) against the
    # filesystem's free bytes, or the deterministic capacity override.
    disk_pts = _series_points(store, _DISK_SERIES, since)
    cap = knobs.MEMWATCH_DISK_CAPACITY.get()
    if disk_pts:
        if cap:
            headroom = max(0.0, float(cap) - disk_pts[-1][1])
        else:
            headroom = _last_value(store, _DISK_FREE_SERIES, since)
        _emit("disk", disk_pts, headroom)

    hbm_pts = _series_points(store, _HBM_SERIES, since)
    limit = _last_value(store, _HBM_LIMIT_SERIES, since)
    if hbm_pts and limit:
        _emit("hbm", hbm_pts, max(0.0, float(limit) - hbm_pts[-1][1]))
    return out


def reset_for_tests() -> None:
    """Drop registered paths + the last summary (NOT the started-thread
    guard: threads are process-lifetime)."""
    with _lock:
        _watched.clear()
        _last_summary.clear()
    _last_sample_mono[0] = 0.0
