"""Structured JSON trace spans — the "where did it wedge" layer.

``span(name)`` emits a *begin* event immediately (flushed) and an *end* event
with wall/process durations on exit. Because the begin line hits the sink
before the body runs, a hang inside the span (the classic wedged axon device
lease) still leaves a begin-without-end record naming the exact stalled
phase; BENCH rounds 4/5 died with no such evidence.

Sink selection via ``NICE_TPU_TRACE``:
  unset / "" / "0"  -> disabled (spans still feed the duration histogram)
  "1" or "stderr"   -> JSON lines on stderr
  anything else     -> append to that file path

The env var is re-read when its value changes, so tests can redirect the
sink per-test with monkeypatch. ``profiler(name)`` additionally wraps a
block in ``jax.profiler.trace`` when ``NICE_TPU_PROFILE`` points at an
output directory — import-guarded so the module stays jax-free otherwise.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import threading
import time
from typing import Optional

from . import metrics

__all__ = ["span", "trace_event", "trace_enabled", "profiler"]

SPAN_SECONDS = metrics.histogram(
    "nice_trace_span_seconds",
    "Wall-clock duration of named trace spans.",
    labelnames=("span",),
)

_lock = threading.Lock()
_sink_env: Optional[str] = None
_sink: Optional[io.TextIOBase] = None
_local = threading.local()


def _get_sink() -> Optional[io.TextIOBase]:
    global _sink_env, _sink
    env = os.environ.get("NICE_TPU_TRACE", "")
    with _lock:
        if env == _sink_env:
            return _sink
        # Env changed: close a previously opened file sink (never stderr).
        if _sink is not None and _sink is not sys.stderr:
            try:
                _sink.close()
            except OSError:
                pass
        _sink_env = env
        if env in ("", "0"):
            _sink = None
        elif env in ("1", "stderr"):
            _sink = sys.stderr
        else:
            try:
                _sink = open(env, "a", encoding="utf-8")
            except OSError as exc:
                print(f"nice_tpu.obs: cannot open trace sink {env!r}: {exc}",
                      file=sys.stderr)
                _sink = None
        return _sink


def trace_enabled() -> bool:
    return _get_sink() is not None


def _emit(record: dict) -> None:
    sink = _get_sink()
    if sink is None:
        return
    line = json.dumps(record, default=repr, separators=(",", ":"))
    with _lock:
        try:
            sink.write(line + "\n")
            sink.flush()  # hang evidence must hit the sink before the body
        except (OSError, ValueError):
            pass


def trace_event(name: str, event: str = "instant", **fields) -> None:
    """One flushed JSON line outside any span lifecycle."""
    rec = {"ts": time.time(), "name": name, "event": event}
    rec.update(fields)
    _emit(rec)


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


@contextlib.contextmanager
def span(name: str, **attrs):
    """Context manager: begin event now, end event (with wall_secs and
    process_secs) on exit. Nesting is tracked per-thread via parent/depth."""
    st = _stack()
    parent = st[-1] if st else None
    depth = len(st)
    enabled = trace_enabled()
    if enabled:
        rec = {
            "ts": time.time(),
            "name": name,
            "event": "begin",
            "depth": depth,
        }
        if parent:
            rec["parent"] = parent
        if attrs:
            rec.update(attrs)
        _emit(rec)
    st.append(name)
    t0 = time.perf_counter()
    p0 = time.process_time()
    status = "ok"
    try:
        yield
    except BaseException:
        status = "error"
        raise
    finally:
        wall = time.perf_counter() - t0
        st.pop()
        SPAN_SECONDS.observe(wall, (name,))
        if enabled:
            rec = {
                "ts": time.time(),
                "name": name,
                "event": "end",
                "depth": depth,
                "status": status,
                "wall_secs": wall,
                "process_secs": time.process_time() - p0,
            }
            if parent:
                rec["parent"] = parent
            _emit(rec)


@contextlib.contextmanager
def profiler(name: str):
    """Opt-in jax.profiler capture: active only when NICE_TPU_PROFILE names
    an output directory. Degrades to a no-op (with one warning) when jax or
    its profiler is unavailable."""
    out_dir = os.environ.get("NICE_TPU_PROFILE", "")
    if not out_dir:
        yield
        return
    try:
        import jax.profiler as jprof
    except Exception as exc:  # noqa: BLE001 — optional dependency
        print(f"nice_tpu.obs: NICE_TPU_PROFILE set but jax.profiler"
              f" unavailable ({exc}); skipping capture", file=sys.stderr)
        yield
        return
    trace_event("profiler", "begin", span=name, dir=out_dir)
    try:
        with jprof.trace(out_dir):
            yield
    finally:
        trace_event("profiler", "end", span=name, dir=out_dir)
