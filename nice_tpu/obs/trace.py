"""Structured JSON trace spans — the "where did it wedge" layer.

``span(name)`` emits a *begin* event immediately (flushed) and an *end* event
with wall/process durations on exit. Because the begin line hits the sink
before the body runs, a hang inside the span (the classic wedged axon device
lease) still leaves a begin-without-end record naming the exact stalled
phase; BENCH rounds 4/5 died with no such evidence.

Distributed tracing: spans and events stamped inside a ``trace_context``
carry a ``trace_id``, so one field's lifecycle — claim on the server, scan on
the client, submit back on the server — reconstructs from the JSON sinks on
either side by grouping on that id. The id is DERIVED from the claim id
(``claim_trace_id``), so both processes agree on it without negotiating:
the client stamps a W3C-style ``traceparent`` header on its requests and the
server continues the same trace in its handler spans. Each span also gets a
random ``span_id`` (and its parent's as ``parent_id``) for exact tree
reconstruction; the human-readable ``parent`` name field is kept alongside.

Sink selection via ``NICE_TPU_TRACE``:
  unset / "" / "0"  -> disabled (spans still feed the duration histogram)
  "1" or "stderr"   -> JSON lines on stderr
  anything else     -> append to that file path

File sinks are size-capped: past ``NICE_TPU_TRACE_MAX_BYTES`` (default
64 MiB; 0 disables) the file rotates to ``<path>.1`` (one backup kept), so a
week-long daemon run cannot grow the sink unboundedly. The sink is flushed
and closed at interpreter exit.

The env var is re-read when its value changes, so tests can redirect the
sink per-test with monkeypatch. ``profiler(name)`` additionally wraps a
block in ``jax.profiler.trace`` when ``NICE_TPU_PROFILE`` points at an
output directory — import-guarded so the module stays jax-free otherwise.
"""

from __future__ import annotations

import atexit
import contextlib
import hashlib
import io
import json
import os
import re
import sys
import threading
import time
from typing import Optional


__all__ = [
    "span",
    "trace_event",
    "trace_enabled",
    "profiler",
    "trace_context",
    "current_trace_id",
    "current_traceparent",
    "claim_trace_id",
    "make_traceparent",
    "parse_traceparent",
]

from .series import TRACE_SPAN_SECONDS as SPAN_SECONDS  # declared centrally (M1)
from nice_tpu.utils import knobs, lockdep

DEFAULT_MAX_SINK_BYTES = 64 * 1024 * 1024

_lock = lockdep.make_lock("obs.trace._lock")
_sink_env: Optional[str] = None
_sink: Optional[io.TextIOBase] = None
_sink_bytes = 0  # current file-sink size (tracked to trigger rotation)
_local = threading.local()


# --- trace context ---------------------------------------------------------

_TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-[0-9a-f]{16}-[0-9a-f]{2}$"
)


def claim_trace_id(claim_id: int) -> str:
    """Deterministic 16-byte trace id for one claim's whole lifecycle.

    Derived (not negotiated): client and server independently compute the
    same id from the claim id, so spans from both processes join into one
    trace even when a request's traceparent header is lost."""
    return hashlib.sha256(f"nice-claim:{claim_id}".encode()).hexdigest()[:32]


@contextlib.contextmanager
def trace_context(trace_id: Optional[str]):
    """Stamp every span/event in this thread with trace_id (None = no-op)."""
    prev = getattr(_local, "trace_id", None)
    _local.trace_id = trace_id
    try:
        yield
    finally:
        _local.trace_id = prev


def current_trace_id() -> Optional[str]:
    return getattr(_local, "trace_id", None)


def make_traceparent(trace_id: str, span_id: Optional[str] = None) -> str:
    """W3C traceparent header value for an outgoing request."""
    return f"00-{trace_id}-{span_id or os.urandom(8).hex()}-01"


def parse_traceparent(header: Optional[str]) -> Optional[str]:
    """trace_id from a traceparent header, or None when absent/malformed."""
    if not header:
        return None
    m = _TRACEPARENT_RE.match(header.strip().lower())
    return m.group(1) if m else None


def current_traceparent() -> Optional[str]:
    """Header value for the ambient trace context, or None outside one."""
    tid = current_trace_id()
    return make_traceparent(tid) if tid else None


# --- sink management -------------------------------------------------------


def _max_sink_bytes() -> int:
    try:
        return knobs.TRACE_MAX_BYTES.get(default=DEFAULT_MAX_SINK_BYTES)
    except ValueError:
        return DEFAULT_MAX_SINK_BYTES


def _get_sink() -> Optional[io.TextIOBase]:
    global _sink_env, _sink, _sink_bytes
    env = knobs.TRACE.get() or ""
    with _lock:
        if env == _sink_env:
            return _sink
        # Env changed: close a previously opened file sink (never stderr).
        if _sink is not None and _sink is not sys.stderr:
            try:
                _sink.close()
            except OSError:
                pass
        _sink_env = env
        if env in ("", "0"):
            _sink = None
        elif env in ("1", "stderr"):
            _sink = sys.stderr
        else:
            try:
                # nicelint: allow A1 (streaming append-only trace sink)
                _sink = open(env, "a", encoding="utf-8")
                _sink_bytes = os.path.getsize(env)
            except OSError as exc:
                print(f"nice_tpu.obs: cannot open trace sink {env!r}: {exc}",
                      file=sys.stderr)
                _sink = None
        return _sink


def _rotate_locked() -> None:
    """Rotate the current file sink to <path>.1 and reopen. _lock held."""
    global _sink, _sink_bytes
    path = _sink_env
    try:
        _sink.close()
    except OSError:
        pass
    try:
        os.replace(path, path + ".1")
    except OSError:
        pass  # rotation is best-effort; keep appending to the same file
    try:
        # nicelint: allow A1 (streaming append-only trace sink)
        _sink = open(path, "a", encoding="utf-8")
        _sink_bytes = 0
    except OSError as exc:
        print(f"nice_tpu.obs: cannot reopen trace sink {path!r}: {exc}",
              file=sys.stderr)
        _sink = None


@atexit.register
def _flush_sink_at_exit() -> None:
    with _lock:
        if _sink is not None:
            try:
                _sink.flush()
                if _sink is not sys.stderr:
                    _sink.close()
            except (OSError, ValueError):
                pass


def trace_enabled() -> bool:
    return _get_sink() is not None


def _emit(record: dict) -> None:
    global _sink_bytes
    sink = _get_sink()
    if sink is None:
        return
    line = json.dumps(record, default=repr, separators=(",", ":"))
    with _lock:
        try:
            sink.write(line + "\n")
            sink.flush()  # hang evidence must hit the sink before the body
        except (OSError, ValueError):
            return
        if sink is not sys.stderr:
            _sink_bytes += len(line) + 1
            cap = _max_sink_bytes()
            if cap > 0 and _sink_bytes >= cap:
                _rotate_locked()


def trace_event(name: str, event: str = "instant", **fields) -> None:
    """One flushed JSON line outside any span lifecycle."""
    rec = {"ts": time.time(), "name": name, "event": event}
    tid = current_trace_id()
    if tid:
        rec["trace_id"] = tid
    rec.update(fields)
    _emit(rec)


def _stack() -> list:
    st = getattr(_local, "stack", None)
    if st is None:
        st = _local.stack = []
    return st


@contextlib.contextmanager
def span(name: str, **attrs):
    """Context manager: begin event now, end event (with wall_secs and
    process_secs) on exit. Nesting is tracked per-thread via parent/depth;
    span_id/parent_id give exact tree edges and trace_id joins the ambient
    distributed trace (see trace_context)."""
    st = _stack()
    parent = st[-1] if st else None
    depth = len(st)
    enabled = trace_enabled()
    span_id = os.urandom(8).hex() if enabled else ""
    trace_id = current_trace_id()
    if enabled:
        rec = {
            "ts": time.time(),
            "name": name,
            "event": "begin",
            "depth": depth,
            "span_id": span_id,
        }
        if trace_id:
            rec["trace_id"] = trace_id
        if parent:
            rec["parent"] = parent[0]
            rec["parent_id"] = parent[1]
        if attrs:
            rec.update(attrs)
        _emit(rec)
    st.append((name, span_id))
    t0 = time.perf_counter()
    p0 = time.process_time()
    status = "ok"
    try:
        yield
    except BaseException:
        status = "error"
        raise
    finally:
        wall = time.perf_counter() - t0
        st.pop()
        SPAN_SECONDS.observe(wall, (name,))
        if enabled:
            rec = {
                "ts": time.time(),
                "name": name,
                "event": "end",
                "depth": depth,
                "span_id": span_id,
                "status": status,
                "wall_secs": wall,
                "process_secs": time.process_time() - p0,
            }
            if trace_id:
                rec["trace_id"] = trace_id
            if parent:
                rec["parent"] = parent[0]
                rec["parent_id"] = parent[1]
            _emit(rec)


@contextlib.contextmanager
def profiler(name: str):
    """Opt-in jax.profiler capture: active only when NICE_TPU_PROFILE names
    an output directory. Degrades to a no-op (with one warning) when jax or
    its profiler is unavailable."""
    out_dir = knobs.PROFILE.get() or ""
    if not out_dir:
        yield
        return
    try:
        import jax.profiler as jprof
    except Exception as exc:  # noqa: BLE001 — optional dependency
        print(f"nice_tpu.obs: NICE_TPU_PROFILE set but jax.profiler"
              f" unavailable ({exc}); skipping capture", file=sys.stderr)
        yield
        return
    trace_event("profiler", "begin", span=name, dir=out_dir)
    try:
        with jprof.trace(out_dir):
            yield
    finally:
        trace_event("profiler", "end", span=name, dir=out_dir)
