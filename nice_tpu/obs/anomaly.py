"""Anomaly detectors over the audit journal + metric history.

Where the SLO engine (obs/slo.py) guards *service* objectives (latency,
error ratios), the anomaly engine watches for *fleet pathologies* that no
single request can see: fields that churn through claims without ever
reaching canon, lease-expiry storms from a crashing client cohort, bursts
of trust slashes, and throughput falling off a cliff relative to its own
recent history. Detectors read the ``field_events`` journal (server/db.py)
and the PR 10 history store, so they see *resolved* churn that the live
gauges have already forgotten.

Each detector yields a value over the look-back window
(``NICE_TPU_ANOMALY_WINDOW_SECS``, scaled by
``NICE_TPU_ANOMALY_WINDOW_SCALE`` for short harness runs) and maps it onto
the familiar ok/warn/page ladder (value < warn_at -> ok; warn_at <= value
< page_at -> warn; value >= page_at -> page), with per-detector
``NICE_TPU_ANOMALY_<NAME>_WARN`` / ``..._PAGE`` overrides. States land in
``nice_anomaly_state{detector}``, transitions in
``nice_anomaly_transitions_total{detector,state}`` plus an
``anomaly_transition`` flight event, and the latest results surface in
``/status`` for fleet.html's anomaly strip. The server evaluates the engine
on the writer actor's history periodic, right after each SLO pass.
"""

from __future__ import annotations

from datetime import datetime, timezone
from typing import Callable, Dict, List, Optional

from . import flight, memwatch
from nice_tpu.utils import knobs, lockdep

__all__ = ["AnomalyDetector", "AnomalyEngine", "default_detectors",
           "STATE_LEVELS"]

STATE_LEVELS = {"ok": 0, "warn": 1, "page": 2}

# Claim-churn needs a minimum event volume before a ratio means anything
# (2 claims / 0 accepts on an idle fleet is not churn).
MIN_CHURN_CLAIMS = 10

# Throughput-cliff needs enough history points for a median to be a
# baseline rather than noise.
MIN_CLIFF_POINTS = 5


def window_secs() -> float:
    try:
        base = max(knobs.ANOMALY_WINDOW_SECS.get(), 1.0)
        scale = max(knobs.ANOMALY_WINDOW_SCALE.get(), 1e-6)
        return base * scale
    except (TypeError, ValueError):
        return 900.0


def _iso(unix_ts: float) -> str:
    """Unix seconds -> the ledger's ISO-8601 UTC format (matches
    server/db.py ts(): lexicographic comparison == time order)."""
    dt = datetime.fromtimestamp(unix_ts, tz=timezone.utc)
    return dt.strftime("%Y-%m-%dT%H:%M:%S.%fZ")


# --- history-store helpers -------------------------------------------------


def _counter_delta(store, prefix: str, since: float) -> Optional[float]:
    """Window delta summed over every series matching the prefix (counters
    are cumulative: delta = last - first). None when no series has data."""
    total, seen = 0.0, False
    for name in store.series_names():
        if not name.startswith(prefix):
            continue
        snap = store.query(name, since=since, tiers=("raw",))
        raw = snap.get("raw", []) if snap else []
        if raw:
            seen = True
            total += max(0.0, raw[-1][1] - raw[0][1])
    return total if seen else None


def _gauge_points(store, prefix: str, since: float) -> List[float]:
    out: List[float] = []
    for name in store.series_names():
        if not name.startswith(prefix):
            continue
        snap = store.query(name, since=since, tiers=("raw",))
        raw = snap.get("raw", []) if snap else []
        out.extend(v for _t, v in raw)
    return out


# --- detectors -------------------------------------------------------------


class AnomalyDetector:
    """One pathology. value_fn(engine, now, since_unix, since_iso) returns
    the window value, or None when the window holds no evidence (no_data ->
    ok, matching the SLO engine's sparse-data behavior)."""

    def __init__(
        self,
        name: str,
        value_fn: Callable,
        warn_at: float,
        page_at: float,
        description: str = "",
    ):
        self.name = name
        self.value_fn = value_fn
        env = name.upper()
        self.warn_at = knobs.ANOMALY_OVERRIDES.get_float(
            f"NICE_TPU_ANOMALY_{env}_WARN", warn_at
        )
        self.page_at = knobs.ANOMALY_OVERRIDES.get_float(
            f"NICE_TPU_ANOMALY_{env}_PAGE", page_at
        )
        self.description = description

    def evaluate(self, engine: "AnomalyEngine", now: float) -> dict:
        win = window_secs()
        since_unix = now - win
        value = self.value_fn(engine, now, since_unix, _iso(since_unix))
        if value is None:
            state = "ok"
        elif value >= self.page_at:
            state = "page"
        elif value >= self.warn_at:
            state = "warn"
        else:
            state = "ok"
        return {
            "detector": self.name,
            "state": state,
            "level": STATE_LEVELS[state],
            "value": value,
            "warn_at": self.warn_at,
            "page_at": self.page_at,
            "window_secs": win,
            "no_data": value is None,
            "description": self.description,
        }


def _stuck_fields(engine, now, since_unix, since_iso):
    """Fields claimed >= NICE_TPU_ANOMALY_STUCK_CLAIMS times in the window
    without ever reaching canon. Any stuck field pages by default — each one
    is work the fleet keeps burning without converging — and the detector
    recovers on its own once canon_promoted lands on the timeline."""
    min_claims = max(knobs.ANOMALY_STUCK_CLAIMS.get(), 1)
    return float(engine.db.count_stuck_fields(min_claims, since_iso))


def _claim_churn(engine, now, since_unix, since_iso):
    """claims-per-accepted-submission ratio: a healthy fleet stays near 1;
    crash-looping clients (or a poisoned field) drive it up."""
    claims = engine.db.count_field_events(
        ("claimed", "block_claimed"), since_iso
    )
    if claims < MIN_CHURN_CLAIMS:
        return None
    accepts = engine.db.count_field_events(("submit_accepted",), since_iso)
    return claims / max(float(accepts), 1.0)


def _lease_expiry_storm(engine, now, since_unix, since_iso):
    return float(
        engine.db.count_field_events(("lease_expired",), since_iso)
    )


def _trust_slash_burst(engine, now, since_unix, since_iso):
    return _counter_delta(
        engine.store, "nice_server_trust_slashes_total", since_unix
    )


def _throughput_cliff(engine, now, since_unix, since_iso):
    """Fractional drop of fleet throughput vs its own window median
    (0 = at baseline, 1 = stopped). Needs enough points for the median to
    be a baseline, and a nonzero baseline (an idle fleet is not a cliff)."""
    points = _gauge_points(
        engine.store, "nice_fleet_numbers_per_sec", since_unix
    )
    if len(points) < MIN_CLIFF_POINTS:
        return None
    ordered = sorted(points)
    median = ordered[len(ordered) // 2]
    if median <= 0:
        return None
    current = points[-1]
    return max(0.0, 1.0 - current / median)


def _mem_leak_trend(engine, now, since_unix, since_iso):
    """Steepest positive growth slope (bytes/sec) across the resident-set
    and watched-disk history series — sustained growth over the window is a
    leak long before anything OOMs. Slope/fit math lives in
    obs/memwatch.trend so the memprof smoke can cross-check it against an
    injected leak rate."""
    slopes = memwatch.trend(engine.store, since_unix)
    if not slopes:
        return None
    worst = max(slopes.values())
    return max(0.0, worst)


def _resource_exhaustion(engine, now, since_unix, since_iso):
    """Time-to-exhaustion forecast: for each of HBM / RSS / disk, the
    fraction of remaining headroom the observed growth slope would consume
    within NICE_TPU_MEMWATCH_HORIZON_SECS. Value 1.0 = some resource runs
    out inside the horizon (page); 0.5 = halfway there (warn)."""
    fc = memwatch.forecast(engine.store, since_unix)
    if not fc:
        return None
    return max(entry["ratio"] for entry in fc.values())


def default_detectors() -> List[AnomalyDetector]:
    return [
        AnomalyDetector(
            "stuck_fields", _stuck_fields, warn_at=1, page_at=1,
            description="fields claimed repeatedly without reaching canon",
        ),
        AnomalyDetector(
            "claim_churn", _claim_churn, warn_at=3, page_at=10,
            description="claims per accepted submission over the window",
        ),
        AnomalyDetector(
            "lease_expiry_storm", _lease_expiry_storm,
            warn_at=10, page_at=50,
            description="leases swept as expired inside the window",
        ),
        AnomalyDetector(
            "trust_slash_burst", _trust_slash_burst, warn_at=1, page_at=5,
            description="trust slashes inside the window",
        ),
        AnomalyDetector(
            "throughput_cliff", _throughput_cliff,
            warn_at=0.5, page_at=0.8,
            description="fleet throughput drop vs its own window median",
        ),
        AnomalyDetector(
            "mem_leak_trend", _mem_leak_trend,
            warn_at=256 * 1024.0, page_at=2 * 1024 * 1024.0,
            description="steepest RSS/disk growth slope (bytes/sec) over"
                        " the window",
        ),
        AnomalyDetector(
            "resource_exhaustion", _resource_exhaustion,
            warn_at=0.5, page_at=1.0,
            description="worst forecast headroom fraction consumed within"
                        " NICE_TPU_MEMWATCH_HORIZON_SECS (1 = exhaustion"
                        " inside the horizon)",
        ),
    ]


class AnomalyEngine:
    """Evaluates detectors against the journal (db) + history store,
    tracking state transitions. Thread-safe: evaluate() runs on the writer
    periodic while /status reads last()."""

    def __init__(self, db, store,
                 detectors: Optional[List[AnomalyDetector]] = None):
        self.db = db
        self.store = store
        self.detectors = (
            detectors if detectors is not None else default_detectors()
        )
        self._lock = lockdep.make_lock("obs.anomaly.AnomalyEngine._lock")
        self._states: Dict[str, str] = {}
        self._last: List[dict] = []
        self.transitions = 0

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        import time

        now = time.time() if now is None else now
        from .series import ANOMALY_STATE, ANOMALY_TRANSITIONS

        results = []
        for det in self.detectors:
            try:
                res = det.evaluate(self, now)
            except Exception:  # noqa: BLE001 — one bad detector can't take
                continue       # down the writer periodic
            results.append(res)
            ANOMALY_STATE.labels(det.name).set(res["level"])
            with self._lock:
                prev = self._states.get(det.name, "ok")
                if res["state"] != prev:
                    self._states[det.name] = res["state"]
                    self.transitions += 1
                    ANOMALY_TRANSITIONS.labels(det.name, res["state"]).inc()
                    flight.record(
                        "anomaly_transition", detector=det.name,
                        from_state=prev, to_state=res["state"],
                        value=res["value"],
                    )
                else:
                    self._states[det.name] = res["state"]
        with self._lock:
            self._last = results
        return results

    def last(self) -> List[dict]:
        with self._lock:
            return list(self._last)
