"""Local /metrics HTTP endpoint for processes that aren't the API server.

The client and daemon run hot loops with no HTTP surface of their own; a
tiny stdlib ThreadingHTTPServer on a localhost port makes their registry
scrapeable. Opt-in via NICE_TPU_METRICS_PORT (port 0 picks a free one).
"""

from __future__ import annotations

import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import metrics

log = logging.getLogger("nice_tpu.obs")

_started_lock = threading.Lock()
_started: Optional[ThreadingHTTPServer] = None


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        if self.path.split("?", 1)[0] not in ("/metrics", "/"):
            self.send_error(404)
            return
        body = metrics.render().encode("utf-8")
        self.send_response(200)
        self.send_header("Content-Type", "text/plain; version=0.0.4")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        log.debug("metrics server: " + fmt, *args)


def serve_metrics(port: int, host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Start a daemon-thread metrics server; returns the server (read the
    bound port from ``server.server_address[1]`` when port=0)."""
    server = ThreadingHTTPServer((host, port), _MetricsHandler)
    t = threading.Thread(
        target=server.serve_forever, name="nice-metrics", daemon=True
    )
    t.start()
    return server


def maybe_serve_metrics() -> Optional[ThreadingHTTPServer]:
    """Start the local /metrics endpoint iff NICE_TPU_METRICS_PORT is set.
    Idempotent per process; a busy port logs a warning instead of raising."""
    global _started
    raw = os.environ.get("NICE_TPU_METRICS_PORT", "")
    if not raw:
        return None
    with _started_lock:
        if _started is not None:
            return _started
        try:
            port = int(raw)
        except ValueError:
            log.warning("NICE_TPU_METRICS_PORT=%r is not an integer", raw)
            return None
        try:
            _started = serve_metrics(port)
        except OSError as exc:
            log.warning("cannot serve /metrics on port %d: %s", port, exc)
            return None
        log.info("serving /metrics on 127.0.0.1:%d",
                 _started.server_address[1])
        return _started
