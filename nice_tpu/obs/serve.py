"""Local /metrics + /debug/flight + /history + /debug/profile HTTP endpoint
for processes that aren't the API server.

The client and daemon run hot loops with no HTTP surface of their own; a
tiny stdlib ThreadingHTTPServer on a localhost port makes their registry
scrapeable, their flight-recorder ring inspectable, and their sampled
time-series history queryable without signalling the process. Opt-in via
NICE_TPU_METRICS_PORT — port 0 binds an ephemeral port so client+daemon on
one host never collide; the actually-bound port is logged and exported as
the ``nice_metrics_bound_port`` gauge (scrape the daemon, learn where its
clients live). Unknown paths — and unknown history series — get a real
``application/json`` 404 body, not the stdlib HTML error page.
"""

from __future__ import annotations

import json
import logging
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from . import flight, history, metrics, pyprof, series
from nice_tpu.utils import knobs, lockdep

log = logging.getLogger("nice_tpu.obs")

_started_lock = lockdep.make_lock("obs.serve._started_lock")
_started: Optional[ThreadingHTTPServer] = None


class _MetricsHandler(BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        path, _, query = self.path.partition("?")
        status = 200
        if path in ("/metrics", "/"):
            body = metrics.render().encode("utf-8")
            ctype = "text/plain; version=0.0.4"
        elif path == "/debug/flight":
            body = json.dumps(
                {
                    "pid": os.getpid(),
                    "capacity": flight.RECORDER.capacity,
                    "total_recorded": flight.RECORDER.total_recorded(),
                    "events": flight.snapshot(),
                },
                default=repr,
            ).encode("utf-8")
            ctype = "application/json"
        elif path == "/history":
            status, payload = history.handle_query(history.STORE, query)
            body = json.dumps(payload, default=repr).encode("utf-8")
            ctype = "application/json"
        elif path == "/debug/profile":
            status, body, ctype = pyprof.handle_query(query)
        else:
            status = 404
            body = json.dumps(
                {
                    "error": f"unknown path {path!r}",
                    "known": ["/metrics", "/debug/flight", "/history",
                              "/debug/profile"],
                }
            ).encode("utf-8")
            ctype = "application/json"
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, fmt, *args):  # silence per-request stderr noise
        log.debug("metrics server: " + fmt, *args)


def serve_metrics(port: int, host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Start a daemon-thread metrics server; returns the server (read the
    bound port from ``server.server_address[1]`` when port=0)."""
    server = ThreadingHTTPServer((host, port), _MetricsHandler)
    series.METRICS_BOUND_PORT.set(server.server_address[1])
    t = threading.Thread(
        target=server.serve_forever, name="nice-metrics", daemon=True
    )
    t.start()
    return server


def maybe_serve_metrics() -> Optional[ThreadingHTTPServer]:
    """Start the local /metrics endpoint iff NICE_TPU_METRICS_PORT is set
    (0 = pick a free port). Idempotent per process; a busy port logs a
    warning instead of raising."""
    global _started
    raw = knobs.METRICS_PORT.raw() or ""
    if not raw:
        return None
    with _started_lock:
        if _started is not None:
            return _started
        try:
            port = int(raw)
        except ValueError:
            log.warning("NICE_TPU_METRICS_PORT=%r is not an integer", raw)
            return None
        try:
            _started = serve_metrics(port)
        except OSError as exc:
            log.warning("cannot serve /metrics on port %d: %s", port, exc)
            return None
        log.info("serving /metrics on 127.0.0.1:%d",
                 _started.server_address[1])
        return _started
