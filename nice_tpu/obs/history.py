"""Time-series history: ring-buffer recorder over the metrics registry.

Point-in-time scrapes (`/metrics`, `/status`) answer "what is happening";
this module answers "what happened" — the backbone the reference builds its
whole public story on (cached search-rate/distribution history tables behind
a static site, PAPER.md L5). Everything is stdlib-only and bounded:

* ``TieredSeries`` — one metric series' history in three fixed-capacity
  downsampling tiers: ``raw`` (every sample), ``1m`` (60 s buckets) and
  ``15m`` (900 s buckets). Coarse tiers keep (bucket_ts, mean, min, max,
  last, n) and are finalized on bucket rollover; queries also include the
  in-progress bucket so short runs still produce multi-tier data.
* ``HistoryStore`` — {series name -> TieredSeries}, fed by
  ``sample_registries()`` which walks one or more metrics registries every
  ``NICE_TPU_HISTORY_SECS`` (default 15): counters/gauges become one series
  per label combination plus an aggregate sum; histograms become
  ``_sum``/``_count`` aggregates plus *windowed* p50/p95/p99 series derived
  from bucket-count deltas between consecutive samples (so the quantiles
  describe the last interval, not the process lifetime).
* ``handle_query()`` — the shared ``GET /history`` implementation used by
  both the server app and the client metrics port (obs/serve.py): JSON
  bodies, real JSON 404s for unknown series, and a directory listing when
  no ``series`` is given.

The server additionally persists finalized points through the writer actor
into the ``metric_history`` table (``HistoryStore.drain_rows()`` +
``Db.insert_metric_history``); the in-memory store stays the source for
``/history`` reads so the hot read path never touches SQLite.
"""

from __future__ import annotations

import collections
import threading
import time
import urllib.parse
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import metrics as metrics_mod
from nice_tpu.utils import knobs, lockdep

__all__ = [
    "TieredSeries",
    "HistoryStore",
    "STORE",
    "handle_query",
    "maybe_start_sampler",
    "sample_interval_secs",
]

TIERS = ("raw", "1m", "15m")

# Per-tier point capacities: ~1 h of raw at 15 s, ~6 h of 1-min, ~7 d of
# 15-min. All three are small fixed rings — a process that runs forever
# holds a bounded history.
RAW_CAP = knobs.HISTORY_RAW_CAP.get()
TIER1_CAP = knobs.HISTORY_1M_CAP.get()
TIER2_CAP = knobs.HISTORY_15M_CAP.get()

QUANTILES = ((50, 0.50), (95, 0.95), (99, 0.99))

# Cap on un-drained persistence rows (client-side stores are never drained).
_PENDING_CAP = 4096


def sample_interval_secs() -> float:
    """The sampling cadence knob (0 disables the background sampler)."""
    try:
        return knobs.HISTORY_SECS.get()
    except ValueError:
        return 15.0


def _tier_secs() -> Tuple[float, float]:
    """Coarse-tier bucket widths; env-scalable so short harness runs (the
    perf gate) can exercise real bucket rollover in seconds."""
    try:
        t1 = knobs.HISTORY_1M_SECS.get()
    except ValueError:
        t1 = 60.0
    try:
        t2 = knobs.HISTORY_15M_SECS.get()
    except ValueError:
        t2 = 900.0
    return max(t1, 1e-6), max(t2, 1e-6)


class _CoarseTier:
    """One downsampling tier: an in-progress aggregate bucket plus a ring of
    finalized (bucket_ts, mean, min, max, last, n) points."""

    __slots__ = ("secs", "points", "cur_ts", "sum", "min", "max", "last", "n")

    def __init__(self, secs: float, cap: int):
        self.secs = secs
        self.points: collections.deque = collections.deque(maxlen=cap)
        self.cur_ts: Optional[float] = None
        self.sum = 0.0
        self.min = 0.0
        self.max = 0.0
        self.last = 0.0
        self.n = 0

    def _bucket(self, ts: float) -> float:
        return ts - (ts % self.secs)

    def add(self, ts: float, value: float):
        """Fold a sample in; returns the finalized point on rollover."""
        b = self._bucket(ts)
        done = None
        if self.cur_ts is not None and b != self.cur_ts:
            done = self._finalize()
        if self.cur_ts is None:
            self.cur_ts = b
            self.sum = self.min = self.max = self.last = value
            self.n = 1
        else:
            self.sum += value
            self.min = min(self.min, value)
            self.max = max(self.max, value)
            self.last = value
            self.n += 1
        return done

    def _finalize(self):
        pt = (self.cur_ts, self.sum / self.n, self.min, self.max,
              self.last, self.n)
        self.points.append(pt)
        self.cur_ts = None
        self.n = 0
        return pt

    def snapshot(self, since: float) -> List[list]:
        out = [list(p) for p in self.points if p[0] >= since]
        if self.n > 0 and self.cur_ts is not None and self.cur_ts >= since:
            out.append([self.cur_ts, self.sum / self.n, self.min, self.max,
                        self.last, self.n])
        return out


class TieredSeries:
    """One series' raw ring + 1m/15m downsampling tiers. Not thread-safe on
    its own — HistoryStore serializes access."""

    __slots__ = ("raw", "t1", "t2", "last_ts")

    def __init__(self, tier1_secs: float, tier2_secs: float):
        self.raw: collections.deque = collections.deque(maxlen=RAW_CAP)
        self.t1 = _CoarseTier(tier1_secs, TIER1_CAP)
        self.t2 = _CoarseTier(tier2_secs, TIER2_CAP)
        self.last_ts = 0.0

    def add(self, ts: float, value: float):
        """Record one sample; returns [(tier, point), ...] finalized now."""
        self.raw.append((ts, value))
        self.last_ts = ts
        done = []
        p1 = self.t1.add(ts, value)
        if p1 is not None:
            done.append(("1m", p1))
        p2 = self.t2.add(ts, value)
        if p2 is not None:
            done.append(("15m", p2))
        return done

    def snapshot(self, since: float, tiers: Sequence[str]) -> Dict[str, list]:
        out: Dict[str, list] = {}
        if "raw" in tiers:
            out["raw"] = [[t, v] for t, v in self.raw if t >= since]
        if "1m" in tiers:
            out["1m"] = self.t1.snapshot(since)
        if "15m" in tiers:
            out["15m"] = self.t2.snapshot(since)
        return out


def _series_key(name: str, labelnames, key) -> str:
    if not key:
        return name
    inner = ",".join(f'{n}="{v}"' for n, v in zip(labelnames, key))
    return f"{name}{{{inner}}}"


def _quantile_from_deltas(bounds, deltas, overflow, q):
    """Linear-interpolated quantile from non-cumulative bucket deltas. The
    overflow (+Inf) bucket clamps to the highest finite bound."""
    total = sum(deltas) + overflow
    if total <= 0:
        return None
    rank = q * total
    cum = 0.0
    lo = 0.0
    for b, d in zip(bounds, deltas):
        if d > 0:
            if cum + d >= rank:
                frac = (rank - cum) / d
                return lo + (b - lo) * frac
            cum += d
        lo = b
    return bounds[-1] if bounds else 0.0


class HistoryStore:
    """Bounded in-memory history for every sampled series.

    One instance per process role: the module-global ``STORE`` backs the
    client metrics port; the server builds its own over both the global
    registry and its private API-latency registry.
    """

    def __init__(self, tier1_secs: Optional[float] = None,
                 tier2_secs: Optional[float] = None):
        t1, t2 = _tier_secs()
        self._t1 = tier1_secs if tier1_secs is not None else t1
        self._t2 = tier2_secs if tier2_secs is not None else t2
        self._lock = lockdep.make_lock("obs.history.HistoryStore._lock")
        self._series: Dict[str, TieredSeries] = {}
        # Previous histogram bucket snapshots, for windowed quantiles.
        self._hist_prev: Dict[str, Tuple[Tuple[int, ...], float, int]] = {}
        # Rows appended since the last drain_rows(): (series, tier, ts,
        # value, vmin, vmax, n). Bounded so never-drained stores can't leak.
        self._pending: collections.deque = collections.deque(
            maxlen=_PENDING_CAP
        )
        self.samples_taken = 0

    # -- recording ---------------------------------------------------------

    def add(self, series: str, value: float, ts: Optional[float] = None):
        ts = time.time() if ts is None else ts
        value = float(value)
        with self._lock:
            s = self._series.get(series)
            if s is None:
                s = self._series[series] = TieredSeries(self._t1, self._t2)
            finalized = s.add(ts, value)
            self._pending.append(
                (series, "raw", ts, value, value, value, 1)
            )
            for tier, (bts, mean, vmin, vmax, _last, n) in finalized:
                self._pending.append(
                    (series, tier, bts, mean, vmin, vmax, n)
                )

    def sample_registries(self, registries, ts: Optional[float] = None) -> int:
        """Walk every metric in the given registries and record one sample
        per derived series. Returns the number of points recorded."""
        ts = time.time() if ts is None else ts
        n = 0
        for reg in registries:
            for name, m in sorted(reg.metrics().items()):
                if isinstance(m, metrics_mod.Histogram):
                    n += self._sample_histogram(name, m, ts)
                elif isinstance(m, (metrics_mod.Counter, metrics_mod.Gauge)):
                    values = m.values()
                    for key, v in values.items():
                        self.add(_series_key(name, m.labelnames, key), v, ts)
                        n += 1
                    if m.labelnames and len(values) > 1:
                        self.add(name, sum(values.values()), ts)
                        n += 1
        self.samples_taken += 1
        return n

    def _sample_histogram(self, name, m, ts) -> int:
        n = 0
        snap = m.bucket_counts()
        agg_sum = 0.0
        agg_count = 0
        for key, (counts, total, count) in snap.items():
            agg_sum += total
            agg_count += count
            skey = _series_key("", m.labelnames, key)  # "{...}" or ""
            prev = self._hist_prev.get(name + skey)
            self._hist_prev[name + skey] = (counts, total, count)
            if prev is None:
                continue
            pc, _ps, pn = prev
            deltas = [c - p for c, p in zip(counts, pc)]
            overflow = (count - sum(counts)) - (pn - sum(pc))
            if count - pn <= 0:
                continue  # nothing observed this window
            for pname, q in QUANTILES:
                qv = _quantile_from_deltas(m.buckets, deltas, overflow, q)
                if qv is not None:
                    self.add(f"{name}_p{pname}{skey}", qv, ts)
                    n += 1
        self.add(f"{name}_sum", agg_sum, ts)
        self.add(f"{name}_count", agg_count, ts)
        return n + 2

    # -- reading -----------------------------------------------------------

    def series_names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def query(self, series: str, since: float = 0.0,
              tiers: Sequence[str] = TIERS) -> Optional[Dict[str, list]]:
        with self._lock:
            s = self._series.get(series)
            if s is None:
                return None
            return s.snapshot(since, tiers)

    def drain_rows(self) -> List[tuple]:
        """Rows appended since the last drain — the server's writer-actor
        periodic persists these into metric_history."""
        with self._lock:
            rows = list(self._pending)
            self._pending.clear()
            return rows


STORE = HistoryStore()

_sampler_lock = lockdep.make_lock("obs.history._sampler_lock")
_sampler_started = False


def maybe_start_sampler(registries=None, store: Optional[HistoryStore] = None,
                        interval: Optional[float] = None) -> bool:
    """Start the background sampling thread once per process (client side;
    the server samples on the writer actor's periodic instead). Returns
    True when the sampler is running. ``NICE_TPU_HISTORY_SECS=0`` disables."""
    global _sampler_started
    secs = sample_interval_secs() if interval is None else interval
    if not secs or secs <= 0:
        return False
    with _sampler_lock:
        if _sampler_started:
            return True
        _sampler_started = True
    regs = registries if registries is not None else [metrics_mod.REGISTRY]
    st = store if store is not None else STORE

    def _run():
        while True:
            time.sleep(secs)
            try:
                st.sample_registries(regs)
            except Exception:  # noqa: BLE001 — sampling must never crash
                pass

    threading.Thread(target=_run, name="nice-history", daemon=True).start()
    return True


# -- shared GET /history handler ------------------------------------------


def _split_series_list(raw: str) -> List[str]:
    """Split a comma-separated series list WITHOUT breaking label sets:
    ``a{x="1",y="2"},b`` is two names — commas inside ``{...}`` belong to
    the name itself."""
    out, cur, depth = [], [], 0
    for ch in raw:
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return [s for s in (x.strip() for x in out) if s]


def handle_query(store: HistoryStore, query_string: str):
    """Shared ``GET /history`` implementation: returns (status, body-dict).

    ``?series=a,b`` selects series (exact names, URL-encoded; commas inside
    ``{...}`` label sets are part of the name); ``?since=TS``
    filters points at-or-after a Unix timestamp; ``?tier=raw|1m|15m`` limits
    tiers. No ``series`` returns the directory of known names. Unknown
    series get a real 404 JSON body naming a sample of known series.
    """
    qs = urllib.parse.parse_qs(query_string or "")
    wanted = []
    for part in qs.get("series", []):
        wanted.extend(_split_series_list(part))
    if not wanted:
        names = store.series_names()
        return 200, {"series": names, "count": len(names)}
    try:
        since = float(qs.get("since", ["0"])[0])
    except ValueError:
        return 400, {"error": "since must be a unix timestamp"}
    tiers: Sequence[str] = TIERS
    if "tier" in qs:
        tiers = tuple(t for t in qs["tier"][0].split(",") if t in TIERS)
        if not tiers:
            return 400, {"error": f"tier must be one of {list(TIERS)}"}
    out: Dict[str, Dict[str, list]] = {}
    missing = []
    for name in wanted:
        snap = store.query(name, since=since, tiers=tiers)
        if snap is None:
            missing.append(name)
        else:
            out[name] = snap
    if missing:
        known = store.series_names()
        return 404, {
            "error": f"unknown series: {', '.join(missing)}",
            "unknown": missing,
            "known_sample": known[:50],
            "known_count": len(known),
        }
    return 200, {"series": out, "since": since, "tiers": list(tiers)}
