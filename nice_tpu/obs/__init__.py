"""nice_tpu.obs — zero-hard-dependency observability layer.

Five pieces, all stdlib-only at import time:

- ``metrics``: a process-wide Prometheus-text registry (counters, gauges,
  histograms) shared by the HTTP server, the client's local /metrics port,
  and the engine pipeline.
- ``trace``: ``span(name)`` / ``trace_event`` structured JSON trace events
  (begin flushed *before* the body runs, so hangs leave evidence), plus
  distributed-trace plumbing — ``trace_context`` stamps spans with a
  trace_id derived from the claim id (``claim_trace_id``) and carried
  between processes as a W3C ``traceparent`` header — and an opt-in
  ``profiler`` wrapper around jax.profiler.
- ``series``: the well-known series names, declared once so emitters and
  scrapers can't drift apart.
- ``flight``: bounded in-process ring of recent structured events, dumped
  atomically to disk on crash / SIGUSR2 / spool quarantine and served at
  ``/debug/flight``.
- ``telemetry``: condenses this process's registry into the compact
  per-client snapshot the server aggregates fleet-wide.
- ``history``: ring-buffer time-series recorder over the registry (raw /
  1m / 15m downsampling tiers) behind ``GET /history``.
- ``stepprof``: the device-step profiler bucketing each field's wall time
  into compile / h2d_feed / device_compute / fold / readback / host_other
  (NICE_TPU_STEPPROF=1; off = zero extra device syncs).
- ``slo``: declarative SLOs with multi-window burn-rate alert states
  (ok / warn / page) evaluated over the history.
- ``journal``: the field lifecycle audit vocabulary + row builders behind
  the server's append-only ``field_events`` table and the client-side
  event buffer that piggybacks on telemetry.
- ``anomaly``: fleet-pathology detectors (stuck fields, claim churn,
  lease-expiry storms, trust-slash bursts, throughput cliffs) over the
  journal + history, with SLO-style ok/warn/page states.
- ``critpath``: fleet critical-path profiler — composes journal
  timelines, client-side RTT/phase stamps, and the writer actor's queue
  waits into reconciled per-field waterfalls, a USE-style utilization
  rollup, and a dominant-segment classifier behind ``GET /critpath``.
- ``stream``: the push-based SSE hub behind ``GET /events/stream`` —
  bounded per-subscriber queues with drop accounting, heartbeats, and
  ``Last-Event-ID`` resume over the journal cursor.
- ``memwatch``: periodic resource sampler — device memory, host RSS
  (utils/resources), compile-cache executable footprint, and watched
  on-disk paths — feeding the nice_mem_* / nice_disk_* series plus the
  leak-trend / time-to-exhaustion anomaly detectors
  (NICE_TPU_MEMWATCH_SECS; 0 = off, zero threads).
- ``pyprof``: always-on statistical wall-clock profiler over
  ``sys._current_frames()``, folded stacks attributed per threadspec
  root, served at ``GET /debug/profile`` and rolled up fleet-wide at
  ``GET /profile/fleet`` (NICE_TPU_PYPROF_HZ; 0 = off, zero threads).
- ``logsink``: the unified JSON-line logging formatter/installer with
  trace_id injection (NICE_TPU_LOG_LEVEL / NICE_TPU_LOG_FILE).

Env vars: NICE_TPU_METRICS_PORT (serve /metrics locally; 0 = ephemeral
port, exported as nice_metrics_bound_port), NICE_TPU_TRACE (span sink:
"stderr"/"1" or a file path; NICE_TPU_TRACE_MAX_BYTES caps+rotates file
sinks), NICE_TPU_PROFILE (jax profiler output dir), NICE_TPU_FLIGHT_DIR /
NICE_TPU_FLIGHT_EVENTS (flight-recorder dump dir / ring capacity).
"""

from . import (  # noqa: F401 — importing pre-seeds
    anomaly,
    critpath,
    flight,
    history,
    journal,
    logsink,
    memwatch,
    pyprof,
    series,
    slo,
    stepprof,
    stream,
    telemetry,
)
from .metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    gauge,
    histogram,
    render,
)
from .serve import maybe_serve_metrics, serve_metrics  # noqa: F401
from .trace import (  # noqa: F401
    claim_trace_id,
    current_trace_id,
    current_traceparent,
    make_traceparent,
    parse_traceparent,
    profiler,
    span,
    trace_context,
    trace_enabled,
    trace_event,
)

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "counter",
    "gauge",
    "histogram",
    "render",
    "series",
    "flight",
    "history",
    "slo",
    "stepprof",
    "telemetry",
    "journal",
    "anomaly",
    "critpath",
    "stream",
    "memwatch",
    "pyprof",
    "logsink",
    "serve_metrics",
    "maybe_serve_metrics",
    "span",
    "trace_event",
    "trace_enabled",
    "trace_context",
    "current_trace_id",
    "current_traceparent",
    "claim_trace_id",
    "make_traceparent",
    "parse_traceparent",
    "profiler",
]
