"""nice_tpu.obs — zero-hard-dependency observability layer.

Three pieces, all stdlib-only at import time:

- ``metrics``: a process-wide Prometheus-text registry (counters, gauges,
  histograms) shared by the HTTP server, the client's local /metrics port,
  and the engine pipeline.
- ``trace``: ``span(name)`` / ``trace_event`` structured JSON trace events
  (begin flushed *before* the body runs, so hangs leave evidence), plus an
  opt-in ``profiler`` wrapper around jax.profiler.
- ``series``: the well-known series names, declared once so emitters and
  scrapers can't drift apart.

Env vars: NICE_TPU_METRICS_PORT (serve /metrics locally), NICE_TPU_TRACE
(span sink: "stderr"/"1" or a file path), NICE_TPU_PROFILE (jax profiler
output dir).
"""

from . import series  # noqa: F401 — importing pre-seeds the series
from .metrics import (  # noqa: F401
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    Registry,
    counter,
    gauge,
    histogram,
    render,
)
from .serve import maybe_serve_metrics, serve_metrics  # noqa: F401
from .trace import profiler, span, trace_enabled, trace_event  # noqa: F401

__all__ = [
    "REGISTRY",
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "counter",
    "gauge",
    "histogram",
    "render",
    "series",
    "serve_metrics",
    "maybe_serve_metrics",
    "span",
    "trace_event",
    "trace_enabled",
    "profiler",
]
