"""Field lifecycle audit journal — the "what happened to field N" layer.

The ledger (server/db.py) stores only *current* state: once a claim churns,
a lease expires, or a consensus hold resolves, the evidence is gone. The
journal is the append-only complement: every field-state transition lands in
the ``field_events`` table as a structured event with a monotonic per-field
sequence number, so ``GET /fields/<id>/timeline`` replays one field's whole
life — generated -> queued -> claimed -> renewed/lease_expired ->
submit_accepted -> spot_check -> consensus_hold -> canon_promoted (or
disqualified -> requeued) — and ``GET /events?since=<id>`` streams the global
feed for external consumers (the delta substrate ROADMAP items 2/4 need).

This module is the shared vocabulary + row builder. Server emission sites
call :func:`event_row` and hand the rows to ``ApiContext.journal`` (async,
through the writer actor) or append them inside an existing write
transaction (atomic with the state change they describe). The journal is
best-effort by design: a failed append increments
``nice_server_journal_write_failures_total`` and records a
``journal_write_failed`` flight event, but never fails the request.

Client-side events (checkpoint save/resume, backend downgrades, spool
replays) cannot reach the table directly — they buffer here via
:func:`record_client_event`, piggyback on the next ``DataToServer.telemetry``
snapshot, and the server merges them into the same timelines with a
``client_`` kind prefix. Client events are keyed by *claim id* (the client
never learns raw field ids); the server resolves claim -> field at merge
time.
"""

from __future__ import annotations

from typing import Optional

from . import trace
from nice_tpu.utils import lockdep

__all__ = [
    "EVENT_KINDS",
    "CLIENT_EVENT_KINDS",
    "event_row",
    "record_client_event",
    "drain_client_events",
    "client_event_rows",
]

# Server-side transition vocabulary (the timeline's causal order for a
# healthy field is roughly left to right).
EVENT_KINDS = (
    "generated",         # field row created by seed_base
    "queued",            # pre-claimed into an in-memory refill queue
    "claimed",           # single-field claim issued
    "block_claimed",     # claimed as part of a /claim_block lease group
    "renewed",           # lease renewed (single claim or whole block)
    "lease_expired",     # sweep released an abandoned lease
    "submit_accepted",   # submission persisted
    "submit_duplicate",  # exactly-once replay (submit_id dedup hit)
    "submit_rejected",   # submission refused (validation / conflict)
    "spot_check",        # trust spot-check ran (detail.verdict pass|fail)
    "consensus_hold",    # untrusted submission held awaiting corroboration
    "canon_promoted",    # submission became canon / check_level advanced
    "disqualified",      # canon submission struck (spot-check fail / admin)
    "requeued",          # field returned to the claim pool after strike
)

# Client-side kinds (merged from telemetry with this exact prefix).
CLIENT_EVENT_KINDS = (
    "client_ckpt_save",
    "client_ckpt_resume",
    "client_downgrade",
    "client_spool_replay",
    # Critical-path segment stamps (obs/critpath.py): request round-trips
    # measured at the client (detail.secs), and the per-field stepprof
    # phase breakdown (detail.{h2d_feed,device_compute,readback,...}).
    "client_claim_rtt",
    "client_submit_rtt",
    "client_phases",
)


def event_row(
    field_id: int,
    kind: str,
    *,
    claim_id: Optional[int] = None,
    client: Optional[str] = None,
    tier: Optional[str] = None,
    check_level: Optional[int] = None,
    ts: Optional[str] = None,
    **detail,
) -> dict:
    """Build one journal row for Db.append_field_events.

    trace_id: derived from the claim when one is in hand (client and server
    compute the same id, so both sides' spans join the event), else the
    ambient request trace context."""
    trace_id = (
        trace.claim_trace_id(claim_id)
        if claim_id is not None
        else trace.current_trace_id()
    )
    if claim_id is not None:
        detail.setdefault("claim_id", claim_id)
    row = {
        "field_id": int(field_id),
        "kind": str(kind),
        "trace_id": trace_id,
        "client": client,
        "tier": tier,
        "check_level": check_level,
        "detail": detail,
    }
    if ts is not None:
        row["ts"] = ts
    return row


# --- client-side event buffer ---------------------------------------------
# Bounded: a client that cannot reach the server for a while must not grow
# memory unboundedly — oldest events drop first (the journal is diagnostic,
# not the ledger of record).

_CLIENT_BUFFER_CAP = 256
_client_lock = lockdep.make_lock("obs.journal._client_lock")
_client_events: list[dict] = []


def record_client_event(kind: str, *, claim_id: Optional[int] = None,
                        **detail) -> None:
    """Buffer one client-side lifecycle event for the next telemetry
    snapshot. kind is recorded without the client_ prefix (e.g.
    "ckpt_save"); the server prefixes it at merge time."""
    evt = {"kind": str(kind)}
    if claim_id is not None:
        evt["claim_id"] = int(claim_id)
    if detail:
        evt["detail"] = detail
    with _client_lock:
        _client_events.append(evt)
        if len(_client_events) > _CLIENT_BUFFER_CAP:
            del _client_events[: len(_client_events) - _CLIENT_BUFFER_CAP]


def drain_client_events() -> list[dict]:
    """Take (and clear) the buffered client events for a telemetry snapshot."""
    with _client_lock:
        events, _client_events[:] = list(_client_events), []
    return events


def client_event_rows(snap: dict, *, client: Optional[str] = None,
                      tier: Optional[str] = None,
                      resolve_claim=None) -> list[dict]:
    """Server-side merge: journal rows from a telemetry snapshot's "events"
    list. Client events carry claim ids, not field ids — resolve_claim maps
    claim_id -> field_id (returning None to skip an unresolvable event)."""
    rows: list[dict] = []
    for evt in snap.get("events") or []:
        if not isinstance(evt, dict):
            continue
        claim_id = evt.get("claim_id")
        field_id = None
        if claim_id is not None and resolve_claim is not None:
            try:
                field_id = resolve_claim(int(claim_id))
            except (ValueError, TypeError):
                field_id = None
        if field_id is None:
            continue
        kind = str(evt.get("kind") or "unknown")[:64]
        detail = evt.get("detail") if isinstance(evt.get("detail"), dict) else {}
        rows.append(
            event_row(
                field_id,
                f"client_{kind}",
                claim_id=int(claim_id),
                client=client,
                tier=tier,
                **detail,
            )
        )
    return rows
