"""Always-on statistical wall-clock profiler with threadspec attribution.

Answers the question the segment taxonomies can't: *which code* is the
``host_other`` / ``unaccounted`` residual. A sampler thread walks
``sys._current_frames()`` ``NICE_TPU_PYPROF_HZ`` times per second and
attributes every sampled stack to its owning **threadspec root** — the
PR 15 ThreadRegistry (analysis/threadspec.py) names every long-lived
thread in the tree, so profiles come out labelled ``db-writer``,
``mesh-feed``, ``telemetry-report``, … instead of ``Thread-7``. The main
thread profiles as ``main``; a thread no ThreadRoot names lands in
``unattributed`` (which the memprof smoke bounds at <10%).

Aggregation is a bounded folded-stack table per root (frame labels are
``file:function`` — no line numbers, so loops don't explode the key
space); past ``NICE_TPU_PYPROF_MAX_STACKS`` distinct stacks, new shapes
collapse into the per-root ``(other)`` bucket. Serving:

* ``GET /debug/profile?fmt=folded|json`` on the API server and on the
  client/daemon metrics port (obs/serve.py) — ``folded`` is the classic
  flamegraph.pl input, ``json`` feeds web/fleet.html's zero-dependency
  flamegraph pane;
* the top-K stacks ride on every telemetry snapshot
  (``obs/telemetry.py``), and ``GET /profile/fleet`` rolls the fleet up.

``NICE_TPU_PYPROF_HZ=0`` means off with **zero overhead**: no sampler
thread is created and ``sample_count()`` stays 0 — the same provable
off-state discipline as stepprof's fence count.
"""

from __future__ import annotations

import logging
import os
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from .series import PYPROF_OVERFLOW, PYPROF_SAMPLES, PYPROF_STACKS
from nice_tpu.utils import knobs, lockdep

log = logging.getLogger("nice_tpu.obs")

__all__ = [
    "hz",
    "sample_count",
    "attribute",
    "take_sample",
    "maybe_start",
    "snapshot",
    "render_folded",
    "top_stacks",
    "handle_query",
    "reset_for_tests",
]

_lock = lockdep.make_lock("obs.pyprof._lock")
_tables: Dict[str, Dict[str, int]] = {}  # root -> folded stack -> samples
_root_samples: Dict[str, int] = {}
_total_samples = 0
_distinct_stacks = 0

_started_lock = lockdep.make_lock("obs.pyprof._started_lock")
_started = False

_OTHER = "(other)"
MAIN_ROOT = "main"
UNATTRIBUTED = "unattributed"

# Runtime thread-name prefixes that differ from their threadspec root —
# ThreadPoolExecutor prefixes are short ("nice-srv_0") while the registry
# names the pool by role ("async-workers"). Checked after the direct root
# scan, longest prefix first.
_RUNTIME_ALIASES: Tuple[Tuple[str, str], ...] = (
    ("nice-srv", "async-workers"),
    ("nice-api", "nice-api-pool"),
)

_root_names_cache: Optional[Tuple[str, ...]] = None


def hz() -> float:
    """Sampling rate; <= 0 means the profiler is off."""
    try:
        return float(knobs.PYPROF_HZ.get())
    except (TypeError, ValueError):
        return 0.0


def sample_count() -> int:
    """Total stacks sampled this process. Stays 0 whenever the profiler is
    disabled — the zero-overhead-off guarantee, testable."""
    return _total_samples


def _root_names() -> Tuple[str, ...]:
    """Registered threadspec root names, longest first so prefix matching
    prefers the most specific root."""
    global _root_names_cache
    if _root_names_cache is None:
        from nice_tpu.analysis.threadspec import THREAD_ROOTS

        _root_names_cache = tuple(
            sorted((r.name for r in THREAD_ROOTS), key=len, reverse=True)
        )
    return _root_names_cache


def attribute(thread_name: str) -> Optional[str]:
    """Owning threadspec root for a runtime thread name (pools spawn
    "<root>_0"-style workers, hence the prefix match); "main" for the main
    thread; None for a thread the registry doesn't know."""
    if thread_name == "MainThread":
        return MAIN_ROOT
    for name in _root_names():
        if thread_name == name or thread_name.startswith(name):
            return name
    for prefix, root in _RUNTIME_ALIASES:
        if thread_name.startswith(prefix):
            return root
    return None


def _fold(frame, depth: int) -> str:
    """Folded-stack key, outermost first: "file:func;file:func;...". No
    line numbers on purpose — a hot loop should be ONE key."""
    parts: List[str] = []
    f = frame
    while f is not None and len(parts) < depth:
        co = f.f_code
        parts.append(f"{os.path.basename(co.co_filename)}:{co.co_name}")
        f = f.f_back
    parts.reverse()
    return ";".join(parts)


def take_sample() -> int:
    """Walk every live thread's current frame once; returns stacks sampled.
    Called by the sampler thread, and directly by tests/the smoke."""
    global _total_samples, _distinct_stacks
    try:
        depth = max(1, int(knobs.PYPROF_DEPTH.get()))
    except (TypeError, ValueError):
        depth = 24
    try:
        max_stacks = max(1, int(knobs.PYPROF_MAX_STACKS.get()))
    except (TypeError, ValueError):
        max_stacks = 2000
    names = {t.ident: t.name for t in threading.enumerate()}
    me = threading.get_ident()
    sampled = 0
    overflowed = 0
    per_root: Dict[str, int] = {}
    frames = sys._current_frames()
    try:
        for ident, frame in frames.items():
            if ident == me:
                continue  # never profile the profiler
            root = attribute(names.get(ident, "")) or UNATTRIBUTED
            folded = _fold(frame, depth)
            with _lock:
                table = _tables.setdefault(root, {})
                if folded not in table and _distinct_stacks >= max_stacks:
                    table[_OTHER] = table.get(_OTHER, 0) + 1
                    overflowed += 1
                else:
                    if folded not in table:
                        _distinct_stacks += 1
                    table[folded] = table.get(folded, 0) + 1
                _root_samples[root] = _root_samples.get(root, 0) + 1
                _total_samples += 1
            per_root[root] = per_root.get(root, 0) + 1
            sampled += 1
    finally:
        del frames  # drop frame references promptly
    for root, n in per_root.items():
        PYPROF_SAMPLES.labels(root).inc(n)
    if overflowed:
        PYPROF_OVERFLOW.inc(overflowed)
    with _lock:
        PYPROF_STACKS.set(_distinct_stacks)
    return sampled


def maybe_start(rate: Optional[float] = None) -> bool:
    """Start the sampler thread once per process. NICE_TPU_PYPROF_HZ=0
    disables — no thread is created at all (zero overhead off)."""
    global _started
    r = hz() if rate is None else rate
    if not r or r <= 0:
        return False
    interval = 1.0 / float(r)
    with _started_lock:
        if _started:
            return True
        _started = True

    def _run():
        while True:
            time.sleep(interval)
            try:
                take_sample()
            except Exception:  # noqa: BLE001 — keep sampling
                log.exception("pyprof sample failed")

    threading.Thread(target=_run, name="nice-pyprof", daemon=True).start()
    log.info("pyprof sampler started (%.1f Hz)", r)
    return True


# --- reporting ------------------------------------------------------------


def snapshot(top_k: Optional[int] = None) -> dict:
    """JSON-shaped profile: per-root sample totals + the hottest stacks
    (all stacks when top_k is None)."""
    with _lock:
        tables = {root: dict(t) for root, t in _tables.items()}
        root_samples = dict(_root_samples)
        total = _total_samples
    roots = {}
    for root, table in sorted(tables.items()):
        stacks = sorted(table.items(), key=lambda kv: (-kv[1], kv[0]))
        if top_k is not None:
            stacks = stacks[:top_k]
        roots[root] = {
            "samples": root_samples.get(root, 0),
            "stacks": [{"stack": s, "count": c} for s, c in stacks],
        }
    return {"hz": hz(), "samples": total, "roots": roots}


def render_folded() -> str:
    """flamegraph.pl-compatible folded stacks, the root name as the base
    frame: "root;file:func;file:func count"."""
    with _lock:
        tables = {root: dict(t) for root, t in _tables.items()}
    lines = []
    for root in sorted(tables):
        for stack, count in sorted(tables[root].items()):
            lines.append(f"{root};{stack} {count}")
    return "\n".join(lines) + ("\n" if lines else "")


def top_stacks(k: Optional[int] = None) -> List[dict]:
    """The k hottest stacks fleet-rollup style: [{root, stack, count}],
    hottest first. Default k = NICE_TPU_PYPROF_TOPK."""
    if k is None:
        try:
            k = max(1, int(knobs.PYPROF_TOPK.get()))
        except (TypeError, ValueError):
            k = 10
    with _lock:
        flat = [
            {"root": root, "stack": stack, "count": count}
            for root, table in _tables.items()
            for stack, count in table.items()
        ]
    flat.sort(key=lambda e: (-e["count"], e["root"], e["stack"]))
    return flat[:k]


def handle_query(query: str) -> Tuple[int, bytes, str]:
    """Shared GET /debug/profile handler for the API server and the local
    metrics endpoint: (status, body, content-type). fmt=folded|json."""
    import json
    from urllib.parse import parse_qs

    fmt = (parse_qs(query or "").get("fmt") or ["json"])[0]
    if fmt == "folded":
        return 200, render_folded().encode("utf-8"), "text/plain"
    if fmt == "json":
        body = json.dumps(snapshot(top_k=50)).encode("utf-8")
        return 200, body, "application/json"
    body = json.dumps(
        {"error": f"unknown fmt {fmt!r}", "known": ["folded", "json"]}
    ).encode("utf-8")
    return 400, body, "application/json"


def reset_for_tests() -> None:
    """Clear aggregated samples (NOT the started-thread guard)."""
    global _total_samples, _distinct_stacks
    with _lock:
        _tables.clear()
        _root_samples.clear()
        _total_samples = 0
        _distinct_stacks = 0
