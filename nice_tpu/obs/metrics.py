"""Zero-dependency metrics registry rendering Prometheus text exposition.

The server's HTTP `/metrics` endpoint, the client's local metrics port, and
the engine's pipeline instrumentation all share one process-wide registry.
Everything here is stdlib-only and thread-safe: the engine observes from its
dispatcher/collector threads while an HTTP thread renders concurrently.

Metric names follow Prometheus conventions (`*_total` counters, `*_seconds`
histograms). Registration is idempotent get-or-create: calling
``counter("x", ...)`` twice returns the same object, so modules can declare
their series at import time without coordinating order. Declared metrics
render even with zero observations — a scrape of a fresh process shows every
series at 0, which keeps smoke tests greppable and dashboards stable.
"""

from __future__ import annotations

import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

from nice_tpu.utils import lockdep

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "Registry",
    "REGISTRY",
    "counter",
    "gauge",
    "histogram",
    "render",
]

DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005,
    0.001,
    0.0025,
    0.005,
    0.01,
    0.025,
    0.05,
    0.1,
    0.25,
    0.5,
    1.0,
    2.5,
    5.0,
    10.0,
)

LabelKey = Tuple[str, ...]


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and v.is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _label_str(names: Sequence[str], values: LabelKey, extra: str = "") -> str:
    parts = [f'{n}="{v}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    if not parts:
        return ""
    return "{" + ",".join(parts) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help_
        self.labelnames = tuple(labelnames)
        self._lock = lockdep.make_lock("obs.metrics._Metric._lock")

    def _key(self, labelvalues: Sequence[str]) -> LabelKey:
        vals = tuple(str(v) for v in labelvalues)
        if len(vals) != len(self.labelnames):
            raise ValueError(
                f"{self.name}: expected labels {self.labelnames}, got {vals}"
            )
        return vals

    def render(self) -> Iterable[str]:  # pragma: no cover - overridden
        return ()


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, labelnames)
        self._values: Dict[LabelKey, float] = {}
        if not self.labelnames:
            self._values[()] = 0.0

    def labels(self, *labelvalues) -> "_BoundCounter":
        key = self._key(labelvalues)
        with self._lock:
            self._values.setdefault(key, 0.0)
        return _BoundCounter(self, key)

    def inc(self, amount: float = 1.0, labelvalues: LabelKey = ()) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        key = self._key(labelvalues)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, labelvalues: LabelKey = ()) -> float:
        key = self._key(labelvalues)
        with self._lock:
            return self._values.get(key, 0.0)

    def values(self) -> Dict[LabelKey, float]:
        """Snapshot of every label combination's value (telemetry/fleet
        aggregation reads the registry instead of double-counting)."""
        with self._lock:
            return dict(self._values)

    def render(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for key, val in items:
            yield f"{self.name}{_label_str(self.labelnames, key)} {_fmt_value(val)}"


class _BoundCounter:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric: Counter, key: LabelKey):
        self._metric = metric
        self._key = key

    def inc(self, amount: float = 1.0) -> None:
        self._metric.inc(amount, self._key)

    def value(self) -> float:
        return self._metric.value(self._key)


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name: str, help_: str, labelnames: Sequence[str] = ()):
        super().__init__(name, help_, labelnames)
        self._values: Dict[LabelKey, float] = {}
        if not self.labelnames:
            self._values[()] = 0.0

    def labels(self, *labelvalues) -> "_BoundGauge":
        key = self._key(labelvalues)
        with self._lock:
            self._values.setdefault(key, 0.0)
        return _BoundGauge(self, key)

    def set(self, value: float, labelvalues: LabelKey = ()) -> None:
        key = self._key(labelvalues)
        with self._lock:
            self._values[key] = float(value)

    def inc(self, amount: float = 1.0, labelvalues: LabelKey = ()) -> None:
        key = self._key(labelvalues)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, labelvalues: LabelKey = ()) -> float:
        key = self._key(labelvalues)
        with self._lock:
            return self._values.get(key, 0.0)

    def values(self) -> Dict[LabelKey, float]:
        with self._lock:
            return dict(self._values)

    def render(self) -> Iterable[str]:
        with self._lock:
            items = sorted(self._values.items())
        for key, val in items:
            yield f"{self.name}{_label_str(self.labelnames, key)} {_fmt_value(val)}"


class _BoundGauge:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric: Gauge, key: LabelKey):
        self._metric = metric
        self._key = key

    def set(self, value: float) -> None:
        self._metric.set(value, self._key)

    def inc(self, amount: float = 1.0) -> None:
        self._metric.inc(amount, self._key)

    def value(self) -> float:
        return self._metric.value(self._key)


class _HistState:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # non-cumulative, per finite bucket
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    kind = "histogram"

    def __init__(
        self,
        name: str,
        help_: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = DEFAULT_BUCKETS,
    ):
        super().__init__(name, help_, labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self._states: Dict[LabelKey, _HistState] = {}
        if not self.labelnames:
            self._states[()] = _HistState(len(self.buckets))

    def labels(self, *labelvalues) -> "_BoundHistogram":
        key = self._key(labelvalues)
        with self._lock:
            self._states.setdefault(key, _HistState(len(self.buckets)))
        return _BoundHistogram(self, key)

    def observe(self, value: float, labelvalues: LabelKey = ()) -> None:
        key = self._key(labelvalues)
        v = float(value)
        with self._lock:
            st = self._states.setdefault(key, _HistState(len(self.buckets)))
            st.sum += v
            st.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    st.counts[i] += 1
                    break

    def label_sums(self) -> Dict[LabelKey, Tuple[float, int]]:
        """Per-label-combination (sum, count) — used by the server's
        deprecated ``*_seconds_total`` alias."""
        with self._lock:
            return {k: (st.sum, st.count) for k, st in self._states.items()}

    def bucket_counts(self) -> Dict[LabelKey, Tuple[Tuple[int, ...], float, int]]:
        """Per-label-combination (per-bucket NON-cumulative counts, sum,
        count) snapshot. The history sampler diffs consecutive snapshots to
        derive windowed quantiles (obs/history.py); ``self.buckets`` gives
        the matching finite upper bounds, with overflow = count - sum(counts)."""
        with self._lock:
            return {
                k: (tuple(st.counts), st.sum, st.count)
                for k, st in self._states.items()
            }

    def render(self) -> Iterable[str]:
        with self._lock:
            items = sorted(
                (k, list(st.counts), st.sum, st.count)
                for k, st in self._states.items()
            )
        for key, counts, total, count in items:
            cum = 0
            for b, c in zip(self.buckets, counts):
                cum += c
                le = f'le="{b}"'
                yield (
                    f"{self.name}_bucket"
                    f"{_label_str(self.labelnames, key, le)} {cum}"
                )
            inf = 'le="+Inf"'
            yield (
                f"{self.name}_bucket"
                f"{_label_str(self.labelnames, key, inf)} {count}"
            )
            yield (
                f"{self.name}_sum{_label_str(self.labelnames, key)}"
                f" {repr(float(total))}"
            )
            yield f"{self.name}_count{_label_str(self.labelnames, key)} {count}"


class _BoundHistogram:
    __slots__ = ("_metric", "_key")

    def __init__(self, metric: Histogram, key: LabelKey):
        self._metric = metric
        self._key = key

    def observe(self, value: float) -> None:
        self._metric.observe(value, self._key)


class Registry:
    """Process-wide metric store. Registration is get-or-create: re-declaring
    a metric with the same name returns the existing object (labelnames must
    match)."""

    def __init__(self):
        self._lock = lockdep.make_lock("obs.metrics.Registry._lock")
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name, help_, labelnames, **kwargs):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as"
                        f" {existing.kind}, not {cls.kind}"
                    )
                if existing.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} labelnames mismatch:"
                        f" {existing.labelnames} vs {tuple(labelnames)}"
                    )
                return existing
            m = cls(name, help_, labelnames, **kwargs)
            self._metrics[name] = m
            return m

    def counter(self, name, help_="", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help_, labelnames)

    def gauge(self, name, help_="", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help_, labelnames)

    def histogram(
        self, name, help_="", labelnames=(), buckets=DEFAULT_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help_, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def metrics(self) -> Dict[str, _Metric]:
        """Point-in-time copy of {name: metric} for iteration without holding
        the registry lock (the history sampler walks every series)."""
        with self._lock:
            return dict(self._metrics)

    def render(self) -> str:
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines = []
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            lines.extend(m.render())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()


def counter(name, help_="", labelnames=(), registry: Registry = None) -> Counter:
    return (registry or REGISTRY).counter(name, help_, labelnames)


def gauge(name, help_="", labelnames=(), registry: Registry = None) -> Gauge:
    return (registry or REGISTRY).gauge(name, help_, labelnames)


def histogram(
    name, help_="", labelnames=(), buckets=DEFAULT_BUCKETS, registry: Registry = None
) -> Histogram:
    return (registry or REGISTRY).histogram(name, help_, labelnames, buckets)


def render(registry: Registry = None) -> str:
    return (registry or REGISTRY).render()
