"""Fleet critical-path profiler: end-to-end latency attribution.

Every canon-promoted field already leaves three partial timing records
behind: its journal timeline (server-side transitions with microsecond
timestamps), the client-side events piggybacked on telemetry (request
round-trips, checkpoint resume, spool replays, the stepprof phase
breakdown), and the writer actor's measured queue wait stamped onto
``submit_accepted``. None of them alone answers "where did this field's
wall-clock go?". This module composes all three into one segmented
waterfall per field::

    queue_wait | claim_rtt | ckpt_resume | h2d_feed | device_compute |
    readback | spool_retry | submit_rtt | writer_wait | canon_promotion |
    unaccounted

and reconciles it: segments must sum to the observed journal wall clock
(first queued/generated -> canon_promoted) within a declared tolerance
(``NICE_TPU_CRITPATH_TOLERANCE`` as a fraction of wall, floored at
``MIN_TOLERANCE_SECS``). The residual is *never hidden* — it is reported
signed per field and any positive remainder lands in the visible
``unaccounted`` segment, so attribution gaps show up as a segment you can
rank, not as silent slack.

Fleet rollup (:class:`CritpathEngine`): per-segment p50/p95 and
share-of-total-wall over the last ``NICE_TPU_CRITPATH_WINDOW_FIELDS``
promoted fields, a USE-style utilization triple (writer-actor busy
fraction from :meth:`WriteActor.busy_stats`, device busy fraction and feed
idle fraction from the fleet's stepprof phase totals), and a
dominant-segment classifier. The engine runs on the writer's history tick
(gauges land in the same sample as the rest of the observatory), serves
``GET /critpath``, and emits a ``bottleneck_shift`` flight event + stream
notification whenever the dominant segment changes or any segment's share
moves by more than ``NICE_TPU_CRITPATH_SHIFT_RATIO``.

Attribution caveats (accepted, documented): client-side segments are
measured on the client's monotonic clock and mapped into the server-side
wall interval, so clock skew between the two never corrupts a segment —
it surfaces as residual. The client round-trips *contain* the writer-actor
queue waits (the handler blocks on the writer future), so the measured
waits are subtracted back out of ``claim_rtt``/``submit_rtt`` and out of
``queue_wait``'s overlap with the in-flight claim request — segments are
disjoint slices of wall clock, not independent stopwatches. stepprof's
``compile`` bucket folds into ``device_compute`` (both are device-side
work) and ``fold`` into ``readback`` (both are device->host transfers);
``host_other`` is by definition unattributed and stays in ``unaccounted``.
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from nice_tpu.utils import knobs, lockdep

from . import flight
from .series import (
    CRITPATH_FIELDS_WINDOW,
    CRITPATH_SEGMENT_P50,
    CRITPATH_SEGMENT_P95,
    CRITPATH_SEGMENT_SHARE,
    CRITPATH_UNRECONCILED,
    CRITPATH_UTILIZATION,
)

__all__ = [
    "SEGMENTS",
    "MIN_TOLERANCE_SECS",
    "field_waterfall",
    "phase_shares",
    "aggregate",
    "CritpathEngine",
]

# Segment taxonomy, in causal order. Kept in sync with the gauge seeds in
# obs/series.py and the table in README.md — nicelint's registry pass will
# flag a gauge labeled with a segment not seeded there.
SEGMENTS = (
    "queue_wait",       # generated/queued -> claimed (sat in the pool)
    "claim_rtt",        # client-measured /claim round-trip
    "ckpt_resume",      # checkpoint load + fast-forward replay
    "h2d_feed",         # host->device feed stalls (stepprof h2d_feed)
    "device_compute",   # device execution incl. compile (stepprof)
    "readback",         # device->host folds + readbacks (stepprof)
    "spool_retry",      # offline spool replay delay
    "submit_rtt",       # client-measured /submit round-trip (minus writer wait)
    "writer_wait",      # writer-actor queue wait, claim + submit ops
                        # (measured at the actor, not inferred)
    "canon_promotion",  # submit_accepted -> canon_promoted (trust path)
    "unaccounted",      # positive residual — visible, never hidden
)

# Tolerance floor: below this absolute slack, sub-second scheduling jitter
# (timestamp quantization, GC pauses) would flap the reconciled bit.
MIN_TOLERANCE_SECS = 0.25

# Journal kinds that anchor the waterfall.
_START_KINDS = ("generated", "queued")
_CLAIM_KINDS = ("claimed", "block_claimed")

# stepprof phase -> segment fold (see module docstring for rationale).
_PHASE_FOLD = {
    "h2d_feed": "h2d_feed",
    "device_compute": "device_compute",
    "compile": "device_compute",
    "fold": "readback",
    "readback": "readback",
}


def _parse_ts(value) -> Optional[float]:
    """Journal ISO timestamp -> epoch seconds (None on junk)."""
    if not value:
        return None
    from nice_tpu.server.db import parse_ts

    try:
        return parse_ts(str(value)).timestamp()
    except (ValueError, TypeError):
        return None


def _detail_secs(evt: dict, key: str = "secs") -> float:
    try:
        return max(0.0, float((evt.get("detail") or {}).get(key, 0.0) or 0.0))
    except (TypeError, ValueError):
        return 0.0


def field_waterfall(
    events: list[dict],
    tolerance_frac: Optional[float] = None,
) -> Optional[dict]:
    """Compose one field's journal timeline into a reconciled waterfall.

    events: the field's full timeline (Db.get_field_timeline order —
    ascending per-field seq). Returns None unless the field reached
    canon_promoted (in-flight fields have no defined wall clock yet).

    The waterfall follows the *canon-producing attempt*: the last
    claim at or before the accepted submission. Client events from
    earlier churned claims (an expired lease's ckpt_resume) still belong
    to this field's end-to-end latency and are summed in — the field
    waited through them regardless of which claim finally landed.
    """
    if tolerance_frac is None:
        tolerance_frac = float(knobs.CRITPATH_TOLERANCE.get())

    promoted = next(
        (e for e in reversed(events) if e.get("kind") == "canon_promoted"),
        None,
    )
    if promoted is None:
        return None
    end = _parse_ts(promoted.get("ts"))
    if end is None:
        return None

    start_evt = next(
        (e for e in events if e.get("kind") in _START_KINDS), None
    )
    claim_evt = next(
        (e for e in events if e.get("kind") in _CLAIM_KINDS), None
    )
    accepted = next(
        (e for e in events if e.get("kind") == "submit_accepted"), None
    )
    start = _parse_ts((start_evt or claim_evt or events[0]).get("ts"))
    if start is None or end < start:
        return None
    wall = end - start

    seg = {s: 0.0 for s in SEGMENTS}

    for evt in events:
        kind = evt.get("kind")
        if kind == "client_claim_rtt":
            seg["claim_rtt"] += _detail_secs(evt)
        elif kind == "client_submit_rtt":
            seg["submit_rtt"] += _detail_secs(evt)
        elif kind == "client_ckpt_resume":
            seg["ckpt_resume"] += _detail_secs(evt)
        elif kind == "client_spool_replay":
            seg["spool_retry"] += _detail_secs(evt)
        elif kind == "client_phases":
            detail = evt.get("detail") or {}
            for phase, target in _PHASE_FOLD.items():
                seg[target] += _detail_secs({"detail": detail}, phase)

    # Disjointness: the client-measured round-trips CONTAIN the server-side
    # writer-queue waits (the handler blocks on the writer future), and the
    # claimed-event timestamp lands INSIDE the claim round-trip. Subtract the
    # measured overlaps so every segment covers its own slice of wall clock:
    #   writer_wait   = claim op wait + submit op wait (measured at the actor)
    #   claim_rtt     = client /claim round-trip minus its writer wait
    #   submit_rtt    = client /submit round-trip minus its writer wait
    #   queue_wait    = generated/queued -> claimed stamp, minus the portion
    #                   the claim request itself was already in flight
    w_claim = _detail_secs(claim_evt, "writer_wait") if claim_evt else 0.0
    w_submit = _detail_secs(accepted, "writer_wait") if accepted else 0.0
    t_claim = _parse_ts(claim_evt.get("ts")) if claim_evt else None
    if t_claim is not None:
        overlap = max(seg["claim_rtt"], w_claim)
        seg["queue_wait"] = max(0.0, (t_claim - start) - overlap)
    seg["claim_rtt"] = max(0.0, seg["claim_rtt"] - w_claim)
    seg["submit_rtt"] = max(0.0, seg["submit_rtt"] - w_submit)
    seg["writer_wait"] = w_claim + w_submit

    t_accept = _parse_ts(accepted.get("ts")) if accepted else None
    if t_accept is not None:
        seg["canon_promotion"] = max(0.0, end - t_accept)

    accounted = sum(v for k, v in seg.items() if k != "unaccounted")
    residual = wall - accounted
    seg["unaccounted"] = max(0.0, residual)
    tolerance = max(MIN_TOLERANCE_SECS, tolerance_frac * wall)
    dominant = max(SEGMENTS, key=lambda s: seg[s])
    return {
        "field_id": promoted.get("field_id"),
        "start_ts": (start_evt or claim_evt or events[0]).get("ts"),
        "end_ts": promoted.get("ts"),
        "wall_secs": round(wall, 6),
        "segments": {s: round(seg[s], 6) for s in SEGMENTS},
        "residual_secs": round(residual, 6),
        "tolerance_secs": round(tolerance, 6),
        "reconciled": abs(residual) <= tolerance,
        "dominant": dominant,
    }


def phase_shares(prof: dict) -> Optional[dict]:
    """Critpath summary of a stepprof phase table (bench.py's per-mode and
    whole-suite breakdowns): fold the profiler's phase buckets into critpath
    segments, compute each segment's share of the summed wall clock, and name
    the dominant one. prof is stepprof.cumulative() shaped —
    {"mode|b<base>|backend": {phase: secs, "wall": secs, ...}}. Returns None
    when the table carries no wall time (profiler off / nothing ran)."""
    wall = 0.0
    totals = {s: 0.0 for s in SEGMENTS}
    for entry in prof.values():
        if not isinstance(entry, dict):
            continue
        try:
            wall += max(0.0, float(entry.get("wall", 0.0) or 0.0))
        except (TypeError, ValueError):
            continue
        for phase, target in _PHASE_FOLD.items():
            try:
                totals[target] += max(0.0, float(entry.get(phase, 0.0) or 0.0))
            except (TypeError, ValueError):
                pass
    if wall <= 0.0:
        return None
    attributed = sum(totals.values())
    totals["unaccounted"] = max(0.0, wall - attributed)
    shares = {
        s: round(totals[s] / wall, 6) for s in SEGMENTS if totals[s] > 0.0
    }
    dominant = max(shares, key=shares.get) if shares else None
    return {
        "wall_secs": round(wall, 6),
        "shares": shares,
        "dominant": dominant,
    }


def _percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile on a pre-sorted list (0 for empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(q * len(sorted_vals))))
    return sorted_vals[idx]


def aggregate(waterfalls: list[dict]) -> dict:
    """Fleet rollup over per-field waterfalls: per-segment p50/p95 +
    share-of-total-wall, the dominant segment, and reconciliation stats."""
    walls = [w["wall_secs"] for w in waterfalls]
    total_wall = sum(walls)
    per_seg: dict[str, dict] = {}
    for s in SEGMENTS:
        vals = sorted(w["segments"][s] for w in waterfalls)
        total = sum(vals)
        per_seg[s] = {
            "p50": round(_percentile(vals, 0.50), 6),
            "p95": round(_percentile(vals, 0.95), 6),
            "total_secs": round(total, 6),
            "share": round(total / total_wall, 6) if total_wall > 0 else 0.0,
        }
    dominant = (
        max(SEGMENTS, key=lambda s: per_seg[s]["share"])
        if total_wall > 0
        else None
    )
    unreconciled = [
        w["field_id"] for w in waterfalls if not w["reconciled"]
    ]
    return {
        "fields": len(waterfalls),
        "total_wall_secs": round(total_wall, 6),
        "segments": per_seg,
        "dominant": dominant,
        "unreconciled_fields": unreconciled,
    }


class CritpathEngine:
    """Windowed fleet critical-path state.

    db/writer are the server's; on_event (optional) receives
    ``(kind, payload)`` for stream fan-out when the bottleneck shifts.
    Thread model: :meth:`evaluate` runs on the writer thread (history
    tick); :meth:`snapshot` may be called from any handler thread — reads
    go through Db's read connections and the short-TTL cache keeps a hot
    ``/critpath`` endpoint from re-walking timelines per request.
    """

    # Snapshot cache TTL: /critpath and the history tick share one
    # computation per interval instead of re-reading N timelines each.
    CACHE_SECS = 2.0

    def __init__(
        self,
        db,
        writer=None,
        on_event: Optional[Callable[[str, dict], None]] = None,
    ):
        self.db = db
        self.writer = writer
        self.on_event = on_event
        self._lock = lockdep.make_lock("obs.critpath.CritpathEngine._lock")
        self._cache: Optional[dict] = None
        self._cache_at = 0.0
        self._last_dominant: Optional[str] = None
        self._last_shares: dict[str, float] = {}
        # Writer busy fraction over the evaluation interval, not process
        # lifetime: diff consecutive (busy, uptime) samples so a stall NOW
        # moves the gauge NOW.
        self._last_busy: Optional[tuple[float, float]] = None
        self._busy_fraction = 0.0
        # Fields already counted into the unreconciled counter (bounded;
        # counter semantics demand we not re-count a field every tick).
        self._counted_unreconciled: set[int] = set()

    # -- read side ---------------------------------------------------------

    def _utilization(self) -> dict:
        """USE-style triple. Device/feed fractions come from the fleet's
        persisted stepprof phase totals (every active client's cumulative
        breakdown, summed server-side); writer busy from the actor."""
        if self.writer is not None and self._last_busy is None:
            # No evaluate() tick yet: fall back to lifetime fraction.
            busy, uptime = self.writer.busy_stats()
            writer_busy = busy / uptime if uptime > 0 else 0.0
        else:
            writer_busy = self._busy_fraction
        device_busy = feed_idle = 0.0
        try:
            totals = self.db.get_fleet_phase_totals()
        except Exception:  # noqa: BLE001 — utilization is best-effort
            totals = {}
        wall = float(totals.get("wall", 0.0) or 0.0)
        if wall > 0:
            device_busy = (
                float(totals.get("device_compute", 0.0) or 0.0)
                + float(totals.get("compile", 0.0) or 0.0)
            ) / wall
            feed_idle = float(totals.get("h2d_feed", 0.0) or 0.0) / wall
        return {
            "writer_busy": round(min(1.0, max(0.0, writer_busy)), 6),
            "device_busy": round(min(1.0, max(0.0, device_busy)), 6),
            "feed_idle": round(min(1.0, max(0.0, feed_idle)), 6),
        }

    def _compute(self) -> dict:
        window = max(1, int(knobs.CRITPATH_WINDOW_FIELDS.get()))
        tol = float(knobs.CRITPATH_TOLERANCE.get())
        field_ids = self.db.get_recent_canon_fields(window)
        waterfalls = []
        for fid in field_ids:
            w = field_waterfall(self.db.get_field_timeline(fid), tol)
            if w is not None:
                waterfalls.append(w)
        agg = aggregate(waterfalls)
        return {
            "window_fields": window,
            "tolerance_frac": tol,
            "utilization": self._utilization(),
            "waterfalls": waterfalls,
            **agg,
        }

    def snapshot(self, max_age_secs: Optional[float] = None) -> dict:
        """Current fleet critical-path view (cached for CACHE_SECS)."""
        ttl = self.CACHE_SECS if max_age_secs is None else max_age_secs
        now = time.monotonic()
        with self._lock:
            if self._cache is not None and now - self._cache_at < ttl:
                return self._cache
        snap = self._compute()
        with self._lock:
            self._cache = snap
            self._cache_at = time.monotonic()
        return snap

    # -- tick side (writer thread) ----------------------------------------

    def evaluate(self) -> Optional[dict]:
        """History-tick hook: refresh gauges, detect bottleneck shifts.

        Returns the shift event payload when one fired (tests), else None.
        """
        if not knobs.CRITPATH.get_bool():
            return None
        if self.writer is not None:
            busy, uptime = self.writer.busy_stats()
            if self._last_busy is not None:
                db_busy = busy - self._last_busy[0]
                db_up = uptime - self._last_busy[1]
                if db_up > 0:
                    self._busy_fraction = min(1.0, max(0.0, db_busy / db_up))
            self._last_busy = (busy, uptime)
        snap = self.snapshot(max_age_secs=0.0)

        for s in SEGMENTS:
            info = snap["segments"][s]
            CRITPATH_SEGMENT_SHARE.labels(s).set(info["share"])
            CRITPATH_SEGMENT_P50.labels(s).set(info["p50"])
            CRITPATH_SEGMENT_P95.labels(s).set(info["p95"])
        for res, val in snap["utilization"].items():
            CRITPATH_UTILIZATION.labels(res).set(val)
        CRITPATH_FIELDS_WINDOW.set(snap["fields"])
        for fid in snap["unreconciled_fields"]:
            if fid not in self._counted_unreconciled:
                self._counted_unreconciled.add(fid)
                CRITPATH_UNRECONCILED.inc()
        if len(self._counted_unreconciled) > 4096:
            self._counted_unreconciled.clear()

        return self._detect_shift(snap)

    def _detect_shift(self, snap: dict) -> Optional[dict]:
        dominant = snap.get("dominant")
        shares = {s: snap["segments"][s]["share"] for s in SEGMENTS}
        ratio = float(knobs.CRITPATH_SHIFT_RATIO.get())
        moved = [
            s for s in SEGMENTS
            if abs(shares[s] - self._last_shares.get(s, 0.0)) > ratio
        ]
        changed = (
            self._last_dominant is not None
            and dominant is not None
            and dominant != self._last_dominant
        )
        prev_dominant, prev_shares = self._last_dominant, self._last_shares
        if dominant is not None:
            self._last_dominant = dominant
            self._last_shares = shares
        if not changed and not (moved and prev_shares):
            return None
        payload = {
            "dominant": dominant,
            "previous": prev_dominant,
            "moved_segments": {
                s: {
                    "from": round(prev_shares.get(s, 0.0), 6),
                    "to": round(shares[s], 6),
                }
                for s in moved
            },
            "fields": snap["fields"],
        }
        flight.record("bottleneck_shift", **payload)
        if self.on_event is not None:
            try:
                self.on_event("critpath", payload)
            except Exception:  # noqa: BLE001 — stream fan-out is best-effort
                pass
        return payload
