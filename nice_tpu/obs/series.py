"""Well-known series shared across layers.

Declared centrally (and dependency-light) so the server — which must never
import jax — can still render every engine pipeline series at zero, and so
spelling stays consistent between the emitting module and the scrape-side
smoke test. Importing this module pre-seeds the common label combinations.
"""

from __future__ import annotations

from . import metrics

# --- engine pipeline (ops/engine.py) ------------------------------------
ENGINE_BATCH_KERNEL_SECONDS = metrics.histogram(
    "nice_engine_batch_kernel_seconds",
    "Device kernel wall time per collected batch, by pipeline path.",
    labelnames=("path",),
)
ENGINE_DISPATCH_OCCUPANCY = metrics.gauge(
    "nice_engine_dispatch_window_occupancy",
    "In-flight batches in the detailed/dense dispatch window.",
)
ENGINE_STRIDE_OCCUPANCY = metrics.gauge(
    "nice_engine_stride_window_occupancy",
    "In-flight descriptor batches in the strided dispatch window.",
)
ENGINE_HOST_FALLBACK = metrics.counter(
    "nice_engine_host_fallback_total",
    "Work routed to the host engine instead of the device, by reason.",
    labelnames=("reason",),
)
ENGINE_AUDITS = metrics.counter(
    "nice_engine_audit_total",
    "Device-vs-host audit re-checks performed on strided batches.",
)
ENGINE_DESCRIPTORS = metrics.counter(
    "nice_engine_stride_descriptors_total",
    "Stride descriptors dispatched to the device.",
)
ENGINE_NUMBERS = metrics.counter(
    "nice_engine_numbers_total",
    "Candidate numbers whose range processing completed, by mode.",
    labelnames=("mode",),
)
ENGINE_READBACK_BYTES = metrics.counter(
    "nice_engine_readback_bytes_total",
    "Device->host result bytes actually transferred, by payload kind "
    "(nm/count scalars, compacted survivor lists, folded stats, dense "
    "fallbacks, strided count tiles).",
    labelnames=("kind",),
)
ENGINE_STATS_TRANSFERS = metrics.counter(
    "nice_engine_stats_transfers_total",
    "Device->host transfers of the detailed stats accumulator, by mode. "
    "With device-resident accumulation this is ~1 per field, not 1 per batch.",
    labelnames=("mode",),
)
ENGINE_SURVIVOR_OVERFLOW = metrics.counter(
    "nice_engine_survivor_overflow_total",
    "Compacted survivor readbacks that overflowed the on-device cap and "
    "fell back to a dense per-lane transfer.",
)
ENGINE_FILTER_PRUNED = metrics.counter(
    "nice_engine_filter_pruned_total",
    "Candidates pruned on-device by the fused residue/stride filter before "
    "any limb math ran, by mode and base.",
    labelnames=("mode", "base"),
)
ENGINE_DISPATCHES = metrics.counter(
    "nice_engine_dispatches_total",
    "Device dispatches issued by the dense engine loops, by mode. With the "
    "megaloop one dispatch covers a whole segment (batch_size * segment "
    "lanes per device), so this collapses by the segment factor vs the "
    "per-batch feed.",
    labelnames=("mode",),
)

# --- pallas + mesh dispatch ---------------------------------------------
PALLAS_DISPATCH_SECONDS = metrics.histogram(
    "nice_pallas_dispatch_seconds",
    "Wall time of one pallas kernel dispatch call (async enqueue under jit;"
    " synchronous execution in interpreter mode).",
    labelnames=("kernel",),
)
MESH_DISPATCH_SECONDS = metrics.histogram(
    "nice_mesh_dispatch_seconds",
    "Wall time of one sharded mesh step dispatch.",
    labelnames=("mode",),
)
MESH_DEVICES = metrics.gauge(
    "nice_mesh_devices",
    "Devices in the most recently constructed mesh.",
)
MESH_FEED_IDLE = metrics.histogram(
    "nice_mesh_feed_idle_seconds",
    "Host-side inter-dispatch gap in the device feed: time between one "
    "sharded dispatch returning and the next being issued. The double-"
    "buffered feed (NICE_TPU_FEED_DEPTH > 0) moves per-batch host "
    "arithmetic off this path, so the gap is the direct measure of feed "
    "overlap.",
    labelnames=("mode",),
    buckets=(1e-5, 3e-5, 1e-4, 3e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0),
)
MESH_RESHARDS = metrics.counter(
    "nice_mesh_reshard_events_total",
    "Elastic mesh downshifts: mid-field rebuilds over surviving devices "
    "after a device loss, by detection reason (device_lost = the dispatch "
    "raised MeshDeviceLost; probe = a post-failure device probe found the "
    "loss).",
    labelnames=("reason",),
)
MESH_RESHARD_SECONDS = metrics.histogram(
    "nice_mesh_reshard_seconds",
    "Wall time of one elastic downshift: partial-accumulator flush, mesh "
    "rebuild over survivors, re-slice of the remaining cursor range.",
    buckets=(0.001, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 30.0),
)
MESH_SLICE_CURSOR = metrics.gauge(
    "nice_mesh_slice_cursor",
    "Per-slice scan cursor of the in-flight field (float: precision-lossy "
    "above 2^53, observability only — the checkpoint manifest carries the "
    "exact cursors).",
    labelnames=("slice",),
)

# --- compiled-executable cache (ops/compile_cache.py) --------------------
COMPILE_CACHE_EVENTS = metrics.counter(
    "nice_compile_cache_events_total",
    "Compilation-cache traffic: the jax persistent cache (layer=persistent,"
    " event=hit/request) and the in-process AOT executable cache"
    " (layer=executable, event=hit/miss/evicted — evictions are the LRU"
    " cap NICE_TPU_COMPILE_CACHE_MAX_EXECUTABLES biting).",
    labelnames=("layer", "event"),
)

# --- kernel autotuner (ops/autotune.py) ----------------------------------
AUTOTUNE_EVENTS = metrics.counter(
    "nice_autotune_events_total",
    "Autotuner winners-table traffic: hit (a tuned winner was applied), miss"
    " (no entry; built-in default used), invalidated (entry dropped because"
    " its plan signature no longer matches this runtime), env_override (an"
    " NICE_TPU_* env var took precedence), sweep (a timing sweep ran), store"
    " (a winner was persisted).",
    labelnames=("event",),
)
for _ev in ("hit", "miss", "invalidated", "env_override", "sweep", "store"):
    AUTOTUNE_EVENTS.labels(_ev)
del _ev

# --- backend init (utils/platform.py) -----------------------------------
BACKEND_INIT_SECONDS = metrics.histogram(
    "nice_backend_init_seconds",
    "Wall time of each jax backend init phase.",
    labelnames=("phase",),
)

# --- client (client/main.py, client/api_client.py) ----------------------
CLIENT_REQUEST_SECONDS = metrics.histogram(
    "nice_client_request_seconds",
    "API round-trip latency per attempt, by endpoint.",
    labelnames=("endpoint",),
)
CLIENT_RETRIES = metrics.counter(
    "nice_client_retries_total",
    "Failed API attempts that triggered a backoff retry, by endpoint.",
    labelnames=("endpoint",),
)
CLIENT_FAILOVERS = metrics.counter(
    "nice_client_failovers_total",
    "Multi-server rotations: an endpoint attempt failed (conn_error/5xx/"
    "fence) and the client moved to the next configured server.",
    labelnames=("endpoint",),
)
CLIENT_FIELDS = metrics.counter(
    "nice_client_fields_total",
    "Fields fully processed by this client, by mode.",
    labelnames=("mode",),
)
CLIENT_NUMBERS = metrics.counter(
    "nice_client_numbers_total",
    "Candidate numbers processed by this client.",
)
CLIENT_FIELD_SECONDS = metrics.histogram(
    "nice_client_field_seconds",
    "Wall time to process one claimed field, by mode.",
    labelnames=("mode",),
    buckets=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0,
             600.0, 1800.0),
)

# --- checkpoint subsystem (ckpt/, ops/engine.py, client/main.py) ---------
CKPT_WRITES = metrics.counter(
    "nice_engine_checkpoint_writes_total",
    "Field-scan snapshots written (atomic manifest+payload files).",
)
CKPT_BYTES = metrics.counter(
    "nice_engine_checkpoint_bytes_total",
    "Bytes of snapshot data written to the checkpoint directory.",
)
CKPT_RESTORES = metrics.counter(
    "nice_engine_checkpoint_restores_total",
    "Field scans resumed from a validated snapshot instead of restarting.",
)
CKPT_BATCHES_SKIPPED = metrics.counter(
    "nice_engine_checkpoint_batches_skipped_total",
    "Dispatch batches skipped (not recomputed) thanks to a resumed cursor.",
)
CKPT_RENEWALS = metrics.counter(
    "nice_engine_checkpoint_renewals_total",
    "Successful /renew_claim heartbeats sent while scanning.",
)
CKPT_REJECTED = metrics.counter(
    "nice_engine_checkpoint_rejected_total",
    "Snapshots rejected on restore, by reason (corrupt CRC/truncation, "
    "plan-signature mismatch, state-contract version drift, unknown format "
    "version).",
    labelnames=("reason",),
)

# --- fault injection + degradation (faults/, ops/engine.py) --------------
FAULTS_INJECTED = metrics.counter(
    "nice_faults_injected_total",
    "Chaos faults actually fired, by injection site and action "
    "(NICE_TPU_FAULTS; zero in production unless someone armed the spec).",
    labelnames=("site", "action"),
)
ENGINE_BACKEND_DOWNGRADES = metrics.counter(
    "nice_engine_backend_downgrades_total",
    "Mid-field backend fallbacks after a dispatch failure "
    "(pallas -> jnp -> scalar chain).",
    labelnames=("from_backend", "to_backend"),
)
SPOOL_JOURNALED = metrics.counter(
    "nice_client_spool_journaled_total",
    "Finished submissions journaled to the on-disk spool after retry "
    "exhaustion instead of being dropped.",
)
SPOOL_REPLAYS = metrics.counter(
    "nice_client_spool_replays_total",
    "Spooled submissions replayed, by outcome (accepted / duplicate / "
    "rejected 4xx / failed-will-retry).",
    labelnames=("outcome",),
)
SPOOL_QUARANTINE_PRUNED = metrics.counter(
    "nice_spool_quarantine_pruned_bytes_total",
    "Bytes of quarantined (.rejected) spool entries deleted by the "
    "size/age retention sweep (NICE_TPU_SPOOL_QUARANTINE_MAX_BYTES / "
    "_MAX_AGE_SECS).",
)

# --- server (server/app.py, server/db.py) --------------------------------
SERVER_CLAIM_EXPIRY = metrics.gauge(
    "nice_server_claim_expiry_window_seconds",
    "Configured claim-lease window: claims older than this are re-claimable "
    "(NICE_TPU_CLAIM_EXPIRY_SECS; default CLAIM_DURATION_HOURS).",
)
SERVER_CLAIM_RENEWALS = metrics.counter(
    "nice_server_claim_renewals_total",
    "Claim leases renewed via /renew_claim.",
)
SERVER_FIELDS_RELEASED = metrics.counter(
    "nice_server_fields_released_total",
    "Pre-claimed queue fields released back to the DB on queue close.",
)
SERVER_DUPLICATE_SUBMITS = metrics.counter(
    "nice_server_duplicate_submits_total",
    "Submissions replayed with an already-persisted submit_id and answered "
    "idempotently instead of double-inserting.",
)
SERVER_OVERLOAD_RESPONSES = metrics.counter(
    "nice_server_overload_responses_total",
    "Requests answered 503 + Retry-After because the in-flight request "
    "count exceeded NICE_TPU_MAX_INFLIGHT.",
)
SERVER_SQLITE_BUSY_RETRIES = metrics.counter(
    "nice_server_sqlite_busy_retries_total",
    "Write transactions retried after SQLITE_BUSY before succeeding.",
)

# --- single-writer DB actor + block leases (server/writer.py, server/app.py)
SERVER_WRITE_BATCH_SIZE = metrics.histogram(
    "nice_server_write_batch_size",
    "Mutations coalesced into one SQLite transaction by the writer actor.",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0),
)
SERVER_WRITER_QUEUE_DEPTH = metrics.gauge(
    "nice_server_writer_queue_depth",
    "Mutations waiting in the writer actor's queue at batch-drain time.",
)
SERVER_WRITER_OP_WAIT_SECONDS = metrics.histogram(
    "nice_server_writer_op_wait_seconds",
    "Writer-actor queue wait per mutation: submit()-enqueue to batch-begin."
    " This is the measured writer-queue-wait segment of the critical path,"
    " not an inference from endpoint latency.",
    buckets=(0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0, 30.0),
)
SERVER_WRITER_OP_EXEC_SECONDS = metrics.histogram(
    "nice_server_writer_op_exec_seconds",
    "Writer-actor execution time per mutation (inside its savepoint,"
    " excluding queue wait and the shared batch commit).",
    buckets=(0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0, 30.0),
)
SERVER_BLOCK_LEASE_SIZE = metrics.histogram(
    "nice_server_block_lease_size",
    "Fields handed out per /claim_block lease.",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
)
SERVER_FIELD_QUEUE_REFILLS = metrics.counter(
    "nice_server_field_queue_refills_total",
    "Low-water-mark refills performed by the field pre-generation pipeline, "
    "by queue.",
    labelnames=("queue",),
)
SERVER_STATUS_CACHE_EVENTS = metrics.counter(
    "nice_server_status_cache_events_total",
    "Read-snapshot cache traffic for the /status fleet block.",
    labelnames=("event",),
)

# --- untrusted-client hardening (server/trust.py, server/app.py) ---------
SERVER_SPOT_CHECKS = metrics.counter(
    "nice_server_spot_checks_total",
    "Spot verifications of accepted submissions on the trusted scalar "
    "engine, by verdict (pass / fail / skipped — skipped = the seeded "
    "sampler elected not to verify this submission).",
    labelnames=("verdict",),
)
SERVER_TRUST_SLASHES = metrics.counter(
    "nice_server_trust_slashes_total",
    "Trust scores slashed to zero after a failed spot check.",
)
SERVER_TRUST_CLIENTS = metrics.gauge(
    "nice_server_trust_clients",
    "Clients in the trust ledger, by tier (trusted / untrusted / suspect).",
    labelnames=("tier",),
)
SERVER_RATE_LIMITED = metrics.counter(
    "nice_server_rate_limited_total",
    "Requests answered 429 + Retry-After by the per-client token-bucket "
    "limiter (distinct from the global 503 overload shed).",
)
SERVER_LEASES_EXPIRED = metrics.counter(
    "nice_server_leases_expired_total",
    "Expired claim leases whose fields the background sweep released for "
    "re-issue (abandoned micro-field claims).",
)
SERVER_CONSENSUS_HOLDS = metrics.counter(
    "nice_server_consensus_holds_total",
    "Detailed submissions from below-threshold clients held at "
    "needs-consensus instead of promoting canon directly.",
)

# --- fleet telemetry aggregation (server/app.py, server/db.py) -----------
# Re-exported from client_telemetry rows the server persists: each client
# ships a compact registry snapshot with every submission and with the
# lightweight POST /telemetry heartbeat. Refreshed on every /status,
# /metrics, and /telemetry request.
FLEET_CLIENTS = metrics.gauge(
    "nice_fleet_clients",
    "Distinct clients whose telemetry heartbeat is fresher than the "
    "activity window (NICE_TPU_FLEET_ACTIVE_SECS, default 900).",
)
FLEET_FIELDS = metrics.gauge(
    "nice_fleet_fields_total",
    "Fields completed across all reporting clients, by mode.",
    labelnames=("mode",),
)
FLEET_NUMBERS = metrics.gauge(
    "nice_fleet_numbers",
    "Candidate numbers processed across all reporting clients.",
)
FLEET_RATE = metrics.gauge(
    "nice_fleet_numbers_per_sec",
    "Summed most-recent per-client throughput (numbers/sec).",
)
FLEET_DOWNGRADES = metrics.gauge(
    "nice_fleet_backend_downgrades",
    "Mid-field backend downgrades across all reporting clients.",
)
FLEET_RESTORES = metrics.gauge(
    "nice_fleet_checkpoint_restores",
    "Checkpoint restores across all reporting clients.",
)
FLEET_FAULTS = metrics.gauge(
    "nice_fleet_faults_injected",
    "Chaos faults fired across all reporting clients.",
)
FLEET_SPOOL_DEPTH = metrics.gauge(
    "nice_fleet_spool_depth",
    "Submissions sitting in on-disk spools across all reporting clients.",
)
FLEET_MESH_DEVICES = metrics.gauge(
    "nice_fleet_mesh_devices",
    "Mesh devices summed across all reporting clients.",
)
FLEET_MESH_RESHARDS = metrics.gauge(
    "nice_fleet_mesh_reshards",
    "Elastic mesh downshift events across all reporting clients.",
)
FLEET_FIELD_LATENCY = metrics.gauge(
    "nice_fleet_field_seconds",
    "Recent server-observed field latency quantiles (claim->accepted "
    "submission), over the last ~200 submissions.",
    labelnames=("quantile",),
)
SERVER_FIELD_ELAPSED = metrics.histogram(
    "nice_server_field_elapsed_seconds",
    "Claim-to-accepted-submission elapsed time as observed by the server, "
    "by mode.",
    labelnames=("mode",),
    buckets=(1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0, 10800.0, 43200.0),
)
SERVER_TELEMETRY_REPORTS = metrics.counter(
    "nice_server_telemetry_reports_total",
    "Client telemetry snapshots persisted, by source (heartbeat POST "
    "/telemetry vs piggyback on a submission).",
    labelnames=("source",),
)

# --- performance observatory (obs/history.py, stepprof.py, slo.py) -------
STEPPROF_PHASE_SECONDS = metrics.histogram(
    "nice_stepprof_phase_seconds",
    "Per-field phase-attributed wall time from the device-step profiler "
    "(NICE_TPU_STEPPROF=1): compile / h2d_feed / device_compute / fold / "
    "readback / host_other, by mode, base and backend.",
    labelnames=("mode", "base", "backend", "phase"),
    buckets=(0.001, 0.005, 0.025, 0.1, 0.5, 2.0, 10.0, 60.0),
)
SLO_STATE = metrics.gauge(
    "nice_slo_state",
    "Burn-rate alert state per SLO (0 = ok, 1 = warn, 2 = page).",
    labelnames=("slo",),
)
SLO_TRANSITIONS = metrics.counter(
    "nice_slo_transitions_total",
    "SLO alert state transitions, by SLO and entered state.",
    labelnames=("slo", "state"),
)
HISTORY_SAMPLES = metrics.counter(
    "nice_history_samples_total",
    "History sampler ticks (each tick records one point per derived "
    "series into the ring-buffer history).",
)
HISTORY_PERSISTED_ROWS = metrics.counter(
    "nice_history_persisted_rows_total",
    "metric_history rows persisted through the writer actor.",
)

# --- field lifecycle audit journal + anomaly engine ----------------------
SERVER_JOURNAL_EVENTS = metrics.counter(
    "nice_server_journal_events_total",
    "field_events journal rows appended, by event kind.",
    labelnames=("kind",),
)
SERVER_JOURNAL_WRITE_FAILURES = metrics.counter(
    "nice_server_journal_write_failures_total",
    "Journal appends that failed inside the writer actor (the audit plane "
    "is best-effort: a failed append never fails the request it describes).",
)
SERVER_JOURNAL_PRUNED = metrics.counter(
    "nice_server_journal_pruned_total",
    "field_events rows dropped by the retention sweep.",
)
ANOMALY_STATE = metrics.gauge(
    "nice_anomaly_state",
    "Anomaly-detector alert state (0 = ok, 1 = warn, 2 = page), by "
    "detector.",
    labelnames=("detector",),
)
ANOMALY_TRANSITIONS = metrics.counter(
    "nice_anomaly_transitions_total",
    "Anomaly-detector state transitions, by detector and entered state.",
    labelnames=("detector", "state"),
)

# --- critical-path engine + live event stream (obs/critpath.py, stream.py)
CRITPATH_SEGMENT_SHARE = metrics.gauge(
    "nice_critpath_segment_share",
    "Fleet-wide share of end-to-end wall-clock attributed to each critical-"
    "path segment over the recent canon-field window (0..1; includes the"
    " visible unaccounted residual).",
    labelnames=("segment",),
)
CRITPATH_SEGMENT_P50 = metrics.gauge(
    "nice_critpath_segment_p50_seconds",
    "Per-segment p50 across the recent canon-field waterfalls.",
    labelnames=("segment",),
)
CRITPATH_SEGMENT_P95 = metrics.gauge(
    "nice_critpath_segment_p95_seconds",
    "Per-segment p95 across the recent canon-field waterfalls.",
    labelnames=("segment",),
)
CRITPATH_UTILIZATION = metrics.gauge(
    "nice_critpath_utilization",
    "USE-style utilization rollup (0..1): writer_busy (writer-actor busy"
    " fraction), device_busy (device-compute share of profiled client"
    " wall), feed_idle (h2d feed-wait share of profiled client wall).",
    labelnames=("resource",),
)
CRITPATH_FIELDS_WINDOW = metrics.gauge(
    "nice_critpath_fields_window",
    "Canon fields in the most recent critical-path aggregation window"
    " (0 = no waterfall evidence yet).",
)
CRITPATH_UNRECONCILED = metrics.counter(
    "nice_critpath_unreconciled_total",
    "Per-field waterfalls whose segments failed to reconcile to observed"
    " wall-clock within NICE_TPU_CRITPATH_TOLERANCE.",
)
STREAM_SUBSCRIBERS = metrics.gauge(
    "nice_stream_subscribers",
    "Open GET /events/stream subscriptions.",
)
STREAM_EVENTS = metrics.counter(
    "nice_stream_events_total",
    "Events fanned out to stream subscribers, by event kind (journal /"
    " anomaly / slo / critpath / heartbeat / sched / resource).",
    labelnames=("kind",),
)
STREAM_DROPPED = metrics.counter(
    "nice_stream_dropped_total",
    "Events dropped because a subscriber's bounded queue was full.",
)
STREAM_EVICTIONS = metrics.counter(
    "nice_stream_evictions_total",
    "Slow consumers evicted after exceeding NICE_TPU_STREAM_MAX_DROPS.",
)

# --- local metrics endpoint (obs/serve.py) -------------------------------
METRICS_BOUND_PORT = metrics.gauge(
    "nice_metrics_bound_port",
    "TCP port the local /metrics endpoint actually bound (matters when "
    "NICE_TPU_METRICS_PORT=0 asks for an ephemeral port; 0 = not serving).",
)

# --- daemon (daemon/main.py) --------------------------------------------
DAEMON_HEARTBEAT = metrics.gauge(
    "nice_daemon_heartbeat_timestamp_seconds",
    "Unix time of the daemon supervisor loop's last tick.",
)
DAEMON_RESTARTS = metrics.counter(
    "nice_daemon_client_restarts_total",
    "Client processes (re)started by the daemon.",
)
DAEMON_CPU = metrics.gauge(
    "nice_daemon_cpu_usage_ratio",
    "Most recent whole-machine CPU usage sample (0..1).",
)
DAEMON_RESTART_BACKOFF = metrics.gauge(
    "nice_daemon_restart_backoff_secs",
    "Crash-loop protection: the restart delay imposed after the client's "
    "latest short-lived nonzero exit (0 = no backoff; resets after a "
    "healthy run).",
)

# --- resource observatory (obs/memwatch.py, obs/pyprof.py) ----------------
MEM_RSS_BYTES = metrics.gauge(
    "nice_mem_rss_bytes",
    "Host resident set of this process at the last memwatch sample "
    "(utils/resources backend ladder: /proc -> psutil -> rusage peak).",
)
MEM_RSS_PEAK_BYTES = metrics.gauge(
    "nice_mem_rss_peak_bytes",
    "Process-lifetime peak resident set (getrusage ru_maxrss).",
)
MEM_DEVICE_BYTES = metrics.gauge(
    "nice_mem_device_bytes",
    "Accelerator bytes in use per device (device.memory_stats; absent "
    "stats report live-array bytes on that device instead).",
    labelnames=("device",),
)
MEM_DEVICE_PEAK_BYTES = metrics.gauge(
    "nice_mem_device_peak_bytes",
    "Accelerator peak bytes in use per device since process start "
    "(device.memory_stats peak_bytes_in_use where the backend exposes it).",
    labelnames=("device",),
)
MEM_DEVICE_LIMIT_BYTES = metrics.gauge(
    "nice_mem_device_limit_bytes",
    "Accelerator memory capacity per device (device.memory_stats "
    "bytes_limit; the exhaustion forecaster's HBM ceiling).",
    labelnames=("device",),
)
MEM_LIVE_ARRAYS = metrics.gauge(
    "nice_mem_live_arrays",
    "jax.live_arrays() population at the last memwatch sample.",
)
MEM_LIVE_ARRAY_BYTES = metrics.gauge(
    "nice_mem_live_array_bytes",
    "Total nbytes of jax.live_arrays() at the last memwatch sample.",
)
MEM_CACHED_EXECUTABLES = metrics.gauge(
    "nice_mem_cached_executables",
    "AOT executables held by the in-process compile cache "
    "(bounded by NICE_TPU_COMPILE_CACHE_MAX_EXECUTABLES).",
)
MEM_EXECUTABLE_BYTES = metrics.gauge(
    "nice_mem_executable_bytes",
    "Best-effort AOT executable footprint per compile-cache (mode, base) "
    "group: generated code size where XLA exposes it, else 0.",
    labelnames=("key",),
)
MEM_SAMPLES = metrics.counter(
    "nice_mem_samples_total",
    "Memwatch samples taken (stays 0 with NICE_TPU_MEMWATCH_SECS=0 — the "
    "memwatch-off proof, like stepprof's fence count).",
)
DISK_USAGE_BYTES = metrics.gauge(
    "nice_disk_usage_bytes",
    "On-disk footprint of each watched path (spool, quarantined spool "
    "entries, checkpoint dir, trace sink, SQLite ledger incl. the "
    "repl_ops journal).",
    labelnames=("what",),
)
DISK_FREE_BYTES = metrics.gauge(
    "nice_disk_free_bytes",
    "Free bytes on the filesystem holding the watched paths (statvfs; the "
    "exhaustion forecaster's disk headroom unless "
    "NICE_TPU_MEMWATCH_DISK_CAPACITY overrides it).",
)
PYPROF_SAMPLES = metrics.counter(
    "nice_pyprof_samples_total",
    "Thread-stack samples taken by the statistical profiler, attributed "
    "to the owning threadspec root ('unattributed' = a thread no "
    "ThreadRoot names; stays 0 with NICE_TPU_PYPROF_HZ=0).",
    labelnames=("root",),
)
PYPROF_STACKS = metrics.gauge(
    "nice_pyprof_stacks",
    "Distinct folded stacks currently retained across all roots "
    "(bounded by NICE_TPU_PYPROF_MAX_STACKS).",
)
PYPROF_OVERFLOW = metrics.counter(
    "nice_pyprof_overflow_total",
    "Samples collapsed into a root's (other) bucket because the folded-"
    "stack table hit NICE_TPU_PYPROF_MAX_STACKS.",
)

# --- replication & failover (server/repl.py) -----------------------------
REPL_SEQ = metrics.gauge(
    "nice_repl_seq",
    "Primary: op-log high-water mark (last committed repl_ops seq).",
)
REPL_APPLIED_SEQ = metrics.gauge(
    "nice_repl_applied_seq",
    "Standby: last op seq applied to the local replica.",
)
REPL_LAG = metrics.gauge(
    "nice_repl_lag_ops",
    "Standby: upstream max seq minus locally applied seq (0 = caught up).",
)
REPL_EPOCH = metrics.gauge(
    "nice_repl_epoch",
    "Fencing epoch this replica believes is current (promotion bumps it).",
)
REPL_OPS_APPLIED = metrics.counter(
    "nice_repl_ops_applied_total",
    "Standby: op-log entries applied to the local replica.",
)
REPL_STREAM_ERRORS = metrics.counter(
    "nice_repl_stream_errors_total",
    "Standby: failed op-log fetch/apply rounds against the upstream.",
)
REPL_FENCED_WRITES = metrics.counter(
    "nice_repl_fenced_writes_total",
    "Writes rejected by the epoch fence (410 deposed-primary or 421"
    " standby misdirect).",
)
REPL_STANDBYS = metrics.gauge(
    "nice_repl_standbys",
    "Primary: standbys seen polling /repl/ops within the liveness window.",
)

# Pre-seed the label combinations every layer emits, so a scrape of a fresh
# process (or of the jax-free server) still shows each series at zero.
for _path in ("detailed", "dense", "strided"):
    ENGINE_BATCH_KERNEL_SECONDS.labels(_path)
for _kind in ("nm", "count", "survivors", "survivors-dense", "stats",
              "strided-counts"):
    ENGINE_READBACK_BYTES.labels(_kind)
for _mode in ("detailed",):
    ENGINE_STATS_TRANSFERS.labels(_mode)
for _layer, _event in (("persistent", "hit"), ("persistent", "request"),
                       ("executable", "hit"), ("executable", "miss"),
                       ("executable", "evicted")):
    COMPILE_CACHE_EVENTS.labels(_layer, _event)
for _reason in ("sliver", "host-route", "limbs"):
    ENGINE_HOST_FALLBACK.labels(_reason)
for _mode in ("detailed", "niceonly"):
    ENGINE_NUMBERS.labels(_mode)
    ENGINE_DISPATCHES.labels(_mode)
    MESH_DISPATCH_SECONDS.labels(_mode)
    MESH_FEED_IDLE.labels(_mode)
    CLIENT_FIELDS.labels(_mode)
    CLIENT_FIELD_SECONDS.labels(_mode)
for _reason in ("device_lost", "probe"):
    MESH_RESHARDS.labels(_reason)
for _kernel in ("detailed", "niceonly_dense", "niceonly_strided", "uniques",
                "survivors"):
    PALLAS_DISPATCH_SECONDS.labels(_kernel)
for _phase in ("import-jax", "configure", "devices"):
    BACKEND_INIT_SECONDS.labels(_phase)
for _endpoint in ("claim", "submit", "validate", "renew", "telemetry"):
    CLIENT_REQUEST_SECONDS.labels(_endpoint)
    CLIENT_RETRIES.labels(_endpoint)
    CLIENT_FAILOVERS.labels(_endpoint)
for _mode in ("detailed", "niceonly"):
    FLEET_FIELDS.labels(_mode)
    SERVER_FIELD_ELAPSED.labels(_mode)
for _q in ("0.5", "0.95"):
    FLEET_FIELD_LATENCY.labels(_q)
for _source in ("heartbeat", "submission"):
    SERVER_TELEMETRY_REPORTS.labels(_source)
for _event in ("hit", "miss"):
    SERVER_STATUS_CACHE_EVENTS.labels(_event)
for _verdict in ("pass", "fail", "skipped"):
    SERVER_SPOT_CHECKS.labels(_verdict)
for _tier in ("trusted", "untrusted", "suspect"):
    SERVER_TRUST_CLIENTS.labels(_tier)
for _queue in ("niceonly", "detailed_thin"):
    SERVER_FIELD_QUEUE_REFILLS.labels(_queue)
for _reason in ("corrupt", "signature", "state_version", "version"):
    CKPT_REJECTED.labels(_reason)
for _outcome in ("delivered", "rejected", "deferred"):
    SPOOL_REPLAYS.labels(_outcome)
for _from, _to in (("pallas", "jnp"), ("jnp", "scalar")):
    ENGINE_BACKEND_DOWNGRADES.labels(_from, _to)
for _slo in ("claim_p99", "submit_success", "feed_idle_p95",
             "spot_check_fail"):
    SLO_STATE.labels(_slo)
for _detector in ("stuck_fields", "claim_churn", "lease_expiry_storm",
                  "trust_slash_burst", "throughput_cliff",
                  "mem_leak_trend", "resource_exhaustion"):
    ANOMALY_STATE.labels(_detector)
for _kind in ("generated", "queued", "claimed", "block_claimed", "renewed",
              "lease_expired", "submit_accepted", "submit_duplicate",
              "submit_rejected", "spot_check", "consensus_hold",
              "canon_promoted", "disqualified", "requeued"):
    SERVER_JOURNAL_EVENTS.labels(_kind)
# Critical-path segment taxonomy (kept in sync with obs/critpath.SEGMENTS,
# which imports these gauges; duplicated here like the journal kinds so a
# scrape of a fresh server shows every segment at zero).
for _seg in ("queue_wait", "claim_rtt", "ckpt_resume", "h2d_feed",
             "device_compute", "readback", "spool_retry", "submit_rtt",
             "writer_wait", "canon_promotion", "unaccounted"):
    CRITPATH_SEGMENT_SHARE.labels(_seg)
    CRITPATH_SEGMENT_P50.labels(_seg)
    CRITPATH_SEGMENT_P95.labels(_seg)
for _resource in ("writer_busy", "device_busy", "feed_idle"):
    CRITPATH_UTILIZATION.labels(_resource)
for _kind in ("journal", "anomaly", "slo", "critpath", "heartbeat", "sched",
              "resource"):
    STREAM_EVENTS.labels(_kind)
for _what in ("spool", "quarantine", "ckpt", "trace", "ledger"):
    DISK_USAGE_BYTES.labels(_what)
PYPROF_SAMPLES.labels("unattributed")
del _what

# --- multi-tenant scheduler (sched/) ------------------------------------
# Tenant labels are operator-chosen names, so nothing here is pre-seeded:
# the series appear the moment the scheduler dispatches its first page.
SCHED_PAGES = metrics.counter(
    "nice_sched_pages_total",
    "Device pages dispatched by the multi-tenant scheduler, by tenant. One "
    "page = one batch-aligned megaloop-segment quantum of a field.",
    labelnames=("tenant",),
)
SCHED_PAGE_SECONDS = metrics.histogram(
    "nice_sched_page_seconds",
    "Wall time of one scheduled page (engine dispatch + fold), by tenant. "
    "The per-tenant SLO specs (obs/slo.tenant_specs) burn against this.",
    labelnames=("tenant",),
    buckets=(0.01, 0.05, 0.25, 1.0, 2.5, 5.0, 15.0, 60.0, 300.0),
)
SCHED_PREEMPTIONS = metrics.counter(
    "nice_sched_preemptions_total",
    "Tenant turns ended at a segment boundary before their work drained, "
    "by preempted tenant and reason (quantum = time-slice expiry; "
    "slo_boost = a burning tenant took the mesh).",
    labelnames=("tenant", "reason"),
)
SCHED_OCCUPANCY = metrics.gauge(
    "nice_sched_tenant_occupancy",
    "Share of scheduler device-busy time attributed to each tenant over "
    "the run so far (0..1; sums to ~1 across tenants once work flows).",
    labelnames=("tenant",),
)
SCHED_MESH_OCCUPANCY = metrics.gauge(
    "nice_sched_mesh_occupancy",
    "Fraction of scheduler wall-clock the mesh spent executing pages "
    "(0..1) — the interleaving win over sequential single-tenant runs.",
)
SCHED_SLO_BURN = metrics.gauge(
    "nice_sched_slo_burn",
    "Short-window SLO burn rate per tenant (1.0 = burning exactly at the "
    "objective; drives the scheduler's priority boost).",
    labelnames=("tenant",),
)
SCHED_STARVED = metrics.counter(
    "nice_sched_tenant_starved_total",
    "Anti-starvation interventions: rounds where a runnable tenant had "
    "been skipped past the starvation bound and was force-scheduled.",
    labelnames=("tenant",),
)
SCHED_FIELDS = metrics.counter(
    "nice_sched_fields_total",
    "Fields fully drained (all pages folded) by the scheduler, by tenant.",
    labelnames=("tenant",),
)

# Flight-recorder + tracing series (M1: declared here, used by obs.flight /
# obs.trace). Kinds the production hooks emit are pre-seeded so a scrape of
# a clean process shows the series at zero.
FLIGHT_EVENTS = metrics.counter(
    "nice_flight_events_total",
    "Structured events appended to the in-process flight-recorder ring, "
    "by kind.",
    labelnames=("kind",),
)
FLIGHT_DUMPS = metrics.counter(
    "nice_flight_dumps_total",
    "Flight-recorder ring dumps written to disk, by trigger reason.",
    labelnames=("reason",),
)
TRACE_SPAN_SECONDS = metrics.histogram(
    "nice_trace_span_seconds",
    "Wall-clock duration of named trace spans.",
    labelnames=("span",),
)
FLIGHT_KNOWN_KINDS = ("dispatch_error", "retry", "fault", "checkpoint",
                      "restore", "downgrade", "spool", "quarantine",
                      "submit", "claim", "crash", "telemetry",
                      # elastic mesh + trust state transitions (PR 8 / PR 9
                      # sites) and SLO alerting — a post-crash dump must
                      # explain them.
                      "mesh_reshard", "device_loss", "spot_check_fail",
                      "trust_slash", "consensus_hold", "slo_transition",
                      # audit plane (journal write failures are silent
                      # otherwise; anomaly transitions mirror slo_transition)
                      "journal_write_failed", "anomaly_transition",
                      # critical-path engine: the fleet's dominant latency
                      # segment changed (obs/critpath.py)
                      "bottleneck_shift",
                      # multi-tenant scheduler (sched/): a tenant lost its
                      # turn at a segment boundary, or the anti-starvation
                      # bound fired for a skipped tenant.
                      "sched_preemption", "tenant_starved",
                      # resource observatory: the spool's quarantine
                      # retention sweep deleted .rejected entries
                      # (obs/memwatch rides anomaly_transition for leak /
                      # exhaustion state changes).
                      "quarantine_pruned")
for _kind in FLIGHT_KNOWN_KINDS:
    FLIGHT_EVENTS.labels(_kind)
for _reason in ("crash", "sigusr2", "quarantine", "manual"):
    FLIGHT_DUMPS.labels(_reason)
