"""Declarative SLOs with multi-window burn-rate alerting over the history.

An ``SloSpec`` names an objective over sampled history (obs/history.py)
rather than instantaneous gauges, in one of two shapes:

* ``quantile`` — a latency-style bound: "no more than ``objective`` of
  sampled windows may see <series> above ``threshold``" (e.g. claim p99
  <= 500 ms, feed idle p95 <= 50 ms). The series are the windowed
  ``*_pNN`` quantiles the history sampler derives from histogram deltas.
* ``ratio`` — an error-budget bound over counter deltas: bad/total over the
  window must stay under ``objective`` (submit 5xx ratio, spot-check fail
  ratio).

State follows the standard multi-window burn-rate scheme: with
``burn = bad_fraction / objective`` evaluated over a short and a long
window, ``page`` requires both windows to burn above ``page_burn`` (fast
AND sustained — a single bad sample can't page), ``warn`` likewise above
``warn_burn``; anything else (including no data) is ``ok``. Window lengths
scale with ``NICE_TPU_SLO_WINDOW_SCALE`` so short harness runs (the perf
gate) can exercise real transitions in seconds; per-spec thresholds accept
``NICE_TPU_SLO_<NAME>_THRESHOLD`` / ``..._OBJECTIVE`` overrides.

The server evaluates its ``SloEngine`` on the writer actor's periodic, right
after each history sample: states land in ``nice_slo_state{slo}`` (0 ok /
1 warn / 2 page), transitions in ``nice_slo_transitions_total{slo,state}``
plus a ``slo_transition`` flight-recorder event, and the latest results
block is surfaced in ``/status`` for the fleet.html alerts strip.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from . import flight
from .history import HistoryStore
from nice_tpu.utils import knobs, lockdep

__all__ = ["SloSpec", "SloEngine", "default_specs", "STATE_LEVELS"]

STATE_LEVELS = {"ok": 0, "warn": 1, "page": 2}


def window_scale() -> float:
    try:
        return max(knobs.SLO_WINDOW_SCALE.get(), 1e-6)
    except (TypeError, ValueError):
        return 1.0


class SloSpec:
    """One objective. ``match`` selects history series by name (prefix plus
    an optional label substring); for ``ratio`` specs ``bad_filter``
    additionally selects the bad subset of the matched series."""

    def __init__(
        self,
        name: str,
        kind: str,  # "quantile" | "ratio"
        series_prefix: str,
        label_filter: str = "",
        bad_filter: Optional[Callable[[str], bool]] = None,
        threshold: float = 0.0,
        objective: float = 0.05,
        short_secs: float = 300.0,
        long_secs: float = 3600.0,
        warn_burn: float = 1.0,
        page_burn: float = 6.0,
        description: str = "",
    ):
        if kind not in ("quantile", "ratio"):
            raise ValueError(f"unknown SLO kind {kind!r}")
        self.name = name
        self.kind = kind
        self.series_prefix = series_prefix
        self.label_filter = label_filter
        self.bad_filter = bad_filter
        env = name.upper()
        self.threshold = knobs.SLO_OVERRIDES.get_float(
            f"NICE_TPU_SLO_{env}_THRESHOLD", threshold
        )
        self.objective = max(
            knobs.SLO_OVERRIDES.get_float(
                f"NICE_TPU_SLO_{env}_OBJECTIVE", objective
            ),
            1e-9,
        )
        self.short_secs = short_secs
        self.long_secs = long_secs
        self.warn_burn = warn_burn
        self.page_burn = page_burn
        self.description = description

    def matches(self, series: str) -> bool:
        return series.startswith(self.series_prefix) and (
            self.label_filter in series
        )

    # -- evaluation --------------------------------------------------------

    def _points(self, store: HistoryStore, since: float):
        out = []
        for name in store.series_names():
            if not self.matches(name):
                continue
            snap = store.query(name, since=since, tiers=("raw",))
            if snap:
                out.append((name, snap.get("raw", [])))
        return out

    def bad_fraction(self, store: HistoryStore, since: float):
        """Fraction of the error budget's denominator that went bad in the
        window, or None when the window holds no data."""
        pts = self._points(store, since)
        if self.kind == "quantile":
            values = [v for _n, raw in pts for _t, v in raw]
            if not values:
                return None
            return sum(1 for v in values if v > self.threshold) / len(values)
        total = bad = 0.0
        for name, raw in pts:
            if len(raw) < 1:
                continue
            # Counters are cumulative: the window's delta is last - first.
            delta = max(0.0, raw[-1][1] - raw[0][1])
            total += delta
            if self.bad_filter is not None and self.bad_filter(name):
                bad += delta
        if total <= 0:
            return None
        return bad / total

    def evaluate(self, store: HistoryStore, now: float) -> dict:
        scale = window_scale()
        short = self.bad_fraction(store, now - self.short_secs * scale)
        long_ = self.bad_fraction(store, now - self.long_secs * scale)
        if short is None:
            short = long_  # sparse data: fall back to the long window
        burn_short = (short / self.objective) if short is not None else None
        burn_long = (long_ / self.objective) if long_ is not None else None
        if burn_short is None or burn_long is None:
            state = "ok"
        elif burn_short >= self.page_burn and burn_long >= self.page_burn:
            state = "page"
        elif burn_short >= self.warn_burn and burn_long >= self.warn_burn:
            state = "warn"
        else:
            state = "ok"
        return {
            "slo": self.name,
            "kind": self.kind,
            "state": state,
            "level": STATE_LEVELS[state],
            "burn_short": burn_short,
            "burn_long": burn_long,
            "threshold": self.threshold,
            "objective": self.objective,
            "no_data": burn_long is None,
            "description": self.description,
        }


def default_specs() -> List[SloSpec]:
    return [
        SloSpec(
            "claim_p99", "quantile",
            series_prefix="nice_api_request_seconds_p99",
            label_filter='endpoint="/claim',
            threshold=0.5, objective=0.10,
            description="claim endpoints p99 <= 500ms for 90% of windows",
        ),
        SloSpec(
            "submit_success", "ratio",
            series_prefix="nice_api_requests_total",
            label_filter='endpoint="/submit',
            bad_filter=lambda s: 'status="5' in s,
            objective=0.01,
            description="submit 5xx ratio <= 1%",
        ),
        SloSpec(
            "feed_idle_p95", "quantile",
            series_prefix="nice_mesh_feed_idle_seconds_p95",
            threshold=0.05, objective=0.25,
            description="host->device feed idle p95 <= 50ms for 75% of "
                        "windows (chips should never starve)",
        ),
        SloSpec(
            "spot_check_fail", "ratio",
            series_prefix="nice_server_spot_checks_total",
            label_filter='verdict="',
            bad_filter=lambda s: 'verdict="fail"' in s,
            objective=0.05,
            description="spot-verification failure ratio <= 5%",
        ),
    ]


def tenant_specs(pairs) -> List[SloSpec]:
    """Per-tenant page-latency SLOs for the multi-tenant scheduler.

    ``pairs`` is an iterable of ``(tenant_name, page_budget_secs)``; tenants
    with a zero/negative budget get no spec. Windows are short (60s/300s)
    because the scheduler feeds one point per page and reacts at page
    granularity — the usual fleet-scale hour window would lag the
    preemption decision it exists to drive. Thresholds and objectives stay
    overridable through the NICE_TPU_SLO_* family like every other spec.
    """
    specs: List[SloSpec] = []
    for name, budget_secs in pairs:
        if budget_secs is None or budget_secs <= 0:
            continue
        specs.append(SloSpec(
            f"tenant_{name}", "quantile",
            series_prefix="nice_sched_page_seconds",
            label_filter=f'tenant="{name}"',
            threshold=float(budget_secs), objective=0.25,
            short_secs=60.0, long_secs=300.0,
            description=f"tenant {name}: page latency <= {budget_secs:g}s "
                        "for 75% of pages",
        ))
    return specs


class SloEngine:
    """Evaluates a spec list against a HistoryStore, tracking state
    transitions. Thread-safe: evaluate() runs on the writer periodic while
    /status reads last()."""

    def __init__(self, store: HistoryStore,
                 specs: Optional[List[SloSpec]] = None):
        self.store = store
        self.specs = specs if specs is not None else default_specs()
        self._lock = lockdep.make_lock("obs.slo.SloEngine._lock")
        self._states: Dict[str, str] = {}
        self._last: List[dict] = []
        self.transitions = 0

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        import time

        now = time.time() if now is None else now
        from .series import SLO_STATE, SLO_TRANSITIONS

        results = []
        for spec in self.specs:
            try:
                res = spec.evaluate(self.store, now)
            except Exception:  # noqa: BLE001 — one bad spec can't take
                continue       # down the writer periodic
            results.append(res)
            SLO_STATE.labels(spec.name).set(res["level"])
            with self._lock:
                prev = self._states.get(spec.name, "ok")
                if res["state"] != prev:
                    self._states[spec.name] = res["state"]
                    self.transitions += 1
                    SLO_TRANSITIONS.labels(spec.name, res["state"]).inc()
                    flight.record(
                        "slo_transition", slo=spec.name,
                        from_state=prev, to_state=res["state"],
                        burn_short=res["burn_short"],
                        burn_long=res["burn_long"],
                    )
                else:
                    self._states[spec.name] = res["state"]
        with self._lock:
            self._last = results
        return results

    def last(self) -> List[dict]:
        with self._lock:
            return list(self._last)
