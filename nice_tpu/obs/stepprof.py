"""Device-step profiler: phase-attributed wall time per engine batch loop.

Buckets each field's wall time into
``{compile, h2d_feed, device_compute, fold, readback, host_other}`` so a
slow field is attributable to a specific phase (the kernel-benchmarking
discipline of "FastKernels" / the TPU blocking analysis in "Large Scale
Distributed Linear Algebra With TPUs" — PAPERS.md).

Design constraints, in order:

1. **Zero hot-path overhead when off.** ``NICE_TPU_STEPPROF=0`` (the
   default) means: no new ``block_until_ready`` fences, no per-batch
   timestamps beyond what the engine already takes, and the per-batch guard
   is a single attribute check (``prof.enabled``). The module-level
   ``fence_count()`` counter proves it — tests assert it stays 0 for a
   disabled run.
2. **Fences only at existing boundaries.** With the profiler on, the one
   new sync is a post-dispatch ``block_until_ready`` that separates
   ``device_compute`` from the host-side loop; ``fold``/``readback`` are
   timed around the collector's *existing* device->host transfers. Under
   the megaloop (NICE_TPU_MEGALOOP) a dispatch IS a whole segment — a
   lax.scan of NICE_TPU_MEGALOOP_SEGMENT batch iterations — so the
   profiler fences once per segment and never per iteration: one
   ``device_compute`` span covers the whole in-program loop, and the
   dispatches-per-slice collapse shows up as fewer, longer spans
   (nice_engine_dispatches_total tracks the count).
   Attribution caveat (documented, accepted): dispatch is async under jit,
   so with the profiler off nothing changes; with it on, the pipeline
   serializes slightly — which is why the gate report A/Bs both settings.
3. **Cross-thread attribution.** The dispatch loop and the collector run in
   different threads; a profiler instance is handed into the collector
   closure explicitly and ``add()`` is lock-guarded. Compile time is
   attributed through a thread-local "current profiler" stack so
   ``ops/compile_cache.py`` can report ``build()`` durations without a
   direct dependency on the engine.

Per-(mode, base, backend) phase totals are emitted into the
``nice_stepprof_phase_seconds`` histogram series on ``finish()``, kept in
``LAST_BREAKDOWN`` (most recent field) and a cumulative table that
``obs/telemetry.py`` folds into ``DataToServer.telemetry`` and ``bench.py``
diffs per mode.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from nice_tpu.utils import knobs, lockdep

__all__ = [
    "PHASES",
    "StepProfiler",
    "enabled",
    "fence_count",
    "note_compile",
    "cumulative",
    "reset",
    "LAST_BREAKDOWN",
]

PHASES = (
    "compile",        # executable build()s (compile_cache misses)
    "h2d_feed",       # waiting on the host->device feed (_SliceFeed.get)
    "device_compute", # dispatch enqueue + on-device execution (fenced)
    "fold",           # device->host accumulator folds (stats transfers)
    "readback",       # scalar/near-miss readbacks + survivor extraction
    "host_other",     # wall - sum(above): host loop, slicing, bookkeeping
)

_state_lock = lockdep.make_lock("obs.stepprof._state_lock")
_fence_count = 0
_cumulative: Dict[str, Dict[str, float]] = {}
LAST_BREAKDOWN: Dict[str, object] = {}

_tls = threading.local()


def enabled() -> bool:
    """Read the knob at call time (not import) so tests/bench can flip it."""
    return knobs.STEPPROF.get_bool()


def fence_count() -> int:
    """Total profiler-inserted device fences this process. Stays 0 whenever
    the profiler is disabled — the no-extra-syncs guarantee, testable."""
    return _fence_count


def reset() -> None:
    """Clear cumulative state (tests / bench A-B runs)."""
    global _fence_count
    with _state_lock:
        _fence_count = 0
        _cumulative.clear()
        LAST_BREAKDOWN.clear()


def cumulative() -> Dict[str, Dict[str, float]]:
    """Copy of {"mode|b<base>|backend": {phase: secs, "wall": secs,
    "fields": n}} accumulated since process start (or reset())."""
    with _state_lock:
        return {k: dict(v) for k, v in _cumulative.items()}


def _current() -> Optional["StepProfiler"]:
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


def note_compile(secs: float) -> None:
    """Called by compile_cache around build(): attribute compile time to the
    dispatch thread's active profiler, if any."""
    prof = _current()
    if prof is not None and prof.enabled:
        prof.add("compile", secs)


class StepProfiler:
    """Per-field phase accumulator. Construct one per engine field pass;
    engine hot loops guard every hook with ``if prof.enabled`` so the
    disabled path costs one attribute load."""

    __slots__ = ("mode", "base", "backend", "enabled", "_buckets", "_lock",
                 "_t_start", "_finished")

    def __init__(self, mode: str, base: int, backend: str,
                 enabled_override: Optional[bool] = None):
        self.mode = mode
        self.base = int(base)
        self.backend = backend
        self.enabled = enabled() if enabled_override is None else bool(
            enabled_override
        )
        self._buckets = {p: 0.0 for p in PHASES} if self.enabled else None
        self._lock = lockdep.make_lock("obs.stepprof.StepProfile._lock") if self.enabled else None
        self._t_start = time.perf_counter() if self.enabled else 0.0
        self._finished = False

    # -- hooks -------------------------------------------------------------

    def add(self, phase: str, secs: float) -> None:
        if not self.enabled or secs <= 0:
            return
        with self._lock:
            self._buckets[phase] += secs

    def fence(self, x) -> None:
        """block_until_ready(x), counted — ONLY when profiling. The disabled
        path returns before touching the device."""
        global _fence_count
        if not self.enabled or x is None:
            return
        t0 = time.perf_counter()
        try:
            import jax

            jax.block_until_ready(x)
        except Exception:  # noqa: BLE001 — non-device values pass through
            pass
        with _state_lock:
            _fence_count += 1
        self.add("device_compute", time.perf_counter() - t0)

    class _Span:
        __slots__ = ("prof", "phase", "t0")

        def __init__(self, prof, phase):
            self.prof = prof
            self.phase = phase

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.prof.add(self.phase, time.perf_counter() - self.t0)

    def measure(self, phase: str):
        """Context manager for non-hot-path phases. Hot loops should take
        explicit timestamps behind ``if prof.enabled`` instead."""
        if not self.enabled:
            return _NULL_SPAN
        return StepProfiler._Span(self, phase)

    def __enter__(self) -> "StepProfiler":
        if self.enabled:
            stack = getattr(_tls, "stack", None)
            if stack is None:
                stack = _tls.stack = []
            stack.append(self)
        return self

    def __exit__(self, *exc) -> None:
        if self.enabled:
            stack = getattr(_tls, "stack", None)
            if stack and stack[-1] is self:
                stack.pop()
            self.finish()

    def start(self) -> "StepProfiler":
        """``__enter__`` alias for flows with multiple exit points (the
        engine loops); pair with ``stop()`` before every return/raise."""
        return self.__enter__()

    def stop(self) -> None:
        """``__exit__`` alias: pop the thread-local stack and finish()."""
        self.__exit__(None, None, None)

    # -- reporting ---------------------------------------------------------

    def breakdown(self) -> Optional[Dict[str, float]]:
        if not self.enabled:
            return None
        with self._lock:
            return dict(self._buckets)

    def finish(self, wall_secs: Optional[float] = None) -> Optional[dict]:
        """Close the field: derive host_other = wall - sum(phases), emit the
        phase histogram series, and fold into the cumulative table."""
        if not self.enabled or self._finished:
            return None
        self._finished = True
        wall = (
            wall_secs if wall_secs is not None
            else time.perf_counter() - self._t_start
        )
        with self._lock:
            b = dict(self._buckets)
        accounted = sum(v for p, v in b.items() if p != "host_other")
        b["host_other"] = max(0.0, wall - accounted)
        from .series import STEPPROF_PHASE_SECONDS

        for phase, secs in b.items():
            if secs > 0:
                STEPPROF_PHASE_SECONDS.labels(
                    self.mode, str(self.base), self.backend, phase
                ).observe(secs)
        key = f"{self.mode}|b{self.base}|{self.backend}"
        entry = dict(b)
        entry["wall"] = wall
        with _state_lock:
            cum = _cumulative.setdefault(
                key, {p: 0.0 for p in PHASES} | {"wall": 0.0, "fields": 0}
            )
            for p in PHASES:
                cum[p] += b[p]
            cum["wall"] += wall
            cum["fields"] += 1
            LAST_BREAKDOWN.clear()
            LAST_BREAKDOWN.update(
                {"key": key, "mode": self.mode, "base": self.base,
                 "backend": self.backend, **entry}
            )
        return entry


class _NullSpan:
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()
