"""Push-based live telemetry stream (Server-Sent Events) — the hub.

``GET /events/stream`` upgrades the pull-only observability surface
(``/events?since=``, adaptive dashboard polling) to push: journal events,
anomaly/SLO state transitions, and critical-path dominant-segment changes
multiplex onto one long-lived SSE response per subscriber. The protocol is
plain SSE so ``EventSource`` in ``web/fleet.html`` consumes it with zero
client dependencies, and the ``id:`` field carries the *journal* global
event id so ``Last-Event-ID`` resume composes with the existing
``/events?since=<id>`` cursor — a reconnecting dashboard replays exactly
the journal rows it missed (including across a server restart, because the
cursor is the durable ``field_events`` rowid) and misses nothing, duplicates
nothing.

Design rules, in order:

1. **Publishers never block.** :meth:`StreamHub.publish` is called from the
   writer thread (post-commit journal flush, history tick transitions) and
   must return immediately: each subscriber owns a bounded deque
   (``NICE_TPU_STREAM_QUEUE``); when it is full the oldest event drops and
   the subscriber's drop counter increments (surfaced to the consumer as a
   ``lagged`` event so it KNOWS it has a gap, and to operators via
   ``nice_stream_dropped_total``). A consumer that keeps lagging past
   ``NICE_TPU_STREAM_MAX_DROPS`` is evicted — slow consumers shed load,
   they don't grow it.
2. **No thread per subscriber.** The hub is sync and loop-agnostic (hence
   unit-testable without asyncio); the async core bridges wakeups onto the
   event loop via each subscriber's waker callback
   (``loop.call_soon_threadsafe``), and the per-connection responder
   coroutine drains the deque and writes frames.
3. **Heartbeats bound silence.** Every ``NICE_TPU_STREAM_HEARTBEAT_SECS``
   without traffic the responder emits a comment-framed heartbeat, so
   proxies don't idle-kill the socket and dead peers are detected within
   one heartbeat interval (the write raises).

Event kinds multiplexed: ``journal`` (one per committed field_event, id =
global journal id), ``slo`` / ``anomaly`` (state transitions from the
history tick), ``critpath`` (bottleneck shifts), ``hello`` (subscription
acknowledged, carries the resume cursor), ``lagged`` (drops happened).
"""

from __future__ import annotations

import asyncio
import json
from collections import deque
from typing import Callable, Optional

from nice_tpu.utils import knobs, lockdep

from .series import (
    STREAM_DROPPED,
    STREAM_EVENTS,
    STREAM_EVICTIONS,
    STREAM_SUBSCRIBERS,
)

__all__ = [
    "StreamEvent",
    "Subscriber",
    "StreamHub",
    "sse_frame",
    "make_sse_responder",
]

# Catch-up replay page size (one /events?since= page per drain round).
REPLAY_PAGE = 500


class StreamEvent:
    """One multiplexed event: kind (SSE event name), JSON-able data, and
    the journal global id when the event IS a journal row (resume cursor)."""

    __slots__ = ("kind", "data", "event_id")

    def __init__(self, kind: str, data: dict, event_id: Optional[int] = None):
        self.kind = kind
        self.data = data
        self.event_id = event_id


def sse_frame(event: StreamEvent) -> bytes:
    """Wire-format one event. ``id:`` only on journal events — SSE clients
    persist the last seen id and send it back as Last-Event-ID, and only
    the journal id is a durable resume cursor."""
    lines = []
    if event.event_id is not None:
        lines.append(f"id: {int(event.event_id)}")
    lines.append(f"event: {event.kind}")
    data = json.dumps(event.data, separators=(",", ":"), sort_keys=True)
    for chunk in data.splitlines() or [""]:
        lines.append(f"data: {chunk}")
    return ("\n".join(lines) + "\n\n").encode("utf-8")


HEARTBEAT_FRAME = b": heartbeat\n\n"


class Subscriber:
    """One consumer's bounded buffer + lag accounting.

    The waker is invoked after the hub releases its lock whenever the
    queue grows, letting the async side schedule a drain
    (``call_soon_threadsafe``) without the hub knowing about event loops.
    """

    __slots__ = ("queue", "dropped", "evicted", "waker", "last_sent_id")

    def __init__(self, maxlen: int, waker: Optional[Callable[[], None]]):
        self.queue: deque[StreamEvent] = deque(maxlen=maxlen)
        self.dropped = 0
        self.evicted = False
        self.waker = waker
        # Highest journal id already delivered to this consumer — set
        # during catch-up replay so live journal events that raced in
        # behind the replayed page are suppressed (no duplicates).
        self.last_sent_id = 0

    def pop_all(self) -> list[StreamEvent]:
        out = []
        while True:
            try:
                out.append(self.queue.popleft())
            except IndexError:
                return out


class StreamHub:
    """Fan-out registry: publish-side is non-blocking, subscriber queues
    are bounded, and all state is behind one lock (publish happens on the
    writer thread; subscribe/unsubscribe on the event loop; tests poke it
    from wherever)."""

    def __init__(self):
        self._lock = lockdep.make_lock("obs.stream.StreamHub._lock")
        self._subs: list[Subscriber] = []

    # -- subscriber lifecycle ---------------------------------------------

    def subscribe(
        self, waker: Optional[Callable[[], None]] = None
    ) -> Optional[Subscriber]:
        """Register a consumer; None when the subscriber cap is reached
        (the endpoint answers 503 — shedding beats collapsing)."""
        cap = int(knobs.STREAM_MAX_SUBSCRIBERS.get())
        maxlen = max(1, int(knobs.STREAM_QUEUE.get()))
        with self._lock:
            if len(self._subs) >= cap:
                return None
            sub = Subscriber(maxlen, waker)
            self._subs.append(sub)
            STREAM_SUBSCRIBERS.set(len(self._subs))
        return sub

    def unsubscribe(self, sub: Subscriber) -> None:
        with self._lock:
            try:
                self._subs.remove(sub)
            except ValueError:
                return
            STREAM_SUBSCRIBERS.set(len(self._subs))

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)

    # -- publish side (any thread, never blocks) ---------------------------

    def publish(
        self, kind: str, data: dict, event_id: Optional[int] = None
    ) -> None:
        """Fan one event out to every live subscriber. Full queue: the
        deque's maxlen discards the oldest event and the drop counter
        records the gap; past NICE_TPU_STREAM_MAX_DROPS the subscriber is
        evicted (marked — its responder notices on next drain)."""
        evt = StreamEvent(kind, data, event_id)
        max_drops = int(knobs.STREAM_MAX_DROPS.get())
        wakers: list[Callable[[], None]] = []
        with self._lock:
            if not self._subs:
                return
            STREAM_EVENTS.labels(kind).inc()
            for sub in self._subs:
                if sub.evicted:
                    continue
                if (
                    event_id is not None
                    and event_id <= sub.last_sent_id
                ):
                    # Journal event already delivered via catch-up replay.
                    continue
                if len(sub.queue) == sub.queue.maxlen:
                    sub.dropped += 1
                    STREAM_DROPPED.inc()
                    if sub.dropped >= max_drops:
                        sub.evicted = True
                        STREAM_EVICTIONS.inc()
                sub.queue.append(evt)
                if sub.waker is not None:
                    wakers.append(sub.waker)
        # Wake outside the lock: wakers hop threads (call_soon_threadsafe)
        # and must not run under the hub lock.
        for wake in wakers:
            try:
                wake()
            except Exception:  # noqa: BLE001 — a dead loop can't block publish
                pass

    def publish_journal_rows(self, rows: list[dict]) -> None:
        """Convenience: one ``journal`` event per enriched journal row
        (rows carry their assigned global id — the post-commit flush path)."""
        for row in rows:
            rid = row.get("id")
            self.publish(
                "journal", row, event_id=int(rid) if rid is not None else None
            )


def make_sse_responder(
    hub: StreamHub,
    replay: Optional[Callable[[int, int], list[dict]]] = None,
    since: int = 0,
):
    """Build the per-connection async responder the server hands to the
    async core's Response.stream.

    Resume protocol: ``since`` is the consumer's last seen journal id
    (``Last-Event-ID`` header, falling back to ``?since=``); ``replay``
    pages the durable journal feed (Db.get_events_since) so the consumer
    first catches up from the table — the same cursor ``/events?since=``
    uses, so resume works across server restarts — then switches to live
    hub delivery. The no-dup/no-miss invariant is enforced twice: the hub
    suppresses journal events already covered by the replay cursor at
    publish time, and the drain loop re-checks each popped journal event
    against ``last_sent_id`` for events that raced in mid-replay.

    Runs on the event loop; all blocking waits are awaits, all writes are
    followed by drain() (peer death surfaces there as ConnectionError,
    handled by the caller)."""

    async def respond(writer) -> None:
        loop = asyncio.get_running_loop()
        wake = asyncio.Event()
        sub = hub.subscribe(
            waker=lambda: loop.call_soon_threadsafe(wake.set)
        )
        if sub is None:  # raced past the cap check at routing time
            return
        reported_drops = 0
        try:
            cursor = max(0, int(since))
            # Phase 1: catch up from the durable journal.
            while replay is not None:
                page = replay(cursor, REPLAY_PAGE)
                for row in page:
                    cursor = max(cursor, int(row["id"]))
                    writer.write(
                        sse_frame(StreamEvent("journal", row, int(row["id"])))
                    )
                # Advance BEFORE draining so live publishes of these very
                # ids are suppressed from here on.
                sub.last_sent_id = max(sub.last_sent_id, cursor)
                await writer.drain()
                if len(page) < REPLAY_PAGE:
                    break
            sub.last_sent_id = max(sub.last_sent_id, cursor)
            writer.write(
                sse_frame(
                    StreamEvent(
                        "hello",
                        {"cursor": cursor,
                         "subscribers": hub.subscriber_count()},
                    )
                )
            )
            await writer.drain()
            # Phase 2: live delivery with heartbeat-bounded silence.
            heartbeat = max(0.1, float(knobs.STREAM_HEARTBEAT_SECS.get()))
            while True:
                try:
                    await asyncio.wait_for(wake.wait(), timeout=heartbeat)
                    wake.clear()
                except asyncio.TimeoutError:
                    writer.write(HEARTBEAT_FRAME)
                    await writer.drain()
                    continue
                wrote = False
                for evt in sub.pop_all():
                    if evt.event_id is not None:
                        if evt.event_id <= sub.last_sent_id:
                            continue  # replay already delivered it
                        sub.last_sent_id = evt.event_id
                    writer.write(sse_frame(evt))
                    wrote = True
                if sub.dropped > reported_drops:
                    writer.write(
                        sse_frame(
                            StreamEvent(
                                "lagged",
                                {"dropped": sub.dropped,
                                 "cursor": sub.last_sent_id,
                                 "evicted": sub.evicted},
                            )
                        )
                    )
                    reported_drops = sub.dropped
                    wrote = True
                if wrote:
                    await writer.drain()
                if sub.evicted:
                    return  # slow consumer: close; it resumes via cursor
        finally:
            hub.unsubscribe(sub)

    return respond
