"""Crash flight recorder: a bounded in-process ring of recent structured
events, dumped atomically to disk when something goes wrong.

Metrics say how often things happen; the flight recorder says what the last
N of them WERE. Production hooks record dispatches-gone-wrong, HTTP retries,
injected faults, checkpoint writes/restores, backend downgrades, and spool
journal/quarantine transitions — cheap enough to leave permanently armed
(one deque append under a lock).

Dump triggers:
  * crash: ``install()`` chains onto ``sys.excepthook``, so any uncaught
    exception leaves a dump next to the wreckage;
  * SIGUSR2: operator-triggered dump of a live, healthy-looking process
    (the "what has it been doing" escape hatch for a wedged client);
  * spool quarantine: a submission the server definitively rejected is
    exactly the moment the preceding event history matters (faults/spool.py
    calls ``dump(reason="quarantine")``);
  * ``GET /debug/flight`` on the local metrics server (obs/serve.py) and on
    the API server reads the live ring without dumping.

Dumps are atomic (tmp + rename) JSON files under ``NICE_TPU_FLIGHT_DIR``
(default: the system temp dir), named ``nice-flight-<pid>-<reason>.json``.
A repeated trigger with the same reason overwrites — the LATEST history
wins, and a crash-looping client cannot fill the disk with dumps.
"""

from __future__ import annotations

import collections
import logging
import os
import signal
import sys
import tempfile
import threading
import time
from typing import Optional


log = logging.getLogger("nice_tpu.obs")

__all__ = ["FlightRecorder", "RECORDER", "record", "snapshot", "dump",
           "install"]

DEFAULT_CAPACITY = 512

from nice_tpu.utils import fsio, knobs, lockdep

from .series import (  # declared centrally (M1)
    FLIGHT_DUMPS,
    FLIGHT_EVENTS,
    FLIGHT_KNOWN_KINDS as _KNOWN_KINDS,
)


class FlightRecorder:
    """Thread-safe bounded ring of {seq, ts, kind, **fields} events."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self.capacity = capacity
        self._events: collections.deque = collections.deque(maxlen=capacity)
        self._lock = lockdep.make_lock("obs.flight.FlightRecorder._lock")
        self._seq = 0

    def record(self, kind: str, **fields) -> None:
        rec = {"seq": 0, "ts": time.time(), "kind": kind}
        rec.update(fields)
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            self._events.append(rec)
        FLIGHT_EVENTS.labels(kind).inc()

    def snapshot(self) -> list[dict]:
        """Copy of the ring, oldest first."""
        with self._lock:
            return list(self._events)

    def total_recorded(self) -> int:
        with self._lock:
            return self._seq

    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> Optional[str]:
        """Atomically write the ring to disk; returns the path (None when the
        write failed — dumping must never take the process down with it)."""
        events = self.snapshot()
        if path is None:
            out_dir = knobs.FLIGHT_DIR.get() or tempfile.gettempdir()
            try:
                os.makedirs(out_dir, exist_ok=True)
            except OSError:
                return None
            path = os.path.join(
                out_dir, f"nice-flight-{os.getpid()}-{reason}.json"
            )
        payload = {
            "dumped_at": time.time(),
            "reason": reason,
            "pid": os.getpid(),
            "argv": sys.argv,
            "total_recorded": self.total_recorded(),
            "capacity": self.capacity,
            "events": events,
        }
        try:
            fsio.atomic_write_json(path, payload, default=repr)
        except OSError as exc:
            log.warning("flight-recorder dump to %s failed: %s", path, exc)
            return None
        FLIGHT_DUMPS.labels(reason).inc()
        log.info("flight recorder dumped %d events to %s (reason=%s)",
                 len(events), path, reason)
        return path


def _capacity() -> int:
    try:
        return max(16, knobs.FLIGHT_EVENTS.get(default=DEFAULT_CAPACITY))
    except ValueError:
        return DEFAULT_CAPACITY


RECORDER = FlightRecorder(_capacity())

record = RECORDER.record
snapshot = RECORDER.snapshot
dump = RECORDER.dump

_installed = False
_install_lock = lockdep.make_lock("obs.flight._install_lock")


def install() -> None:
    """Arm the crash/SIGUSR2 dump triggers (idempotent).

    Chains the previous sys.excepthook; the SIGUSR2 handler is only
    installed from the main thread on platforms that have the signal, and
    never clobbers a non-default handler someone else installed."""
    global _installed
    with _install_lock:
        if _installed:
            return
        _installed = True

    prev_hook = sys.excepthook

    def _crash_hook(exc_type, exc, tb):
        record("crash", error=repr(exc), type=exc_type.__name__)
        RECORDER.dump(reason="crash")
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _crash_hook

    if (
        hasattr(signal, "SIGUSR2")
        and threading.current_thread() is threading.main_thread()
    ):
        try:
            existing = signal.getsignal(signal.SIGUSR2)
            if existing in (signal.SIG_DFL, signal.SIG_IGN, None):
                signal.signal(
                    signal.SIGUSR2,
                    lambda signum, frame: RECORDER.dump(reason="sigusr2"),
                )
        except (OSError, ValueError):
            pass  # e.g. restricted environments; crash hook still armed
