"""Client-side fleet telemetry snapshot.

``snapshot()`` condenses this process's metrics registry into a compact,
JSON-safe dict the server can aggregate: throughput, backend mix, mid-field
downgrades, checkpoint restores, injected faults, and spool depth. It reads
the same counters the local /metrics endpoint renders — no second set of
books — and adds a per-call rate sample (numbers/sec since the previous
snapshot) so the server can sum instantaneous fleet throughput without
differentiating counters itself.

Two transports carry the snapshot (both in client/api_client.py):
piggybacked on every submission under ``DataToServer.telemetry``, and a
lightweight ``POST /telemetry`` heartbeat so idle or long-scanning clients
stay visible. ``client_id`` is stable for the life of the process
(user@host/pid), so the server's ``client_telemetry`` table upserts one row
per running client.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from typing import Optional

from . import journal, series
from nice_tpu.utils import lockdep

__all__ = ["snapshot", "client_id", "SNAPSHOT_VERSION"]

SNAPSHOT_VERSION = 1

_lock = lockdep.make_lock("obs.telemetry._lock")
_prev_numbers = 0.0
_prev_time: Optional[float] = None


def client_id(username: str = "") -> str:
    """Process-stable fleet identity: user@host/pid."""
    host = socket.gethostname() or "unknown-host"
    user = username or os.environ.get("USER", "anonymous")
    return f"{user}@{host}/{os.getpid()}"


def _sum(counter) -> float:
    return sum(counter.values().values())


def snapshot(
    username: str = "",
    backend: str = "",
    spool_depth: int = 0,
    client_version: str = "",
) -> dict:
    """Current registry condensed to the /telemetry wire format."""
    global _prev_numbers, _prev_time
    now = time.time()
    numbers = _sum(series.CLIENT_NUMBERS)
    with _lock:
        if _prev_time is None or now <= _prev_time:
            rate = 0.0
        else:
            rate = max(0.0, (numbers - _prev_numbers) / (now - _prev_time))
        _prev_numbers = numbers
        _prev_time = now
    fields = {
        mode: int(v)
        for (mode,), v in series.CLIENT_FIELDS.values().items()
        if v
    }
    downgrades = {
        f"{frm}->{to}": int(v)
        for (frm, to), v in series.ENGINE_BACKEND_DOWNGRADES.values().items()
        if v
    }
    idle = series.MESH_FEED_IDLE.label_sums()
    mesh = {
        "devices": int(series.MESH_DEVICES.value()),
        "reshards": int(_sum(series.MESH_RESHARDS)),
        "feed_idle_sum": round(sum(s for s, _ in idle.values()), 6),
        "feed_idle_count": int(sum(c for _, c in idle.values())),
    }
    # Device-step profiler phase attribution (NICE_TPU_STEPPROF=1): the
    # cumulative per-(mode|base|backend) bucket table, empty — and omitted
    # from the wire — when the profiler never ran.
    from . import stepprof

    phase_breakdown = {
        key: {k: round(v, 6) if isinstance(v, float) else v
              for k, v in entry.items()}
        for key, entry in stepprof.cumulative().items()
    }
    out = {
        "v": SNAPSHOT_VERSION,
        "client_id": client_id(username),
        "username": username,
        "client_version": client_version,
        "backend": backend,
        "ts": now,
        "numbers": int(numbers),
        "numbers_per_sec": round(rate, 3),
        "fields": fields,
        "downgrades": downgrades,
        "downgrades_total": int(_sum(series.ENGINE_BACKEND_DOWNGRADES)),
        "restores": int(series.CKPT_RESTORES.value()),
        "faults": int(_sum(series.FAULTS_INJECTED)),
        "spool_depth": int(spool_depth),
        "mesh": mesh,
    }
    if phase_breakdown:
        out["phase_breakdown"] = phase_breakdown
    # Resource observatory piggyback: the latest memwatch watermarks and
    # the profiler's per-root sample totals + top-K folded stacks. Both
    # omitted when their subsystem is off (zero samples) so the wire shape
    # is unchanged for fleets running with the knobs at 0.
    from . import memwatch, pyprof

    mem = memwatch.summary()
    if mem:
        out["mem"] = mem
    if pyprof.sample_count() > 0:
        prof = pyprof.snapshot(top_k=0)
        out["pyprof"] = {
            "samples": prof["samples"],
            "roots": {
                root: entry["samples"]
                for root, entry in prof["roots"].items()
            },
            "top": pyprof.top_stacks(),
        }
    # Client-side audit events (ckpt save/resume, downgrade, spool replay)
    # piggyback on the snapshot; the server merges them into the same
    # field_events timeline (obs/journal.py). Omitted when empty to keep
    # the wire size stable.
    events = journal.drain_client_events()
    if events:
        out["events"] = events
    return out
