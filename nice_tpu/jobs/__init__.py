"""Scheduled batch jobs: consensus, analytics downsampling, cache refresh."""
