"""Scheduled batch jobs runner.

Per base (reference jobs/src/main.rs:15-254):
  1. consensus pass — for every field with detailed submissions, group
     identical results, promote the majority group's earliest submission to
     canon, set check_level = group size + 1 (reset to <=1 when no
     submissions remain)
  2. downsampling pass — per-chunk and per-base checked counts / minimum
     check level; distribution + top-10k numbers + niceness mean/stdev only
     when > 20% of the chunk is detailed-checked
  3. refresh leaderboard / search-rate caches
"""

from __future__ import annotations

import argparse
import json
import logging

from nice_tpu.core import consensus, distribution_stats, number_stats
from nice_tpu.core.constants import DOWNSAMPLE_CUTOFF_PERCENT
from nice_tpu.core.types import SubmissionRecord
from nice_tpu.server.db import Db, pad

log = logging.getLogger("nice_tpu.jobs")


def _untrusted_submission_ids(
    db: Db, submissions: list[SubmissionRecord], threshold: float,
    _cache: dict,
) -> frozenset:
    """Submission ids from below-threshold clients (legacy rows with no
    client_token count as trusted — they predate the trust ledger). The
    per-run cache keeps this at one trust read per client, not per field."""
    if threshold <= 0:
        return frozenset()
    out = set()
    for sub in submissions:
        token = sub.client_token
        if token is None:
            continue
        if token not in _cache:
            row = db.get_client_trust(token)
            _cache[token] = bool(
                row and not row["suspect"] and row["trust"] >= threshold
            )
        if not _cache[token]:
            out.add(sub.submission_id)
    return frozenset(out)


def _one_vote_per_tenant(
    submissions: list[SubmissionRecord],
) -> list[SubmissionRecord]:
    """Collapse repeat submissions of identical content from the same named
    tenant to the earliest one, so a standing re-scan tenant (near-miss
    mining resubmits canon fields on every pass) cannot single-handedly
    inflate a field's check level: each (tenant, content) pair casts one
    consensus vote. Untenanted rows pass through untouched, so with no
    tenants in play the consensus input — and output — is byte-identical
    to before."""
    seen: set = set()
    out: list[SubmissionRecord] = []
    for sub in submissions:  # id ASC: the earliest per pair is kept
        if sub.tenant is None or sub.distribution is None:
            out.append(sub)
            continue
        distribution = distribution_stats.shrink_distribution(sub.distribution)
        distribution.sort(key=lambda d: d.num_uniques)
        numbers = number_stats.shrink_numbers(sub.numbers)
        numbers.sort(key=lambda n: n.number)
        key = (sub.tenant, tuple(distribution), tuple(numbers))
        if key in seen:
            continue
        seen.add(key)
        out.append(sub)
    return out


def run_consensus_for_base(db: Db, base: int) -> int:
    """Returns the number of fields whose canon/check_level changed."""
    from nice_tpu.utils import knobs

    changed = 0
    threshold = knobs.TRUST_THRESHOLD.get()
    trust_cache: dict = {}
    for field in db.get_fields_with_detailed_submissions(base):
        submissions = _one_vote_per_tenant(
            db.get_detailed_submissions_by_field(field.field_id)
        )
        untrusted_ids = _untrusted_submission_ids(
            db, submissions, threshold, trust_cache
        )
        canon, check_level = consensus.evaluate_consensus(
            field, submissions, untrusted_ids
        )
        if canon is None:
            if field.canon_submission_id is not None or field.check_level > 1:
                log.warning(
                    "field %d claimed checked (sub %s, CL%d) but has no"
                    " submissions; reset to CL%d",
                    field.field_id,
                    field.canon_submission_id,
                    field.check_level,
                    check_level,
                )
                db.update_field_canon_and_cl(field.field_id, None, check_level)
                changed += 1
        elif (
            field.canon_submission_id != canon.submission_id
            or field.check_level != check_level
        ):
            db.update_field_canon_and_cl(
                field.field_id, canon.submission_id, check_level
            )
            changed += 1
    return changed


def _chunk_stats(db: Db, base: int) -> dict[int, tuple[int, int, int]]:
    """chunk_id -> (minimum_cl, checked_niceonly, checked_detailed):
    niceonly counts fields at CL>=1, detailed at CL>=2 (reference
    db_util/fields.rs:780-802)."""
    stats: dict[int, tuple[int, int, int]] = {}
    for field in db.get_fields_in_base(base):
        if field.chunk_id is None:
            continue
        min_cl, nice, det = stats.get(field.chunk_id, (255, 0, 0))
        min_cl = min(min_cl, field.check_level)
        if field.check_level >= 1:
            nice += field.range_size
        if field.check_level >= 2:
            det += field.range_size
        stats[field.chunk_id] = (min_cl, nice, det)
    return stats


def _canon_submissions(db: Db, base: int) -> list[tuple[SubmissionRecord, int]]:
    """(canon submission, chunk_id) for every field with one."""
    out = []
    for field in db.get_fields_in_base(base):
        if field.canon_submission_id is not None:
            try:
                sub = db.get_submission_by_id(field.canon_submission_id)
            except KeyError:
                continue
            out.append((sub, field.chunk_id))
    return out


def run_downsampling_for_base(db: Db, base: int) -> None:
    stats = _chunk_stats(db, base)
    canon = _canon_submissions(db, base)
    subs_by_chunk: dict[int, list[SubmissionRecord]] = {}
    all_subs: list[SubmissionRecord] = []
    for sub, chunk_id in canon:
        all_subs.append(sub)
        if chunk_id is not None:
            subs_by_chunk.setdefault(chunk_id, []).append(sub)

    base_checked_niceonly = 0
    base_checked_detailed = 0
    base_minimum_cl = 255

    for chunk in db.get_chunks_in_base(base):
        chunk_id = chunk["id"]
        chunk_size = int(chunk["range_size"])
        min_cl, checked_niceonly, checked_detailed = stats.get(chunk_id, (0, 0, 0))
        pct_detailed = checked_detailed / chunk_size if chunk_size else 0.0
        cols = {
            "checked_niceonly": pad(checked_niceonly),
            "checked_detailed": pad(checked_detailed),
            "minimum_cl": min_cl,
        }
        if pct_detailed > DOWNSAMPLE_CUTOFF_PERCENT:
            subs = subs_by_chunk.get(chunk_id, [])
            dist = distribution_stats.downsample_distributions(subs, base)
            numbers = number_stats.downsample_numbers(subs)
            mean, stdev = distribution_stats.mean_stdev_from_distribution(dist)
            cols.update(
                distribution=json.dumps([d.__dict__ for d in dist]),
                numbers=json.dumps(
                    [{**n.__dict__, "number": str(n.number)} for n in numbers]
                ),
                niceness_mean=mean,
                niceness_stdev=stdev,
            )
        else:
            cols.update(
                distribution="[]", numbers="[]",
                niceness_mean=None, niceness_stdev=None,
            )
        db.update_chunk_stats(chunk_id, **cols)
        base_checked_niceonly += checked_niceonly
        base_checked_detailed += checked_detailed
        base_minimum_cl = min(base_minimum_cl, min_cl)

    from nice_tpu.core import base_range

    br = base_range.get_base_range(base)
    base_size = (br[1] - br[0]) if br else 0
    pct_detailed = base_checked_detailed / base_size if base_size else 0.0
    cols = {
        "checked_niceonly": pad(base_checked_niceonly),
        "checked_detailed": pad(base_checked_detailed),
        "minimum_cl": base_minimum_cl,
    }
    if pct_detailed > DOWNSAMPLE_CUTOFF_PERCENT:
        dist = distribution_stats.downsample_distributions(all_subs, base)
        numbers = number_stats.downsample_numbers(all_subs)
        mean, stdev = distribution_stats.mean_stdev_from_distribution(dist)
        cols.update(
            distribution=json.dumps([d.__dict__ for d in dist]),
            numbers=json.dumps(
                [{**n.__dict__, "number": str(n.number)} for n in numbers]
            ),
            niceness_mean=mean,
            niceness_stdev=stdev,
        )
    else:
        cols.update(
            distribution="[]", numbers="[]",
            niceness_mean=None, niceness_stdev=None,
        )
    db.update_base_stats(base, **cols)


def run_all(db: Db) -> None:
    for base in db.get_bases():
        log.info("=== BASE %d CONSENSUS ===", base)
        changed = run_consensus_for_base(db, base)
        log.info("consensus updated %d fields", changed)
        log.info("=== BASE %d DOWNSAMPLING ===", base)
        run_downsampling_for_base(db, base)
    log.info("=== REFRESHING SEARCH CACHES ===")
    db.refresh_search_caches()
    log.info("search caches refreshed")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="nice-tpu-jobs")
    p.add_argument("--db", default="nice.db")
    p.add_argument("--log-level", default="info")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    db = Db(args.db)
    run_all(db)
    db.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
