"""nicelint: project-invariant static analysis for nice_tpu.

Seven AST-based rule families, each enforcing an invariant the codebase
otherwise holds only by convention:

==== =====================================================================
W1   writer-actor discipline — mutating ``server/db.py`` calls outside
     ``server/writer.py`` / sanctioned init paths
L1   event-loop purity — no blocking calls reachable from the async core's
     loop-thread functions
D1   device-sync discipline — ``block_until_ready`` / ``jax.device_get`` /
     ``np.asarray``-on-device-array only at ``# nicelint: fence`` sites in
     the engine/mesh hot paths
M1   metrics discipline — every ``nice_*`` series name used anywhere is
     declared in ``obs/series.py``, with literal (bounded) label sets
K1   knob discipline — every ``NICE_TPU_*`` read goes through
     ``nice_tpu/utils/knobs.py``; generated knob docs must not drift
A1   atomic-write discipline — state files written only via
     ``nice_tpu.utils.fsio``
X1   lock-order — static lock graph from nested ``with`` acquisitions must
     be acyclic; project locks must be built via ``lockdep.make_lock``
==== =====================================================================

Violations are compared against a committed ratchet baseline
(``nice_tpu/analysis/baseline.json``): new violations fail, baselined ones
burn down, stale baseline entries fail ``--strict``. Inline escapes:

* ``# nicelint: allow W1 (reason)`` — suppress a rule on that line
* ``# nicelint: fence`` — sanctioned D1 device-sync fence
* ``# nicelint: loop-thread`` — mark a function as an L1 root

Everything here is stdlib-only (``ast`` + ``tokenize``): the linter must
run in CI images with no third-party packages installed.
"""

from nice_tpu.analysis.core import (  # noqa: F401
    Project,
    SourceFile,
    Violation,
    all_rules,
    load_baseline,
    run_rules,
    save_baseline,
)
