"""schedex: deterministic interleaving explorer for the coordination plane.

Static analysis (racelint, R1-R5) tells us *where* a race could live;
schedex tells us *whether a specific interleaving actually breaks an
invariant*, and — crucially — replays that interleaving byte-for-byte
from its schedule id so a fix can be regression-tested against the
exact window that bit us.

How it works
------------
A :class:`Scheduler` runs a small set of named threads cooperatively:
exactly one managed thread executes at a time, and control transfers
only at *switch points*.  Switch points come from two places:

* instrumented primitives (:class:`Lock`, :class:`Event`,
  :class:`Queue`, :class:`Future`) that scenario code injects into the
  production objects under test — usually via the lockdep factory hook
  (:func:`instrument`), which makes ``lockdep.make_lock`` hand out
  schedex locks for the duration of a scenario's build;
* explicit ``sched.yield_point("label")`` calls in modeled scenarios.

Because preemption can only happen at switch points, a run is fully
determined by its :class:`Policy`:

* ``FIFOPolicy``      — never preempts; the baseline serial schedule.
* ``RandomPolicy(s)`` — seeded ``random.Random(s)`` pick at every
  switch point; the same seed always yields the same trace.
* ``PreemptPolicy(p)``— FIFO except at switch-point indices in ``p``,
  where the scheduler rotates to the next runnable thread.  With the
  baseline run's switch-point count N, :func:`explore` enumerates all
  k<=2 subsets of [0, N) (DPOR-lite, preemption-bounded), capped by
  ``NICE_TPU_SCHEDEX_MAX_SCHEDULES``.

Every schedule has a string id (``fifo``, ``rand:7``, ``pre:3``,
``pre:3,11``); :func:`replay` re-runs one id and must reproduce the
identical trace — that is the regression contract the in-code
``nicelint: allow R5`` comments in server/app.py and ops/engine.py
point at.

The whole module is import-cost only: production code never imports
schedex, and with ``NICE_TPU_SCHEDEX=0`` (the default) no hook is
installed and ``lockdep.make_lock`` returns plain ``threading.Lock``s
(asserted by tests/test_racelint.py and the racecheck_smoke bench
line).
"""

from __future__ import annotations

import contextlib
import dataclasses
import random
import threading
import time

from nice_tpu.utils import knobs, lockdep


class SchedexAborted(BaseException):
    """Raised inside managed threads when a run is torn down.

    Derives from BaseException so scenario code's ``except Exception``
    handlers cannot swallow the teardown.
    """


class DeadlockError(AssertionError):
    """No runnable thread, at least one blocked thread: a real deadlock."""


# ---------------------------------------------------------------------------
# policies


class Policy:
    """Decides which runnable thread runs after each switch point."""

    id: str = "?"

    def pick(self, preferred, runnable, step):
        raise NotImplementedError


class FIFOPolicy(Policy):
    """Run the current thread until it blocks or finishes; never preempt."""

    id = "fifo"

    def pick(self, preferred, runnable, step):
        if preferred in runnable:
            return preferred
        return runnable[0]


class RandomPolicy(Policy):
    """Seeded uniform pick at every switch point — deterministic per seed."""

    def __init__(self, seed: int):
        self.seed = seed
        self.id = f"rand:{seed}"
        self._rng = random.Random(seed)

    def pick(self, preferred, runnable, step):
        return self._rng.choice(runnable)


class PreemptPolicy(Policy):
    """FIFO, except at the given switch-point indices force a rotation.

    ``points`` indexes the global switch-point counter of the run; at
    those steps control rotates to the next runnable thread after the
    preferred one (registration order), which is how a bounded DPOR
    enumeration plants at most k context switches.
    """

    def __init__(self, points):
        self.points = frozenset(points)
        self.id = "pre:" + ",".join(str(p) for p in sorted(self.points))

    def pick(self, preferred, runnable, step):
        if preferred not in runnable:
            return runnable[0]
        if step in self.points and len(runnable) > 1:
            i = runnable.index(preferred)
            return runnable[(i + 1) % len(runnable)]
        return preferred


def policy_for(schedule_id: str) -> Policy:
    """Parse a schedule id back into its policy (the replay entry point)."""
    if schedule_id == "fifo":
        return FIFOPolicy()
    if schedule_id.startswith("rand:"):
        return RandomPolicy(int(schedule_id.split(":", 1)[1]))
    if schedule_id.startswith("pre:"):
        return PreemptPolicy(int(p) for p in schedule_id.split(":", 1)[1].split(","))
    raise ValueError(f"unknown schedule id {schedule_id!r}")


# ---------------------------------------------------------------------------
# scheduler


class Scheduler:
    """Cooperative single-token scheduler over named threads."""

    def __init__(self, policy: Policy):
        self.policy = policy
        self._cv = threading.Condition()
        self._threads: dict[str, dict] = {}
        self._order: list[str] = []
        self._ident: dict[int, str] = {}
        self._current: str | None = None
        self._step = 0
        self.trace: list[tuple[int, str, str]] = []
        self._abort = False
        self.failures: list[tuple[str, BaseException]] = []
        self._started = False

    # -- registration ------------------------------------------------------

    def thread(self, name: str, fn, *args) -> None:
        if self._started:
            raise RuntimeError("cannot add threads after run()")
        if name in self._threads:
            raise ValueError(f"duplicate thread name {name!r}")
        t = threading.Thread(
            target=self._bootstrap, args=(name, fn, args),
            name=f"schedex:{name}", daemon=True,
        )
        self._threads[name] = {"thread": t, "state": "runnable", "pred": None}
        self._order.append(name)

    def _me(self) -> str | None:
        return self._ident.get(threading.get_ident())

    def is_managed(self) -> bool:
        return self._me() is not None

    # -- core scheduling (all under self._cv) ------------------------------

    def _runnable(self) -> list[str]:
        return [n for n in self._order if self._threads[n]["state"] == "runnable"]

    def _reschedule(self, preferred: str | None, step: int) -> None:
        for rec in self._threads.values():
            if rec["state"] == "blocked" and rec["pred"]():
                rec["state"] = "runnable"
                rec["pred"] = None
        runnable = self._runnable()
        if not runnable:
            blocked = [n for n in self._order
                       if self._threads[n]["state"] == "blocked"]
            if blocked:
                self.failures.append(
                    ("<scheduler>", DeadlockError(
                        f"deadlock: all live threads blocked: {blocked}")))
                self._abort = True
            self._current = None
        else:
            if preferred is not None and preferred in runnable:
                self._current = self.policy.pick(preferred, runnable, step)
            else:
                self._current = self.policy.pick(None, runnable, step)
        self._cv.notify_all()

    def switch_point(self, point: str, block_pred=None) -> None:
        """Yield control; optionally block until ``block_pred()`` is true.

        No-op on unmanaged threads so instrumented primitives stay safe
        to touch from the driver thread (e.g. in ``Scenario.check``).
        """
        name = self._me()
        if name is None:
            return
        rec = self._threads[name]
        with self._cv:
            step = self._step
            self._step += 1
            self.trace.append((step, name, point))
            while True:
                if block_pred is not None and not block_pred():
                    rec["state"] = "blocked"
                    rec["pred"] = block_pred
                self._reschedule(name, step)
                while self._current != name and not self._abort:
                    self._cv.wait(0.05)
                if self._abort:
                    raise SchedexAborted()
                rec["state"] = "runnable"
                rec["pred"] = None
                if block_pred is None or block_pred():
                    return

    def yield_point(self, point: str) -> None:
        """A pure preemption opportunity for modeled scenario code."""
        self.switch_point(point)

    # -- thread lifecycle --------------------------------------------------

    def _bootstrap(self, name, fn, args):
        self._ident[threading.get_ident()] = name
        with self._cv:
            while self._current != name and not self._abort:
                self._cv.wait(0.05)
        if self._abort:
            return
        try:
            fn(*args)
        except SchedexAborted:
            pass
        except BaseException as exc:  # scenario invariants raise AssertionError
            with self._cv:
                self.failures.append((name, exc))
        finally:
            with self._cv:
                self._threads[name]["state"] = "done"
                if self._current == name or self._current is None:
                    self._reschedule(None, self._step)
                self._cv.notify_all()

    def run(self, timeout: float | None = None) -> None:
        """Start every registered thread and drive the run to completion."""
        if timeout is None:
            timeout = float(knobs.SCHEDEX_TIMEOUT_SECS.get())
        self._started = True
        for name in self._order:
            self._threads[name]["thread"].start()
        with self._cv:
            self._reschedule(self._order[0] if self._order else None, 0)
        deadline = time.monotonic() + timeout
        for name in self._order:
            self._threads[name]["thread"].join(
                max(0.0, deadline - time.monotonic()))
        alive = [n for n in self._order if self._threads[n]["thread"].is_alive()]
        if alive:
            with self._cv:
                self._abort = True
                self.failures.append(
                    ("<scheduler>", TimeoutError(
                        f"watchdog: threads still alive after {timeout}s: {alive}")))
                self._cv.notify_all()
            for name in alive:
                self._threads[name]["thread"].join(1.0)


# ---------------------------------------------------------------------------
# instrumented primitives
#
# Each wrapper degrades to real-threading behaviour when touched from an
# unmanaged thread, so driver code (scenario build/check on the pytest
# thread) can use the same objects safely.


class Lock:
    """Scheduler-aware (R)Lock; a drop-in for ``lockdep.make_lock`` output."""

    def __init__(self, sched: Scheduler, name: str, reentrant: bool = False):
        self._sched = sched
        self._name = name
        self._re = reentrant
        self._owner: str | None = None
        self._count = 0
        self._fallback = threading.RLock()  # nicelint: allow X1 (scheduler machinery, not a project lock: minting it via make_lock inside the instrument() hook window would recurse)

    def _free_for(self, me: str):
        def pred():
            return self._owner is None or (self._re and self._owner == me)
        return pred

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = self._sched._me()
        if me is None:
            return self._fallback.acquire(blocking, timeout)
        pred = self._free_for(me)
        if not blocking:
            self._sched.switch_point(f"tryacquire:{self._name}")
            if not pred():
                return False
        else:
            self._sched.switch_point(f"acquire:{self._name}", block_pred=pred)
        self._owner = me
        self._count += 1
        return True

    def release(self) -> None:
        me = self._sched._me()
        if me is None:
            self._fallback.release()
            return
        if self._owner != me:
            raise RuntimeError(f"release of {self._name} by non-owner {me}")
        self._count -= 1
        if self._count == 0:
            self._owner = None
        self._sched.switch_point(f"release:{self._name}")

    def locked(self) -> bool:
        return self._owner is not None

    __enter__ = acquire

    def __exit__(self, *exc):
        self.release()


class Event:
    """Scheduler-aware ``threading.Event``."""

    def __init__(self, sched: Scheduler, name: str):
        self._sched = sched
        self._name = name
        self._flag = False
        self._real = threading.Event()

    def is_set(self) -> bool:
        return self._flag

    def set(self) -> None:
        self._flag = True
        self._real.set()
        if self._sched.is_managed():
            self._sched.switch_point(f"event-set:{self._name}")

    def clear(self) -> None:
        self._flag = False
        self._real.clear()

    def wait(self, timeout: float | None = None) -> bool:
        if not self._sched.is_managed():
            return self._real.wait(timeout)
        if timeout is not None:
            # Deterministic model of a timed wait: yield once, then
            # report whatever the flag is — never stall the schedule.
            self._sched.switch_point(f"event-wait:{self._name}")
            return self._flag
        self._sched.switch_point(
            f"event-wait:{self._name}", block_pred=lambda: self._flag)
        return True


class Queue:
    """Scheduler-aware FIFO with ``queue.Queue``'s put/get surface."""

    def __init__(self, sched: Scheduler, name: str, maxsize: int = 0):
        self._sched = sched
        self._name = name
        self._maxsize = maxsize
        self._items: list = []

    def qsize(self) -> int:
        return len(self._items)

    def empty(self) -> bool:
        return not self._items

    def put(self, item, block: bool = True, timeout: float | None = None) -> None:
        if self._sched.is_managed():
            if self._maxsize > 0 and block:
                self._sched.switch_point(
                    f"put:{self._name}",
                    block_pred=lambda: len(self._items) < self._maxsize)
            else:
                self._sched.switch_point(f"put:{self._name}")
        self._items.append(item)

    def put_nowait(self, item) -> None:
        self.put(item, block=False)

    def get(self, block: bool = True, timeout: float | None = None):
        import queue as _q
        if not self._sched.is_managed():
            if not self._items:
                raise _q.Empty
            return self._items.pop(0)
        if block and timeout is None:
            self._sched.switch_point(
                f"get:{self._name}", block_pred=lambda: bool(self._items))
        else:
            # Timed/non-blocking get: one deterministic yield, then Empty
            # if nothing arrived — models the timeout without wall time.
            self._sched.switch_point(f"get:{self._name}")
            if not self._items:
                raise _q.Empty
        return self._items.pop(0)

    def get_nowait(self):
        return self.get(block=False)


class Future:
    """Scheduler-aware ``concurrent.futures.Future`` subset."""

    def __init__(self, sched: Scheduler, name: str):
        self._sched = sched
        self._name = name
        self._done = False
        self._value = None
        self._exc: BaseException | None = None

    def done(self) -> bool:
        return self._done

    def set_result(self, value) -> None:
        self._value = value
        self._done = True
        if self._sched.is_managed():
            self._sched.switch_point(f"future-set:{self._name}")

    def set_exception(self, exc: BaseException) -> None:
        self._exc = exc
        self._done = True
        if self._sched.is_managed():
            self._sched.switch_point(f"future-set:{self._name}")

    def result(self, timeout: float | None = None):
        if self._sched.is_managed():
            self._sched.switch_point(
                f"future-wait:{self._name}", block_pred=lambda: self._done)
        elif not self._done:
            raise TimeoutError(f"future {self._name} not resolved")
        if self._exc is not None:
            raise self._exc
        return self._value


@contextlib.contextmanager
def instrument(sched: Scheduler):
    """Route ``lockdep.make_lock``/``make_rlock`` to schedex locks.

    Scenario ``build`` runs production constructors inside this window
    so the objects under test carry instrumented locks; the hook is
    always restored, keeping the production path zero-cost afterwards.
    """
    prev = lockdep.factory_hook()
    lockdep.set_factory_hook(
        lambda name, kind: Lock(sched, name, reentrant=(kind == "rlock")))
    try:
        yield sched
    finally:
        lockdep.set_factory_hook(prev)


# ---------------------------------------------------------------------------
# exploration


@dataclasses.dataclass
class ScheduleResult:
    schedule_id: str
    ok: bool
    failures: list[str]
    trace: list[tuple[int, str, str]]
    switch_points: int

    def as_dict(self) -> dict:
        return {
            "schedule": self.schedule_id,
            "ok": self.ok,
            "failures": self.failures,
            "switch_points": self.switch_points,
        }


@dataclasses.dataclass
class ExploreReport:
    scenario: str
    schedules_run: int
    failing: list[ScheduleResult]
    baseline_switch_points: int
    truncated: int  # systematic schedules dropped by the cap

    @property
    def ok(self) -> bool:
        return not self.failing

    def first_failing(self) -> ScheduleResult | None:
        return self.failing[0] if self.failing else None

    def as_dict(self) -> dict:
        return {
            "scenario": self.scenario,
            "schedules_run": self.schedules_run,
            "baseline_switch_points": self.baseline_switch_points,
            "truncated": self.truncated,
            "ok": self.ok,
            "failing": [f.as_dict() for f in self.failing],
        }


def run_schedule(scenario_factory, policy: Policy,
                 timeout: float | None = None) -> ScheduleResult:
    """One scenario instance under one policy, with guaranteed cleanup."""
    scenario = scenario_factory()
    sched = Scheduler(policy)
    try:
        for name, fn in scenario.build(sched):
            sched.thread(name, fn)
        sched.run(timeout=timeout)
        failures = [f"{name}: {exc!r}" for name, exc in sched.failures]
        if not failures:
            try:
                scenario.check()
            except AssertionError as exc:
                failures.append(f"invariant: {exc}")
        return ScheduleResult(
            schedule_id=policy.id,
            ok=not failures,
            failures=failures,
            trace=list(sched.trace),
            switch_points=sched._step,
        )
    finally:
        cleanup = getattr(scenario, "cleanup", None)
        if cleanup is not None:
            cleanup()


def replay(scenario_factory, schedule_id: str) -> ScheduleResult:
    """Re-run one schedule byte-for-byte (same id => same trace)."""
    return run_schedule(scenario_factory, policy_for(schedule_id))


def explore(scenario_factory, seeds: int | None = None,
            preemptions: int | None = None,
            max_schedules: int | None = None,
            stop_on_failure: bool = False) -> ExploreReport:
    """Baseline + bounded systematic preemptions + seeded random sweeps."""
    if seeds is None:
        seeds = int(knobs.SCHEDEX_SEEDS.get())
    if preemptions is None:
        preemptions = int(knobs.SCHEDEX_PREEMPTIONS.get())
    if max_schedules is None:
        max_schedules = int(knobs.SCHEDEX_MAX_SCHEDULES.get())

    baseline = run_schedule(scenario_factory, FIFOPolicy())
    results = [baseline]
    n = baseline.switch_points

    combos: list[tuple[int, ...]] = []
    if preemptions >= 1:
        combos.extend((i,) for i in range(n))
    if preemptions >= 2:
        combos.extend((i, j) for i in range(n) for j in range(i + 1, n))
    truncated = 0
    if len(combos) > max_schedules:
        # Stride-sample so coverage stays spread across the run instead
        # of clustering at the first switch points.
        stride = -(-len(combos) // max_schedules)
        kept = combos[::stride]
        truncated = len(combos) - len(kept)
        combos = kept

    failing = [] if baseline.ok else [baseline]
    for combo in combos:
        if stop_on_failure and failing:
            break
        res = run_schedule(scenario_factory, PreemptPolicy(combo))
        results.append(res)
        if not res.ok:
            failing.append(res)
    for seed in range(seeds):
        if stop_on_failure and failing:
            break
        res = run_schedule(scenario_factory, RandomPolicy(seed))
        results.append(res)
        if not res.ok:
            failing.append(res)

    name = getattr(scenario_factory, "scenario_name", None) or getattr(
        scenario_factory, "__name__", str(scenario_factory))
    return ExploreReport(
        scenario=name,
        schedules_run=len(results),
        failing=failing,
        baseline_switch_points=n,
        truncated=truncated,
    )
