"""KernelSpec contract registry — jaxlint's ground truth for ops/ kernels.

Every public batch entry point in ``ops/vector_engine.py`` and
``ops/pallas_engine.py`` declares here, as functions of (plan, batch):

* abstract input shapes/dtypes (what the tracer feeds ``jax.make_jaxpr``),
* expected output shapes/dtypes (rule J6 checks the traced ``out_avals``
  against these across the base sweep),
* donated argument indices (rule J3 checks ``donated_invars`` on the
  traced plan),
* the set of element-type casts the kernel is allowed to contain
  (rule J1 flags any ``convert_element_type`` outside it),
* value bounds on carried state (rule J2 seeds its interval analysis
  from these; the bound IS the contract — e.g. the histogram accumulator
  stays below ``HIST_ACC_BOUND`` because the engine flushes it first),
* the bounded domain of every static argument (rule J5's recompile
  surface), and
* applicability predicates (which bases a kernel supports), including the
  pallas histogram-row cap: lifting ``_HIST_ROWS_MAX`` in the engine
  without updating ``MAX_HIST_ROWS`` here breaks a lint, not a fleet.

The registry is declarative and import-cheap; tracing happens in
``analysis/jaxrules/tracer.py``.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Tuple

U32_FULL = (0, 2**32 - 1)
I32_FULL = (-(2**31), 2**31 - 1)

# Device-resident i32 histogram accumulators are flushed by the engine loop
# long before bins approach i32 saturation (process_range_detailed sizes
# flush_every so total counted lanes stay under 2**30), so traced plans may
# assume this bound on carried accumulator state. J2 proves "no i32 wrap"
# ON TOP of this bound; widening it past 2**30 makes the per-batch
# ``hist_acc + hist`` add unprovable and J2 will say so.
HIST_ACC_BOUND = (0, 1 << 30)

# One batch's stats tile (the pallas kernels' carried out-ref state, and the
# per-dispatch histogram the accum plans add into the accumulator): at most
# 2**17 lanes per dispatch, each contributing < 2**9 digit events, so 2**26
# bounds every bin with room to spare. J2 seeds pallas output refs with this
# and proves HIST_ACC_BOUND + PER_BATCH_HIST_BOUND fits i32.
PER_BATCH_HIST_BOUND = (0, 1 << 26)

# Pallas stats-tile histogram row cap: must equal pallas_engine._HIST_ROWS_MAX
# (J6 cross-checks both directions over a probe sweep). Bases with
# ceil((base+2)/128) rows above this cap fall back to the jnp backend.
# 16 rows admits bases up to 2046 (the old 4-row cap pinned the sweep at
# 510); the stats tile stays a bounded trace-time constant either way.
MAX_HIST_ROWS = 16

# Casts the limb/stats kernels are allowed to contain (J1). Everything else —
# in particular any float dtype and any widening past 32 bits — is a finding.
CASTS_DEFAULT = frozenset({
    ("bool", "uint32"),    # ve._carry: wrap flag -> u32 carry
    ("bool", "int32"),     # histogram/mask one-hot counts
    ("uint32", "int32"),   # popcount accumulators -> i32 stats domain
    ("int32", "uint32"),   # lane iota -> u32 candidate offset
})

# Survivor-compaction capacity used for traces (a representative static).
TRACE_SURVIVOR_CAP = 256

# jax.jit surfaces in ops/ that are allowed to exist: the decorated
# vector-engine entry points plus the pallas callable factories (each factory
# jits one inner ``run``). Rule J5 flags any other jit site in ops/ — a new
# jitted kernel must be declared (and usually spec'd) before it ships.
KNOWN_JIT_SURFACES = frozenset({
    # vector_engine decorated entry points
    "detailed_batch", "uniques_batch", "survivors_batch",
    "detailed_accum_batch", "niceonly_dense_batch",
    "niceonly_filtered_batch",
    # vector_engine megaloop entry points (lax.scan over the batch kernels)
    "detailed_accum_megaloop", "niceonly_dense_megaloop",
    "niceonly_filtered_megaloop",
    # pallas_engine callable factories (lru-cached, jit inside)
    "_stats_callable", "_uniques_callable", "_survivors_callable",
    "_detailed_accum_callable", "_detailed_megaloop_callable",
    "_strided_callable",
})

# Donation provenance for rule J3's read-after-donate scan: local names bound
# from these factories are callables whose Nth positional argument is donated.
DONATING_FACTORIES: Dict[str, int] = {
    "_detailed_accum_callable": 0,      # pallas_engine factory
    "_detailed_accum_executable": 0,    # engine AOT wrapper
    "make_sharded_stats_accum_step": 0, # parallel/mesh factory
    "_build_stats_accum_step": 0,
    # megaloop twins (PR 17): same donated-accumulator position
    "_detailed_megaloop_callable": 0,
    "_detailed_megaloop_executable": 0,
    "make_sharded_megaloop_accum_step": 0,
    "_build_megaloop_accum_step": 0,
}
# Directly-called donating entry points: callee name -> donated positional
# argument index at the call site.
DONATING_CALLS: Dict[str, int] = {
    "detailed_accum_batch": 2,          # (plan, batch_size, hist_acc, ...)
    "detailed_accum_megaloop": 3,       # (plan, batch_size, n_iters, acc, ..)
}

# Files rule J6 scans for public ``*_batch`` ops that must carry a spec.
DISCOVERY_MODULES = (
    "nice_tpu/ops/vector_engine.py",
    "nice_tpu/ops/pallas_engine.py",
)


@dataclasses.dataclass(frozen=True)
class TraceTarget:
    """One traceable plan: a flat-positional callable over abstract args."""
    fn: Callable
    args: tuple                          # jax.ShapeDtypeStruct per flat arg
    arg_bounds: Dict[int, Tuple[int, int]]  # flat arg index -> value bound
    donate: Tuple[int, ...] = ()         # flat arg indices expected donated
    ref_bound: Optional[Tuple[int, int]] = None  # pallas out-ref state bound
    # Declared i32 dot_general accumulator bound (the MXU limb-multiply
    # contraction): J2's interval interpreter intersects this with its naive
    # per-element bound, so headroom is discharged by a stated theorem about
    # the digit split (ops/mxu.accum_bound), not a baseline allow.
    dot_bound: Optional[Tuple[int, int]] = None
    # Declared bounds on lax.scan/while carried state, as ((flat_carry_index,
    # (lo, hi)), ...): J2 seeds the loop-body carry invars from these instead
    # of topping the whole loop out. Like HIST_ACC_BOUND, each bound IS a
    # contract the engine upholds (e.g. the megaloop's remaining-lanes
    # countdown starts from a valid_total the dispatch loop caps, and the
    # carried histogram stays under the flush budget). Undeclared carry
    # slots seed at dtype top.
    carry_bounds: Tuple[Tuple[int, Tuple[int, int]], ...] = ()


@dataclasses.dataclass(frozen=True)
class KernelSpec:
    name: str                      # "vector_engine.detailed_accum_batch"
    module: str                    # repo-relative source path
    backend: str                   # "jnp" | "pallas"
    kind: str                      # stats|accum|uniques|survivors|niceonly|strided|limbmath
    sweep: str                     # "full": every sweep base; "small": cheap bases only
    build: Callable                # (plan, batch, carry_interval) -> TraceTarget
    out_shapes: Callable           # (plan, batch) -> ((shape, dtype name), ...)
    static_domain: Tuple[Tuple[str, str], ...] = ()
    allowed_casts: frozenset = CASTS_DEFAULT
    applies: Callable = lambda plan: True  # noqa: E731
    takes_carry_interval: bool = True
    max_hist_rows: Optional[int] = None
    max_const_elems: int = 1 << 16
    # Optional limbmath cadence override: (plan) -> cadence tuple. None =
    # the full carry_cadences sweep. The MXU arm trims to the endpoint
    # cadences — its new proof surface (the dot_general accumulator) is
    # cadence-independent, and the shared carry-save resolve is already
    # swept at every cadence through the VPU arm's specs.
    cadences: Optional[Callable] = None

    @property
    def func(self) -> str:
        return self.name.split(".", 1)[1]


SPECS: Dict[str, KernelSpec] = {}


def register(spec: KernelSpec) -> KernelSpec:
    assert spec.name not in SPECS, spec.name
    SPECS[spec.name] = spec
    return spec


def all_specs() -> Dict[str, KernelSpec]:
    return dict(SPECS)


def carry_cadences(plan) -> Tuple[int, ...]:
    """The carry_interval sweep J2 must cover: 0 (resolve once), 1 (resolve
    every term), and the max useful cadence (one full fold per limb pass)."""
    return tuple(sorted({0, 1, plan.limbs_n}))


# -- shared shape builders ---------------------------------------------------

def _sds(shape, dtype):
    import jax
    import jax.numpy as jnp
    return jax.ShapeDtypeStruct(shape, getattr(jnp, dtype))


def _ve_range_args(plan):
    """(start limb scalars u32 * limbs_n, valid_count i32) — the dense-range
    argument tail shared by every vector_engine batch entry point."""
    return tuple(_sds((), "uint32") for _ in range(plan.limbs_n)) + \
        (_sds((), "int32"),)


def _pe_range_args(plan):
    """(start limbs u32[limbs_n], valid_count i32 scalar) — the pallas twins
    take the start as one scalar-prefetched array."""
    return (_sds((plan.limbs_n,), "uint32"), _sds((), "int32"))


def _hist_rows(plan) -> int:
    return -(-(plan.base + 2) // 128)


_STATIC_RANGE = (
    ("base", "plan registry; bases with a valid range (<= 2046 under the "
     "16-row pallas histogram cap)"),
    ("batch_size", "autotune sweep powers of two, <= 2**26"),
    ("carry_interval", "0..limbs_n (autotuned cadence)"),
)
_STATIC_PALLAS = _STATIC_RANGE + (
    ("block_rows", "divisors of batch_size/128, <= 128"),
)


# -- vector_engine (jnp backend) specs ---------------------------------------

def _ve_spec(func, kind, out_shapes, build, sweep="full", **kw):
    return register(KernelSpec(
        name=f"vector_engine.{func}",
        module="nice_tpu/ops/vector_engine.py",
        backend="jnp", kind=kind, sweep=sweep,
        build=build, out_shapes=out_shapes,
        static_domain=kw.pop("static_domain", _STATIC_RANGE), **kw,
    ))


def _build_ve_detailed(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve
    L = plan.limbs_n

    def fn(*a):
        return ve.detailed_batch(plan, batch, list(a[:L]), a[L],
                                 carry_interval=ci)
    return TraceTarget(fn, _ve_range_args(plan), {L: (0, batch)})


_ve_spec(
    "detailed_batch", "stats",
    lambda plan, batch: (((plan.base + 2,), "int32"), ((), "int32")),
    _build_ve_detailed,
)


def _build_ve_uniques(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve
    L = plan.limbs_n

    def fn(*a):
        return ve.uniques_batch(plan, batch, list(a[:L]), carry_interval=ci)
    return TraceTarget(fn, _ve_range_args(plan)[:-1], {})


_ve_spec(
    "uniques_batch", "uniques",
    lambda plan, batch: (((batch,), "int32"),),
    _build_ve_uniques, sweep="small",
)


def _build_ve_survivors(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve
    L = plan.limbs_n
    cap = TRACE_SURVIVOR_CAP

    def fn(*a):
        return ve.survivors_batch(plan, batch, plan.near_miss_cutoff, cap,
                                  list(a[:L]), a[L], carry_interval=ci)
    return TraceTarget(fn, _ve_range_args(plan), {L: (0, batch)})


_ve_spec(
    "survivors_batch", "survivors",
    lambda plan, batch: (((), "int32"),
                         ((TRACE_SURVIVOR_CAP,), "int32"),
                         ((TRACE_SURVIVOR_CAP,), "int32")),
    _build_ve_survivors, sweep="small",
    static_domain=_STATIC_RANGE + (
        ("thresh", "near_miss_cutoff (detailed) or base-1 (niceonly)"),
        ("cap", "survivor capacity; powers of two <= 2**16"),
    ),
)


def _build_ve_accum(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve
    L = plan.limbs_n

    def fn(acc, *a):
        return ve.detailed_accum_batch(plan, batch, acc, list(a[:L]), a[L],
                                       carry_interval=ci)
    args = (_sds((plan.base + 2,), "int32"),) + _ve_range_args(plan)
    return TraceTarget(fn, args, {0: HIST_ACC_BOUND, L + 1: (0, batch)},
                       donate=(0,))


_ve_spec(
    "detailed_accum_batch", "accum",
    lambda plan, batch: (((plan.base + 2,), "int32"), ((), "int32")),
    _build_ve_accum,
)


def _build_ve_niceonly(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve
    L = plan.limbs_n

    def fn(*a):
        return ve.niceonly_dense_batch(plan, batch, list(a[:L]), a[L],
                                       carry_interval=ci)
    return TraceTarget(fn, _ve_range_args(plan), {L: (0, batch)})


_ve_spec(
    "niceonly_dense_batch", "niceonly",
    lambda plan, batch: (((), "int32"),),
    _build_ve_niceonly,
)


# Fused residue-filter niceonly: congruence mask -> prefix-scatter
# compaction -> limb math on survivors only. Returns (nice, pruned).
def _build_ve_filtered(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve
    L = plan.limbs_n

    def fn(*a):
        return ve.niceonly_filtered_batch(plan, batch, list(a[:L]), a[L],
                                          carry_interval=ci)
    return TraceTarget(fn, _ve_range_args(plan), {L: (0, batch)})


_ve_spec(
    "niceonly_filtered_batch", "niceonly",
    lambda plan, batch: (((), "int32"), ((), "int32")),
    _build_ve_filtered,
)


# -- megaloop specs (PR 17) --------------------------------------------------
# Whole-segment lax.scan plans: the batch kernels above run inside a scan
# whose carry is (cursor u32[limbs_n], remaining-lanes countdown, the
# folded accumulators). J2 discharges the loop-carry headroom from the
# declared carry_bounds: the countdown starts at a dispatch-capped
# valid_total (so `rem - min(rem, batch)` cannot wrap), and the carried
# histogram/counters stay under the engine's flush budget — the same
# contract HIST_ACC_BOUND states for the per-batch accumulator. Traced at
# a fixed 2-iteration segment; the carry algebra is independent of the
# segment length (J5 tracks `segment` as a bounded static instead).
_TRACE_SEG = 2

# Remaining-lanes countdown: non-negative by the dispatch-loop contract
# (valid_total <= batch * segment <= the flush budget), which is exactly
# what makes the in-loop `rem - valid` subtraction provably wrap-free.
_REM_BOUND = (0, 2**31 - 1)
_COUNT_ACC_BOUND = (0, 1 << 30)

_STATIC_MEGALOOP = (
    ("segment", "megaloop iterations fused per dispatch; env/autotuned, "
     "clamped to the i32 histogram flush budget"),
)


def _build_ve_mega_accum(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve

    def fn(acc, start, valid_total):
        return ve.detailed_accum_megaloop(
            plan, batch, _TRACE_SEG, acc, start, valid_total,
            carry_interval=ci,
        )
    args = ((_sds((plan.base + 2,), "int32"),) +
            (_sds((plan.limbs_n,), "uint32"), _sds((), "int32")))
    return TraceTarget(
        fn, args, {0: HIST_ACC_BOUND, 2: (0, batch * _TRACE_SEG)},
        donate=(0,),
        # scan carry: (cursor, rem, hist acc, near-miss acc)
        carry_bounds=((1, _REM_BOUND), (2, HIST_ACC_BOUND),
                      (3, _COUNT_ACC_BOUND)),
    )


_ve_spec(
    "detailed_accum_megaloop", "accum",
    lambda plan, batch: (((plan.base + 2,), "int32"), ((), "int32")),
    _build_ve_mega_accum, sweep="small",
    static_domain=_STATIC_RANGE + _STATIC_MEGALOOP,
)


def _build_ve_mega_niceonly(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve

    def fn(start, valid_total):
        return ve.niceonly_dense_megaloop(
            plan, batch, _TRACE_SEG, start, valid_total, carry_interval=ci,
        )
    args = (_sds((plan.limbs_n,), "uint32"), _sds((), "int32"))
    return TraceTarget(
        fn, args, {1: (0, batch * _TRACE_SEG)},
        # scan carry: (cursor, rem, count)
        carry_bounds=((1, _REM_BOUND), (2, _COUNT_ACC_BOUND)),
    )


_ve_spec(
    "niceonly_dense_megaloop", "niceonly",
    lambda plan, batch: (((), "int32"),),
    _build_ve_mega_niceonly, sweep="small",
    static_domain=_STATIC_RANGE + _STATIC_MEGALOOP,
)


def _build_ve_mega_filtered(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve

    def fn(start, valid_total):
        return ve.niceonly_filtered_megaloop(
            plan, batch, _TRACE_SEG, start, valid_total, carry_interval=ci,
        )
    args = (_sds((plan.limbs_n,), "uint32"), _sds((), "int32"))
    return TraceTarget(
        fn, args, {1: (0, batch * _TRACE_SEG)},
        # scan carry: (cursor, rem, count, pruned)
        carry_bounds=((1, _REM_BOUND), (2, _COUNT_ACC_BOUND),
                      (3, _COUNT_ACC_BOUND)),
    )


_ve_spec(
    "niceonly_filtered_megaloop", "niceonly",
    lambda plan, batch: (((), "int32"), ((), "int32")),
    _build_ve_mega_filtered, sweep="small",
    static_domain=_STATIC_RANGE + _STATIC_MEGALOOP,
)


# Limb-math core traced without jit: sqr + mul + digit extraction exactly as
# num_uniques_lanes composes them. This is the J2 carry-headroom proof
# surface — swept over carry_interval {0, 1, max} per base.
def _build_ve_limbmath(plan, batch, ci):
    from nice_tpu.ops import vector_engine as ve

    def fn(*limbs):
        return ve.num_uniques_lanes(plan, list(limbs), ci)
    args = tuple(_sds((batch,), "uint32") for _ in range(plan.limbs_n))
    return TraceTarget(fn, args, {})


_ve_spec(
    "num_uniques_lanes", "limbmath",
    lambda plan, batch: (((batch,), "int32"),),
    _build_ve_limbmath,
)


# MXU arm of the limb-math core: the same sqr + mul + digit-extraction
# composition routed through the banded Toeplitz dot_general (ops/mxu.py).
# The TraceTarget declares the contraction's accumulator bound
# (mxu.accum_bound — a theorem about the 8x16-bit digit split), which J2
# intersects with its naive interval so MXU headroom is proved, not allowed.
def _mxu_supports(plan) -> bool:
    from nice_tpu.ops import mxu
    return mxu.supports_plan(plan)


def _build_ve_limbmath_mxu(plan, batch, ci):
    from nice_tpu.ops import mxu, vector_engine as ve

    def fn(*limbs):
        return ve.num_uniques_lanes(plan, list(limbs), ci, use_mxu=True)
    args = tuple(_sds((batch,), "uint32") for _ in range(plan.limbs_n))
    return TraceTarget(fn, args, {},
                       dot_bound=(0, mxu.accum_bound(plan.limbs_n)))


_ve_spec(
    "num_uniques_lanes_mxu", "limbmath",
    lambda plan, batch: (((batch,), "int32"),),
    _build_ve_limbmath_mxu,
    applies=_mxu_supports,
    cadences=lambda plan: tuple(sorted({0, plan.limbs_n})),
    static_domain=_STATIC_RANGE + (
        ("use_mxu", "boolean engine arm (env NICE_TPU_MXU > autotuned)"),
    ),
)


# -- pallas_engine specs -----------------------------------------------------

def _pe_spec(func, kind, out_shapes, build, sweep="full", **kw):
    kw.setdefault("max_hist_rows", MAX_HIST_ROWS)
    kw.setdefault("applies", _pe_supports)
    return register(KernelSpec(
        name=f"pallas_engine.{func}",
        module="nice_tpu/ops/pallas_engine.py",
        backend="pallas", kind=kind, sweep=sweep,
        build=build, out_shapes=out_shapes,
        static_domain=kw.pop("static_domain", _STATIC_PALLAS), **kw,
    ))


def _pe_supports(plan) -> bool:
    return _hist_rows(plan) <= MAX_HIST_ROWS


def _build_pe_detailed(plan, batch, ci):
    from nice_tpu.ops import pallas_engine as pe

    def fn(start, valid):
        return pe.detailed_batch(plan, batch, start, valid,
                                 carry_interval=ci)
    return TraceTarget(fn, _pe_range_args(plan), {1: (0, batch)},
                       ref_bound=PER_BATCH_HIST_BOUND)


_pe_spec(
    "detailed_batch", "stats",
    lambda plan, batch: (((128 * _hist_rows(plan),), "int32"), ((), "int32")),
    _build_pe_detailed,
)


def _build_pe_niceonly(plan, batch, ci):
    from nice_tpu.ops import pallas_engine as pe

    def fn(start, valid):
        return pe.niceonly_dense_batch(plan, batch, start, valid,
                                       carry_interval=ci)
    return TraceTarget(fn, _pe_range_args(plan), {1: (0, batch)},
                       ref_bound=PER_BATCH_HIST_BOUND)


_pe_spec(
    "niceonly_dense_batch", "niceonly",
    lambda plan, batch: (((), "int32"),),
    _build_pe_niceonly,
)


# Fused-filter pallas twin: the residue congruence mask is evaluated inside
# the stats kernel (SIMD masking, no compaction) so pruned lanes never feed
# the nice count; returns (nice, pruned) tallies from the stats tile.
def _build_pe_fused(plan, batch, ci):
    from nice_tpu.ops import pallas_engine as pe

    def fn(start, valid):
        return pe.niceonly_fused_batch(plan, batch, start, valid,
                                       carry_interval=ci)
    return TraceTarget(fn, _pe_range_args(plan), {1: (0, batch)},
                       ref_bound=PER_BATCH_HIST_BOUND)


_pe_spec(
    "niceonly_fused_batch", "niceonly",
    lambda plan, batch: (((), "int32"), ((), "int32")),
    _build_pe_fused,
)


def _build_pe_uniques(plan, batch, ci):
    from nice_tpu.ops import pallas_engine as pe

    def fn(start):
        return pe.uniques_batch(plan, batch, start)
    return TraceTarget(fn, _pe_range_args(plan)[:1], {},
                       ref_bound=PER_BATCH_HIST_BOUND)


_pe_spec(
    "uniques_batch", "uniques",
    lambda plan, batch: (((batch,), "int32"),),
    _build_pe_uniques, sweep="small", takes_carry_interval=False,
)


def _build_pe_survivors(plan, batch, ci):
    from nice_tpu.ops import pallas_engine as pe
    cap = TRACE_SURVIVOR_CAP

    def fn(start, valid):
        return pe.survivors_batch(plan, batch, plan.near_miss_cutoff, cap,
                                  start, valid)
    return TraceTarget(fn, _pe_range_args(plan), {1: (0, batch)},
                       ref_bound=PER_BATCH_HIST_BOUND)


_pe_spec(
    "survivors_batch", "survivors",
    lambda plan, batch: (((), "int32"),
                         ((TRACE_SURVIVOR_CAP,), "int32"),
                         ((TRACE_SURVIVOR_CAP,), "int32")),
    _build_pe_survivors, sweep="small", takes_carry_interval=False,
    static_domain=_STATIC_PALLAS + (
        ("thresh", "near_miss_cutoff (detailed) or base-1 (niceonly)"),
        ("cap", "survivor capacity; powers of two <= 2**16"),
    ),
)


def _build_pe_accum(plan, batch, ci):
    from nice_tpu.ops import pallas_engine as pe

    def fn(acc, start, valid):
        return pe.detailed_accum_batch(plan, batch, acc, start, valid,
                                       carry_interval=ci)
    args = (_sds((plan.base + 2,), "int32"),) + _pe_range_args(plan)
    return TraceTarget(fn, args, {0: HIST_ACC_BOUND, 2: (0, batch)},
                       donate=(0,), ref_bound=PER_BATCH_HIST_BOUND)


_pe_spec(
    "detailed_accum_batch", "accum",
    lambda plan, batch: (((plan.base + 2,), "int32"), ((), "int32")),
    _build_pe_accum,
)


# Pallas megaloop (PR 17): the lax.scan wraps the pallas stats kernel —
# same carry contract as the jnp twin, with the per-iteration stats tile
# still bounded by ref_bound.
def _build_pe_mega_accum(plan, batch, ci):
    from nice_tpu.ops import pallas_engine as pe

    def fn(acc, start, valid_total):
        return pe.detailed_accum_megaloop(
            plan, batch, 2, acc, start, valid_total, carry_interval=ci,
        )
    args = (_sds((plan.base + 2,), "int32"),) + _pe_range_args(plan)
    return TraceTarget(
        fn, args, {0: HIST_ACC_BOUND, 2: (0, batch * 2)},
        donate=(0,), ref_bound=PER_BATCH_HIST_BOUND,
        # scan carry: (cursor, rem, hist acc, near-miss acc)
        carry_bounds=((1, (0, 2**31 - 1)), (2, HIST_ACC_BOUND),
                      (3, (0, 1 << 30))),
    )


_pe_spec(
    "detailed_accum_megaloop", "accum",
    lambda plan, batch: (((plan.base + 2,), "int32"), ((), "int32")),
    _build_pe_mega_accum, sweep="small",
    static_domain=_STATIC_PALLAS + (
        ("segment", "megaloop iterations fused per dispatch; env/autotuned, "
         "clamped to the i32 histogram flush budget"),
    ),
)


# Stride-compacted niceonly: the offsets table is a deliberate large VMEM
# constant (host-expanded CRT residue table), so this spec raises the
# burned-constant ceiling J5 applies to it. Traced with a tiny 1-residue
# table; shape contracts do not depend on the table contents.
_STRIDED_TRACE_DESC = 128
_STRIDED_TRACE_PERIODS = 128


def _build_pe_strided(plan, batch, ci):
    from nice_tpu.ops import pallas_engine as pe
    spec = pe.StrideSpec(2, (1,))

    def fn(desc):
        return pe.niceonly_strided_batch(
            plan, spec, desc, periods=_STRIDED_TRACE_PERIODS)
    args = (_sds((_STRIDED_TRACE_DESC, pe._DESC_WIDTH), "uint32"),)
    return TraceTarget(fn, args, {}, ref_bound=PER_BATCH_HIST_BOUND)


_pe_spec(
    "niceonly_strided_batch", "strided",
    lambda plan, batch: (((8, 128), "int32"),),
    _build_pe_strided, sweep="small", takes_carry_interval=False,
    applies=lambda plan: plan.limbs_n <= 4 and _pe_supports(plan),
    max_const_elems=1 << 21,
    static_domain=(
        ("base", "plan registry; strided kernel asserts limbs_n <= 4"),
        ("stride spec", "CRT modulus + residue table per (base, depth)"),
        ("num_desc", "descriptor-group sizes, <= STRIDED_DESC_MAX=1024"),
        ("periods", "stride periods, <= STRIDED_PERIODS_MAX=1024"),
    ),
)
