"""R1: shared-attribute mutation across thread roots + the coverage gate.

Two checks:

1. **Coverage gate** — every ``Thread(`` / ``ThreadPoolExecutor(`` /
   ``ThreadingHTTPServer(`` construction in ``nice_tpu/`` and ``scripts/``
   must match a ThreadRegistry entry by (file, enclosing scope, kind);
   a registered root whose spawn site no longer exists is stale. The
   registry only stays the ground truth if drifting from it is a finding.

2. **Multi-root unguarded mutation** — an attribute or module global
   written by functions reachable from ≥2 registered roots, where the
   write sites share NO common lock label and the object carries no
   ownership declaration in ``threadspec.SHARED_STATE``. Declared objects
   are R2's job (checked against their declaration); undeclared
   multi-root state is exactly what a future shard refactor trips over.
"""

from __future__ import annotations

from typing import List

from nice_tpu.analysis import threadspec
from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.racerules import rrule


@rrule("R1")
def check(project: Project, ctx) -> List[Violation]:
    out: List[Violation] = []

    # 1a. unregistered spawn sites
    registered = threadspec.roots_by_site()
    for site in ctx.spawn_sites:
        if (site.path, site.scope, site.kind) not in registered:
            out.append(Violation(
                "R1", site.path, site.line,
                f"unregistered {site.kind} spawn ({site.call}) in "
                f"{site.scope} — declare a ThreadRoot in "
                "analysis/threadspec.py so racelint knows its role, locks "
                "and blocking budget",
                detail=f"unregistered-{site.kind}:{site.scope}",
            ))

    # 1b. stale registry entries
    seen = {(s.path, s.scope, s.kind) for s in ctx.spawn_sites}
    for root in threadspec.THREAD_ROOTS:
        if root.kind == "loop":
            # loop roots take over the calling thread; their anchor is the
            # scope function itself, not a spawn call
            if (root.path, root.spawn_scope) not in ctx.functions:
                out.append(Violation(
                    "R1", root.path, 1,
                    f"stale loop root {root.name!r}: no function "
                    f"{root.spawn_scope} in {root.path}",
                    detail=f"stale-root:{root.name}",
                ))
            continue
        if (root.path, root.spawn_scope, root.kind) not in seen:
            out.append(Violation(
                "R1", root.path, 1,
                f"stale ThreadRoot {root.name!r}: no {root.kind} spawn in "
                f"{root.spawn_scope} — update analysis/threadspec.py",
                detail=f"stale-root:{root.name}",
            ))

    # 2. multi-root unguarded writes of undeclared state
    for (path, scope, attr), sites in sorted(ctx.writes.items()):
        if attr.startswith("__"):
            continue
        if threadspec.shared_state_for(path, scope, attr) is not None:
            continue  # declared: R2 verifies the declaration instead
        roots = set()
        for site in sites:
            roots |= ctx.roots_reaching((site.path, site.func))
        if len(roots) < 2:
            continue
        common = None
        for site in sites:
            common = site.held if common is None else (common & site.held)
        if common:
            continue
        first = min(sites, key=lambda s: s.line)
        out.append(Violation(
            "R1", path, first.line,
            f"{scope}.{attr} mutated from {len(roots)} thread roots "
            f"({', '.join(sorted(roots))}) with no common lock and no "
            "SHARED_STATE declaration — declare ownership in "
            "analysis/threadspec.py or guard every write",
            detail=f"shared:{scope}.{attr}",
        ))
    return out
