"""R5: check-then-act atomicity on shared dicts and caches.

The repo's canonical lost-update shape: read a cache under its lock,
release the lock to do expensive work (build a fleet block, construct a
mesh), re-acquire and store — an invalidation landing in the unlocked
window is silently overwritten (the status-cache / ``_cached_mesh`` /
trust ``peek_known`` pattern). Statically:

* within one function, a read of object ``V`` (``V.get(...)``, ``V[k]``
  load, ``k in V``) inside a ``with <lock>`` span, followed by a write of
  the same ``V`` inside a LATER span of the SAME lock, with at least one
  unlocked line between the spans, is a finding. ``V.setdefault(...)``
  in the second span is the sanctioned atomic re-validation idiom and
  exempt; a generation-checked store is sanctioned via an inline
  ``# nicelint: allow R5 (...)`` whose honesty the schedex regression
  scenarios enforce dynamically.
* any ``functools.lru_cache`` function whose ``cache_clear()`` is called
  at runtime (outside tests) is flagged: the clear/rebuild window of an
  lru cache cannot be guarded at all — hold an explicit dict + lock +
  generation instead (what ops/engine's mesh cache does now).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.racerules import rrule
from nice_tpu.analysis.racerules.context import MUTATOR_METHODS

ANALYSIS_PREFIX = "nice_tpu/analysis/"

WRITE_METHODS = MUTATOR_METHODS - {"setdefault"}


def _accesses(fn: ast.AST) -> List[Tuple[int, str, str]]:
    """(line, 'read'|'write', dotted-object) container accesses in fn."""
    out: List[Tuple[int, str, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                obj = astutil.dotted(f.value)
                if not obj:
                    continue
                if f.attr == "get":
                    out.append((node.lineno, "read", obj))
                elif f.attr in WRITE_METHODS:
                    out.append((node.lineno, "write", obj))
        elif isinstance(node, ast.Subscript):
            obj = astutil.dotted(node.value)
            if not obj:
                continue
            kind = "read" if isinstance(node.ctx, ast.Load) else "write"
            out.append((node.lineno, kind, obj))
        elif isinstance(node, ast.Compare):
            if any(isinstance(op, (ast.In, ast.NotIn)) for op in node.ops):
                for cmp_ in node.comparators:
                    obj = astutil.dotted(cmp_)
                    if obj:
                        out.append((node.lineno, "read", obj))
    return out


def _check_then_act(ctx, path: str, qn: str,
                    fn: ast.AST) -> List[Violation]:
    spans = sorted(ctx.held_spans.get((path, qn), ()),
                   key=lambda s: s[0])
    if len(spans) < 2:
        return []
    accesses = _accesses(fn)
    out: List[Violation] = []
    seen: Set[str] = set()
    for i, (a0, a1, la) in enumerate(spans):
        for (b0, b1, lb) in spans[i + 1:]:
            if la != lb or b0 <= a1:
                continue
            if b0 - a1 < 2:
                continue  # no statement in between: no unlocked window
            reads = {obj for (ln, kind, obj) in accesses
                     if kind == "read" and a0 <= ln <= a1}
            writes = {(ln, obj) for (ln, kind, obj) in accesses
                      if kind == "write" and b0 <= ln <= b1}
            for ln, obj in sorted(writes):
                if obj in reads and obj not in seen:
                    seen.add(obj)
                    out.append(Violation(
                        "R5", path, ln,
                        f"check-then-act on {obj}: read under {la} at "
                        f"line {a0}, stored back under the same lock "
                        f"after an unlocked window — an invalidation in "
                        "the window is lost (use setdefault or a "
                        "generation-checked store + schedex scenario)",
                        detail=f"check-then-act:"
                               f"{qn.rsplit('.', 1)[-1]}:{obj}",
                    ))
    return out


@rrule("R5")
def check(project: Project, ctx) -> List[Violation]:
    out: List[Violation] = []

    # 1. locked read -> unlocked window -> locked write, per function
    for (path, qn), fn in sorted(ctx.functions.items()):
        if not path.startswith("nice_tpu/") or \
                path.startswith(ANALYSIS_PREFIX):
            continue
        out.extend(_check_then_act(ctx, path, qn, fn))

    # 2. lru_cache with a runtime cache_clear
    lru_fns: Dict[str, Tuple[str, int]] = {}
    for src in project.python_files("nice_tpu/"):
        if src.relpath.startswith(ANALYSIS_PREFIX):
            continue
        tree = src.tree()
        if tree is None:
            continue
        for qn, fn in astutil.iter_functions(tree):
            for deco in getattr(fn, "decorator_list", []):
                name = astutil.call_name(deco) if \
                    isinstance(deco, ast.Call) else astutil.dotted(deco)
                if name and name.rsplit(".", 1)[-1] == "lru_cache":
                    lru_fns[qn.rsplit(".", 1)[-1]] = (src.relpath,
                                                      fn.lineno)
    if lru_fns:
        for src in project.python_files("nice_tpu/"):
            if src.relpath.startswith(ANALYSIS_PREFIX):
                continue
            tree = src.tree()
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                name = astutil.call_name(node)
                if not name or not name.endswith(".cache_clear"):
                    continue
                target = name.rsplit(".", 2)[-2]
                if target in lru_fns:
                    dpath, dline = lru_fns.pop(target)
                    out.append(Violation(
                        "R5", dpath, dline,
                        f"lru_cache on {target}() is cache_clear()ed at "
                        f"{src.relpath}:{node.lineno} — the clear/rebuild "
                        "window cannot be guarded; use an explicit dict "
                        "with a lock and a generation counter",
                        detail=f"lru-clear:{target}",
                    ))
    return out
