"""R4: writer-actor discipline — "accepted ⇒ durable", statically.

The writer actor's contract (server/writer.py): a submission's Future
resolves ONLY after the batch transaction that made it durable has
committed, and only the writer thread resolves futures. Three checks:

* ``Future.set_result`` / ``set_exception`` appear nowhere in
  ``nice_tpu/`` outside the writer module (schedex's instrumented
  futures in ``analysis/`` are exempt machinery);
* inside ``server/writer.py`` itself, no future is resolved lexically
  inside a ``_txn()`` with-span — resolving before commit would
  acknowledge a write that can still roll back;
* a mutating ``Db`` method (W1's discovery: ``self._txn`` closure) is
  never called from a function reachable from a NON-writer thread root
  outside the sanctioned modules — W1 polices the call-site grammar in
  ``server/``; this closes the cross-root reachability angle everywhere.
"""

from __future__ import annotations

import ast
from typing import List

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.racerules import rrule
from nice_tpu.analysis.rules.w1_writer import mutating_db_methods

WRITER_PATH = "nice_tpu/server/writer.py"
DB_PATH = "nice_tpu/server/db.py"
ANALYSIS_PREFIX = "nice_tpu/analysis/"
RESOLVE_CALLS = ("set_result", "set_exception")


def _txn_spans(tree: ast.AST) -> List[tuple]:
    spans = []
    for node in ast.walk(tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            expr = item.context_expr
            name = astutil.call_name(expr) if isinstance(expr, ast.Call) \
                else astutil.dotted(expr)
            if name and name.rsplit(".", 1)[-1] == "_txn":
                spans.append((node.lineno,
                              getattr(node, "end_lineno", node.lineno)))
    return spans


@rrule("R4")
def check(project: Project, ctx) -> List[Violation]:
    out: List[Violation] = []
    mutating = mutating_db_methods(project)

    for src in project.python_files("nice_tpu/"):
        if src.relpath.startswith(ANALYSIS_PREFIX):
            continue
        tree = src.tree()
        if tree is None:
            continue
        enclosing = astutil.enclosing_function_map(tree)
        txn_spans = _txn_spans(tree) if src.relpath == WRITER_PATH else []
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            if not name or "." not in name:
                continue
            method = name.rsplit(".", 1)[-1]
            line = node.lineno
            fn = enclosing.get(line, "<module>")

            if method in RESOLVE_CALLS:
                if src.relpath != WRITER_PATH:
                    out.append(Violation(
                        "R4", src.relpath, line,
                        f"{name}() outside the writer module — only the "
                        "writer actor resolves futures (accepted ⇒ "
                        "durable)",
                        detail=f"resolve-outside-writer:{fn}",
                    ))
                elif any(a <= line <= b for a, b in txn_spans):
                    out.append(Violation(
                        "R4", src.relpath, line,
                        f"{name}() inside the batch _txn() span — a "
                        "future must resolve only after commit, or an "
                        "acknowledged write can roll back",
                        detail=f"resolve-inside-txn:{fn}",
                    ))
                continue

            # cross-root ledger mutation
            if method in mutating and src.relpath not in (WRITER_PATH,
                                                          DB_PATH):
                obj = name.rpartition(".")[0]
                if not (obj == "db" or obj.endswith(".db")):
                    continue
                roots = ctx.roots_reaching((src.relpath,
                                            enclosing.get(line, "")))
                foreign = roots - {"db-writer"}
                if foreign:
                    out.append(Violation(
                        "R4", src.relpath, line,
                        f"mutating Db call {name}() reachable from "
                        f"non-writer roots ({', '.join(sorted(foreign))})"
                        " — route through the writer actor",
                        detail=f"ledger-foreign:{fn}->{method}",
                    ))
    return out
