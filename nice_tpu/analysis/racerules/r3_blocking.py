"""R3: blocking calls where blocking is forbidden.

Two obligations from the ThreadRegistry:

* a root declared ``may_block=False`` (the selector event loop) must not
  reach blocking operations through the CROSS-module call graph — L1
  already walks same-module reachability inside ``server/``, so this rule
  only reports sites outside ``server/`` to stay additive, not
  duplicative;
* a blocking call must not happen while lexically holding a lock whose
  LockSpec says ``may_block_under=False`` — holding the status-cache lock
  across sqlite or an HTTP wait stalls every reader, which is exactly the
  class of bug the writer-actor architecture exists to prevent. Locks
  that SERIALIZE a blocking resource (the db lock, the native build lock)
  are declared ``may_block_under=True`` and exempt.

Blocking tables extend nicelint L1's: sqlite/file/socket/subprocess plus
``queue.get`` / ``Event.wait`` / ``Thread.join`` without a timeout and
HTTP response waits.
"""

from __future__ import annotations

import ast
from typing import List, Tuple

from nice_tpu.analysis import astutil, threadspec
from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.racerules import rrule
from nice_tpu.analysis.rules.l1_loop_purity import (
    BLOCKING_EXACT, BLOCKING_SUFFIXES,
)

EXTRA_SUFFIXES = {
    ".getresponse": "HTTP response wait",
    ".urlopen": "HTTP request wait",
}
# .get / .wait / .join block only without a timeout; receivers are
# filtered to queue/event/thread-ish names to avoid dict.get noise.
TIMEOUT_WAITS = {
    ".get": ("_q", "queue"),
    ".wait": ("event", "_stop", "_wake", "_refill", "cv", "cond"),
    ".join": ("thread", "_thread", "_t"),
}

ANALYSIS_PREFIX = "nice_tpu/analysis/"
SERVER_PREFIX = "nice_tpu/server/"


def _has_timeout(node: ast.Call) -> bool:
    if any(kw.arg == "timeout" for kw in node.keywords):
        return True
    return len(node.args) >= 2 or (
        len(node.args) == 1 and not isinstance(node.args[0], ast.Constant))


def _blocking_calls(fn: ast.AST) -> List[Tuple[int, str, str]]:
    found: List[Tuple[int, str, str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if not name:
            continue
        if name in BLOCKING_EXACT:
            found.append((node.lineno, name, BLOCKING_EXACT[name]))
            continue
        matched = False
        for suffix, why in {**BLOCKING_SUFFIXES, **EXTRA_SUFFIXES}.items():
            if name.endswith(suffix) and name != "self" + suffix:
                found.append((node.lineno, name, why))
                matched = True
                break
        if matched:
            continue
        for suffix, recv_hints in TIMEOUT_WAITS.items():
            if not name.endswith(suffix) or "." not in name:
                continue
            recv = name.rsplit(".", 1)[0].lower()
            if any(h in recv for h in recv_hints) and not \
                    _has_timeout(node):
                found.append((node.lineno, name,
                              f"{suffix[1:]}() without timeout"))
            break
    return found


@rrule("R3")
def check(project: Project, ctx) -> List[Violation]:
    out: List[Violation] = []

    no_block_roots = [r for r in threadspec.THREAD_ROOTS
                      if not r.may_block]

    for (path, qn), fn in sorted(ctx.functions.items()):
        if not path.startswith("nice_tpu/") or \
                path.startswith(ANALYSIS_PREFIX):
            continue
        calls = _blocking_calls(fn)
        if not calls:
            continue
        key = (path, qn)
        roots_here = ctx.roots_reaching(key)

        # (a) reachable from a may_block=False root, outside L1's beat
        for root in no_block_roots:
            if root.name not in roots_here:
                continue
            if path.startswith(SERVER_PREFIX):
                continue  # L1 owns same-plane server/ reachability
            for line, callee, why in calls:
                out.append(Violation(
                    "R3", path, line,
                    f"{callee}() reachable from no-block root "
                    f"{root.name} via {qn}: {why}",
                    detail=f"noblock:{root.name}:{qn.rsplit('.', 1)[-1]}"
                           f"->{callee}",
                ))

        # (b) blocking while holding a may_block_under=False lock
        for line, callee, why in calls:
            for label in sorted(ctx.held_at(key, line)):
                spec = threadspec.lock_spec(label)
                if spec is None or spec.may_block_under:
                    continue
                out.append(Violation(
                    "R3", path, line,
                    f"{callee}() while holding {label} "
                    f"(may_block_under=False): {why} — release the lock "
                    "or declare the lock as serializing a blocking "
                    "resource",
                    detail=f"block-under:{label}:{callee}",
                ))
    return out
