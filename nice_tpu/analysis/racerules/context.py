"""Shared analysis context for the R-rules.

Builds, once per racelint run:

* the spawn-site scan (every ``Thread(`` / ``ThreadPoolExecutor(`` /
  ``ThreadingHTTPServer(`` construction with its enclosing scope) that the
  R1 coverage gate matches against the ThreadRegistry;
* a cross-module call graph rooted at each registered thread entry, with
  scope-correct resolution of ``self.method``, same-module names,
  ``from``-imports and class constructors, plus dispatch-aware edges
  (``writer.call(fn)`` runs ``fn`` on the db-writer root,
  ``run_in_executor(fn)`` on the worker pool, ``pool.submit(fn)`` on the
  pool whose spawn scope encloses the submit);
* per-function attribute/global write sites with the set of lock labels
  lexically held at each site (resolved through nicelint X1's lock maps);
* the static X1 acquisition graph and the runtime lockdep order graph
  loaded from ``docs/lockorder.json`` (R2's cross-check input).

Everything is plain AST work — no imports of project modules, so racelint
stays runnable on a box with no accelerator and no server deps.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
from typing import Dict, List, Optional, Set, Tuple

from nice_tpu.analysis import astutil, core, threadspec
from nice_tpu.analysis.rules import x1_lock_order as x1

# The analyzer's own machinery (schedex spawns scheduler threads) is not
# part of the coordination plane the registry audits.
GATE_EXEMPT_PREFIXES = ("nice_tpu/analysis/", "tests/")

# Receivers whose .call/.submit/.add_periodic arguments execute on the
# writer actor thread.
WRITER_RECV_HINTS = ("writer", "actor")
WRITER_DISPATCH_SUFFIXES = (".call", ".submit", ".add_periodic")

MUTATOR_METHODS = {
    "append", "appendleft", "add", "update", "pop", "popleft", "popitem",
    "clear", "remove", "discard", "extend", "insert", "setdefault",
}
# setdefault is a mutator for R1/R2 ownership purposes but is the SAFE
# re-validation idiom for R5 (atomic under the lock).

FuncKey = Tuple[str, str]  # (relpath, qualname)


@dataclasses.dataclass(frozen=True)
class SpawnSite:
    path: str
    line: int
    scope: str
    kind: str           # thread | pool | http-server
    call: str


@dataclasses.dataclass(frozen=True)
class WriteSite:
    path: str
    func: str           # qualname of the writing function
    line: int
    held: frozenset     # lock labels lexically held at the write
    via: str            # source text of the written expression


class RaceContext:
    def __init__(self, root: str):
        self.root = root
        self.spawn_sites: List[SpawnSite] = []
        self.functions: Dict[FuncKey, ast.AST] = {}
        self.edges: Dict[FuncKey, Set[FuncKey]] = {}
        self.root_reach: Dict[str, Set[FuncKey]] = {}
        # (path, scope, attr) -> write sites; scope is a class name or
        # "<module>"
        self.writes: Dict[Tuple[str, str, str], List[WriteSite]] = {}
        self.held_spans: Dict[FuncKey, List[Tuple[int, int, str]]] = {}
        self.lock_labels: Dict[str, Tuple[str, int]] = {}
        self.static_edges: Dict[str, Set[str]] = {}
        self.runtime_edges: Dict[str, Set[str]] = {}
        self.runtime_graph_path: Optional[str] = None
        self.report: Dict[str, object] = {}

    # -- queries -----------------------------------------------------------
    def roots_reaching(self, key: FuncKey) -> Set[str]:
        return {name for name, reach in self.root_reach.items()
                if key in reach}

    def held_at(self, key: FuncKey, line: int) -> Set[str]:
        return {label for (a, b, label) in self.held_spans.get(key, ())
                if a <= line <= b}


# ---------------------------------------------------------------- builders


def _module_path(project: core.Project, dotted_mod: str) -> Optional[str]:
    """'nice_tpu.server.db' -> 'nice_tpu/server/db.py' when it exists."""
    rel = dotted_mod.replace(".", "/") + ".py"
    if project.get(rel) is not None:
        return rel
    rel_init = dotted_mod.replace(".", "/") + "/__init__.py"
    if project.get(rel_init) is not None:
        return rel_init
    return None


def _import_maps(project: core.Project, tree: ast.AST):
    """(module alias -> relpath, imported symbol -> relpath)."""
    mod_alias: Dict[str, str] = {}
    sym_from: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                path = _module_path(project, alias.name)
                if path:
                    mod_alias[alias.asname or alias.name.split(".")[-1]] = \
                        path
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            base = node.module
            for alias in node.names:
                sub = _module_path(project, f"{base}.{alias.name}")
                if sub:
                    mod_alias[alias.asname or alias.name] = sub
                else:
                    path = _module_path(project, base)
                    if path:
                        sym_from[alias.asname or alias.name] = path
    return mod_alias, sym_from


def _collect_functions(ctx: RaceContext, project: core.Project) -> None:
    for src in project.python_files():
        tree = src.tree()
        if tree is None:
            continue
        for qn, fn in astutil.iter_functions(tree):
            ctx.functions[(src.relpath, qn)] = fn


def _short_index(ctx: RaceContext) -> Dict[str, Dict[str, List[str]]]:
    """path -> short name -> qualnames in that file."""
    idx: Dict[str, Dict[str, List[str]]] = {}
    for (path, qn) in ctx.functions:
        idx.setdefault(path, {}).setdefault(
            qn.rsplit(".", 1)[-1], []).append(qn)
    return idx


def _resolve_callee(ctx, project, path, caller_qn, name,
                    mod_alias, sym_from, classes,
                    short_idx) -> Optional[FuncKey]:
    """Best-effort static resolution of a call target to a FuncKey."""
    if name.startswith("self."):
        method = name.split(".", 1)[1].split(".", 1)[0]
        cls = caller_qn.split(".", 1)[0]
        key = (path, f"{cls}.{method}")
        if key in ctx.functions:
            return key
        return None
    if "." not in name:
        if name in classes:
            key = (path, f"{name}.__init__")
            return key if key in ctx.functions else None
        if (path, name) in ctx.functions:
            return (path, name)
        if name in sym_from:
            tgt = (sym_from[name], name)
            if tgt in ctx.functions:
                return tgt
        # unique nested/short match inside the same file
        cands = short_idx.get(path, {}).get(name, [])
        if len(cands) == 1:
            return (path, cands[0])
        return None
    head, rest = name.split(".", 1)
    if head in mod_alias and "." not in rest:
        tgt = (mod_alias[head], rest)
        if tgt in ctx.functions:
            return tgt
    return None


def _callable_args(node: ast.Call) -> List[ast.AST]:
    """Arguments (incl. keyword values like ``target=``) that plausibly
    name a callable handed somewhere else to run."""
    out = list(node.args)
    out.extend(kw.value for kw in node.keywords if kw.arg)
    return out


def _build_call_graph(ctx: RaceContext, project: core.Project) -> None:
    short_idx = _short_index(ctx)
    dispatch: Dict[str, Set[FuncKey]] = {}   # root name -> extra entries
    pool_scopes = {
        (r.path, r.spawn_scope): r.name
        for r in threadspec.THREAD_ROOTS if r.kind == "pool"
    }

    for src in project.python_files():
        tree = src.tree()
        if tree is None:
            continue
        mod_alias, sym_from = _import_maps(project, tree)
        classes = {n.name for n in ast.walk(tree)
                   if isinstance(n, ast.ClassDef)}
        for qn, fn in astutil.iter_functions(tree):
            caller = (src.relpath, qn)
            targets = ctx.edges.setdefault(caller, set())
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = astutil.call_name(node)
                if not name:
                    continue
                resolved = _resolve_callee(
                    ctx, project, src.relpath, qn, name,
                    mod_alias, sym_from, classes, short_idx)
                if resolved:
                    targets.add(resolved)
                # dispatch-aware edges: callables handed to another root
                # execute THERE, not here.
                route = None
                recv = name.rsplit(".", 1)[0].lower() if "." in name else ""
                if name.endswith(WRITER_DISPATCH_SUFFIXES) and any(
                        h in recv for h in WRITER_RECV_HINTS):
                    route = "db-writer"
                elif name.endswith(".run_in_executor"):
                    route = "async-workers"
                elif name.endswith(".submit"):
                    route = pool_scopes.get((src.relpath, qn))
                if route is None:
                    continue
                for arg in _callable_args(node):
                    aname = astutil.dotted(arg)
                    cand = None
                    if aname:
                        cand = _resolve_callee(
                            ctx, project, src.relpath, qn, aname,
                            mod_alias, sym_from, classes, short_idx)
                    elif isinstance(arg, ast.Lambda):
                        for sub in ast.walk(arg):
                            if isinstance(sub, ast.Call):
                                sname = astutil.call_name(sub)
                                if sname:
                                    t = _resolve_callee(
                                        ctx, project, src.relpath, qn,
                                        sname, mod_alias, sym_from,
                                        classes, short_idx)
                                    if t:
                                        dispatch.setdefault(
                                            route, set()).add(t)
                        continue
                    if cand:
                        dispatch.setdefault(route, set()).add(cand)
    ctx.report["dispatch_entries"] = {
        k: sorted(f"{p}:{q}" for p, q in v) for k, v in dispatch.items()}
    _build_reach(ctx, project, dispatch)


def _build_reach(ctx: RaceContext, project: core.Project,
                 dispatch: Dict[str, Set[FuncKey]]) -> None:
    loop_entries: Set[FuncKey] = set()
    for src in project.python_files("nice_tpu/server/"):
        tree = src.tree()
        if tree is None:
            continue
        marks = src.loop_thread_lines()
        for qn, fn in astutil.iter_functions(tree):
            if isinstance(fn, ast.AsyncFunctionDef):
                loop_entries.add((src.relpath, qn))
            elif any(ln in marks for ln in (fn.lineno, fn.lineno - 1)):
                loop_entries.add((src.relpath, qn))

    for root in threadspec.THREAD_ROOTS:
        entries: Set[FuncKey] = {
            (root.path, e) for e in root.entries
            if (root.path, e) in ctx.functions
        }
        entries |= dispatch.get(root.name, set())
        if root.kind == "loop":
            entries |= loop_entries
        reach: Set[FuncKey] = set()
        frontier = list(entries)
        while frontier:
            key = frontier.pop()
            if key in reach:
                continue
            reach.add(key)
            for callee in ctx.edges.get(key, ()):
                if callee not in reach:
                    frontier.append(callee)
        ctx.root_reach[root.name] = reach
    ctx.report["root_reach_sizes"] = {
        name: len(reach) for name, reach in sorted(ctx.root_reach.items())}


# ------------------------------------------------------- writes and locks


def _held_spans_for(fn: ast.AST, table, attr_labels
                    ) -> List[Tuple[int, int, str]]:
    spans: List[Tuple[int, int, str]] = []

    def walk(body):
        for stmt in body:
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    expr = astutil.dotted(item.context_expr)
                    label = x1._resolve(expr, table, attr_labels) \
                        if expr else None
                    if label:
                        spans.append(
                            (stmt.lineno,
                             getattr(stmt, "end_lineno", stmt.lineno),
                             label))
                walk(stmt.body)
            elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                continue  # nested defs run later, not under these holds
            else:
                for block in x1._stmt_bodies(stmt):
                    walk(block)

    walk(getattr(fn, "body", []))
    return spans


def _module_globals(tree: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in tree.body:
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    out.add(tgt.id)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            if isinstance(node.target, ast.Name):
                out.add(node.target.id)
    return out


def _write_targets(node: ast.AST, globals_: Set[str]
                   ) -> List[Tuple[str, str, str]]:
    """(scope-kind, attr-or-name, via) writes performed by one statement
    or call node. scope-kind is 'self' or 'global'."""
    out: List[Tuple[str, str, str]] = []

    def attr_of(value: ast.AST) -> Optional[Tuple[str, str, str]]:
        d = astutil.dotted(value)
        if not d:
            return None
        if d.startswith("self.") and d.count(".") == 1:
            return ("self", d.split(".", 1)[1], d)
        if "." not in d and d in globals_:
            return ("global", d, d)
        return None

    if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for tgt in targets:
            if isinstance(tgt, ast.Subscript):
                hit = attr_of(tgt.value)
                if hit:
                    out.append(hit)
            elif isinstance(tgt, (ast.Attribute, ast.Name)):
                hit = attr_of(tgt)
                if hit:
                    out.append(hit)
    elif isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATOR_METHODS:
            hit = attr_of(fn.value)
            if hit:
                out.append(hit)
    return out


def _collect_writes(ctx: RaceContext, project: core.Project) -> None:
    per_module, attr_labels = x1._collect_lock_maps(project)
    for src in project.python_files("nice_tpu/"):
        tree = src.tree()
        if tree is None:
            continue
        table = per_module.get(src.relpath, {})
        globals_ = _module_globals(tree)
        classes = {n.name for n in ast.walk(tree)
                   if isinstance(n, ast.ClassDef)}
        for qn, fn in astutil.iter_functions(tree):
            key = (src.relpath, qn)
            ctx.held_spans[key] = _held_spans_for(fn, table, attr_labels)
            short = qn.rsplit(".", 1)[-1]
            if short in ("__init__", "__new__"):
                continue  # construction happens-before publication
            head = qn.split(".")[0]
            cls = head if head in classes else None
            has_global = {
                n for g in ast.walk(fn) if isinstance(g, ast.Global)
                for n in g.names}
            for node in ast.walk(fn):
                for kind, name, via in _write_targets(node, globals_):
                    if kind == "self":
                        if cls is None:
                            continue
                        ident = (src.relpath, cls, name)
                    else:
                        # a bare NAME = ... without a global statement is
                        # a local shadowing the module global; container
                        # mutation (NAME[k] = / NAME.update()) always hits
                        # the shared object
                        plain_rebind = isinstance(
                            node, (ast.Assign, ast.AugAssign, ast.AnnAssign)
                        ) and not _is_subscript_store(node)
                        if plain_rebind and name not in has_global:
                            continue
                        ident = (src.relpath, "<module>", name)
                    line = getattr(node, "lineno", fn.lineno)
                    ctx.writes.setdefault(ident, []).append(WriteSite(
                        src.relpath, qn, line,
                        frozenset(ctx.held_at(key, line)), via))
    ctx.report["shared_write_identities"] = len(ctx.writes)


def _is_subscript_store(node: ast.AST) -> bool:
    if isinstance(node, ast.Assign):
        return any(isinstance(t, ast.Subscript) for t in node.targets)
    if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        return isinstance(node.target, ast.Subscript)
    return False


# ------------------------------------------------------------- spawn scan


def _collect_spawns(ctx: RaceContext, project: core.Project) -> None:
    for src in project.python_files():
        if src.relpath.startswith(GATE_EXEMPT_PREFIXES):
            continue
        if not src.relpath.startswith(("nice_tpu/", "scripts/")):
            continue
        tree = src.tree()
        if tree is None:
            continue
        enclosing = astutil.enclosing_function_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node) or ""
            tail = name.rsplit(".", 1)[-1]
            kind = threadspec.SPAWN_KINDS.get(tail)
            if kind is None:
                continue
            scope = enclosing.get(node.lineno, "<module>")
            ctx.spawn_sites.append(SpawnSite(
                src.relpath, node.lineno, scope, kind, name))
    ctx.report["spawn_sites"] = len(ctx.spawn_sites)


def _collect_lock_labels(ctx: RaceContext, project: core.Project) -> None:
    for src in project.python_files("nice_tpu/"):
        tree = src.tree()
        if tree is None:
            continue
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                label = x1._lock_label(node)
                if label and label != "<unnamed>":
                    ctx.lock_labels.setdefault(
                        label, (src.relpath, node.lineno))
    ctx.report["lock_labels"] = len(ctx.lock_labels)


# ------------------------------------------------------------------ entry


def load_runtime_graph(path: str) -> Dict[str, Set[str]]:
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    edges = raw.get("edges", raw) if isinstance(raw, dict) else {}
    return {str(k): {str(x) for x in v} for k, v in edges.items()}


def build_context(root: str, project: core.Project,
                  lockorder_path: Optional[str] = None) -> RaceContext:
    ctx = RaceContext(root)
    _collect_spawns(ctx, project)
    _collect_functions(ctx, project)
    _build_call_graph(ctx, project)
    _collect_writes(ctx, project)
    _collect_lock_labels(ctx, project)
    ctx.static_edges = x1.lock_graph(project)
    if lockorder_path is None:
        lockorder_path = os.path.join(root, "docs", "lockorder.json")
    ctx.runtime_graph_path = lockorder_path
    if os.path.exists(lockorder_path):
        try:
            ctx.runtime_edges = load_runtime_graph(lockorder_path)
        except (OSError, ValueError):
            ctx.runtime_edges = {}
    ctx.report["runtime_edges"] = sum(
        len(v) for v in ctx.runtime_edges.values())
    ctx.report["static_edges"] = sum(
        len(v) for v in ctx.static_edges.values())
    return ctx
