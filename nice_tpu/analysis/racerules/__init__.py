"""racelint: thread-ownership race analysis (the R-rule family).

nicelint reads source AST for project invariants, jaxlint reads traced
jaxprs; this family reads source AST AGAINST the declared threading
contract in ``analysis/threadspec.py`` — who may touch which shared state
from which thread root. Same ratchet baseline, same ``# nicelint: allow``
escape grammar, same strict gate.

Rules:

* **R1 shared-mutation** — an attribute or module global mutated by code
  reachable from ≥2 registered thread roots with no common guarding lock
  and no ownership declaration; plus the coverage gate itself (an
  unregistered ``Thread(``/pool spawn, or a stale registry entry).
* **R2 lock-discipline** — every declared ``lock:<label>`` field is
  written only under that lock; owner-only fields only from their root's
  reachable set; immutable-after-init fields only from ``__init__``; the
  static X1 acquisition graph unioned with the runtime lockdep graph
  (``docs/lockorder.json``) must stay acyclic, so a static/runtime order
  divergence is flagged before it deadlocks live.
* **R3 blocking-under-lock** — blocking calls (sqlite, HTTP waits,
  ``Future.result``, ``queue.get`` without timeout, ``time.sleep``)
  reachable from a ``may_block=False`` root or lexically inside a lock
  whose LockSpec says ``may_block_under=False``.
* **R4 writer-discipline** — ``Future.set_result``/``set_exception`` only
  inside the writer actor module, and never inside the batch transaction
  span ("accepted ⇒ durable"); direct ledger mutation outside the writer
  root's reach.
* **R5 check-then-act** — a read of a shared dict/cache under a lock,
  an unlocked window, then a write under the same lock in one function
  (the status-cache / ``_cached_mesh`` / trust ``peek_known`` shape), and
  any ``lru_cache`` whose ``cache_clear`` is invoked at runtime (an
  unguardable clear/rebuild window).

Run via ``scripts/racelint.py`` (or ``just racelint``). The dynamic half
is ``analysis/schedex.py`` — racelint proves discipline statically,
schedex replays the interleavings that motivated it.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from nice_tpu.analysis import core

_RRULES: Dict[str, object] = {}


def rrule(rule_id: str):
    def deco(fn):
        _RRULES[rule_id] = fn
        return fn
    return deco


def all_rrules() -> Dict[str, object]:
    # Import side-effect registers every R-rule module exactly once.
    from nice_tpu.analysis.racerules import (  # noqa: F401
        r1_shared_mutation, r2_lock_discipline, r3_blocking,
        r4_writer_discipline, r5_check_then_act,
    )
    return dict(_RRULES)


def run_race_rules(
    project: core.Project,
    ctx,
    only: Optional[Iterable[str]] = None,
):
    """(violations, used allow sites) over a built RaceContext, through the
    shared nicelint runner so inline escapes work identically."""
    registry = {
        rule_id: (lambda p, _fn=fn: _fn(p, ctx))
        for rule_id, fn in all_rrules().items()
    }
    return core.run_rules_tracked(project, only=only, registry=registry)
