"""R2: lock discipline against the declared ownership contract.

* every ``lock:<label>`` SHARED_STATE field is written only while the
  named lock is lexically held;
* ``owner:<root>`` fields are written only by code reachable from that
  root (plus construction);
* ``immutable-after-init`` fields have no post-init writes at all;
* every ``lockdep.make_lock`` label in the tree has a LockSpec (and every
  LockSpec still names a live label) — the lock inventory is part of the
  contract;
* the static X1 acquisition graph unioned with the runtime lockdep graph
  (``docs/lockorder.json``, exported by ``python -m nice_tpu.utils.lockdep
  --dump-graph``) must stay acyclic: a cycle that only appears in the
  union is a static/runtime order divergence — two halves of the codebase
  each locally consistent, jointly a deadlock — flagged here before it
  can happen live.
"""

from __future__ import annotations

from typing import Dict, List, Set

from nice_tpu.analysis import threadspec
from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.racerules import rrule
from nice_tpu.analysis.rules.x1_lock_order import _find_cycle

THREADSPEC_PATH = "nice_tpu/analysis/threadspec.py"


@rrule("R2")
def check(project: Project, ctx) -> List[Violation]:
    out: List[Violation] = []

    # 1. SHARED_STATE declarations vs observed write sites
    for decl in threadspec.SHARED_STATE:
        ident = (decl.path, decl.scope, decl.attr)
        sites = ctx.writes.get(ident, [])
        label = decl.lock_label
        owner = decl.owner_root
        if label is not None:
            for site in sites:
                if label not in site.held:
                    out.append(Violation(
                        "R2", site.path, site.line,
                        f"{decl.scope}.{decl.attr} is declared "
                        f"lock:{label} but this write in {site.func} does "
                        "not hold it",
                        detail=f"unlocked:{decl.scope}.{decl.attr}:"
                               f"{site.func.rsplit('.', 1)[-1]}",
                    ))
        elif owner is not None:
            for site in sites:
                roots = ctx.roots_reaching((site.path, site.func))
                foreign = roots - {owner}
                if foreign:
                    out.append(Violation(
                        "R2", site.path, site.line,
                        f"{decl.scope}.{decl.attr} is declared "
                        f"owner:{owner} but {site.func} is reachable from "
                        f"{', '.join(sorted(foreign))}",
                        detail=f"foreign-write:{decl.scope}.{decl.attr}:"
                               f"{site.func.rsplit('.', 1)[-1]}",
                    ))
        elif decl.ownership == "immutable-after-init":
            for site in sites:
                out.append(Violation(
                    "R2", site.path, site.line,
                    f"{decl.scope}.{decl.attr} is declared immutable-"
                    f"after-init but {site.func} writes it",
                    detail=f"mutated-immutable:{decl.scope}.{decl.attr}",
                ))
        # queue-transferred / atomic carry no static obligation

    # 2. lock inventory coverage
    for label, (path, line) in sorted(ctx.lock_labels.items()):
        if threadspec.lock_spec(label) is None:
            out.append(Violation(
                "R2", path, line,
                f"lock {label!r} has no LockSpec in "
                "analysis/threadspec.py — declare what it guards and "
                "whether blocking under it is legitimate",
                detail=f"undeclared-lock:{label}",
            ))
    for spec in threadspec.LOCK_SPECS:
        if spec.label not in ctx.lock_labels:
            out.append(Violation(
                "R2", THREADSPEC_PATH, 1,
                f"stale LockSpec {spec.label!r}: no make_lock with that "
                "label in the tree",
                detail=f"stale-lock:{spec.label}",
            ))

    # 3. static/runtime lock-order cross-check
    if not ctx.runtime_edges:
        out.append(Violation(
            "R2", THREADSPEC_PATH, 1,
            "no runtime lock-order graph (docs/lockorder.json missing or "
            "empty) — regenerate with `python -m nice_tpu.utils.lockdep "
            "--dump-graph docs/lockorder.json`",
            detail="missing-lockorder",
        ))
    else:
        union: Dict[str, Set[str]] = {
            k: set(v) for k, v in ctx.static_edges.items()}
        for outer, inners in ctx.runtime_edges.items():
            union.setdefault(outer, set()).update(inners)
        cycle = _find_cycle(union)
        if cycle and not _find_cycle(ctx.static_edges) \
                and not _find_cycle(ctx.runtime_edges):
            out.append(Violation(
                "R2", THREADSPEC_PATH, 1,
                "static/runtime lock-order divergence: the union of the "
                "X1 static graph and docs/lockorder.json contains the "
                "cycle " + " -> ".join(cycle) + " — two locally "
                "consistent orders that jointly deadlock",
                detail="order-divergence:" + "->".join(sorted(set(cycle))),
            ))
    return out
