"""J6: KernelSpec registry enforcement.

(a) **Coverage**: every public ``*_batch`` op in the kernel modules
    (``kernelspec.DISCOVERY_MODULES``) must carry a KernelSpec — shapes are
    contracts, not emergent behavior.
(b) **Shape drift**: every traced plan's output avals must equal the spec's
    declared ``out_shapes(plan, batch)`` at every sweep base.
(c) **Capability drift**: the pallas histogram-row cap is declared twice on
    purpose — ``pallas_engine._HIST_ROWS_MAX`` (what the kernel unrolls)
    and ``kernelspec.MAX_HIST_ROWS`` (what the contract promises). They
    must agree, and ``supports_base`` must match the contract's predicate
    over a probe sweep that brackets the cap. Lifting the engine cap
    without updating the contract (or vice versa) breaks a lint here, not
    a fleet.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import List

from nice_tpu.analysis import astutil, kernelspec
from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.jaxrules import jrule, trace_violation

# Brackets the cap: 40/80/510 are sweep bases; 2100 needs a 17th histogram
# row ((2100+2)/128 = 17) and must be rejected until the 16-row cap is
# lifted in both places. 513 (5 rows, and the cheapest 5-row plan — the
# same 29-limb class as 510) sits INSIDE the lifted cap and probes
# that the old 4-row ceiling stays gone.
PROBE_BASES = (40, 80, 510, 513)
PROBE_BASE_ABOVE_CAP = 2100


def check(project: Project, ctx) -> List[Violation]:
    out = {}
    for v in _check_coverage(project):
        out.setdefault(v.key, v)
    for v in _check_shapes(ctx):
        out.setdefault(v.key, v)
    for v in _check_hist_rows():
        out.setdefault(v.key, v)
    return list(out.values())


jrule("J6")(check)


def _check_coverage(project: Project) -> List[Violation]:
    out = []
    specs = kernelspec.all_specs()
    for rel in kernelspec.DISCOVERY_MODULES:
        src = project.get(rel)
        if src is None:
            continue
        tree = src.tree()
        if tree is None:
            continue
        stem = rel.rsplit("/", 1)[-1][:-3]
        for top in tree.body:
            if not isinstance(top, ast.FunctionDef):
                continue
            if top.name.startswith("_") or not top.name.endswith("_batch"):
                continue
            if f"{stem}.{top.name}" not in specs:
                out.append(Violation(
                    "J6", src.relpath, top.lineno,
                    f"public op '{top.name}' has no KernelSpec — declare "
                    f"its shapes/dtypes/casts in analysis/kernelspec.py",
                    detail=f"unspecced-op:{top.name}",
                ))
    _ = astutil  # imported for parity with sibling rules
    return out


def _check_shapes(ctx) -> List[Violation]:
    from nice_tpu.ops.limbs import get_plan
    out = []
    for trace in ctx.traces:
        plan = get_plan(trace.base)
        expected = tuple(
            (tuple(shape), str(dtype))
            for shape, dtype in trace.spec.out_shapes(plan, trace.batch)
        )
        got = tuple(
            (tuple(getattr(v.aval, "shape", ())),
             str(getattr(v.aval, "dtype", "?")))
            for v in trace.closed.jaxpr.outvars
        )
        if got != expected:
            out.append(trace_violation(
                "J6", ctx, trace, None,
                f"{trace.key}: traced outputs {got} != KernelSpec contract "
                f"{expected} — update the spec or fix the kernel",
                f"shape-drift:b{trace.base}",
            ))
    return out


def _check_hist_rows() -> List[Violation]:
    from nice_tpu.ops import pallas_engine as pe
    from nice_tpu.ops.limbs import get_plan
    out = []
    if pe._HIST_ROWS_MAX != kernelspec.MAX_HIST_ROWS:
        out.append(Violation(
            "J6", "nice_tpu/ops/pallas_engine.py", 1,
            f"_HIST_ROWS_MAX={pe._HIST_ROWS_MAX} but the KernelSpec "
            f"contract says MAX_HIST_ROWS={kernelspec.MAX_HIST_ROWS} — "
            f"update both together (and re-run the base sweep)",
            detail="hist-rows-mismatch",
        ))
    probes = [get_plan(b) for b in PROBE_BASES]
    probes.append(dataclasses.replace(get_plan(PROBE_BASES[0]),
                                      base=PROBE_BASE_ABOVE_CAP))
    for plan in probes:
        want = kernelspec._hist_rows(plan) <= kernelspec.MAX_HIST_ROWS
        if pe.supports_base(plan) != want:
            out.append(Violation(
                "J6", "nice_tpu/ops/pallas_engine.py", 1,
                f"supports_base(base={plan.base}) = "
                f"{pe.supports_base(plan)} disagrees with the KernelSpec "
                f"hist-row contract ({want})",
                detail=f"supports-base-drift:b{plan.base}",
            ))
    return out
