"""jaxlint: jaxpr-level kernel verification (the J-rule family).

nicelint (``analysis/rules/``) reads source AST; this family reads the
TRACED TRUTH — ``jax.make_jaxpr`` over the real kernel plans on abstract
inputs, CPU-only and CI-safe. Same ratchet baseline, same
``# nicelint: allow`` escape grammar (J findings attribute to real repo
file:line via jaxpr source info), same strict gate.

Rules:

* **J1 dtype-flow** — every ``convert_element_type`` in a kernel jaxpr must
  be a cast the KernelSpec declares; silent promotion out of the u32 limb
  domain (or into floats) is a finding.
* **J2 carry-headroom** — interval abstract interpretation proving every
  integer add/sub/mul either cannot wrap or feeds the carry-save
  wrap-detection idiom, for every sweep base and carry-interval cadence
  (see ``interval.py`` for the theorem).
* **J3 donation discipline** — donated buffers are donated in the traced
  plan (``donated_invars``), survive lowering (``tf.aliasing_output``), and
  are never read after donation at engine call sites (AST layer).
* **J4 transfer/sync purity** — no host callbacks, ``device_put`` or
  implicit transfers inside jitted step functions (the graph-level truth
  behind nicelint's syntactic D1).
* **J5 recompile surface** — jit sites in ops/ must be declared surfaces;
  static-arg domains stay bounded; no dynamic argument burned into the
  jaxpr as a constant (and no undeclared giant constants).
* **J6 KernelSpec registry** — every public ``*_batch`` op declares a spec
  (``analysis/kernelspec.py``) and every traced plan's output avals match
  it across the base sweep; the pallas histogram-row cap is cross-checked
  so lifting ``_HIST_ROWS_MAX`` breaks a lint, not a fleet.

Run via ``scripts/jaxlint.py`` (or ``just jaxlint``).
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

from nice_tpu.analysis import core

_JRULES: Dict[str, object] = {}


def jrule(rule_id: str):
    def deco(fn):
        _JRULES[rule_id] = fn
        return fn
    return deco


def all_jrules() -> Dict[str, object]:
    # Import side-effect registers every J-rule module exactly once.
    from nice_tpu.analysis.jaxrules import (  # noqa: F401
        j1_dtype_flow, j2_headroom, j3_donation, j4_transfer,
        j5_recompile, j6_kernelspec,
    )
    return dict(_JRULES)


def run_jax_rules(
    project: core.Project,
    ctx,
    only: Optional[Iterable[str]] = None,
):
    """(violations, used allow sites) over a built TraceContext, through the
    shared nicelint runner so inline escapes work identically."""
    registry = {
        rule_id: (lambda p, _fn=fn: _fn(p, ctx))
        for rule_id, fn in all_jrules().items()
    }
    return core.run_rules_tracked(project, only=only, registry=registry)


def trace_violation(rule_id: str, ctx, trace, eqn, message: str,
                    detail_tag: str) -> core.Violation:
    """A finding attributed to the repo source line that emitted ``eqn``
    (so the standard allow grammar applies), falling back to the spec's
    module when no user frame survives tracing."""
    from nice_tpu.analysis.jaxrules import tracer

    site = tracer.src_site(eqn, ctx.root) if eqn is not None else None
    if site is not None:
        path, line, fname = site
        detail = f"{detail_tag}:{fname}"
    else:
        path, line = trace.spec.module, 1
        detail = f"{detail_tag}:{trace.spec.name}"
    return core.Violation(rule_id, path, line, message, detail)
