"""J2: carry-headroom interval analysis.

Runs the interval abstract interpreter (``interval.py``) over every traced
plan — full kernel plans at the autotuned cadence plus the dedicated
limb-math sweep over ``carry_interval in {0, 1, max}`` — and reports every
arithmetic op that may wrap its dtype without feeding the carry-save
wrap-detection idiom. This is the machine-checked form of the invariant the
autotuner currently takes on faith: carry-save columns in
``mul_limbs``/``sqr_limbs`` cannot overflow for any swept base, any limb
count, any resolution cadence — and the MXU arm's i32 dot_general
accumulator stays under the declared digit-split bound
(``TraceTarget.dot_bound``, sourced from ``ops/mxu.accum_bound``).

Input bounds seed from the KernelSpec (notably the histogram accumulator's
flush contract); per-trace proof statistics land in the CI report under
``report["j2"]``.
"""

from __future__ import annotations

from typing import List

from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.jaxrules import jrule, trace_violation
from nice_tpu.analysis.jaxrules.interval import IntervalInterpreter


def check(project: Project, ctx) -> List[Violation]:
    out = {}
    report = ctx.report.setdefault("j2", {})
    for trace in ctx.traces:
        interp = IntervalInterpreter(
            ref_bound=trace.target.ref_bound,
            dot_bound=trace.target.dot_bound,
            carry_bounds=dict(trace.target.carry_bounds or ()),
        )
        interp.run(trace.closed, dict(trace.target.arg_bounds))
        entry = interp.stats.as_report()
        entry["obligations"] = len(interp.obligations)
        report[trace.key] = entry
        for ob in interp.obligations:
            lo, hi = ob.math_range
            v = trace_violation(
                "J2", ctx, trace, ob.eqn,
                f"{ob.dtype} {ob.prim} may wrap in {trace.key}: "
                f"value range [{lo}, {hi}] exceeds the dtype and no "
                f"wrap-check idiom consumes it — prove the bound or add "
                f"carry detection",
                f"headroom:{ob.prim}:{ob.dtype}",
            )
            out.setdefault(v.key, v)
    return list(out.values())


jrule("J2")(check)
