"""J3: donation discipline, in three layers.

(a) **Traced plan**: every buffer a KernelSpec declares donated must carry
``donated_invars=True`` on the traced ``pjit`` eqn — a refactor that drops
``donate_argnums`` (or reorders arguments under it) fails here.

(b) **Lowered plan**: the cheapest sweep base is actually lowered and the
MLIR must contain a ``tf.aliasing_output`` attribute — donation that
silently degrades to a copy (no aliasable output) fails here. XLA's
"donated buffers were not usable" warning is promoted to a test failure in
pyproject's filterwarnings; this is the static twin.

(c) **Call sites (AST)**: a donated buffer is DEAD after the call. At every
engine/mesh call site of a donating callable (provenance tracked from the
factories in ``kernelspec.DONATING_FACTORIES``), the donated argument name
must be rebound by the same statement (``acc, nm = dispatch(acc, item)``)
or not read again before its next rebind.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Tuple

from nice_tpu.analysis import astutil, kernelspec
from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.jaxrules import jrule, trace_violation

AST_SCOPE = ("nice_tpu/ops/", "nice_tpu/parallel/")


def check(project: Project, ctx) -> List[Violation]:
    out = {}
    for v in _check_traces(ctx):
        out.setdefault(v.key, v)
    for v in _check_lowerings(ctx):
        out.setdefault(v.key, v)
    for v in _check_call_sites(project):
        out.setdefault(v.key, v)
    return list(out.values())


jrule("J3")(check)


# -- (a) traced donation ----------------------------------------------------

def _check_traces(ctx) -> List[Violation]:
    out = []
    for trace in ctx.traces:
        donate = trace.target.donate
        if not donate:
            continue
        jaxpr = trace.closed.jaxpr
        pjit_eqns = [e for e in jaxpr.eqns if e.primitive.name == "pjit"]
        for d in donate:
            arg_var = jaxpr.invars[d]
            donated = False
            for eqn in pjit_eqns:
                flags = eqn.params.get("donated_invars", ())
                for op, flag in zip(eqn.invars, flags):
                    if op is arg_var and flag:
                        donated = True
            if not donated:
                out.append(trace_violation(
                    "J3", ctx, trace, None,
                    f"{trace.key}: argument {d} is declared donated in the "
                    f"KernelSpec but the traced plan does not donate it "
                    f"(donate_argnums dropped or reordered?)",
                    f"donation-dropped:arg{d}",
                ))
    return out


# -- (b) lowered aliasing ---------------------------------------------------

def _check_lowerings(ctx) -> List[Violation]:
    out = []
    for trace in ctx.traces:
        text = trace.aliasing_text
        if text is None:
            continue
        if text.startswith("<lowering failed"):
            ctx.report.setdefault("j3_lowering", {})[trace.key] = text
            continue
        if "tf.aliasing_output" not in text:
            out.append(trace_violation(
                "J3", ctx, trace, None,
                f"{trace.key}: lowered module carries no "
                f"tf.aliasing_output — the donated accumulator is being "
                f"copied, not aliased",
                "donation-not-aliased",
            ))
    return out


# -- (c) read-after-donate at call sites ------------------------------------

def _donating_names(tree) -> Dict[str, int]:
    """Module-wide map of local names that are donating callables, by
    provenance: assigned from a known factory, or a local ``def`` that
    forwards one of its params straight into a donating call."""
    names: Dict[str, int] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            callee = (astutil.call_name(node.value) or "").split(".")[-1]
            if callee in kernelspec.DONATING_FACTORIES:
                names[node.targets[0].id] = \
                    kernelspec.DONATING_FACTORIES[callee]
    # one propagation pass: wrappers like ``def dispatch(acc_, item):
    # return accum_exec(acc_, ...)`` donate their own parameter
    for _ in range(2):
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            params = [a.arg for a in node.args.args]
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                idx = _donated_index(call, names)
                if idx is None or idx >= len(call.args):
                    continue
                arg = call.args[idx]
                if isinstance(arg, ast.Name) and arg.id in params:
                    names.setdefault(node.name, params.index(arg.id))
    return names


def _donated_index(call: ast.Call, local_names: Dict[str, int]):
    callee = (astutil.call_name(call) or "").split(".")[-1]
    if callee in local_names:
        return local_names[callee]
    if callee in kernelspec.DONATING_CALLS:
        return kernelspec.DONATING_CALLS[callee]
    return None


def _check_call_sites(project: Project) -> List[Violation]:
    out = []
    for src in project.python_files():
        if not src.relpath.startswith(AST_SCOPE):
            continue
        tree = src.tree()
        if tree is None:
            continue
        names = _donating_names(tree)
        if not names and not kernelspec.DONATING_CALLS:
            continue
        # iter_functions yields nested defs both standalone and inside their
        # parent's walk; key on (line, var) so each read reports once, with
        # the innermost (later-yielded) qualname winning.
        found: Dict[Tuple[int, str], Violation] = {}
        for qn, fn in astutil.iter_functions(tree):
            for v in _scan_function(src, qn, fn, names):
                found[(v.line, v.detail.rsplit(":", 1)[-1])] = v
        out.extend(found.values())
    return out


def _own_nodes(fn):
    """ast.walk that stays inside ``fn``'s own scope — nested defs/lambdas
    rebind names (their own params!) and get scanned separately."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
            stack.extend(ast.iter_child_nodes(node))


def _scan_function(src, qualname, fn, names) -> List[Violation]:
    loads: Dict[str, List[int]] = {}
    stores: Dict[str, List[int]] = {}
    sites: List[Tuple[int, int, str]] = []
    for node in _own_nodes(fn):
        if isinstance(node, ast.Name):
            target = loads if isinstance(node.ctx, ast.Load) else stores
            target.setdefault(node.id, []).append(node.lineno)
        if isinstance(node, ast.Call):
            idx = _donated_index(node, names)
            if idx is None or idx >= len(node.args):
                continue
            arg = node.args[idx]
            if isinstance(arg, ast.Name):
                # a multi-line call spans several lines; reads inside the
                # call's own span ARE the donation, not a read-after
                end = getattr(node, "end_lineno", node.lineno) or node.lineno
                sites.append((node.lineno, end, arg.id))
    out = []
    for line, call_end, name in sites:
        rebinds = sorted(ln for ln in stores.get(name, []) if ln >= line)
        horizon = rebinds[0] if rebinds else None
        reads = [ln for ln in loads.get(name, [])
                 if ln > call_end and (horizon is None or ln < horizon)]
        if reads:
            out.append(Violation(
                "J3", src.relpath, reads[0],
                f"'{name}' is read after being donated at line {line} "
                f"(donated buffers are dead; rebind the name at the call "
                f"statement)",
                detail=f"read-after-donate:{qualname}:{name}",
            ))
    return out
