"""J1: dtype-flow discipline in traced kernel plans.

The limb kernels live in a closed uint32 arithmetic domain with i32 stats
outputs. Every ``convert_element_type`` in a traced plan must be one of the
casts its KernelSpec declares (``allowed_casts``); anything else — a float
sneaking in via an accidental ``jnp.mean``, a silent promotion to 64-bit
under an x64 flag flip, a new cast added without updating the contract —
is a finding. Same-dtype converts (weak-type normalization) are benign.
"""

from __future__ import annotations

from typing import List

from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.jaxrules import jrule, trace_violation
from nice_tpu.analysis.jaxrules.tracer import iter_eqns


def check(project: Project, ctx) -> List[Violation]:
    out = {}
    for trace in ctx.traces:
        allowed = trace.spec.allowed_casts
        for eqn in iter_eqns(trace.closed.jaxpr):
            if eqn.primitive.name != "convert_element_type":
                continue
            src_dt = str(getattr(eqn.invars[0].aval, "dtype", "?"))
            dst_dt = str(eqn.params.get("new_dtype", "?"))
            if src_dt == dst_dt:
                continue
            if (src_dt, dst_dt) in allowed:
                continue
            v = trace_violation(
                "J1", ctx, trace, eqn,
                f"undeclared cast {src_dt}->{dst_dt} in {trace.key} — "
                f"declare it in the KernelSpec allowed_casts or fix the "
                f"kernel",
                f"cast:{src_dt}->{dst_dt}",
            )
            out.setdefault(v.key, v)
    return list(out.values())


jrule("J1")(check)
