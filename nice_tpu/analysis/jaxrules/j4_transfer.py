"""J4: transfer/sync purity of jitted step functions.

The engine's one-transfer-per-field readback contract only holds if the
jitted plans themselves are pure device programs: no host callbacks, no
``device_put``, no infeed/outfeed, no debug prints. nicelint's D1 catches
the syntactic cases; this rule checks the traced graph, where a callback
hidden behind three helper layers is still one eqn.
"""

from __future__ import annotations

from typing import List

from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.jaxrules import jrule, trace_violation
from nice_tpu.analysis.jaxrules.tracer import iter_eqns

FORBIDDEN = frozenset({
    "device_put", "infeed", "outfeed", "copy_to_host_async",
    "host_local_array_to_global_array", "debug_print",
})


def _is_forbidden(name: str) -> bool:
    return name in FORBIDDEN or "callback" in name


def check(project: Project, ctx) -> List[Violation]:
    out = {}
    for trace in ctx.traces:
        for eqn in iter_eqns(trace.closed.jaxpr):
            name = eqn.primitive.name
            if not _is_forbidden(name):
                continue
            v = trace_violation(
                "J4", ctx, trace, eqn,
                f"host transfer/sync primitive '{name}' inside the jitted "
                f"plan {trace.key} — step functions must be pure device "
                f"programs",
                f"transfer:{name}",
            )
            out.setdefault(v.key, v)
    return list(out.values())


jrule("J4")(check)
