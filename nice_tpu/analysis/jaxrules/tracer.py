"""Plan tracing for the jaxpr-level rules (J1..J6).

Every rule runs over the SAME set of traces, built once per jaxlint
invocation: for each registered KernelSpec x sweep base (x carry-interval
cadence for the limb-math proof surface), ``jax.make_jaxpr`` on abstract
``ShapeDtypeStruct`` inputs. CPU-only and device-free — pallas kernels trace
in interpreter mode and still expose their inner kernel jaxpr on the
``pallas_call`` eqn, so the rules see the real Mosaic-bound program.

Tracing the 29-limb base-510 plan costs tens of seconds; the budget knob
(``NICE_TPU_JAXLINT_TRACE_BUDGET_SECS``) bounds the total and anything
skipped is reported loudly (and fails --strict) rather than silently
narrowing the sweep.
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from nice_tpu.analysis import kernelspec

# Trace batch: big enough to exercise lane-aligned histogram layout
# (batch % 128 == 0, the pallas minimum), small enough to trace fast. The
# jaxpr is shape-polymorphic in nothing — but every rule's claim is about
# dtypes, value ranges, and structure, which do not change with batch.
TRACE_BATCH = 256

# "small"-sweep specs (rare-path extraction kernels) skip bases above this:
# their jaxprs repeat the same limb math the full-sweep plans already cover,
# and a 29-limb trace of every spec would blow the CI budget.
SMALL_SWEEP_MAX = 100


@dataclasses.dataclass
class Trace:
    spec: kernelspec.KernelSpec
    base: int
    batch: int
    carry_interval: int
    target: kernelspec.TraceTarget
    closed: object                 # jax ClosedJaxpr
    elapsed: float
    aliasing_text: Optional[str] = None   # lowered MLIR for donation checks

    @property
    def key(self) -> str:
        return f"{self.spec.name}@b{self.base}ci{self.carry_interval}"


class TraceContext:
    """The shared input of every J-rule run: traces + a report accumulator
    that the CLI archives as the CI artifact."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.traces: List[Trace] = []
        self.skipped: List[dict] = []
        self.report: Dict[str, object] = {}

    def by_kind(self, *kinds: str) -> List[Trace]:
        return [t for t in self.traces if t.spec.kind in kinds]


def build_context(
    root: str,
    bases: Iterable[int],
    specs: Optional[Iterable[kernelspec.KernelSpec]] = None,
    budget_secs: float = 3600.0,
    lower_accum: bool = True,
) -> TraceContext:
    """Trace every (spec, base[, cadence]) combination within budget."""
    import jax

    from nice_tpu.ops.limbs import get_plan

    ctx = TraceContext(root)
    bases = sorted(set(int(b) for b in bases))
    spec_list = sorted(specs if specs is not None
                       else kernelspec.all_specs().values(),
                       key=lambda s: s.name)
    t_start = time.perf_counter()
    timings = []
    for spec in spec_list:
        for base in bases:
            if spec.sweep == "small" and base > SMALL_SWEEP_MAX:
                continue
            plan = get_plan(base)
            if not spec.applies(plan):
                continue
            if spec.kind == "limbmath":
                cis = (spec.cadences or kernelspec.carry_cadences)(plan)
            elif spec.takes_carry_interval:
                cis = (0,)
            else:
                cis = (0,)
            for ci in cis:
                spent = time.perf_counter() - t_start
                if spent > budget_secs:
                    ctx.skipped.append({
                        "spec": spec.name, "base": base,
                        "carry_interval": ci,
                        "reason": f"trace budget exhausted "
                                  f"({spent:.0f}s > {budget_secs:.0f}s)",
                    })
                    continue
                target = spec.build(plan, TRACE_BATCH, ci)
                t0 = time.perf_counter()
                closed = jax.make_jaxpr(target.fn)(*target.args)
                elapsed = time.perf_counter() - t0
                trace = Trace(spec, base, TRACE_BATCH, ci, target, closed,
                              elapsed)
                if lower_accum and spec.kind == "accum" and base == bases[0]:
                    trace.aliasing_text = _lowered_text(spec, plan,
                                                        TRACE_BATCH, ci)
                ctx.traces.append(trace)
                timings.append({"trace": trace.key,
                                "secs": round(elapsed, 3),
                                "eqns": sum(1 for _ in iter_eqns(
                                    closed.jaxpr))})
    ctx.report["traces"] = timings
    ctx.report["skipped"] = ctx.skipped
    return ctx


def _lowered_text(spec, plan, batch, ci) -> Optional[str]:
    """MLIR for the donation check (J3): lowering is much costlier than
    tracing, so only the cheapest sweep base pays for it."""
    try:
        if spec.backend == "pallas":
            from nice_tpu.ops import pallas_engine as pe
            br = pe._effective_block_rows(batch, pe.BLOCK_ROWS)
            jitted = pe._detailed_accum_callable(plan, batch, br,
                                                 carry_interval=ci)
            target = spec.build(plan, batch, ci)
            return jitted.lower(*target.args).as_text()
        from nice_tpu.ops import vector_engine as ve
        target = spec.build(plan, batch, ci)
        acc, rest = target.args[0], target.args[1:]
        return ve.detailed_accum_batch.lower(
            plan, batch, acc, list(rest[:-1]), rest[-1],
            carry_interval=ci).as_text()
    except Exception as exc:  # lowering is best-effort evidence
        return f"<lowering failed: {exc}>"


# -- jaxpr walking ----------------------------------------------------------

def _core():
    import jax
    return jax.core


def _inner_jaxpr(val):
    core = _core()
    if isinstance(val, core.ClosedJaxpr):
        return val.jaxpr
    if isinstance(val, core.Jaxpr):
        return val
    return None


def sub_jaxprs(eqn) -> Iterator[object]:
    """Inner jaxprs of a call-like eqn (pjit, pallas_call, cond, ...)."""
    for val in eqn.params.values():
        j = _inner_jaxpr(val)
        if j is not None:
            yield j
        elif isinstance(val, (list, tuple)):
            for item in val:
                j = _inner_jaxpr(item)
                if j is not None:
                    yield j


def iter_eqns(jaxpr) -> Iterator[object]:
    """All eqns, recursing into inner jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def src_site(eqn, root: str) -> Optional[Tuple[str, int, str]]:
    """(repo-relative path, line, function name) of the user frame that
    emitted this eqn, or None when attribution is unavailable. Real sites
    make the standard ``# nicelint: allow`` grammar work for J-rules."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
    except Exception:
        return None
    if frame is None:
        return None
    file_name = getattr(frame, "file_name", "") or ""
    if not file_name.startswith(root + os.sep):
        return None
    return (
        os.path.relpath(file_name, root),
        int(getattr(frame, "start_line", 1) or 1),
        getattr(frame, "function_name", "") or "<unknown>",
    )
