"""Value-range abstract interpretation over jaxprs (the J2 engine).

The theorem this module checks, per traced kernel plan: every integer
``add``/``sub``/``mul`` (and every reduction/accumulation) either

* provably cannot wrap its dtype — the interval of the true mathematical
  result fits the machine range — or
* feeds the carry-save wrap-detection idiom the limb kernels are built on
  (``s = a + b; wrap = s < b`` — the comparison against an operand recovers
  the dropped 2**32 bit, see ve._cs_add / ve._cs_resolve / ve.add_u32), or
* matches the division-remainder peephole ``x - (x // c) * c`` whose result
  is [0, c-1] by construction (the chunked radix digit extraction).

Anything else is an undischarged headroom obligation -> a J2 finding. The
carry-save headroom claim ("columns cannot overflow for any base <= 510 at
any carry_interval cadence") reduces to: the *wrap counters* themselves are
provably non-wrapping u32 adds (their magnitude is bounded by the term count
of a column, orders of magnitude below 2**32), and every data add is either
proven or checked. Shifts are exempt from obligations: ``t << 16`` in mul32
intentionally drops high bits (they are carried separately via ``t >> 16``).

Interval environments seed from the KernelSpec arg bounds (e.g. the
histogram accumulator's flush contract HIST_ACC_BOUND); closed-over
constants use their true min/max. Unknown primitives degrade soundly to the
full dtype range. Pallas kernel jaxprs interpret through ``get``/``swap``/
``addupdate`` with a declared carried-state bound on output refs.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

Interval = Tuple[int, int]


def dtype_interval(dtype) -> Optional[Interval]:
    import numpy as np
    d = np.dtype(dtype)
    if d.kind == "b":
        return (0, 1)
    if d.kind == "u":
        return (0, (1 << (d.itemsize * 8)) - 1)
    if d.kind == "i":
        return (-(1 << (d.itemsize * 8 - 1)), (1 << (d.itemsize * 8 - 1)) - 1)
    return None  # floats and friends: untracked


def _union(a: Interval, b: Interval) -> Interval:
    return (min(a[0], b[0]), max(a[1], b[1]))


@dataclasses.dataclass
class Obligation:
    """A may-wrap arithmetic op awaiting discharge by the wrap-check idiom."""
    prim: str
    dtype: str
    eqn: object
    math_range: Interval          # the unwrapped mathematical result range
    operands: tuple               # invar objects / literal values (for idiom
                                  # matching: wrap checks compare vs operands)
    discharged: bool = False
    checkable: bool = True        # reductions have no idiom; must be proven


@dataclasses.dataclass
class ProofStats:
    eqns: int = 0
    arith: int = 0
    proven: int = 0               # arithmetic proven in-range
    checked: int = 0              # discharged by the wrap-check idiom
    rem_peephole: int = 0
    unknown_prims: set = dataclasses.field(default_factory=set)
    widest_u32_sum: int = 0       # largest proven non-wrap u32 math upper

    def as_report(self) -> dict:
        return {
            "eqns": self.eqns, "arith_ops": self.arith,
            "proven_in_range": self.proven,
            "wrap_checked": self.checked,
            "divmod_peepholes": self.rem_peephole,
            "widest_proven_u32_sum": self.widest_u32_sum,
            "unknown_prims": sorted(self.unknown_prims),
        }


class IntervalInterpreter:
    def __init__(self, ref_bound: Optional[Interval] = None,
                 dot_bound: Optional[Interval] = None,
                 carry_bounds: Optional[Dict[int, Interval]] = None):
        self.ref_bound = ref_bound
        # Declared dot_general accumulator bound (TraceTarget.dot_bound):
        # intersected with the naive per-element product bound, so a spec
        # can discharge MXU contraction headroom with a stated theorem
        # (ops/mxu.accum_bound) instead of a baseline allow.
        self.dot_bound = dot_bound
        # Declared lax.scan carried-state bounds (TraceTarget.carry_bounds),
        # flat carry index -> interval: the megaloop plans' loop-carry
        # contract. Consumed by the OUTERMOST scan only (_h_scan); any inner
        # scan degrades to the while_loop top-out.
        self.carry_bounds = dict(carry_bounds) if carry_bounds else {}
        self._carry_bounds_used = False
        self.obligations: List[Obligation] = []
        self.stats = ProofStats()
        # var -> defining record for peephole matching
        self._defs: Dict[int, Tuple[str, tuple]] = {}
        # var -> pending obligation (discharged when a comparison consumes it)
        self._pending: Dict[int, Obligation] = {}

    # -- env helpers --------------------------------------------------------

    def _aval_dtype(self, v):
        aval = getattr(v, "aval", None)
        return getattr(aval, "dtype", None)

    def _read(self, env, v) -> Optional[Interval]:
        from jax.core import Literal
        if isinstance(v, Literal):
            val = v.val
            try:
                import numpy as np
                arr = np.asarray(val)
                if arr.dtype.kind in "bui":
                    return (int(arr.min()), int(arr.max()))
            except Exception:
                pass
            return None
        got = env.get(id(v))
        if got is not None:
            return got
        return dtype_interval(self._aval_dtype(v)) \
            if self._aval_dtype(v) is not None else None

    def _top(self, v) -> Optional[Interval]:
        dt = self._aval_dtype(v)
        return dtype_interval(dt) if dt is not None else None

    def _operand_key(self, v):
        from jax.core import Literal
        if isinstance(v, Literal):
            return ("lit", repr(v.val))
        return ("var", id(v))

    # -- entry --------------------------------------------------------------

    def run(self, closed, in_intervals: Dict[int, Interval]):
        """Interpret a ClosedJaxpr; in_intervals maps invar index -> bound."""
        import numpy as np
        jaxpr = closed.jaxpr
        env: Dict[int, Interval] = {}
        for cv, cval in zip(jaxpr.constvars, closed.consts):
            try:
                arr = np.asarray(cval)
                if arr.dtype.kind in "bui" and arr.size:
                    env[id(cv)] = (int(arr.min()), int(arr.max()))
            except Exception:
                pass
        for i, v in enumerate(jaxpr.invars):
            iv = in_intervals.get(i)
            env[id(v)] = iv if iv is not None else \
                (self._top(v) or (0, 0))
        self.interp(jaxpr, env, grid=None)
        # anything still pending was never consumed by a wrap check
        for ob in self._pending.values():
            if not ob.discharged:
                self.obligations.append(ob)
        return self

    # -- core loop ----------------------------------------------------------

    def interp(self, jaxpr, env: Dict[int, Interval], grid) -> None:
        for eqn in jaxpr.eqns:
            self.stats.eqns += 1
            self._eqn(eqn, env, grid)

    def _set(self, env, outvars, iv_list):
        for v, iv in zip(outvars, iv_list):
            if iv is None:
                iv = self._top(v)
            if iv is not None:
                env[id(v)] = iv

    def _eqn(self, eqn, env, grid) -> None:
        name = eqn.primitive.name
        handler = _HANDLERS.get(name)
        if handler is not None:
            handler(self, eqn, env, grid)
            return
        if self._try_call_like(eqn, env, grid):
            return
        self.stats.unknown_prims.add(name)
        self._set(env, eqn.outvars, [self._top(v) for v in eqn.outvars])

    # -- call-like recursion ------------------------------------------------

    def _try_call_like(self, eqn, env, grid) -> bool:
        from nice_tpu.analysis.jaxrules.tracer import _inner_jaxpr
        for key in ("jaxpr", "call_jaxpr"):
            inner = eqn.params.get(key)
            ij = _inner_jaxpr(inner) if inner is not None else None
            if ij is None:
                continue
            consts = getattr(inner, "consts", [])
            if len(ij.invars) != len(eqn.invars):
                return False
            sub_env: Dict[int, Interval] = {}
            import numpy as np
            for cv, cval in zip(ij.constvars, consts):
                try:
                    arr = np.asarray(cval)
                    if arr.dtype.kind in "bui" and arr.size:
                        sub_env[id(cv)] = (int(arr.min()), int(arr.max()))
                except Exception:
                    pass
            for iv_var, op in zip(ij.invars, eqn.invars):
                got = self._read(env, op)
                if got is not None:
                    sub_env[id(iv_var)] = got
            self.interp(ij, sub_env, grid)
            self._set(env, eqn.outvars,
                      [self._read(sub_env, v) for v in ij.outvars])
            self._alias_wrapper_def(eqn, ij)
            return True
        return False

    def _alias_wrapper_def(self, eqn, ij) -> None:
        """Provenance through trivial one-eqn wrappers: ``x // c`` traces as
        ``pjit[floor_divide](x, c)``, hiding the div the remainder peephole
        needs. When the inner jaxpr is a single div/mul over the wrapper's
        own invars, record the outer outvar as defined by that op with the
        OUTER operands substituted in."""
        if len(ij.eqns) != 1 or len(ij.outvars) != 1:
            return
        inner_eqn = ij.eqns[0]
        prim = inner_eqn.primitive.name
        if prim not in ("div", "mul") or ij.outvars[0] is not \
                inner_eqn.outvars[0]:
            return
        invar_map = {id(iv): op for iv, op in zip(ij.invars, eqn.invars)}
        mapped = []
        for op in inner_eqn.invars:
            outer = op if _is_lit(op) else invar_map.get(id(op))
            if outer is None:
                return
            mapped.append(outer)
        self._defs[id(eqn.outvars[0])] = (prim, tuple(mapped))


def _is_dropvar(v) -> bool:
    return type(v).__name__ == "DropVar"


# -- primitive transfer functions -------------------------------------------

def _binop_ranges(interp, eqn, env):
    a, b = eqn.invars[0], eqn.invars[1]
    return interp._read(env, a), interp._read(env, b)


def _arith(interp: IntervalInterpreter, eqn, env, math_range: Interval,
           checkable: bool = True) -> None:
    """Shared wrap-obligation logic for add/sub/mul/reductions."""
    out = eqn.outvars[0]
    rng = interp._top(out)
    interp.stats.arith += 1
    if rng is None or math_range is None:
        interp._set(env, eqn.outvars, [rng])
        return
    if math_range[0] >= rng[0] and math_range[1] <= rng[1]:
        interp.stats.proven += 1
        if rng == (0, 2**32 - 1):
            interp.stats.widest_u32_sum = max(
                interp.stats.widest_u32_sum, math_range[1])
        env[id(out)] = math_range
        return
    ob = Obligation(eqn.primitive.name, str(interp._aval_dtype(out)), eqn,
                    math_range, tuple(eqn.invars), checkable=checkable)
    if checkable:
        interp._pending[id(out)] = ob
    else:
        interp.obligations.append(ob)
    env[id(out)] = rng  # wrapped result can be anything in the dtype


def _h_add(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    if ia is None or ib is None:
        interp._set(env, eqn.outvars, [None])
        return
    _arith(interp, eqn, env, (ia[0] + ib[0], ia[1] + ib[1]))


def _h_sub(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    if ia is None or ib is None:
        interp._set(env, eqn.outvars, [None])
        return
    # division-remainder peephole: sub(x, mul(div(x, c), c)) -> [0, c-1]
    peep = _rem_peephole(interp, eqn)
    if peep is not None:
        interp.stats.rem_peephole += 1
        interp.stats.arith += 1
        interp.stats.proven += 1
        env[id(eqn.outvars[0])] = peep
        return
    _arith(interp, eqn, env, (ia[0] - ib[1], ia[1] - ib[0]))


def _rem_peephole(interp, eqn) -> Optional[Interval]:
    a, b = eqn.invars[0], eqn.invars[1]
    bdef = interp._defs.get(id(b))
    if not bdef or bdef[0] != "mul":
        return None
    m1, m2 = bdef[1]
    for q, c in ((m1, m2), (m2, m1)):
        qdef = interp._defs.get(id(q)) if not _is_lit(q) else None
        if not qdef or qdef[0] != "div":
            continue
        x, c2 = qdef[1]
        if interp._operand_key(x) != interp._operand_key(a):
            continue
        cv, c2v = _lit_value(c), _lit_value(c2)
        if cv is None or cv != c2v or cv <= 0:
            continue
        return (0, cv - 1)
    return None


def _is_lit(v) -> bool:
    from jax.core import Literal
    return isinstance(v, Literal)


def _lit_value(v) -> Optional[int]:
    from jax.core import Literal
    if isinstance(v, Literal):
        try:
            return int(v.val)
        except Exception:
            return None
    return None


def _h_mul(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    out = eqn.outvars[0]
    interp._defs[id(out)] = ("mul", (eqn.invars[0], eqn.invars[1]))
    if ia is None or ib is None:
        interp._set(env, eqn.outvars, [None])
        return
    prods = [ia[0] * ib[0], ia[0] * ib[1], ia[1] * ib[0], ia[1] * ib[1]]
    # multiplications have no wrap-check idiom in the kernels: they must be
    # proven in range (mul32 decomposes into 16-bit halves for exactly this)
    _arith(interp, eqn, env, (min(prods), max(prods)), checkable=False)


def _h_div(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    out = eqn.outvars[0]
    interp._defs[id(out)] = ("div", (eqn.invars[0], eqn.invars[1]))
    if ia is None or ib is None or ia[0] < 0 or ib[0] <= 0:
        interp._set(env, eqn.outvars, [None])
        return
    env[id(out)] = (ia[0] // ib[1], ia[1] // ib[0])


def _h_rem(interp, eqn, env, grid):
    _, ib = _binop_ranges(interp, eqn, env)
    if ib is None or ib[0] <= 0:
        interp._set(env, eqn.outvars, [None])
        return
    env[id(eqn.outvars[0])] = (0, ib[1] - 1)


def _h_compare(interp, eqn, env, grid):
    # the wrap-check idiom: (a + b) < b  /  (a + b) < a discharges the add
    if eqn.primitive.name in ("lt", "gt"):
        x, y = eqn.invars
        for s, other in ((x, y), (y, x)):
            ob = interp._pending.get(id(s))
            if ob is not None and not ob.discharged:
                okeys = {interp._operand_key(o) for o in ob.operands}
                if interp._operand_key(other) in okeys:
                    ob.discharged = True
                    interp.stats.checked += 1
    interp._set(env, eqn.outvars, [(0, 1)])


def _h_and(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    if ia is None or ib is None or ia[0] < 0 or ib[0] < 0:
        interp._set(env, eqn.outvars, [None])
        return
    env[id(eqn.outvars[0])] = (0, min(ia[1], ib[1]))


def _h_or(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    if ia is None or ib is None or ia[0] < 0 or ib[0] < 0:
        interp._set(env, eqn.outvars, [None])
        return
    hi = max(ia[1], ib[1])
    env[id(eqn.outvars[0])] = (max(ia[0], ib[0]),
                               (1 << hi.bit_length()) - 1 if hi else 0)


def _h_xor(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    if ia is None or ib is None or ia[0] < 0 or ib[0] < 0:
        interp._set(env, eqn.outvars, [None])
        return
    bits = max(ia[1].bit_length(), ib[1].bit_length())
    env[id(eqn.outvars[0])] = (0, (1 << bits) - 1 if bits else 0)


def _h_shl(interp, eqn, env, grid):
    # shifts never carry obligations: << is the mul32 masking idiom (high
    # bits are recovered separately via >>); an out-of-range shift is top.
    ia, ib = _binop_ranges(interp, eqn, env)
    out = eqn.outvars[0]
    rng = interp._top(out)
    if ia is None or ib is None or rng is None or ia[0] < 0 or ib[0] < 0:
        interp._set(env, eqn.outvars, [rng])
        return
    lo, hi = ia[0] << ib[0], ia[1] << ib[1]
    env[id(out)] = (lo, hi) if hi <= rng[1] else rng


def _h_shr(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    if ia is None or ib is None or ia[0] < 0 or ib[0] < 0:
        interp._set(env, eqn.outvars, [None])
        return
    env[id(eqn.outvars[0])] = (ia[0] >> ib[1], ia[1] >> ib[0])


def _h_convert(interp, eqn, env, grid):
    ia = interp._read(env, eqn.invars[0])
    out = eqn.outvars[0]
    rng = interp._top(out)
    if ia is None or rng is None:
        interp._set(env, eqn.outvars, [rng])
        return
    env[id(out)] = ia if (ia[0] >= rng[0] and ia[1] <= rng[1]) else rng


def _h_select(interp, eqn, env, grid):
    iv = None
    for case in eqn.invars[1:]:
        ci = interp._read(env, case)
        if ci is None:
            iv = None
            break
        iv = ci if iv is None else _union(iv, ci)
    interp._set(env, eqn.outvars, [iv])


def _h_identity(interp, eqn, env, grid):
    interp._set(env, eqn.outvars, [interp._read(env, eqn.invars[0])])


def _h_union_all(interp, eqn, env, grid):
    iv = None
    for op in eqn.invars:
        ci = interp._read(env, op)
        if ci is None:
            iv = None
            break
        iv = ci if iv is None else _union(iv, ci)
    interp._set(env, eqn.outvars, [iv])


def _h_iota(interp, eqn, env, grid):
    shape = eqn.params.get("shape") or getattr(eqn.outvars[0].aval, "shape",
                                               (1,))
    dim = eqn.params.get("dimension", 0)
    n = shape[dim] if shape else 1
    env[id(eqn.outvars[0])] = (0, max(int(n) - 1, 0))


def _reduce_count(eqn) -> int:
    axes = eqn.params.get("axes", ())
    shape = getattr(eqn.invars[0].aval, "shape", ())
    n = 1
    for a in axes:
        if a < len(shape):
            n *= int(shape[a])
    return max(n, 1)


def _h_reduce_sum(interp, eqn, env, grid):
    ia = interp._read(env, eqn.invars[0])
    if ia is None:
        interp._set(env, eqn.outvars, [None])
        return
    n = _reduce_count(eqn)
    _arith(interp, eqn, env, (min(ia[0], ia[0] * n), max(ia[1], ia[1] * n)),
           checkable=False)


def _h_cumsum(interp, eqn, env, grid):
    ia = interp._read(env, eqn.invars[0])
    if ia is None:
        interp._set(env, eqn.outvars, [None])
        return
    axis = eqn.params.get("axis", 0)
    shape = getattr(eqn.invars[0].aval, "shape", ())
    n = int(shape[axis]) if axis < len(shape) else 1
    _arith(interp, eqn, env, (min(ia[0], ia[0] * n), max(ia[1], ia[1] * n)),
           checkable=False)


def _h_reduce_minmax(interp, eqn, env, grid):
    interp._set(env, eqn.outvars, [interp._read(env, eqn.invars[0])])


def _h_minmax(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    if ia is None or ib is None:
        interp._set(env, eqn.outvars, [None])
        return
    if eqn.primitive.name == "max":
        env[id(eqn.outvars[0])] = (max(ia[0], ib[0]), max(ia[1], ib[1]))
    else:
        env[id(eqn.outvars[0])] = (min(ia[0], ib[0]), min(ia[1], ib[1]))


def _h_popcount(interp, eqn, env, grid):
    import numpy as np
    dt = interp._aval_dtype(eqn.invars[0])
    bits = np.dtype(dt).itemsize * 8 if dt is not None else 64
    env[id(eqn.outvars[0])] = (0, bits)


def _h_scatter(interp, eqn, env, grid):
    # result values come from the operand or the updates
    io = interp._read(env, eqn.invars[0])
    iu = interp._read(env, eqn.invars[-1])
    iv = _union(io, iu) if io is not None and iu is not None else None
    interp._set(env, eqn.outvars, [iv])


def _h_scatter_add(interp, eqn, env, grid):
    # worst case every update lands in one cell: op + n_updates * update
    import math
    io = interp._read(env, eqn.invars[0])
    iu = interp._read(env, eqn.invars[-1])
    if io is None or iu is None:
        interp._set(env, eqn.outvars, [None])
        return
    shape = getattr(eqn.invars[-1].aval, "shape", ())
    n = max(int(math.prod(shape)), 1)
    _arith(interp, eqn, env,
           (io[0] + n * min(iu[0], 0), io[1] + n * max(iu[1], 0)),
           checkable=False)


def _h_dot_general(interp, eqn, env, grid):
    ia, ib = _binop_ranges(interp, eqn, env)
    if ia is None or ib is None:
        interp._set(env, eqn.outvars, [None])
        return
    dims = eqn.params.get("dimension_numbers")
    shape = getattr(eqn.invars[0].aval, "shape", ())
    n = 1
    if dims:
        (lhs_contract, _), _ = dims
        for a in lhs_contract:
            if a < len(shape):
                n *= int(shape[a])
    prods = [ia[0] * ib[0], ia[0] * ib[1], ia[1] * ib[0], ia[1] * ib[1]]
    n = max(n, 1)
    lo, hi = min(prods) * n, max(prods) * n
    if interp.dot_bound is not None:
        # The declared contraction bound narrows the naive sum-of-products
        # interval (the naive bound multiplies by the FULL contraction depth
        # even when the operand structure — e.g. the banded Toeplitz digit
        # split — guarantees a tighter sum).
        lo = max(lo, interp.dot_bound[0])
        hi = min(hi, interp.dot_bound[1])
        if lo > hi:
            lo, hi = interp.dot_bound
    _arith(interp, eqn, env, (lo, hi), checkable=False)


def _h_cond(interp, eqn, env, grid):
    branches = eqn.params.get("branches", ())
    operands = eqn.invars[1:]
    outs = None
    for br in branches:
        ij = br.jaxpr if hasattr(br, "jaxpr") else br
        if len(ij.invars) != len(operands):
            outs = None
            break
        sub_env: Dict[int, Interval] = {}
        import numpy as np
        for cv, cval in zip(ij.constvars, getattr(br, "consts", [])):
            try:
                arr = np.asarray(cval)
                if arr.dtype.kind in "bui" and arr.size:
                    sub_env[id(cv)] = (int(arr.min()), int(arr.max()))
            except Exception:
                pass
        for iv_var, op in zip(ij.invars, operands):
            got = interp._read(env, op)
            if got is not None:
                sub_env[id(iv_var)] = got
        interp.interp(ij, sub_env, grid)
        res = [interp._read(sub_env, v) for v in ij.outvars]
        if outs is None:
            outs = res
        else:
            outs = [_union(a, b) if a is not None and b is not None else None
                    for a, b in zip(outs, res)]
    interp._set(env, eqn.outvars, outs or
                [interp._top(v) for v in eqn.outvars])


def _h_while(interp, eqn, env, grid):
    interp._set(env, eqn.outvars, [interp._top(v) for v in eqn.outvars])


def _h_scan(interp, eqn, env, grid):
    """lax.scan under declared carried-state bounds (the megaloop plans).

    The body is interpreted ONCE with its carry invars seeded from
    ``carry_bounds`` (undeclared slots seed at dtype top, consts/xs from the
    outer operand intervals). Each declared bound is an inductive invariant
    the engine upholds across iterations — the same contract style as
    HIST_ACC_BOUND for the per-batch accumulator (e.g. the remaining-lanes
    countdown starts non-negative and only shrinks; the carried histogram
    stays under the flush budget) — so a single body pass surfaces every
    intra-iteration wrap obligation, and the loop's carry outputs re-seed at
    the declared bounds. With no declared bounds this still interprets the
    body (arithmetic checked against dtype-top seeds) and tops the outputs
    out, strictly stronger than the old while_loop handling."""
    from nice_tpu.analysis.jaxrules.tracer import _inner_jaxpr
    inner = eqn.params.get("jaxpr")
    ij = _inner_jaxpr(inner) if inner is not None else None
    num_consts = int(eqn.params.get("num_consts", 0))
    num_carry = int(eqn.params.get("num_carry", 0))
    if ij is None or len(ij.invars) != len(eqn.invars):
        _h_while(interp, eqn, env, grid)
        return
    declared = {} if interp._carry_bounds_used else interp.carry_bounds
    interp._carry_bounds_used = True
    sub_env: Dict[int, Interval] = {}
    import numpy as np
    for cv, cval in zip(ij.constvars, getattr(inner, "consts", [])):
        try:
            arr = np.asarray(cval)
            if arr.dtype.kind in "bui" and arr.size:
                sub_env[id(cv)] = (int(arr.min()), int(arr.max()))
        except Exception:
            pass
    for i, (iv_var, op) in enumerate(zip(ij.invars, eqn.invars)):
        if num_consts <= i < num_consts + num_carry:
            bound = declared.get(i - num_consts) or interp._top(iv_var)
            if bound is not None:
                sub_env[id(iv_var)] = bound
            continue
        # consts and xs: the outer operand interval bounds every per-
        # iteration slice the body sees.
        got = interp._read(env, op)
        if got is not None:
            sub_env[id(iv_var)] = got
    interp.interp(ij, sub_env, grid)
    outs = []
    for j, ov in enumerate(eqn.outvars):
        if j < num_carry:
            outs.append(declared.get(j) or interp._top(ov))
        elif j < len(ij.outvars):
            # stacked ys: the body's per-iteration bound covers every slice
            outs.append(interp._read(sub_env, ij.outvars[j]))
        else:
            outs.append(interp._top(ov))
    interp._set(env, eqn.outvars, outs)


def _h_pallas_call(interp, eqn, env, grid):
    from nice_tpu.analysis.jaxrules.tracer import _inner_jaxpr
    inner = eqn.params.get("jaxpr")
    ij = _inner_jaxpr(inner)
    if ij is None:
        interp._set(env, eqn.outvars, [interp._top(v) for v in eqn.outvars])
        return
    g = _pallas_grid(eqn)
    sub_env: Dict[int, Interval] = {}
    # kernel invars = scalar-prefetch refs + input refs, then output refs;
    # operands line up with the non-output prefix.
    n_ops = len(eqn.invars)
    for i, iv_var in enumerate(ij.invars):
        if i < n_ops:
            got = interp._read(env, eqn.invars[i])
            if got is not None:
                sub_env[id(iv_var)] = got
        else:
            bound = interp.ref_bound or interp._ref_dtype_top(iv_var)
            if bound is not None:
                sub_env[id(iv_var)] = bound
    interp.interp(ij, sub_env, g)
    interp._set(env, eqn.outvars, [interp.ref_bound or interp._top(v)
                                   for v in eqn.outvars])


def _ref_dtype_top(self, v) -> Optional[Interval]:
    aval = getattr(v, "aval", None)
    inner = getattr(aval, "inner_aval", aval)
    dt = getattr(inner, "dtype", None)
    return dtype_interval(dt) if dt is not None else None


IntervalInterpreter._ref_dtype_top = _ref_dtype_top


def _pallas_grid(eqn):
    gm = eqn.params.get("grid_mapping")
    grid = getattr(gm, "grid", None)
    if grid:
        try:
            return tuple(int(g) for g in grid)
        except Exception:
            return None
    return None


def _h_program_id(interp, eqn, env, grid):
    axis = eqn.params.get("axis", 0)
    if grid is not None and axis < len(grid):
        env[id(eqn.outvars[0])] = (0, max(int(grid[axis]) - 1, 0))
    else:
        env[id(eqn.outvars[0])] = (0, (1 << 20) - 1)


def _h_get(interp, eqn, env, grid):
    ref = eqn.invars[0]
    iv = env.get(id(ref))
    if iv is None:
        iv = interp._ref_dtype_top(ref)
    interp._set(env, eqn.outvars, [iv])


def _h_swap(interp, eqn, env, grid):
    ref, val = eqn.invars[0], eqn.invars[1]
    old = env.get(id(ref)) or interp._ref_dtype_top(ref)
    iv_val = interp._read(env, val)
    if old is not None and iv_val is not None:
        env[id(ref)] = _union(old, iv_val)
    interp._set(env, eqn.outvars, [old])


def _h_addupdate(interp, eqn, env, grid):
    ref, val = eqn.invars[0], eqn.invars[1]
    old = env.get(id(ref)) or interp._ref_dtype_top(ref)
    iv_val = interp._read(env, val)
    rng = interp._ref_dtype_top(ref)
    interp.stats.arith += 1
    if old is None or iv_val is None or rng is None:
        return
    mathr = (old[0] + iv_val[0], old[1] + iv_val[1])
    if mathr[0] >= rng[0] and mathr[1] <= rng[1]:
        interp.stats.proven += 1
        env[id(ref)] = mathr
    else:
        interp.obligations.append(Obligation(
            "addupdate", str(_ref_dtype(ref)), eqn, mathr,
            tuple(eqn.invars), checkable=False))
        env[id(ref)] = rng


def _ref_dtype(v):
    aval = getattr(v, "aval", None)
    inner = getattr(aval, "inner_aval", aval)
    return getattr(inner, "dtype", None)


_IDENTITY_PRIMS = (
    "broadcast_in_dim", "reshape", "squeeze", "expand_dims", "copy",
    "transpose", "rev", "slice", "dynamic_slice", "gather", "stop_gradient",
    "convert_element_type_weak", "reduce_precision",
)

_HANDLERS = {
    "add": _h_add, "sub": _h_sub, "mul": _h_mul,
    "div": _h_div, "rem": _h_rem,
    "lt": _h_compare, "le": _h_compare, "gt": _h_compare,
    "ge": _h_compare, "eq": _h_compare, "ne": _h_compare,
    "and": _h_and, "or": _h_or, "xor": _h_xor,
    "shift_left": _h_shl,
    "shift_right_logical": _h_shr, "shift_right_arithmetic": _h_shr,
    "convert_element_type": _h_convert,
    "select_n": _h_select,
    "concatenate": _h_union_all, "pad": _h_union_all,
    "iota": _h_iota,
    "reduce_sum": _h_reduce_sum, "cumsum": _h_cumsum,
    "reduce_max": _h_reduce_minmax, "reduce_min": _h_reduce_minmax,
    "reduce_or": lambda i, e, env, g: i._set(env, e.outvars, [(0, 1)]),
    "reduce_and": lambda i, e, env, g: i._set(env, e.outvars, [(0, 1)]),
    "max": _h_minmax, "min": _h_minmax,
    "population_count": _h_popcount,
    "scatter": _h_scatter, "scatter-add": _h_scatter_add,
    "dot_general": _h_dot_general,
    "cond": _h_cond, "while": _h_while, "scan": _h_scan,
    "pallas_call": _h_pallas_call,
    "program_id": _h_program_id,
    "get": _h_get, "swap": _h_swap, "addupdate": _h_addupdate,
    "not": lambda i, e, env, g: i._set(
        env, e.outvars,
        [(0, 1) if i._top(e.outvars[0]) == (0, 1)
         else i._top(e.outvars[0])]),
}
for _p in _IDENTITY_PRIMS:
    _HANDLERS[_p] = _h_identity
