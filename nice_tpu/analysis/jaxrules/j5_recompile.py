"""J5: recompile-surface audit.

Every ``jax.jit`` in ops/ is a recompile surface keyed by its static
arguments. This rule keeps that surface enumerable:

(a) every jit site in the kernel modules must be a declared surface
    (``kernelspec.KNOWN_JIT_SURFACES``) — new jitted kernels are declared
    (and spec'd) before they ship;
(b) no dynamic argument gets burned into the traced jaxpr as a constant —
    the traced plan must have exactly as many invars as the spec feeds it
    (a Python scalar captured by closure shrinks the invars and recompiles
    per value);
(c) closed-over constants stay small (``max_const_elems`` per spec; the
    strided offsets table is a declared exception) — a giant constant is
    usually a dynamic array accidentally captured at trace time;
(d) the static-arg tuple count across the sweep stays under the knob
    ceiling, and no spec documents an unbounded static domain. Observed
    variants land in the CI report under ``report["j5"]``.
"""

from __future__ import annotations

import ast
from typing import List

from nice_tpu.analysis import astutil, kernelspec
from nice_tpu.analysis.core import Project, Violation
from nice_tpu.analysis.jaxrules import jrule, trace_violation

MAX_VARIANTS_DEFAULT = 1024


def check(project: Project, ctx) -> List[Violation]:
    out = {}
    for v in _check_jit_sites(project):
        out.setdefault(v.key, v)
    for v in _check_burned_args(ctx):
        out.setdefault(v.key, v)
    for v in _check_variants(ctx):
        out.setdefault(v.key, v)
    return list(out.values())


jrule("J5")(check)


# -- (a) undeclared jit sites ----------------------------------------------

def _jit_in(node: ast.AST) -> bool:
    """Does this expression mention jax.jit (directly or via
    functools.partial(jax.jit, ...))?"""
    for sub in ast.walk(node):
        name = astutil.dotted(sub) or ""
        if name in ("jax.jit", "jit") or name.endswith(".jit"):
            return True
    return False


def _check_jit_sites(project: Project) -> List[Violation]:
    out = []
    for rel in kernelspec.DISCOVERY_MODULES:
        src = project.get(rel)
        if src is None:
            continue
        tree = src.tree()
        if tree is None:
            continue
        for top in tree.body:
            if not isinstance(top, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            has_jit = any(_jit_in(d) for d in top.decorator_list)
            if not has_jit:
                for node in ast.walk(top):
                    if isinstance(node, ast.Call) and _jit_in(node.func):
                        has_jit = True
                        break
                    if isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) and \
                            node is not top and \
                            any(_jit_in(d) for d in node.decorator_list):
                        has_jit = True
                        break
            if has_jit and top.name not in kernelspec.KNOWN_JIT_SURFACES:
                out.append(Violation(
                    "J5", src.relpath, top.lineno,
                    f"undeclared jit surface '{top.name}' — add it to "
                    f"kernelspec.KNOWN_JIT_SURFACES (and give it a "
                    f"KernelSpec) before shipping a new recompile surface",
                    detail=f"unregistered-jit:{top.name}",
                ))
    return out


# -- (b)+(c) burned constants ----------------------------------------------

def _all_consts(closed):
    """(jaxpr, const) pairs, recursing into call-like eqns."""
    from nice_tpu.analysis.jaxrules.tracer import _inner_jaxpr, iter_eqns
    yield from ((closed.jaxpr, c) for c in closed.consts)
    for eqn in iter_eqns(closed.jaxpr):
        for val in eqn.params.values():
            inner = _inner_jaxpr(val)
            if inner is not None and hasattr(val, "consts"):
                yield from ((inner, c) for c in val.consts)


def _check_burned_args(ctx) -> List[Violation]:
    import numpy as np
    out = []
    for trace in ctx.traces:
        n_invars = len(trace.closed.jaxpr.invars)
        n_args = len(trace.target.args)
        if n_invars != n_args:
            out.append(trace_violation(
                "J5", ctx, trace, None,
                f"{trace.key}: traced plan has {n_invars} inputs but the "
                f"spec feeds {n_args} — a dynamic argument was burned into "
                f"the jaxpr as a constant (recompiles per value)",
                "burned-arg",
            ))
        cap = trace.spec.max_const_elems
        for _, const in _all_consts(trace.closed):
            try:
                size = int(np.asarray(const).size)
            except Exception:
                continue
            if size > cap:
                out.append(trace_violation(
                    "J5", ctx, trace, None,
                    f"{trace.key}: closed-over constant of {size} elements "
                    f"exceeds the spec ceiling ({cap}) — an array captured "
                    f"at trace time?",
                    "giant-const",
                ))
                break
    return out


# -- (d) static-arg cardinality ---------------------------------------------

def _check_variants(ctx, max_variants: int = MAX_VARIANTS_DEFAULT) -> \
        List[Violation]:
    out = []
    variants = {}
    for trace in ctx.traces:
        variants.setdefault(trace.spec.name, set()).add(
            (trace.base, trace.batch, trace.carry_interval))
    report = {
        name: {"observed_static_tuples": len(keys),
               "static_domain": dict(
                   kernelspec.all_specs()[name].static_domain)}
        for name, keys in sorted(variants.items())
    }
    ctx.report["j5"] = report
    total = sum(len(k) for k in variants.values())
    limit = ctx.report.get("j5_max_variants", max_variants)
    if total > limit:
        out.append(Violation(
            "J5", "nice_tpu/analysis/kernelspec.py", 1,
            f"static-arg surface across the sweep is {total} variants "
            f"(> {limit}) — unbounded recompile surface",
            detail="variant-ceiling",
        ))
    for name, spec in sorted(kernelspec.all_specs().items()):
        for param, doc in spec.static_domain:
            if "unbounded" in doc.lower():
                out.append(Violation(
                    "J5", spec.module, 1,
                    f"{name}: static arg '{param}' documents an unbounded "
                    f"domain — bound it or the executable cache cannot",
                    detail=f"unbounded-static:{name}:{param}",
                ))
    return out
