"""nicelint framework: source model, inline escapes, ratchet baseline.

The design center is the RATCHET: a violation's identity must survive
unrelated edits, so baseline keys are ``rule|path|detail`` with no line
numbers — the line is carried separately for display only. A baselined
violation therefore stays baselined as the file grows around it, and fixing
it strands a stale key that ``--strict`` forces out of the baseline file.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Callable, Dict, Iterable, List, Optional, Set, Tuple

# Escape grammar: a comment of the form "nicelint: allow <RULE>[,<RULE>...]"
# with an optional parenthesised reason, on the flagged line or the line above.
_ALLOW_RE = re.compile(
    r"#\s*nicelint:\s*allow\s+([A-Z]\d(?:\s*,\s*[A-Z]\d)*)\b"
)
_FENCE_RE = re.compile(r"#\s*nicelint:\s*fence\b")
_LOOP_THREAD_RE = re.compile(r"#\s*nicelint:\s*loop-thread\b")


class Violation:
    """One finding. ``key`` (rule|path|detail) is the ratchet identity and
    deliberately excludes the line number."""

    __slots__ = ("rule", "path", "line", "message", "detail")

    def __init__(self, rule: str, path: str, line: int, message: str,
                 detail: str):
        self.rule = rule
        self.path = path
        self.line = line
        self.message = message
        self.detail = detail

    @property
    def key(self) -> str:
        return f"{self.rule}|{self.path}|{self.detail}"

    def to_json(self) -> dict:
        return {
            "rule": self.rule,
            "path": self.path,
            "line": self.line,
            "message": self.message,
            "key": self.key,
        }

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<Violation {self.rule} {self.path}:{self.line} {self.detail}>"


class SourceFile:
    """One parsed file plus its inline nicelint escape markers."""

    def __init__(self, root: str, relpath: str):
        self.root = root
        self.relpath = relpath
        with open(os.path.join(root, relpath), encoding="utf-8",
                  errors="replace") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.AST] = None
        self._parse_error: Optional[SyntaxError] = None
        self._allows: Optional[Dict[int, Set[str]]] = None
        self._fences: Optional[Set[int]] = None
        self._loop_thread_marks: Optional[Set[int]] = None

    # -- parsing -----------------------------------------------------------

    @property
    def is_python(self) -> bool:
        return self.relpath.endswith(".py")

    def tree(self) -> Optional[ast.AST]:
        """The module AST, or None on syntax errors (ruff's E9 floor owns
        those; nicelint rules just skip the file)."""
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text)
            except SyntaxError as exc:
                self._parse_error = exc
        return self._tree

    # -- inline escapes ----------------------------------------------------

    def _scan_markers(self) -> None:
        self._allows = {}
        self._fences = set()
        self._loop_thread_marks = set()
        for i, line in enumerate(self.lines, start=1):
            if "nicelint" not in line:
                continue
            m = _ALLOW_RE.search(line)
            if m:
                rules = {r.strip() for r in m.group(1).split(",")}
                self._allows.setdefault(i, set()).update(rules)
            if _FENCE_RE.search(line):
                self._fences.add(i)
            if _LOOP_THREAD_RE.search(line):
                self._loop_thread_marks.add(i)

    def allowed(self, rule: str, line: int) -> bool:
        """True when ``line`` (or the line above, for markers placed on
        their own comment line) carries an allow for ``rule``."""
        return self.allow_site(rule, line) is not None

    def allow_site(self, rule: str, line: int) -> Optional[int]:
        """The marker line that allows ``rule`` at ``line``, or None."""
        if self._allows is None:
            self._scan_markers()
        for ln in (line, line - 1):
            rules = self._allows.get(ln)
            if rules and rule in rules:
                return ln
        return None

    def allow_markers(self) -> Dict[int, Set[str]]:
        """marker line -> rule ids, for the dead-suppression audit."""
        if self._allows is None:
            self._scan_markers()
        return dict(self._allows)

    def string_spanned_lines(self) -> Set[int]:
        """Lines covered by string constants (docstrings, fixture sources).
        Escape markers on these lines are documentation, not suppressions —
        the dead-suppression audit must not count them."""
        tree = self.tree()
        if tree is None:
            return set()
        out: Set[int] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                end = getattr(node, "end_lineno", node.lineno) or node.lineno
                out.update(range(node.lineno, end + 1))
        return out

    def is_fence(self, line: int) -> bool:
        if self._fences is None:
            self._scan_markers()
        return line in self._fences or (line - 1) in self._fences

    def loop_thread_lines(self) -> Set[int]:
        if self._loop_thread_marks is None:
            self._scan_markers()
        return set(self._loop_thread_marks)


class Project:
    """The file set nicelint runs over. Python files under the package,
    scripts, and tests; plus non-Python assets (web UI, docs) that the M1/K1
    usage scans read as text."""

    PY_DIRS = ("nice_tpu", "scripts", "tests")
    TEXT_GLOB_DIRS = ("web",)
    TEXT_EXTS = (".html", ".js", ".mjs", ".css")

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._files: Optional[List[SourceFile]] = None

    def files(self) -> List[SourceFile]:
        if self._files is not None:
            return self._files
        out: List[SourceFile] = []
        for top in self.PY_DIRS:
            base = os.path.join(self.root, top)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__" and
                               not d.startswith(".")]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), self.root
                        )
                        out.append(SourceFile(self.root, rel))
        for top in self.TEXT_GLOB_DIRS:
            base = os.path.join(self.root, top)
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames if d != "node_modules"]
                for fn in sorted(filenames):
                    if fn.endswith(self.TEXT_EXTS):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), self.root
                        )
                        out.append(SourceFile(self.root, rel))
        self._files = out
        return out

    def python_files(self, prefix: str = "") -> List[SourceFile]:
        return [f for f in self.files()
                if f.is_python and f.relpath.startswith(prefix)]

    def get(self, relpath: str) -> Optional[SourceFile]:
        for f in self.files():
            if f.relpath == relpath:
                return f
        return None


# -- rule registry ---------------------------------------------------------

Rule = Callable[[Project], List[Violation]]
_RULES: Dict[str, Rule] = {}


def rule(rule_id: str):
    def deco(fn: Rule) -> Rule:
        _RULES[rule_id] = fn
        return fn
    return deco


def all_rules() -> Dict[str, Rule]:
    # Import side-effect registers every rule module exactly once.
    from nice_tpu.analysis import rules  # noqa: F401
    return dict(_RULES)


AllowSite = Tuple[str, int, str]  # (path, marker line, rule id)


def filter_allowed(
    project: Project, violations: Iterable[Violation]
) -> Tuple[List[Violation], Set[AllowSite]]:
    """Drop inline-allowed findings; also return the marker sites that
    actually suppressed something (the dead-suppression audit's ground
    truth)."""
    kept: List[Violation] = []
    used: Set[AllowSite] = set()
    for v in violations:
        src = project.get(v.path)
        site = src.allow_site(v.rule, v.line) if src is not None else None
        if site is not None:
            used.add((v.path, site, v.rule))
            continue
        kept.append(v)
    return kept, used


def run_rules(project: Project,
              only: Optional[Iterable[str]] = None) -> List[Violation]:
    """Run rule families (all by default) and drop inline-allowed findings."""
    return run_rules_tracked(project, only=only)[0]


def run_rules_tracked(
    project: Project,
    only: Optional[Iterable[str]] = None,
    registry: Optional[Dict[str, Rule]] = None,
) -> Tuple[List[Violation], Set[AllowSite]]:
    """run_rules plus the set of allow-marker sites that fired. ``registry``
    swaps in a different rule family (jaxlint passes its J-rules)."""
    rules = registry if registry is not None else all_rules()
    wanted = set(only) if only else None
    raw: List[Violation] = []
    for rule_id, fn in sorted(rules.items()):
        if wanted is not None and rule_id not in wanted:
            continue
        raw.extend(fn(project))
    out, used = filter_allowed(project, raw)
    out.sort(key=lambda v: (v.path, v.line, v.rule, v.detail))
    return out, used


# -- dead-suppression audit (rule S1) ---------------------------------------

DEAD_SUPPRESSION_RULE = "S1"
# Escape markers inside tests/ stay exempt: rule-fixture sources embed the
# grammar in string literals and harness files legitimately park allows that
# only fire for some fixture variants.
DEAD_SUPPRESSION_SKIP = ("tests/",)


def dead_suppressions(
    project: Project,
    ran_rules: Iterable[str],
    used: Set[AllowSite],
    skip_prefixes: Tuple[str, ...] = DEAD_SUPPRESSION_SKIP,
) -> List[Violation]:
    """Allow markers whose rule no longer fires at that site. Only markers
    naming a rule in ``ran_rules`` are judged — a K1 allow is not dead just
    because the run was --rules W1. Identity is line-number-free:
    rule S1, detail ``dead:<rule>:<enclosing scope>``."""
    ran = set(ran_rules)
    out: List[Violation] = []
    for src in project.python_files():
        if src.relpath.startswith(skip_prefixes):
            continue
        markers = src.allow_markers()
        if not markers:
            continue
        doc_lines = src.string_spanned_lines()
        scopes = _line_scope_map(src)
        for line in sorted(markers):
            if line in doc_lines:
                continue
            for rule_id in sorted(markers[line]):
                if rule_id not in ran:
                    continue
                if (src.relpath, line, rule_id) in used:
                    continue
                scope = scopes.get(line, "<module>")
                out.append(Violation(
                    DEAD_SUPPRESSION_RULE, src.relpath, line,
                    f"dead escape: '# nicelint: allow {rule_id}' but {rule_id} "
                    f"no longer fires here — delete the marker",
                    detail=f"dead:{rule_id}:{scope}",
                ))
    return out


def _line_scope_map(src: SourceFile) -> Dict[int, str]:
    """line -> innermost enclosing function name (S1's stable identity)."""
    tree = src.tree()
    if tree is None:
        return {}
    out: Dict[int, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = getattr(node, "end_lineno", node.lineno) or node.lineno
            for ln in range(node.lineno, end + 1):
                out[ln] = node.name  # walk order: inner defs overwrite outer
    return out


# -- ratchet baseline ------------------------------------------------------

BASELINE_RELPATH = os.path.join("nice_tpu", "analysis", "baseline.json")


def load_baseline(root: str) -> Dict[str, str]:
    """key -> justification. Missing file means an empty baseline."""
    path = os.path.join(root, BASELINE_RELPATH)
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    entries = data.get("entries", {})
    if isinstance(entries, list):  # tolerate the bare-list form
        return {k: "" for k in entries}
    return dict(entries)


def save_baseline(root: str, entries: Dict[str, str]) -> None:
    path = os.path.join(root, BASELINE_RELPATH)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = {
        "comment": (
            "nicelint ratchet baseline. Every key is rule|path|detail for a "
            "KNOWN violation with a justification; new violations fail CI "
            "immediately. Regenerate with: python scripts/nicelint.py "
            "--update-baseline"
        ),
        "entries": {k: entries[k] for k in sorted(entries)},
    }
    with open(path, "w", encoding="utf-8") as f:  # nicelint: allow A1 (dev-only tool output, not crash-safety state)
        json.dump(payload, f, indent=1)
        f.write("\n")


def filter_baseline(
    baseline: Dict[str, str], rule_ids: Iterable[str]
) -> Dict[str, str]:
    """The slice of a shared baseline one analyzer family owns. nicelint and
    jaxlint ratchet against the same file; each must only see (and declare
    stale) keys for rules it actually ran. S1 keys are split by the rule
    embedded in their ``dead:<rule>:...`` detail, since both CLIs emit S1
    for their own rule family."""
    ids = set(rule_ids)
    out: Dict[str, str] = {}
    for key, why in baseline.items():
        rule_id, _, detail = key.split("|", 2) if key.count("|") >= 2 \
            else (key.split("|", 1)[0], "", "")
        if rule_id == DEAD_SUPPRESSION_RULE:
            inner = detail.split(":", 2)[1] if detail.startswith("dead:") \
                else ""
            if DEAD_SUPPRESSION_RULE in ids and inner in ids:
                out[key] = why
        elif rule_id in ids:
            out[key] = why
    return out


def diff_against_baseline(
    violations: List[Violation], baseline: Dict[str, str]
) -> Tuple[List[Violation], List[str]]:
    """(new_violations, stale_baseline_keys)."""
    found = {v.key for v in violations}
    new = [v for v in violations if v.key not in baseline]
    stale = sorted(k for k in baseline if k not in found)
    return new, stale
