"""Small shared AST helpers for the nicelint rules."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple


def dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for a Name/Attribute chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(node: ast.Call) -> Optional[str]:
    return dotted(node.func)


def iter_functions(tree: ast.AST) -> Iterator[Tuple[str, ast.AST]]:
    """(qualified_name, node) for every def/async def, including methods
    ('Class.method') and nested functions ('outer.<locals>.inner')."""

    def walk(node: ast.AST, prefix: str) -> Iterator[Tuple[str, ast.AST]]:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qn = f"{prefix}{child.name}"
                yield qn, child
                yield from walk(child, f"{qn}.<locals>.")
            elif isinstance(child, ast.ClassDef):
                yield from walk(child, f"{prefix}{child.name}.")
            else:
                yield from walk(child, prefix)

    yield from walk(tree, "")


def enclosing_function_map(tree: ast.AST) -> Dict[int, str]:
    """line -> qualified name of the innermost enclosing function. Lines in
    module-level code are absent."""
    out: Dict[int, str] = {}
    for qn, fn in iter_functions(tree):
        start = fn.lineno
        end = getattr(fn, "end_lineno", start)
        for ln in range(start, end + 1):
            # innermost wins: later (nested) functions overwrite their span
            prev = out.get(ln)
            if prev is None or len(qn) >= len(prev):
                out[ln] = qn
    return out


def local_call_targets(fn: ast.AST) -> Set[str]:
    """Plain-name and self-method call targets inside a function body:
    {'helper', 'self._sweep'} -> {'helper', '_sweep'}. Also includes bare
    names passed as call ARGUMENTS (callbacks handed to executors/actors
    still execute the callee's code somewhere)."""
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = call_name(node)
        if name:
            if name.startswith("self."):
                out.add(name.split(".", 1)[1].split(".", 1)[0])
            elif "." not in name:
                out.add(name)
    return out


def string_literals(tree: ast.AST) -> Iterator[Tuple[str, int]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            yield node.value, node.lineno
