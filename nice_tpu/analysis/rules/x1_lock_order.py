"""X1: static lock-order analysis (the static half of runtime lockdep).

Two checks:

1. Every project lock must be constructed through ``lockdep.make_lock`` /
   ``make_rlock`` — a bare ``threading.Lock()`` in ``nice_tpu/`` escapes
   both the runtime instrumentation and this rule's graph.

2. The acquisition-order graph extracted from nested ``with`` statements
   must be acyclic. Lock identities are the dotted names passed to
   ``make_lock`` (the same names runtime lockdep reports), resolved from
   assignment sites: ``X = lockdep.make_lock("mod._lock")`` maps the
   module-level name or ``self.<attr>`` to that label. Cross-module
   acquisitions (``self.db._lock`` in the writer) resolve through the
   class-attribute table built from every file, keyed by the final
   ``<obj>.<attr>`` pair.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, Violation, rule

LOCKDEP_PATH = "nice_tpu/utils/lockdep.py"
MAKE_FUNCS = ("make_lock", "make_rlock")


def _lock_label(node: ast.Call) -> Optional[str]:
    name = astutil.call_name(node) or ""
    if name.rsplit(".", 1)[-1] not in MAKE_FUNCS:
        return None
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return "<unnamed>"


def _collect_lock_maps(project: Project):
    """Per-module {expr -> label} plus a global {attr -> label} fallback
    for cross-module acquisitions like ``self.db._lock``."""
    per_module: Dict[str, Dict[str, str]] = {}
    # attr name -> set of labels assigned to a self.<attr> anywhere
    attr_labels: Dict[str, Set[str]] = {}
    for src in project.python_files("nice_tpu/"):
        tree = src.tree()
        if tree is None:
            continue
        table: Dict[str, str] = {}
        for node in ast.walk(tree):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            if not isinstance(node.value, ast.Call):
                continue
            label = _lock_label(node.value)
            if label is None:
                continue
            target = astutil.dotted(node.targets[0])
            if not target:
                continue
            table[target] = label  # "self._lock" or module-level "_lock"
            if target.startswith("self."):
                attr = target.split(".", 1)[1]
                attr_labels.setdefault(attr, set()).add(label)
        per_module[src.relpath] = table
    return per_module, attr_labels


def _resolve(expr: str, table: Dict[str, str],
             attr_labels: Dict[str, Set[str]]) -> Optional[str]:
    if expr in table:
        return table[expr]
    # "self.db._lock" / "ctx.db._lock": resolve by final attribute when the
    # project has exactly one lock with that attribute name on a class the
    # receiver plausibly is ("<...>.db._lock" matched against "server.db.*").
    attr = expr.rsplit(".", 1)[-1]
    candidates = attr_labels.get(attr, set())
    if len(candidates) == 1:
        return next(iter(candidates))
    if len(candidates) > 1:
        # disambiguate via the receiver's name: self.db._lock prefers the
        # label containing ".db." or ending in "Db._lock"-style casing.
        parts = expr.split(".")
        if len(parts) >= 2:
            recv = parts[-2].lower()
            scored = [c for c in candidates if f".{recv}." in c.lower()]
            if len(scored) == 1:
                return scored[0]
    return None


def _walk_withs(body: List[ast.stmt], held: Tuple[str, ...],
                table: Dict[str, str], attr_labels: Dict[str, Set[str]],
                edges: Dict[str, Set[str]], sites: Dict[Tuple[str, str],
                                                        Tuple[str, int]],
                relpath: str) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_held = held
            for item in stmt.items:
                expr = astutil.dotted(item.context_expr)
                label = _resolve(expr, table, attr_labels) if expr else None
                if label is None:
                    continue
                if new_held and new_held[-1] != label:
                    outer = new_held[-1]
                    edges.setdefault(outer, set()).add(label)
                    sites.setdefault((outer, label),
                                     (relpath, stmt.lineno))
                new_held = new_held + (label,)
            _walk_withs(stmt.body, new_held, table, attr_labels, edges,
                        sites, relpath)
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def body runs later, not under the current holds
            _walk_withs(stmt.body, (), table, attr_labels, edges, sites,
                        relpath)
        elif isinstance(stmt, ast.ClassDef):
            _walk_withs(stmt.body, (), table, attr_labels, edges, sites,
                        relpath)
        else:
            for child_body in _stmt_bodies(stmt):
                _walk_withs(child_body, held, table, attr_labels, edges,
                            sites, relpath)


def _stmt_bodies(stmt: ast.stmt):
    for field in ("body", "orelse", "finalbody"):
        block = getattr(stmt, field, None)
        if block:
            yield block
    for handler in getattr(stmt, "handlers", []) or []:
        yield handler.body


def _find_cycle(edges: Dict[str, Set[str]]) -> Optional[List[str]]:
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in
             set(edges) | {m for vs in edges.values() for m in vs}}
    stack: List[str] = []

    def dfs(node: str) -> Optional[List[str]]:
        color[node] = GRAY
        stack.append(node)
        for nxt in sorted(edges.get(node, ())):
            if color[nxt] == GRAY:
                return stack[stack.index(nxt):] + [nxt]
            if color[nxt] == WHITE:
                found = dfs(nxt)
                if found:
                    return found
        stack.pop()
        color[node] = BLACK
        return None

    for node in sorted(color):
        if color[node] == WHITE:
            found = dfs(node)
            if found:
                return found
    return None


@rule("X1")
def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    # 1. bare Lock()/RLock() constructions
    for src in project.python_files("nice_tpu/"):
        if src.relpath == LOCKDEP_PATH:
            continue
        tree = src.tree()
        if tree is None:
            continue
        enclosing = astutil.enclosing_function_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node) or ""
            if name in ("threading.Lock", "threading.RLock"):
                fn = enclosing.get(node.lineno, "<module>")
                out.append(Violation(
                    "X1", src.relpath, node.lineno,
                    f"bare {name}() in {fn} — construct project locks via "
                    "lockdep.make_lock()/make_rlock() so runtime lockdep "
                    "and the static graph see them",
                    detail=f"bare-lock:{fn}",
                ))

    # 2. static acquisition-order graph
    per_module, attr_labels = _collect_lock_maps(project)
    edges: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for src in project.python_files("nice_tpu/"):
        tree = src.tree()
        if tree is None:
            continue
        table = per_module.get(src.relpath, {})
        _walk_withs(tree.body, (), table, attr_labels, edges, sites,
                    src.relpath)
    cycle = _find_cycle(edges)
    if cycle:
        first_edge = (cycle[0], cycle[1]) if len(cycle) > 1 else None
        relpath, line = sites.get(first_edge, ("nice_tpu", 1)) \
            if first_edge else ("nice_tpu", 1)
        out.append(Violation(
            "X1", relpath, line,
            "lock-order cycle: " + " -> ".join(cycle),
            detail="cycle:" + "->".join(sorted(set(cycle))),
        ))
    return out


def lock_graph(project: Project) -> Dict[str, Set[str]]:
    """The extracted static acquisition-order graph (CLI --graph dump)."""
    per_module, attr_labels = _collect_lock_maps(project)
    edges: Dict[str, Set[str]] = {}
    sites: Dict[Tuple[str, str], Tuple[str, int]] = {}
    for src in project.python_files("nice_tpu/"):
        tree = src.tree()
        if tree is None:
            continue
        _walk_withs(tree.body, (), per_module.get(src.relpath, {}),
                    attr_labels, edges, sites, src.relpath)
    return edges
