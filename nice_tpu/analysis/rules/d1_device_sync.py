"""D1: device-sync discipline in the engine/mesh hot paths.

PR 10's device-step profiler guarantees it adds zero device syncs; that
only stays true if every sync site in ``ops/engine.py`` and
``parallel/mesh.py`` is deliberate. Each ``block_until_ready``,
``jax.device_get``, or ``np.asarray``-of-a-device-value call must sit on a
line marked ``# nicelint: fence`` (or directly below a fence comment line)
— making every host-device synchronization point grep-able and reviewed.

``np.asarray`` over obvious host data (list/tuple/comprehension literals,
``np.*`` results) is skipped; only Name/Attribute arguments — potential
device arrays — count.
"""

from __future__ import annotations

import ast
from typing import List

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, Violation, rule

SCOPE = ("nice_tpu/ops/engine.py", "nice_tpu/parallel/mesh.py")

HOST_ARG_TYPES = (ast.List, ast.ListComp, ast.Tuple, ast.GeneratorExp,
                  ast.Dict, ast.Constant, ast.BinOp)


def _is_sync_call(node: ast.Call) -> str:
    name = astutil.call_name(node) or ""
    if name.endswith(".block_until_ready"):
        return "block_until_ready"
    if name in ("jax.device_get", "device_get"):
        return "jax.device_get"
    if name in ("np.asarray", "numpy.asarray", "np.array", "numpy.array"):
        if node.args and isinstance(node.args[0], HOST_ARG_TYPES):
            return ""  # host-literal construction, no device sync
        if node.args and isinstance(node.args[0], ast.Call):
            inner = astutil.call_name(node.args[0]) or ""
            if inner.startswith(("np.", "numpy.")):
                return ""  # np-on-np, host side
        return name
    return ""


@rule("D1")
def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    for relpath in SCOPE:
        src = project.get(relpath)
        if src is None or src.tree() is None:
            continue
        enclosing = astutil.enclosing_function_map(src.tree())
        for node in ast.walk(src.tree()):
            if not isinstance(node, ast.Call):
                continue
            kind = _is_sync_call(node)
            if not kind:
                continue
            if src.is_fence(node.lineno):
                continue
            fn = enclosing.get(node.lineno, "<module>")
            out.append(Violation(
                "D1", relpath, node.lineno,
                f"device sync {kind} outside a '# nicelint: fence' site "
                f"in {fn}",
                detail=f"{fn}->{kind}",
            ))
    return out
