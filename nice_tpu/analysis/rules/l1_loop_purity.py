"""L1: event-loop purity.

Roots are the async functions of ``server/async_core.py`` (they run ON the
event loop) plus any function carrying a ``# nicelint: loop-thread`` marker
(the limiter/shed/multiplier callables the async core invokes from the loop
thread). From each root the rule follows same-module direct calls — NOT
values handed to ``run_in_executor`` or the writer actor, which is exactly
the sanctioned way to leave the loop — and flags reachable blocking
operations: ``time.sleep``, file ``open()``, sqlite, blocking socket
constructors, subprocess, and ``Future.result()``-style waits.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, SourceFile, Violation, rule

ASYNC_CORE = "nice_tpu/server/async_core.py"
SERVER_PREFIX = "nice_tpu/server/"

BLOCKING_EXACT = {
    "time.sleep": "time.sleep blocks the loop thread",
    "open": "file I/O blocks the loop thread",
    "sqlite3.connect": "sqlite access on the loop thread",
    "socket.create_connection": "blocking socket connect",
    "subprocess.run": "subprocess wait on the loop thread",
    "subprocess.check_output": "subprocess wait on the loop thread",
    "subprocess.check_call": "subprocess wait on the loop thread",
}
BLOCKING_SUFFIXES = {
    ".result": "Future.result() waits on the loop thread",
    ".execute": "DB execute on the loop thread",
    ".executemany": "DB execute on the loop thread",
    ".fsync": "fsync on the loop thread",
}
# Executor/actor dispatch: arguments to these run OFF the loop; the callee
# is not loop-reachable through them.
OFFLOAD_SUFFIXES = (".run_in_executor",)


def _function_table(src: SourceFile) -> Dict[str, ast.AST]:
    tree = src.tree()
    if tree is None:
        return {}
    return {qn.rsplit(".", 1)[-1]: fn
            for qn, fn in astutil.iter_functions(tree)}


def _roots(src: SourceFile) -> Set[str]:
    tree = src.tree()
    if tree is None:
        return set()
    roots: Set[str] = set()
    marks = src.loop_thread_lines()
    for qn, fn in astutil.iter_functions(tree):
        short = qn.rsplit(".", 1)[-1]
        if src.relpath == ASYNC_CORE and isinstance(fn, ast.AsyncFunctionDef):
            roots.add(short)
        start = fn.lineno
        # a marker on the def line, the decorator line, or the line above
        if any(ln in marks for ln in (start, start - 1)):
            roots.add(short)
        else:
            deco_lines = {d.lineno for d in getattr(fn, "decorator_list", [])}
            if deco_lines & marks:
                roots.add(short)
    return roots


def _direct_calls(fn: ast.AST) -> Set[str]:
    """Same-module call targets, EXCLUDING anything passed as an argument
    to an offload dispatcher (run_in_executor)."""
    offload_arg_spans: List[Tuple[int, int]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name and name.endswith(OFFLOAD_SUFFIXES):
                for arg in node.args:
                    offload_arg_spans.append(
                        (arg.lineno, getattr(arg, "end_lineno", arg.lineno))
                    )
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        if any(a <= node.lineno <= b for a, b in offload_arg_spans):
            continue
        name = astutil.call_name(node)
        if not name:
            continue
        if name.startswith("self."):
            out.add(name.split(".", 1)[1].split(".", 1)[0])
        elif "." not in name:
            out.add(name)
    return out


def _blocking_calls(fn: ast.AST) -> List[Tuple[int, str, str]]:
    found = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if not name:
            continue
        if name in BLOCKING_EXACT:
            found.append((node.lineno, name, BLOCKING_EXACT[name]))
            continue
        for suffix, why in BLOCKING_SUFFIXES.items():
            if name.endswith(suffix) and name != "self" + suffix:
                found.append((node.lineno, name, why))
                break
    return found


@rule("L1")
def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    for src in project.python_files(SERVER_PREFIX):
        roots = _roots(src)
        if not roots:
            continue
        table = _function_table(src)
        # Reachable set via same-module direct calls.
        reachable: Set[str] = set()
        frontier = [r for r in roots if r in table]
        while frontier:
            name = frontier.pop()
            if name in reachable:
                continue
            reachable.add(name)
            for callee in _direct_calls(table[name]):
                if callee in table and callee not in reachable:
                    frontier.append(callee)
        for name in sorted(reachable):
            for line, callee, why in _blocking_calls(table[name]):
                out.append(Violation(
                    "L1", src.relpath, line,
                    f"{callee}() reachable from loop-thread root "
                    f"({name}): {why}",
                    detail=f"{name}->{callee}",
                ))
    return out
