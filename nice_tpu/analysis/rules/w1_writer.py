"""W1: writer-actor discipline.

Every mutating ``server/db.py`` method must be invoked from writer context
— inside ``server/writer.py`` itself, inside ``server/db.py`` (methods
compose), or from a function the writer actor runs (anything handed to
``writer.call`` / ``writer.submit`` / ``ctx.write`` / ``add_periodic``,
transitively through same-module helpers). A mutating call anywhere else in
``nice_tpu/server/`` bypasses the single-writer funnel and reintroduces the
multi-writer SQLite contention the actor exists to remove.

Mutating methods are discovered from ``server/db.py`` itself: a ``Db``
method whose body references ``self._txn`` (the write-transaction context
manager), transitively closed over same-class method calls. Sanctioned init
paths (crash recovery before the writer starts) carry an inline
``# nicelint: allow W1 (reason)``.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Set

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, Violation, rule

DB_PATH = "nice_tpu/server/db.py"
WRITER_PATH = "nice_tpu/server/writer.py"
SERVER_PREFIX = "nice_tpu/server/"

# Call targets whose function-valued arguments run on the writer thread.
DISPATCH_SUFFIXES = (".call", ".submit", ".write", ".add_periodic")


def mutating_db_methods(project: Project) -> Set[str]:
    db = project.get(DB_PATH)
    if db is None or db.tree() is None:
        return set()
    methods: Dict[str, ast.AST] = {}
    for node in ast.walk(db.tree()):
        if isinstance(node, ast.ClassDef) and node.name == "Db":
            for item in node.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    methods[item.name] = item
    mutating = {
        name for name, fn in methods.items()
        if any(
            astutil.dotted(n) == "self._txn"
            for n in ast.walk(fn) if isinstance(n, (ast.Attribute,))
        )
    }
    # Transitive closure: a method that calls a mutating method mutates.
    changed = True
    while changed:
        changed = False
        for name, fn in methods.items():
            if name in mutating:
                continue
            if astutil.local_call_targets(fn) & mutating:
                mutating.add(name)
                changed = True
    return mutating


def _writer_context_functions(tree: ast.AST) -> Set[str]:
    """Unqualified names of functions this module hands to the writer
    actor, transitively closed over same-module calls."""
    seeds: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if not name or not name.endswith(DISPATCH_SUFFIXES):
            continue
        for arg in node.args:
            target = astutil.dotted(arg)
            if target:
                seeds.add(target.rsplit(".", 1)[-1])
    if not seeds:
        return seeds
    bodies = [(qn.rsplit(".", 1)[-1], fn)
              for qn, fn in astutil.iter_functions(tree)]
    names = {short for short, _ in bodies}
    changed = True
    while changed:
        changed = False
        for short, fn in bodies:
            if short in seeds:
                for callee in astutil.local_call_targets(fn):
                    if callee in names and callee not in seeds:
                        seeds.add(callee)
                        changed = True
    return seeds


def _dispatch_spans(tree: ast.AST) -> List[tuple]:
    """Line spans of writer-dispatch call expressions: a mutating call
    lexically inside one (a lambda handed to ctx.write) is sanctioned."""
    spans = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            name = astutil.call_name(node)
            if name and name.endswith(DISPATCH_SUFFIXES):
                spans.append((node.lineno, getattr(node, "end_lineno",
                                                   node.lineno)))
    return spans


@rule("W1")
def check(project: Project) -> List[Violation]:
    mutating = mutating_db_methods(project)
    if not mutating:
        return []
    out: List[Violation] = []
    for src in project.python_files(SERVER_PREFIX):
        if src.relpath in (DB_PATH, WRITER_PATH):
            continue
        tree = src.tree()
        if tree is None:
            continue
        writer_ctx = _writer_context_functions(tree)
        spans = _dispatch_spans(tree)
        enclosing = astutil.enclosing_function_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = astutil.call_name(node)
            if not name or "." not in name:
                continue
            obj, _, method = name.rpartition(".")
            if method not in mutating:
                continue
            # Only db-object receivers: self.db / ctx.db / db / <x>.db
            if not (obj == "db" or obj.endswith(".db")):
                continue
            line = node.lineno
            if any(a <= line <= b for a, b in spans):
                continue
            fn = enclosing.get(line, "")
            if fn.rsplit(".", 1)[-1] in writer_ctx or \
                    fn.split(".", 1)[0] in writer_ctx:
                continue
            out.append(Violation(
                "W1", src.relpath, line,
                f"mutating Db call {name}() outside writer context — "
                "route through the writer actor (ctx.write / writer.call)",
                detail=f"{fn or '<module>'}->{method}",
            ))
    return out
