"""K1: knob discipline.

(a) No direct ``NICE_TPU_*`` environment READS inside ``nice_tpu/``
outside the registry itself — every read goes through
``nice_tpu.utils.knobs`` so type, default, and documentation live in one
place. (Scripts and tests may read the environment for harness plumbing;
they still fall under (b).)

(b) Every ``NICE_TPU_*`` name appearing as a string literal in Python
source must be declared in the registry (exact knob or prefix family) —
an undeclared name is either a typo or an undocumented knob.

(c) Generated docs must not drift: ``docs/KNOBS.md`` must equal
``knobs.render_markdown()`` and the README's generated knob block must
equal the registry rendering. Regenerate with
``python scripts/nicelint.py --write-docs``.

The docs check only engages when the analyzed tree ships the real
registry (``nice_tpu/utils/knobs.py`` exists), so fixture mini-projects
in the rule tests are exempt.
"""

from __future__ import annotations

import ast
import os
import re
from typing import List

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, Violation, rule

KNOBS_PATH = "nice_tpu/utils/knobs.py"
_KNOB_RE = re.compile(r"^NICE_TPU_[A-Z0-9_]*[A-Z0-9]$")

README_BEGIN = "<!-- nicelint:knobs:begin"
README_END = "<!-- nicelint:knobs:end -->"


def _env_read_name(node: ast.Call) -> str:
    """The literal knob name when this call reads the environment."""
    name = astutil.call_name(node) or ""
    if name.endswith(("os.environ.get", "environ.get")) or \
            name in ("os.getenv", "getenv"):
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            return node.args[0].value
    return ""


def _declared(name: str) -> bool:
    from nice_tpu.utils import knobs
    if knobs.is_declared(name):
        return True
    return any(fam.matches(name) for fam in knobs.PREFIXES)


@rule("K1")
def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    for src in project.python_files():
        if src.relpath == KNOBS_PATH:
            continue
        tree = src.tree()
        if tree is None:
            continue
        in_package = src.relpath.startswith("nice_tpu/")
        # (a) direct env reads in the package
        if in_package:
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                env_name = _env_read_name(node)
                if env_name.startswith("NICE_TPU_"):
                    out.append(Violation(
                        "K1", src.relpath, node.lineno,
                        f"direct read of {env_name} — go through "
                        "nice_tpu.utils.knobs",
                        detail=f"direct-read:{env_name}",
                    ))
                # subscript reads: os.environ["NICE_TPU_X"]
            for node in ast.walk(tree):
                if isinstance(node, ast.Subscript) and \
                        isinstance(node.ctx, ast.Load) and \
                        (astutil.dotted(node.value) or "").endswith("environ"):
                    sl = node.slice
                    if isinstance(sl, ast.Constant) and \
                            isinstance(sl.value, str) and \
                            sl.value.startswith("NICE_TPU_"):
                        out.append(Violation(
                            "K1", src.relpath, node.lineno,
                            f"direct read of {sl.value} — go through "
                            "nice_tpu.utils.knobs",
                            detail=f"direct-read:{sl.value}",
                        ))
        # (b) undeclared literals (everywhere, including scripts/tests)
        seen = set()
        for value, line in astutil.string_literals(tree):
            if not _KNOB_RE.match(value):
                continue
            if value in seen:
                continue
            seen.add(value)
            if not _declared(value):
                out.append(Violation(
                    "K1", src.relpath, line,
                    f"undeclared knob {value} — declare it in "
                    "nice_tpu/utils/knobs.py",
                    detail=f"undeclared:{value}",
                ))

    # (c) generated-docs drift — only against the real registry tree
    if project.get(KNOBS_PATH) is not None:
        from nice_tpu.utils import knobs
        docs_rel = os.path.join("docs", "KNOBS.md")
        docs_path = os.path.join(project.root, docs_rel)
        want = knobs.render_markdown()
        if not os.path.exists(docs_path):
            out.append(Violation(
                "K1", docs_rel, 1,
                "docs/KNOBS.md missing — run scripts/nicelint.py "
                "--write-docs",
                detail="docs-missing",
            ))
        else:
            with open(docs_path, encoding="utf-8") as f:
                have = f.read()
            if have != want:
                out.append(Violation(
                    "K1", docs_rel, 1,
                    "docs/KNOBS.md drifted from the knob registry — run "
                    "scripts/nicelint.py --write-docs",
                    detail="docs-drift",
                ))
        readme_path = os.path.join(project.root, "README.md")
        if os.path.exists(readme_path):
            with open(readme_path, encoding="utf-8") as f:
                readme = f.read()
            for group, block in _readme_blocks(readme):
                want_block = knobs.render_group_markdown(group)
                if block.strip() != want_block.strip():
                    out.append(Violation(
                        "K1", "README.md", 1,
                        f"README generated knob table ({group}) drifted — "
                        "run scripts/nicelint.py --write-docs",
                        detail=f"readme-drift:{group}",
                    ))
    return out


def _readme_blocks(readme: str):
    """Yields (group, current_block_text) for every generated marker pair:
    <!-- nicelint:knobs:begin GROUP --> ... <!-- nicelint:knobs:end -->"""
    pos = 0
    while True:
        start = readme.find(README_BEGIN, pos)
        if start < 0:
            return
        head_end = readme.index("-->", start) + 3
        group = readme[start + len(README_BEGIN):head_end - 3].strip()
        end = readme.find(README_END, head_end)
        if end < 0:
            return
        yield group, readme[head_end:end]
        pos = end + len(README_END)


def rewrite_readme(readme: str) -> str:
    """The --write-docs counterpart of the drift check."""
    from nice_tpu.utils import knobs
    out = []
    pos = 0
    while True:
        start = readme.find(README_BEGIN, pos)
        if start < 0:
            out.append(readme[pos:])
            return "".join(out)
        head_end = readme.index("-->", start) + 3
        group = readme[start + len(README_BEGIN):head_end - 3].strip()
        end = readme.find(README_END, head_end)
        if end < 0:
            out.append(readme[pos:])
            return "".join(out)
        out.append(readme[pos:head_end])
        out.append("\n" + knobs.render_group_markdown(group).strip() + "\n")
        pos = end
