"""A1: atomic-write discipline.

State files inside ``nice_tpu/`` are written only through
``nice_tpu.utils.fsio`` (same-dir temp + fsync + rename + dir fsync). Any
other write-mode ``open()`` / ``os.fdopen()`` in the package is a
violation: either migrate it to fsio, or — for genuinely streaming sinks
(trace logs) and non-state artifacts — carry an inline
``# nicelint: allow A1 (reason)``.
"""

from __future__ import annotations

import ast
from typing import List, Optional

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, Violation, rule

FSIO_PATH = "nice_tpu/utils/fsio.py"
WRITE_CHARS = set("wax+")


def _mode_of(node: ast.Call) -> Optional[str]:
    """The literal mode argument of an open()/os.fdopen() call, when the
    call is one and the mode is statically known."""
    name = astutil.call_name(node) or ""
    if name not in ("open", "os.fdopen", "fdopen", "io.open"):
        return None
    mode = None
    if len(node.args) >= 2:
        arg = node.args[1]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            mode = arg.value
        else:
            return "<dynamic>"
    for kw in node.keywords:
        if kw.arg == "mode":
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                mode = kw.value.value
            else:
                return "<dynamic>"
    return mode if mode is not None else "r"


@rule("A1")
def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    for src in project.python_files("nice_tpu/"):
        if src.relpath == FSIO_PATH:
            continue
        tree = src.tree()
        if tree is None:
            continue
        enclosing = astutil.enclosing_function_map(tree)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            mode = _mode_of(node)
            if mode is None:
                continue
            if mode != "<dynamic>" and not (set(mode) & WRITE_CHARS):
                continue
            fn = enclosing.get(node.lineno, "<module>")
            out.append(Violation(
                "A1", src.relpath, node.lineno,
                f"write-mode open({mode!r}) in {fn} — state files go "
                "through nice_tpu.utils.fsio (tmp+fsync+rename)",
                detail=f"{fn}:{mode}",
            ))
    return out
