"""M1: metrics discipline.

(a) Process-global series must be declared in ``obs/series.py``: any
``metrics.counter/gauge/histogram(...)`` call (the obs.metrics module
functions register on the global registry) outside series.py is a
violation. Private ``Registry()`` instances declare through a method call
(``self.registry.counter``) and are exempt — but their names still join
the declared set.

(b) Every ``nice_*`` series-name token used anywhere (Python, web UI)
must resolve to a declared series — exactly, or as a derived-series suffix
(``_p50``/``_p95``/``_p99``/``_sum``/``_count``/``_bucket``, optionally
with a tier suffix) of one. Undeclared tokens are violations; so are
declared-but-unknown spellings in dashboards (catching dashboard drift
when a series is renamed).

(c) Label sets must be bounded: a declaration's ``labelnames`` must be a
literal tuple/list of string literals, never computed.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Set, Tuple

from nice_tpu.analysis import astutil
from nice_tpu.analysis.core import Project, SourceFile, Violation, rule

SERIES_PATH = "nice_tpu/obs/series.py"
METRICS_PATH = "nice_tpu/obs/metrics.py"
DECL_FUNCS = ("counter", "gauge", "histogram")

_NAME_RE = re.compile(r"\bnice_[a-z0-9_]+\b")
_SERIES_TOKEN = re.compile(r"^nice_[a-z0-9_]+$")
# Derived-series machinery: history quantiles/aggregates and the renderer's
# histogram sub-series.
_DERIVED = re.compile(
    r"_(?:p50|p95|p99|sum|count|bucket|total)(?:_[a-z0-9]+)?$"
)

# Tokens that look like series names but are not (package name, sqlite
# file stems, native library symbols, CSS/JS identifiers).
IGNORE_TOKENS = {
    "nice_tpu", "nice_native", "nice_numbers", "nice_count", "nice_list",
    "nice_autotune", "nice_flight", "nice_sp_",
}


def _decl_calls(src: SourceFile):
    tree = src.tree()
    if tree is None:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = astutil.call_name(node)
        if not name:
            continue
        parts = name.split(".")
        if parts[-1] not in DECL_FUNCS or len(parts) < 2:
            continue
        first = None
        if node.args and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            first = node.args[0].value
        yield node, name, first


def declared_series(project: Project) -> Set[str]:
    declared: Set[str] = set()
    for src in project.python_files("nice_tpu/"):
        for _node, _name, first in _decl_calls(src):
            if first and first.startswith("nice_"):
                declared.add(first)
    return declared


def _labelnames_literal(node: ast.Call) -> Tuple[bool, List[str]]:
    for kw in node.keywords:
        if kw.arg != "labelnames":
            continue
        if isinstance(kw.value, (ast.Tuple, ast.List)):
            vals = []
            for el in kw.value.elts:
                if isinstance(el, ast.Constant) and isinstance(el.value, str):
                    vals.append(el.value)
                else:
                    return False, []
            return True, vals
        return False, []
    return True, []  # no labels: trivially bounded


def _usable(used: str, declared: Set[str]) -> bool:
    if used in declared:
        return True
    stripped = _DERIVED.sub("", used)
    if stripped != used and stripped in declared:
        return True
    # Prefix fragments ("nice_mesh_" in a dashboard's startswith filter,
    # "nice_api_request" in a test assertion) are fine when at least one
    # declared series begins with them.
    return any(d.startswith(used) for d in declared)


@rule("M1")
def check(project: Project) -> List[Violation]:
    out: List[Violation] = []
    declared = declared_series(project)
    if not declared:
        return []

    for src in project.python_files("nice_tpu/"):
        if src.relpath == METRICS_PATH:
            continue
        for node, name, first in _decl_calls(src):
            # (a) global-registry declaration outside series.py: the call
            # target is the metrics MODULE itself ('metrics.counter' or
            # 'obs.metrics.counter'), not a registry instance.
            head = name.rsplit(".", 2)
            module_call = head[-2] == "metrics" if len(head) >= 2 else False
            if module_call and src.relpath != SERIES_PATH:
                out.append(Violation(
                    "M1", src.relpath, node.lineno,
                    f"global metric declared outside obs/series.py: "
                    f"{first or name}",
                    detail=f"global-decl:{first or name}",
                ))
            # (c) bounded labels
            literal, _vals = _labelnames_literal(node)
            if not literal:
                out.append(Violation(
                    "M1", src.relpath, node.lineno,
                    f"metric {first or name} declares computed labelnames "
                    "(label sets must be literal and bounded)",
                    detail=f"labels:{first or name}",
                ))

    # (b) usage scan across Python + web assets
    decl_lines: Dict[str, Set[int]] = {}
    for src in project.files():
        if src.is_python:
            tree = src.tree()
            if tree is None:
                continue
            tokens = []
            for value, line in astutil.string_literals(tree):
                if _SERIES_TOKEN.match(value):
                    tokens.append((value, line))
        else:
            tokens = [
                (m.group(0), src.text.count("\n", 0, m.start()) + 1)
                for m in _NAME_RE.finditer(src.text)
            ]
        for used, line in tokens:
            if used in IGNORE_TOKENS:
                continue
            if _usable(used, declared):
                continue
            key = f"{src.relpath}:{used}"
            if line in decl_lines.get(key, set()):
                continue
            decl_lines.setdefault(key, set()).add(line)
            out.append(Violation(
                "M1", src.relpath, line,
                f"series name {used!r} is not declared in obs/series.py",
                detail=f"undeclared:{used}",
            ))
    return out
