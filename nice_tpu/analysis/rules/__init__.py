"""Rule modules register themselves with core.rule on import."""

from nice_tpu.analysis.rules import (  # noqa: F401
    a1_atomic_write,
    d1_device_sync,
    k1_knobs,
    l1_loop_purity,
    m1_metrics,
    w1_writer,
    x1_lock_order,
)
