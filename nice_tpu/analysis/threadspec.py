"""ThreadRegistry: the declarative ground truth for racelint (R-rules).

nicelint checks syntax-level project invariants and jaxlint checks traced
kernel plans; racelint checks WHO may touch WHAT from WHICH thread. That
contract has to live somewhere reviewable, so this module declares:

* :class:`ThreadRoot` — every long-lived thread root in the tree: where it
  is spawned (file + enclosing scope), what code it runs, its role, which
  registered locks it is expected to take, and whether it may block.
  ``scripts/racelint.py`` cross-checks the registry against every
  ``threading.Thread(`` / ``ThreadPoolExecutor(`` / ``ThreadingHTTPServer(``
  construction in ``nice_tpu/`` and ``scripts/`` — an unregistered spawn is
  an R1 finding, a registered root with no surviving spawn site is stale.
* :class:`LockSpec` — every ``lockdep.make_lock``/``make_rlock`` label,
  what it guards, and whether blocking work is legitimate while holding it
  (the db lock guards sqlite itself; the status-cache lock must never be
  held across I/O). R3 flags blocking calls under ``may_block_under=False``
  locks; an undeclared label is a finding.
* :class:`SharedState` — per-object ownership declarations (lock-guarded,
  owner-thread-only, immutable-after-init, queue-transferred, or
  GIL-atomic). R2 verifies write sites against the declaration; R1 flags
  multi-root mutation of anything UNDECLARED with no common lock.

Keep entries honest: the registry is the audit trail ROADMAP item 2
(sharded coordination plane) will multiply by N processes.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional, Tuple

__all__ = [
    "ThreadRoot",
    "LockSpec",
    "SharedState",
    "THREAD_ROOTS",
    "LOCK_SPECS",
    "SHARED_STATE",
    "roots_by_site",
    "lock_spec",
    "shared_state_for",
    "SPAWN_KINDS",
]

# Call-name suffix -> spawn kind the coverage gate matches on.
SPAWN_KINDS = {
    "Thread": "thread",
    "ThreadPoolExecutor": "pool",
    "ThreadingHTTPServer": "http-server",
}


@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    """One registered thread root (or pool / loop takeover)."""

    name: str            # runtime thread name, or a symbolic id for pools
    path: str            # repo-relative file containing the spawn call
    spawn_scope: str     # qualified function enclosing the spawn call
    entries: Tuple[str, ...]  # qualnames (in ``path``) the root executes;
                              # empty = stdlib code only (serve_forever)
    role: str            # writer-actor | event-loop | worker-pool | producer
                         # | collector | periodic | probe | http-server | helper
    kind: str = "thread"  # thread | pool | http-server | loop
    may_block: bool = True
    locks: Tuple[str, ...] = ()   # lockdep labels this root may acquire
    notes: str = ""


@dataclasses.dataclass(frozen=True)
class LockSpec:
    label: str
    guards: str
    may_block_under: bool = False


@dataclasses.dataclass(frozen=True)
class SharedState:
    path: str            # repo-relative file owning the object
    scope: str           # class name, or "<module>" for module globals
    attr: str
    ownership: str       # "lock:<label>" | "owner:<root-name>" |
                         # "immutable-after-init" | "queue-transferred" |
                         # "atomic"
    notes: str = ""

    @property
    def lock_label(self) -> Optional[str]:
        if self.ownership.startswith("lock:"):
            return self.ownership.split(":", 1)[1]
        return None

    @property
    def owner_root(self) -> Optional[str]:
        if self.ownership.startswith("owner:"):
            return self.ownership.split(":", 1)[1]
        return None


THREAD_ROOTS: Tuple[ThreadRoot, ...] = (
    # ------------------------------------------------------------- server/
    ThreadRoot(
        name="db-writer",
        path="nice_tpu/server/writer.py",
        spawn_scope="WriteActor.__init__",
        entries=("WriteActor._run",),
        role="writer-actor",
        locks=("server.db.Db._lock",),
        notes="single mutator of the ledger; futures resolve only after "
              "the batch txn commits (accepted => durable)",
    ),
    ThreadRoot(
        name="field-queue-refill",
        path="nice_tpu/server/field_queue.py",
        spawn_scope="FieldQueue.start",
        entries=("FieldQueue._refill_loop",),
        role="producer",
        locks=("server.field_queue.FieldQueue._lock", "server.db.Db._lock"),
        notes="started from __init__ on a primary; a standby defers start() "
              "until promotion (refills would mutate the replicated ledger)",
    ),
    ThreadRoot(
        name="repl-applier",
        path="nice_tpu/server/repl.py",
        spawn_scope="ReplApplier.__init__",
        entries=("ReplApplier._run",),
        role="collector",
        locks=("server.repl.ReplState._lock",),
        notes="standby op-log puller; every replica mutation goes through "
              "writer.call so the DB writer stays the single mutator",
    ),
    ThreadRoot(
        name="async-workers",
        path="nice_tpu/server/async_core.py",
        spawn_scope="AsyncHTTPServer.__init__",
        entries=(),
        role="worker-pool",
        kind="pool",
        notes="run_in_executor offload target; handlers run here, never "
              "on the selector loop",
    ),
    ThreadRoot(
        name="async-loop",
        path="nice_tpu/server/async_core.py",
        spawn_scope="AsyncHTTPServer.serve_forever",
        entries=("AsyncHTTPServer.serve_forever",),
        role="event-loop",
        kind="loop",
        may_block=False,
        notes="takes over the calling thread (mark_loop_thread); L1/R3 "
              "forbid blocking work here",
    ),
    # ---------------------------------------------------------------- ops/
    ThreadRoot(
        name="engine-collector",
        path="nice_tpu/ops/engine.py",
        spawn_scope="_Collector.__init__",
        entries=("_Collector._run",),
        role="collector",
        notes="runtime thread name is the dynamic collector label",
    ),
    ThreadRoot(
        name="mesh-feed",
        path="nice_tpu/ops/engine.py",
        spawn_scope="_SliceFeed.__init__",
        entries=("_SliceFeed._fill",),
        role="producer",
    ),
    ThreadRoot(
        name="niceonly-msd",
        path="nice_tpu/ops/engine.py",
        spawn_scope="_niceonly_pallas",
        entries=("_niceonly_pallas.<locals>.produce",),
        role="producer",
    ),
    ThreadRoot(
        name="niceonly-msd-pool",
        path="nice_tpu/ops/engine.py",
        spawn_scope="_niceonly_pallas.<locals>.produce",
        entries=(),
        role="worker-pool",
        kind="pool",
        notes="scoped with-block pool inside the producer",
    ),
    ThreadRoot(
        name="native-detailed-pool",
        path="nice_tpu/ops/engine.py",
        spawn_scope="_native_detailed",
        entries=(),
        role="worker-pool",
        kind="pool",
        notes="scoped with-block pool for host-native compute",
    ),
    ThreadRoot(
        name="native-niceonly-pool",
        path="nice_tpu/ops/engine.py",
        spawn_scope="_native_niceonly",
        entries=(),
        role="worker-pool",
        kind="pool",
        notes="scoped with-block pool for host-native compute",
    ),
    # ---------------------------------------------------------------- obs/
    ThreadRoot(
        name="nice-history",
        path="nice_tpu/obs/history.py",
        spawn_scope="maybe_start_sampler",
        entries=("maybe_start_sampler.<locals>._run",),
        role="periodic",
        locks=("obs.history._sampler_lock", "obs.history.HistoryStore._lock"),
    ),
    ThreadRoot(
        name="nice-memwatch",
        path="nice_tpu/obs/memwatch.py",
        spawn_scope="maybe_start_sampler",
        entries=("maybe_start_sampler.<locals>._run",),
        role="periodic",
        locks=("obs.memwatch._sampler_lock", "obs.memwatch._lock"),
        notes="client/daemon resource sampler (NICE_TPU_MEMWATCH_SECS=0 "
              "means the thread is never created); the server samples on "
              "the writer periodic instead",
    ),
    ThreadRoot(
        name="nice-pyprof",
        path="nice_tpu/obs/pyprof.py",
        spawn_scope="maybe_start",
        entries=("maybe_start.<locals>._run",),
        role="periodic",
        locks=("obs.pyprof._started_lock", "obs.pyprof._lock"),
        notes="statistical wall-clock sampler over sys._current_frames() "
              "(NICE_TPU_PYPROF_HZ=0 means the thread is never created)",
    ),
    ThreadRoot(
        name="nice-metrics-httpd",
        path="nice_tpu/obs/serve.py",
        spawn_scope="serve_metrics",
        entries=(),
        role="http-server",
        kind="http-server",
        notes="per-connection handler threads from ThreadingHTTPServer",
    ),
    ThreadRoot(
        name="nice-metrics",
        path="nice_tpu/obs/serve.py",
        spawn_scope="serve_metrics",
        entries=(),
        role="http-server",
        locks=("obs.serve._started_lock",),
        notes="runs stdlib serve_forever",
    ),
    ThreadRoot(
        name="legacy-httpd",
        path="nice_tpu/server/app.py",
        spawn_scope="serve",
        entries=(),
        role="http-server",
        kind="http-server",
        notes="legacy NICE_TPU_SERVER_CORE=thread core; per-connection "
              "handler threads",
    ),
    # ------------------------------------------------------------- client/
    ThreadRoot(
        name="nice-api-pool",
        path="nice_tpu/client/api_client.py",
        spawn_scope="AsyncApi.__init__",
        entries=(),
        role="worker-pool",
        kind="pool",
        notes="claim/submit overlap pipeline; futures consumed by the "
              "client main loop",
    ),
    ThreadRoot(
        name="nice-prefetch",
        path="nice_tpu/client/main.py",
        spawn_scope="_prefetch_on_claim.<locals>._cb",
        entries=("_prefetch_on_claim.<locals>._cb.<locals>._warm_all",),
        role="helper",
    ),
    ThreadRoot(
        name="telemetry-report",
        path="nice_tpu/client/main.py",
        spawn_scope="_TelemetryReporter.__init__",
        entries=("_TelemetryReporter._run",),
        role="periodic",
    ),
    ThreadRoot(
        name="claim-renew",
        path="nice_tpu/client/main.py",
        spawn_scope="_ClaimRenewer.__init__",
        entries=("_ClaimRenewer._run",),
        role="periodic",
    ),
    ThreadRoot(
        name="block-renew",
        path="nice_tpu/client/main.py",
        spawn_scope="_BlockRenewer.__init__",
        entries=("_BlockRenewer._run",),
        role="periodic",
    ),
    # -------------------------------------------------------------- sched/
    ThreadRoot(
        name="sched-slo",
        path="nice_tpu/sched/scheduler.py",
        spawn_scope="MultiTenantScheduler.start_slo_thread",
        entries=("MultiTenantScheduler.start_slo_thread.<locals>._slo_run",),
        role="periodic",
        locks=(
            "sched.scheduler.MultiTenantScheduler._lock",
            "obs.slo.SloEngine._lock",
            "obs.history.HistoryStore._lock",
        ),
        notes="per-tenant SLO burn evaluation for long runs; tests drive "
              "_slo_tick synchronously instead",
    ),
    # -------------------------------------------------------------- utils/
    ThreadRoot(
        name="platform-probe",
        path="nice_tpu/utils/platform.py",
        spawn_scope="probe_backend",
        entries=("probe_backend.<locals>.probe",),
        role="probe",
        notes="daemon probe joined with a timeout; may outlive the join",
    ),
    # ------------------------------------------------------------ scripts/
    ThreadRoot(
        name="crash-resume-httpd",
        path="scripts/crash_resume_smoke.py",
        spawn_scope="main",
        entries=(),
        role="helper",
        notes="smoke-test server thread (stdlib serve_forever)",
    ),
    ThreadRoot(
        name="telemetry-smoke-httpd",
        path="scripts/telemetry_smoke.py",
        spawn_scope="_fleet_smoke",
        entries=(),
        role="helper",
        notes="smoke-test server thread (stdlib serve_forever)",
    ),
    ThreadRoot(
        name="perf-gate-httpd",
        path="scripts/perf_gate.py",
        spawn_scope="run_observatory",
        entries=(),
        role="helper",
        notes="observatory server thread (stdlib serve_forever)",
    ),
    ThreadRoot(
        name="memprof-smoke-httpd",
        path="scripts/memprof_smoke.py",
        spawn_scope="main",
        entries=(),
        role="helper",
        notes="smoke-test server thread (stdlib serve_forever); named so "
              "the pyprof attribution check can account for it",
    ),
    ThreadRoot(
        name="sched-smoke-httpd",
        path="scripts/sched_smoke.py",
        spawn_scope="_start_server",
        entries=(),
        role="helper",
        notes="smoke-test server thread (stdlib serve_forever)",
    ),
    ThreadRoot(
        name="critpath-smoke-client",
        path="scripts/critpath_smoke.py",
        spawn_scope="main",
        entries=("_client_worker",),
        role="helper",
        notes="concurrent smoke clients (claim + scalar submit over HTTP); "
              "joined with a timeout before the critpath assertions",
    ),
)


LOCK_SPECS: Tuple[LockSpec, ...] = (
    # may_block_under=True is reserved for locks that exist to serialize a
    # blocking resource — holding them across I/O is the point, not a bug.
    LockSpec("server.db.Db._lock", "sqlite connection + ledger txns",
             may_block_under=True),
    LockSpec("server.db.Db._pool_lock", "read-connection pool",
             may_block_under=True),
    LockSpec("server.app.ApiContext._inflight_lock",
             "in-flight submission dedup set"),
    LockSpec("server.app.ApiContext._status_cache_lock",
             "status-cache dict + generation counter"),
    LockSpec("server.async_core.TokenBucketLimiter._lock",
             "token-bucket counters"),
    LockSpec("server.trust.TrustLedger._lock", "trust score cache"),
    LockSpec("server.field_queue.FieldQueue._lock",
             "refill inventory + wanted flag"),
    LockSpec("ops.adaptive_floor.AdaptiveFloor._lock", "controller state"),
    LockSpec("ops.adaptive_floor._CONTROLLERS_LOCK",
             "controller registry dict"),
    LockSpec("ops.compile_cache._lock", "compiled-fn cache",
             may_block_under=True),
    LockSpec("ops.autotune._lock", "autotune measurement cache",
             may_block_under=True),
    LockSpec("ops.engine._mesh_cache_lock",
             "device-tuple -> mesh cache + generation counter"),
    LockSpec("faults.injector.FaultPlan._lock", "fault plan counters"),
    LockSpec("faults.injector._plan_lock", "active plan slot"),
    LockSpec("obs.telemetry._lock", "telemetry buffer"),
    LockSpec("obs.history.HistoryStore._lock", "history ring",
             may_block_under=True),
    LockSpec("obs.history._sampler_lock", "sampler once-guard"),
    LockSpec("obs.trace._lock", "trace ring"),
    LockSpec("obs.metrics._Metric._lock", "metric cells"),
    LockSpec("obs.metrics.Registry._lock", "metric registry"),
    LockSpec("obs.slo.SloEngine._lock", "SLO windows"),
    LockSpec("obs.stepprof._state_lock", "stepprof install state"),
    LockSpec("obs.stepprof.StepProfile._lock", "step ring"),
    LockSpec("obs.flight.FlightRecorder._lock", "flight ring"),
    LockSpec("obs.flight._install_lock", "recorder install slot"),
    LockSpec("obs.anomaly.AnomalyEngine._lock", "anomaly windows"),
    LockSpec("obs.memwatch._lock", "watched-path table + last sample",
             may_block_under=True),
    LockSpec("obs.memwatch._sampler_lock", "memwatch sampler once-guard"),
    LockSpec("obs.pyprof._lock", "folded-stack tables + sample counters"),
    LockSpec("obs.pyprof._started_lock", "pyprof sampler once-guard"),
    LockSpec("obs.serve._started_lock", "metrics-server once-guard"),
    LockSpec("obs.journal._client_lock", "journal client slot",
             may_block_under=True),
    LockSpec("parallel.mesh._dead_lock", "dead-device set"),
    LockSpec("parallel.mesh.OccupancyMeter._lock",
             "busy-interval accumulator + observation window"),
    LockSpec("sched.scheduler.MultiTenantScheduler._lock",
             "per-tenant deficit/skip/boost maps + run counters"),
    LockSpec("parallel.mesh._step_lock", "step-fn cache"),
    LockSpec("parallel.mesh._DISPATCH_LOCK", "collective dispatch",
             may_block_under=True),
    LockSpec("native._build_lock", "native extension build",
             may_block_under=True),
    LockSpec("client.main.progress_cb.lock", "progress line state"),
    LockSpec("obs.stream.StreamHub._lock",
             "subscriber table + drop/eviction counters; publish never "
             "blocks under it (bounded put_nowait only)"),
    LockSpec("obs.critpath.CritpathEngine._lock",
             "snapshot cache + bottleneck-shift state"),
    LockSpec("server.app.ApiContext._stream_stage_lock",
             "journal rows staged for post-commit stream publish"),
    LockSpec("server.repl.ReplState._lock",
             "role/epoch/fence cache + standby registry + applied-seq gauges"),
    LockSpec("client.api_client._epoch_lock",
             "last-seen replication epoch stamped on outgoing writes"),
    LockSpec("client.api_client._dead_hosts_lock",
             "dead-endpoint marks used to evict pooled keep-alive sockets"),
    LockSpec("client.api_client._failover_lock",
             "sticky per-server-list failover cursor"),
)


SHARED_STATE: Tuple[SharedState, ...] = (
    # server/app.py — the status cache is the canonical R5 subject: reads
    # and the generation check are under the lock, the build is not.
    SharedState("nice_tpu/server/app.py", "ApiContext", "_status_cache",
                "lock:server.app.ApiContext._status_cache_lock"),
    SharedState("nice_tpu/server/app.py", "ApiContext", "_status_cache_gen",
                "lock:server.app.ApiContext._status_cache_lock",
                notes="invalidation generation; bumped on every invalidate "
                      "so a stale rebuild cannot store over it"),
    SharedState("nice_tpu/server/app.py", "ApiContext", "_inflight",
                "lock:server.app.ApiContext._inflight_lock"),
    # server/writer.py — ownership by construction: the queue transfers
    # batches into the writer thread, which alone resolves futures.
    SharedState("nice_tpu/server/writer.py", "WriteActor", "_q",
                "queue-transferred"),
    SharedState("nice_tpu/server/writer.py", "WriteActor", "_closed",
                "atomic",
                notes="single bool flip read by submitters, set on close"),
    SharedState("nice_tpu/server/writer.py", "WriteActor", "_periodics",
                "owner:db-writer",
                notes="periodic schedule registered before start, then "
                      "driven only by the writer loop"),
    # server/trust.py — the peek_known pattern: cache reads and writes both
    # under the ledger lock.
    SharedState("nice_tpu/server/trust.py", "TrustLedger", "_cache",
                "lock:server.trust.TrustLedger._lock"),
    # server/field_queue.py
    SharedState("nice_tpu/server/field_queue.py", "FieldQueue", "_niceonly",
                "lock:server.field_queue.FieldQueue._lock"),
    SharedState("nice_tpu/server/field_queue.py", "FieldQueue",
                "_detailed_thin",
                "lock:server.field_queue.FieldQueue._lock"),
    # server/repl.py — cached repl_meta mirror: HTTP workers read role/epoch
    # on every request; the applier and promotion path write.
    SharedState("nice_tpu/server/repl.py", "ReplState", "_role",
                "lock:server.repl.ReplState._lock"),
    SharedState("nice_tpu/server/repl.py", "ReplState", "_epoch",
                "lock:server.repl.ReplState._lock"),
    SharedState("nice_tpu/server/repl.py", "ReplState", "_fenced",
                "lock:server.repl.ReplState._lock",
                notes="sticky: once a newer client epoch is seen the deposed "
                      "primary rejects every later write with 410"),
    SharedState("nice_tpu/server/repl.py", "ReplState", "_standbys",
                "lock:server.repl.ReplState._lock"),
    # client/api_client.py — module-level failover state shared by the main
    # thread and the telemetry reporter.
    SharedState("nice_tpu/client/api_client.py", "<module>", "_last_epoch",
                "lock:client.api_client._epoch_lock"),
    SharedState("nice_tpu/client/api_client.py", "<module>", "_dead_hosts",
                "lock:client.api_client._dead_hosts_lock"),
    SharedState("nice_tpu/client/api_client.py", "<module>", "_failover_idx",
                "lock:client.api_client._failover_lock"),
    # ops/engine.py — the mesh cache rebuilt on elastic downshift.
    SharedState("nice_tpu/ops/engine.py", "<module>", "_MESH_CACHE",
                "lock:ops.engine._mesh_cache_lock"),
    SharedState("nice_tpu/ops/engine.py", "<module>", "_MESH_CACHE_GEN",
                "lock:ops.engine._mesh_cache_lock",
                notes="downshift generation; a rebuild that started before "
                      "an invalidation must not repopulate the cache"),
    # parallel/mesh.py
    SharedState("nice_tpu/parallel/mesh.py", "<module>", "_STEP_CACHE",
                "lock:parallel.mesh._step_lock"),
    # obs/history.py
    SharedState("nice_tpu/obs/history.py", "<module>", "_sampler_started",
                "lock:obs.history._sampler_lock"),
    # obs/memwatch.py — watched paths registered by wiring code, read by
    # whichever host drives sampling (thread or writer periodic).
    SharedState("nice_tpu/obs/memwatch.py", "<module>", "_watched",
                "lock:obs.memwatch._lock"),
    SharedState("nice_tpu/obs/memwatch.py", "<module>", "_last_summary",
                "lock:obs.memwatch._lock"),
    SharedState("nice_tpu/obs/memwatch.py", "<module>", "_sampler_started",
                "lock:obs.memwatch._sampler_lock"),
    # obs/pyprof.py — the sampler writes the tables; HTTP handlers and the
    # telemetry reporter read them.
    SharedState("nice_tpu/obs/pyprof.py", "<module>", "_tables",
                "lock:obs.pyprof._lock"),
    SharedState("nice_tpu/obs/pyprof.py", "<module>", "_root_samples",
                "lock:obs.pyprof._lock"),
    SharedState("nice_tpu/obs/pyprof.py", "<module>", "_total_samples",
                "lock:obs.pyprof._lock"),
    SharedState("nice_tpu/obs/pyprof.py", "<module>", "_distinct_stacks",
                "lock:obs.pyprof._lock"),
    SharedState("nice_tpu/obs/pyprof.py", "<module>", "_started",
                "lock:obs.pyprof._started_lock"),
    # sched/scheduler.py — the run loop mutates these while the sched-slo
    # periodic and stats() readers look on.
    SharedState("nice_tpu/sched/scheduler.py", "MultiTenantScheduler",
                "_boost",
                "lock:sched.scheduler.MultiTenantScheduler._lock"),
    SharedState("nice_tpu/sched/scheduler.py", "MultiTenantScheduler",
                "_deficit",
                "lock:sched.scheduler.MultiTenantScheduler._lock"),
    SharedState("nice_tpu/sched/scheduler.py", "MultiTenantScheduler",
                "_skipped",
                "lock:sched.scheduler.MultiTenantScheduler._lock"),
    SharedState("nice_tpu/sched/scheduler.py", "MultiTenantScheduler",
                "_exhausted",
                "lock:sched.scheduler.MultiTenantScheduler._lock"),
)


def roots_by_site() -> Dict[Tuple[str, str, str], Tuple[ThreadRoot, ...]]:
    """(path, spawn_scope, kind) -> registered roots at that site."""
    out: Dict[Tuple[str, str, str], list] = {}
    for root in THREAD_ROOTS:
        out.setdefault((root.path, root.spawn_scope, root.kind),
                       []).append(root)
    return {k: tuple(v) for k, v in out.items()}


def lock_spec(label: str) -> Optional[LockSpec]:
    for spec in LOCK_SPECS:
        if spec.label == label:
            return spec
    return None


def shared_state_for(path: str, scope: str,
                     attr: str) -> Optional[SharedState]:
    for decl in SHARED_STATE:
        if decl.path == path and decl.scope == scope and decl.attr == attr:
            return decl
    return None
