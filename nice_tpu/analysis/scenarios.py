"""Targeted schedex scenarios for the coordination plane.

Each scenario names the two (or more) thread roots it crosses, builds
the shared objects (real production objects where practical, faithful
models otherwise), and states the invariant that every interleaving
must preserve.  Scenarios come in pairs where a race was fixed:

* the real-code scenario (``expect = "pass"``) drives the production
  functions and must hold under *every* explored schedule — this is
  the regression test the ``nicelint: allow R5`` comments in
  server/app.py and ops/engine.py point at;
* a ``*_prefix`` twin (``expect = "race"``) replays the pre-fix body
  against the same invariant and must FAIL under at least one schedule
  within the k<=2 preemption bound — proving the explorer can actually
  see the window the fix closed.

``racy_counter`` is the permanently-racy calibration fixture: if the
explorer ever stops catching it, the explorer is broken, not the code.
"""

from __future__ import annotations

import time

from nice_tpu.analysis import schedex
from nice_tpu.utils import lockdep


class Scenario:
    scenario_name = "?"
    expect = "pass"  # or "race" for pre-fix twins / calibration fixtures

    def build(self, sched: schedex.Scheduler):
        raise NotImplementedError

    def check(self) -> None:
        pass

    def cleanup(self) -> None:
        pass


# ---------------------------------------------------------------------------
# status cache: writer batch / lease sweep invalidation vs. fleet rebuild
# (threads crossed: legacy-httpd request handler vs. db-writer periodics)


class _StatusCacheBase(Scenario):
    """Shared wiring: a skeletal ApiContext whose status-cache lock is a
    schedex lock (built through the lockdep factory hook), with
    build_fleet_block patched to read a mutable source-of-truth."""

    def _wire(self, sched: schedex.Scheduler):
        from nice_tpu.server import app
        self._app = app
        ctx = object.__new__(app.ApiContext)
        ctx.status_cache_ttl = 300.0
        ctx._status_cache = {}
        ctx._status_cache_gen = 0
        with schedex.instrument(sched):
            ctx._status_cache_lock = lockdep.make_lock(
                "server.app.ApiContext._status_cache_lock")
        self.ctx = ctx
        self.source = {"value": 1}
        self._orig_build = app.build_fleet_block
        app.build_fleet_block = lambda _ctx: {"value": self.source["value"]}
        return ctx

    def _writer(self):
        # Models a write landing: mutate source of truth, then
        # invalidate — the real "accepted => durable" ordering.
        self.source["value"] = 2
        self.ctx.invalidate_status_cache()

    def check(self) -> None:
        final = self.ctx.cached_fleet_block()
        assert final["value"] == 2, (
            f"stale fleet block served after invalidation: {final} "
            f"(source={self.source})")

    def cleanup(self) -> None:
        if getattr(self, "_orig_build", None) is not None:
            self._app.build_fleet_block = self._orig_build
            self._orig_build = None


class StatusCacheInvalidateVsRebuild(_StatusCacheBase):
    """Real ApiContext.cached_fleet_block vs. invalidate_status_cache."""

    scenario_name = "status_cache_invalidate_vs_rebuild"
    expect = "pass"

    def build(self, sched):
        ctx = self._wire(sched)
        return [
            ("status-reader", ctx.cached_fleet_block),
            ("status-writer", self._writer),
        ]


class StatusCachePreFix(_StatusCacheBase):
    """The pre-fix body: unconditional store after building outside the
    lock.  A preemption between build and store caches the stale block."""

    scenario_name = "status_cache_prefix"
    expect = "race"

    def build(self, sched):
        ctx = self._wire(sched)

        def prefix_cached_fleet_block():
            now = time.monotonic()
            with ctx._status_cache_lock:
                entry = ctx._status_cache.get("fleet")
                if entry is not None and now - entry[0] < ctx.status_cache_ttl:
                    return entry[1]
            block = self._app.build_fleet_block(ctx)
            with ctx._status_cache_lock:
                ctx._status_cache["fleet"] = (time.monotonic(), block)
            return block

        return [
            ("status-reader", prefix_cached_fleet_block),
            ("status-writer", self._writer),
        ]


# ---------------------------------------------------------------------------
# mesh cache: feed/dispatch rebuild vs. elastic downshift
# (threads crossed: nice-dispatch callers vs. the downshift path)


class _MeshCacheBase(Scenario):
    """Shared wiring: the real ops.engine mesh-cache globals with the
    module lock swapped for a schedex lock and make_mesh stubbed.

    The stubbed make_mesh stamps each mesh with the cache generation at
    build time, so the invariant can state exactly what the fix
    guarantees: a store never survives an invalidation that happened
    mid-build (an entry whose build-gen predates the final generation
    is the downshift-masking bug).  A dispatch whose *argument* tuple
    is stale but whose build started after the downshift is the
    caller's live_devices re-read to catch, not the cache's."""

    def _wire(self, sched: schedex.Scheduler):
        from nice_tpu.ops import engine
        from nice_tpu.parallel import mesh as pmesh
        self._engine = engine
        self._pmesh = pmesh
        engine._MESH_CACHE.clear()
        engine._MESH_CACHE_GEN = 0
        self._orig_lock = engine._mesh_cache_lock
        engine._mesh_cache_lock = schedex.Lock(
            sched, "ops.engine._mesh_cache_lock")
        self._orig_make = pmesh.make_mesh
        pmesh.make_mesh = lambda devs: (
            "mesh", tuple(devs), engine._MESH_CACHE_GEN)
        # Source of truth for which devices are alive; the downshift
        # marks deaths *before* invalidating, like the real engine.
        self.alive = {0, 1, 2, 3}
        self.survivors = (0, 1)

    def _downshift(self):
        self.alive = set(self.survivors)
        self._engine._invalidate_mesh_cache()
        self._engine._cached_mesh(tuple(sorted(self.alive)))

    def check(self) -> None:
        cache = dict(self._engine._MESH_CACHE)
        final_gen = self._engine._MESH_CACHE_GEN
        assert self.survivors in cache, (
            f"downshift rebuild lost: survivor mesh missing from {cache}")
        stale = {k: v for k, v in cache.items() if v[2] != final_gen}
        assert not stale, (
            f"entries built before an invalidation survived it "
            f"(final gen {final_gen}): {stale}")

    def cleanup(self) -> None:
        if getattr(self, "_engine", None) is None:
            return
        self._engine._mesh_cache_lock = self._orig_lock
        self._pmesh.make_mesh = self._orig_make
        self._engine._MESH_CACHE.clear()
        self._engine._MESH_CACHE_GEN = 0
        self._engine = None


class MeshCacheClearVsRebuild(_MeshCacheBase):
    """Real engine._cached_mesh vs. _invalidate_mesh_cache."""

    scenario_name = "mesh_cache_clear_vs_rebuild"
    expect = "pass"

    def build(self, sched):
        self._wire(sched)

        def dispatch():
            self._engine._cached_mesh(tuple(sorted(self.alive)))

        return [("nice-dispatch", dispatch), ("downshift", self._downshift)]


class MeshCachePreFix(_MeshCacheBase):
    """The pre-fix lru_cache shape: whatever was built gets stored, even
    if a downshift invalidated mid-build."""

    scenario_name = "mesh_cache_prefix"
    expect = "race"

    def build(self, sched):
        self._wire(sched)
        engine = self._engine

        def prefix_cached_mesh(devs):
            with engine._mesh_cache_lock:
                mesh = engine._MESH_CACHE.get(devs)
            if mesh is not None:
                return mesh
            from nice_tpu.parallel import mesh as pmesh
            built = pmesh.make_mesh(list(devs))
            with engine._mesh_cache_lock:
                return engine._MESH_CACHE.setdefault(devs, built)

        def dispatch():
            prefix_cached_mesh(tuple(sorted(self.alive)))

        return [("nice-dispatch", dispatch), ("downshift", self._downshift)]


# ---------------------------------------------------------------------------
# lease sweep vs. concurrent submit (modeled on the server's claim flow)


class _LeaseBase(Scenario):
    def _wire(self, sched):
        self.lock = schedex.Lock(sched, "model.lease_table")
        self.leases = {"claim-1": "field-A"}
        self.accepted: list[str] = []
        self.requeued: list[str] = []

    def _submit(self):
        # The disciplined submit path: claim-check and accept are one
        # atomic step, mirroring the 409-on-expired-lease contract.
        with self.lock:
            fid = self.leases.pop("claim-1", None)
            if fid is not None:
                self.accepted.append(fid)

    def check(self) -> None:
        hits = [("accepted", f) for f in self.accepted]
        hits += [("requeued", f) for f in self.requeued]
        assert len(hits) == 1, (
            f"field-A must land exactly once (accept XOR requeue), got {hits}")


class LeaseSweepVsSubmit(_LeaseBase):
    """Disciplined sweep: expiry-check and requeue are one atomic step."""

    scenario_name = "lease_sweep_vs_submit"
    expect = "pass"

    def build(self, sched):
        self._wire(sched)

        def sweep():
            with self.lock:
                fid = self.leases.pop("claim-1", None)
                if fid is not None:
                    self.requeued.append(fid)

        return [("lease-sweeper", sweep), ("submit-handler", self._submit)]


class LeaseSweepPreFix(_LeaseBase):
    """Check-then-act sweep: expiry decided in one lock block, requeue
    done in another — a submit in the window double-delivers the field."""

    scenario_name = "lease_sweep_prefix"
    expect = "race"

    def build(self, sched):
        self._wire(sched)

        def sweep():
            with self.lock:
                expired = "claim-1" in self.leases
            if expired:
                with self.lock:
                    self.requeued.append("field-A")
                    self.leases.pop("claim-1", None)

        return [("lease-sweeper", sweep), ("submit-handler", self._submit)]


# ---------------------------------------------------------------------------
# spool replay vs. claim expiry (modeled on crash-recovery redelivery)


class SpoolReplayVsClaimExpiry(Scenario):
    """Crash-recovery spool replay racing the lease sweeper redelivering
    an expired claim for the same field: delivery must be exactly-once,
    which holds because mark-and-deliver is one atomic step."""

    scenario_name = "spool_replay_vs_claim_expiry"
    expect = "pass"

    def build(self, sched):
        self.lock = schedex.Lock(sched, "model.delivery_ledger")
        self.delivered: dict[str, str] = {}
        self.duplicates: list[tuple[str, str]] = []

        def deliver(fid, src):
            with self.lock:
                if fid in self.delivered:
                    self.duplicates.append((fid, src))
                    return
                self.delivered[fid] = src

        def replay():
            for fid in ("field-1", "field-2"):
                deliver(fid, "spool-replay")

        def expiry():
            deliver("field-1", "lease-expiry")

        return [("spool-replayer", replay), ("lease-sweeper", expiry)]

    def check(self) -> None:
        assert not self.duplicates or all(
            f in self.delivered for f, _ in self.duplicates), "ledger corrupt"
        assert set(self.delivered) == {"field-1", "field-2"}, (
            f"lost fields: delivered={self.delivered}")


# ---------------------------------------------------------------------------
# replication: promotion (epoch bump + fence) vs. an in-flight write
# (threads crossed: async-workers write handler vs. the promotion path)


class _PromoteBase(Scenario):
    """Models the epoch fence on a deposed primary: ReplState keeps the
    role/epoch/fence cache under one lock, and the write path's
    fence-check must be atomic with stamping the op into the log — a
    check in one lock block and an append in another lets a promotion
    land between them and a dead-epoch write slip past the fence."""

    def _wire(self, sched):
        self.lock = schedex.Lock(sched, "server.repl.ReplState._lock")
        self.state = {"epoch": 1, "fenced": False}
        self.log: list[dict] = []
        self.rejected: list[int] = []

    def _promote(self):
        # A resurrected client stamps X-Nice-Epoch from the promoted
        # standby: the deposed primary fences itself and the cluster
        # epoch moves on — one atomic step, like ReplState.note_client_epoch.
        with self.lock:
            self.state["fenced"] = True
            self.state["epoch"] += 1

    def check(self) -> None:
        fence_seq = next(
            (n for n, op in enumerate(self.log) if op["post_fence"]), None)
        assert fence_seq is None, (
            f"write landed on the deposed primary after the fence: "
            f"{self.log} (rejected={self.rejected})")


class PromoteVsInflightWrite(_PromoteBase):
    """Disciplined write path: fence-check and op-append are one atomic
    step under the ReplState lock, so the 410 answer and the op log can
    never disagree about which side of the promotion a write landed on."""

    scenario_name = "promote_vs_inflight_write"
    expect = "pass"

    def build(self, sched):
        self._wire(sched)

        def write():
            with self.lock:
                if self.state["fenced"]:
                    self.rejected.append(410)
                    return
                self.log.append({
                    "epoch": self.state["epoch"],
                    "post_fence": self.state["fenced"],
                })

        return [("write-handler", write), ("promoter", self._promote)]


class PromoteVsInflightWritePreFix(_PromoteBase):
    """The split shape: fence checked in one lock block, op appended in
    another.  A promotion in the window fences the primary *after* it
    decided to accept — the double-canonicalization split-brain."""

    scenario_name = "promote_vs_inflight_write_prefix"
    expect = "race"

    def build(self, sched):
        self._wire(sched)

        def write():
            with self.lock:
                fenced = self.state["fenced"]
            if fenced:
                self.rejected.append(410)
                return
            with self.lock:
                self.log.append({
                    "epoch": self.state["epoch"],
                    "post_fence": self.state["fenced"],
                })

        return [("write-handler", write), ("promoter", self._promote)]


# ---------------------------------------------------------------------------
# client failover cursor: success store vs. concurrent rotation
# (threads crossed: worker request threads vs. telemetry reporter — the
# regression the ``nicelint: allow R5`` in client/api_client.py points at)


class _FailoverCursorBase(Scenario):
    """Models api_client._failover_idx: a request reads the cursor under
    the lock, runs its HTTP call outside it, then stores the index that
    worked.  A concurrent thread that rotated away from a now-dead
    server must not have its rotation clobbered by the older success."""

    def _wire(self, sched):
        self._sched = sched
        self.lock = schedex.Lock(sched, "client.api_client._failover_lock")
        self.idx = {"k": 0}
        self.gen = {"k": 0}
        # Source of truth the modeled HTTP call reads: which server answers.
        self.alive = {1: True, 2: True}

    def _pick(self) -> int:
        # The request itself: the first live server answers.
        return 1 if self.alive[1] else 2

    def _rotator(self):
        # Another thread's request just failed over: server 1 is dead,
        # server 2 answered.  Newer knowledge stores atomically and bumps
        # the generation (the invalidate_status_cache role in this pair).
        self.alive[1] = False
        self._sched.yield_point("rotator:dead")
        with self.lock:
            self.idx["k"] = 2
            self.gen["k"] += 1

    def check(self) -> None:
        assert self.idx["k"] == 2, (
            f"rotation away from the dead server was lost: cursor points "
            f"at {self.idx['k']} (gen={self.gen['k']})")


class FailoverCursorRotateVsStore(_FailoverCursorBase):
    """Generation-checked store: the stale success (server 1, observed
    before it died) can never overwrite the newer rotation to server 2."""

    scenario_name = "failover_cursor_rotate_vs_store"
    expect = "pass"

    def build(self, sched):
        self._wire(sched)

        def requester():
            # Success observed on whichever server was live at call time;
            # the gen check decides whether that (possibly stale) success
            # may stick once the request returns to store it.
            with self.lock:
                g = self.gen["k"]
            sched.yield_point("requester:pick")
            target = self._pick()
            sched.yield_point("requester:http")
            with self.lock:
                if self.gen["k"] == g:
                    self.idx["k"], self.gen["k"] = target, g + 1

        return [("requester", requester), ("rotator", self._rotator)]


class FailoverCursorPreFix(_FailoverCursorBase):
    """Unconditional store: a preemption between the requester's read
    and its store lets the stale success bury the rotation."""

    scenario_name = "failover_cursor_prefix"
    expect = "race"

    def build(self, sched):
        self._wire(sched)

        def requester():
            with self.lock:
                self.idx["k"] % 3
            target = self._pick()
            sched.yield_point("requester:http")
            with self.lock:
                self.idx["k"] = target

        return [("requester", requester), ("rotator", self._rotator)]


# ---------------------------------------------------------------------------
# calibration: a permanently-racy lost-update counter


class RacyCounter(Scenario):
    """Unlocked read-modify-write; any single preemption between the
    read and the write loses an update.  Must always be caught."""

    scenario_name = "racy_counter"
    expect = "race"

    def build(self, sched):
        self.state = {"n": 0}

        def bump(tag):
            for i in range(2):
                v = self.state["n"]
                sched.yield_point(f"{tag}:rmw{i}")
                self.state["n"] = v + 1

        return [("bump-a", lambda: bump("a")), ("bump-b", lambda: bump("b"))]

    def check(self) -> None:
        assert self.state["n"] == 4, (
            f"lost update: counter is {self.state['n']}, want 4")


SCENARIOS: dict[str, type[Scenario]] = {
    cls.scenario_name: cls
    for cls in (
        StatusCacheInvalidateVsRebuild,
        StatusCachePreFix,
        MeshCacheClearVsRebuild,
        MeshCachePreFix,
        LeaseSweepVsSubmit,
        LeaseSweepPreFix,
        SpoolReplayVsClaimExpiry,
        PromoteVsInflightWrite,
        PromoteVsInflightWritePreFix,
        FailoverCursorRotateVsStore,
        FailoverCursorPreFix,
        RacyCounter,
    )
}
