"""MXU-mapped big-int limb multiplication: banded Toeplitz ``dot_general``.

The carry-save multiply in ops/vector_engine.py grinds every 32x32->64
partial product through the VPU one elementwise ``mul32`` at a time. The
column sums it accumulates are exactly a 1-D convolution of the two
operands' digit vectors — the shape "Large Scale Distributed Linear Algebra
With Tensor Processing Units" (PAPERS.md) maps onto the MXU: express the
convolution as a banded Toeplitz matrix of shifted digit windows and
contract it against the other operand with one ``dot_general`` per column
band, accumulating in i32 on the systolic array instead of half-word
arithmetic on the VPU.

Digit split (chosen so the i32 accumulator provably cannot overflow and the
interval analysis in analysis/jaxrules/interval.py discharges it):

- the LONGER operand is quartered into 8-bit digits ``q`` (values in
  [0, 255], extracted in the u32 domain before the i32 cast);
- the SHORTER operand is halved into 16-bit digits ``h`` ([0, 65535]);
- output column ``t`` (worth 2^(8t)) is ``C_t = sum_j q[t - 2j] * h[j]`` —
  at most ``2 * short_limbs`` terms of at most ``255 * 65535`` each, so
  ``C_t <= 2 * short_limbs * 255 * 65535``, which fits i32 for every plan
  with ``limbs_n <= 64`` (bases far beyond the 510 sweep cap).

Reassembly feeds the 8-bit columns back into the SAME carry-save
(sums, wraps) representation as vector_engine (``_cs_add`` splitting each
column across its two overlapping u32 limbs, one deferred ``_cs_resolve``),
so results are bit-identical to ``mul_limbs``/``sqr_limbs`` under the
truncation-to-out_len contract: dropped columns and the final high spill
are all multiples of 2^(32*out_len).

All shapes are trace-time constants; limb entries may be any shape (1-D
(batch,) lanes from vector_engine or 2-D (rows, 128) Pallas tiles) — the
Toeplitz contraction batches over every leading axis. The engine arbitrates
MXU-vs-VPU per (mode, base, backend) through ``resolve_tuning``
(env NICE_TPU_MXU > autotuned ``use_mxu`` arm > default off).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from nice_tpu.ops.limbs import BasePlan
from nice_tpu.ops.vector_engine import U32, _cs_add, _cs_resolve

I32 = jnp.int32

# Output columns contracted per dot_general call. Bounds the Toeplitz
# operand at (..., BAND_COLS, halves) i32 per band — 16 keeps the band
# buffer small enough that the MXU arm's VMEM/RAM footprint is set by the
# batch axis the autotuner already sweeps.
BAND_COLS = 16

_DIGIT_MAX = 255   # 8-bit Toeplitz digits
_HALF_MAX = 65535  # 16-bit contraction halves


def accum_bound(short_limbs: int) -> int:
    """Worst-case column sum of the i32 dot_general accumulator: every one
    of the ``2 * short_limbs`` halves multiplies a maximal 8-bit digit.
    This is the DECLARED bound the J2 interval interpreter checks against
    the traced contraction (kernelspec dot_bound) — a theorem about the
    digit split, not a measured allowance."""
    return 2 * short_limbs * _DIGIT_MAX * _HALF_MAX


def supports_plan(plan: BasePlan) -> bool:
    """True when every MXU contraction this plan needs provably fits i32.

    The contraction depth is the half-limb count of the SHORTER operand of
    each product — ``n`` itself for both n*n and n^2*n — so the bound is
    set by ``plan.limbs_n`` alone."""
    return accum_bound(plan.limbs_n) < 2**31


def _digits8(limbs: list) -> jnp.ndarray:
    """Quarter u32 limbs into 8-bit digits, LS digit first, stacked on a new
    trailing axis. Masked in the u32 domain so every value is provably in
    [0, 255] before the i32 cast (a direct u32->i32 limb cast could go
    negative and sink the interval analysis)."""
    cols = []
    for limb in limbs:
        for k in range(4):
            cols.append(
                ((limb >> np.uint32(8 * k)) & np.uint32(0xFF)).astype(I32)
            )
    return jnp.stack(cols, axis=-1)


def _halves16(limbs: list) -> jnp.ndarray:
    """Halve u32 limbs into 16-bit digits on a new trailing axis (i32,
    provably in [0, 65535])."""
    cols = []
    for limb in limbs:
        for k in range(2):
            cols.append(
                ((limb >> np.uint32(16 * k)) & np.uint32(0xFFFF)).astype(I32)
            )
    return jnp.stack(cols, axis=-1)


def _column_sums(q: jnp.ndarray, h: jnp.ndarray, t_cols: int) -> jnp.ndarray:
    """All product columns ``C_t = sum_j q[..., t - 2j] * h[..., j]`` for
    ``t < t_cols`` as one i32 dot_general per BAND_COLS-column band.

    Per band, the HB shifted windows of the (zero-padded) digit vector are
    stacked into a (..., band, HB) Toeplitz operand and contracted against
    the halves on the trailing axis, batching over every leading axis —
    (batch,) jnp lanes and (rows, 128) Pallas tiles take the same path."""
    qa = q.shape[-1]
    hb = h.shape[-1]
    axis = q.ndim - 1
    # Left pad so window j's start (t - 2j) is never negative; right pad so
    # the last band's window end (left + t_cols) always exists.
    left = 2 * (hb - 1)
    width = left + max(qa, t_cols)
    pad = [(0, 0)] * (q.ndim - 1) + [(left, width - left - qa)]
    qp = jnp.pad(q, pad)
    nb = h.ndim - 1  # leading batch axes
    dims = ((nb + 1,), (nb,)), (tuple(range(nb)), tuple(range(nb)))
    bands = []
    for t0 in range(0, t_cols, BAND_COLS):
        bt = min(BAND_COLS, t_cols - t0)
        windows = [
            jax.lax.slice_in_dim(
                qp, left + t0 - 2 * j, left + t0 - 2 * j + bt, axis=axis
            )
            for j in range(hb)
        ]
        toe = jnp.stack(windows, axis=-1)  # (..., bt, HB)
        bands.append(
            jax.lax.dot_general(
                toe, h, dimension_numbers=dims, preferred_element_type=I32
            )
        )
    return jnp.concatenate(bands, axis=-1)  # (..., t_cols)


def _columns_to_limbs(c: jnp.ndarray, out_len: int) -> list:
    """Reassemble 8-bit column sums into ``out_len`` u32 limbs through the
    shared carry-save representation: column t (worth 2^(8t)) splits across
    limb t>>2 and — when t is not limb-aligned — the low bits of limb
    t>>2 + 1; one deferred ``_cs_resolve`` propagates carries. The i32->u32
    cast is exact (column sums are non-negative and < 2^31), and a spill
    past limb out_len-1 is a multiple of 2^(32*out_len) — dropped by the
    same truncation contract as mul_limbs."""
    zero = jnp.zeros(c.shape[:-1], U32)
    sums = [zero] * out_len
    wraps = [zero] * out_len
    for t in range(c.shape[-1]):
        k, s = divmod(8 * t, 32)
        if k >= out_len:
            break
        cu = c[..., t].astype(U32)
        _cs_add(sums, wraps, k, (cu << np.uint32(s)) if s else cu)
        if s and k + 1 < out_len:
            _cs_add(sums, wraps, k + 1, cu >> np.uint32(32 - s))
    return _cs_resolve(sums, wraps)


def mul_limbs_mxu(a: list, b: list, out_len: int) -> list:
    """MXU multiply with the same contract as vector_engine.mul_limbs:
    LSW-first limb lists in, ``a * b mod 2^(32*out_len)`` out, bit-identical
    limbs. The SHORTER operand supplies the 16-bit contraction halves
    (bounding the i32 accumulator — see ``accum_bound``); the longer one
    the 8-bit Toeplitz digits."""
    if len(b) > len(a):
        a, b = b, a
    assert accum_bound(len(b)) < 2**31, (len(a), len(b))
    q = _digits8(a)
    h = _halves16(b)
    # Columns past the full convolution are identically zero; columns past
    # 4*out_len only contribute multiples of 2^(32*out_len).
    t_cols = min(4 * out_len, q.shape[-1] + 2 * h.shape[-1] - 1)
    return _columns_to_limbs(_column_sums(q, h, t_cols), out_len)


def sqr_limbs_mxu(a: list, out_len: int) -> list:
    """Squaring through the general MXU multiply. The VPU path halves its
    multiply count by symmetry; on the MXU the symmetric products ride the
    same contraction, so no specialization is needed for bit-identity or
    throughput."""
    return mul_limbs_mxu(a, a, out_len)
