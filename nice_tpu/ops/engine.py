"""Field-processing engine: dispatches ranges to a backend and assembles
exact FieldResults.

Backends:
  "scalar" — the Python-int oracle (ops/scalar.py)
  "jax"    — the vectorized fixed-width engine (ops/vector_engine.py), jitted
             for CPU or a single TPU chip
  (the sharded multi-chip path lives in parallel/; Pallas kernels plug in as
   a drop-in replacement for the batch functions)

The JAX backends require the range to lie inside the base's valid range (the
fixed-width digit-extraction contract); out-of-range slivers — which occur
only in synthetic tests, never in server fields — fall back to the scalar
oracle per sub-range.
"""

from __future__ import annotations

from collections import deque

import numpy as np

from nice_tpu.core import base_range
from nice_tpu.core.types import (
    FieldResults,
    FieldSize,
    NiceNumberSimple,
    UniquesDistributionSimple,
)
from nice_tpu.ops import scalar
from nice_tpu.ops.limbs import get_plan, int_to_limbs
from nice_tpu.ops import vector_engine as ve

# Default lanes per device batch. Large enough to amortize dispatch, small
# enough to keep intermediates comfortably in HBM.
DEFAULT_BATCH_SIZE = 1 << 18

# Max batches in flight during pipelined dispatch: bounds live device buffers
# (and the runtime queue) so arbitrarily large fields run in constant memory.
DISPATCH_WINDOW = 32


def _clamp_to_base_range(range_: FieldSize, base: int):
    """Split range into (pre, core, post) where core is inside the base range."""
    br = base_range.get_base_range(base)
    if br is None:
        return (range_, None, None)
    lo = max(range_.start(), br[0])
    hi = min(range_.end(), br[1])
    if lo >= hi:
        return (range_, None, None)
    pre = FieldSize(range_.start(), lo) if range_.start() < lo else None
    core = FieldSize(lo, hi)
    post = FieldSize(hi, range_.end()) if hi < range_.end() else None
    return (pre, core, post)


def _split_for_jax(range_: FieldSize, base: int, scalar_fn):
    """Clamp to the base range; run scalar_fn on out-of-range slivers.

    Returns (core, sliver_results) where core may be None (range entirely
    outside the base range — caller should go fully scalar).
    """
    pre, core, post = _clamp_to_base_range(range_, base)
    slivers = [scalar_fn(part) for part in (pre, post) if part is not None]
    return core, slivers


def process_range_detailed(
    range_: FieldSize,
    base: int,
    backend: str = "jax",
    batch_size: int = DEFAULT_BATCH_SIZE,
) -> FieldResults:
    """Full histogram + near-miss list, exact, any backend."""
    if backend == "scalar":
        return scalar.process_range_detailed(range_, base)
    if backend != "jax":
        raise ValueError(f"unknown backend {backend!r}")

    core, slivers = _split_for_jax(
        range_, base, lambda part: scalar.process_range_detailed(part, base)
    )
    if core is None:
        return scalar.process_range_detailed(range_, base)

    plan = get_plan(base)
    hist = np.zeros(plan.base + 2, dtype=np.int64)
    nice_numbers: list[NiceNumberSimple] = []
    for sub in slivers:
        for d in sub.distribution:
            hist[d.num_uniques] += d.count
        nice_numbers.extend(sub.nice_numbers)

    # Dispatch batches asynchronously ahead of collection (the device queue
    # executes in order while the host keeps dispatching — the reference's
    # overlapped launch pipeline, client_process_gpu.rs:667-682). The window
    # bounds in-flight device buffers so arbitrarily large fields run in
    # constant memory.
    start = core.start()
    total = core.size()
    pending: deque = deque()

    def collect_one():
        batch_start, valid, start_limbs, bh, nm = pending.popleft()
        bh = np.asarray(bh, dtype=np.int64)
        bh[0] -= batch_size - valid  # remove tail-padding lanes from bin 0
        np.add(hist, bh, out=hist)
        if int(nm) > 0:
            # Rare path: re-derive per-lane uniques for this batch only.
            uniques = np.asarray(ve.uniques_batch(plan, batch_size, start_limbs))
            idxs = np.nonzero(uniques[:valid] > plan.near_miss_cutoff)[0]
            for i in idxs.tolist():
                nice_numbers.append(
                    NiceNumberSimple(
                        number=batch_start + i, num_uniques=int(uniques[i])
                    )
                )

    done = 0
    while done < total:
        valid = min(batch_size, total - done)
        batch_start = start + done
        start_limbs = int_to_limbs(batch_start, plan.limbs_n)
        bh, nm = ve.detailed_batch(plan, batch_size, start_limbs, np.int32(valid))
        pending.append((batch_start, valid, start_limbs, bh, nm))
        if len(pending) >= DISPATCH_WINDOW:
            collect_one()
        done += valid
    while pending:
        collect_one()

    nice_numbers.sort(key=lambda n: n.number)
    distribution = tuple(
        UniquesDistributionSimple(num_uniques=i, count=int(hist[i]))
        for i in range(1, base + 1)
    )
    return FieldResults(distribution=distribution, nice_numbers=tuple(nice_numbers))


def process_range_niceonly(
    range_: FieldSize,
    base: int,
    stride_table=None,
    backend: str = "jax",
    batch_size: int = DEFAULT_BATCH_SIZE,
) -> FieldResults:
    """Nice-number search. The jax backend currently runs the dense masked
    check over MSD-surviving sub-ranges; the stride-compacted device
    enumeration arrives with the Pallas niceonly kernel."""
    if backend == "scalar":
        return scalar.process_range_niceonly(range_, base, stride_table)
    if backend != "jax":
        raise ValueError(f"unknown backend {backend!r}")

    from nice_tpu.ops import msd_filter

    core, slivers = _split_for_jax(
        range_,
        base,
        lambda part: scalar.process_range_niceonly(part, base, stride_table),
    )
    if core is None:
        return scalar.process_range_niceonly(range_, base, stride_table)

    nice_numbers: list[NiceNumberSimple] = []
    for sub in slivers:
        nice_numbers.extend(sub.nice_numbers)

    plan = get_plan(base)
    pending: deque = deque()

    def collect_one():
        batch_start, valid, start_limbs, count = pending.popleft()
        if int(count) > 0:
            uniques = np.asarray(ve.uniques_batch(plan, batch_size, start_limbs))
            for i in np.nonzero(uniques[:valid] == base)[0].tolist():
                nice_numbers.append(
                    NiceNumberSimple(number=batch_start + i, num_uniques=base)
                )

    for sub_range in msd_filter.get_valid_ranges(core, base):
        start = sub_range.start()
        total = sub_range.size()
        done = 0
        while done < total:
            valid = min(batch_size, total - done)
            batch_start = start + done
            start_limbs = int_to_limbs(batch_start, plan.limbs_n)
            count = ve.niceonly_dense_batch(
                plan, batch_size, start_limbs, np.int32(valid)
            )
            pending.append((batch_start, valid, start_limbs, count))
            if len(pending) >= DISPATCH_WINDOW:
                collect_one()
            done += valid
    while pending:
        collect_one()

    nice_numbers.sort(key=lambda n: n.number)
    return FieldResults(distribution=(), nice_numbers=tuple(nice_numbers))
