"""Field-processing engine: dispatches ranges to a backend and assembles
exact FieldResults.

Backends:
  "scalar" — the Python-int oracle (ops/scalar.py)
  "jax"    — the vectorized fixed-width engine (ops/vector_engine.py), jitted
             for CPU or a single TPU chip
  (the sharded multi-chip path lives in parallel/; Pallas kernels plug in as
   a drop-in replacement for the batch functions)

The JAX backends require the range to lie inside the base's valid range (the
fixed-width digit-extraction contract); out-of-range slivers — which occur
only in synthetic tests, never in server fields — fall back to the scalar
oracle per sub-range.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import time
from collections import deque
from typing import NamedTuple

import numpy as np

from nice_tpu import faults
from nice_tpu import obs
from nice_tpu.obs import stepprof
from nice_tpu.core import base_range
from nice_tpu.core.types import (
    FieldResults,
    FieldSize,
    NiceNumberSimple,
    UniquesDistributionSimple,
)
from nice_tpu.ops import compile_cache
from nice_tpu.ops import pallas_engine as pe
from nice_tpu.ops import scalar
from nice_tpu.ops.limbs import get_plan, int_to_limbs, ints_to_limbs
from nice_tpu.ops import vector_engine as ve
from nice_tpu.utils import knobs
from nice_tpu.obs.series import (
    CKPT_BATCHES_SKIPPED,
    CKPT_RESTORES,
    ENGINE_AUDITS,
    ENGINE_BACKEND_DOWNGRADES,
    ENGINE_BATCH_KERNEL_SECONDS,
    ENGINE_DESCRIPTORS,
    ENGINE_DISPATCHES,
    ENGINE_DISPATCH_OCCUPANCY,
    ENGINE_FILTER_PRUNED,
    ENGINE_HOST_FALLBACK,
    ENGINE_NUMBERS,
    ENGINE_READBACK_BYTES,
    ENGINE_STATS_TRANSFERS,
    ENGINE_STRIDE_OCCUPANCY,
    ENGINE_SURVIVOR_OVERFLOW,
    MESH_FEED_IDLE,
    MESH_RESHARDS,
    MESH_RESHARD_SECONDS,
    MESH_SLICE_CURSOR,
)

log = logging.getLogger(__name__)

# Default lanes per device batch. Large enough to amortize dispatch, small
# enough to keep intermediates comfortably in HBM.
DEFAULT_BATCH_SIZE = 1 << 18

# Max batches in flight during pipelined dispatch: bounds live device buffers
# (and the runtime queue) so arbitrarily large fields run in constant memory.
DISPATCH_WINDOW = 32

# Megaloop: batch iterations fused into one device-resident lax.scan per
# dispatch (NICE_TPU_MEGALOOP_SEGMENT overrides; NICE_TPU_MEGALOOP=0 reverts
# to the per-batch feed). Each segment is one dispatch + one 4-byte readback
# instead of `segment` of each; the checkpoint cadence (segment boundaries)
# becomes the only forced sync. 8 amortizes the host RTT ~8x while keeping
# resume granularity at 8 * batch_size numbers.
MEGALOOP_SEGMENT_DEFAULT = 8

# Sub-batch size for the rare-path per-lane re-scan: small enough that the
# device->host uniques transfer stays modest even when the stats batch is 2^28.
RARE_SCAN_BATCH = 1 << 20

# On-device survivor-compaction output rows per rare-scan sub-batch. Near
# misses run ~1e-5 of lanes at production bases, so 4096 rows (32 KiB of
# readback, vs 4 MiB for the dense per-lane array) overflow only on
# accept-rich synthetic ranges — which fall back to the dense transfer for
# correctness (and count in nice_engine_survivor_overflow_total).
SURVIVOR_CAP = 4096

# In-flight strided descriptor groups: deep enough to hide the per-dispatch
# device round-trip latency behind compute (the axon tunnel adds tens of ms
# per result readback; each pending entry holds only the tiny (8, 128) count
# tile plus six u64 columns, so memory stays negligible).
STRIDE_WINDOW = 16

# Audit every Nth ZERO-count descriptor with a host re-scan (0 disables;
# NICE_TPU_AUDIT_EVERY overrides). Descriptors with nonzero counts are always
# host-verified as a side effect of extracting their numbers, so a kernel bug
# that OVERcounts is caught immediately — but one that undercounts to zero
# was previously silent (at 1e13 scale, hits=0 rested on one code path).
# Sampled auditing closes that blind spot for ~1-2% extra collector time on
# massive fields (soundness analog of client_process_gpu.rs:1289-1324).
STRIDE_AUDIT_EVERY = 1024


class _Collector:
    """Bounded-queue worker thread applying `fn` to put() items.

    Shared scaffolding for the dispatch pipelines: result readback (and any
    host re-scan behind it) runs off the dispatch thread — np.asarray blocks
    in C with the GIL released, so dispatch and collection genuinely overlap.
    On worker failure the queue is drained so producers' put() calls never
    block forever; shutdown() joins without raising (safe in a finally) and
    raise_if_failed() re-raises the worker's exception on the caller.
    Use as a context manager: __exit__ always shuts the worker down, so a
    KeyboardInterrupt (or any exception between construction and the dispatch
    loop's own cleanup) can never leak the collector thread.

    occupancy: optional obs gauge tracking the in-flight window depth (queue
    backlog + the item being processed) — the live measure of whether the
    pipeline is dispatch-bound (gauge near 0) or collection-bound (gauge
    pinned at maxsize)."""

    def __init__(self, fn, maxsize: int, name: str, on_fail=None,
                 occupancy=None):
        import queue as queue_mod
        import threading

        self._fn = fn
        self._err: list = [None]
        self._on_fail = on_fail
        self._occupancy = occupancy
        self._q: queue_mod.Queue = queue_mod.Queue(maxsize=maxsize)
        self._t = threading.Thread(target=self._run, name=name, daemon=True)
        self._t.start()

    def _run(self):
        try:
            while True:
                item = self._q.get()
                if item is None:
                    return
                self._fn(*item)
                if self._occupancy is not None:
                    self._occupancy.set(self._q.qsize())
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            self._err[0] = e
            if self._on_fail is not None:
                # Fail-fast hook: lets the pipeline's producer stop at its
                # next chunk instead of filtering for up to a full producer
                # chunk before the dispatcher notices at a group boundary
                # (advisor r4, engine.py:838).
                self._on_fail()
            while self._q.get() is not None:
                pass  # drain so producers' puts never block forever

    def __enter__(self) -> "_Collector":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()

    def failed(self) -> bool:
        return self._err[0] is not None

    def put(self, item) -> None:
        self._q.put(item)
        if self._occupancy is not None:
            self._occupancy.set(self._q.qsize() + 1)

    def shutdown(self) -> None:
        self._q.put(None)
        self._t.join()

    def raise_if_failed(self) -> None:
        if self._err[0] is not None:
            raise self._err[0]


# Periodic-checkpoint cadence defaults (overridable per call or via env).
CKPT_EVERY_BATCHES = 256
CKPT_EVERY_SECS = 30.0


class _CkptTicker:
    """Decides when a periodic checkpoint is due: every N batches or every T
    seconds, whichever fires first (either can be 0 to disable that trigger).
    Single-threaded by construction — each dispatch path owns one ticker and
    tick()s it from exactly one thread."""

    def __init__(self, every_batches=None, every_secs=None):
        self.every_batches = int(
            every_batches if every_batches is not None
            else knobs.CKPT_BATCHES.get(default=CKPT_EVERY_BATCHES)
        )
        self.every_secs = float(
            every_secs if every_secs is not None
            else knobs.CKPT_SECS.get(default=CKPT_EVERY_SECS)
        )
        self._batches = 0
        self._last = time.monotonic()

    def tick(self) -> bool:
        self._batches += 1
        now = time.monotonic()
        if (self.every_batches > 0 and self._batches >= self.every_batches) or (
            self.every_secs > 0 and now - self._last >= self.every_secs
        ):
            self._batches = 0
            self._last = now
            return True
        return False


def _pick_backend(plan, batch_size: int, backend: str) -> str:
    """Resolve "jax" to the Pallas kernels when on TPU and the base/batch
    supports them (histogram fits one 128-lane row; batch is whole blocks).
    On other platforms "jax" resolves to the XLA-compiled jnp engine; passing
    backend="pallas" explicitly forces the kernels (interpreter mode off-TPU,
    used by the test suite)."""
    if backend == "pallas":
        if not pe.supports_base(plan):
            raise ValueError(
                f"base {plan.base} exceeds the Pallas stats tile "
                f"(base+2 > {pe._HIST_ROWS_MAX * 128})"
            )
        if batch_size % 128 != 0:
            raise ValueError(f"pallas batch_size must be a multiple of 128, got {batch_size}")
        return backend
    if backend != "jax":
        return backend
    import jax

    if (
        jax.default_backend() == "tpu"
        and pe.supports_base(plan)
        and batch_size % pe.BLOCK_LANES == 0
    ):
        return "pallas"
    return "jnp"


class BackendDispatchError(RuntimeError):
    """A backend failed mid-field while dispatching batches.

    Raised by the _process_range_* impls at the degradation boundary so the
    public wrappers can re-dispatch the remainder of the field on the next
    backend in the fallback chain.

    backend: the RESOLVED backend that failed ("pallas" / "jnp" / ...), not
    the caller's "jax" alias — the chain steps from what actually ran.
    state:   a checkpoint-contract resume dict ({"cursor", "hist",
             "nice_numbers"}) covering everything folded before the failure,
             or None when nothing is salvageable (the field restarts from the
             caller's own resume point, if any).
    cause:   the original exception."""

    def __init__(self, backend: str, state, cause: BaseException):
        super().__init__(f"backend {backend!r} failed mid-dispatch: {cause!r}")
        self.backend = backend
        self.state = state
        self.cause = cause


# Degradation chain: each resolved backend's replacement when it fails
# mid-field. Ends at the scalar oracle (pure Python ints — no device, no
# compiled kernels), whose failures propagate to the caller.
_FALLBACK_NEXT = {"pallas": "jnp", "jnp": "scalar"}


def _fallback_enabled() -> bool:
    return not knobs.NO_FALLBACK.get_bool()


def _fire_dispatch_fault(n_batch: int, backend: str, batch_start: int) -> None:
    """Chaos hook (engine.dispatch): any configured action raises, exercising
    the same degradation boundary a real device failure would hit."""
    act = faults.fire(
        "engine.dispatch", batch=n_batch, backend=backend, start=batch_start
    )
    if act is not None:
        raise RuntimeError(f"injected engine.dispatch fault: {act}")


def _run_with_fallback(impl, range_, base, backend, kwargs) -> FieldResults:
    """Run a _process_range_* impl under the pallas -> jnp -> scalar chain.

    On BackendDispatchError the failed batch (and everything after it) is
    re-dispatched on the next backend via the checkpoint/resume contract —
    work folded before the failure is kept, not recomputed. Each downgrade
    increments nice_engine_backend_downgrades_total and is stamped into
    FieldResults.backend_downgrades ("from->to") so it travels with the
    submission. NICE_TPU_NO_FALLBACK=1 disables the chain (the error
    propagates, for tests and debugging)."""
    downgrades: list[str] = []
    kw = kwargs
    while True:
        try:
            results = impl(range_, base, backend=backend, **kw)
        except BackendDispatchError as e:
            obs.flight.record(
                "dispatch_error", backend=e.backend, base=base,
                cause=repr(e.cause)[:200],
            )
            nxt = _FALLBACK_NEXT.get(e.backend)
            if nxt is None or not _fallback_enabled():
                raise
            ENGINE_BACKEND_DOWNGRADES.labels(e.backend, nxt).inc()
            downgrades.append(f"{e.backend}->{nxt}")
            cursor = e.state["cursor"] if e.state is not None else None
            obs.flight.record(
                "downgrade", from_backend=e.backend, to_backend=nxt,
                base=base, cursor=cursor, cause=repr(e.cause)[:200],
            )
            obs.trace_event(
                "engine.downgrade", from_backend=e.backend, to_backend=nxt,
                base=base, cursor=cursor,
            )
            log.warning(
                "backend %s failed mid-field [%d, %d): %r — %s on %s "
                "(downgrade %d)",
                e.backend, range_.start(), range_.end(), e.cause,
                "resuming at cursor %d" % e.state["cursor"]
                if e.state is not None else "restarting",
                nxt, len(downgrades),
            )
            backend = nxt
            kw = dict(kwargs)
            # A consistent mid-field state resumes the scan where it broke;
            # a lost state falls back to the caller's own resume point (its
            # snapshot still covers that prefix) or a clean restart.
            kw["resume"] = e.state if e.state is not None else kwargs.get("resume")
            continue
        if downgrades:
            results = dataclasses.replace(
                results,
                backend_downgrades=results.backend_downgrades
                + tuple(downgrades),
            )
        return results


from nice_tpu.utils import lockdep

# Device-tuple -> mesh cache. Was a functools.lru_cache, but an lru cache's
# clear/rebuild window cannot be guarded: a dispatch thread entering
# _cached_mesh between a downshift's cache_clear() and its rebuild could
# repopulate the cache with a mesh over dead devices (racelint R5; replayed
# by the schedex mesh_cache_clear_vs_rebuild scenario). Explicit dict +
# lock + generation instead: reads and the generation check are under the
# lock, make_mesh runs outside it, and a store only lands if no
# invalidation happened mid-build.
_MESH_CACHE: dict = {}
_MESH_CACHE_GEN = 0
_mesh_cache_lock = lockdep.make_lock("ops.engine._mesh_cache_lock")


def _cached_mesh(devs: tuple):
    from nice_tpu.parallel import mesh as pmesh

    with _mesh_cache_lock:
        mesh = _MESH_CACHE.get(devs)
        gen = _MESH_CACHE_GEN
    if mesh is not None:
        return mesh
    built = pmesh.make_mesh(list(devs))
    with _mesh_cache_lock:
        if _MESH_CACHE_GEN == gen:
            return _MESH_CACHE.setdefault(devs, built)
    # Invalidated while building: serve the mesh without caching it, so a
    # stale device tuple can never outlive the downshift that killed it.
    return built


def _invalidate_mesh_cache() -> None:
    global _MESH_CACHE_GEN
    with _mesh_cache_lock:
        _MESH_CACHE_GEN += 1
        _MESH_CACHE.clear()


def _mesh_or_none():
    """Multi-chip context: a 1-D mesh over all visible devices when more than
    one is visible and sharding is not disabled (NICE_TPU_SHARD=0). The
    engine dispatches whole super-batches (batch_size lanes per device) through
    parallel/mesh.py sharded steps, which run the same single-chip kernels per
    device and psum the stats over ICI (P8). The mesh (and the jitted sharded
    steps keyed on it) are cached so repeated process_range_* calls never
    retrace."""
    import jax

    if not knobs.SHARD.get_bool():
        return None
    from nice_tpu.parallel import mesh as pmesh

    # Devices a downshift marked dead stay excluded until heal_devices(), so
    # the fields AFTER a reshard also start on the survivor mesh instead of
    # re-discovering the loss one dispatch failure at a time.
    devs = pmesh.live_devices(jax.devices())
    if len(devs) < 2:
        return None
    return _cached_mesh(tuple(devs))


def _shard_inputs(plan, core_end: int, batch_start: int, valid: int,
                  batch_size: int, n_dev: int):
    """Exact per-device (starts u32[n_dev, limbs_n], valids i32[n_dev]) for one
    super-batch, computed on the host with Python ints (no in-graph offset
    arithmetic, so no u32/i32 overflow at any field size). Device starts are
    clamped to the core end so tail devices with zero valid lanes never leave
    the base range (their lanes are masked, but digit extraction still runs)."""
    starts = ints_to_limbs(
        [min(batch_start + d * batch_size, core_end) for d in range(n_dev)],
        plan.limbs_n,
    )
    valids = np.asarray(
        [max(0, min(batch_size, valid - d * batch_size)) for d in range(n_dev)],
        dtype=np.int32,
    )
    return starts, valids


# --- double-buffered host->device feed + elastic downshift (pod layer) ----

# Depth of the host->device feed queue: how many super-batches ahead the
# producer thread precomputes per-slice (starts, valids) limb rows. 0 runs
# the feed synchronously on the dispatch thread — the pre-pod baseline, kept
# as a measurable A/B via NICE_TPU_FEED_DEPTH=0 for the scaling harness.
FEED_DEPTH_DEFAULT = 2


def _feed_depth() -> int:
    try:
        d = knobs.FEED_DEPTH.get(default=FEED_DEPTH_DEFAULT)
    except ValueError:
        d = FEED_DEPTH_DEFAULT
    return max(0, min(64, d))


def _elastic_enabled() -> bool:
    """Elastic mesh downshift (reshard onto survivors when a device drops
    mid-field) is on by default; NICE_TPU_ELASTIC=0 restores the PR 4
    behavior of degrading the whole field down the backend chain."""
    return knobs.ELASTIC.get_bool()


# Feed/reshard stats of the most recent device dispatch loop, read by the
# scaling harness and tests — Prometheus histograms expose only sum/count,
# not the p50/p95 the MULTICHIP report needs.
LAST_FEED_STATS: dict = {}


def _record_feed_stats(mode, gaps, dispatches, n_dev_start, n_dev_end,
                       reshards, reshard_secs, depth) -> None:
    # nicelint: allow D1 (gaps is a host-side list of floats)
    g = np.asarray(gaps, dtype=np.float64)
    LAST_FEED_STATS.clear()
    LAST_FEED_STATS.update({
        "mode": mode,
        "feed_depth": int(depth),
        "dispatches": int(dispatches),
        "gaps": int(g.size),
        "idle_p50": float(np.percentile(g, 50)) if g.size else 0.0,
        "idle_p95": float(np.percentile(g, 95)) if g.size else 0.0,
        "idle_mean": float(g.mean()) if g.size else 0.0,
        "idle_total": float(g.sum()) if g.size else 0.0,
        "n_dev_start": int(n_dev_start),
        "n_dev_end": int(n_dev_end),
        "reshards": int(reshards),
        "reshard_secs": float(reshard_secs),
    })


class _FeedItem(NamedTuple):
    starts: np.ndarray  # u32[n_slices, limbs_n] per-slice start limb rows
    valids: np.ndarray  # i32[n_slices] valid lanes per slice
    segs: tuple         # ((start, valid), ...) as Python ints, per slice
    markers: tuple      # ((seg_idx, cursor), ...) per slice, AFTER this batch
    lanes: int          # total valid lanes in the super-batch


class _SliceFeed:
    """Double-buffered host->device feed over per-slice work queues.

    queues[d] is slice d's list of ascending disjoint [start, end) segments
    (one slice per mesh device; parallel/mesh.py partition_segments builds
    them). Each get() yields one super-batch taking up to batch_size
    candidates from every slice's queue head — a slice never spans a segment
    boundary within one batch, because its device computes a contiguous run
    from its start row. With depth > 0 a producer thread precomputes the
    limb rows of the next items while the current batch runs on-device, so
    dispatch never blocks on host arithmetic; depth == 0 computes inline
    (the synchronous baseline the scaling harness A/Bs against).

    markers are the resume vocabulary: item.markers[d] = (seg_idx, cursor)
    AFTER taking the batch, so remaining(queues, markers-of-the-last-
    SUCCESSFUL-item) is exactly the uncovered range, automatically including
    a batch that failed in flight."""

    def __init__(self, plan, queues, batch_size: int, core_end: int,
                 depth: int):
        self._iter = self._generate(plan, queues, batch_size, core_end)
        self._depth = depth
        if depth > 0:
            import queue as queue_mod
            import threading

            self._q: "queue_mod.Queue" = queue_mod.Queue(maxsize=depth)
            self._err: list = [None]
            self._stop = threading.Event()
            self._t = threading.Thread(
                target=self._fill, name="mesh-feed", daemon=True
            )
            self._t.start()

    @staticmethod
    def start_markers(queues) -> tuple:
        return tuple((0, q[0][0] if q else 0) for q in queues)

    @staticmethod
    def _generate(plan, queues, batch_size, core_end):
        pos = [[0, q[0][0] if q else 0] for q in queues]
        while True:
            segs, markers, lanes = [], [], 0
            for d, q in enumerate(queues):
                si, cur = pos[d]
                if si >= len(q):
                    # Exhausted slice: zero-lane row clamped inside the base
                    # range (digit extraction still runs on masked lanes).
                    segs.append((core_end, 0))
                    markers.append((si, cur))
                    continue
                take = min(batch_size, q[si][1] - cur)
                segs.append((cur, take))
                lanes += take
                cur += take
                if cur >= q[si][1]:
                    si += 1
                    if si < len(q):
                        cur = q[si][0]
                pos[d] = [si, cur]
                markers.append((si, cur))
            if lanes == 0:
                return
            starts = ints_to_limbs([s for s, _ in segs], plan.limbs_n)
            valids = np.asarray([v for _, v in segs], dtype=np.int32)
            yield _FeedItem(starts, valids, tuple(segs), tuple(markers), lanes)

    @staticmethod
    def remaining(queues, markers) -> list[tuple[int, int]]:
        """Uncovered [start, end) segments given the per-slice markers of
        the last successfully dispatched item (sorted, merged)."""
        rem = []
        for q, (si, cur) in zip(queues, markers):
            if si < len(q):
                if cur < q[si][1]:
                    rem.append((max(cur, q[si][0]), q[si][1]))
                rem.extend((s, e) for s, e in q[si + 1:])
        rem.sort()
        merged: list[list[int]] = []
        for s, e in rem:
            if merged and s <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], e)
            else:
                merged.append([s, e])
        return [(s, e) for s, e in merged]

    def _fill(self):
        import queue as queue_mod

        try:
            for item in self._iter:
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        break
                    except queue_mod.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # noqa: BLE001 — re-raised by get()
            self._err[0] = e
        finally:
            while not self._stop.is_set():
                try:
                    self._q.put(None, timeout=0.1)
                    return
                except queue_mod.Full:
                    continue

    def get(self):
        """Next _FeedItem, or None once every slice queue is exhausted."""
        if self._depth == 0:
            return next(self._iter, None)
        item = self._q.get()
        if item is None and self._err[0] is not None:
            raise self._err[0]
        return item

    def stop(self) -> None:
        """Tear the producer down (idempotent; safe mid-stream — the queue
        is drained until the producer thread exits, so no put() deadlocks)."""
        if self._depth == 0:
            self._iter.close()
            return
        import queue as queue_mod

        self._stop.set()
        while self._t.is_alive():
            try:
                self._q.get_nowait()
            except queue_mod.Empty:
                self._t.join(timeout=0.05)
        self._t.join()


def _fire_mesh_fault(n_batch: int, n_dev: int, batch_start: int) -> None:
    """Chaos hook (mesh.dispatch): action "dead[:i[+j...]]" simulates losing
    the mesh axis position(s) i... (default: the last device) by raising
    MeshDeviceLost — the signal the elastic downshift reshard path consumes.
    Any other action raises a plain RuntimeError, which exercises the PR 4
    backend-fallback chain instead."""
    act = faults.fire(
        "mesh.dispatch", batch=n_batch, n_dev=n_dev, start=batch_start
    )
    if act is None:
        return
    if act == "dead" or act.startswith("dead:"):
        from nice_tpu.parallel import mesh as pmesh

        if ":" in act:
            lost = [int(x) for x in act.split(":", 1)[1].split("+")]
        else:
            lost = [n_dev - 1]
        lost = [i for i in lost if 0 <= i < n_dev]
        if lost:
            raise pmesh.MeshDeviceLost(lost)
    raise RuntimeError(f"injected mesh.dispatch fault: {act}")


def _diagnose_survivors(mesh, err):
    """After a mesh dispatch failure: (survivor device list, reason) when
    one or more devices actually dropped while at least one survives, else
    (None, ""). MeshDeviceLost names the axis positions directly (and the
    lost devices are registered with the liveness layer so subsequent fields
    avoid them); any other failure probes every device."""
    from nice_tpu.parallel import mesh as pmesh

    devices = list(mesh.devices.flat)
    if isinstance(err, pmesh.MeshDeviceLost):
        lost_pos = set(i for i in err.lost if i < len(devices))
        if lost_pos and len(lost_pos) < len(devices):
            pmesh.simulate_device_loss(
                int(devices[i].id) for i in lost_pos
            )
            return (
                [d for i, d in enumerate(devices) if i not in lost_pos],
                "device_lost",
            )
        return None, ""
    alive, lost = pmesh.probe_devices(devices)
    if lost and alive:
        return alive, "probe"
    return None, ""


def _resume_segments(resume, start: int, end: int) -> list[tuple[int, int]]:
    """Uncovered [start, end)-clamped segments encoded by a resume state:
    the "remaining" list when present (per-slice state), else the legacy
    prefix-cursor contract ([range.start, cursor) fully covered)."""
    if resume.get("remaining") is not None:
        segs = [
            (max(start, int(s)), min(end, int(e)))
            for s, e in resume["remaining"]
        ]
        return [(s, e) for s, e in segs if s < e]
    pos = max(start, min(end, int(resume["cursor"])))
    return [(pos, end)] if pos < end else []


def _rare_scan_survivors(plan, batch_start: int, valid: int, batch_size: int,
                         backend: str, thresh: int):
    """Yield (number, num_uniques) for every candidate in [batch_start,
    +valid) with num_uniques > thresh.

    Near-miss/nice extraction is the rare path. The old shape re-probed
    sub-batches and then transferred FULL per-lane uniques arrays for any
    sub-batch with a hit (4 MiB per 2^20 lanes); now each sub-batch runs the
    on-device survivor-compaction kernel (ve/pe.survivors_batch) and the
    readback is the compacted (count, idx[cap], uniq[cap]) — 32 KiB worst
    case, 4 bytes when empty. thresh = plan.near_miss_cutoff serves detailed;
    thresh = base - 1 serves niceonly (uniques > base-1 <=> == base). Only if
    count overflows SURVIVOR_CAP (accept-rich synthetic ranges) does the
    dense per-lane transfer run, for correctness.
    """
    mod = pe if backend == "pallas" else ve
    sub_size = min(RARE_SCAN_BATCH, batch_size)
    # Small (test-sized) sub-batches never need more rows than they have
    # lanes — and capping there keeps the compacted readback strictly no
    # larger than the dense one at any batch size.
    cap = min(SURVIVOR_CAP, sub_size)
    done = 0
    while done < valid:
        sub_valid = min(sub_size, valid - done)
        sub_start = batch_start + done
        start_limbs = int_to_limbs(sub_start, plan.limbs_n)
        count, idx, uniq = mod.survivors_batch(
            plan, sub_size, thresh, cap, start_limbs, np.int32(sub_valid),
        )
        # nicelint: fence (survivor-count readback; metered below)
        count = int(np.asarray(count))
        if count == 0:
            ENGINE_READBACK_BYTES.labels("survivors").inc(4)
        elif count <= cap:
            # nicelint: fence (compacted survivor index readback)
            idx = np.asarray(idx)
            # nicelint: fence (compacted unique-count readback)
            uniq = np.asarray(uniq)
            ENGINE_READBACK_BYTES.labels("survivors").inc(
                4 + idx.nbytes + uniq.nbytes
            )
            for i, u in zip(idx[:count].tolist(), uniq[:count].tolist()):
                yield sub_start + i, u
        else:
            ENGINE_SURVIVOR_OVERFLOW.inc()
            # nicelint: fence (dense unique readback on overflow)
            u = np.asarray(mod.uniques_batch(plan, sub_size, start_limbs))
            ENGINE_READBACK_BYTES.labels("survivors-dense").inc(4 + u.nbytes)
            u = u[:sub_valid]
            for i in np.nonzero(u > thresh)[0].tolist():
                yield sub_start + int(i), int(u[i])
        done += sub_valid


def _clamp_to_base_range(range_: FieldSize, base: int):
    """Split range into (pre, core, post) where core is inside the base range."""
    br = base_range.get_base_range(base)
    if br is None:
        return (range_, None, None)
    lo = max(range_.start(), br[0])
    hi = min(range_.end(), br[1])
    if lo >= hi:
        return (range_, None, None)
    pre = FieldSize(range_.start(), lo) if range_.start() < lo else None
    core = FieldSize(lo, hi)
    post = FieldSize(hi, range_.end()) if hi < range_.end() else None
    return (pre, core, post)


def _split_for_jax(range_: FieldSize, base: int, scalar_fn,
                   skip_slivers: bool = False):
    """Clamp to the base range; run scalar_fn on out-of-range slivers.

    Returns (core, sliver_results) where core may be None (range entirely
    outside the base range — caller should go fully scalar). skip_slivers
    suppresses the sliver recomputation: a resumed scan's checkpoint state
    already folded them in (slivers run up-front, before the first
    checkpoint can fire).
    """
    pre, core, post = _clamp_to_base_range(range_, base)
    slivers = []
    if not skip_slivers:
        for part in (pre, post):
            if part is None:
                continue
            ENGINE_HOST_FALLBACK.labels("sliver").inc()
            slivers.append(scalar_fn(part))
    return core, slivers


def _chunked_host_scan(
    range_: FieldSize, base: int, mode: str, chunk: int, progress,
    checkpoint_cb, resume, every_batches, every_secs, stride_table=None,
) -> FieldResults:
    """Scalar-oracle scan in resumable chunks: the checkpoint/resume analog of
    the device dispatch loops for backend='scalar' (and for ranges entirely
    outside the base range). Cursor semantics match the device paths — a
    checkpoint state covers every candidate in [range.start, cursor)."""
    detailed = mode == "detailed"
    hist = np.zeros(base + 2, dtype=np.int64) if detailed else None
    nice: list[NiceNumberSimple] = []
    start, total = range_.start(), range_.size()
    end = range_.end()
    chunk = max(1, chunk)
    segs = [(start, end)] if total else []
    filtered = False
    if resume is not None:
        # A per-slice "remaining" state (from the pod dispatch loops) may
        # leave several disjoint uncovered segments; a "filtered" niceonly
        # state additionally guarantees the gaps BETWEEN them hold no nice
        # numbers (MSD/stride-filtered), so scanning only the segments is
        # still exact. Both degrade cleanly to the legacy prefix cursor.
        segs = _resume_segments(resume, start, end)
        filtered = bool(resume.get("filtered"))
        if detailed:
            if resume.get("hist") is None:
                raise ValueError("detailed resume state is missing a histogram")
            # nicelint: allow D1 (resume histogram arrives as host JSON)
            h = np.asarray(resume["hist"], dtype=np.int64)
            if h.shape != hist.shape:
                raise ValueError(
                    f"resume histogram shape {h.shape} != {hist.shape}"
                )
            hist[:] = h
        nice = [
            NiceNumberSimple(number=int(n), num_uniques=int(u))
            for n, u in resume["nice_numbers"]
        ]
        CKPT_RESTORES.inc()
        done0 = total - sum(e_ - s_ for s_, e_ in segs)
        CKPT_BATCHES_SKIPPED.inc(done0 // chunk)
        log.info(
            "%s scalar resume: %d segment(s) remaining (%d of %d numbers "
            "already done)", mode, len(segs), done0, total,
        )
    ticker = (
        _CkptTicker(every_batches, every_secs) if checkpoint_cb else None
    )
    n_batch = 0
    done = total - sum(e_ - s_ for s_, e_ in segs)
    with obs.span("engine.scalar", base=base, size=total, mode=mode,
                  backend="scalar"):
        while segs:
            s, e = segs[0]
            n = min(chunk, e - s)
            # End of the degradation chain: an injected (or real) scalar
            # failure propagates to the caller — there is nothing left to
            # fall back to.
            _fire_dispatch_fault(n_batch, "scalar", s)
            n_batch += 1
            sub_range = FieldSize(s, s + n)
            if detailed:
                sub = scalar.process_range_detailed(sub_range, base)
                for d in sub.distribution:
                    hist[d.num_uniques] += d.count
            else:
                sub = scalar.process_range_niceonly(
                    sub_range, base, stride_table
                )
            nice.extend(sub.nice_numbers)
            done += n
            if s + n >= e:
                segs.pop(0)
            else:
                segs[0] = (s + n, e)
            if progress is not None:
                progress(done, total)
            if ticker is not None and ticker.tick():
                checkpoint_cb({
                    "cursor": segs[0][0] if segs else end,
                    "hist": None if hist is None else hist.copy(),
                    "nice_numbers": [
                        (x.number, x.num_uniques) for x in nice
                    ],
                    "remaining": [[s_, e_] for s_, e_ in segs],
                    "filtered": filtered,
                })
    nice.sort(key=lambda x: x.number)
    if not detailed:
        return FieldResults(distribution=(), nice_numbers=tuple(nice))
    distribution = tuple(
        UniquesDistributionSimple(num_uniques=i, count=int(hist[i]))
        for i in range(1, base + 1)
    )
    return FieldResults(distribution=distribution, nice_numbers=tuple(nice))


def _native_detailed(
    range_: FieldSize, base: int, threads: int, progress=None
) -> FieldResults:
    """Multi-threaded native CPU detailed loop (the analog of the reference's
    rayon par_iter client, client/src/main.rs:154-207). ctypes releases the
    GIL, so a thread pool gets real parallelism."""
    from concurrent.futures import ThreadPoolExecutor

    from nice_tpu import native
    from nice_tpu.core import number_stats

    if not native.available():
        raise RuntimeError(
            "backend='native' requested but the C++ library is unavailable "
            "(no toolchain?); use backend='scalar' or 'jax'"
        )
    cutoff = number_stats.get_near_miss_cutoff(base)
    total = range_.size()
    chunk = max(65536, total // (threads * 8) or 1)
    spans = [
        (range_.start() + off, min(chunk, total - off))
        for off in range(0, total, chunk)
    ]
    hist = np.zeros(base + 2, dtype=np.int64)
    nice_numbers: list[NiceNumberSimple] = []
    done = 0
    with ThreadPoolExecutor(max_workers=threads) as pool:
        for span_, res in zip(spans, pool.map(
            lambda s: native.process_range_detailed(s[0], s[1], base, cutoff),
            spans,
        )):
            if res is None:
                # Out-of-bounds base or >u128 values; the caller picked the
                # native backend explicitly, so raise rather than silently
                # switching engines mid-field.
                raise RuntimeError(
                    f"native backend does not support base {base} at this range; "
                    "use backend='scalar'"
                )
            sub_hist, misses = res
            # nicelint: fence (per-subrange histogram fold to host)
            np.add(hist, np.asarray(sub_hist, dtype=np.int64), out=hist)
            nice_numbers.extend(
                NiceNumberSimple(number=n, num_uniques=u) for n, u in misses
            )
            done += span_[1]
            if progress is not None:
                progress(done, total)
    nice_numbers.sort(key=lambda n: n.number)
    distribution = tuple(
        UniquesDistributionSimple(num_uniques=i, count=int(hist[i]))
        for i in range(1, base + 1)
    )
    return FieldResults(distribution=distribution, nice_numbers=tuple(nice_numbers))


def _native_niceonly(
    range_: FieldSize, base: int, stride_table, threads: int, progress=None,
    msd_floor: int | None = None,
) -> FieldResults:
    """Native filter cascade: C++ MSD subdivision -> stride-table gap jumps ->
    early-exit checks, fanned across threads per MSD range.

    msd_floor overrides the MSD recursion floor: the small-field host route
    passes a coarse floor so the per-range Python overhead (bisect + ctypes
    call) stays negligible against the ~20 ns/candidate native kernel."""
    from concurrent.futures import ThreadPoolExecutor

    from nice_tpu import native
    from nice_tpu.ops import msd_filter, stride_filter

    if not native.available():
        raise RuntimeError(
            "backend='native' requested but the C++ library is unavailable "
            "(no toolchain?); use backend='scalar' or 'jax'"
        )
    if stride_table is None:
        stride_table = stride_filter.get_stride_table(
            base, _host_stride_depth(base)
        )
    if stride_table.num_residues == 0:
        return FieldResults(distribution=(), nice_numbers=())

    gap_table = stride_table.gap_array
    modulus, residues = stride_table.modulus, stride_table.residues_u32

    def run(sub: FieldSize) -> list[int]:
        first, idx = stride_table.first_valid_at_or_after(sub.start())
        if first >= sub.end():
            return []
        found = native.iterate_range_strided(
            first, idx, sub.end(), base, gap_table,
            modulus=modulus, residues=residues,
        )
        if found is None:
            raise RuntimeError(
                f"native backend does not support base {base} at this range; "
                "use backend='scalar'"
            )
        return found

    if msd_floor is not None:
        ranges = msd_filter.get_valid_ranges(
            range_, base, min_range_size=msd_floor,
            max_depth=_msd_depth_for(range_.size(), msd_floor),
        )
    else:
        ranges = msd_filter.get_valid_ranges(range_, base)
    total = sum(r.size() for r in ranges)
    done = 0
    nice_numbers: list[NiceNumberSimple] = []
    with ThreadPoolExecutor(max_workers=threads) as pool:
        for sub, found in zip(ranges, pool.map(run, ranges)):
            nice_numbers.extend(
                NiceNumberSimple(number=n, num_uniques=base) for n in found
            )
            done += sub.size()
            if progress is not None:
                progress(done, total)
    nice_numbers.sort(key=lambda n: n.number)
    return FieldResults(distribution=(), nice_numbers=tuple(nice_numbers))


def _native_threads() -> int:
    import os

    return max(1, int(os.environ.get("NICE_THREADS", os.cpu_count() or 1)))


def _host_stride_depth(base: int) -> int:
    """Deepest CRT table worth building for HOST iteration: deeper k strictly
    shrinks the candidate fraction, bounded by table memory/build time (the
    gap+residue arrays are ~16 B/residue) and the kernels' u32 modulus."""
    from nice_tpu.ops import stride_filter

    best = 1
    for k in (2, 3):
        modulus = (base - 1) * base**k
        if modulus >= 1 << 25:  # ~5e8 B tables beyond this; build >1 s
            break
        if stride_filter.stride_residue_count(base, k) > 2_000_000:
            break
        best = k
    return best


# Niceonly fields at or below this size are routed to the native host engine
# instead of the device when the polynomial-residue fast kernel applies: one
# device dispatch costs a full device->host readback RTT (30-110 ms through
# the axon tunnel, utils/platform.py), while the host kernel sustains
# ~5e8 numbers/s on one core — so for sub-3e7 fields the host wins outright.
# The reference makes the same per-field backend choice between its CPU and
# GPU clients (client_process_gpu.rs:515-531). NICE_TPU_HOST_NICEONLY_MAX
# overrides (0 disables).
HOST_NICEONLY_MAX = 1 << 25


def _host_route_niceonly(core: FieldSize, base: int) -> bool:
    from nice_tpu import native

    limit = knobs.HOST_NICEONLY_MAX_KNOB.get(default=HOST_NICEONLY_MAX)
    if core.size() > limit or not native.available():
        return False
    # Mirror of the native fast-path eligibility (nice_native.cpp): candidate
    # values in u64, digit masks in u64, and the poly kernel's u64 bounds.
    if base > 64 or core.end() >= (1 << 63) // (base - 1):
        return False
    d3 = base**3
    return core.end() ** 2 < (1 << 62) * d3**3


def _pick_stride_depth(base: int, typical: int, max_k: int = 3) -> tuple[int, int]:
    """Choose the CRT stride depth k and kernel periods for the strided
    device path.

    This is the TPU re-design of the reference's fused low-digit GPU
    prefilter (nice_kernels.cu:329-383, gated per base by measured survival,
    client_process_gpu.rs:407-450): on a VPU there is no warp divergence to
    early-exit with, so instead of evaluating the low-digit predicate on
    device we FOLD it into the CRT stride table (deeper k = modulus
    (b-1)*b^k filters k low digits of the sqube) and let the host index
    arithmetic compact the lanes before they ever reach the device (P7).

    Deeper k trades a bigger modulus (coarser descriptor spans -> masked-lane
    waste on narrow MSD ranges) for fewer candidate lanes per number. The
    score is expected device lanes per covered number on a typical surviving
    range width; a deeper k must beat the shallower one by >5% (the
    reference's measured-win gate, which compiled its prefilter out at b42+
    where survival made it a loss).

    `typical` is the expected surviving-range width. Callers derive it from
    the MSD floor alone (1.5x floor: the adaptive-depth recursion bounds
    leaves to (floor, 2*floor]), which makes the choice — and therefore the
    compiled kernel shape — deterministic per (base, floor): a benchmark
    warm-up field compiles exactly the kernel the timed field will run, and
    a production client never recompiles between fields at a stable floor.
    Depths are scored with stride_residue_count (CRT product, no table
    build); only the chosen depth's table is materialized. periods is a
    power of two so a drifting adaptive floor reuses shapes.
    """
    from nice_tpu.ops import stride_filter

    typical = max(1, typical)
    best: tuple[float, int, int] | None = None
    for k in range(1, max_k + 1):
        modulus = (base - 1) * base**k
        if modulus >= 1 << 32:
            break  # kernel offset arithmetic is u32
        num_res = stride_filter.stride_residue_count(base, k)
        if num_res == 0:
            return k, 1  # provably nothing to search at any depth
        if num_res > pe.STRIDED_OFFS_LANES_MAX:
            # The residue table alone exceeds the offsets-VMEM budget even at
            # periods=1 (e.g. base 73 at k=3: ~4M residues); this depth cannot
            # be expanded, so skip it rather than let the periods cap go to 1
            # and trip the kernel-build assert.
            continue
        cap = min(
            pe.STRIDED_PERIODS_MAX,
            ((1 << 32) - 1) // modulus,  # u32 span
            max(1, pe.STRIDED_OFFS_LANES_MAX // num_res),  # VMEM offsets
        )
        raw = max(1, min(cap, typical // modulus))
        periods = 1 << (raw.bit_length() - 1)
        span = periods * modulus
        # Expected device lanes per covered number on the typical range.
        descs = -(-typical // span)
        score = descs * periods * num_res / typical
        if best is None or score < best[0] * 0.95:
            best = (score, k, periods)
    assert best is not None
    return best[1], best[2]


def _msd_depth_for(size: int, floor: int) -> int:
    """Recursion depth that actually reaches `floor`-sized leaves.

    The reference's fixed depth cap (msd_prefix_filter.rs:283, depth 22) was
    tuned for CPU fields <= 1e9; at device scale (massive = 1e13) a fixed cap
    silently decouples the adaptive floor from real leaf width (1e13 / 2^22
    ~ 2.4e6 > any floor), so the cap grows with the field instead.
    """
    from nice_tpu.ops import msd_filter

    need = max(0, (max(1, size) // max(1, floor)).bit_length()) + 1
    return max(msd_filter.MSD_RECURSIVE_MAX_DEPTH, need)


def _host_strided_scan(table, base: int, start: int, end: int) -> list[int]:
    """Exact nice numbers among stride candidates in [start, end) (host path,
    native C++ when available)."""
    from nice_tpu import native

    if start >= end:
        return []
    first, idx = table.first_valid_at_or_after(start)
    if first >= end:
        return []
    found = native.iterate_range_strided(
        first, idx, end, base, table.gap_array,
        modulus=table.modulus, residues=table.residues_u32,
    )
    if found is None:
        return [
            n.number for n in table.iterate_range(FieldSize(start, end), base)
        ]
    return found


def _strided_floor(ctrl, field_size: int) -> int:
    """Effective MSD floor for a strided-device field: the adaptive floor,
    raised so a field never spans more than ~2^21 recursion leaves.

    The controller converges between fields; a single huge field (massive =
    1e13) would otherwise run at a floor tuned for 1e9 production fields,
    which at 1e13 means ~5e5 leaves whose boundary-quantization waste halves
    the descriptor fill factor. Measured on the massive benchmark (b50,
    1e13): floor 2^21 -> 1.06M descriptors at 50% fill, 244 s; floor 2^22 ->
    601k descriptors at 75% fill, 184 s, while survival only rises
    10.5% -> 11.3% (the MSD filter saturates at scale, so the coarser floor
    costs almost nothing in extra candidates). A pinned floor
    (NICE_TPU_MSD_FLOOR) is always honored exactly."""
    from nice_tpu.ops import adaptive_floor

    if ctrl.pinned:
        return ctrl.current()
    return max(ctrl.current(), min(field_size >> 21, adaptive_floor.FLOOR_MAX))


class _StridedSetup(NamedTuple):
    plan: object
    ctrl: object
    floor: int
    k: int
    periods: int
    table: object
    spec: object
    desc_max: int
    n_dev: int
    sharded_step: object  # None on single-device


def _strided_setup(base: int, field_size: int) -> "_StridedSetup | None":
    """Kernel-shape derivation shared by warm_niceonly and _niceonly_pallas.

    ONE code path decides (floor, stride depth, periods, descriptor rows,
    sharded step) so a warm-up can never compile a different kernel than the
    field it warms — the drift that would silently re-introduce timed-region
    Mosaic compiles. Returns None when the strided device path cannot run
    this base (too many limbs, or provably no nice numbers)."""
    from nice_tpu.ops import adaptive_floor, stride_filter

    plan = get_plan(base)
    if plan.limbs_n > 4 or stride_filter.stride_residue_count(base, 1) == 0:
        return None
    ctrl = adaptive_floor.get_floor_controller("strided")
    floor = _strided_floor(ctrl, field_size)
    k, periods = _pick_stride_depth(base, floor + floor // 2)
    table = stride_filter.get_stride_table(base, k)
    if table.num_residues == 0:
        return None  # a deeper refinement emptied out: nothing can be nice
    spec = pe.StrideSpec(table.modulus, tuple(table.valid_residues))
    if pe._interpret():
        desc_max, periods = 8, min(periods, 8)  # keep interpreter tests fast
    else:
        desc_max = pe.STRIDED_DESC_MAX
    mesh = _mesh_or_none()
    if mesh is not None:
        from nice_tpu.parallel import mesh as pmesh

        n_dev = mesh.devices.size
        sharded_step = pmesh.make_sharded_strided_step(
            plan, spec, desc_max, periods, mesh
        )
    else:
        n_dev, sharded_step = 1, None
    return _StridedSetup(
        plan, ctrl, floor, k, periods, table, spec, desc_max, n_dev,
        sharded_step,
    )


def resolve_tuning(
    mode: str, base: int, backend: str, batch_size: int | None = None,
) -> tuple[int, int, int, int, int]:
    """Resolve the kernel-shape knobs for one dispatch: (batch_size,
    block_rows, carry_interval, use_mxu, megaloop) under the autotuner's
    env > tuned > default precedence (ops/autotune.py; NICE_TPU_BATCH /
    NICE_TPU_BLOCK_ROWS / NICE_TPU_CARRY_INTERVAL / NICE_TPU_MXU /
    NICE_TPU_MEGALOOP_SEGMENT pin a knob for one run).

    The table is keyed by the backend string the CALLER requested ("jax" /
    "pallas" / "jnp") — the same spelling scripts/tune_kernels.py records
    under — not the _pick_backend resolution; a tuned entry can't leak
    across accelerators anyway because its signature pins the platform.
    An explicitly passed batch_size is honored untouched (bench and the
    tuning harness sweep it themselves); block_rows / carry_interval /
    use_mxu are always resolved. Host backends (scalar/native) get plain
    defaults — these knobs don't exist there.

    use_mxu routes limb products through the banded Toeplitz dot_general
    path (ops/mxu.py, bit-identical); it is forced to 0 for any plan whose
    MXU accumulator bound does not fit i32 (mxu.supports_plan), so a stale
    pin can never select an unprovable kernel.

    megaloop is the segment length of the device-resident batch loop (number
    of batch iterations fused into one lax.scan dispatch); 1 means the
    per-batch feed, and NICE_TPU_MEGALOOP=0 forces it to 1 regardless of
    any tuned/pinned segment length."""
    if backend not in ("jax", "jnp", "pallas"):
        return batch_size or DEFAULT_BATCH_SIZE, pe.BLOCK_ROWS, 0, 0, 1
    from nice_tpu.ops import autotune, mxu

    if batch_size is None:
        batch_size = autotune.choose(
            mode, base, backend, "batch_size", DEFAULT_BATCH_SIZE
        )
    block_rows = autotune.choose(
        mode, base, backend, "block_rows", pe.BLOCK_ROWS
    )
    carry_interval = autotune.choose(
        mode, base, backend, "carry_interval", 0
    )
    use_mxu = autotune.choose(mode, base, backend, "use_mxu", 0)
    if use_mxu and not mxu.supports_plan(get_plan(base)):
        use_mxu = 0
    if knobs.MEGALOOP.get():
        megaloop = autotune.choose(
            mode, base, backend, "megaloop", MEGALOOP_SEGMENT_DEFAULT
        )
        megaloop = max(1, int(megaloop))
    else:
        megaloop = 1
    return batch_size, block_rows, carry_interval, 1 if use_mxu else 0, megaloop


def page_quantum(
    mode: str, base: int, backend: str, batch_size: int | None = None,
) -> int:
    """Numbers per megaloop segment for this workload's tuned shape:
    batch_size * megaloop under the same env > tuned > default precedence
    as resolve_tuning. This is the scheduler's page-alignment quantum —
    a sub-range cut at multiples of it starts and ends exactly on segment
    boundaries, so a page handoff is an elastic interruption point and
    never splits a fused lax.scan dispatch."""
    resolved_batch, _rows, _carry, _mxu, megaloop = resolve_tuning(
        mode, base, backend, batch_size
    )
    return max(1, int(resolved_batch)) * max(1, int(megaloop))


def _batch_arg_shapes(plan):
    """Example (start_limbs, valid_count) arg shapes for AOT lowering."""
    import jax
    import jax.numpy as jnp

    return (
        jax.ShapeDtypeStruct((plan.limbs_n,), jnp.uint32),
        jax.ShapeDtypeStruct((), jnp.int32),
    )


def _detailed_accum_executable(plan, batch_size: int, backend: str,
                               block_rows: int = 0, carry_interval: int = 0,
                               use_mxu: int = 0):
    """AOT-compiled single-device detailed step with a device-resident
    accumulator: exec(hist_acc i32[base+2], start_limbs, valid) ->
    (new_acc, near_miss_count). Cached per (plan, batch, backend, shape
    knobs) so a second field of the same shape never re-lowers (and the
    persistent cache makes a second *process* skip XLA compilation too).
    carry_interval / use_mxu are static argnames burned in at lowering;
    block_rows only shapes the pallas grid (0 = module default)."""
    import jax
    import jax.numpy as jnp

    def build():
        acc = jax.ShapeDtypeStruct((plan.base + 2,), jnp.int32)
        if backend == "pallas":
            br = pe._effective_block_rows(batch_size, block_rows or pe.BLOCK_ROWS)
            jitted = pe._detailed_accum_callable(
                plan, batch_size, br, carry_interval=carry_interval,
                use_mxu=bool(use_mxu),
            )
            return compile_cache.aot(jitted, acc, *_batch_arg_shapes(plan))
        return compile_cache.aot(
            ve.detailed_accum_batch, plan, batch_size, acc,
            *_batch_arg_shapes(plan), carry_interval=carry_interval,
            use_mxu=bool(use_mxu),
        )

    return compile_cache.executable(
        ("detailed-accum", backend, plan, batch_size, block_rows,
         carry_interval, use_mxu),
        build,
    )


def _niceonly_dense_executable(plan, batch_size: int, carry_interval: int = 0,
                               use_mxu: int = 0, fused: bool = False):
    """AOT-compiled single-device dense niceonly count step (jnp; the pallas
    niceonly path is strided and never reaches the dense loop).

    fused=True compiles ve.niceonly_filtered_batch — the residue filter
    evaluated on-device in front of the limb math — whose executable
    returns (nice_count, pruned) instead of a bare count."""

    def build():
        fn = ve.niceonly_filtered_batch if fused else ve.niceonly_dense_batch
        return compile_cache.aot(
            fn, plan, batch_size,
            *_batch_arg_shapes(plan), carry_interval=carry_interval,
            use_mxu=bool(use_mxu),
        )

    return compile_cache.executable(
        ("niceonly-dense", plan, batch_size, carry_interval, use_mxu, fused),
        build,
    )


def _detailed_megaloop_executable(plan, batch_size: int, seg: int,
                                  backend: str, block_rows: int = 0,
                                  carry_interval: int = 0, use_mxu: int = 0):
    """AOT-compiled single-device detailed megaloop: a lax.scan of `seg`
    batch iterations with a device-resident (cursor, remaining, histogram,
    near-miss) carry — exec(hist_acc, start_limbs, valid_total) ->
    (new_acc, near_miss_count). One dispatch and one 4-byte readback per
    segment instead of per batch. Keyed on the segment shape so warm
    restarts (and tail segments of a different length) hit the executable
    cache without re-lowering."""
    import jax
    import jax.numpy as jnp

    def build():
        acc = jax.ShapeDtypeStruct((plan.base + 2,), jnp.int32)
        if backend == "pallas":
            br = pe._effective_block_rows(
                batch_size, block_rows or pe.BLOCK_ROWS
            )
            jitted = pe._detailed_megaloop_callable(
                plan, batch_size, seg, br, carry_interval=carry_interval,
                use_mxu=bool(use_mxu),
            )
            return compile_cache.aot(jitted, acc, *_batch_arg_shapes(plan))
        return compile_cache.aot(
            ve.detailed_accum_megaloop, plan, batch_size, seg, acc,
            *_batch_arg_shapes(plan), carry_interval=carry_interval,
            use_mxu=bool(use_mxu),
        )

    return compile_cache.executable(
        ("detailed-mega", backend, plan, batch_size, seg, block_rows,
         carry_interval, use_mxu),
        build,
    )


def _niceonly_megaloop_executable(plan, batch_size: int, seg: int,
                                  carry_interval: int = 0, use_mxu: int = 0,
                                  fused: bool = False):
    """AOT-compiled single-device dense niceonly megaloop (jnp): a lax.scan
    of `seg` count batches with a device-resident carry. Returns
    exec(start_limbs, valid_total) -> count (unfused) or (count, pruned)
    (fused residue filter). Keyed on the segment shape like the detailed
    variant."""

    def build():
        fn = (
            ve.niceonly_filtered_megaloop if fused
            else ve.niceonly_dense_megaloop
        )
        return compile_cache.aot(
            fn, plan, batch_size, seg,
            *_batch_arg_shapes(plan), carry_interval=carry_interval,
            use_mxu=bool(use_mxu),
        )

    return compile_cache.executable(
        ("niceonly-mega", plan, batch_size, seg, carry_interval, use_mxu,
         fused),
        build,
    )


def _clamp_segment(seg: int, batch_size: int, n_dev: int) -> int:
    """Cap the megaloop segment so one un-flushed segment stays inside the
    i32 histogram-bin headroom budget: flush_every is computed from the
    per-dispatch lane count (batch_size * seg * n_dev), and a segment whose
    own lanes exceed half the i32 range would make flush_every=1 vacuous."""
    return max(1, min(int(seg), ((1 << 31) - 1) // (2 * batch_size * n_dev)))


def warm_detailed(base: int, batch_size: int | None = None,
                  backend: str = "jax") -> None:
    """Pre-lower/AOT-compile the exact per-batch executables a detailed field
    of this shape will dispatch (the detailed analog of warm_niceonly).
    Benchmarks call this before the timed region; a client calls it per
    claimed field — after the first call per (base, batch, backend) it is a
    pure executable-cache hit, and with JAX_COMPILATION_CACHE_DIR set a fresh
    process deserializes instead of recompiling. batch_size=None resolves the
    shape knobs through resolve_tuning exactly like the field dispatch will,
    so the warm compiles the kernel the field actually runs."""
    if backend in ("scalar", "native"):
        return
    compile_cache.setup()
    batch_size, block_rows, carry_interval, use_mxu, mega = resolve_tuning(
        "detailed", base, backend, batch_size
    )
    plan = get_plan(base)
    backend = _pick_backend(plan, batch_size, backend)
    mesh = _mesh_or_none()
    if mesh is not None:
        from nice_tpu.parallel import mesh as pmesh

        # parallel/mesh.py caches these per (kind, device ids, shape), so the
        # warm IS the field's step — no second memo layer that would pin a
        # stale Mesh across a downshift.
        n_dev = int(mesh.devices.size)
        seg = _clamp_segment(mega, batch_size, n_dev)
        if seg > 1:
            pmesh.make_sharded_megaloop_accum_step(
                plan, batch_size, seg, mesh, kernel=backend
            )
        else:
            pmesh.make_sharded_stats_accum_step(
                plan, batch_size, mesh, kernel=backend
            )
        pmesh.make_sharded_stats_fold(mesh)
    else:
        seg = _clamp_segment(mega, batch_size, 1)
        if seg > 1:
            _detailed_megaloop_executable(
                plan, batch_size, seg, backend, block_rows, carry_interval,
                use_mxu,
            )
        else:
            _detailed_accum_executable(
                plan, batch_size, backend, block_rows, carry_interval,
                use_mxu,
            )


def warm_niceonly(base: int, field_size: int = 0, field_start: int | None = None) -> None:
    """Compile (and execute once, with zero real rows) the exact strided
    kernel a niceonly field will run at the current adaptive floor.
    Benchmarks call this before the timed region; a client can call it per
    claimed field — after the first call per (base, floor) it is a single
    cached dispatch of an all-padding group.

    The reference has no analog (CUDA JIT-compiles per arch at startup,
    client_process_gpu.rs:249-259); under XLA, compile happens at first
    dispatch, so without an explicit warm a benchmark's first field would
    time Mosaic compilation instead of throughput. field_size feeds the
    huge-field floor guard (_strided_floor), which shapes the kernel."""
    if field_size:
        # Fields this size may route to the native host engine instead of
        # the device (_host_route_niceonly); warm THAT path — stride table,
        # native library, and the polynomial-residue context — and skip the
        # (unused) device kernel compile. Eligibility is probed at the real
        # field position when given, else at the top of the base range (the
        # worst case for the kernel's u64 bounds).
        br = base_range.get_base_range(base)
        if br is not None and br[1] > br[0]:
            if field_start is not None:
                probe = FieldSize(
                    max(br[0], min(field_start, br[1] - 1)),
                    max(br[0] + 1, min(field_start + field_size, br[1])),
                )
            else:
                probe = FieldSize(max(br[0], br[1] - field_size), br[1])
            if _host_route_niceonly(probe, base):
                _native_niceonly(
                    FieldSize(br[0], min(br[1], br[0] + 1024)), base, None, 1,
                    msd_floor=1 << 18,
                )
                return
    s = _strided_setup(base, field_size)
    if s is None:
        return
    packed = np.zeros((s.desc_max * s.n_dev, 12), dtype=np.uint32)
    if s.sharded_step is not None:
        # nicelint: fence (warm-up: force compile + first step)
        np.asarray(s.sharded_step(packed, np.zeros(s.n_dev, dtype=np.int32)))
    else:
        # nicelint: fence (warm-up: force compile + first step)
        np.asarray(
            pe.niceonly_strided_batch(
                s.plan, s.spec, packed, periods=s.periods, n_real=0
            )
        )


def _niceonly_pallas(core: FieldSize, base: int, progress=None,
                     checkpoint=None, checkpoint_batches=None,
                     checkpoint_secs=None) -> list[int]:
    """Device niceonly: host MSD filter (coarse floor) -> stride-compacted
    descriptor batches on the TPU -> host re-scan of hit descriptors.

    checkpoint: optional callable(watermark, found) fired from the collector
    thread on the periodic cadence. Groups are collected strictly in order
    and the MSD/stride gaps hold no nice numbers, so at call time `found`
    holds EVERY nice number in [core.start, watermark).

    The heterogeneous pipeline of the reference GPU path
    (client_process_gpu.rs:589-709): the host filter produces range
    descriptors, the device counts nice candidates per descriptor by index
    arithmetic (P7), and only descriptors with hits are re-enumerated on the
    host to recover the actual numbers. A count/re-scan mismatch raises (the
    reference treats inconsistent device output as a hard error,
    client_process_gpu.rs:776-781).
    """
    import time

    from nice_tpu.ops import msd_filter

    # Coarse host filter down to the adaptive recursion floor: cheap device
    # lanes make a high floor optimal (reference floor sweep,
    # client_process_gpu.rs:85-94); the controller retunes it per field to
    # hold host-filter time ~= device-tail time, and NICE_TPU_MSD_FLOOR pins
    # it (the analog of NICE_GPU_MSD_FLOOR, client_process_gpu.rs:103-184).
    # _strided_setup is shared with warm_niceonly, so a warm-up compiles
    # EXACTLY this field's kernel; None means provably nothing to search.
    s = _strided_setup(base, core.size())
    if s is None:
        return []
    plan, ctrl, floor_used = s.plan, s.ctrl, s.floor
    k, periods, table, spec = s.k, s.periods, s.table, s.spec
    desc_max, n_dev, sharded_step = s.desc_max, s.n_dev, s.sharded_step
    modulus = table.modulus
    span = periods * modulus
    # Descriptor batches shard across the mesh when >1 device is visible:
    # each device runs the strided kernel on its own desc_max rows and the
    # per-descriptor count tiles are stacked (not reduced — the host needs
    # every count to pick re-scan ranges).
    group_cap = desc_max * n_dev

    nice: list[int] = []

    # --- 3-thread heterogeneous pipeline -----------------------------------
    # producer thread:  native MSD filter over processing chunks -> q_ranges
    # dispatcher (this thread): descriptor columns -> device executions
    # collector thread: count readbacks + host re-scans of hit descriptors
    #
    # This is the overlapped thread fan-out of the reference GPU client
    # (client_process_gpu.rs:589-709: filter threads stream range descriptors
    # over an mpsc channel into batched launches while the device drains
    # earlier batches). Field time is max(host filter, device tail), not
    # host + device: the native filter and the collector's readback/re-scan
    # both release the GIL, so all three stages make progress even on a
    # 1-core host, and the count readback RTT (~68 ms/group through the axon
    # tunnel) comes off the dispatch thread's critical path entirely.
    import queue as queue_mod
    import threading

    host_busy = [0.0]   # accumulated native-filter seconds (producer)
    dev_busy = [0.0]    # accumulated readback+re-scan seconds (collector)
    prod_err: list = [None]
    stop = threading.Event()
    q_ranges: queue_mod.Queue = queue_mod.Queue(maxsize=8)

    # Producer chunk: enough leaves that each native call amortizes its
    # ctypes overhead, small enough that the dispatcher starts quickly and
    # the two stages interleave smoothly. The size//256 term keeps huge
    # fields at ~256 chunks total (massive at floor*256 alone would make
    # ~19k native calls, ~0.8 s of pure ctypes overhead) — 256 chunks is
    # still far finer than the pipeline needs for overlap.
    chunk = max(floor_used * 256, core.size() // 256)
    n_ranges = [0]

    # Filter-thread pool size: the reference fans its MSD filter across N
    # CPU threads feeding the GPU launches (client_process_gpu.rs:624-660).
    # The native filter releases the GIL, so a pool gets real parallelism on
    # multi-core hosts; chunk RESULTS are emitted strictly in submission
    # order (coalesced_stream's single-pass merge depends on ascending
    # ranges). On this repo's 1-core bench host the pool degenerates to the
    # old serial behavior at n=1... with n>1 it simply overlaps in the GIL
    # gaps, so the default is the full NICE_THREADS/cpu count.
    n_filter_threads = _native_threads()

    def produce():
        from concurrent.futures import ThreadPoolExecutor

        def spans():
            pos = core.start()
            while pos < core.end():
                sub_end = min(pos + chunk, core.end())
                yield pos, sub_end
                pos = sub_end

        def filt(span):
            t0 = time.monotonic()
            rs = msd_filter.get_valid_ranges(
                FieldSize(span[0], span[1]), base,
                min_range_size=floor_used,
                max_depth=_msd_depth_for(span[1] - span[0], floor_used),
            )
            return rs, time.monotonic() - t0

        try:
            with ThreadPoolExecutor(
                max_workers=n_filter_threads, thread_name_prefix="niceonly-msd"
            ) as pool:
                pending: deque = deque()
                it = spans()
                done = False
                while not stop.is_set():
                    while not done and len(pending) < n_filter_threads + 2:
                        span = next(it, None)
                        if span is None:
                            done = True
                            break
                        pending.append((span, pool.submit(filt, span)))
                    if not pending:
                        break
                    span, fut = pending.popleft()
                    rs, secs = fut.result()
                    host_busy[0] += secs
                    while not stop.is_set():
                        try:
                            q_ranges.put(rs, timeout=0.2)
                            break
                        except queue_mod.Full:
                            continue
                    if progress is not None:
                        # Filter-front progress: the dispatcher/device trail
                        # by at most the bounded queues, so this tracks field
                        # completion to within a few descriptor groups.
                        progress(span[1] - core.start(), core.size())
                if stop.is_set():
                    for _, fut in pending:
                        fut.cancel()
        except BaseException as e:  # noqa: BLE001 — re-raised on main thread
            prod_err[0] = e
        finally:
            while True:
                try:
                    q_ranges.put(None, timeout=0.2)  # sentinel
                    break
                except queue_mod.Full:
                    if stop.is_set():
                        break  # dispatcher exited; nobody waits for us

    def range_stream():
        while True:
            rs = q_ranges.get()
            if rs is None:
                if prod_err[0] is not None:
                    raise prod_err[0]
                return
            n_ranges[0] += len(rs)
            yield from rs

    # Descriptors stream as numpy COLUMNS, never as a materialized Python
    # list: the massive benchmark (1e13 @ b50) has ~3e7 descriptors, so
    # per-descriptor Python objects or int_to_limbs calls would dominate the
    # run (the reference hit the same wall and batches 65k ranges per launch,
    # client_process_gpu.rs:667-682). Values are carried as TWO u64 half
    # columns — strided-capable bases go up to limbs_n == 4 (< 2^128), and
    # bases 60-95 really do have range ends above 2^64.
    M64 = np.uint64(0xFFFFFFFFFFFFFFFF)
    mask32 = np.uint64(0xFFFFFFFF)

    def _halves(x: int, k: int) -> tuple[np.ndarray, np.ndarray]:
        return (
            np.full(k, x & 0xFFFFFFFFFFFFFFFF, dtype=np.uint64),
            np.full(k, x >> 64, dtype=np.uint64),
        )

    def coalesced_stream():
        """Merge adjacent surviving ranges into maximal runs.

        Surviving leaves cluster (massive's tail region runs at 35-43%
        survival), and every range boundary costs ~half a descriptor span
        of masked lanes on average; merging the 2^22-floor leaves of the
        massive benchmark removes ~20% of all device lanes. The producer
        emits ranges in ascending order (chunked recursion preserves
        order), so a single-pass merge suffices.

        Runs flush at 64 descriptor spans: an unbounded merge would hold
        back a completely-gap-free field's single run until the host filter
        finished (serializing the pipeline this function sits inside) and
        then materialize whole-field columns at once; at 64 spans the split
        boundary costs <1% extra lanes while dispatch stays streaming."""
        flush_limit = span * 64
        cur_lo = cur_hi = None
        for r in range_stream():
            lo, hi = r.start(), r.end()
            if cur_hi == lo:
                cur_hi = hi
            else:
                if cur_lo is not None:
                    yield cur_lo, cur_hi
                cur_lo, cur_hi = lo, hi
            if cur_hi - cur_lo >= flush_limit:
                yield cur_lo, cur_hi
                cur_lo = cur_hi = None
        if cur_lo is not None:
            yield cur_lo, cur_hi

    def desc_columns():
        """Yield 6 u64 column arrays (n0_lo, n0_hi, lo_lo, lo_hi, hi_lo,
        hi_hi) per surviving (coalesced) MSD run."""
        for lo, hi in coalesced_stream():
            first = (lo // modulus) * modulus
            k = -(-(hi - first) // span)
            if k <= 0:
                continue
            # n0 = first + i*span as split u64 halves with vectorized carry.
            offs = np.arange(k, dtype=np.uint64) * np.uint64(span)
            n0_lo = (np.uint64(first & 0xFFFFFFFFFFFFFFFF) + offs) & M64
            carry = (n0_lo < offs).astype(np.uint64)
            n0_hi = np.uint64(first >> 64) + carry
            yield (n0_lo, n0_hi, *_halves(lo, k), *_halves(hi, k))

    def grouped_columns():
        """Re-chunk the per-range columns into group_cap-sized groups."""
        bufs: list[list[np.ndarray]] = [[] for _ in range(6)]
        buffered = 0
        for cols in desc_columns():
            for b, c in zip(bufs, cols):
                b.append(c)
            buffered += len(cols[0])
            while buffered >= group_cap:
                cat = [np.concatenate(b) for b in bufs]
                yield tuple(c[:group_cap] for c in cat)
                bufs = [[c[group_cap:]] for c in cat]
                buffered = len(bufs[0][0])
        if buffered:
            yield tuple(np.concatenate(b) for b in bufs)

    def pack(cols) -> np.ndarray:
        arr = np.zeros((group_cap, 12), dtype=np.uint32)
        k = len(cols[0])
        for j in range(6):  # u64 half j fills u32 limb pair (2*j, 2*j+1)
            arr[:k, 2 * j] = (cols[j] & mask32).astype(np.uint32)
            arr[:k, 2 * j + 1] = (cols[j] >> np.uint64(32)).astype(np.uint32)
        return arr

    def _at(cols, j: int, g: int) -> int:
        return int(cols[2 * j][g]) | (int(cols[2 * j + 1][g]) << 64)

    audit_every = knobs.AUDIT_EVERY.get(default=STRIDE_AUDIT_EVERY)
    audit_seen = [0]  # zero-count descriptors seen so far (audit phase)
    ticker = (
        _CkptTicker(checkpoint_batches, checkpoint_secs)
        if checkpoint else None
    )

    def collect_item(cols, counts_dev):
        # Per-device (8, 128) tiles: descriptor (dev d, local i) count lands
        # flat at [d, i] after collapsing each device's tile.
        # nicelint: fence (descriptor-count tile readback)
        counts = np.asarray(counts_dev).reshape(n_dev, -1)
        k = len(cols[0])
        flat = counts[:, :desc_max].reshape(-1)[:k]
        for g in np.nonzero(flat)[0].tolist():
            n0, lo, hi = _at(cols, 0, g), _at(cols, 1, g), _at(cols, 2, g)
            count = int(flat[g])
            found = _host_strided_scan(
                table, base, max(lo, n0), min(hi, n0 + span)
            )
            if len(found) != count:
                raise RuntimeError(
                    f"device/host nice-count mismatch in descriptor "
                    f"(n0={n0}, [{lo},{hi})): device {count}, host {len(found)}"
                )
            nice.extend(found)
        if audit_every:
            # Sampled undercount audit: host re-scan every audit_every'th
            # zero-count descriptor; any hit the device missed is a hard
            # error (see STRIDE_AUDIT_EVERY).
            zeros = np.nonzero(flat == 0)[0]
            first = (-audit_seen[0]) % audit_every
            for j in range(first, len(zeros), audit_every):
                g = int(zeros[j])
                n0, lo, hi = _at(cols, 0, g), _at(cols, 1, g), _at(cols, 2, g)
                found = _host_strided_scan(
                    table, base, max(lo, n0), min(hi, n0 + span)
                )
                if found:
                    raise RuntimeError(
                        f"device undercount: descriptor (n0={n0}, "
                        f"[{lo},{hi})) counted 0 on device but host found "
                        f"{len(found)} nice numbers (audit)"
                    )
                ENGINE_AUDITS.inc()
            audit_seen[0] += len(zeros)
        if ticker is not None and ticker.tick():
            # Watermark = coverage frontier of this (in-order) group: the end
            # of its last descriptor. Everything below it is either collected
            # or a filter gap that provably holds no nice numbers.
            g = k - 1
            watermark = min(_at(cols, 2, g), _at(cols, 0, g) + span)
            checkpoint(watermark, list(nice))

    def timed_collect_item(cols, counts_dev):
        t0 = time.monotonic()
        collect_item(cols, counts_dev)
        secs = time.monotonic() - t0
        dev_busy[0] += secs
        ENGINE_BATCH_KERNEL_SECONDS.labels("strided").observe(secs)

    producer = threading.Thread(target=produce, name="niceonly-msd", daemon=True)
    t_wall0 = time.monotonic()
    producer.start()
    n_desc = 0
    n_groups = 0
    # Dispatcher stall accounting: gen (host desc-gen + waiting on the
    # producer), disp (jax dispatch call), put (backpressure from the
    # collector/device window) — the trace tells which stage bounds the wall.
    t_gen = t_disp = t_put = 0.0
    try:
        with _Collector(
            timed_collect_item, STRIDE_WINDOW, "niceonly-collect",
            on_fail=stop.set, occupancy=ENGINE_STRIDE_OCCUPANCY,
        ) as collector:
            try:
                t0 = time.monotonic()
                for cols in grouped_columns():
                    t1 = time.monotonic()
                    t_gen += t1 - t0
                    if collector.failed():
                        break
                    k_real = len(cols[0])
                    n_desc += k_real
                    ENGINE_DESCRIPTORS.inc(k_real)
                    _fire_dispatch_fault(n_groups, "pallas", _at(cols, 0, 0))
                    n_groups += 1
                    packed = pack(cols)
                    if sharded_step is not None:
                        per_dev_real = np.clip(
                            k_real - np.arange(n_dev) * desc_max, 0, desc_max
                        ).astype(np.int32)
                        counts = sharded_step(packed, per_dev_real)
                    else:
                        counts = pe.niceonly_strided_batch(
                            plan, spec, packed, periods=periods, n_real=k_real
                        )
                    t2 = time.monotonic()
                    t_disp += t2 - t1
                    collector.put((cols, counts))
                    t0 = time.monotonic()
                    t_put += t0 - t2
            finally:
                # Stop the producer before the collector drains so a failing
                # run does not keep filtering for a full producer chunk.
                stop.set()
    finally:
        producer.join()
    if prod_err[0] is not None:
        raise prod_err[0]
    collector.raise_if_failed()
    wall = time.monotonic() - t_wall0
    # The controller balances producer busy-time against collector busy-time
    # (readback + re-scan): with the stages overlapped, wall ~= max of the
    # two, and the floor sweet spot is still where they meet. When the
    # huge-field guard overrode the floor, the split was measured at a floor
    # the controller is not at, so feeding it back would mis-tune the
    # production floor — skip.
    if floor_used == ctrl.current():
        # host_busy sums per-thread filter seconds; the controller balances
        # WALL times, so scale by the real parallelism available to the pool.
        eff = max(1, min(n_filter_threads, os.cpu_count() or 1))
        ctrl.observe(host_busy[0] / eff, dev_busy[0], core.size())
    # Per-phase trace (the reference logs its msd/gpu-tail split per field,
    # client_process_gpu.rs:103-184): floor + depth + busy seconds per stage.
    # INFO, not DEBUG: bench.py configures INFO logging so the driver
    # artifact's stderr tail carries every mode's phase split (VERDICT r4
    # weak #2 — massive's wall time was unexplainable from the record).
    log.info(
        "niceonly b%d [%d, %d): wall %.3fs | msd %.3fs busy (floor %d, %d "
        "ranges) | collect %.3fs busy (k=%d periods=%d, %d descriptors, %d "
        "devices) | dispatch gen %.3fs disp %.3fs put %.3fs | %d nice",
        base, core.start(), core.end(), wall, host_busy[0], floor_used,
        n_ranges[0], dev_busy[0], k, periods, n_desc, n_dev,
        t_gen, t_disp, t_put, len(nice),
    )
    return nice


def process_range_detailed(
    range_: FieldSize,
    base: int,
    backend: str = "jax",
    batch_size: int | None = None,
    progress=None,
    *,
    checkpoint_cb=None,
    resume=None,
    checkpoint_batches=None,
    checkpoint_secs=None,
) -> FieldResults:
    """Full histogram + near-miss list, exact, any backend — with graceful
    backend degradation: a mid-field dispatch failure on pallas re-dispatches
    the failed batch (and the rest of the field) on jnp, and a jnp failure on
    the scalar oracle, resuming from the failure cursor so completed work is
    kept. Downgrades land in FieldResults.backend_downgrades and the
    nice_engine_backend_downgrades_total counter; NICE_TPU_NO_FALLBACK=1
    disables the chain. See _process_range_detailed for the full
    checkpoint/resume contract."""
    return _run_with_fallback(
        _process_range_detailed, range_, base, backend,
        dict(
            batch_size=batch_size, progress=progress,
            checkpoint_cb=checkpoint_cb, resume=resume,
            checkpoint_batches=checkpoint_batches,
            checkpoint_secs=checkpoint_secs,
        ),
    )


def _process_range_detailed(
    range_: FieldSize,
    base: int,
    backend: str = "jax",
    batch_size: int | None = None,
    progress=None,
    *,
    checkpoint_cb=None,
    resume=None,
    checkpoint_batches=None,
    checkpoint_secs=None,
) -> FieldResults:
    """Full histogram + near-miss list, exact, any backend.

    progress: optional callable(done_numbers, total_numbers) invoked from the
    dispatch loop (the reference client's tqdm per-field progress,
    client/src/main.rs:183-196); may be called from a worker thread.

    checkpoint_cb: optional callable(state) fired every checkpoint_batches
    dispatches / checkpoint_secs seconds (NICE_TPU_CKPT_BATCHES /
    NICE_TPU_CKPT_SECS when unset) with a CONSISTENT resume state:
    {"cursor": pos, "hist": int64[base+2], "nice_numbers": [(number,
    uniques), ...]} where every candidate in [range.start, pos) plus any
    out-of-range slivers is fully folded in. It runs on the collector thread
    (the only thread that mutates hist/nice_numbers), so the state it sees
    always matches its cursor. resume: a state previously handed to
    checkpoint_cb; the scan restarts at its cursor with histogram/survivors
    preloaded and slivers NOT recomputed. backend='native' supports neither
    (checkpoint_cb is ignored; resume raises).

    batch_size=None (the default) resolves batch/block_rows/carry_interval
    through the autotuner (resolve_tuning: env > tuned winners > defaults);
    an explicit batch_size pins the batch and still resolves the others."""
    batch_size, block_rows, carry_interval, use_mxu, mega = resolve_tuning(
        "detailed", base, backend, batch_size
    )
    if backend == "scalar":
        if checkpoint_cb is None and resume is None:
            with obs.span("engine.scalar", base=base, size=range_.size(),
                          mode="detailed", backend="scalar"):
                return scalar.process_range_detailed(range_, base)
        return _chunked_host_scan(
            range_, base, "detailed", batch_size, progress,
            checkpoint_cb, resume, checkpoint_batches, checkpoint_secs,
        )
    if backend == "native":
        if resume is not None:
            raise ValueError(
                "backend 'native' does not support resuming from a checkpoint"
            )
        return _native_detailed(range_, base, _native_threads(), progress)
    if backend not in ("jax", "jnp", "pallas"):
        raise ValueError(f"unknown backend {backend!r}")

    core, slivers = _split_for_jax(
        range_, base, lambda part: scalar.process_range_detailed(part, base),
        skip_slivers=resume is not None,
    )
    if core is None:
        if checkpoint_cb is None and resume is None:
            return scalar.process_range_detailed(range_, base)
        return _chunked_host_scan(
            range_, base, "detailed", batch_size, progress,
            checkpoint_cb, resume, checkpoint_batches, checkpoint_secs,
        )

    plan = get_plan(base)
    backend = _pick_backend(plan, batch_size, backend)
    compile_cache.setup()
    # Device-step profiler (NICE_TPU_STEPPROF=1): started here so AOT
    # compile_cache builds below attribute to this field via the
    # thread-local stack; stop() pairs with every exit after the collector.
    prof = stepprof.StepProfiler("detailed", base, backend).start()
    hist = np.zeros(plan.base + 2, dtype=np.int64)
    nice_numbers: list[NiceNumberSimple] = []
    for sub in slivers:
        for d in sub.distribution:
            hist[d.num_uniques] += d.count
        nice_numbers.extend(sub.nice_numbers)

    # Dispatch batches asynchronously ahead of collection (the device queue
    # executes in order while the host keeps dispatching — the reference's
    # overlapped launch pipeline, client_process_gpu.rs:667-682). The window
    # bounds in-flight device buffers so arbitrarily large fields run in
    # constant memory.
    #
    # Pod layer on top: the core splits into one work queue per device
    # (per-slice cursors), a _SliceFeed precomputes the next super-batch's
    # limb rows on its own thread while batch k runs on-device
    # (NICE_TPU_FEED_DEPTH), and a device loss mid-field reshards the
    # REMAINING segments onto the survivor mesh instead of downgrading the
    # whole field to jnp/scalar (NICE_TPU_ELASTIC=0 restores that).
    #
    # The histogram lives ON THE DEVICE across batches: each dispatch donates
    # the running accumulator back to the step (jit donate_argnums), so the
    # only per-batch readback is the 4-byte near-miss scalar. The accumulator
    # transfers once per field (plus i32-overflow guard flushes), and on the
    # sharded path the per-device rows are psum'd exactly once at field end.
    mesh = _mesh_or_none()
    if mesh is not None:
        from nice_tpu.parallel import mesh as pmesh
    else:
        pmesh = None
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    # Megaloop (PR 17): fuse `seg` batch iterations into one device-resident
    # lax.scan per dispatch. The feed item granularity becomes one SEGMENT
    # (batch_size * seg lanes per device); the dispatch/collector/checkpoint
    # machinery below is untouched because a segment looks exactly like a
    # large batch to it — one dispatch, one nm readback, markers at segment
    # boundaries (the only forced sync cadence).
    seg = _clamp_segment(mega, batch_size, n_dev)

    def _bind(mesh_, n_dev_):
        """(dispatch, new_acc, fold_np) for the current mesh layout —
        rebuilt by the elastic downshift when the layout shrinks. backend is
        already resolved to exactly "pallas" or "jnp" here; pass it through
        so an explicit backend="jnp" is honored on TPU too."""
        if mesh_ is not None:
            if seg > 1:
                step = pmesh.make_sharded_megaloop_accum_step(
                    plan, batch_size, seg, mesh_, kernel=backend
                )
            else:
                step = pmesh.make_sharded_stats_accum_step(
                    plan, batch_size, mesh_, kernel=backend
                )
            fold = pmesh.make_sharded_stats_fold(mesh_)

            def disp(acc_, item):
                return step(acc_, item.starts, item.valids)

            def mk_acc():
                return np.zeros((n_dev_, plan.base + 2), dtype=np.int32)

            def fold_np(acc_):
                # ONE psum per field/flush, off the dispatch thread.
                # nicelint: fence (single psum readback per field/flush)
                return np.asarray(fold(acc_), dtype=np.int64)[: plan.base + 2]
        else:
            # Tuned shape knobs apply on the single-device path; the sharded
            # step above stays at module defaults (its per-device kernel
            # shape is owned by parallel/mesh.py).
            if seg > 1:
                accum_exec = _detailed_megaloop_executable(
                    plan, batch_size, seg, backend, block_rows,
                    carry_interval, use_mxu,
                )
            else:
                accum_exec = _detailed_accum_executable(
                    plan, batch_size, backend, block_rows, carry_interval,
                    use_mxu,
                )

            def disp(acc_, item):
                return accum_exec(
                    acc_, item.starts[0], np.int32(int(item.valids[0]))
                )

            def mk_acc():
                return np.zeros(plan.base + 2, dtype=np.int32)

            def fold_np(acc_):
                # nicelint: fence (accumulator readback at fold time)
                return np.asarray(acc_, dtype=np.int64)[: plan.base + 2]

        return disp, mk_acc, fold_np

    dispatch, new_acc, fold_np = _bind(mesh, n_dev)
    lanes = batch_size * seg * n_dev

    start = core.start()
    total = core.size()
    segments = [(start, core.end())] if total else []

    if resume is not None:
        if resume.get("hist") is None:
            raise ValueError("detailed resume state is missing a histogram")
        # nicelint: allow D1 (resume histogram arrives as host JSON)
        h = np.asarray(resume["hist"], dtype=np.int64)
        if h.shape != hist.shape:
            raise ValueError(
                f"resume histogram shape {h.shape} != {hist.shape}"
            )
        hist[:] = h
        nice_numbers[:] = [
            NiceNumberSimple(number=int(n), num_uniques=int(u))
            for n, u in resume["nice_numbers"]
        ]
        segments = _resume_segments(resume, start, core.end())
        done0 = total - sum(e - s for s, e in segments)
        CKPT_RESTORES.inc()
        CKPT_BATCHES_SKIPPED.inc(done0 // lanes)
        log.info(
            "detailed resume: %d segment(s) remaining (%d of %d numbers "
            "already done)", len(segments), done0, total,
        )
    else:
        done0 = 0

    import time as _time

    def _ckpt_state(rem):
        return {
            "cursor": rem[0][0] if rem else core.end(),
            "hist": hist.copy(),
            "nice_numbers": [
                (n.number, n.num_uniques) for n in nice_numbers
            ],
            "remaining": [[s, e] for s, e in rem],
        }

    def collect_item(kind, *payload):
        t0 = _time.monotonic()
        if kind == "nm":
            segs, nm = payload
            ENGINE_READBACK_BYTES.labels("nm").inc(4)
            # nicelint: fence (nm flag readback gates the rare path)
            if int(np.asarray(nm)) > 0:
                # Rare path: compacted survivor extraction, per slice seg.
                for seg_start, seg_valid in segs:
                    if seg_valid <= 0:
                        continue
                    for number, uniq in _rare_scan_survivors(
                        plan, seg_start, seg_valid, batch_size, backend,
                        plan.near_miss_cutoff,
                    ):
                        nice_numbers.append(
                            NiceNumberSimple(number=number, num_uniques=uniq)
                        )
        elif kind == "stats":  # device-resident accumulator, ~once per field
            acc_, fold_fn = payload
            h = fold_fn(acc_)
            ENGINE_READBACK_BYTES.labels("stats").inc(h.nbytes)
            ENGINE_STATS_TRANSFERS.labels("detailed").inc()
            # Bin 0 carries tail-padding lane counts; no consumer reads it
            # (distributions report bins 1..base), so no correction needed.
            np.add(hist, h, out=hist)
        elif kind == "stats_host":  # already folded (downshift boundary)
            (h,) = payload
            ENGINE_STATS_TRANSFERS.labels("detailed").inc()
            np.add(hist, h, out=hist)
        else:  # "ckpt": marker enqueued AFTER a stats flush — everything up
            # to its remaining-set is already folded into hist/nice_numbers.
            (rem,) = payload
            checkpoint_cb(_ckpt_state(rem))
        dt = _time.monotonic() - t0
        if prof.enabled:  # collector thread; add() is lock-guarded
            if kind == "nm":
                prof.add("readback", dt)
            elif kind in ("stats", "stats_host"):
                prof.add("fold", dt)
        ENGINE_BATCH_KERNEL_SECONDS.labels("detailed").observe(dt)

    # Collection (the near-miss readback + rare-path re-scan) runs on its
    # own thread: each readback pays the device->host RTT (~68 ms through
    # the axon tunnel), which would otherwise serialize against dispatch.
    # Only the collector touches hist/nice_numbers.
    # i32 histogram bins saturate after ~2^31 counts; every batch adds at
    # most `lanes` to a bin (padding also lands in bin 0), so flush the
    # accumulator to the collector with wide margin before that.
    flush_every = max(1, ((1 << 31) - 1) // (2 * lanes))
    ticker = (
        _CkptTicker(checkpoint_batches, checkpoint_secs)
        if checkpoint_cb else None
    )
    feed_depth = _feed_depth()
    acc = new_acc()
    since_flush = 0
    done = done0
    n_batch = 0
    n_dev0 = n_dev
    reshards = 0
    reshard_secs = 0.0
    idle_gaps: list[float] = []
    prof_on = prof.enabled  # hoisted: the disabled per-batch cost is a load
    err_final = None  # (exception, remaining segments or None)
    with _Collector(collect_item, DISPATCH_WINDOW, "detailed-collect",
                    occupancy=ENGINE_DISPATCH_OCCUPANCY) as collector:
        with obs.span("engine.detailed", base=base, size=total,
                      backend=backend):
            while segments:
                if collector.failed():
                    break
                queues = (
                    pmesh.partition_segments(
                        segments, n_dev, batch_size * seg
                    )
                    if mesh is not None else [list(segments)]
                )
                feed = _SliceFeed(
                    plan, queues, batch_size * seg, core.end(), feed_depth
                )
                markers = _SliceFeed.start_markers(queues)
                failure = None
                t_prev = None
                try:
                    while True:
                        if collector.failed():
                            break
                        t_feed = _time.monotonic() if prof_on else 0.0
                        item = feed.get()
                        if item is None:
                            segments = []
                            break
                        now = _time.monotonic()
                        if prof_on:
                            prof.add("h2d_feed", now - t_feed)
                        if t_prev is not None:
                            gap = now - t_prev
                            MESH_FEED_IDLE.labels("detailed").observe(gap)
                            if len(idle_gaps) < 65536:
                                idle_gaps.append(gap)
                        try:
                            # The chaos hooks precede the real dispatch so an
                            # injected failure leaves the donated accumulator
                            # alive and the flush below folds a consistent
                            # prefix.
                            _fire_dispatch_fault(
                                n_batch, backend, item.segs[0][0]
                            )
                            if mesh is not None:
                                _fire_mesh_fault(
                                    n_batch, n_dev, item.segs[0][0]
                                )
                            t_disp = _time.monotonic() if prof_on else 0.0
                            acc, nm = dispatch(acc, item)
                            ENGINE_DISPATCHES.labels("detailed").inc()
                            if prof_on:
                                # Enqueue + jit tracing cost of the call
                                # itself, then the only profiler-added device
                                # sync: fence the step so on-device execution
                                # separates from the host loop. Off = no
                                # fence at all.
                                prof.add(
                                    "device_compute",
                                    _time.monotonic() - t_disp,
                                )
                                prof.fence(nm)
                        except Exception as e:  # noqa: BLE001 — boundary
                            failure = e
                            break
                        t_prev = _time.monotonic()
                        markers = item.markers
                        n_batch += 1
                        since_flush += 1
                        done += item.lanes
                        collector.put(("nm", item.segs, nm))
                        if mesh is not None:
                            for d, (_si, cur) in enumerate(item.markers):
                                MESH_SLICE_CURSOR.labels(str(d)).set(cur)
                        if ticker is not None and ticker.tick():
                            # Export the donated device accumulator ahead of
                            # the marker: by the time "ckpt" reaches the
                            # collector, every batch before it has been
                            # folded host-side.
                            collector.put(("stats", acc, fold_np))
                            acc = new_acc()
                            since_flush = 0
                            collector.put(
                                ("ckpt",
                                 _SliceFeed.remaining(queues, markers))
                            )
                        elif since_flush >= flush_every:
                            collector.put(("stats", acc, fold_np))
                            acc = new_acc()
                            since_flush = 0
                        if progress is not None:
                            progress(done, total)
                finally:
                    feed.stop()
                if failure is None:
                    continue  # exhausted (or collector failed) — loop exits
                rem = _SliceFeed.remaining(queues, markers)
                survivors = None
                if mesh is not None and _elastic_enabled():
                    survivors, reason = _diagnose_survivors(mesh, failure)
                    obs.flight.record(
                        "device_loss", mode="detailed", base=base,
                        survivors=len(survivors) if survivors else 0,
                        reason=reason if survivors else "fatal",
                        error=repr(failure)[:200],
                    )
                if not survivors:
                    err_final = (failure, rem)
                    break
                # Elastic downshift: fold the partial per-device accumulator
                # SYNCHRONOUSLY (the old layout's fold must run before the
                # old mesh goes away), hand the host-side rows to the
                # collector, rebuild the mesh over the survivors, and
                # re-slice the remaining range. No whole-field downgrade.
                t_r0 = _time.monotonic()
                try:
                    folded = fold_np(acc)
                except Exception as fold_err:  # noqa: BLE001
                    # The failure invalidated the donated accumulator: the
                    # unflushed batches are unrecoverable, so no consistent
                    # mid-field state exists — degrade like PR 4 would.
                    log.warning(
                        "downshift abandoned: partial accumulator fold "
                        "failed: %r", fold_err,
                    )
                    err_final = (failure, None)
                    break
                collector.put(("stats_host", folded))
                since_flush = 0
                pmesh.clear_step_cache(pmesh.mesh_device_ids(mesh))
                _invalidate_mesh_cache()
                mesh = _cached_mesh(tuple(survivors))
                prev_n = n_dev
                n_dev = len(survivors)
                dispatch, new_acc, fold_np = _bind(mesh, n_dev)
                acc = new_acc()
                # seg stays fixed across downshifts (the headroom budget only
                # GROWS as n_dev shrinks), so the surviving devices reuse the
                # already-compiled segment executable.
                lanes = batch_size * seg * n_dev
                flush_every = max(1, ((1 << 31) - 1) // (2 * lanes))
                segments = rem
                reshards += 1
                dt = _time.monotonic() - t_r0
                reshard_secs += dt
                MESH_RESHARDS.labels(reason).inc()
                MESH_RESHARD_SECONDS.observe(dt)
                obs.flight.record(
                    "mesh_reshard", mode="detailed", base=base,
                    reason=reason, n_dev=n_dev, lost=prev_n - n_dev,
                )
                obs.trace_event(
                    "mesh.reshard", mode="detailed", base=base,
                    reason=reason, n_dev=n_dev,
                )
                log.warning(
                    "mesh downshift (detailed b%d): %d -> %d devices "
                    "(%s, %r); re-sliced %d remaining segment(s)",
                    base, prev_n, n_dev, reason, failure, len(rem),
                )
            if since_flush:
                # Best-effort on the failure path: a real device error may
                # have invalidated the donated accumulator, in which case the
                # collector's fold fails too and the state below degrades to
                # a full restart.
                collector.put(("stats", acc, fold_np))
    _record_feed_stats("detailed", idle_gaps, n_batch, n_dev0, n_dev,
                       reshards, reshard_secs, feed_depth)
    prof.stop()  # collector drained: fold/readback attribution is complete
    if err_final is not None:
        err, rem = err_final
        # The collector has drained: hist/nice_numbers now cover every batch
        # dispatched before the failure — exactly the checkpoint contract
        # with the failed batch inside the remaining set.
        state = None
        if rem is not None and not collector.failed():
            state = _ckpt_state(rem)
        raise BackendDispatchError(backend, state, err)
    collector.raise_if_failed()
    ENGINE_NUMBERS.labels("detailed").inc(range_.size())

    nice_numbers.sort(key=lambda n: n.number)
    distribution = tuple(
        UniquesDistributionSimple(num_uniques=i, count=int(hist[i]))
        for i in range(1, base + 1)
    )
    return FieldResults(distribution=distribution, nice_numbers=tuple(nice_numbers))


def process_range_niceonly(
    range_: FieldSize,
    base: int,
    stride_table=None,
    backend: str = "jax",
    batch_size: int | None = None,
    progress=None,
    *,
    checkpoint_cb=None,
    resume=None,
    checkpoint_batches=None,
    checkpoint_secs=None,
) -> FieldResults:
    """Nice-number search with graceful backend degradation: a mid-field
    dispatch failure re-dispatches the remainder of the field on the next
    backend in the pallas -> jnp -> scalar chain via the checkpoint/resume
    watermark contract (strided-pipeline failures restart the clipped core —
    its internal state is not resumable from outside). Downgrades land in
    FieldResults.backend_downgrades and the
    nice_engine_backend_downgrades_total counter; NICE_TPU_NO_FALLBACK=1
    disables the chain. See _process_range_niceonly for the full contract."""
    return _run_with_fallback(
        lambda r, b, backend, **kw: _process_range_niceonly(
            r, b, stride_table, backend=backend, **kw
        ),
        range_, base, backend,
        dict(
            batch_size=batch_size, progress=progress,
            checkpoint_cb=checkpoint_cb, resume=resume,
            checkpoint_batches=checkpoint_batches,
            checkpoint_secs=checkpoint_secs,
        ),
    )


def _process_range_niceonly(
    range_: FieldSize,
    base: int,
    stride_table=None,
    backend: str = "jax",
    batch_size: int | None = None,
    progress=None,
    *,
    checkpoint_cb=None,
    resume=None,
    checkpoint_batches=None,
    checkpoint_secs=None,
) -> FieldResults:
    """Nice-number search via the stride-compacted device pipeline (TPU) or
    the dense masked scan (jnp fallback).

    progress: optional callable(done_numbers, total_numbers); on the strided
    path it reports the filter front (see _niceonly_pallas), on the dense
    path dispatched lanes. May be called from a worker thread.

    checkpoint_cb/resume/checkpoint_batches/checkpoint_secs: as in
    process_range_detailed, with state["hist"] always None. The cursor is a
    watermark: every nice number below it is in state["nice_numbers"]. The
    gaps the MSD/stride filters skipped contain no nice numbers by
    construction, so a resume that re-derives the filters (even at a
    different adaptive floor) under any plan with a matching signature finds
    exactly the remaining set.

    batch_size=None resolves batch/carry_interval through the autotuner
    (resolve_tuning); the strided pallas pipeline picks its own shapes and
    ignores the dense-scan knobs."""
    batch_size, _block_rows, carry_interval, use_mxu, mega = resolve_tuning(
        "niceonly", base, backend, batch_size
    )
    if backend == "scalar":
        if checkpoint_cb is None and resume is None:
            with obs.span("engine.scalar", base=base, size=range_.size(),
                          mode="niceonly", backend="scalar"):
                return scalar.process_range_niceonly(
                    range_, base, stride_table
                )
        return _chunked_host_scan(
            range_, base, "niceonly", batch_size, progress,
            checkpoint_cb, resume, checkpoint_batches, checkpoint_secs,
            stride_table=stride_table,
        )
    if backend == "native":
        if resume is not None:
            raise ValueError(
                "backend 'native' does not support resuming from a checkpoint"
            )
        return _native_niceonly(
            range_, base, stride_table, _native_threads(), progress
        )
    if backend not in ("jax", "jnp", "pallas"):
        raise ValueError(f"unknown backend {backend!r}")

    from nice_tpu.ops import msd_filter

    core, slivers = _split_for_jax(
        range_,
        base,
        lambda part: scalar.process_range_niceonly(part, base, stride_table),
        skip_slivers=resume is not None,
    )
    if core is None:
        if checkpoint_cb is None and resume is None:
            return scalar.process_range_niceonly(range_, base, stride_table)
        return _chunked_host_scan(
            range_, base, "niceonly", batch_size, progress,
            checkpoint_cb, resume, checkpoint_batches, checkpoint_secs,
            stride_table=stride_table,
        )

    nice_numbers: list[NiceNumberSimple] = []
    for sub in slivers:
        nice_numbers.extend(sub.nice_numbers)

    resume_segments = None
    resume_filtered = False
    if resume is not None:
        nice_numbers[:] = [
            NiceNumberSimple(number=int(n), num_uniques=int(u))
            for n, u in resume["nice_numbers"]
        ]
        resume_segments = _resume_segments(resume, core.start(), core.end())
        # "filtered" marks a remaining-set whose gaps were already proven
        # empty (MSD/stride) — the dense path scans the segments directly
        # instead of re-deriving the filter.
        resume_filtered = bool(resume.get("filtered"))
        covered = core.size() - sum(e - s for s, e in resume_segments)
        CKPT_RESTORES.inc()
        CKPT_BATCHES_SKIPPED.inc(covered // max(1, batch_size))
        log.info(
            "niceonly resume: %d segment(s) remaining (%d of %d core "
            "numbers already covered)",
            len(resume_segments), covered, core.size(),
        )
        if not resume_segments:
            # The snapshot already covers the whole core; assembly only.
            nice_numbers.sort(key=lambda n: n.number)
            ENGINE_NUMBERS.labels("niceonly").inc(range_.size())
            return FieldResults(
                distribution=(), nice_numbers=tuple(nice_numbers)
            )

    plan = get_plan(base)
    requested = backend
    backend = _pick_backend(plan, batch_size, backend)
    if backend == "pallas" and plan.limbs_n > 4:
        # Strided descriptors carry candidates as 4 u32 limbs (bases up to
        # ~96). An explicit pallas request must not silently change engines.
        if requested == "pallas":
            raise ValueError(
                f"base {base} needs {plan.limbs_n} u32 limbs; the strided "
                "pallas niceonly path carries 4 — use backend='jax' (dense "
                "device scan) or 'native'/'scalar'"
            )
        log.warning(
            "niceonly base %d exceeds 4 u32 limbs; falling back from the "
            "strided pallas path to the dense device scan",
            base,
        )
        ENGINE_HOST_FALLBACK.labels("limbs").inc()
        backend = "jnp"
    if backend == "pallas":
        if resume_segments is not None:
            # The strided pipeline (and the native host route below) scans
            # ONE contiguous core: collapse a per-slice remaining set to its
            # minimum cursor, dropping restored numbers inside the rescanned
            # span so the covered islands above it can't double-report.
            # (Sliver/post-core numbers sit outside [pos, core.end()).)
            pos = resume_segments[0][0]
            core_end = core.end()
            nice_numbers[:] = [
                n for n in nice_numbers
                if n.number < pos or n.number >= core_end
            ]
            if pos > core.start():
                core = FieldSize(pos, core_end)
        if _host_route_niceonly(core, base):
            # Small-field fast path: one device dispatch costs a readback RTT
            # that dwarfs the compute for sub-3e7 fields — the native host
            # kernel finishes before the device round-trip would (see
            # _host_route_niceonly). Cascade semantics are identical.
            # Coarse MSD floor: per-range Python+ctypes overhead (~80 us) is
            # the dominant cost at this scale, and sub-RTT fields are mostly
            # ones the MSD filter cannot prune anyway (else they'd be cheap).
            ENGINE_HOST_FALLBACK.labels("host-route").inc()
            with obs.span("engine.niceonly-host", base=base,
                          size=core.size(), backend="native"):
                sub = _native_niceonly(
                    core, base, None, _native_threads(), progress,
                    msd_floor=max(1 << 20, core.size() // 8),
                )
            nice_numbers.extend(sub.nice_numbers)
            nice_numbers.sort(key=lambda n: n.number)
            ENGINE_NUMBERS.labels("niceonly").inc(range_.size())
            return FieldResults(
                distribution=(), nice_numbers=tuple(nice_numbers)
            )
        # Stride-compacted device path (picks its own table depth via
        # _pick_stride_depth and expands offsets host-side; any passed
        # stride_table only parameterizes the scalar/host paths).
        ckpt_closure = None
        # Freeze the pre-core survivors (slivers / restored prefix): the
        # strided collector only sees numbers from the clipped core.
        prior = [(n.number, n.num_uniques) for n in nice_numbers]
        if checkpoint_cb is not None:

            def ckpt_closure(watermark, found):
                checkpoint_cb({
                    "cursor": watermark,
                    "hist": None,
                    "nice_numbers": prior + [(n, base) for n in found],
                })

        try:
            with obs.span(
                "engine.niceonly-strided", base=base, size=core.size(),
                backend="pallas",
            ):
                found = _niceonly_pallas(
                    core, base, progress=progress,
                    checkpoint=ckpt_closure,
                    checkpoint_batches=checkpoint_batches,
                    checkpoint_secs=checkpoint_secs,
                )
        except Exception as e:  # noqa: BLE001 — degradation boundary
            # The strided pipeline's progress lives in its own threads;
            # restart the (clipped) core on the next backend, keeping the
            # slivers / restored prefix.
            raise BackendDispatchError(
                "pallas",
                {"cursor": core.start(), "hist": None, "nice_numbers": prior},
                e,
            ) from e
        nice_numbers.extend(
            NiceNumberSimple(number=n, num_uniques=base) for n in found
        )
        nice_numbers.sort(key=lambda n: n.number)
        ENGINE_NUMBERS.labels("niceonly").inc(range_.size())
        return FieldResults(distribution=(), nice_numbers=tuple(nice_numbers))

    compile_cache.setup()
    # Device-step profiler — same shape as the detailed path; the dense
    # loop's MSD host filter lands in host_other by construction.
    prof = stepprof.StepProfiler("niceonly", base, backend).start()
    mesh = _mesh_or_none()
    if mesh is not None:
        from nice_tpu.parallel import mesh as pmesh
    else:
        pmesh = None
    n_dev = int(mesh.devices.size) if mesh is not None else 1
    # Megaloop segment for the dense loop — same contract as the detailed
    # path: one lax.scan dispatch covers batch_size * seg lanes per device.
    seg = _clamp_segment(mega, batch_size, n_dev)

    def _bind(mesh_, n_dev_):
        """Dispatch closure for the current mesh layout — rebuilt by the
        elastic downshift. Only the jnp dense path reaches here (the pallas
        strided path returned above), so the per-device kernel is jnp by
        construction. Every dispatch returns (count, pruned) with pruned
        None on the unfused paths, so the collector sees one shape."""
        if mesh_ is not None:
            # The sharded step stays unfused: its per-device kernel shape is
            # owned by parallel/mesh.py.
            if seg > 1:
                step = pmesh.make_sharded_megaloop_count_step(
                    plan, batch_size, seg, mesh_
                )
            else:
                step = pmesh.make_sharded_stats_step(
                    plan, batch_size, mesh_, "niceonly", kernel="jnp"
                )

            def disp(item):
                return step(item.starts, item.valids), None
        else:
            # Fused residue filter (NICE_TPU_FUSED_FILTER, default on):
            # the congruence mask prunes lanes on-device BEFORE limb math,
            # worthwhile whenever the filter actually excludes classes.
            from nice_tpu.ops import residue_filter

            fused = (
                knobs.FUSED_FILTER.get()
                and base > 2
                and len(residue_filter.get_residue_filter(base)) < base - 1
            )
            if seg > 1:
                count_exec = _niceonly_megaloop_executable(
                    plan, batch_size, seg, carry_interval, use_mxu, fused
                )
            else:
                count_exec = _niceonly_dense_executable(
                    plan, batch_size, carry_interval, use_mxu, fused
                )
            if fused:

                def disp(item):
                    return count_exec(
                        item.starts[0], np.int32(int(item.valids[0]))
                    )
            else:

                def disp(item):
                    return count_exec(
                        item.starts[0], np.int32(int(item.valids[0]))
                    ), None

        return disp

    dispatch = _bind(mesh, n_dev)

    import time

    ticker = (
        _CkptTicker(checkpoint_batches, checkpoint_secs)
        if checkpoint_cb else None
    )

    def _ckpt_state(rem):
        # The covered complement of `rem` is scanned OR filtered-empty
        # (MSD gaps), hence "filtered": a resume scans only the segments.
        return {
            "cursor": rem[0][0] if rem else core.end(),
            "hist": None,
            "nice_numbers": [
                (n.number, n.num_uniques) for n in nice_numbers
            ],
            "remaining": [[s, e] for s, e in rem],
            "filtered": True,
        }

    def collect_item(kind, *payload):
        t0 = time.monotonic()
        if kind == "count":
            segs, (count, pruned) = payload
            ENGINE_READBACK_BYTES.labels("count").inc(
                4 if pruned is None else 8
            )
            if pruned is not None:
                # nicelint: fence (pruned tally readback, fused filter)
                pruned_n = int(np.asarray(pruned))
                ENGINE_FILTER_PRUNED.labels("niceonly", str(base)).inc(
                    pruned_n
                )
            # nicelint: fence (count flag readback gates extraction)
            if int(np.asarray(count)) > 0:
                # uniques > base-1 <=> == base: compacted nice extraction,
                # per slice seg.
                for seg_start, seg_valid in segs:
                    if seg_valid <= 0:
                        continue
                    for number, _uniq in _rare_scan_survivors(
                        plan, seg_start, seg_valid, batch_size, backend,
                        base - 1,
                    ):
                        nice_numbers.append(
                            NiceNumberSimple(number=number, num_uniques=base)
                        )
        else:  # "ckpt": by now every batch before the marker is folded.
            (rem,) = payload
            checkpoint_cb(_ckpt_state(rem))
        dt = time.monotonic() - t0
        if prof.enabled and kind == "count":
            prof.add("readback", dt)
        ENGINE_BATCH_KERNEL_SECONDS.labels("dense").observe(dt)

    # Same adaptive host-filter floor as the strided device path: the dense
    # device scan is cheap per lane, so a fine (250) floor would be
    # host-dominated (the setting the reference tunes away from for device
    # backends, client_process_gpu.rs:85-94).
    from nice_tpu.ops import adaptive_floor

    ctrl = adaptive_floor.get_floor_controller("dense")
    t_host0 = time.monotonic()
    floor_used = ctrl.current()
    if resume_segments is not None and resume_filtered:
        # Cut from an earlier run's post-filter set: the gaps are already
        # proven empty, so scan the segments directly (per-slice resume).
        ran_filter = False
        segments = list(resume_segments)
    else:
        ran_filter = True
        scan_from = (
            resume_segments if resume_segments is not None
            else [(core.start(), core.end())]
        )
        segments = []
        for s, e in scan_from:
            for r in msd_filter.get_valid_ranges(
                FieldSize(s, e), base, min_range_size=floor_used,
                max_depth=_msd_depth_for(e - s, floor_used),
            ):
                segments.append((r.start(), r.end()))
    host_secs = time.monotonic() - t_host0
    t_dev0 = time.monotonic()
    n_segments0 = len(segments)
    grand_total = sum(e - s for s, e in segments)
    grand_done = 0
    feed_depth = _feed_depth()
    n_batch = 0
    n_dev0 = n_dev
    reshards = 0
    reshard_secs = 0.0
    idle_gaps: list[float] = []
    prof_on = prof.enabled
    # The count readback (+ rare-path extraction behind a hit) runs on the
    # shared _Collector like every other path; only the collector touches
    # nice_numbers. Pod layer: per-slice queues, threaded feed, elastic
    # downshift — see _process_range_detailed for the shape.
    err_final = None  # (exception, remaining segments)
    with _Collector(collect_item, DISPATCH_WINDOW, "dense-collect",
                    occupancy=ENGINE_DISPATCH_OCCUPANCY) as collector:
        with obs.span("engine.niceonly-dense", base=base, size=core.size(),
                      backend=backend):
            while segments:
                if collector.failed():
                    break
                queues = (
                    pmesh.partition_segments(
                        segments, n_dev, batch_size * seg
                    )
                    if mesh is not None else [list(segments)]
                )
                feed = _SliceFeed(
                    plan, queues, batch_size * seg, core.end(), feed_depth
                )
                markers = _SliceFeed.start_markers(queues)
                failure = None
                t_prev = None
                try:
                    while True:
                        if collector.failed():
                            break
                        t_feed = time.monotonic() if prof_on else 0.0
                        item = feed.get()
                        if item is None:
                            segments = []
                            break
                        now = time.monotonic()
                        if prof_on:
                            prof.add("h2d_feed", now - t_feed)
                        if t_prev is not None:
                            gap = now - t_prev
                            MESH_FEED_IDLE.labels("niceonly").observe(gap)
                            if len(idle_gaps) < 65536:
                                idle_gaps.append(gap)
                        try:
                            _fire_dispatch_fault(
                                n_batch, backend, item.segs[0][0]
                            )
                            if mesh is not None:
                                _fire_mesh_fault(
                                    n_batch, n_dev, item.segs[0][0]
                                )
                            t_disp = time.monotonic() if prof_on else 0.0
                            counts = dispatch(item)
                            ENGINE_DISPATCHES.labels("niceonly").inc()
                            if prof_on:
                                prof.add(
                                    "device_compute",
                                    time.monotonic() - t_disp,
                                )
                                prof.fence(counts)
                        except Exception as e:  # noqa: BLE001 — boundary
                            failure = e
                            break
                        t_prev = time.monotonic()
                        markers = item.markers
                        n_batch += 1
                        grand_done += item.lanes
                        collector.put(("count", item.segs, counts))
                        if mesh is not None:
                            for d, (_si, cur) in enumerate(item.markers):
                                MESH_SLICE_CURSOR.labels(str(d)).set(cur)
                        if ticker is not None and ticker.tick():
                            collector.put(
                                ("ckpt",
                                 _SliceFeed.remaining(queues, markers))
                            )
                        if progress is not None:
                            progress(grand_done, grand_total)
                finally:
                    feed.stop()
                if failure is None:
                    continue  # exhausted (or collector failed) — loop exits
                rem = _SliceFeed.remaining(queues, markers)
                survivors = None
                if mesh is not None and _elastic_enabled():
                    survivors, reason = _diagnose_survivors(mesh, failure)
                    obs.flight.record(
                        "device_loss", mode="niceonly", base=base,
                        survivors=len(survivors) if survivors else 0,
                        reason=reason if survivors else "fatal",
                        error=repr(failure)[:200],
                    )
                if not survivors:
                    err_final = (failure, rem)
                    break
                # Elastic downshift: no accumulator to fold here — rebuild
                # the mesh over the survivors and re-slice the remainder.
                t_r0 = time.monotonic()
                pmesh.clear_step_cache(pmesh.mesh_device_ids(mesh))
                _invalidate_mesh_cache()
                mesh = _cached_mesh(tuple(survivors))
                prev_n = n_dev
                n_dev = len(survivors)
                dispatch = _bind(mesh, n_dev)
                segments = rem
                reshards += 1
                dt = time.monotonic() - t_r0
                reshard_secs += dt
                MESH_RESHARDS.labels(reason).inc()
                MESH_RESHARD_SECONDS.observe(dt)
                obs.flight.record(
                    "mesh_reshard", mode="niceonly", base=base,
                    reason=reason, n_dev=n_dev, lost=prev_n - n_dev,
                )
                obs.trace_event(
                    "mesh.reshard", mode="niceonly", base=base,
                    reason=reason, n_dev=n_dev,
                )
                log.warning(
                    "mesh downshift (niceonly b%d): %d -> %d devices "
                    "(%s, %r); re-sliced %d remaining segment(s)",
                    base, prev_n, n_dev, reason, failure, len(rem),
                )
    _record_feed_stats("niceonly", idle_gaps, n_batch, n_dev0, n_dev,
                       reshards, reshard_secs, feed_depth)
    prof.stop()
    if err_final is not None:
        err, rem = err_final
        # The collector has drained: nice_numbers holds every hit outside
        # the remaining set — a valid per-slice (filtered) resume state.
        state = None
        if not collector.failed():
            state = _ckpt_state(rem)
        raise BackendDispatchError(backend, state, err)
    collector.raise_if_failed()
    device_secs = time.monotonic() - t_dev0
    if ran_filter:
        ctrl.observe(host_secs, device_secs, core.size())
    log.info(
        "niceonly-dense b%d [%d, %d): msd %.3fs (floor %d, %d segments) | "
        "device %.3fs | %d nice",
        base, core.start(), core.end(), host_secs, floor_used,
        n_segments0, device_secs, len(nice_numbers),
    )
    ENGINE_NUMBERS.labels("niceonly").inc(range_.size())

    nice_numbers.sort(key=lambda n: n.number)
    return FieldResults(distribution=(), nice_numbers=tuple(nice_numbers))
