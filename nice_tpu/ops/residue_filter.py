"""Residue filter: valid n mod (b-1) classes.

If n is nice, the digits of n^2 and n^3 are a permutation of 0..b-1, whose sum
is b(b-1)/2. Digit sums are preserved mod (b-1), so n^2 + n^3 must be congruent
to b(b-1)/2 mod (b-1). Mirrors reference common/src/residue_filter.rs:6-11.
"""

from __future__ import annotations

from functools import lru_cache


@lru_cache(maxsize=None)
def get_residue_filter(base: int) -> tuple[int, ...]:
    """Residues r in [0, b-1) with r^2 + r^3 congruent to b(b-1)/2 mod (b-1)."""
    target_residue = base * (base - 1) // 2 % (base - 1)
    return tuple(
        r
        for r in range(base - 1)
        if (r * r + r * r * r) % (base - 1) == target_residue
    )
