"""Per-(mode, base, backend) kernel-shape autotuner with a persistent winners
table.

The measured-sweep discipline of the reference's floor sweep
(client_process_gpu.rs:85-94) applied to the kernel shape knobs this repo
previously hand-committed: block_rows (Pallas grid block), batch size (lanes
per dispatch), and carry_interval (the carry-save resolution interval in
ops/vector_engine.py). `sweep()` times configurations through the
scripts/tune_kernels.py harness (--json mode) in a subprocess — real dispatch
path, compile excluded by warmup — and persists the winner per
(mode, base, backend) key in a JSON table stored BESIDE the persistent
compile cache, keyed the same way the executable cache keys its entries.

Every entry carries a plan signature (base, limb widths, jax version +
platform). A lookup whose stored signature no longer matches the current
runtime is dropped and counted as `invalidated` — a JAX upgrade or a plan
change (new limb widths after a base-range fix) silently falls back to
defaults until re-tuned, never applies stale shapes.

Precedence when the engine resolves a knob (engine.resolve_tuning):
    1. explicit env var (NICE_TPU_BATCH / NICE_TPU_BLOCK_ROWS /
       NICE_TPU_CARRY_INTERVAL) — operator pin, counted as env_override
    2. tuned winner from this table — counted as hit
    3. built-in default — counted as miss

Traffic lands in nice_autotune_events_total (obs/series.py).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from pathlib import Path

from nice_tpu.obs.series import AUTOTUNE_EVENTS
from nice_tpu.utils import fsio, knobs, lockdep

# Knob -> operator env-var pin. The same vars steer scripts/tune_kernels.py
# configs, so the sweep exercises exactly the precedence path it tunes.
ENV_VARS = {
    "batch_size": "NICE_TPU_BATCH",
    "block_rows": "NICE_TPU_BLOCK_ROWS",
    "carry_interval": "NICE_TPU_CARRY_INTERVAL",
    "use_mxu": "NICE_TPU_MXU",
    "megaloop": "NICE_TPU_MEGALOOP_SEGMENT",
}

_lock = lockdep.make_lock("ops.autotune._lock")
_cache: dict = {"path": None, "mtime": None, "table": None}


def winners_path() -> Path:
    """Where the winners table lives: NICE_TPU_AUTOTUNE_FILE wins; else
    beside the persistent compile cache (JAX_COMPILATION_CACHE_DIR); else a
    per-user cache dir (same fallback family as the compile cache docs)."""
    p = knobs.AUTOTUNE_FILE.get()
    if p:
        return Path(p)
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        return Path(cache_dir) / "nice_autotune.json"
    return Path.home() / ".cache" / "nice_tpu" / "nice_autotune.json"


def key(mode: str, base: int, backend: str) -> str:
    """Winners-table key, spelled like a compile_cache executable key."""
    return f"{mode}|b{base}|{backend}"


def signature(base: int) -> dict:
    """Invalidation fingerprint: shape-determining plan constants plus the
    runtime (same runtime spelling as ckpt.manager.plan_signature). Any
    drift — a JAX upgrade, a different accelerator, a plan change — makes
    stored winners unusable until a re-tune."""
    import jax

    from nice_tpu.ops.limbs import get_plan

    plan = get_plan(base)
    return {
        "base": base,
        "limbs": [plan.limbs_n, plan.limbs_sq, plan.limbs_cu],
        "runtime": f"jax-{jax.__version__}-{jax.default_backend()}",
    }


def reset_for_tests() -> None:
    """Drop the in-process winners cache (the file is left alone)."""
    with _lock:
        _cache.update(path=None, mtime=None, table=None)


def _load() -> dict:
    """Winners table, cached per (path, mtime) so repeated lookups on the
    dispatch path cost a stat, not a parse."""
    path = winners_path()
    try:
        mtime = path.stat().st_mtime_ns
    except OSError:
        return {}
    with _lock:
        if _cache["path"] == str(path) and _cache["mtime"] == mtime:
            return _cache["table"]
    try:
        with open(path) as f:
            table = json.load(f)
        if not isinstance(table, dict):
            table = {}
    except (OSError, ValueError):
        table = {}
    with _lock:
        _cache.update(path=str(path), mtime=mtime, table=table)
    return table


def params(mode: str, base: int, backend: str) -> dict | None:
    """The tuned winner params for one key, or None. Signature-checked:
    a stale entry counts as `invalidated` and reads as absent."""
    entry = _load().get(key(mode, base, backend))
    if entry is None:
        return None
    try:
        if entry.get("signature") != signature(base):
            AUTOTUNE_EVENTS.labels("invalidated").inc()
            return None
    except Exception:
        return None  # no valid plan for this base anymore
    return entry.get("params") or None


def choose(mode: str, base: int, backend: str, param: str, default: int) -> int:
    """One knob under the env > tuned > default precedence (see module doc)."""
    env = ENV_VARS.get(param)
    if env:
        raw = knobs.lookup(env).raw()
        if raw:
            AUTOTUNE_EVENTS.labels("env_override").inc()
            return int(raw)
    tuned = params(mode, base, backend)
    if tuned is not None and param in tuned:
        AUTOTUNE_EVENTS.labels("hit").inc()
        return int(tuned[param])
    AUTOTUNE_EVENTS.labels("miss").inc()
    return default


def tenant_report(workloads) -> list[dict]:
    """Tuning status for a set of scheduler tenants: one row per
    (name, mode, base, backend) workload saying whether a signature-valid
    winner exists and what shape the tenant will actually run with
    (resolve_tuning's precedence applied per tenant, not per process).
    The multi-tenant scheduler logs this at startup and sched_smoke
    archives it, treating the tuning table as production infrastructure
    rather than a local file."""
    from nice_tpu.ops import engine

    out = []
    for name, mode, base, backend in workloads:
        tuned = params(mode, base, backend)
        batch, rows, carry, mxu_flag, megaloop = engine.resolve_tuning(
            mode, base, backend
        )
        out.append({
            "tenant": name,
            "key": key(mode, base, backend),
            "tuned": tuned is not None,
            "batch_size": batch,
            "block_rows": rows,
            "carry_interval": carry,
            "use_mxu": mxu_flag,
            "megaloop": megaloop,
            "page_quantum": engine.page_quantum(mode, base, backend),
        })
    return out


def record(mode: str, base: int, backend: str, new_params: dict,
           throughput: float | None = None, swept: list | None = None,
           phase_breakdown: dict | None = None) -> Path:
    """Persist a winner (atomic tmp+rename; concurrent writers last-wins at
    whole-file granularity, which is fine for a tuning table).

    phase_breakdown: optional stepprof phase->secs dict captured while the
    winner was measured (NICE_TPU_STEPPROF=1), stored alongside throughput
    so a later regression can be attributed to a phase, not just a total."""
    path = winners_path()
    path.parent.mkdir(parents=True, exist_ok=True)
    table = dict(_load())
    entry = {
        "params": {k: int(v) for k, v in new_params.items()},
        "signature": signature(base),
        "throughput": throughput,
        "swept": swept or [],
    }
    if phase_breakdown:
        entry["phase_breakdown"] = {
            k: round(float(v), 6) for k, v in phase_breakdown.items()
            if isinstance(v, (int, float))
        }
    table[key(mode, base, backend)] = entry
    # A1: fsync-before-rename via the shared helper (the old mkstemp path
    # skipped the fsync, so a crash could publish a truncated table).
    fsio.atomic_write_json(str(path), table, indent=1, sort_keys=True)
    AUTOTUNE_EVENTS.labels("store").inc()
    reset_for_tests()  # next lookup re-reads the fresh file
    return path


def sweep(mode: str, bench_mode: str, backend: str, *,
          batch_shifts: list[int], rows: list[int] | None = None,
          carry: list[int] | None = None, mxu: str | None = None,
          slice_size: int = 1_000_000,
          timeout: float = 900.0) -> dict | None:
    """Run the scripts/tune_kernels.py timing harness over the cartesian
    config grid and persist the best-throughput config as this key's winner.

    The harness runs in a SUBPROCESS (fresh jax, honest compile-cache
    behavior) with --json; each stdout line is one timed config. Returns the
    winning params dict, or None if no config produced a timing."""
    script = Path(__file__).resolve().parent.parent.parent / "scripts" / "tune_kernels.py"
    cmd = [
        sys.executable, str(script), "detailed" if mode == "detailed" else "niceonly",
        "--mode", bench_mode, "--backend", backend, "--json",
        "--slice", str(slice_size),
        "--batches", ",".join(str(s) for s in batch_shifts),
    ]
    if rows:
        cmd += ["--sweep-rows", ",".join(str(r) for r in rows)]
    if carry:
        cmd += ["--carry", ",".join(str(c) for c in carry)]
    if mxu:
        cmd += ["--mxu", mxu]
    AUTOTUNE_EVENTS.labels("sweep").inc()
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout,
        cwd=str(script.parent.parent),
    )
    results = []
    for line in proc.stdout.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            continue
        if rec.get("numbers_per_sec"):
            results.append(rec)
    if proc.returncode != 0 and not results:
        raise RuntimeError(
            f"tune_kernels sweep failed (rc={proc.returncode}): "
            f"{proc.stderr[-2000:]}"
        )
    if not results:
        return None
    best = max(results, key=lambda r: r["numbers_per_sec"])
    new_params = {
        k: best[k]
        for k in ("batch_size", "block_rows", "carry_interval", "use_mxu",
                  "megaloop")
        if best.get(k) is not None
    }
    record(
        mode, int(best["base"]), backend, new_params,
        throughput=float(best["numbers_per_sec"]),
        swept=[
            {k: r.get(k) for k in
             ("batch_size", "block_rows", "carry_interval", "use_mxu",
              "megaloop", "numbers_per_sec")}
            for r in results
        ],
        # The harness subprocess reports a stepprof breakdown when it ran
        # with NICE_TPU_STEPPROF=1; absent otherwise.
        phase_breakdown=best.get("phase_breakdown"),
    )
    return new_params
