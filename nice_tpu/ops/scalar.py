"""Scalar reference engine — the exact oracle every other backend diffs against.

Python ints are arbitrary-precision, so one implementation covers every base
(the reference needs u128 / U256 / malachite tiers: client_process.rs:47-71,
222-253). This is the trusted implementation in the differential-test strategy
(reference test pattern: fixed-width paths vs malachite, SURVEY.md section 4):
scalar <-> jnp vector engine <-> Pallas kernels must agree bit-for-bit.

Also used directly by the server for submission verification
(reference api/src/main.rs:352-358) and by `--validate`.
"""

from __future__ import annotations

from nice_tpu.core import number_stats
from nice_tpu.core.types import (
    FieldResults,
    FieldSize,
    NiceNumberSimple,
    UniquesDistributionSimple,
)


def get_num_unique_digits(num: int, base: int) -> int:
    """Number of unique digits in (n^2, n^3) written in base b.

    A number is nice iff this equals b (reference client_process.rs:47-143).
    """
    indicator = 0
    squared = num * num
    cubed = squared * num
    n = squared
    while n != 0:
        n, d = divmod(n, base)
        indicator |= 1 << d
    n = cubed
    while n != 0:
        n, d = divmod(n, base)
        indicator |= 1 << d
    return indicator.bit_count()


def get_is_nice(num: int, base: int) -> bool:
    """Early-exit duplicate check (reference client_process.rs:222-413)."""
    indicator = 0
    squared = num * num
    n = squared
    while n != 0:
        n, d = divmod(n, base)
        bit = 1 << d
        if indicator & bit:
            return False
        indicator |= bit
    n = squared * num
    while n != 0:
        n, d = divmod(n, base)
        bit = 1 << d
        if indicator & bit:
            return False
        indicator |= bit
    return True


def process_range_detailed(range_: FieldSize, base: int) -> FieldResults:
    """Full histogram + near-miss list for a half-open range
    (reference client_process.rs:150-191)."""
    nice_list_cutoff = number_stats.get_near_miss_cutoff(base)
    histogram = [0] * (base + 2)
    nice_numbers: list[NiceNumberSimple] = []

    for num in range_.range_iter():
        num_uniques = get_num_unique_digits(num, base)
        histogram[num_uniques] += 1
        if num_uniques > nice_list_cutoff:
            nice_numbers.append(
                NiceNumberSimple(number=num, num_uniques=num_uniques)
            )

    distribution = tuple(
        UniquesDistributionSimple(num_uniques=i, count=histogram[i])
        for i in range(1, base + 1)
    )
    return FieldResults(distribution=distribution, nice_numbers=tuple(nice_numbers))


def process_range_niceonly(
    range_: FieldSize, base: int, stride_table=None
) -> FieldResults:
    """Nice-number-only search with the full filter cascade
    (reference client_process.rs:439-465): recursive MSD range subdivision,
    then CRT stride iteration with early-exit checks."""
    from nice_tpu.ops import msd_filter, stride_filter

    if stride_table is None:
        stride_table = stride_filter.get_stride_table(base, 1)

    valid_msd_ranges = msd_filter.get_valid_ranges(range_, base)

    nice_list: list[NiceNumberSimple] = []
    for sub_range in valid_msd_ranges:
        nice_list.extend(stride_table.iterate_range(sub_range, base))

    return FieldResults(distribution=(), nice_numbers=tuple(nice_list))
