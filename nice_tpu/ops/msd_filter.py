"""MSD prefix filter: skip whole sub-ranges via most-significant-digit analysis.

If every square (or cube) in [a, b) shares a most-significant-digit prefix that
contains a duplicate, or the square and cube prefixes overlap, or (for ranges
within one b^k residue class) MSD and LSD digits collide, no number in the
range can be nice and the whole range is skipped. Recursive binary subdivision
(depth <= 22, floor 250, factor 2) yields the surviving sub-ranges.

Mirrors reference common/src/msd_prefix_filter.rs:382-674. A C++ native
implementation (nice_tpu/native) is used on the hot host path when available;
this module is the semantic definition and fallback, and both are
differential-tested against each other (reference test pattern,
msd_prefix_filter.rs:700-787).

DELIBERATE DEVIATION: the reference additionally applies a "cross MSD x LSD
collision check" (msd_prefix_filter.rs:501-559) gated on
`first // b^k == last // b^k`. That gate does NOT establish the check's stated
premise ("all numbers in the range share the same n mod b^k" — only a
size-1 range does), so the reference can skip ranges that contain nice numbers
(e.g. [50, 70) in base 10 contains 69, but the start square 2500's low digits
[0, 0] trigger a skip). The reference's own tests never hit this because
ranges <= 250 bypass the filter. We drop the unsound check: our filter skips
slightly fewer ranges but never loses a nice number.
"""

from __future__ import annotations

from nice_tpu.core.types import FieldSize

# Recursion tuning (reference msd_prefix_filter.rs:281-287).
MSD_RECURSIVE_MAX_DEPTH = 22
MSD_RECURSIVE_MIN_RANGE_SIZE = 250
MSD_RECURSIVE_SUBDIVISION_FACTOR = 2

# Number of least significant digits used by the cross MSD x LSD check.
MSD_LSD_OVERLAP_K_VALUE = 2


def to_digits_asc(n: int, base: int) -> list[int]:
    """Base digits, LSD first. n == 0 -> [0]."""
    if n == 0:
        return [0]
    out = []
    while n:
        n, d = divmod(n, base)
        out.append(d)
    return out


def _common_msd_prefix(d1: list[int], d2: list[int]) -> list[int]:
    """Longest shared most-significant-digit prefix (LSD-first inputs);
    reference msd_prefix_filter.rs:296-314."""
    out = []
    len1, len2 = len(d1), len(d2)
    for i in range(min(len1, len2)):
        a = d1[len1 - 1 - i]
        if a == d2[len2 - 1 - i]:
            out.append(a)
        else:
            break
    return out


def _has_duplicate_digits(digits: list[int]) -> bool:
    seen = 0
    for d in digits:
        bit = 1 << d
        if seen & bit:
            return True
        seen |= bit
    return False


def _has_overlapping_digits(d1: list[int], d2: list[int]) -> bool:
    seen = 0
    for d in d1:
        seen |= 1 << d
    for d in d2:
        if seen & (1 << d):
            return True
    return False


def has_duplicate_msd_prefix(range_: FieldSize, base: int) -> bool:
    """True when the whole half-open range can be skipped
    (reference msd_prefix_filter.rs:382-563)."""
    assert range_.size() > 0
    assert base <= 256, "Base must be 256 or less"

    if range_.size() == 1:
        return False

    first = range_.first()
    last = range_.last()

    start_sq = to_digits_asc(first * first, base)
    end_sq = to_digits_asc(last * last, base)
    # Digit-count changes across the range make prefixes ambiguous; err safe.
    if len(start_sq) != len(end_sq):
        return False

    square_prefix = _common_msd_prefix(start_sq, end_sq)
    if _has_duplicate_digits(square_prefix):
        return True

    start_cu = to_digits_asc(first * first * first, base)
    end_cu = to_digits_asc(last * last * last, base)
    if len(start_cu) != len(end_cu):
        return False

    cube_prefix = _common_msd_prefix(start_cu, end_cu)
    if _has_duplicate_digits(cube_prefix):
        return True

    if _has_overlapping_digits(square_prefix, cube_prefix):
        return True

    # NOTE: the reference's cross MSD x LSD check is intentionally omitted —
    # it is unsound as gated (see module docstring).
    return False


def get_valid_ranges_recursive(
    range_: FieldSize,
    base: int,
    current_depth: int = 0,
    max_depth: int = MSD_RECURSIVE_MAX_DEPTH,
    min_range_size: int = MSD_RECURSIVE_MIN_RANGE_SIZE,
    subdivision_factor: int = MSD_RECURSIVE_SUBDIVISION_FACTOR,
) -> list[FieldSize]:
    """Recursively subdivide, returning sub-ranges that still need processing
    (reference msd_prefix_filter.rs:583-658)."""
    if current_depth >= max_depth:
        return [range_]
    if range_.size() <= min_range_size:
        return [range_]
    if has_duplicate_msd_prefix(range_, base):
        return []
    if range_.size() < min_range_size * subdivision_factor:
        return [range_]

    chunk_size = range_.size() // subdivision_factor
    valid_ranges: list[FieldSize] = []
    for i in range(subdivision_factor):
        sub_start = range_.range_start + i * chunk_size
        sub_end = (
            range_.range_end
            if i == subdivision_factor - 1
            else sub_start + chunk_size
        )
        if sub_start < sub_end:
            valid_ranges.extend(
                get_valid_ranges_recursive(
                    FieldSize(sub_start, sub_end),
                    base,
                    current_depth + 1,
                    max_depth,
                    min_range_size,
                    subdivision_factor,
                )
            )
    return valid_ranges


def get_valid_ranges(
    range_: FieldSize,
    base: int,
    min_range_size: int = MSD_RECURSIVE_MIN_RANGE_SIZE,
    max_depth: int = MSD_RECURSIVE_MAX_DEPTH,
) -> list[FieldSize]:
    """Default-parameter wrapper (reference msd_prefix_filter.rs:665-674).

    min_range_size is the recursion floor: device consumers raise it (the
    reference GPU's adaptive floor, client_process_gpu.rs:103-156) because a
    coarser filter trades host CPU time for cheap device lanes.

    Uses the C++ implementation when available (the host-side hot path when
    feeding range descriptors to the device, reference GPU pipeline
    client_process_gpu.rs:624-660); falls back to the Python definition."""
    from nice_tpu import native

    res = native.msd_valid_ranges(
        range_.start(),
        range_.end(),
        base,
        max_depth,
        min_range_size,
        MSD_RECURSIVE_SUBDIVISION_FACTOR,
    )
    if res is not None:
        return [FieldSize(s, e) for s, e in res]
    return get_valid_ranges_recursive(
        range_, base, max_depth=max_depth, min_range_size=min_range_size
    )
