"""LSD filter: valid last-k-digit suffixes mod b^k.

The last k digits of n determine the last k digits of n^2 and n^3. A suffix is
invalid when any digit of (n^2 mod b^k) collides with any digit of
(n^3 mod b^k) — a guaranteed duplicate. Mirrors reference
common/src/lsd_filter.rs:67-238.

The bitmap construction is vectorized (numpy over all b^k suffixes at once)
because stride-depth planning consults deep tables: the scalar loop takes ~5 s
at b=50, k=3 (125k suffixes in pure Python) while the vectorized pass takes
~0.1 s. `_bitmap_scalar` keeps the direct transcription of the definition as
the differential-test oracle (tests/test_filters.py).
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np


def _extract_digits(value: int, base: int, num_digits: int) -> set[int]:
    """Unique digits among the low `num_digits` digits, stopping at zero
    (reference lsd_filter.rs:132-148: always inserts the first digit)."""
    digits = set()
    remaining = value
    for _ in range(num_digits):
        remaining, d = divmod(remaining, base)
        digits.add(d)
        if remaining == 0:
            break
    return digits


@lru_cache(maxsize=None)
def get_valid_lsds(base: int) -> tuple[int, ...]:
    """Single-digit filter: LSDs where n^2 and n^3 end in different digits
    (reference lsd_filter.rs:67-121)."""
    out = []
    for lsd in range(base):
        if (lsd * lsd) % base != (lsd * lsd * lsd) % base:
            out.append(lsd)
    return tuple(out)


def _bitmap_scalar(base: int, k: int) -> np.ndarray:
    """Direct transcription of the definition (the test oracle)."""
    modulus = base**k
    bitmap = np.zeros(modulus, dtype=bool)
    for suffix in range(modulus):
        sq = (suffix * suffix) % modulus
        cb = (suffix * suffix * suffix) % modulus
        sq_digits = _extract_digits(sq, base, k)
        cb_digits = _extract_digits(cb, base, k)
        if sq_digits.isdisjoint(cb_digits):
            bitmap[suffix] = True
    return bitmap


def _digit_presence_masks(values: np.ndarray, base: int, k: int) -> np.ndarray:
    """u64[..., n_words] digit-presence bitmasks of the low k digits of each
    value, with the reference's stop-at-zero rule: peel digits LSD-first,
    always recording the first, and stop once the remaining quotient is zero.

    The word count scales with the base (digits span [0, base)): bases up to
    256 need four u64 words. A fixed two-word layout silently produced
    `one << (d - 64)` with d >= 128 — a >= 64-bit shift, undefined in numpy —
    for bases above 128 (advisor finding, round 3)."""
    n_words = (base + 63) // 64
    one = np.uint64(1)
    masks = np.zeros(values.shape + (n_words,), dtype=np.uint64)
    rem = values.astype(np.int64)
    alive = np.ones(values.shape, dtype=bool)
    for _ in range(k):
        d = rem % base
        rem = rem // base
        bit = one << (d.astype(np.uint64) & np.uint64(63))
        word = d >> 6
        for w in range(n_words):
            masks[..., w] |= np.where(alive & (word == w), bit, 0)
        alive &= rem != 0
    return masks


@lru_cache(maxsize=None)
def get_valid_multi_lsd_bitmap(base: int, k: int) -> np.ndarray:
    """bitmap[s] == True when suffix s (mod b^k) can produce a nice number
    (reference lsd_filter.rs:174-224). Returns a read-only bool ndarray."""
    modulus = base**k
    s = np.arange(modulus, dtype=np.int64)
    # s < b^k <= ~9e5^... keep products in range: s*s < modulus^2 and the cube
    # is reduced in two steps so every intermediate stays below 2^63
    # (modulus <= 96^3 < 2^20, so modulus^2 < 2^40).
    sq = (s * s) % modulus
    cb = (sq * s) % modulus
    sq_masks = _digit_presence_masks(sq, base, k)
    cb_masks = _digit_presence_masks(cb, base, k)
    bitmap = ~np.any(sq_masks & cb_masks, axis=-1)
    bitmap.setflags(write=False)
    return bitmap


@lru_cache(maxsize=None)
def valid_multi_lsd_count(base: int, k: int) -> int:
    """Number of valid k-digit suffixes (used by stride-depth planning to
    score depths without materializing full stride tables)."""
    return int(get_valid_multi_lsd_bitmap(base, k).sum())


def get_recommended_k(base: int) -> int:
    """Locked to 1 in the reference after benchmarking (lsd_filter.rs:234-238)."""
    return 1
