"""LSD filter: valid last-k-digit suffixes mod b^k.

The last k digits of n determine the last k digits of n^2 and n^3. A suffix is
invalid when any digit of (n^2 mod b^k) collides with any digit of
(n^3 mod b^k) — a guaranteed duplicate. Mirrors reference
common/src/lsd_filter.rs:67-238.
"""

from __future__ import annotations

from functools import lru_cache


def _extract_digits(value: int, base: int, num_digits: int) -> set[int]:
    """Unique digits among the low `num_digits` digits, stopping at zero
    (reference lsd_filter.rs:132-148: always inserts the first digit)."""
    digits = set()
    remaining = value
    for _ in range(num_digits):
        remaining, d = divmod(remaining, base)
        digits.add(d)
        if remaining == 0:
            break
    return digits


@lru_cache(maxsize=None)
def get_valid_lsds(base: int) -> tuple[int, ...]:
    """Single-digit filter: LSDs where n^2 and n^3 end in different digits
    (reference lsd_filter.rs:67-121)."""
    out = []
    for lsd in range(base):
        if (lsd * lsd) % base != (lsd * lsd * lsd) % base:
            out.append(lsd)
    return tuple(out)


@lru_cache(maxsize=None)
def get_valid_multi_lsd_bitmap(base: int, k: int) -> tuple[bool, ...]:
    """bitmap[s] == True when suffix s (mod b^k) can produce a nice number
    (reference lsd_filter.rs:174-224)."""
    modulus = base**k
    bitmap = [False] * modulus
    for suffix in range(modulus):
        sq = (suffix * suffix) % modulus
        cb = (suffix * suffix * suffix) % modulus
        sq_digits = _extract_digits(sq, base, k)
        cb_digits = _extract_digits(cb, base, k)
        if sq_digits.isdisjoint(cb_digits):
            bitmap[suffix] = True
    return tuple(bitmap)


def get_recommended_k(base: int) -> int:
    """Locked to 1 in the reference after benchmarking (lsd_filter.rs:234-238)."""
    return 1
