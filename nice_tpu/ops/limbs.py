"""Per-base fixed-width plans and host-side limb packing.

TPUs have no native u64/u128: candidates, squares, and cubes are represented
as vectors of u32 limbs (LSW first), with 32x32->64 products decomposed into
16-bit halves (the VPU analog of the reference CUDA kernel's u32-limb /
u64-accumulator scheme, nice_kernels.cu:164-179, re-derived for 32-bit
accumulators).

Everything shape-determining is precomputed here per base — limb counts, exact
digit counts, the chunked radix divisor — and burned into the traced program
as constants. This is the same JIT-specialize-per-(base, mode) philosophy the
reference applies via const generics and NVRTC -D defines
(client_process_gpu.rs:318-381): every `%`/`//` in the kernel has a
compile-time divisor that XLA strength-reduces to multiply-shift.

Digit extraction relies on the exact-digit-count theorem (core/base_range.py):
inside a base's valid range, digits(n^2) and digits(n^3) are constants, so
extraction runs a fixed trip count with no leading-zero ("phantom digit")
masking — the bug class the reference fought in its GPU prefilter
(nice_kernels.cu:46-49) simply cannot occur.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import numpy as np

from nice_tpu.core import base_range, number_stats


def bits_for(value: int) -> int:
    """Bits needed to store any integer in [0, value)."""
    return max((value - 1).bit_length(), 1)


def limbs_for(value: int) -> int:
    """u32 limbs needed to store any integer in [0, value)."""
    return (bits_for(value) + 31) // 32


def halfwords_for(value: int) -> int:
    """16-bit halfwords needed to store any integer in [0, value)."""
    return (bits_for(value) + 15) // 16


@dataclass(frozen=True)
class BasePlan:
    """All trace-time constants for one base's kernels."""

    base: int
    range_start: int
    range_end: int
    d_sq: int  # exact digit count of n^2 in the valid range
    d_cu: int  # exact digit count of n^3
    limbs_n: int  # u32 limbs for n
    limbs_sq: int
    limbs_cu: int
    hw_sq: int  # 16-bit halfwords for n^2
    hw_cu: int
    chunk_e: int  # digits peeled per chunk division
    chunk_div: int  # base ** chunk_e, <= 2^16
    n_masks: int  # u32 digit-presence masks (ceil(base / 32))
    near_miss_cutoff: int

    @property
    def total_digits(self) -> int:
        return self.d_sq + self.d_cu  # == base


@functools.lru_cache(maxsize=None)
def get_plan(base: int) -> BasePlan:
    r = base_range.get_base_range(base)
    if r is None:
        raise ValueError(f"base {base} has no valid range")
    start, end = r
    d_sq, d_cu = base_range.sqube_digit_counts(base)

    # Largest e with base^e <= 2^16 keeps every chunk-division intermediate
    # (rem * 2^16 + halfword < chunk_div * 2^16) inside u32.
    chunk_e = 1
    while base ** (chunk_e + 1) <= 1 << 16:
        chunk_e += 1

    max_n = end - 1
    return BasePlan(
        base=base,
        range_start=start,
        range_end=end,
        d_sq=d_sq,
        d_cu=d_cu,
        limbs_n=limbs_for(max_n + 1),
        limbs_sq=limbs_for(base**d_sq),
        limbs_cu=limbs_for(base**d_cu),
        hw_sq=halfwords_for(base**d_sq),
        hw_cu=halfwords_for(base**d_cu),
        chunk_div=base**chunk_e,
        chunk_e=chunk_e,
        n_masks=(base + 31) // 32,
        near_miss_cutoff=number_stats.get_near_miss_cutoff(base),
    )


def int_to_limbs(x: int, num_limbs: int) -> np.ndarray:
    """Pack a Python int into LSW-first u32 limbs."""
    if x < 0 or x >= 1 << (32 * num_limbs):
        raise ValueError(f"{x} does not fit in {num_limbs} u32 limbs")
    return np.array(
        [(x >> (32 * i)) & 0xFFFFFFFF for i in range(num_limbs)], dtype=np.uint32
    )


def limbs_to_int(limbs) -> int:
    """Inverse of int_to_limbs (accepts any array-like of u32)."""
    out = 0
    for i, limb in enumerate(np.asarray(limbs, dtype=np.uint64).tolist()):
        out |= int(limb) << (32 * i)
    return out


def ints_to_limbs(xs: list[int], num_limbs: int) -> np.ndarray:
    """Pack many ints into a (len(xs), num_limbs) LSW-first u32 array."""
    out = np.empty((len(xs), num_limbs), dtype=np.uint32)
    for row, x in enumerate(xs):
        out[row] = int_to_limbs(x, num_limbs)
    return out


def ints_to_limb_arrays(xs: list[int], num_limbs: int) -> list[np.ndarray]:
    """Pack many ints into the engine's LIMB-MAJOR layout: a list of
    num_limbs contiguous (len(xs),) u32 arrays, LSW first.

    This is the layout every kernel computes in — one full array (a full
    (rows, 128) VPU tile inside the Pallas kernels) per limb, so each
    carry-save partial-product column is a single dense vector op with no
    per-lane gather. The (rows, limbs) row-major form from ints_to_limbs is
    only used for host-side packing of descriptor tables."""
    packed = ints_to_limbs(xs, num_limbs)
    return [np.ascontiguousarray(packed[:, i]) for i in range(num_limbs)]


def limb_arrays_to_ints(limbs: list) -> list[int]:
    """Inverse of ints_to_limb_arrays (accepts any list of u32 array-likes)."""
    cols = [np.asarray(l, dtype=np.uint32) for l in limbs]
    return [
        limbs_to_int([c[row] for c in cols]) for row in range(len(cols[0]))
    ]
