"""Persistent compilation cache + in-process AOT executable cache.

Two layers attack the two distinct recompile costs the benchmark rounds
measured (BENCH r0/r5: whole rounds blanked by init watchdogs; VERDICT
task #1):

1. The **jax persistent compilation cache** keeps XLA/Mosaic compilation
   artifacts on disk across *processes*: point ``JAX_COMPILATION_CACHE_DIR``
   at a stable directory and the second run of any mode deserializes its
   executables instead of recompiling (the TPU analog of the reference
   caching its NVRTC PTX per arch, client_process_gpu.rs:249-259). setup()
   drops jax's minimum-compile-time/entry-size gates to zero because this
   project's kernels are many small programs, each individually below the
   default 1 s threshold.

2. The **executable cache** memoizes AOT-compiled (``.lower().compile()``)
   batch kernels *within* a process, keyed by (mode, backend, plan, shape):
   the engine pre-lowers its per-(base, limb-plan, mode) kernels at field
   start, so server fields and bench modes never pay jit dispatch-time
   tracing mid-field, and a second field of the same shape is a pure cache
   hit.

Both layers report into ``nice_compile_cache_events_total`` so bench/CI can
assert cache hits instead of inferring them from wall time alone.
"""

from __future__ import annotations

import os
import threading
import time

from nice_tpu.obs import stepprof
from nice_tpu.obs.series import COMPILE_CACHE_EVENTS
from nice_tpu.utils import lockdep

_lock = lockdep.make_lock("ops.compile_cache._lock")
_setup_done = [False]
_executables: dict = {}

# jax.monitoring event names -> our counter labels. Both exist in jax 0.4.x;
# "request" counts every compilation that consulted the persistent cache,
# "hit" the subset served from disk.
_EVENTS = {
    "/jax/compilation_cache/cache_hits": ("persistent", "hit"),
    "/jax/compilation_cache/compile_requests_use_cache": (
        "persistent",
        "request",
    ),
}


def _listener(event, **kwargs):
    labels = _EVENTS.get(event)
    if labels is not None:
        COMPILE_CACHE_EVENTS.labels(*labels).inc()


def setup() -> None:
    """Idempotent: enable the persistent compilation cache (when a directory
    is configured) and start counting its hits. Safe to call per field."""
    with _lock:
        if _setup_done[0]:
            return
        _setup_done[0] = True
    import jax

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    if getattr(jax.config, "jax_compilation_cache_dir", None):
        # The defaults (min 1 s compile, min 64 KiB entry) would exclude
        # every kernel in this repo — they are many small programs.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except AttributeError:
            pass  # option absent on older jax; the time gate suffices
    try:
        jax.monitoring.register_event_listener(_listener)
    except Exception:  # pragma: no cover - monitoring API drift
        pass


def aot(jitted, *args, **kwargs):
    """AOT-compile a jitted function at example args (ShapeDtypeStructs are
    fine for the dynamic ones). The returned executable takes only the
    dynamic args — static_argnums AND static keyword args (static_argnames,
    e.g. the carry-save ``carry_interval``) are burned in at lowering time."""
    return jitted.lower(*args, **kwargs).compile()


def executable(key, build):
    """Get-or-build a compiled executable. ``build`` runs outside the lock
    (compiles can take seconds); a racing duplicate build is discarded."""
    with _lock:
        ex = _executables.get(key)
    if ex is not None:
        COMPILE_CACHE_EVENTS.labels("executable", "hit").inc()
        return ex
    t0 = time.perf_counter()
    ex = build()
    stepprof.note_compile(time.perf_counter() - t0)
    with _lock:
        prior = _executables.get(key)
        if prior is None:
            _executables[key] = ex
    if prior is None:
        COMPILE_CACHE_EVENTS.labels("executable", "miss").inc()
        return ex
    COMPILE_CACHE_EVENTS.labels("executable", "hit").inc()
    return prior


def counts() -> dict:
    """Current cache-event counters (for bench/CI assertions)."""
    c = COMPILE_CACHE_EVENTS
    return {
        "persistent_hits": c.value(("persistent", "hit")),
        "persistent_requests": c.value(("persistent", "request")),
        "executable_hits": c.value(("executable", "hit")),
        "executable_misses": c.value(("executable", "miss")),
    }


def reset_for_tests() -> None:
    """Drop the in-process executable cache (counters are left alone)."""
    with _lock:
        _executables.clear()
