"""Persistent compilation cache + in-process AOT executable cache.

Two layers attack the two distinct recompile costs the benchmark rounds
measured (BENCH r0/r5: whole rounds blanked by init watchdogs; VERDICT
task #1):

1. The **jax persistent compilation cache** keeps XLA/Mosaic compilation
   artifacts on disk across *processes*: point ``JAX_COMPILATION_CACHE_DIR``
   at a stable directory and the second run of any mode deserializes its
   executables instead of recompiling (the TPU analog of the reference
   caching its NVRTC PTX per arch, client_process_gpu.rs:249-259). setup()
   drops jax's minimum-compile-time/entry-size gates to zero because this
   project's kernels are many small programs, each individually below the
   default 1 s threshold.

2. The **executable cache** memoizes AOT-compiled (``.lower().compile()``)
   batch kernels *within* a process, keyed by (mode, backend, plan, shape):
   the engine pre-lowers its per-(base, limb-plan, mode) kernels at field
   start, so server fields and bench modes never pay jit dispatch-time
   tracing mid-field, and a second field of the same shape is a pure cache
   hit.

Both layers report into ``nice_compile_cache_events_total`` so bench/CI can
assert cache hits instead of inferring them from wall time alone.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict

from nice_tpu.obs import stepprof
from nice_tpu.obs.series import COMPILE_CACHE_EVENTS
from nice_tpu.utils import knobs, lockdep

_lock = lockdep.make_lock("ops.compile_cache._lock")
_setup_done = [False]
# Insertion/hit-ordered so the NICE_TPU_COMPILE_CACHE_MAX_EXECUTABLES cap
# can evict least-recently-hit executables (a long-lived multi-tenant
# process warms a new (mode, plan, batch) key per tenant forever otherwise).
_executables: "OrderedDict" = OrderedDict()

# jax.monitoring event names -> our counter labels. Both exist in jax 0.4.x;
# "request" counts every compilation that consulted the persistent cache,
# "hit" the subset served from disk.
_EVENTS = {
    "/jax/compilation_cache/cache_hits": ("persistent", "hit"),
    "/jax/compilation_cache/compile_requests_use_cache": (
        "persistent",
        "request",
    ),
}


def _listener(event, **kwargs):
    labels = _EVENTS.get(event)
    if labels is not None:
        COMPILE_CACHE_EVENTS.labels(*labels).inc()


def setup() -> None:
    """Idempotent: enable the persistent compilation cache (when a directory
    is configured) and start counting its hits. Safe to call per field."""
    with _lock:
        if _setup_done[0]:
            return
        _setup_done[0] = True
    import jax

    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if cache_dir:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
    if getattr(jax.config, "jax_compilation_cache_dir", None):
        # The defaults (min 1 s compile, min 64 KiB entry) would exclude
        # every kernel in this repo — they are many small programs.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        try:
            jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        except AttributeError:
            pass  # option absent on older jax; the time gate suffices
    try:
        jax.monitoring.register_event_listener(_listener)
    except Exception:  # pragma: no cover - monitoring API drift
        pass


def aot(jitted, *args, **kwargs):
    """AOT-compile a jitted function at example args (ShapeDtypeStructs are
    fine for the dynamic ones). The returned executable takes only the
    dynamic args — static_argnums AND static keyword args (static_argnames,
    e.g. the carry-save ``carry_interval``) are burned in at lowering time."""
    return jitted.lower(*args, **kwargs).compile()


def _max_executables() -> int:
    try:
        return max(0, int(knobs.COMPILE_CACHE_MAX_EXECUTABLES.get()))
    except (TypeError, ValueError):
        return 0


def _evict_over_cap_locked() -> int:
    """Drop least-recently-hit executables past the cap (caller holds
    _lock). 0 = unbounded."""
    cap = _max_executables()
    evicted = 0
    if cap > 0:
        while len(_executables) > cap:
            _executables.popitem(last=False)
            evicted += 1
    return evicted


def executable(key, build):
    """Get-or-build a compiled executable. ``build`` runs outside the lock
    (compiles can take seconds); a racing duplicate build is discarded."""
    with _lock:
        ex = _executables.get(key)
        if ex is not None:
            _executables.move_to_end(key)
    if ex is not None:
        COMPILE_CACHE_EVENTS.labels("executable", "hit").inc()
        return ex
    t0 = time.perf_counter()
    ex = build()
    stepprof.note_compile(time.perf_counter() - t0)
    with _lock:
        prior = _executables.get(key)
        if prior is None:
            _executables[key] = ex
            evicted = _evict_over_cap_locked()
        else:
            _executables.move_to_end(key)
            evicted = 0
    if evicted:
        COMPILE_CACHE_EVENTS.labels("executable", "evicted").inc(evicted)
    if prior is None:
        COMPILE_CACHE_EVENTS.labels("executable", "miss").inc()
        return ex
    COMPILE_CACHE_EVENTS.labels("executable", "hit").inc()
    return prior


def counts() -> dict:
    """Current cache-event counters (for bench/CI assertions)."""
    c = COMPILE_CACHE_EVENTS
    return {
        "persistent_hits": c.value(("persistent", "hit")),
        "persistent_requests": c.value(("persistent", "request")),
        "executable_hits": c.value(("executable", "hit")),
        "executable_misses": c.value(("executable", "miss")),
        "executable_evictions": c.value(("executable", "evicted")),
    }


def _group_of(key) -> str:
    """Stable per-(mode, base) grouping label for a cache key: the leading
    kind string plus the base of any limb plan riding in the key."""
    if isinstance(key, tuple) and key:
        kind = str(key[0])
        for el in key[1:]:
            base = getattr(el, "base", None)
            if base is not None:
                return f"{kind}|b{base}"
        return kind
    return str(key)


def _executable_nbytes(ex) -> int:
    """Best-effort AOT footprint: XLA's generated code size where the
    compiled artifact exposes memory_analysis(), else 0 (the count is still
    meaningful evidence)."""
    try:
        ma = ex.memory_analysis()
    except Exception:  # noqa: BLE001 — analysis is backend-optional
        return 0
    for attr in ("generated_code_size_in_bytes", "temp_size_in_bytes"):
        v = getattr(ma, attr, None)
        if v:
            return int(v)
    return 0


def footprint() -> dict:
    """Memwatch feed: executable count + per-(mode, base) byte estimate,
    {"count": n, "groups": {"detailed-mega|b13": bytes, ...}}."""
    with _lock:
        entries = list(_executables.items())
    groups: dict = {}
    for key, ex in entries:
        g = _group_of(key)
        groups[g] = groups.get(g, 0) + _executable_nbytes(ex)
    return {"count": len(entries), "groups": groups}


def reset_for_tests() -> None:
    """Drop the in-process executable cache (counters are left alone)."""
    with _lock:
        _executables.clear()
