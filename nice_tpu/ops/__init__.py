"""Compute engine (L1): scalar oracle, filter cascade, vector/TPU engines."""
