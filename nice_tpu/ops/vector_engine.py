"""Vectorized fixed-width niceness engine in pure jnp (uint32 lanes).

This is the XLA-compiled compute graph shared (structurally) with the Pallas
kernels: all per-base shape decisions come from ops/limbs.BasePlan and are
trace-time constants. Values are lists of (batch,) uint32 arrays — one array
per limb — so XLA keeps limbs in registers and fuses the whole digit pipeline.

Pipeline per candidate lane (mirrors reference nice_kernels.cu:420-531, but
mask-based instead of warp-divergent early exit):
    n = start + iota                      (zero input transfer)
    sq = n * n, cu = sq * n               (carry-save 16-bit-half products)
    digits via chunked radix extraction   (constant divisors, fixed trip count)
    presence bitmasks -> popcount         -> num_uniques
    histogram via bincount; near-misses extracted on a rare second pass

Multi-limb products are CARRY-SAVE: every 32x32->64 partial product is
accumulated into independent per-column (sum, wrap-count) u32 pairs — no carry
chain crosses columns during accumulation, so the partial products of one
result have no serial dependence on each other — and carries are resolved in
ONE deferred pass per result (plus optional periodic folds, the tunable
`carry_interval`). Squaring goes through a dedicated specialization
(`sqr_limbs`) that computes each off-diagonal a_i*a_j once and accumulates it
twice, roughly halving the multiply count for n^2 and for the first half of
n^3 = n^2 * n.

Correctness contract: the processed range must lie inside the base's valid
range (engine.py enforces; the exact-digit-count theorem holds there).

Why u32 limbs and not f32 24-bit limbs (the browser engine's trick): measured
VPU op throughput on a v5e is at parity (u32 mul 0.25 T ops/s serial-chain vs
f32 mul 0.22 / f32 fma 0.24; u32 div-by-const 0.19), so an f32 engine would
only add the ~1.33x limb-count overhead of 24-bit limbs. Measured round 4.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from nice_tpu.ops.limbs import BasePlan, bits_for, halfwords_for

U32 = jnp.uint32
MASK16 = np.uint32(0xFFFF)


# --------------------------------------------------------------------------
# u32 limb primitives
# --------------------------------------------------------------------------

def mul32(a, b):
    """Full 32x32 -> 64 product as (lo, hi) u32, via 16-bit halves."""
    a_lo = a & MASK16
    a_hi = a >> 16
    b_lo = b & MASK16
    b_hi = b >> 16
    ll = a_lo * b_lo
    lh = a_lo * b_hi
    hl = a_hi * b_lo
    hh = a_hi * b_hi
    t = (ll >> 16) + (lh & MASK16) + (hl & MASK16)
    lo = (ll & MASK16) | (t << 16)
    hi = hh + (lh >> 16) + (hl >> 16) + (t >> 16)
    return lo, hi


def _carry(flag):
    return flag.astype(U32)


def _cs_add(sums: list, wraps: list, k: int, v) -> None:
    """Carry-save accumulate v into column k: sums[k] += v with the u32 wrap
    counted in wraps[k] (each wrap is worth 2^32 at column k, i.e. exactly 1
    at column k+1). No carry chain crosses columns, so accumulations into
    different columns have no serial dependence."""
    s = sums[k] + v
    wraps[k] = wraps[k] + _carry(s < v)
    sums[k] = s


def _cs_fold(sums: list, wraps: list) -> None:
    """Partial carry resolution: fold each column's wrap count into the next
    column's sum (itself carry-save, so columns stay independent). Called
    every `resolve_every` accumulation rows to keep the wrap counters near
    zero mid-product; the final wrap count (worth 2^32^len, beyond the
    truncation width) is dropped."""
    zero = jnp.zeros_like(sums[0])
    for k in range(1, len(sums)):
        c = wraps[k - 1]
        wraps[k - 1] = zero
        _cs_add(sums, wraps, k, c)
    wraps[-1] = zero


def _cs_resolve(sums: list, wraps: list) -> list:
    """One deferred carry-resolution pass: the only cross-column dependence
    chain in the whole product. The carry into column k+1 is column k's wrap
    count plus at most 1 (from adding the incoming carry), which is far below
    2^32 — wrap counts are bounded by the number of accumulated terms
    (<= 2 * limb count + folds)."""
    out = []
    carry = jnp.zeros_like(sums[0])
    for k in range(len(sums)):
        s = sums[k] + carry
        wrap = _carry(s < carry)
        out.append(s)
        carry = wraps[k] + wrap
    return out


def mul_limbs(a: list, b: list, out_len: int, resolve_every: int = 0) -> list:
    """Carry-save multiply of LSW-first limb lists, truncated to out_len
    (result == a*b mod 2^(32*out_len); exact when out_len covers the product).

    Each 32x32->64 partial product lands as independent (lo -> column i+j,
    hi -> column i+j+1) carry-save accumulations; one _cs_resolve pass per
    result propagates carries. resolve_every > 0 additionally folds wrap
    counts back into the sums every that-many rows of a — a tuning knob
    (shorter live ranges vs extra adds) exposed as the autotuner's
    carry-resolution interval."""
    zero = jnp.zeros_like(a[0])
    sums = [zero] * out_len
    wraps = [zero] * out_len
    for i, ai in enumerate(a):
        if i >= out_len:
            break
        for j, bj in enumerate(b):
            k = i + j
            if k >= out_len:
                break
            lo, hi = mul32(ai, bj)
            _cs_add(sums, wraps, k, lo)
            if k + 1 < out_len:
                _cs_add(sums, wraps, k + 1, hi)
        if resolve_every > 0 and (i + 1) % resolve_every == 0:
            _cs_fold(sums, wraps)
    return _cs_resolve(sums, wraps)


def sqr_limbs(a: list, out_len: int, resolve_every: int = 0) -> list:
    """Squaring specialization of mul_limbs: a_i*a_j == a_j*a_i, so each
    off-diagonal product is computed ONCE and accumulated twice (carry-save
    adds are cheap; doubling the product instead would need its own carry-out
    column), with the diagonal a_i^2 once — (la^2 + la) / 2 multiplies instead
    of la^2. Same truncation semantics as mul_limbs."""
    zero = jnp.zeros_like(a[0])
    sums = [zero] * out_len
    wraps = [zero] * out_len
    la = len(a)
    for i in range(la):
        if 2 * i >= out_len:
            break
        lo, hi = mul32(a[i], a[i])
        _cs_add(sums, wraps, 2 * i, lo)
        if 2 * i + 1 < out_len:
            _cs_add(sums, wraps, 2 * i + 1, hi)
        for j in range(i + 1, la):
            k = i + j
            if k >= out_len:
                break
            lo, hi = mul32(a[i], a[j])
            _cs_add(sums, wraps, k, lo)
            _cs_add(sums, wraps, k, lo)
            if k + 1 < out_len:
                _cs_add(sums, wraps, k + 1, hi)
                _cs_add(sums, wraps, k + 1, hi)
        if resolve_every > 0 and (i + 1) % resolve_every == 0:
            _cs_fold(sums, wraps)
    return _cs_resolve(sums, wraps)


def add_u32(limbs: list, x) -> list:
    """limbs + x where x is a (batch,) u32 (e.g. the lane iota)."""
    out = []
    carry = x
    for limb in limbs:
        s = limb + carry
        carry = _carry(s < limb)
        out.append(s)
    return out


def limbs_lt(a: list, b: list):
    """Elementwise a < b for equal-length LSW-first limb lists (entries may be
    arrays or broadcastable scalars)."""
    assert len(a) == len(b)
    lt = a[-1] < b[-1]
    eq = a[-1] == b[-1]
    for i in range(len(a) - 2, -1, -1):
        lt = lt | (eq & (a[i] < b[i]))
        eq = eq & (a[i] == b[i])
    return lt


def limbs_ge(a: list, b: list):
    return ~limbs_lt(a, b)


# --------------------------------------------------------------------------
# Digit extraction (chunked radix, constant divisors)
# --------------------------------------------------------------------------

def limbs_to_halfwords_msw(limbs: list, hw_count: int) -> list:
    """u32 limb list -> MSW-first list of 16-bit values held in u32 lanes."""
    out = []
    for i in range(hw_count - 1, -1, -1):
        out.append((limbs[i // 2] >> (16 * (i % 2))) & MASK16)
    return out


def _divmod_halfwords(hws_msw: list, divisor: int, out_len: int):
    """Long division of an MSW-first halfword list by a constant <= 2^16.

    Every intermediate (rem * 2^16 + halfword < divisor * 2^16 <= 2^32) fits
    in u32. Returns (quotient truncated to out_len MSW-first halfwords, rem).
    """
    c = np.uint32(divisor)
    rem = jnp.zeros_like(hws_msw[0])
    q = []
    for h in hws_msw:
        cur = (rem << 16) | h
        qi = cur // c
        rem = cur - qi * c
        q.append(qi)
    return q[len(q) - out_len :], rem


def set_digit_masks(plan: BasePlan, masks: list, digits: list) -> list:
    """OR each digit's presence bit into the u32 mask words."""
    one = np.uint32(1)
    zero = np.uint32(0)
    for d in digits:
        bit = jnp.left_shift(one, d & np.uint32(31))
        if plan.n_masks == 1:
            masks[0] = masks[0] | bit
        elif plan.n_masks == 2:
            # Two-word specialization (32 < base <= 64, incl. the b40/b50
            # benchmark bases): one compare routes the bit, saving the
            # word-index shift and a second compare per digit — ~40 digits
            # per candidate makes this measurable.
            hi = d >= np.uint32(32)
            masks[0] = masks[0] | jnp.where(hi, zero, bit)
            masks[1] = masks[1] | jnp.where(hi, bit, zero)
        else:
            w = d >> 5
            for wi in range(plan.n_masks):
                masks[wi] = masks[wi] | jnp.where(w == np.uint32(wi), bit, zero)
    return masks


def accumulate_digit_masks(plan: BasePlan, masks: list, limbs: list, num_digits: int, hw_count: int) -> list:
    """Extract all base digits of a value with exactly num_digits digits and
    OR each into the presence masks immediately.

    Chunked radix scheme: peel chunk_e digits at a time with one
    multi-halfword long division by the constant chunk_div, then split the
    small remainder into single digits with scalar constant divisions
    (reference nice_kernels.cu:203-247, sized for u32 instead of u64
    intermediates). Folding digits into masks as they appear keeps at most
    one digit array live, bounding the Pallas kernel's VMEM footprint at
    ~the halfword list instead of all `base` digit arrays."""
    base = np.uint32(plan.base)
    hws = limbs_to_halfwords_msw(limbs, hw_count)
    remaining = num_digits
    while remaining > plan.chunk_e:
        remaining -= plan.chunk_e
        new_hw = halfwords_for(plan.base**remaining)
        hws, rem = _divmod_halfwords(hws, plan.chunk_div, new_hw)
        # One constant division per digit — d = rem - (rem // b) * b; rem % b
        # would be a second division Mosaic does not CSE — and none at all
        # for the chunk's last digit (rem < b there, so the quotient is
        # provably zero and rem IS the digit).
        for _ in range(plan.chunk_e - 1):
            q = rem // base
            masks = set_digit_masks(plan, masks, [rem - q * base])
            rem = q
        masks = set_digit_masks(plan, masks, [rem])
    assert len(hws) == 1, (plan.base, num_digits, len(hws))
    rem = hws[0]
    for _ in range(remaining - 1):
        q = rem // base
        masks = set_digit_masks(plan, masks, [rem - q * base])
        rem = q
    if remaining > 0:
        masks = set_digit_masks(plan, masks, [rem])
    return masks


def num_uniques_lanes(plan: BasePlan, n_limbs: list, carry_interval: int = 0,
                      use_mxu: bool = False):
    """num_uniques of (n^2, n^3) for a batch of candidates given as limbs.

    carry_interval is the carry-save resolution interval (0 = resolve only
    once per product) — a pure performance knob, bit-identical results at any
    value; the autotuner sweeps it per (mode, base, backend). use_mxu routes
    the limb products through the banded Toeplitz dot_general path
    (ops/mxu.py) — also bit-identical, also autotuner-arbitrated
    (env NICE_TPU_MXU > tuned use_mxu arm > default VPU)."""
    if use_mxu:
        from nice_tpu.ops import mxu

        sq = mxu.sqr_limbs_mxu(n_limbs, plan.limbs_sq)
        cu = mxu.mul_limbs_mxu(sq, n_limbs, plan.limbs_cu)
    else:
        sq = sqr_limbs(n_limbs, plan.limbs_sq, resolve_every=carry_interval)
        cu = mul_limbs(sq, n_limbs, plan.limbs_cu,
                       resolve_every=carry_interval)
    masks = [jnp.zeros_like(n_limbs[0]) for _ in range(plan.n_masks)]
    masks = accumulate_digit_masks(plan, masks, sq, plan.d_sq, plan.hw_sq)
    masks = accumulate_digit_masks(plan, masks, cu, plan.d_cu, plan.hw_cu)
    uniques = jax.lax.population_count(masks[0])
    for m in masks[1:]:
        uniques = uniques + jax.lax.population_count(m)
    return uniques.astype(jnp.int32)


# --------------------------------------------------------------------------
# Batch entry points (jitted per (base, batch_size))
# --------------------------------------------------------------------------

def _iota_lanes(plan: BasePlan, start_limbs, batch_size: int) -> list:
    idx = jnp.arange(batch_size, dtype=U32)
    base_limbs = [
        jnp.broadcast_to(start_limbs[i], (batch_size,)) for i in range(plan.limbs_n)
    ]
    return add_u32(base_limbs, idx)


def histogram_lanes(plan: BasePlan, uniques, valid):
    """Exact histogram of num_uniques via one-hot accumulation. Scatter-adds
    (jnp.bincount) serialize on TPU; a lane-aligned one-hot reduction stays on
    the VPU (the analog of the reference kernel's per-warp shared-memory
    histograms, nice_kernels.cu:496-530). Invalid lanes count into bin 0."""
    u = jnp.where(valid, uniques, 0)
    bins = jnp.arange(plan.base + 2, dtype=jnp.int32)
    cols = 128 if u.size % 128 == 0 else 1  # lane-aligned when possible
    u2 = u.reshape(-1, cols)
    onehot = (u2[:, :, None] == bins[None, None, :]).astype(jnp.int32)
    return jnp.sum(onehot, axis=(0, 1))


def detailed_from_uniques(plan: BasePlan, uniques, valid):
    """Shared tail of the detailed step: (histogram, near_miss_count).
    Used by both the single-chip batch and the sharded per-device step so the
    masking/near-miss semantics cannot diverge."""
    hist = histogram_lanes(plan, uniques, valid)
    nm_count = jnp.sum(
        (valid & (uniques > plan.near_miss_cutoff)).astype(jnp.int32)
    )
    return hist, nm_count


@functools.partial(jax.jit, static_argnums=(0, 1),
                   static_argnames=("carry_interval", "use_mxu"))
def detailed_batch(plan: BasePlan, batch_size: int, start_limbs, valid_count,
                   *, carry_interval: int = 0, use_mxu: bool = False):
    """(histogram int32[base+2], near_miss_count int32) for one batch.

    Lanes >= valid_count are masked into histogram bin 0 (real candidates
    always have num_uniques >= 1).
    """
    n = _iota_lanes(plan, start_limbs, batch_size)
    uniques = num_uniques_lanes(plan, n, carry_interval, use_mxu)
    lane = jnp.arange(batch_size, dtype=jnp.int32)
    return detailed_from_uniques(plan, uniques, lane < valid_count)


@functools.partial(jax.jit, static_argnums=(0, 1),
                   static_argnames=("carry_interval", "use_mxu"))
def uniques_batch(plan: BasePlan, batch_size: int, start_limbs,
                  *, carry_interval: int = 0, use_mxu: bool = False):
    """Per-lane num_uniques (rare-path extraction of near misses / nice)."""
    n = _iota_lanes(plan, start_limbs, batch_size)
    return num_uniques_lanes(plan, n, carry_interval, use_mxu)


def compact_survivors(uniques, valid, thresh: int, cap: int):
    """On-device survivor compaction: prefix-sum scatter of the lanes with
    num_uniques > thresh into cap-sized output arrays.

    Returns (count i32, idx i32[cap], uniq i32[cap]): surviving lane indices
    (ascending) and their uniques counts, with entries >= count undefined
    (zeros). Survivors past cap are dropped — callers compare count against
    cap and re-run dense on overflow. The point: a readback transfers
    2*cap + 1 words instead of the full per-lane array (the device-side
    analog of the reference only shipping hit indices back from its GPU
    prefilter, client_process_gpu.rs:407-413).
    """
    mask = valid & (uniques > thresh)
    pos = jnp.cumsum(mask.astype(jnp.int32)) - 1
    # Non-survivors (and overflow survivors) get an out-of-range target;
    # mode="drop" discards them in-graph, no host round trip.
    tgt = jnp.where(mask, pos, cap)
    lane = jnp.arange(uniques.shape[0], dtype=jnp.int32)
    idx = jnp.zeros(cap, jnp.int32).at[tgt].set(lane, mode="drop")
    uniq = jnp.zeros(cap, jnp.int32).at[tgt].set(uniques, mode="drop")
    return jnp.sum(mask.astype(jnp.int32)), idx, uniq


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3),
                   static_argnames=("carry_interval", "use_mxu"))
def survivors_batch(plan: BasePlan, batch_size: int, thresh: int, cap: int,
                    start_limbs, valid_count, *, carry_interval: int = 0,
                    use_mxu: bool = False):
    """Compacted rare-path extraction: (count, idx[cap], uniq[cap]) of lanes
    with num_uniques > thresh. thresh = near_miss_cutoff serves detailed;
    thresh = base - 1 serves niceonly (uniques > base-1 <=> == base)."""
    n = _iota_lanes(plan, start_limbs, batch_size)
    uniques = num_uniques_lanes(plan, n, carry_interval, use_mxu)
    lane = jnp.arange(batch_size, dtype=jnp.int32)
    return compact_survivors(uniques, lane < valid_count, thresh, cap)


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=(2,),
                   static_argnames=("carry_interval", "use_mxu"))
def detailed_accum_batch(plan: BasePlan, batch_size: int, hist_acc,
                         start_limbs, valid_count, *, carry_interval: int = 0,
                         use_mxu: bool = False):
    """detailed_batch folded into a DEVICE-RESIDENT histogram accumulator.

    hist_acc (i32[base+2], donated) is carried across batches on the device;
    only the near-miss scalar crosses the bus per batch, and the accumulator
    itself transfers once per field (engine.process_range_detailed flushes it
    well before i32 bins could saturate). Padding lanes land in bin 0, which
    no consumer reads (distributions report bins 1..base)."""
    n = _iota_lanes(plan, start_limbs, batch_size)
    uniques = num_uniques_lanes(plan, n, carry_interval, use_mxu)
    lane = jnp.arange(batch_size, dtype=jnp.int32)
    hist, nm = detailed_from_uniques(plan, uniques, lane < valid_count)
    return hist_acc + hist, nm


@functools.partial(jax.jit, static_argnums=(0, 1),
                   static_argnames=("carry_interval", "use_mxu"))
def niceonly_dense_batch(plan: BasePlan, batch_size: int, start_limbs,
                        valid_count, *, carry_interval: int = 0,
                        use_mxu: bool = False):
    """Count of fully nice lanes in a dense range batch."""
    n = _iota_lanes(plan, start_limbs, batch_size)
    uniques = num_uniques_lanes(plan, n, carry_interval, use_mxu)
    lane = jnp.arange(batch_size, dtype=jnp.int32)
    valid = lane < valid_count
    return jnp.sum((valid & (uniques == plan.base)).astype(jnp.int32))


# --------------------------------------------------------------------------
# Fused residue-filter pruning (device-side, before any limb math)
# --------------------------------------------------------------------------

def _mod_const(x, c: int):
    """x mod c for u32 lanes via the divide/multiply-back idiom (one constant
    division; jnp.mod would be a second division Mosaic does not CSE, and the
    subtract form is the wrap-free remainder shape the J2 interval
    interpreter's peephole proves to be in [0, c-1])."""
    cv = np.uint32(c)
    q = x // cv
    return x - q * cv


def residue_keep_lanes(plan: BasePlan, n_limbs: list):
    """Per-lane residue-filter membership, by direct congruence (no table,
    no gather): a nice n must satisfy n^2 + n^3 == b(b-1)/2 (mod b-1)
    (digit sums are permutation-invariant — ops/residue_filter.py), so a
    lane survives iff r = n mod (b-1) satisfies the congruence.

    r comes from a limb fold (2^(32i) mod m weights): every term is below
    m^2 < 2^22 and the sum over <= 64 limbs stays below 2^28, so the whole
    evaluation is u32-exact and interval-provable. Membership equals
    ``r in residue_filter.get_residue_filter(base)`` exactly."""
    m = plan.base - 1
    target = plan.base * (plan.base - 1) // 2 % m
    acc = jnp.zeros_like(n_limbs[0])
    for i, limb in enumerate(n_limbs):
        w = np.uint32(pow(2, 32 * i, m))
        acc = acc + _mod_const(limb, m) * w
    r = _mod_const(acc, m)
    t = _mod_const(r * r, m)            # r^2 mod m   (r*r < 2^22)
    cube = _mod_const(t * r, m)         # r^3 mod m   (t*r < 2^22)
    return _mod_const(t + cube, m) == np.uint32(target)


def filtered_cap(plan: BasePlan, batch_size: int) -> int:
    """Static survivor cap for a CONSECUTIVE window of batch_size candidates:
    each residue class contributes at most ceil(batch/(b-1)) members to any
    window, so |R| * ceil(batch/(b-1)) is a true bound (never drops a
    survivor); lane-aligned up to a multiple of 128 and clamped at
    batch_size (survivors cannot exceed the window)."""
    from nice_tpu.ops import residue_filter

    m = plan.base - 1
    n_res = len(residue_filter.get_residue_filter(plan.base))
    cap = n_res * ((batch_size + m - 1) // m)
    cap = min(-(-cap // 128) * 128, batch_size)
    return max(cap, 1)


@functools.partial(jax.jit, static_argnums=(0, 1),
                   static_argnames=("carry_interval", "use_mxu"))
def niceonly_filtered_batch(plan: BasePlan, batch_size: int, start_limbs,
                            valid_count, *, carry_interval: int = 0,
                            use_mxu: bool = False):
    """niceonly_dense_batch with the residue filter FUSED in front of the
    limb math: the congruence mask is evaluated on the raw lane values,
    survivors are prefix-scatter compacted into a filtered_cap-sized tile
    (the compact_survivors idiom), and only those lanes pay
    squaring/cubing/digit extraction. The filter excludes exactly the lanes
    that cannot be FULLY nice, so the count is bit-identical to the dense
    kernel's.

    Returns (nice_count int32, pruned int32) — pruned feeds the
    nice_engine_filter_pruned_total series."""
    n = _iota_lanes(plan, start_limbs, batch_size)
    lane = jnp.arange(batch_size, dtype=jnp.int32)
    valid = lane < valid_count
    keep = valid & residue_keep_lanes(plan, n)
    cap = filtered_cap(plan, batch_size)
    pos = jnp.cumsum(keep.astype(jnp.int32)) - 1
    tgt = jnp.where(keep, pos, cap)
    idx = jnp.zeros(cap, jnp.int32).at[tgt].set(lane, mode="drop")
    cnt = jnp.sum(keep.astype(jnp.int32))
    survivors = [limb[idx] for limb in n]
    uniques = num_uniques_lanes(plan, survivors, carry_interval, use_mxu)
    sub = jnp.arange(cap, dtype=jnp.int32)
    # Padding slots replay lane 0; the sub < cnt mask keeps them out.
    nice = jnp.sum(((sub < cnt) & (uniques == plan.base)).astype(jnp.int32))
    pruned = jnp.sum(valid.astype(jnp.int32)) - cnt
    return nice, pruned


# --------------------------------------------------------------------------
# Megaloop: whole-segment scans with a device-resident carry (PR 17)
# --------------------------------------------------------------------------
#
# One dispatch covers n_iters consecutive batches: a lax.scan advances the
# field cursor IN-PROGRAM and folds each batch's result into the carried
# accumulator, so the host's per-batch dispatch/readback work collapses to
# one launch and one scalar readback per segment. The carry deliberately
# counts DOWN a `rem` lane budget instead of carrying a loop index: the
# `rem - valid` subtraction stays provably non-negative under the declared
# carry bound (see analysis/kernelspec.py carry_bounds), where an `i + 1`
# index increment seeded at the dtype top would be an undischargeable J2
# wrap obligation. Tail segments reuse the full-shape executable with a
# smaller valid_total — over-run lanes mask exactly as the per-batch kernels
# mask padding lanes, so results are byte-identical to the batch loop.

def _advance_cursor(plan: BasePlan, cursor, batch_size: int):
    """cursor (u32[limbs_n]) + batch_size, as a stacked u32 array (the scan
    carry needs an array, not the limb list the batch kernels consume)."""
    limbs = add_u32([cursor[i] for i in range(plan.limbs_n)],
                    np.uint32(batch_size))
    return jnp.stack(limbs)


@functools.partial(jax.jit, static_argnums=(0, 1, 2), donate_argnums=(3,),
                   static_argnames=("carry_interval", "use_mxu"))
def detailed_accum_megaloop(plan: BasePlan, batch_size: int, n_iters: int,
                            hist_acc, start_limbs, valid_total, *,
                            carry_interval: int = 0, use_mxu: bool = False):
    """n_iters batches of detailed_batch folded into the donated hist_acc.

    Returns (hist_acc + sum of per-batch histograms, total near-miss count).
    valid_total is the whole segment's lane budget; each iteration consumes
    up to batch_size of it, so a short final batch masks exactly as the
    per-batch path does (spill lanes land in bin 0, which no consumer
    reads)."""
    def body(carry, _):
        cursor, rem, acc, nm_acc = carry
        valid = jnp.minimum(rem, jnp.int32(batch_size))
        hist, nm = detailed_batch(plan, batch_size, cursor, valid,
                                  carry_interval=carry_interval,
                                  use_mxu=use_mxu)
        return (_advance_cursor(plan, cursor, batch_size), rem - valid,
                acc + hist, nm_acc + nm), None

    init = (jnp.asarray(start_limbs, U32),
            jnp.asarray(valid_total, jnp.int32), hist_acc, jnp.int32(0))
    (_cursor, _rem, acc, nm), _ = jax.lax.scan(body, init, None,
                                               length=n_iters)
    return acc, nm


@functools.partial(jax.jit, static_argnums=(0, 1, 2),
                   static_argnames=("carry_interval", "use_mxu"))
def niceonly_dense_megaloop(plan: BasePlan, batch_size: int, n_iters: int,
                            start_limbs, valid_total, *,
                            carry_interval: int = 0, use_mxu: bool = False):
    """Total nice count over n_iters batches of niceonly_dense_batch."""
    def body(carry, _):
        cursor, rem, count = carry
        valid = jnp.minimum(rem, jnp.int32(batch_size))
        c = niceonly_dense_batch(plan, batch_size, cursor, valid,
                                 carry_interval=carry_interval,
                                 use_mxu=use_mxu)
        return (_advance_cursor(plan, cursor, batch_size), rem - valid,
                count + c), None

    init = (jnp.asarray(start_limbs, U32),
            jnp.asarray(valid_total, jnp.int32), jnp.int32(0))
    (_cursor, _rem, count), _ = jax.lax.scan(body, init, None,
                                             length=n_iters)
    return count


@functools.partial(jax.jit, static_argnums=(0, 1, 2),
                   static_argnames=("carry_interval", "use_mxu"))
def niceonly_filtered_megaloop(plan: BasePlan, batch_size: int, n_iters: int,
                               start_limbs, valid_total, *,
                               carry_interval: int = 0,
                               use_mxu: bool = False):
    """(total nice count, total pruned) over n_iters filtered batches."""
    def body(carry, _):
        cursor, rem, count, pruned_acc = carry
        valid = jnp.minimum(rem, jnp.int32(batch_size))
        c, pruned = niceonly_filtered_batch(plan, batch_size, cursor, valid,
                                            carry_interval=carry_interval,
                                            use_mxu=use_mxu)
        return (_advance_cursor(plan, cursor, batch_size), rem - valid,
                count + c, pruned_acc + pruned), None

    init = (jnp.asarray(start_limbs, U32),
            jnp.asarray(valid_total, jnp.int32), jnp.int32(0), jnp.int32(0))
    (_cursor, _rem, count, pruned), _ = jax.lax.scan(body, init, None,
                                                     length=n_iters)
    return count, pruned
