"""CRT stride iteration: combine residue (mod b-1) and LSD (mod b^k) filters.

Instead of testing filters per candidate, precompute the valid residues of the
combined modulus M = (b-1) * b^k (gcd(b-1, b^k) = 1) and jump candidate to
candidate with a gap table — zero per-candidate filter cost. Mirrors reference
common/src/stride_filter.rs:20-155.

The table also powers the TPU niceonly kernel's dense candidate enumeration:
candidate g maps to B0 + (g // R) * M + valid_residues[g % R] (the reference
GPU's index-arithmetic trick, nice_kernels.cu:452-457), which device kernels
compute branch-free.
"""

from __future__ import annotations

import bisect
from functools import lru_cache

import numpy as np

from nice_tpu.core.types import FieldSize, NiceNumberSimple
from nice_tpu.ops import lsd_filter, residue_filter
from nice_tpu.ops.scalar import get_is_nice


class StrideTable:
    """Precomputed valid residues mod M = (b-1) * b^k, plus gap table."""

    def __init__(self, base: int, k: int):
        b_minus_1 = base - 1
        b_k = base**k
        self.base = base
        self.k = k
        self.modulus = b_minus_1 * b_k

        residue_set = np.array(residue_filter.get_residue_filter(base), dtype=np.int64)
        lsd_bitmap = np.asarray(lsd_filter.get_valid_multi_lsd_bitmap(base, k))

        r = np.arange(self.modulus, dtype=np.int64)
        passes_residue = np.isin(r % b_minus_1, residue_set)
        passes_lsd = lsd_bitmap[r % b_k]
        valid = np.nonzero(passes_residue & passes_lsd)[0]

        self.valid_residues: list[int] = valid.tolist()
        if len(valid):
            gaps = np.empty(len(valid), dtype=np.int64)
            gaps[:-1] = valid[1:] - valid[:-1]
            gaps[-1] = self.modulus - valid[-1] + valid[0]
            self.gap_table: list[int] = gaps.tolist()
            # ndarray twins for the native engine: zero-copy pointer passing
            # (a per-call ctypes rebuild of a depth-3 table once dominated the
            # whole native niceonly path) and the u32 residue array keys the
            # polynomial-residue fast kernel (modulus < 2^32 always holds —
            # deeper tables are rejected by the depth planner's u32 guard).
            self.gap_array = gaps.astype(np.uint64)
            self.gap_array.setflags(write=False)
            self.residues_u32 = valid.astype(np.uint32)
            self.residues_u32.setflags(write=False)
        else:
            self.gap_table = []
            self.gap_array = np.empty(0, dtype=np.uint64)
            self.residues_u32 = np.empty(0, dtype=np.uint32)

    @property
    def num_residues(self) -> int:
        return len(self.valid_residues)

    def first_valid_at_or_after(self, start: int) -> tuple[int, int]:
        """Smallest valid candidate n >= start, plus its residue index
        (reference stride_filter.rs:99-124).

        Raises ValueError when the table is empty (a base whose residue filter
        admits nothing, e.g. 15 — such bases provably contain no nice numbers;
        callers should use num_residues == 0 as "nothing to search").
        """
        if not self.valid_residues:
            raise ValueError(
                f"base {self.base} has no valid stride residues: no number "
                "can be nice"
            )
        r = start % self.modulus
        idx = bisect.bisect_left(self.valid_residues, r)
        if idx >= len(self.valid_residues):
            idx = 0
        target_r = self.valid_residues[idx]
        if target_r >= r:
            n = start + (target_r - r)
        else:
            n = start + (self.modulus - r + target_r)
        return (n, idx)

    def candidate_index(self, n: int) -> int:
        """Global dense index g of valid candidate n: g = (n // M) * R + idx.

        Inverse of candidate_at. n must be a valid candidate.
        """
        cycle, r = divmod(n, self.modulus)
        idx = bisect.bisect_left(self.valid_residues, r)
        assert (
            idx < len(self.valid_residues) and self.valid_residues[idx] == r
        ), "n is not a valid stride candidate"
        return cycle * len(self.valid_residues) + idx

    def candidate_at(self, g: int) -> int:
        """Candidate value for dense index g (the P7 index-arithmetic map)."""
        cycle, j = divmod(g, len(self.valid_residues))
        return cycle * self.modulus + self.valid_residues[j]

    def count_candidates(self, range_: FieldSize) -> int:
        """Number of valid candidates in a half-open range, via dense indices."""
        if not self.valid_residues:
            return 0
        n0, idx0 = self.first_valid_at_or_after(range_.start())
        if n0 >= range_.end():
            return 0
        g0 = (n0 // self.modulus) * len(self.valid_residues) + idx0
        n1, idx1 = self.first_valid_at_or_after(range_.end())
        g1 = (n1 // self.modulus) * len(self.valid_residues) + idx1
        return g1 - g0

    def iterate_range(self, range_: FieldSize, base: int) -> list[NiceNumberSimple]:
        """Gap-jump through valid candidates, early-exit nice check on each
        (reference stride_filter.rs:139-155)."""
        if not self.valid_residues:
            return []
        results: list[NiceNumberSimple] = []
        n, idx = self.first_valid_at_or_after(range_.start())
        end = range_.end()
        gap_table = self.gap_table
        num = len(gap_table)
        while n < end:
            if get_is_nice(n, base):
                results.append(NiceNumberSimple(number=n, num_uniques=base))
            n += gap_table[idx]
            idx += 1
            if idx == num:
                idx = 0
        return results


@lru_cache(maxsize=None)
def get_stride_table(base: int, k: int) -> StrideTable:
    """Shared per-(base, k) table (built once per process)."""
    return StrideTable(base, k)


@lru_cache(maxsize=None)
def stride_residue_count(base: int, k: int) -> int:
    """num_residues of the (base, k) table WITHOUT building it.

    gcd(b-1, b^k) = 1, so by CRT the count factors into
    |valid residues mod b-1| * |valid k-suffixes mod b^k| — stride-depth
    planning scores every depth with this product and materializes only the
    chosen table (the deep tables are ~100x costlier to build than to score)."""
    return len(residue_filter.get_residue_filter(base)) * (
        lsd_filter.valid_multi_lsd_count(base, k)
    )
