"""Adaptive MSD recursion floor for the heterogeneous niceonly pipeline.

The niceonly device path is a two-phase pipeline per field: the HOST runs the
MSD prefix filter down to a recursion floor (coarse floor = cheap host work,
more surviving lanes for the device; fine floor = expensive host recursion,
fewer lanes), then the DEVICE scans the surviving stride candidates. The
optimal floor balances the two phases — the reference measured 350 s -> 4.8 s
per 1e12 numbers between floor 250 and 64k on one core (ref
client_process_gpu.rs:85-94) and retunes the floor per field to hold
msd_time ~= device_tail_time (ref client_process_gpu.rs:103-184).

This is the TPU re-derivation of that controller. Differences from the
reference are deliberate:

- The TPU device tail is far cheaper per lane than the CUDA kernel it was
  tuned against (the stride kernel derives candidates on-device with zero HBM
  traffic), so the ceiling is higher and the default seed coarser.
- Timing uses time.monotonic() around explicit phase boundaries in
  engine._niceonly_pallas; there is no stream-event machinery to integrate.

NICE_TPU_MSD_FLOOR pins the floor and disables adaptation (the analog of
NICE_GPU_MSD_FLOOR).
"""

from __future__ import annotations

import os

from nice_tpu.utils import knobs, lockdep

# Below ~250 the device receives virtually the dense range; the cap exists
# only to bound descriptor-span growth (the reference sweep shows survival
# saturating, so past some point a coarser floor stops buying host time —
# but on a 1-core host driving a whole chip the balance point can sit far
# coarser than the reference's 64k GPU sweet spot, so the cap is generous
# and the controller finds the knee).
FLOOR_MIN = 250
FLOOR_MAX = 1 << 24

# Fields to observe before adapting (one-time jit/compile costs would skew
# the first ratios).
WARMUP_FIELDS = 2

# Max multiplicative nudge per field, either direction.
MAX_STEP = 1.5

# Phases shorter than this are measurement noise; treat as "free".
MIN_SECS = 0.002

# Fields whose whole pipeline ran faster than this carry no tuning signal
# (warm-up probes, benchmark 1-number fields, fully-filtered ranges): one
# fixed dispatch latency dwarfs the phase split and would walk the floor
# away from its balance point between real fields.
TRIVIAL_SECS = 0.25

# Fields spanning fewer than this many recursion leaves at the current floor
# carry no phase-split signal either: the "device" time of a one-leaf field
# is dominated by one-time kernel compilation and fixed dispatch latency, not
# lane throughput. Observed failure mode without this gate: a 1-number
# benchmark warm-up field measured device = 4.7 s (pure Mosaic compile),
# walked the floor down 1.5x, which flipped the stride-depth plan
# (k=1/periods=1024 -> k=3/periods=1) and forced a RECOMPILE inside the timed
# field — niceonly extra-large read 4.6 s instead of its real 0.15 s.
SIGNAL_MIN_LEAVES = 16

# Seed calibrated so a 32-core host lands near the reference's 16k sweet
# spot; fewer cores -> coarser floor (host recursion is the bottleneck).
_SEED_CORE_PRODUCT = 2_097_152


class AdaptiveFloor:
    """Per-process controller; thread-safe (client workers share one)."""

    def __init__(self, pinned: int | None = None, seed: int | None = None):
        self._lock = lockdep.make_lock("ops.adaptive_floor.AdaptiveFloor._lock")
        self.pinned = pinned is not None
        if pinned is not None:
            self.floor = float(max(1, pinned))
            self._warmup = 0
        else:
            if seed is None:
                cores = os.cpu_count() or 32
                seed = _SEED_CORE_PRODUCT // cores
            self.floor = float(min(max(seed, FLOOR_MIN), FLOOR_MAX))
            self._warmup = WARMUP_FIELDS

    def current(self) -> int:
        return int(self.floor)

    def observe(
        self, host_secs: float, device_secs: float, numbers: int | None = None
    ) -> None:
        """Record one field's phase split and nudge the floor toward
        host_secs ~= device_secs. No-op when pinned or warming up.

        `numbers` is the field size; fields spanning < SIGNAL_MIN_LEAVES
        recursion leaves at the current floor are ignored (their timing is
        compile/dispatch latency, not throughput — see SIGNAL_MIN_LEAVES).
        The warm-up counter is consumed only by signal-bearing fields, so a
        string of tiny probe fields cannot exhaust it before the first real
        field (whose device time includes one-time kernel compilation) shows
        up."""
        if self.pinned:
            return
        with self._lock:
            down_only = False
            if numbers is not None and numbers < SIGNAL_MIN_LEAVES * self.floor:
                # Too few leaves for a trustworthy split. Probe-sized fields
                # (including compile-dominated warm-ups) carry no signal at
                # all; larger fields that merely fall under the gate (e.g. a
                # 5e6-number workload against a coarse seed floor) may still
                # refine DOWNWARD — without this a too-coarse seed would
                # freeze the controller for small-field workloads forever.
                if numbers < SIGNAL_MIN_LEAVES * FLOOR_MIN:
                    return
                down_only = True
            if host_secs + device_secs < TRIVIAL_SECS:
                return  # field too small to tell anything
            if self._warmup > 0:
                self._warmup -= 1
                return
            if device_secs < MIN_SECS:
                ratio = MAX_STEP  # device idle: host filter is over-working
            elif host_secs < MIN_SECS:
                ratio = 1.0 / MAX_STEP  # host free: refine the filter
            else:
                ratio = host_secs / device_secs
            ratio = min(max(ratio, 1.0 / MAX_STEP), MAX_STEP)
            if down_only and ratio >= 1.0:
                return  # sub-gate fields may refine, never coarsen
            new_floor = self.floor * ratio
            if ratio > 1.0 and numbers is not None:
                # Never coarsen past the point where fields of the size we
                # just observed would fall below the leaf gate: without this
                # cap a few host-dominated fields ratchet the floor one-way
                # until 16*floor exceeds the workload's field size and the
                # controller freezes with no recovery path.
                new_floor = min(new_floor, numbers / SIGNAL_MIN_LEAVES)
                new_floor = max(new_floor, self.floor)  # cap, not a shrink
            self.floor = min(max(new_floor, FLOOR_MIN), FLOOR_MAX)


_CONTROLLERS: dict[str, AdaptiveFloor] = {}
_CONTROLLERS_LOCK = lockdep.make_lock("ops.adaptive_floor._CONTROLLERS_LOCK")


def get_floor_controller(pipeline: str = "strided") -> AdaptiveFloor:
    """Per-pipeline controller; NICE_TPU_MSD_FLOOR pins all of them.

    The strided-descriptor and dense device pipelines have DIFFERENT optimal
    floors (a strided device lane is far cheaper per surviving number than a
    dense lane), so a shared controller would oscillate between their balance
    points when a client alternates bases; each pipeline keys its own."""
    with _CONTROLLERS_LOCK:
        ctrl = _CONTROLLERS.get(pipeline)
        if ctrl is None:
            raw = knobs.MSD_FLOOR.raw()
            pinned = None
            if raw:
                try:
                    pinned = max(1, int(float(raw)))
                except (ValueError, OverflowError):  # e.g. "abc", "inf"
                    pass  # fall through to adaptive
            ctrl = _CONTROLLERS[pipeline] = AdaptiveFloor(pinned=pinned)
        return ctrl


def reset_for_tests() -> None:
    with _CONTROLLERS_LOCK:
        _CONTROLLERS.clear()
