"""Pallas TPU kernels for the niceness hot loops.

Drop-in replacements for the ve.*_batch entry points: the whole per-candidate
pipeline (derive n from a start offset, square, cube, chunked radix digit
extraction, digit-mask popcount, histogram) runs inside one Mosaic kernel with
zero HBM traffic — no input tensors at all (candidates are derived on-device
from the scalar-prefetched start limbs, the analog of the reference's
input-free grid-stride CUDA kernel, nice_kernels.cu:486-531), and the only
output is a (2,128) i32 SMEM stats tile accumulated across sequential grid
steps (the analog of its per-warp shared-mem histograms, nice_kernels.cu:496-530).

The arithmetic is shared with ops/vector_engine.py — those helpers are pure
elementwise jnp on u32 arrays of any shape, so the exact same code traces into
the Mosaic kernel on (rows, 128) VPU blocks. One implementation, two
compilers, bit-identical results (the cross-backend parity contract the whole
reference test strategy is built on, SURVEY.md §4).

Output tile layout (row, col), with hist_rows = ceil((base+2)/128):
  [b // 128, b % 128]  histogram bin b of num_uniques, b < base+2 (padding
                       lanes counted in bin 0)
  [hist_rows, 0]       near-miss count (detailed) / nice count (niceonly)

The histogram spans as many 128-lane SMEM rows as the base needs, so hi-base
plans pass supports_base instead of falling back to jnp (the tile stays a few
hundred bytes of SMEM either way); limb storage is limb-major throughout —
one (rows, 128) VPU tile per limb — so every carry-save partial-product
column is a full-tile vector op.

On non-TPU backends the kernels run in interpreter mode automatically, which
is how the test suite exercises them without hardware (the analog of the
reference's NVRTC compile-only + CPU-mirror tests, client_process_gpu.rs:1421).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from nice_tpu.obs.series import PALLAS_DISPATCH_SECONDS
from nice_tpu.ops import vector_engine as ve
from nice_tpu.ops.limbs import BasePlan

# Lanes per grid step: 128 sublanes x 128 lanes. Keeps every live (rows, 128)
# u32 intermediate at 64 KiB so the whole pipeline (~15 live arrays during
# extraction) sits comfortably in the ~16 MiB of VMEM. Committed sweep
# (round 4, b40 2^26-lane batch on a v5e): rows 64/128/256/512 ->
# 1.39/1.39/1.32/1.22 G lanes/s — smaller blocks leave VMEM headroom for
# Mosaic's pipelining; 128 chosen over 64 for fewer grid steps.
BLOCK_ROWS = 128
BLOCK_LANES = BLOCK_ROWS * 128


@functools.lru_cache(maxsize=None)
def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# Histogram rows in the stats tile: a plan-derived cap, not a hard-coded 4.
# 16 rows covers bases up to 2046 — the whole sweep range the limb planner
# can express — while still bounding the unrolled per-bin accumulation in
# the kernel (the SMEM tile stays a few KiB). The old 4-row cap silently
# pinned the searchable range at base 510.
_HIST_ROWS_MAX = 16


def _hist_rows(plan: BasePlan) -> int:
    """128-lane SMEM rows the histogram needs (bins 0..base+1)."""
    return -(-(plan.base + 2) // 128)


def supports_base(plan: BasePlan) -> bool:
    """The stats tile spans ceil((base+2)/128) histogram rows (plus one
    counter row); any base whose histogram fits _HIST_ROWS_MAX rows runs."""
    return _hist_rows(plan) <= _HIST_ROWS_MAX


def _effective_block_rows(batch_size: int, block_rows: int) -> int:
    """Largest block (<= block_rows) that tiles batch_size exactly — shrinks
    for small batches and for batch sizes not divisible by the default block."""
    import math

    if batch_size % 128 != 0:
        raise ValueError(f"batch_size must be a multiple of 128, got {batch_size}")
    return math.gcd(batch_size // 128, block_rows)


def _block_iota(block_rows: int):
    row = jax.lax.broadcasted_iota(jnp.int32, (block_rows, 128), 0)
    col = jax.lax.broadcasted_iota(jnp.int32, (block_rows, 128), 1)
    return row * 128 + col


def _derive_lanes(plan: BasePlan, start_ref, idx, block_rows: int):
    """n = start + global lane index, as broadcast u32 limbs."""
    base_limbs = [
        jnp.full((block_rows, 128), start_ref[i], dtype=jnp.uint32)
        for i in range(plan.limbs_n)
    ]
    return ve.add_u32(base_limbs, idx.astype(jnp.uint32))


def _make_kernel(plan: BasePlan, mode: str, block_rows: int,
                 carry_interval: int = 0, use_mxu: bool = False):
    """mode: "detailed" (histogram + near-miss count), "niceonly" (count),
    or "niceonly-fused" (count + pruned, with the residue-filter congruence
    evaluated in-kernel so pruned lanes never count as candidates).
    carry_interval: carry-save resolution interval threaded into
    ve.num_uniques_lanes (bit-identical results at any value). use_mxu
    mirrors the ops/mxu.py Toeplitz dot_general packing into the kernel —
    the limb helpers are shape-polymorphic, so the same contraction traces
    onto (rows, 128) Mosaic tiles."""
    hist_rows = _hist_rows(plan)

    def kernel(start_ref, valid_ref, out_ref):
        step = pl.program_id(0)
        lane0 = step * (block_rows * 128)
        idx = _block_iota(block_rows) + lane0
        n = _derive_lanes(plan, start_ref, idx, block_rows)
        uniques = ve.num_uniques_lanes(plan, n, carry_interval, use_mxu)
        valid = idx < valid_ref[0]

        @pl.when(step == 0)
        def _():
            # Zero the whole tile (SMEM output buffers start undefined).
            for r in range(hist_rows + 1):
                for b in range(128):
                    out_ref[r, b] = 0

        if mode == "detailed":
            u = jnp.where(valid, uniques, 0)
            for b in range(plan.base + 2):
                out_ref[b // 128, b % 128] += jnp.sum(
                    (u == b).astype(jnp.int32)
                )
            out_ref[hist_rows, 0] += jnp.sum(
                (valid & (uniques > plan.near_miss_cutoff)).astype(jnp.int32)
            )
        elif mode == "niceonly-fused":
            # The fused residue prune: lanes failing the n^2+n^3 congruence
            # (ve.residue_keep_lanes — pure u32 arithmetic, Mosaic-safe)
            # cannot be fully nice, so they are masked out of the nice
            # count and tallied in the pruned counter at [hist_rows, 1].
            keep = ve.residue_keep_lanes(plan, n)
            out_ref[hist_rows, 0] += jnp.sum(
                (valid & keep & (uniques == plan.base)).astype(jnp.int32)
            )
            out_ref[hist_rows, 1] += jnp.sum(
                (valid & ~keep).astype(jnp.int32)
            )
        else:
            out_ref[hist_rows, 0] += jnp.sum(
                (valid & (uniques == plan.base)).astype(jnp.int32)
            )

    return kernel


@functools.lru_cache(maxsize=None)
def _stats_callable(plan: BasePlan, mode: str, batch_size: int,
                    block_rows: int, carry_interval: int = 0,
                    use_mxu: bool = False):
    assert batch_size % (block_rows * 128) == 0, (batch_size, block_rows)
    num_blocks = batch_size // (block_rows * 128)
    hist_rows = _hist_rows(plan)
    tile_rows = hist_rows + 1  # histogram rows + the counter row
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # start limbs + valid count land in SMEM
        grid=(num_blocks,),
        in_specs=[],
        # Stats tile lives in SMEM: Mosaic only allows scalar stores there,
        # and the per-bin counts are scalars by construction.
        out_specs=pl.BlockSpec(
            (tile_rows, 128), lambda step, *_: (0, 0), memory_space=pltpu.SMEM
        ),
    )
    call = pl.pallas_call(
        _make_kernel(plan, mode, block_rows, carry_interval, use_mxu),
        out_shape=jax.ShapeDtypeStruct((tile_rows, 128), jnp.int32),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )

    @jax.jit
    def run(start_limbs, valid_count):
        tile = call(start_limbs, jnp.reshape(valid_count, (1,)).astype(jnp.int32))
        if mode == "niceonly-fused":
            return tile[hist_rows, 0], tile[hist_rows, 1]
        return tile[:hist_rows].reshape(-1), tile[hist_rows, 0]

    return run


import contextlib
import time as _time


@contextlib.contextmanager
def _timed(kernel: str):
    """Per-dispatch timing for the public kernel entry points (under jit the
    call is an async enqueue, so this measures dispatch cost; in interpreter
    mode it is the full synchronous kernel execution)."""
    t0 = _time.perf_counter()
    try:
        yield
    finally:
        PALLAS_DISPATCH_SECONDS.labels(kernel).observe(_time.perf_counter() - t0)


def detailed_batch(plan: BasePlan, batch_size: int, start_limbs, valid_count,
                   block_rows: int = BLOCK_ROWS, carry_interval: int = 0,
                   use_mxu: bool = False):
    """(histogram i32[128 * hist_rows] (bins 0..base+1), near_miss_count i32)."""
    block_rows = _effective_block_rows(batch_size, block_rows)
    run = _stats_callable(plan, "detailed", batch_size, block_rows,
                          carry_interval, use_mxu)
    with _timed("detailed"):
        return run(start_limbs, valid_count)


def niceonly_dense_batch(plan: BasePlan, batch_size: int, start_limbs,
                         valid_count, block_rows: int = BLOCK_ROWS,
                         carry_interval: int = 0, use_mxu: bool = False):
    """Count of fully nice lanes in a dense range batch (i32)."""
    block_rows = _effective_block_rows(batch_size, block_rows)
    run = _stats_callable(plan, "niceonly", batch_size, block_rows,
                          carry_interval, use_mxu)
    with _timed("niceonly_dense"):
        return run(start_limbs, valid_count)[1]


def niceonly_fused_batch(plan: BasePlan, batch_size: int, start_limbs,
                         valid_count, block_rows: int = BLOCK_ROWS,
                         carry_interval: int = 0, use_mxu: bool = False):
    """niceonly_dense_batch with the residue filter fused into the kernel:
    (nice_count i32, pruned i32). Bit-identical count (the congruence only
    excludes lanes that cannot be fully nice); pruned feeds the
    nice_engine_filter_pruned_total series."""
    block_rows = _effective_block_rows(batch_size, block_rows)
    run = _stats_callable(plan, "niceonly-fused", batch_size, block_rows,
                          carry_interval, use_mxu)
    with _timed("niceonly_fused"):
        return run(start_limbs, valid_count)


# --------------------------------------------------------------------------
# Stride-compacted niceonly kernel (P7 candidate compaction)
# --------------------------------------------------------------------------
#
# Candidates are enumerated by index arithmetic from the CRT stride table —
# candidate i of a descriptor is n = n0 + offsets[i], where the offset table
# offsets[i] = (i // R) * M + residues[i % R] is pre-expanded ON THE HOST
# (u32, periods * M < 2^32 checked at kernel build) and laid out as dense
# (block_rows, 128) VMEM tiles. This is the TPU analog of the reference GPU's
# on-device candidate reconstruction B0 + (g/R)*M + residues[g%R]
# (nice_kernels.cu:452-457) — the host expansion replaces the div/mod, keeps
# every block a full (8, 128) VPU tile at ANY stride depth (a deep-k table
# with periods=1 would starve the sublane axis in a periods-by-residues
# layout), and costs periods*R*4 bytes of VMEM (70 KB at b40 k=1 ... 2.6 MB
# at b50 k=3).
#
# One execution processes up to STRIDED_DESC_MAX range descriptors (one per
# outer grid step; the inner grid walks offset tiles), because each
# pallas_call execution carries a fixed dispatch latency — the analog of the
# reference batching 65k ranges per launch (client_process_gpu.rs:667-682).
# Each descriptor is (n0 limbs, range-lo limbs, range-hi limbs) packed into a
# scalar-prefetched u32 row; per-descriptor nice counts land in the SMEM
# stats tile so the host re-scans only descriptors that actually hit.

STRIDED_DESC_MAX = 1024   # descriptors per execution (stats tile rows 0..7)
STRIDED_PERIODS = 128     # default stride periods per descriptor
STRIDED_PERIODS_MAX = 1024  # planning cap (span stays far below u32)
STRIDED_OFFS_LANES_MAX = 1 << 20  # offsets-table VMEM budget (4 MiB of u32)
_DESC_WIDTH = 12          # u32 fields per descriptor: n0[4] lo[4] hi[4]
# Offset rows per grid step. Committed sweep (round 4, b50 k=1 p=1024 full
# 1024-descriptor group on a v5e): max 32/64/128/256/512 ->
# 1.11/1.13/1.12/1.08/0.82 G lanes/s.
_STRIDED_BLOCK_ROWS_MAX = 64
_STRIDED_STEP_OVERHEAD_ROWS = 16  # Mosaic per-grid-step cost, in row units


class StrideSpec:
    """Hashable trace-time stride constants (modulus + residue table).

    The hash is computed once: deep tables carry ~1e5-1e6 residues and this
    object keys every lru-cached kernel lookup on the dispatch path."""

    def __init__(self, modulus: int, residues: tuple):
        assert modulus < 1 << 32
        self.modulus = modulus
        self.residues = tuple(int(r) for r in residues)
        self._hash = hash((self.modulus, self.residues))

    def __hash__(self):
        return self._hash

    def __eq__(self, other):
        return (
            isinstance(other, StrideSpec)
            and self.modulus == other.modulus
            and self.residues == other.residues
        )

    @property
    def num_residues(self) -> int:
        return len(self.residues)


def _strided_tiling(total: int) -> tuple[int, int]:
    """(rows, block_rows) for a `total`-lane offset table.

    block_rows is the per-grid-step row count: big blocks amortize Mosaic's
    per-step overhead (the original (8, 128) tiles spent ~2x the lane compute
    on grid-step overhead at 1024 lanes/step), but must divide the padded row
    count. Minimizes padded_rows + steps * overhead over a small search of
    8-row paddings."""
    rows0 = max(1, -(-total // 128))
    r8 = -(-rows0 // 8) * 8
    best = None
    for rows in range(r8, r8 + 137, 8):
        m = rows // 8
        d = max(x for x in range(1, _STRIDED_BLOCK_ROWS_MAX // 8 + 1) if m % x == 0)
        br = 8 * d
        cost = rows + (rows // br) * _STRIDED_STEP_OVERHEAD_ROWS
        if best is None or cost < best[0]:
            best = (cost, rows, br)
    _, rows, br = best
    return rows, br


def _expanded_offsets(spec: StrideSpec, periods: int) -> tuple[np.ndarray, int]:
    """Dense candidate offsets (i // R) * M + residues[i % R] for one
    descriptor span, as (rows, 128) u32 with zero padding, plus the
    block_rows each grid step consumes."""
    res = np.asarray(spec.residues, dtype=np.uint32)
    offs = (
        np.arange(periods, dtype=np.uint32)[:, None] * np.uint32(spec.modulus)
        + res[None, :]
    ).reshape(-1)
    rows, block_rows = _strided_tiling(offs.size)
    out = np.zeros(rows * 128, dtype=np.uint32)
    out[: offs.size] = offs
    return out.reshape(rows, 128), block_rows


def _make_strided_kernel(plan: BasePlan, spec: StrideSpec, periods: int,
                         block_rows: int):
    total = periods * spec.num_residues

    def kernel(nreal_ref, desc_ref, offs_ref, out_ref):
        d = pl.program_id(0)
        t = pl.program_id(1)

        @pl.when((d == 0) & (t == 0))
        def _():
            for r in range(8):
                for c in range(128):
                    out_ref[r, c] = 0

        # Descriptor groups are padded to the kernel's static num_desc so one
        # compiled shape serves every group size; padded rows (d >= n_real)
        # skip the whole lane pipeline — without this a small field's single
        # 8-descriptor group paid the full 1024-descriptor compute (~0.26 s
        # measured for what is ~2 ms of real work).
        @pl.when(d < nreal_ref[0])
        def _():
            offs = offs_ref[pl.ds(t * block_rows, block_rows), :]
            n0 = [
                jnp.full((block_rows, 128), desc_ref[d, i], dtype=jnp.uint32)
                for i in range(plan.limbs_n)
            ]
            n = ve.add_u32(n0, offs)

            idx = _block_iota(block_rows) + t * (block_rows * 128)
            lo = [desc_ref[d, 4 + i] for i in range(plan.limbs_n)]
            hi = [desc_ref[d, 8 + i] for i in range(plan.limbs_n)]
            valid = (idx < total) & ve.limbs_ge(n, lo) & ve.limbs_lt(n, hi)

            uniques = ve.num_uniques_lanes(plan, n)
            cnt = jnp.sum((valid & (uniques == plan.base)).astype(jnp.int32))
            out_ref[d // 128, d % 128] += cnt

    return kernel


@functools.lru_cache(maxsize=None)
def _strided_callable(plan: BasePlan, spec: StrideSpec, num_desc: int,
                      periods: int):
    assert num_desc <= STRIDED_DESC_MAX
    assert plan.limbs_n <= 4
    assert periods * spec.modulus < 1 << 32  # u32 offset arithmetic
    offs, block_rows = _expanded_offsets(spec, periods)
    assert offs.nbytes <= 4 * STRIDED_OFFS_LANES_MAX  # VMEM budget
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # real-descriptor count + table land in SMEM
        grid=(num_desc, offs.shape[0] // block_rows),
        in_specs=[
            # Whole offset table resident in VMEM; the kernel dynamic-slices
            # its (block_rows, 128) tile.
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (8, 128), lambda d, t, *_: (0, 0), memory_space=pltpu.SMEM
        ),
    )
    call = pl.pallas_call(
        _make_strided_kernel(plan, spec, periods, block_rows),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )

    @jax.jit
    def run(desc, n_real):
        return call(jnp.reshape(n_real, (1,)).astype(jnp.int32), desc, offs)

    return run


def niceonly_strided_batch(plan: BasePlan, spec: StrideSpec, desc: np.ndarray,
                           periods: int = STRIDED_PERIODS,
                           n_real: int | None = None):
    """Per-descriptor nice counts (i32[8,128], flattened index = descriptor row).

    desc: u32[num_desc, 12] rows of (n0 limbs[4], lo limbs[4], hi limbs[4]),
    LSW first, zero-padded. Each descriptor counts nice numbers among stride
    candidates n = n0 + p*M + residues[j] (p < periods) with lo <= n < hi.

    n_real: rows [n_real, num_desc) are padding and skip all lane compute
    (their counts are 0). Defaults to every row being real.
    """
    assert desc.ndim == 2 and desc.shape[1] == _DESC_WIDTH, desc.shape
    run = _strided_callable(plan, spec, desc.shape[0], periods)
    with _timed("niceonly_strided"):
        return run(desc, np.int32(desc.shape[0] if n_real is None else n_real))


# --------------------------------------------------------------------------
# Per-lane uniques (rare-path near-miss / nice extraction)
# --------------------------------------------------------------------------

@functools.lru_cache(maxsize=None)
def _uniques_callable(plan: BasePlan, batch_size: int, block_rows: int,
                      carry_interval: int = 0):
    assert batch_size % (block_rows * 128) == 0, (batch_size, block_rows)
    num_blocks = batch_size // (block_rows * 128)

    def kernel(start_ref, out_ref):
        step = pl.program_id(0)
        idx = _block_iota(block_rows) + step * (block_rows * 128)
        n = _derive_lanes(plan, start_ref, idx, block_rows)
        out_ref[:] = ve.num_uniques_lanes(plan, n, carry_interval)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(num_blocks,),
        in_specs=[],
        out_specs=pl.BlockSpec(
            (block_rows, 128), lambda step, *_: (step, 0), memory_space=pltpu.VMEM
        ),
    )
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((batch_size // 128, 128), jnp.int32),
        grid_spec=grid_spec,
        interpret=_interpret(),
    )

    @jax.jit
    def run(start_limbs):
        return call(start_limbs).reshape(batch_size)

    return run


def uniques_batch(plan: BasePlan, batch_size: int, start_limbs,
                  block_rows: int = BLOCK_ROWS):
    """Per-lane num_uniques for one batch (i32[batch_size])."""
    block_rows = _effective_block_rows(batch_size, block_rows)
    with _timed("uniques"):
        return _uniques_callable(plan, batch_size, block_rows)(start_limbs)


@functools.lru_cache(maxsize=None)
def _survivors_callable(plan: BasePlan, batch_size: int, thresh: int,
                        cap: int, block_rows: int):
    """Pallas twin of ve.survivors_batch: the per-lane uniques kernel plus the
    shared compaction tail fused under ONE jit, so the full uniques array
    stays in device memory — only the (count, idx[cap], uniq[cap]) compacted
    result ever crosses the bus."""
    uniques_call = _uniques_callable(plan, batch_size, block_rows)

    @jax.jit
    def run(start_limbs, valid_count):
        uniques = uniques_call(start_limbs)
        lane = jnp.arange(batch_size, dtype=jnp.int32)
        return ve.compact_survivors(
            uniques, lane < valid_count, thresh, cap
        )

    return run


def survivors_batch(plan: BasePlan, batch_size: int, thresh: int, cap: int,
                    start_limbs, valid_count, block_rows: int = BLOCK_ROWS):
    """Compacted rare-path extraction (count, idx[cap], uniq[cap]) of lanes
    with num_uniques > thresh; see ve.survivors_batch for semantics."""
    block_rows = _effective_block_rows(batch_size, block_rows)
    run = _survivors_callable(plan, batch_size, thresh, cap, block_rows)
    with _timed("survivors"):
        return run(start_limbs, valid_count)


@functools.lru_cache(maxsize=None)
def _detailed_accum_callable(plan: BasePlan, batch_size: int, block_rows: int,
                             carry_interval: int = 0, use_mxu: bool = False):
    """Detailed stats kernel folding into a device-resident accumulator
    (donated i32[base+2]); see ve.detailed_accum_batch."""
    stats_call = _stats_callable(plan, "detailed", batch_size, block_rows,
                                 carry_interval, use_mxu)
    width = plan.base + 2

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(hist_acc, start_limbs, valid_count):
        hist, nm = stats_call(start_limbs, valid_count)
        return hist_acc + hist[:width], nm

    return run


def detailed_accum_batch(plan: BasePlan, batch_size: int, hist_acc,
                         start_limbs, valid_count,
                         block_rows: int = BLOCK_ROWS,
                         carry_interval: int = 0, use_mxu: bool = False):
    """detailed_batch folded into a device-resident histogram accumulator
    (hist_acc i32[base+2], donated); returns (new_acc, near_miss_count)."""
    block_rows = _effective_block_rows(batch_size, block_rows)
    run = _detailed_accum_callable(plan, batch_size, block_rows,
                                   carry_interval, use_mxu)
    with _timed("detailed"):
        return run(hist_acc, start_limbs, valid_count)


@functools.lru_cache(maxsize=None)
def _detailed_megaloop_callable(plan: BasePlan, batch_size: int, n_iters: int,
                                block_rows: int, carry_interval: int = 0,
                                use_mxu: bool = False):
    """Megaloop twin of _detailed_accum_callable: a lax.scan around the stats
    pallas_call advances the field cursor IN-PROGRAM across n_iters batches
    and folds every histogram into the donated accumulator — one dispatch and
    one scalar readback per segment (see ve.detailed_accum_megaloop for the
    carry/masking contract)."""
    stats_call = _stats_callable(plan, "detailed", batch_size, block_rows,
                                 carry_interval, use_mxu)
    width = plan.base + 2

    @functools.partial(jax.jit, donate_argnums=(0,))
    def run(hist_acc, start_limbs, valid_total):
        def body(carry, _):
            cursor, rem, acc, nm_acc = carry
            valid = jnp.minimum(rem, jnp.int32(batch_size))
            hist, nm = stats_call(cursor, valid)
            return (ve._advance_cursor(plan, cursor, batch_size),
                    rem - valid, acc + hist[:width], nm_acc + nm), None

        init = (jnp.asarray(start_limbs, jnp.uint32),
                jnp.asarray(valid_total, jnp.int32), hist_acc, jnp.int32(0))
        (_cursor, _rem, acc, nm), _ = jax.lax.scan(body, init, None,
                                                   length=n_iters)
        return acc, nm

    return run


def detailed_accum_megaloop(plan: BasePlan, batch_size: int, n_iters: int,
                            hist_acc, start_limbs, valid_total,
                            block_rows: int = BLOCK_ROWS,
                            carry_interval: int = 0, use_mxu: bool = False):
    """n_iters batches of the detailed stats kernel folded into the donated
    hist_acc in one device program; returns (new_acc, near_miss_total)."""
    block_rows = _effective_block_rows(batch_size, block_rows)
    run = _detailed_megaloop_callable(plan, batch_size, n_iters, block_rows,
                                      carry_interval, use_mxu)
    with _timed("detailed_megaloop"):
        return run(hist_acc, start_limbs, valid_total)
