"""Near-miss cutoffs and nice-number list expand/shrink/downsample.

Mirrors reference common/src/number_stats.rs. The cutoff computation replicates
the reference's f32 arithmetic bit-for-bit (numpy float32), because e.g.
10 * 0.9_f32 rounds to exactly 9.0 while naive float64 gives 9.000000000000002
-> different floor at some bases would change which numbers are recorded.
"""

from __future__ import annotations

import math

import numpy as np

from nice_tpu.core.constants import NEAR_MISS_CUTOFF_PERCENT, SAVE_TOP_N_NUMBERS
from nice_tpu.core.types import NiceNumber, NiceNumberSimple, SubmissionRecord


def get_near_miss_cutoff(base: int) -> int:
    """floor(base as f32 * 0.9f32): numbers with MORE uniques than this are saved
    (reference number_stats.rs:15-17)."""
    return int(math.floor(float(np.float32(base) * np.float32(NEAR_MISS_CUTOFF_PERCENT))))


def expand_numbers(numbers: list[NiceNumberSimple], base: int) -> list[NiceNumber]:
    """Add derived stats (reference number_stats.rs:23-34). niceness is f32."""
    base_f32 = np.float32(base)
    return [
        NiceNumber(
            number=n.number,
            num_uniques=n.num_uniques,
            base=base,
            niceness=float(np.float32(n.num_uniques) / base_f32),
        )
        for n in numbers
    ]


def shrink_numbers(numbers: list[NiceNumber]) -> list[NiceNumberSimple]:
    """Strip derived stats (reference number_stats.rs:57-65)."""
    return [NiceNumberSimple(number=n.number, num_uniques=n.num_uniques) for n in numbers]


def downsample_numbers(submissions: list[SubmissionRecord]) -> list[NiceNumber]:
    """Aggregate all submissions' numbers; keep the top 10k by num_uniques
    (reference number_stats.rs:39-53; stable sort preserves insertion order for
    ties, matching Rust's sort_by)."""
    all_numbers: list[NiceNumber] = []
    for sub in submissions:
        all_numbers.extend(sub.numbers)
    all_numbers.sort(key=lambda n: n.num_uniques, reverse=True)
    return all_numbers[:SAVE_TOP_N_NUMBERS]
