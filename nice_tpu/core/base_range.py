"""Valid search interval per base, from number theory.

A number n is a candidate in base b only when digits_b(n^2) + digits_b(n^3) == b.
That constraint pins n to one contiguous interval per base, derived from the
b % 5 case analysis (reference common/src/base_range.rs:14-32). Python ints are
arbitrary precision, so one implementation covers every base (the reference
needs separate Natural/u128 variants).

A corollary used heavily by the TPU engine (see ops/limbs.py): within the valid
interval the digit counts of n^2 and n^3 are individually *exact* constants:

    b % 5 == 0 (k=b//5): digits(n^2) = 2k,   digits(n^3) = 3k
    b % 5 == 2:          digits(n^2) = 2k+1, digits(n^3) = 3k+1
    b % 5 == 3:          digits(n^2) = 2k+1, digits(n^3) = 3k+2
    b % 5 == 4:          digits(n^2) = 2k+2, digits(n^3) = 3k+2

which lets device kernels use fixed-trip-count digit extraction with no
leading-zero masking.
"""

from __future__ import annotations

import math
from typing import Optional

from nice_tpu.core.types import FieldSize


def floor_root(x: int, n: int) -> int:
    """Exact integer floor(x ** (1/n)) for x >= 0, n >= 1."""
    if x < 0:
        raise ValueError("floor_root of negative number")
    if n == 1 or x in (0, 1):
        return x
    if n == 2:
        return math.isqrt(x)
    # Newton's method on integers, starting from an over-estimate.
    r = 1 << -(-x.bit_length() // n)  # 2^ceil(bits/n) >= x^(1/n)
    while True:
        nxt = ((n - 1) * r + x // r ** (n - 1)) // n
        if nxt >= r:
            break
        r = nxt
    # r is now floor or at most one too high; correct downward.
    while r**n > x:
        r -= 1
    return r


def ceiling_root(x: int, n: int) -> int:
    """Exact integer ceil(x ** (1/n))."""
    r = floor_root(x, n)
    return r if r**n == x else r + 1


def get_base_range(base: int) -> Optional[tuple[int, int]]:
    """Half-open [start, end) of valid n for a base, or None when empty.

    Mirrors reference base_range.rs:14-32 (b % 5 case analysis).
    """
    b = base
    k = base // 5
    m = base % 5
    if m == 0:
        return (ceiling_root(b ** (3 * k - 1), 3), b**k)
    if m == 1:
        return None
    if m == 2:
        return (b**k, ceiling_root(b ** (3 * k + 1), 3))
    if m == 3:
        return (
            ceiling_root(b ** (3 * k + 1), 3),
            ceiling_root(b ** (2 * k + 1), 2),
        )
    if m == 4:
        return (
            ceiling_root(b ** (2 * k + 1), 2),
            ceiling_root(b ** (3 * k + 2), 3),
        )
    return None


def get_base_range_field(base: int) -> Optional[FieldSize]:
    """get_base_range as a FieldSize (reference base_range.rs:43-54)."""
    r = get_base_range(base)
    if r is None:
        return None
    return FieldSize(r[0], r[1])


def sqube_digit_counts(base: int) -> tuple[int, int]:
    """Exact (digits(n^2), digits(n^3)) for every n in the base's valid range.

    See the module docstring derivation; counts always sum to `base`.
    Raises for bases with an empty range (b % 5 == 1).
    """
    k = base // 5
    m = base % 5
    if m == 0:
        return (2 * k, 3 * k)
    if m == 2:
        return (2 * k + 1, 3 * k + 1)
    if m == 3:
        return (2 * k + 1, 3 * k + 2)
    if m == 4:
        return (2 * k + 2, 3 * k + 2)
    raise ValueError(f"base {base} has no valid range (base % 5 == 1)")
