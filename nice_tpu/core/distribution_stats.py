"""Histogram expand/shrink/aggregate and niceness mean/stdev.

Mirrors reference common/src/distribution_stats.rs. All derived floats use
numpy float32 to match the reference's f32 arithmetic.
"""

from __future__ import annotations

import numpy as np

from nice_tpu.core.types import (
    SubmissionRecord,
    UniquesDistribution,
    UniquesDistributionSimple,
)


def expand_distribution(
    distributions: list[UniquesDistributionSimple], base: int
) -> list[UniquesDistribution]:
    """Add niceness/density stats (reference distribution_stats.rs:12-27)."""
    total_count = sum(d.count for d in distributions)
    assert total_count > 0
    base_f32 = np.float32(base)
    total_f32 = np.float32(total_count)
    return [
        UniquesDistribution(
            num_uniques=d.num_uniques,
            count=d.count,
            niceness=float(np.float32(d.num_uniques) / base_f32),
            density=float(np.float32(d.count) / total_f32),
        )
        for d in distributions
    ]


def shrink_distribution(
    distribution: list[UniquesDistribution],
) -> list[UniquesDistributionSimple]:
    """Strip derived stats (reference distribution_stats.rs:94-102)."""
    return [
        UniquesDistributionSimple(num_uniques=d.num_uniques, count=d.count)
        for d in distribution
    ]


def downsample_distributions(
    submissions: list[SubmissionRecord], base: int
) -> list[UniquesDistribution]:
    """Aggregate counts per num_uniques across submissions
    (reference distribution_stats.rs:32-67)."""
    counter = [
        UniquesDistributionSimple(num_uniques=n, count=0) for n in range(base + 1)
    ]
    for sub in submissions:
        if sub.distribution is None:
            continue
        for dist in sub.distribution:
            if 0 <= dist.num_uniques <= base:
                old = counter[dist.num_uniques]
                counter[dist.num_uniques] = UniquesDistributionSimple(
                    num_uniques=old.num_uniques, count=old.count + dist.count
                )
    return expand_distribution(counter[1:], base)


def mean_stdev_from_distribution(
    distribution: list[UniquesDistribution],
) -> tuple[float, float]:
    """f32 mean and stdev of niceness weighted by count
    (reference distribution_stats.rs:75-90)."""
    count = sum(d.count for d in distribution)
    assert count > 0
    mean = np.float32(0.0)
    stdev = np.float32(0.0)
    for d in distribution:
        c = np.float32(d.count)
        nice = np.float32(d.niceness)
        mean = np.float32(mean + nice * c)
        stdev = np.float32(stdev + c * np.float32(nice * nice))
    count_f = np.float32(count)
    mean = np.float32(mean / count_f)
    stdev = np.float32(np.sqrt(np.float32(stdev / count_f - np.float32(mean * mean))))
    return (float(mean), float(stdev))
