"""Group fields into ~100 analytics chunks (reference generate_chunks.rs:20-62)."""

from __future__ import annotations

import math

from nice_tpu.core.types import FieldSize

TARGET_NUM_CHUNKS = 100.0


def group_fields_into_chunks(fields: list[FieldSize]) -> list[FieldSize]:
    """Group consecutive fields into at most TARGET_NUM_CHUNKS chunks."""
    if not fields:
        raise ValueError("fields must not be empty")
    num_fields_per_chunk = math.ceil(len(fields) / TARGET_NUM_CHUNKS)
    chunks: list[FieldSize] = []
    for i in range(0, len(fields), num_fields_per_chunk):
        group = fields[i : i + num_fields_per_chunk]
        chunks.append(FieldSize(group[0].range_start, group[-1].range_end))
    return chunks
