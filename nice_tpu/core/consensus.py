"""Field consensus: pick the canonical submission and the new check level.

Mirrors reference common/src/consensus.rs:13-73. Submissions are grouped by
their (sorted distribution, sorted numbers) content; the largest group wins and
its earliest submission becomes canon; check_level = group size + 1, capped at
255. Zero submissions resets canon and caps check_level at 1.
"""

from __future__ import annotations

from typing import Optional

from nice_tpu.core import distribution_stats, number_stats
from nice_tpu.core.types import (
    FieldRecord,
    SubmissionCandidate,
    SubmissionRecord,
)


def evaluate_consensus(
    field: FieldRecord, submissions: list[SubmissionRecord]
) -> tuple[Optional[SubmissionRecord], int]:
    """Return (canon submission or None, new check_level)."""
    if not submissions:
        return (None, min(field.check_level, 1))
    if len(submissions) == 1:
        return (submissions[0], 2)

    groups: dict[SubmissionCandidate, list[SubmissionRecord]] = {}
    for sub in submissions:
        if sub.distribution is None:
            raise ValueError(
                f"No distribution found in detailed submission #{sub.submission_id}"
            )
        distribution = distribution_stats.shrink_distribution(sub.distribution)
        distribution.sort(key=lambda d: d.num_uniques)
        numbers = number_stats.shrink_numbers(sub.numbers)
        numbers.sort(key=lambda n: n.number)
        key = SubmissionCandidate(
            distribution=tuple(distribution), numbers=tuple(numbers)
        )
        groups.setdefault(key, []).append(sub)

    majority_group = max(groups.values(), key=len)
    first_submission = min(majority_group, key=lambda s: s.submit_time)
    check_level = min(len(majority_group) + 1, 255)
    return (first_submission, check_level)
