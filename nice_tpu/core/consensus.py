"""Field consensus: pick the canonical submission and the new check level.

Mirrors reference common/src/consensus.rs:13-73. Submissions are grouped by
their (sorted distribution, sorted numbers) content; the largest group wins and
its earliest submission becomes canon; check_level = group size + 1, capped at
255. Zero submissions resets canon and caps check_level at 1.

Untrusted-client extension: callers may pass the set of submission ids that
came from below-trust-threshold clients. An untrusted submission can never
carry a field to canon ALONE — it needs a second, INDEPENDENT submission whose
content agrees (the agreeing group is its corroboration). Independence is by
client_token, not by row: duplicate submissions from one untrusted client
count once, both for the corroboration test and for check_level, so a client
that re-claims its own released field and re-submits identical content cannot
self-corroborate. With an empty untrusted set the behavior is byte-identical
to the reference.
"""

from __future__ import annotations

from typing import FrozenSet, Optional

from nice_tpu.core import distribution_stats, number_stats
from nice_tpu.core.types import (
    FieldRecord,
    SubmissionCandidate,
    SubmissionRecord,
)


def evaluate_consensus(
    field: FieldRecord,
    submissions: list[SubmissionRecord],
    untrusted_ids: FrozenSet[int] = frozenset(),
) -> tuple[Optional[SubmissionRecord], int]:
    """Return (canon submission or None, new check_level)."""
    if not submissions:
        return (None, min(field.check_level, 1))
    if len(submissions) == 1:
        if submissions[0].submission_id in untrusted_ids:
            # Needs consensus: hold at check_level 1 so the claim
            # strategies re-issue the field to an independent client.
            return (None, 1)
        return (submissions[0], 2)

    groups: dict[SubmissionCandidate, list[SubmissionRecord]] = {}
    for sub in submissions:
        if sub.distribution is None:
            raise ValueError(
                f"No distribution found in detailed submission #{sub.submission_id}"
            )
        distribution = distribution_stats.shrink_distribution(sub.distribution)
        distribution.sort(key=lambda d: d.num_uniques)
        numbers = number_stats.shrink_numbers(sub.numbers)
        numbers.sort(key=lambda n: n.number)
        key = SubmissionCandidate(
            distribution=tuple(distribution), numbers=tuple(numbers)
        )
        groups.setdefault(key, []).append(sub)

    majority_group = max(groups.values(), key=len)
    first_submission = min(majority_group, key=lambda s: s.submit_time)
    trusted_members = [
        s for s in majority_group if s.submission_id not in untrusted_ids
    ]
    untrusted_tokens = {
        s.client_token
        for s in majority_group
        if s.submission_id in untrusted_ids
    }
    vouchers = len(trusted_members) + len(untrusted_tokens)
    if not trusted_members and vouchers < 2:
        # The winning content is vouched for by exactly one client, and an
        # untrusted one: no corroboration, no canon — even if that client
        # submitted the same content more than once.
        return (None, 1)
    check_level = min(vouchers + 1, 255)
    return (first_submission, check_level)
