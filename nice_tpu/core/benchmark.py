"""Offline benchmark field definitions (reference common/src/benchmark.rs:40-76)."""

from __future__ import annotations

import enum

from nice_tpu.core import base_range
from nice_tpu.core.types import DataToClient


class BenchmarkMode(str, enum.Enum):
    BASE_TEN = "base-ten"
    DEFAULT = "default"
    LARGE = "large"
    EXTRA_LARGE = "extra-large"
    MASSIVE = "massive"
    HI_BASE = "hi-base"
    MSD_EFFECTIVE = "msd-effective"
    MSD_INEFFECTIVE = "msd-ineffective"


_BASES = {
    BenchmarkMode.BASE_TEN: 10,
    BenchmarkMode.DEFAULT: 40,
    BenchmarkMode.LARGE: 40,
    BenchmarkMode.EXTRA_LARGE: 40,
    BenchmarkMode.MASSIVE: 50,
    BenchmarkMode.HI_BASE: 80,
    BenchmarkMode.MSD_EFFECTIVE: 50,
    BenchmarkMode.MSD_INEFFECTIVE: 50,
}

_STARTS = {
    BenchmarkMode.MSD_EFFECTIVE: 26_507_984_537_059_635,
    BenchmarkMode.MSD_INEFFECTIVE: 94_760_515_586_064_977,
}

_SIZES = {
    BenchmarkMode.DEFAULT: 1_000_000,
    BenchmarkMode.LARGE: 100_000_000,
    BenchmarkMode.EXTRA_LARGE: 1_000_000_000,
    BenchmarkMode.MASSIVE: 10_000_000_000_000,
    BenchmarkMode.HI_BASE: 1_000_000_000,
    BenchmarkMode.MSD_EFFECTIVE: 1_000_000_000_000,
    BenchmarkMode.MSD_INEFFECTIVE: 10_000_000,
}


def get_benchmark_field(mode: BenchmarkMode) -> DataToClient:
    """Benchmark field as a half-open range, matching the reference exactly."""
    base = _BASES[mode]
    br = base_range.get_base_range_field(base)
    assert br is not None
    range_start = _STARTS.get(mode, br.range_start)
    range_size = _SIZES.get(mode, br.size())  # BASE_TEN: whole base range
    return DataToClient(
        claim_id=0,
        base=base,
        range_start=range_start,
        range_end=range_start + range_size,
        range_size=range_size,
    )
