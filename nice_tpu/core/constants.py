"""Global constants shared across the framework.

Values match the reference (common/src/lib.rs:33-42, number_stats.rs:5) so that
results, wire formats, and server policies are interchangeable.
"""

# Fraction of base digits that must be unique for a number to be recorded as a
# "near miss" (reference lib.rs:34). Kept as a float; the cutoff computation in
# number_stats replicates the reference's f32 rounding semantics exactly.
NEAR_MISS_CUTOFF_PERCENT = 0.9

# Minimum fraction of a chunk that must be checked before downsampled stats are
# published for it (reference lib.rs:35).
DOWNSAMPLE_CUTOFF_PERCENT = 0.2

# A claim expires (and the field becomes claimable again) after this many hours
# (reference lib.rs:36). Lease-based recovery: no heartbeats anywhere.
CLAIM_DURATION_HOURS = 1

# HTTP client request timeout (reference lib.rs:37).
CLIENT_REQUEST_TIMEOUT_SECS = 5

# Detailed runners never get a field larger than this (reference lib.rs:39-42).
DETAILED_SEARCH_MAX_FIELD_SIZE = 1_000_000_000

# Cap on nice-number lists kept after aggregation (reference number_stats.rs:5).
SAVE_TOP_N_NUMBERS = 10_000
