"""Split a base range into searchable fields (reference generate_fields.rs:14-34)."""

from __future__ import annotations

from nice_tpu.core.types import FieldSize


def break_range_into_fields(min_: int, max_: int, size: int) -> list[FieldSize]:
    """Break [min_, max_) into half-open fields of width `size` (last smaller)."""
    fields: list[FieldSize] = []
    start = min_
    end = min_
    while end < max_:
        end = min(start + size, max_)
        fields.append(FieldSize(start, end))
        start = end
    return fields
