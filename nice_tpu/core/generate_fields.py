"""Split a base range into searchable fields (reference generate_fields.rs:14-34)."""

from __future__ import annotations

from typing import Iterator

from nice_tpu.core.types import FieldSize


def iter_fields(min_: int, max_: int, size: int) -> Iterator[FieldSize]:
    """Stream [min_, max_) as half-open fields of width `size` (last smaller).

    Generator form of break_range_into_fields: seeding a wide base produces
    hundreds of thousands of fields, and the pre-generation pipeline wants to
    feed them to executemany without materializing the whole list first.
    """
    start = min_
    while start < max_:
        end = min(start + size, max_)
        yield FieldSize(start, end)
        start = end


def break_range_into_fields(min_: int, max_: int, size: int) -> list[FieldSize]:
    """Break [min_, max_) into half-open fields of width `size` (last smaller)."""
    return list(iter_fields(min_, max_, size))
