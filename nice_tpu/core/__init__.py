"""Core domain: shared types, constants, and pure number-theory math (L0)."""

from nice_tpu.core.constants import (
    CLAIM_DURATION_HOURS,
    CLIENT_REQUEST_TIMEOUT_SECS,
    DETAILED_SEARCH_MAX_FIELD_SIZE,
    DOWNSAMPLE_CUTOFF_PERCENT,
    NEAR_MISS_CUTOFF_PERCENT,
    SAVE_TOP_N_NUMBERS,
)
from nice_tpu.core.types import (
    DataToClient,
    DataToServer,
    FieldResults,
    FieldSize,
    NiceNumber,
    NiceNumberSimple,
    SearchMode,
    UniquesDistribution,
    UniquesDistributionSimple,
    ValidationData,
)
