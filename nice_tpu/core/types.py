"""Shared domain types.

Dataclass equivalents of the reference wire/DB structs (common/src/lib.rs:44-323)
with identical field names, so JSON payloads are interchangeable between this
framework and the reference's clients/servers. u128 values are plain Python
ints (arbitrary precision); JSON serialisation emits them as numbers, matching
serde_json's u128 handling.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from datetime import datetime
from typing import Any, Iterator, Optional


class SearchMode(str, enum.Enum):
    """Search modes supported by server and client (reference lib.rs:46-52)."""

    DETAILED = "Detailed"
    NICEONLY = "Niceonly"

    def __str__(self) -> str:  # display parity: "Detailed" / "Nice-only"
        return "Detailed" if self is SearchMode.DETAILED else "Nice-only"


class FieldClaimStrategy(enum.Enum):
    """How the server picks a field for a claim (reference lib.rs:64-71)."""

    NEXT = "Next"
    RANDOM = "Random"
    THIN = "Thin"


@dataclass(frozen=True)
class FieldSize:
    """Half-open search range [range_start, range_end) (reference lib.rs:85-153)."""

    range_start: int
    range_end: int

    def __post_init__(self) -> None:
        if not self.range_start < self.range_end:
            raise ValueError(
                "Range has invalid bounds, range_start must be < range_end "
                "(half-open interval)"
            )

    @property
    def range_size(self) -> int:
        return self.range_end - self.range_start

    def first(self) -> int:
        return self.range_start

    def last(self) -> int:
        return self.range_end - 1

    def start(self) -> int:
        return self.range_start

    def end(self) -> int:
        return self.range_end

    def size(self) -> int:
        return self.range_end - self.range_start

    def range_iter(self) -> Iterator[int]:
        return iter(range(self.range_start, self.range_end))

    def chunks(self, chunk_size: int) -> list["FieldSize"]:
        """Break the range into half-open chunks of at most chunk_size."""
        out = []
        start = self.range_start
        while start < self.range_end:
            end = min(start + chunk_size, self.range_end)
            out.append(FieldSize(start, end))
            start = end
        return out


@dataclass(frozen=True)
class UniquesDistributionSimple:
    """One histogram bucket: count of numbers with num_uniques unique digits."""

    num_uniques: int
    count: int


@dataclass(frozen=True)
class UniquesDistribution:
    """Extended histogram bucket with derived stats (reference lib.rs:173-179)."""

    num_uniques: int
    count: int
    niceness: float
    density: float


@dataclass(frozen=True)
class NiceNumberSimple:
    """A notably nice number (reference lib.rs:182-186)."""

    number: int
    num_uniques: int


@dataclass(frozen=True)
class NiceNumber:
    """Extended nice number with derived stats (reference lib.rs:189-195)."""

    number: int
    num_uniques: int
    base: int
    niceness: float


@dataclass
class DataToClient:
    """A field sent to the client for processing (reference lib.rs:252-258)."""

    claim_id: int
    base: int
    range_start: int
    range_end: int
    range_size: int

    def to_field_size(self) -> FieldSize:
        return FieldSize(self.range_start, self.range_end)

    @staticmethod
    def from_json(d: dict[str, Any]) -> "DataToClient":
        return DataToClient(
            claim_id=int(d["claim_id"]),
            base=int(d["base"]),
            range_start=int(d["range_start"]),
            range_end=int(d["range_end"]),
            range_size=int(d["range_size"]),
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "claim_id": self.claim_id,
            "base": self.base,
            "range_start": self.range_start,
            "range_end": self.range_end,
            "range_size": self.range_size,
        }


@dataclass
class DataToServer:
    """Compiled results sent to the server (reference lib.rs:262-268).

    submit_id / backend_downgrades are optional extensions beyond the
    reference wire format: both are omitted from the JSON when unset, so
    payloads stay byte-interchangeable with reference clients/servers that
    never heard of them. submit_id (claim id + content hash) is the
    exactly-once idempotency key; backend_downgrades records any mid-field
    engine fallbacks (e.g. "pallas->jnp") that produced these results;
    telemetry piggybacks the client's fleet snapshot (obs.telemetry) on the
    submission so the server's client_telemetry table stays fresh without
    an extra request. telemetry is attached AFTER submit_id is computed —
    it must never perturb the content hash (a recomputed submission would
    otherwise mint a new submit_id and defeat exactly-once dedup)."""

    claim_id: int
    username: str
    client_version: str
    unique_distribution: Optional[list[UniquesDistributionSimple]]
    nice_numbers: list[NiceNumberSimple]
    submit_id: Optional[str] = None
    backend_downgrades: Optional[list[str]] = None
    telemetry: Optional[dict] = None

    def to_json(self) -> dict[str, Any]:
        out = {
            "claim_id": self.claim_id,
            "username": self.username,
            "client_version": self.client_version,
            "unique_distribution": None
            if self.unique_distribution is None
            else [
                {"num_uniques": d.num_uniques, "count": d.count}
                for d in self.unique_distribution
            ],
            "nice_numbers": [
                {"number": n.number, "num_uniques": n.num_uniques}
                for n in self.nice_numbers
            ],
        }
        if self.submit_id is not None:
            out["submit_id"] = self.submit_id
        if self.backend_downgrades:
            out["backend_downgrades"] = list(self.backend_downgrades)
        if self.telemetry is not None:
            out["telemetry"] = dict(self.telemetry)
        return out

    @staticmethod
    def from_json(d: dict[str, Any]) -> "DataToServer":
        dist = d.get("unique_distribution")
        submit_id = d.get("submit_id")
        downgrades = d.get("backend_downgrades")
        return DataToServer(
            claim_id=int(d["claim_id"]),
            username=str(d["username"]),
            client_version=str(d["client_version"]),
            unique_distribution=None
            if dist is None
            else [
                UniquesDistributionSimple(int(x["num_uniques"]), int(x["count"]))
                for x in dist
            ],
            nice_numbers=[
                NiceNumberSimple(int(x["number"]), int(x["num_uniques"]))
                for x in d.get("nice_numbers", [])
            ],
            submit_id=None if submit_id is None else str(submit_id),
            backend_downgrades=None
            if downgrades is None
            else [str(x) for x in downgrades],
            telemetry=d.get("telemetry"),
        )


@dataclass
class ValidationData:
    """Field info plus canonical results for the self-check endpoint
    (reference lib.rs:274-282)."""

    base: int
    field_id: int
    range_start: int
    range_end: int
    range_size: int
    unique_distribution: list[UniquesDistributionSimple]
    nice_numbers: list[NiceNumberSimple]

    @staticmethod
    def from_json(d: dict[str, Any]) -> "ValidationData":
        return ValidationData(
            base=int(d["base"]),
            field_id=int(d["field_id"]),
            range_start=int(d["range_start"]),
            range_end=int(d["range_end"]),
            range_size=int(d["range_size"]),
            unique_distribution=[
                UniquesDistributionSimple(int(x["num_uniques"]), int(x["count"]))
                for x in d["unique_distribution"]
            ],
            nice_numbers=[
                NiceNumberSimple(int(x["number"]), int(x["num_uniques"]))
                for x in d["nice_numbers"]
            ],
        )

    def to_json(self) -> dict[str, Any]:
        return {
            "base": self.base,
            "field_id": self.field_id,
            "range_start": self.range_start,
            "range_end": self.range_end,
            "range_size": self.range_size,
            "unique_distribution": [
                {"num_uniques": d_.num_uniques, "count": d_.count}
                for d_ in self.unique_distribution
            ],
            "nice_numbers": [
                {"number": n.number, "num_uniques": n.num_uniques}
                for n in self.nice_numbers
            ],
        }


@dataclass(frozen=True)
class FieldResults:
    """Results of processing a field or chunk (reference lib.rs:319-323).

    backend_downgrades: "from->to" entries, one per mid-field engine
    fallback that contributed to these results (empty when the scan ran
    clean on the requested backend)."""

    distribution: tuple[UniquesDistributionSimple, ...]
    nice_numbers: tuple[NiceNumberSimple, ...]
    backend_downgrades: tuple[str, ...] = ()


@dataclass
class FieldRecord:
    """A field row from the DB ledger (reference lib.rs:236-247)."""

    field_id: int
    base: int
    chunk_id: Optional[int]
    range_start: int
    range_end: int
    range_size: int
    last_claim_time: Optional[datetime]
    canon_submission_id: Optional[int]
    check_level: int
    prioritize: bool


@dataclass
class ClaimRecord:
    """A claim log row (reference lib.rs:286-292).

    client_token / lease_expiry / lease_secs are the untrusted-client
    extensions: the trust identity the claim was issued to and its explicit
    lease window (None on rows minted by pre-trust servers, which follow the
    legacy global expiry cutoff only)."""

    claim_id: int
    field_id: int
    search_mode: SearchMode
    claim_time: datetime
    user_ip: str
    client_token: Optional[str] = None
    lease_expiry: Optional[datetime] = None
    lease_secs: Optional[float] = None
    # Multi-tenant scheduler routing: which named tenant this claim was
    # issued for (None on single-workload claims and pre-sched rows).
    tenant: Optional[str] = None


@dataclass
class SubmissionRecord:
    """A validated submission row (reference lib.rs:296-309)."""

    submission_id: int
    claim_id: int
    field_id: int
    search_mode: SearchMode
    submit_time: datetime
    elapsed_secs: float
    username: str
    user_ip: str
    client_version: str
    disqualified: bool
    distribution: Optional[list[UniquesDistribution]]
    numbers: list[NiceNumber]
    client_token: Optional[str] = None
    # Derived from the owning claim (claims.tenant) when the row was
    # submitted under a scheduler tenant; analytics group by it.
    tenant: Optional[str] = None


@dataclass(frozen=True)
class SubmissionCandidate:
    """Submission stripped of metadata, used as the consensus hash key
    (reference lib.rs:312-316)."""

    distribution: tuple[UniquesDistributionSimple, ...]
    numbers: tuple[NiceNumberSimple, ...]


@dataclass
class BaseRecord:
    """Aggregate per-base analytics row (reference lib.rs:198-211)."""

    base: int
    range_start: int
    range_end: int
    range_size: int
    checked_detailed: int
    checked_niceonly: int
    minimum_cl: int
    niceness_mean: Optional[float]
    niceness_stdev: Optional[float]
    distribution: list[UniquesDistribution] = field(default_factory=list)
    numbers: list[NiceNumber] = field(default_factory=list)


@dataclass
class ChunkRecord:
    """Aggregate per-chunk analytics row (reference lib.rs:214-228)."""

    chunk_id: int
    base: int
    range_start: int
    range_end: int
    range_size: int
    checked_detailed: int
    checked_niceonly: int
    minimum_cl: int
    niceness_mean: Optional[float]
    niceness_stdev: Optional[float]
    distribution: list[UniquesDistribution] = field(default_factory=list)
    numbers: list[NiceNumber] = field(default_factory=list)
