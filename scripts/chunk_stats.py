#!/usr/bin/env python
"""Per-chunk progress stats from the coordination ledger (reference
scripts/chunk_stats.rs).

Usage: python scripts/chunk_stats.py --db nice.db [--base 40]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.server.db import Db, unpad  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--db", default="nice.db")
    p.add_argument("--base", type=int, default=None)
    args = p.parse_args()
    db = Db(args.db)
    try:
        bases = [args.base] if args.base else db.get_bases()
        for base in bases:
            chunks = db.get_chunks_in_base(base)
            print(f"base {base}: {len(chunks)} chunks")
            print(f"{'chunk':>8} {'size':>14} {'checked_nice':>13} "
                  f"{'checked_det':>12} {'minimum_cl':>10}")
            for c in chunks:
                size = unpad(c["range_end"]) - unpad(c["range_start"])
                fmt = lambda v: "-" if v is None else v
                print(
                    f"{c['id']:>8} {size:>14} {fmt(c['checked_niceonly']):>13} "
                    f"{fmt(c['checked_detailed']):>12} {fmt(c['minimum_cl']):>10}"
                )
    finally:
        db.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
