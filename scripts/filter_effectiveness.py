#!/usr/bin/env python
"""Measure the kill rate of each niceonly filter on sample ranges (reference
scripts/filter_effectiveness.rs): residue (mod b-1), LSD (mod b^k), the
combined CRT stride, and the recursive MSD prefix filter.

Results are cached under scripts/.cache keyed by the SHA-256 of the
parameters (reference filter_effectiveness.rs:22-31).

Usage: python scripts/filter_effectiveness.py --base 40 --size 1000000 [--k 1]
"""

import argparse
import hashlib
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.core import base_range  # noqa: E402
from nice_tpu.core.types import FieldSize  # noqa: E402
from nice_tpu.ops import lsd_filter, msd_filter, residue_filter  # noqa: E402
from nice_tpu.ops.stride_filter import get_stride_table  # noqa: E402

CACHE_DIR = Path(__file__).resolve().parent / ".cache"


def measure(base: int, start: int, size: int, k: int) -> dict:
    rng = FieldSize(start, start + size)
    b1 = base - 1
    residues = set(residue_filter.get_residue_filter(base))
    lsd_bitmap = lsd_filter.get_valid_multi_lsd_bitmap(base, k)
    table = get_stride_table(base, k)

    residue_pass = sum(1 for n in range(start, start + size) if n % b1 in residues)
    lsd_pass = sum(1 for n in range(start, start + size) if lsd_bitmap[n % base**k])
    stride_pass = table.count_candidates(rng)

    t0 = time.monotonic()
    surviving = msd_filter.get_valid_ranges(rng, base)
    msd_time = time.monotonic() - t0
    msd_pass = sum(r.size() for r in surviving)

    return {
        "base": base,
        "start": start,
        "size": size,
        "k": k,
        "residue_survival": residue_pass / size,
        "lsd_survival": lsd_pass / size,
        "stride_survival": stride_pass / size,
        "msd_survival": msd_pass / size,
        "msd_filter_secs": round(msd_time, 4),
        "msd_surviving_ranges": len(surviving),
        "combined_survival": (msd_pass / size) * (stride_pass / size),
    }


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--base", type=int, default=40)
    p.add_argument("--size", type=int, default=1_000_000)
    p.add_argument("--start", type=int, default=None, help="default: range start")
    p.add_argument("--k", type=int, default=1)
    p.add_argument("--no-cache", action="store_true")
    args = p.parse_args()

    r = base_range.get_base_range(args.base)
    if r is None:
        print(f"base {args.base} has no valid range", file=sys.stderr)
        return 1
    start = args.start if args.start is not None else r[0]

    key = hashlib.sha256(
        json.dumps([args.base, start, args.size, args.k]).encode()
    ).hexdigest()[:16]
    cache_file = CACHE_DIR / f"filter_effectiveness_{key}.json"
    if cache_file.exists() and not args.no_cache:
        print(cache_file.read_text().strip())
        return 0

    out = measure(args.base, start, args.size, args.k)
    CACHE_DIR.mkdir(exist_ok=True)
    cache_file.write_text(json.dumps(out, indent=2) + "\n")
    print(json.dumps(out, indent=2))
    return 0


if __name__ == "__main__":
    sys.exit(main())
