"""Fleet-scale load harness: thousands of simulated clients against a real
server process.

Each simulated client is a lightweight asyncio coroutine speaking
hand-rolled HTTP/1.1 keep-alive — NOT a full engine client — running the
niceonly honor-system loop (claim -> submit, no compute), so one harness
process can drive 10k+ of them. The population mirrors the real fleet:
~80% block-mode clients (one /claim_block + one /submit_block round-trip
per --block-size fields), ~20% per-field compatibility clients
(/claim/niceonly + /submit per field). Requests pass through the
nice_tpu.faults injector at the same http.<endpoint> sites the real client
uses, with a pinned seed, so every run injects the same drops and
connection errors; dropped submit responses are replayed, exercising the
exactly-once submit_id path at scale.

Reported (JSON, one file): p50/p95/p99 claim and submit latency, request
and field throughput, error and duplicate counts, fields-per-round-trip for
block clients, a keep-alive vs fresh-connection RTT probe, and a post-run
exactly-once audit straight from the ledger (zero lost owned submissions,
zero double-canonicalized submit_ids).

Usage:
    python scripts/load_harness.py --clients 10000 --out LOAD_r01.json
    python scripts/load_harness.py --clients 200 --rounds 1   # smoke scale

Importable: tests call run_load(...) directly with a small population.
"""

from __future__ import annotations

import argparse
import asyncio
import hashlib
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from nice_tpu import CLIENT_VERSION, faults  # noqa: E402

BASE = 30  # widest practical seeded range (~494M numbers)
DEFAULT_FAULT_SPEC = (
    "http.submit_block:drop_response@0.02,"
    "http.submit:drop_response@0.02,"
    "http.claim_block:conn_error@0.01,"
    "http.claim:conn_error@0.01"
)
DEFAULT_FAULT_SEED = 1
REQUEST_ATTEMPTS = 4


def _pick_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _raise_nofile(target: int = 65536) -> None:
    """10k keep-alive sockets (plus the server's side, which inherits the
    limit through exec) need headroom over the usual 1024 soft cap."""
    try:
        import resource

        soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
        want = min(target, hard) if hard > 0 else target
        if soft < want:
            resource.setrlimit(resource.RLIMIT_NOFILE, (want, hard))
    except (ImportError, ValueError, OSError):
        pass


class MiniConn:
    """One persistent HTTP/1.1 keep-alive connection (asyncio streams).

    A stale reused socket (server closed an idle connection) gets one
    transparent reconnect, mirroring the real client transport."""

    def __init__(self, host: str, port: int):
        self.host = host
        self.port = port
        self.reader = None
        self.writer = None

    async def _connect(self) -> None:
        self.reader, self.writer = await asyncio.open_connection(
            self.host, self.port
        )

    async def close(self) -> None:
        if self.writer is not None:
            try:
                self.writer.close()
                await self.writer.wait_closed()
            except (ConnectionError, OSError):
                pass
            self.reader = self.writer = None

    async def request(self, method: str, target: str, body=None, headers=None):
        """Returns (status, parsed_json). Raises OSError on transport
        failure (after the one stale-socket reconnect). headers: extra
        request headers (the adversarial harness sets X-Client-Token)."""
        payload = b"" if body is None else json.dumps(body).encode()
        head = (
            f"{method} {target} HTTP/1.1\r\n"
            f"Host: {self.host}\r\n"
            "Accept: application/json\r\n"
        )
        for name, value in (headers or {}).items():
            head += f"{name}: {value}\r\n"
        if payload:
            head += (
                "Content-Type: application/json\r\n"
                f"Content-Length: {len(payload)}\r\n"
            )
        head += "\r\n"
        raw = head.encode() + payload
        for fresh_retry in (False, True):
            reused = self.writer is not None
            if not reused:
                await self._connect()
            try:
                self.writer.write(raw)
                await self.writer.drain()
                status_line = await self.reader.readline()
                if not status_line:
                    raise ConnectionResetError("empty response")
                status = int(status_line.split()[1])
                length = 0
                close_after = False
                while True:
                    line = await self.reader.readline()
                    if line in (b"\r\n", b"\n", b""):
                        break
                    name, _, value = line.decode("latin-1").partition(":")
                    lname = name.strip().lower()
                    if lname == "content-length":
                        length = int(value.strip())
                    elif lname == "connection":
                        close_after = value.strip().lower() == "close"
                resp_body = (
                    await self.reader.readexactly(length) if length else b""
                )
                if close_after:
                    await self.close()
                return status, (json.loads(resp_body) if resp_body else None)
            except (ConnectionError, asyncio.IncompleteReadError, OSError):
                await self.close()
                if reused and not fresh_retry:
                    continue
                raise


class Stats:
    def __init__(self):
        self.claim_lat: list[float] = []
        self.submit_lat: list[float] = []
        self.fields_claimed = 0
        self.submissions_accepted = 0
        self.duplicates = 0
        self.http_errors = 0
        self.transport_errors = 0
        self.injected = 0
        self.requests = 0
        self.claim_rtts = 0  # block + per-field claim round-trips
        self.block_fields = 0  # fields handed out by /claim_block alone
        self.block_claim_rtts = 0
        self.owned_submit_ids: list[str] = []


def _submission(claim_id: int, username: str) -> dict:
    """Honor-system niceonly payload with the real client's submit_id
    derivation (claim id + content hash)."""
    payload = {
        "claim_id": claim_id,
        "username": username,
        "client_version": CLIENT_VERSION,
        "unique_distribution": None,
        "nice_numbers": [],
    }
    content = json.dumps(payload, sort_keys=True).encode()
    payload["submit_id"] = (
        f"{claim_id}-{hashlib.sha256(content).hexdigest()[:16]}"
    )
    return payload


async def _faulted_request(
    conn: MiniConn, stats: Stats, endpoint: str, method: str, target: str,
    body=None,
):
    """One logical request with fault injection + bounded replay, mirroring
    retry_request: drop_response performs the request and discards the
    reply; conn_error skips the wire entirely. Returns (status, json) or
    None when every attempt failed."""
    for _attempt in range(REQUEST_ATTEMPTS):
        act = faults.fire(f"http.{endpoint}", target=target)
        try:
            if act == "drop_response":
                stats.injected += 1
                stats.requests += 1
                await conn.request(method, target, body)
                continue  # the reply vanished; replay
            if act in ("conn_error", "raise"):
                stats.injected += 1
                continue
            stats.requests += 1
            status, resp = await conn.request(method, target, body)
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            stats.transport_errors += 1
            continue
        if status >= 500:
            stats.http_errors += 1
            await asyncio.sleep(0.05)
            continue
        return status, resp
    return None


async def _settle_submission_reply(stats: Stats, items: list[dict], resp):
    """Account one accepted /submit- or /submit_block-style reply."""
    results = resp.get("results") if isinstance(resp, dict) else None
    if results is None:
        results = [resp] * len(items)
    for item, result in zip(items, results):
        if not isinstance(result, dict) or result.get("status") == "error":
            stats.http_errors += 1
            continue
        if result.get("duplicate"):
            stats.duplicates += 1
        else:
            stats.submissions_accepted += 1
        stats.owned_submit_ids.append(item["submit_id"])


async def _block_client(cfg, stats: Stats, sem: asyncio.Semaphore, idx: int):
    async with sem:
        conn = MiniConn(cfg["host"], cfg["port"])
        try:
            for _round in range(cfg["rounds"]):
                t0 = time.monotonic()
                got = await _faulted_request(
                    conn, stats, "claim_block", "POST", "/claim_block",
                    {
                        "mode": "niceonly",
                        "count": cfg["block_size"],
                        "username": f"load-{idx}",
                    },
                )
                stats.claim_lat.append(time.monotonic() - t0)
                if got is None or got[0] != 200:
                    stats.http_errors += got is not None
                    continue
                block = got[1]
                fields = block["fields"]
                stats.fields_claimed += len(fields)
                stats.claim_rtts += 1
                stats.block_fields += len(fields)
                stats.block_claim_rtts += 1
                subs = [
                    _submission(f["claim_id"], f"load-{idx}") for f in fields
                ]
                t0 = time.monotonic()
                got = await _faulted_request(
                    conn, stats, "submit_block", "POST", "/submit_block",
                    {"block_id": block["block_id"], "submissions": subs},
                )
                stats.submit_lat.append(time.monotonic() - t0)
                if got is None or got[0] != 200:
                    stats.http_errors += got is not None
                    continue
                await _settle_submission_reply(stats, subs, got[1])
        finally:
            await conn.close()


async def _per_field_client(
    cfg, stats: Stats, sem: asyncio.Semaphore, idx: int
):
    async with sem:
        conn = MiniConn(cfg["host"], cfg["port"])
        try:
            for _round in range(cfg["rounds"]):
                t0 = time.monotonic()
                got = await _faulted_request(
                    conn, stats, "claim", "GET",
                    f"/claim/niceonly?username=load-{idx}",
                )
                stats.claim_lat.append(time.monotonic() - t0)
                if got is None or got[0] != 200:
                    stats.http_errors += got is not None
                    continue
                stats.fields_claimed += 1
                stats.claim_rtts += 1
                sub = _submission(got[1]["claim_id"], f"load-{idx}")
                t0 = time.monotonic()
                got = await _faulted_request(
                    conn, stats, "submit", "POST", "/submit", sub
                )
                stats.submit_lat.append(time.monotonic() - t0)
                if got is None or got[0] != 200:
                    stats.http_errors += got is not None
                    continue
                await _settle_submission_reply(stats, [sub], got[1])
        finally:
            await conn.close()


async def _keepalive_probe(host: str, port: int, n: int = 50) -> dict:
    """Satellite measurement: mean /status RTT over one persistent
    connection vs a fresh TCP connection per request."""
    conn = MiniConn(host, port)
    await conn.request("GET", "/status")  # warm the status cache + socket
    t0 = time.monotonic()
    for _ in range(n):
        await conn.request("GET", "/status")
    keepalive = (time.monotonic() - t0) / n
    await conn.close()
    t0 = time.monotonic()
    for _ in range(n):
        one = MiniConn(host, port)
        await one.request("GET", "/status")
        await one.close()
    fresh = (time.monotonic() - t0) / n
    return {
        "keepalive_ms_mean": round(keepalive * 1e3, 3),
        "fresh_conn_ms_mean": round(fresh * 1e3, 3),
        "delta_ms": round((fresh - keepalive) * 1e3, 3),
    }


def _pctl(vals: list[float], q: float) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    idx = min(len(s) - 1, int(q * (len(s) - 1) + 0.5))
    return round(s[idx] * 1e3, 3)  # ms


def _seed_db(db_path: str, target_fields: int) -> int:
    from nice_tpu.core.base_range import get_base_range
    from nice_tpu.server.db import Db

    lo, hi = get_base_range(BASE)
    field_size = max(1, (hi - lo) // target_fields)
    db = Db(db_path)
    n = db.seed_base(BASE, field_size=field_size)
    db.close()
    return n


def _verify_exactly_once(db_path: str, stats: Stats) -> dict:
    """Post-run ledger audit: every owned submit_id persisted exactly once,
    and NO submit_id anywhere has two rows (the dropped-response replays
    must all have deduplicated)."""
    import sqlite3

    conn = sqlite3.connect(db_path)
    try:
        present = {
            r[0]
            for r in conn.execute(
                "SELECT submit_id FROM submissions WHERE submit_id IS NOT NULL"
            )
        }
        doubles = conn.execute(
            "SELECT COUNT(*) FROM (SELECT submit_id FROM submissions"
            " WHERE submit_id IS NOT NULL GROUP BY submit_id"
            " HAVING COUNT(*) > 1)"
        ).fetchone()[0]
    finally:
        conn.close()
    owned = set(stats.owned_submit_ids)
    lost = len(owned - present)
    return {
        "owned": len(owned),
        "lost": lost,
        "double_canonicalized": doubles,
        "violations": lost + doubles,
    }


async def _drive(cfg, stats: Stats) -> None:
    sem = asyncio.Semaphore(cfg["concurrency"])
    n_block = int(cfg["clients"] * cfg["block_share"])
    tasks = [
        asyncio.create_task(_block_client(cfg, stats, sem, i))
        for i in range(n_block)
    ]
    tasks += [
        asyncio.create_task(_per_field_client(cfg, stats, sem, i))
        for i in range(n_block, cfg["clients"])
    ]
    await asyncio.gather(*tasks)


def run_load(
    api_url: str | None = None,
    *,
    clients: int = 10_000,
    block_share: float = 0.8,
    block_size: int = 16,
    rounds: int = 1,
    concurrency: int = 500,
    fault_spec: str | None = DEFAULT_FAULT_SPEC,
    fault_seed: int = DEFAULT_FAULT_SEED,
    db_path: str | None = None,
    run_label: str = "r01",
    keep_workdir: bool = False,
) -> dict:
    """Run the harness; returns the report dict. With api_url=None a server
    subprocess is spawned on a freshly seeded ledger (db_path then names
    where to put it; default a temp dir)."""
    _raise_nofile()
    faults.configure(fault_spec, seed=fault_seed)
    workdir = None
    server = None
    logf = None
    try:
        if api_url is None:
            workdir = tempfile.mkdtemp(prefix="load-harness-")
            db_path = db_path or os.path.join(workdir, "load.db")
            expected = int(
                clients * rounds * (block_share * block_size
                                    + (1 - block_share))
            )
            seeded = _seed_db(db_path, int(expected * 1.4) + 2_000)
            port = _pick_port()
            env = dict(
                os.environ,
                NICE_TPU_MAX_INFLIGHT="4096",
                NICE_TPU_SERVER_WORKERS="32",
                JAX_PLATFORMS="cpu",
            )
            env.pop("NICE_TPU_FAULTS", None)  # faults live client-side here
            logf = open(os.path.join(workdir, "server.log"), "ab")
            server = subprocess.Popen(
                [
                    sys.executable, "-m", "nice_tpu.server",
                    "--db", db_path, "--host", "127.0.0.1",
                    "--port", str(port),
                ],
                stdout=logf, stderr=subprocess.STDOUT, env=env,
            )
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if server.poll() is not None:
                    raise RuntimeError("server subprocess died on startup")
                try:
                    with socket.create_connection(("127.0.0.1", port), 1):
                        break
                except OSError:
                    time.sleep(0.05)
            else:
                raise RuntimeError("server never started listening")
            host = "127.0.0.1"
        else:
            from urllib.parse import urlsplit

            parts = urlsplit(api_url)
            host, port = parts.hostname, parts.port or 80
            seeded = None

        cfg = {
            "host": host,
            "port": port,
            "clients": clients,
            "block_share": block_share,
            "block_size": block_size,
            "rounds": rounds,
            "concurrency": concurrency,
        }
        stats = Stats()
        t0 = time.monotonic()
        asyncio.run(_drive(cfg, stats))
        duration = time.monotonic() - t0
        probe = asyncio.run(_keepalive_probe(host, port))

        n_block = int(clients * block_share)
        report = {
            "run": run_label,
            "clients": clients,
            "block_clients": n_block,
            "per_field_clients": clients - n_block,
            "block_size": block_size,
            "rounds_per_client": rounds,
            "concurrency": concurrency,
            "fault_spec": fault_spec,
            "fault_seed": fault_seed,
            "seeded_fields": seeded,
            "duration_secs": round(duration, 2),
            "claim": {
                "count": len(stats.claim_lat),
                "p50_ms": _pctl(stats.claim_lat, 0.50),
                "p95_ms": _pctl(stats.claim_lat, 0.95),
                "p99_ms": _pctl(stats.claim_lat, 0.99),
            },
            "submit": {
                "count": len(stats.submit_lat),
                "p50_ms": _pctl(stats.submit_lat, 0.50),
                "p95_ms": _pctl(stats.submit_lat, 0.95),
                "p99_ms": _pctl(stats.submit_lat, 0.99),
            },
            "throughput": {
                "requests": stats.requests,
                "requests_per_sec": round(stats.requests / duration, 1),
                "fields_claimed": stats.fields_claimed,
                "fields_per_sec": round(stats.fields_claimed / duration, 1),
                "submissions_accepted": stats.submissions_accepted,
            },
            "fields_per_claim_rtt": round(
                stats.fields_claimed / max(1, stats.claim_rtts), 2
            ),
            "fields_per_rtt_block": round(
                stats.block_fields / max(1, stats.block_claim_rtts), 2
            ),
            "errors": {
                "http_errors": stats.http_errors,
                "transport_errors": stats.transport_errors,
                "injected_faults": stats.injected,
            },
            "duplicates": stats.duplicates,
            "keepalive_probe": probe,
        }
        if db_path and os.path.exists(db_path):
            # Give the writer actor a beat to flush its final batches.
            time.sleep(0.3)
            report["exactly_once"] = _verify_exactly_once(db_path, stats)
        return report
    finally:
        faults.configure(None)
        if server is not None:
            server.terminate()
            try:
                server.wait(timeout=10)
            except subprocess.TimeoutExpired:
                server.kill()
                server.wait()
        if logf is not None:
            logf.close()
        if workdir and not keep_workdir:
            import shutil

            shutil.rmtree(workdir, ignore_errors=True)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="load_harness")
    p.add_argument("--clients", type=int, default=10_000)
    p.add_argument("--block-share", type=float, default=0.8)
    p.add_argument("--block-size", type=int, default=16)
    p.add_argument("--rounds", type=int, default=1)
    p.add_argument("--concurrency", type=int, default=500)
    p.add_argument("--fault-spec", default=DEFAULT_FAULT_SPEC)
    p.add_argument("--fault-seed", type=int, default=DEFAULT_FAULT_SEED)
    p.add_argument("--api-url", default=None,
                   help="drive an existing server instead of spawning one")
    p.add_argument("--run-label", default="r01")
    p.add_argument("--out", default=None, help="write the JSON report here")
    args = p.parse_args(argv)
    report = run_load(
        args.api_url,
        clients=args.clients,
        block_share=args.block_share,
        block_size=args.block_size,
        rounds=args.rounds,
        concurrency=args.concurrency,
        fault_spec=args.fault_spec,
        fault_seed=args.fault_seed,
        run_label=args.run_label,
    )
    text = json.dumps(report, indent=2)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    violations = report.get("exactly_once", {}).get("violations", 0)
    return 0 if violations == 0 else 1


if __name__ == "__main__":
    raise SystemExit(main())
