#!/usr/bin/env python
"""Performance-observatory run + regression gate.

One short REAL run that exercises the whole observatory stack and writes
an ``OBSERVATORY_rNN.json`` evidence report:

  history     in-process server (port 0), driven /claim + /submit traffic,
              manual history ticks with shrunken tier widths so a ~12 s
              run rolls raw -> 1m -> 15m buckets; multi-tier payloads are
              read back over GET /history and persisted rows counted in
              the metric_history table.
  slo         the claim-latency SLO threshold is forced to 0 via its env
              override, so real traffic breaches it (ok -> page); the
              threshold is then restored operator-style to exercise the
              recovery transition (-> ok).
  stepprof    A/B engine runs: NICE_TPU_STEPPROF=0 (asserting ZERO
              profiler fences) vs =1 (per-(mode|base|backend) phase
              breakdown whose bucket sum must reconcile with measured
              wall time within 10%), plus a hot-path overhead estimate.
  regression  a fresh short ``bench.py`` suite diffed against the newest
              committed BENCH_r*.json from the SAME backend (TPU baselines
              are never compared against CPU CI runs), and a small
              ``load_harness`` run diffed against LOAD_r01.json latency.
              A >25% throughput drop or latency growth is a warning.

Exit code is 0 unless --strict is given AND a gate check failed (the CI
step runs warn-only initially, per the rollout plan).

Usage:
    python scripts/perf_gate.py --out OBSERVATORY_r01.json
    python scripts/perf_gate.py --strict            # fail CI on regression
    python scripts/perf_gate.py --skip-load --skip-bench   # observatory only
"""

from __future__ import annotations

import argparse
import glob
import hashlib
import json
import os
import statistics
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

# Observatory knobs for the short run — set BEFORE nice_tpu imports so the
# server context picks them up: manual ticks (no sampler thread), 2 s "1m"
# and 10 s "15m" buckets so every tier finalizes inside the run, and a
# claim-latency SLO threshold of zero so real traffic breaches it.
GATE_ENV = {
    "NICE_TPU_HISTORY_SECS": "3600",
    "NICE_TPU_HISTORY_1M_SECS": "2",
    "NICE_TPU_HISTORY_15M_SECS": "10",
    "NICE_TPU_SLO_CLAIM_P99_THRESHOLD": "0.0",
    # Resource observatory: memwatch samples on every history tick (the
    # 0.5 s tick cadence outruns this 1 s throttle, so ~half the ticks
    # sample); pyprof stays thread-less — the driver calls take_sample()
    # itself so the profile is deterministic per tick.
    "NICE_TPU_MEMWATCH_SECS": "1",
    "NICE_TPU_PYPROF_HZ": "0",
}
for _k, _v in GATE_ENV.items():
    os.environ[_k] = _v

REGRESSION_TOLERANCE = 0.25  # >25% worse than baseline = warn/fail

# Ticks at 0.5 s for 12.5 s: ~6 finalized 2 s buckets and at least one
# finalized 10 s bucket on every continuously-sampled series.
TICK_SECS = 0.5
TICKS = 25


def _get_json(url: str, timeout: float = 10.0):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def _post_json(url: str, body: dict, timeout: float = 10.0):
    req = urllib.request.Request(
        url,
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read().decode("utf-8"))


def _submission(claim_id: int, username: str, client_version: str) -> dict:
    """The real client's submit_id derivation (claim id + content hash)."""
    payload = {
        "claim_id": claim_id,
        "username": username,
        "client_version": client_version,
        "unique_distribution": None,
        "nice_numbers": [],
    }
    content = json.dumps(payload, sort_keys=True).encode()
    payload["submit_id"] = (
        f"{claim_id}-{hashlib.sha256(content).hexdigest()[:16]}"
    )
    return payload


# -- section 1: history + SLO against a live server -------------------------


def run_observatory(report: dict, problems: list) -> None:
    from nice_tpu import CLIENT_VERSION, obs
    from nice_tpu.server import app as server_app
    from nice_tpu.server.db import Db

    with tempfile.TemporaryDirectory(prefix="perf-gate-") as workdir:
        db_path = os.path.join(workdir, "gate.db")
        db = Db(db_path)
        # ~100 claimable fields: enough for every driving round to claim.
        db.seed_base(30, field_size=5_000_000)
        db.close()
        srv = server_app.serve(db_path, host="127.0.0.1", port=0)
        threading.Thread(
            target=srv.serve_forever, name="perf-gate-httpd", daemon=True
        ).start()
        ctx = srv.context
        base_url = f"http://127.0.0.1:{srv.server_address[1]}"
        try:
            _drive_and_tick(report, problems, base_url, ctx, CLIENT_VERSION)
            _check_history(report, problems, base_url, ctx)
            _check_slo(report, problems, base_url, ctx, obs)
        finally:
            srv.shutdown()


def _drive_and_tick(report, problems, base_url, ctx, client_version):
    """Real claim/submit/status traffic interleaved with history ticks."""
    t0 = time.monotonic()
    claims = submits = 0
    for i in range(TICKS):
        try:
            got = _get_json(f"{base_url}/claim/niceonly?username=gate-{i}")
            claims += 1
            sub = _submission(got["claim_id"], f"gate-{i}", client_version)
            _post_json(f"{base_url}/submit", sub)
            submits += 1
        except urllib.error.HTTPError:
            pass  # seeded fields can run out near the end; ticks continue
        _get_json(f"{base_url}/status")
        ctx.history_tick()
        # One profiler sweep per tick (NICE_TPU_PYPROF_HZ=0 keeps the
        # sampler thread off; driving it here makes the per-root profile
        # deterministic enough to diff against the MEMWATCH baseline).
        from nice_tpu.obs import pyprof

        pyprof.take_sample()
        time.sleep(TICK_SECS)
    report["history"]["traffic"] = {
        "claims": claims,
        "submits": submits,
        "ticks": TICKS,
        "drive_secs": round(time.monotonic() - t0, 3),
    }
    if claims < 5:
        problems.append(f"only {claims} claims succeeded while driving")


def _check_history(report, problems, base_url, ctx):
    directory = _get_json(f"{base_url}/history")
    names = directory["series"]
    report["history"]["series_count"] = directory["count"]

    # Multi-tier evidence: every continuously sampled series must have
    # raw + 1m points, and the run is long enough for 15m buckets too.
    multi, sample = 0, {}
    for name in names:
        q = urllib.parse.quote(name)
        body = _get_json(f"{base_url}/history?series={q}")
        tiers = body["series"][name]
        counts = {t: len(p) for t, p in tiers.items()}
        if counts.get("raw", 0) >= 2 and counts.get("1m", 0) >= 2:
            multi += 1
            if len(sample) < 5:
                sample[name] = tiers
    report["history"]["multi_tier_series"] = multi
    report["history"]["tier_point_counts"] = {
        n: {t: len(p) for t, p in tiers.items()} for n, tiers in sample.items()
    }
    report["history"]["sample_payload"] = sample
    if multi < 5:
        problems.append(
            f"only {multi} series have multi-tier history (need >= 5)"
        )

    persisted = ctx.db.get_metric_history_series()
    report["history"]["persisted_series"] = len(persisted)
    if not persisted:
        problems.append("history ticks persisted no metric_history rows")

    # The 404 contract the fleet UI and progress_charts rely on.
    try:
        _get_json(f"{base_url}/history?series=definitely_not_a_series")
        problems.append("/history returned 200 for an unknown series")
    except urllib.error.HTTPError as e:
        body = json.loads(e.read())
        report["history"]["unknown_series_404"] = (
            e.code == 404 and body.get("unknown") == ["definitely_not_a_series"]
        )
        if not report["history"]["unknown_series_404"]:
            problems.append("/history unknown-series 404 contract broken")


def _check_slo(report, problems, base_url, ctx, obs):
    """The forced-threshold breach must have paged; restoring the threshold
    must recover to ok — both transitions on real traffic."""
    states = {s["slo"]: s for s in ctx.slo.last()}
    claim = states.get("claim_p99")
    transitions_at_breach = ctx.slo.transitions
    report["slo"]["breach"] = claim
    if not claim or claim["state"] == "ok":
        problems.append(
            "forced claim_p99 threshold breach did not leave ok "
            f"(state={claim and claim['state']})"
        )

    # Operator-style recovery: restore a sane threshold and re-evaluate.
    for spec in ctx.slo.specs:
        if spec.name == "claim_p99":
            spec.threshold = 1e9
    recovered = {s["slo"]: s for s in ctx.slo.evaluate()}["claim_p99"]
    report["slo"]["recovered"] = recovered
    report["slo"]["transitions"] = ctx.slo.transitions
    if recovered["state"] != "ok":
        problems.append("claim_p99 did not recover to ok after restore")
    if ctx.slo.transitions < 2:
        problems.append(
            f"expected >= 2 SLO transitions, saw {ctx.slo.transitions}"
        )

    status = _get_json(f"{base_url}/status")
    report["slo"]["status_block"] = status.get("slo")
    if not status.get("slo"):
        problems.append("/status is missing the slo block")

    events = [
        e for e in obs.flight.snapshot()
        if e.get("kind") == "slo_transition"
    ]
    report["slo"]["flight_transition_events"] = len(events)


def run_resource_gate(report: dict, problems: list) -> None:
    """Resource-observatory leg: the memwatch samples taken on the section-1
    history ticks must exist, and the per-root profile collected there is
    diffed against the committed MEMWATCH_r01.json smoke baseline — a root
    whose share of samples moved by more than REGRESSION_TOLERANCE
    (absolute) means the process's time went somewhere new."""
    from nice_tpu.obs import memwatch, pyprof
    from nice_tpu.obs.series import MEM_SAMPLES

    gate = report["resources"] = {}
    gate["mem_samples"] = int(MEM_SAMPLES.value())
    gate["memwatch_summary"] = memwatch.summary()
    if gate["mem_samples"] < 2:
        problems.append(
            f"memwatch took only {gate['mem_samples']} samples across "
            f"{TICKS} history ticks (NICE_TPU_MEMWATCH_SECS=1)"
        )

    snap = pyprof.snapshot(top_k=5)
    total = snap["samples"]
    shares = {
        root: entry["samples"] / total
        for root, entry in snap["roots"].items()
    } if total else {}
    gate["pyprof"] = {
        "samples": total,
        "root_shares": {r: round(s, 4) for r, s in sorted(shares.items())},
    }
    if not total:
        problems.append("pyprof collected no samples during the drive")
        return

    try:
        baseline = json.loads((ROOT / "MEMWATCH_r01.json").read_text())
    except (OSError, ValueError):
        gate["pyprof"]["note"] = (
            "no MEMWATCH_r01.json baseline; profile-shift diff skipped"
        )
        return
    old_shares = (baseline.get("pyprof") or {}).get("root_shares")
    if not isinstance(old_shares, dict):
        gate["pyprof"]["note"] = (
            "baseline has no pyprof.root_shares; profile-shift diff starts "
            "with the next committed MEMWATCH record"
        )
        return
    shifts = {}
    for root in sorted(set(old_shares) | set(shares)):
        if root.endswith("-httpd"):
            # Harness-specific serve threads (memprof-smoke-httpd here,
            # perf-gate-httpd there) differ between runs by design.
            continue
        a = float(old_shares.get(root, 0.0))
        b = float(shares.get(root, 0.0))
        if abs(b - a) > REGRESSION_TOLERANCE:
            shifts[root] = {"baseline": round(a, 4), "current": round(b, 4)}
    gate["pyprof"]["baseline"] = "MEMWATCH_r01.json"
    gate["pyprof"]["shifted_roots"] = shifts
    for root, move in shifts.items():
        problems.append(
            f"pyprof root {root} share moved "
            f"{move['baseline']:.0%} -> {move['current']:.0%} "
            f"(> {REGRESSION_TOLERANCE:.0%} shift vs MEMWATCH baseline)"
        )


# -- section 2: device-step profiler A/B ------------------------------------


def run_stepprof(report: dict, problems: list, reps: int) -> None:
    import jax

    from nice_tpu.core.base_range import get_base_range
    from nice_tpu.core.types import FieldSize
    from nice_tpu.obs import stepprof
    from nice_tpu.ops import engine

    os.environ["NICE_TPU_HOST_NICEONLY_MAX"] = "0"  # keep niceonly on-device
    report["stepprof"]["backend"] = jax.default_backend()
    base = 30
    start, _ = get_base_range(base)
    field = FieldSize(start, start + 400_000)

    def one_detailed():
        t0 = time.monotonic()
        engine.process_range_detailed(field, base, batch_size=1 << 12)
        return time.monotonic() - t0

    # Warm the compile caches once so A/B walls compare steady-state.
    os.environ["NICE_TPU_STEPPROF"] = "0"
    one_detailed()

    stepprof.reset()
    off_walls = [one_detailed() for _ in range(reps)]
    report["stepprof"]["profiler_off"] = {
        "walls_secs": [round(w, 4) for w in off_walls],
        "mean_secs": round(statistics.mean(off_walls), 4),
        "fences": stepprof.fence_count(),
        "cumulative_keys": sorted(stepprof.cumulative()),
    }
    if stepprof.fence_count() != 0:
        problems.append(
            f"NICE_TPU_STEPPROF=0 still issued {stepprof.fence_count()} fences"
        )

    os.environ["NICE_TPU_STEPPROF"] = "1"
    stepprof.reset()
    on_walls = [one_detailed() for _ in range(reps)]
    engine.process_range_niceonly(field, base, batch_size=1 << 12)
    cum = stepprof.cumulative()
    report["stepprof"]["profiler_on"] = {
        "walls_secs": [round(w, 4) for w in on_walls],
        "mean_secs": round(statistics.mean(on_walls), 4),
        "fences": stepprof.fence_count(),
        "phase_breakdown": cum,
    }
    os.environ["NICE_TPU_STEPPROF"] = "0"

    modes = {k.split("|", 1)[0] for k in cum}
    if not {"detailed", "niceonly"} <= modes:
        problems.append(f"phase breakdown missing a mode: {sorted(modes)}")
    for key, entry in cum.items():
        bucket_sum = sum(entry[p] for p in stepprof.PHASES)
        ok = abs(bucket_sum - entry["wall"]) <= 0.10 * entry["wall"]
        report["stepprof"].setdefault("reconciliation", {})[key] = {
            "bucket_sum_secs": round(bucket_sum, 4),
            "wall_secs": round(entry["wall"], 4),
            "within_10pct": ok,
        }
        if not ok:
            problems.append(
                f"stepprof buckets for {key} sum to {bucket_sum:.3f}s "
                f"vs wall {entry['wall']:.3f}s (>10% apart)"
            )

    off_mean, on_mean = statistics.mean(off_walls), statistics.mean(on_walls)
    overhead = (on_mean - off_mean) / off_mean if off_mean else 0.0
    report["stepprof"]["overhead_frac_on_vs_off"] = round(overhead, 4)


# Absolute headroom on the feed-idle fraction before the megaloop arm counts
# as regressed: single-run CPU profiles jitter by a few points, and the gate
# must not flap on that noise.
MEGALOOP_IDLE_MARGIN = 0.10


def run_megaloop_gate(report: dict, problems: list) -> None:
    """Feed-idle gate for the megaloop (NICE_TPU_MEGALOOP).

    The megaloop exists to collapse the host-side share of a slice — the
    ``h2d_feed`` + ``host_other`` stepprof phases that the per-batch feed
    loop spends staging cursors and bookkeeping between dispatches. Profile
    the same field with the loop pinned off and on: the megaloop arm's
    feed-idle fraction must not exceed the per-batch arm's by more than the
    noise margin, and its dispatch count must actually collapse.
    """
    from nice_tpu.core.base_range import get_base_range
    from nice_tpu.core.types import FieldSize
    from nice_tpu.obs import stepprof
    from nice_tpu.obs.series import ENGINE_DISPATCHES
    from nice_tpu.ops import engine

    base = 30
    start, _ = get_base_range(base)
    field = FieldSize(start, start + 400_000)
    arms: dict = {}
    prev = os.environ.get("NICE_TPU_MEGALOOP")
    os.environ["NICE_TPU_STEPPROF"] = "1"
    try:
        for arm, pin in (("feed", "0"), ("megaloop", "1")):
            os.environ["NICE_TPU_MEGALOOP"] = pin
            engine.process_range_detailed(field, base, batch_size=1 << 12)
            stepprof.reset()
            d0 = ENGINE_DISPATCHES.value(("detailed",))
            engine.process_range_detailed(field, base, batch_size=1 << 12)
            cum = stepprof.cumulative()
            key = next(k for k in cum if k.startswith("detailed|"))
            entry = cum[key]
            idle = entry["h2d_feed"] + entry["host_other"]
            arms[arm] = {
                "wall_secs": round(entry["wall"], 4),
                "h2d_feed_secs": round(entry["h2d_feed"], 4),
                "host_other_secs": round(entry["host_other"], 4),
                "idle_frac": round(idle / entry["wall"], 4)
                if entry["wall"] else 0.0,
                "dispatches": int(
                    ENGINE_DISPATCHES.value(("detailed",)) - d0
                ),
            }
    finally:
        os.environ["NICE_TPU_STEPPROF"] = "0"
        if prev is None:
            os.environ.pop("NICE_TPU_MEGALOOP", None)
        else:
            os.environ["NICE_TPU_MEGALOOP"] = prev
    report["stepprof"]["megaloop_feed_idle"] = arms
    drift = arms["megaloop"]["idle_frac"] - arms["feed"]["idle_frac"]
    if drift > MEGALOOP_IDLE_MARGIN:
        problems.append(
            f"megaloop feed-idle regression: idle frac "
            f"{arms['megaloop']['idle_frac']:.2f} vs "
            f"{arms['feed']['idle_frac']:.2f} with the per-batch feed loop "
            f"(> +{MEGALOOP_IDLE_MARGIN:.2f} margin)"
        )
    if arms["megaloop"]["dispatches"] >= arms["feed"]["dispatches"] > 1:
        problems.append(
            f"megaloop did not collapse dispatches: "
            f"{arms['megaloop']['dispatches']} vs "
            f"{arms['feed']['dispatches']} per-batch"
        )


# -- section 3: regression gate vs committed baselines ----------------------


def _baseline_platform(bench: dict) -> str:
    cmd = bench.get("cmd", "")
    if "NICE_BENCH_PLATFORM=cpu" in cmd:
        return "cpu"
    if "NICE_BENCH_PLATFORM=" in cmd:
        return cmd.split("NICE_BENCH_PLATFORM=", 1)[1].split()[0]
    return "tpu"  # unannotated committed runs were TPU-lease runs


def _latest_bench_baseline(platform: str):
    """Newest committed BENCH_r*.json with a parseable suite from the SAME
    backend — cross-backend diffs (TPU baseline vs CPU CI) are meaningless.
    Returns (name, parsed headline) — the headline carries the suite plus,
    from rounds with the profiler on, the critpath dominant-segment block."""
    for path in sorted(glob.glob(str(ROOT / "BENCH_r*.json")), reverse=True):
        try:
            bench = json.loads(Path(path).read_text())
        except (OSError, ValueError):
            continue
        parsed = bench.get("parsed") or {}
        if not parsed.get("suite") or _baseline_platform(bench) != platform:
            continue
        return os.path.basename(path), parsed
    return None, None


def run_bench_gate(report: dict, problems: list, budget: int) -> None:
    import jax

    platform = jax.default_backend()
    baseline_name, baseline = _latest_bench_baseline(platform)
    gate = report["regression"]["bench"] = {
        "platform": platform,
        "baseline": baseline_name,
    }
    if baseline is None:
        gate["note"] = (
            f"no committed BENCH_r*.json from backend {platform!r}; "
            "throughput diff skipped"
        )
        return

    env = dict(
        os.environ,
        NICE_BENCH_PLATFORM=platform,
        NICE_BENCH_SUITE="default:detailed,msd-ineffective:niceonly",
        NICE_BENCH_BUDGET=str(budget),
        # Profiler on so the fresh headline carries a critpath block (the
        # dominant-segment shares diffed against the committed baseline).
        NICE_TPU_STEPPROF="1",
    )
    env.pop("NICE_BENCH_T0", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=ROOT, env=env, capture_output=True, text=True, timeout=budget * 4,
    )
    headline = None
    for line in reversed(proc.stdout.splitlines()):
        try:
            parsed = json.loads(line)
        except ValueError:
            continue
        if isinstance(parsed, dict) and "suite" in parsed:
            headline = parsed
            break
    if proc.returncode != 0 or headline is None:
        problems.append(
            f"gate bench run failed (rc={proc.returncode}); "
            f"tail: {proc.stdout[-300:]!r}"
        )
        gate["error"] = f"rc={proc.returncode}"
        return

    suite = headline["suite"]
    baseline_suite = baseline.get("suite") or {}
    gate["fresh_suite"] = suite
    gate["cases"] = {}
    for case, new in suite.items():
        old = baseline_suite.get(case)
        if not old or old.get("skipped") or new.get("skipped"):
            continue
        old_v, new_v = float(old["value"]), float(new["value"])
        drop = (old_v - new_v) / old_v if old_v else 0.0
        regressed = drop > REGRESSION_TOLERANCE
        gate["cases"][case] = {
            "baseline": old_v,
            "current": new_v,
            "drop_frac": round(drop, 4),
            "regressed": regressed,
        }
        if regressed:
            problems.append(
                f"bench {case}: {new_v:.0f} vs baseline {old_v:.0f} "
                f"numbers/sec/chip ({drop:.0%} drop > "
                f"{REGRESSION_TOLERANCE:.0%})"
            )
    _critpath_diff(gate, problems, baseline, headline)
    _mem_diff(gate, problems, baseline, headline)


def _mem_diff(
    gate: dict, problems: list, baseline: dict, headline: dict
) -> None:
    """Diff the bench suite's peak-RSS watermark between rounds: throughput
    can hold steady while the run quietly doubles its resident set."""
    block = gate["peak_mem"] = {}
    new_mem = headline.get("peak_mem")
    if not new_mem:
        block["note"] = "fresh run carried no peak_mem block; diff skipped"
        return
    block["current"] = new_mem
    old_mem = baseline.get("peak_mem")
    if not old_mem or not old_mem.get("peak_rss_bytes"):
        block["note"] = (
            "baseline round predates peak_mem accounting; memory diff "
            "starts with the next committed bench record"
        )
        return
    block["baseline"] = old_mem
    old_peak = float(old_mem["peak_rss_bytes"])
    new_peak = float(new_mem.get("peak_rss_bytes") or 0)
    growth = (new_peak - old_peak) / old_peak if old_peak else 0.0
    block["growth_frac"] = round(growth, 4)
    block["regressed"] = growth > REGRESSION_TOLERANCE
    if block["regressed"]:
        problems.append(
            f"bench peak RSS {new_peak / 1e6:.0f}MB vs baseline "
            f"{old_peak / 1e6:.0f}MB ({growth:.0%} growth > "
            f"{REGRESSION_TOLERANCE:.0%})"
        )


def _critpath_diff(
    gate: dict, problems: list, baseline: dict, headline: dict
) -> None:
    """Diff the bench critpath dominant-segment shares between rounds: a
    segment whose share of wall moved by more than REGRESSION_TOLERANCE
    (absolute) means the workload's bottleneck shifted — the throughput
    number alone can hide that (e.g. compute got faster while feed stalls
    grew to fill the gap)."""
    block = gate["critpath"] = {}
    new_cp = headline.get("critpath")
    if not new_cp:
        block["note"] = (
            "fresh run produced no critpath summary (profiler recorded no "
            "wall); shift diff skipped"
        )
        return
    block["current"] = new_cp
    old_cp = baseline.get("critpath")
    if not old_cp:
        block["note"] = (
            "baseline round has no critpath block; shift diff starts with "
            "the next committed bench record"
        )
        return
    block["baseline"] = old_cp
    old_shares = old_cp.get("shares") or {}
    new_shares = new_cp.get("shares") or {}
    shifts = {}
    for seg in sorted(set(old_shares) | set(new_shares)):
        a = float(old_shares.get(seg, 0.0))
        b = float(new_shares.get(seg, 0.0))
        if abs(b - a) > REGRESSION_TOLERANCE:
            shifts[seg] = {"baseline": round(a, 4), "current": round(b, 4)}
    block["shifted_segments"] = shifts
    dominant_changed = old_cp.get("dominant") != new_cp.get("dominant")
    block["dominant"] = {
        "baseline": old_cp.get("dominant"),
        "current": new_cp.get("dominant"),
        "changed": dominant_changed,
    }
    for seg, move in shifts.items():
        problems.append(
            f"critpath segment {seg} share moved "
            f"{move['baseline']:.0%} -> {move['current']:.0%} "
            f"(> {REGRESSION_TOLERANCE:.0%} shift vs baseline)"
        )


def run_load_gate(report: dict, problems: list) -> None:
    """Small load-harness run vs LOAD_r01.json latency. The committed
    baseline is a 10k-client/500-way run; this 120-client probe only trips
    on catastrophic latency regressions, by design."""
    from scripts.load_harness import run_load

    try:
        baseline = json.loads((ROOT / "LOAD_r01.json").read_text())
    except (OSError, ValueError):
        report["regression"]["load"] = {"note": "no LOAD_r01.json baseline"}
        return
    result = run_load(
        clients=120, block_share=0.8, block_size=8, rounds=1,
        concurrency=40, fault_spec=None,
    )
    gate = report["regression"]["load"] = {
        "baseline": "LOAD_r01.json",
        "baseline_clients": baseline.get("clients"),
        "probe_clients": 120,
        "note": "probe is ~100x lighter than the baseline run; only "
                "catastrophic latency regressions can trip this leg",
    }
    for op in ("claim", "submit"):
        old_p95 = float(baseline[op]["p95_ms"])
        new_p95 = float(result[op]["p95_ms"])
        regressed = new_p95 > old_p95 * (1 + REGRESSION_TOLERANCE)
        gate[op] = {
            "baseline_p95_ms": old_p95,
            "probe_p95_ms": new_p95,
            "probe_p99_ms": float(result[op]["p99_ms"]),
            "regressed": regressed,
        }
        if regressed:
            problems.append(
                f"load {op} p95 {new_p95:.0f}ms vs baseline "
                f"{old_p95:.0f}ms (>25% worse at 1/100th the load)"
            )
    gate["probe_requests_per_sec"] = result["throughput"][
        "requests_per_sec"
    ]


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--out", default="OBSERVATORY_r01.json")
    p.add_argument("--strict", action="store_true",
                   help="exit 1 on any gate problem (default: warn only)")
    p.add_argument("--reps", type=int, default=3,
                   help="engine A/B repetitions per profiler state")
    p.add_argument("--bench-budget", type=int, default=70,
                   help="wall budget (s) for the fresh bench run")
    p.add_argument("--skip-bench", action="store_true")
    p.add_argument("--skip-load", action="store_true")
    args = p.parse_args(argv)

    report: dict = {
        "run": "perf-gate",
        "generated_ts": time.time(),
        "gate_env": GATE_ENV,
        "history": {},
        "slo": {},
        "stepprof": {},
        "regression": {},
        "problems": [],
    }
    problems: list = []

    print("== observatory: history + SLO against a live server ==")
    run_observatory(report, problems)
    print("== resources: memwatch samples + profile-shift diff ==")
    run_resource_gate(report, problems)
    print("== stepprof: profiler A/B engine runs ==")
    run_stepprof(report, problems, args.reps)
    print("== stepprof: megaloop feed-idle gate ==")
    run_megaloop_gate(report, problems)
    if not args.skip_bench:
        print("== regression: fresh bench vs committed baseline ==")
        run_bench_gate(report, problems, args.bench_budget)
    if not args.skip_load:
        print("== regression: small load probe vs LOAD_r01 ==")
        run_load_gate(report, problems)

    report["problems"] = problems
    report["ok"] = not problems
    Path(args.out).write_text(json.dumps(report, indent=1, sort_keys=True))
    print(f"wrote {args.out}")
    for prob in problems:
        print(f"WARN: {prob}")
    if problems and args.strict:
        return 1
    if problems:
        print(f"{len(problems)} problem(s); warn-only (pass --strict to fail)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
