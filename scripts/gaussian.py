#!/usr/bin/env python
"""Gaussian fit of per-base uniques distributions (reference scripts/gaussian.py:
fetch base stats, compare the empirical distribution against a normal fit, and
estimate the odds of a fully-nice number per base).

Reads base stats from a ledger (--db) or a running API (--api). For each base
with recorded distribution data: fits N(mean, stdev), reports the tail
probability P(uniques == base) under the fit vs the search size needed for one
expected nice number, and optionally renders a chart per base.

Usage:
    python scripts/gaussian.py --db nice.db
    python scripts/gaussian.py --api http://127.0.0.1:8127 --plot /tmp/gauss.png
"""

from __future__ import annotations

import argparse
import json
import math
import sys
import urllib.request
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def load_bases(args) -> list[dict]:
    if args.api:
        with urllib.request.urlopen(f"{args.api}/stats/bases", timeout=30) as r:
            return json.loads(r.read())
    from nice_tpu.server.db import Db  # noqa: E402

    db = Db(args.db)
    try:
        return db.get_base_stats()
    finally:
        db.close()


def normal_sf(z: float) -> float:
    """Survival function of the standard normal (no scipy needed)."""
    return 0.5 * math.erfc(z / math.sqrt(2))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--db", default="nice.db")
    p.add_argument("--api", help="API base URL (overrides --db)")
    p.add_argument("--plot", help="write a PNG chart to this path (matplotlib)")
    args = p.parse_args()

    bases = [b for b in load_bases(args) if b.get("niceness_mean") is not None]
    if not bases:
        print("no bases with distribution stats yet (run some detailed fields)")
        return 0

    print(
        f"{'base':>5} {'mean':>9} {'stdev':>8} {'z(nice)':>8} "
        f"{'P(nice) fit':>12} {'E[search for 1]':>16}"
    )
    rows = []
    for b in bases:
        base = b["base"] if "base" in b else b["id"]
        mean = float(b["niceness_mean"]) * base  # stored as niceness fraction
        stdev = float(b["niceness_stdev"]) * base
        if stdev <= 0:
            continue
        # P(uniques >= base) under the fit, with continuity correction.
        z = (base - 0.5 - mean) / stdev
        p_nice = normal_sf(z)
        expect = (1 / p_nice) if p_nice > 0 else float("inf")
        rows.append((base, mean, stdev, z, p_nice))
        print(
            f"{base:>5} {mean:>9.3f} {stdev:>8.3f} {z:>8.2f} "
            f"{p_nice:>12.3e} {expect:>16.3e}"
        )

    if args.plot and rows:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        # One chart, one series (Okabe-Ito blue), one axis: the z-distance of
        # "fully nice" from each base's fitted mean — the headline quantity.
        fig, ax = plt.subplots(figsize=(8, 4.5))
        xs = [r[0] for r in rows]
        zs = [r[3] for r in rows]
        ax.bar(xs, zs, color="#0072B2", width=0.7)
        ax.set_xlabel("base")
        ax.set_ylabel("z-score of uniques == base under N(mean, stdev)")
        ax.set_title("How many standard deviations away is a nice number?")
        ax.grid(axis="y", color="#dddddd", linewidth=0.6)
        ax.set_axisbelow(True)
        for spine in ("top", "right"):
            ax.spines[spine].set_visible(False)
        for x, z in zip(xs, zs):
            ax.annotate(
                f"{z:.1f}", (x, z), textcoords="offset points", xytext=(0, 3),
                ha="center", fontsize=8, color="#444444",
            )
        fig.tight_layout()
        fig.savefig(args.plot, dpi=140)
        print(f"wrote {args.plot}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
