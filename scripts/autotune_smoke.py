"""Autotuner end-to-end smoke check: sweep -> persist -> reload -> invalidate.

With ``NICE_TPU_AUTOTUNE_FILE`` pointed inside the directory given as
argv[1], this script proves the full winner lifecycle on a tiny field:

1. ``ops/autotune.sweep`` times 2 configurations of one small slice through
   the scripts/tune_kernels.py harness (--json) and persists the best as the
   (mode, base, backend) winner.
2. A CHILD PROCESS (fresh interpreter — the restart the acceptance criteria
   demand) resolves the same key through engine.resolve_tuning and must get
   the swept winner back with the ``hit`` counter incremented, then run a
   real field at the tuned shape and match the scalar oracle.
3. The winner's stored plan signature is tampered (a fake jax runtime) and
   the next resolve must fall back to defaults with the ``invalidated``
   counter incremented — a stale winner is never applied.

Prints ONE JSON line; exit 0 iff every stage held. Usage:

    python scripts/autotune_smoke.py /tmp/autotune-dir
"""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_CHILD = r"""
import json, os, sys
from nice_tpu.core import base_range
from nice_tpu.core.types import FieldSize
from nice_tpu.ops import engine, scalar
from nice_tpu.obs.series import AUTOTUNE_EVENTS

hits0 = AUTOTUNE_EVENTS.value(("hit",))
bs, br, ci, use_mxu, _mega = engine.resolve_tuning("detailed", 40, "jax")
hits = AUTOTUNE_EVENTS.value(("hit",)) - hits0

lo, _hi = base_range.get_base_range(40)
rng = FieldSize(lo, lo + 512)
got = engine.process_range_detailed(rng, 40, backend="jax")
want = scalar.process_range_detailed(rng, 40)
print(json.dumps({
    "resolved": [bs, br, ci, use_mxu],
    "hits": hits,
    "field_ok": got == want,
}))
"""


def main() -> int:
    workdir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/nice-autotune-smoke"
    os.makedirs(workdir, exist_ok=True)
    winners = os.path.join(workdir, "nice_autotune.json")
    os.environ["NICE_TPU_AUTOTUNE_FILE"] = winners
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    from nice_tpu.obs.series import AUTOTUNE_EVENTS
    from nice_tpu.ops import autotune, engine

    # 1. Sweep two configurations on a small slice; persist the winner.
    won = autotune.sweep(
        "detailed", "default", "jax",
        batch_shifts=[12, 13], carry=[0], slice_size=4096, timeout=600,
    )
    stored = os.path.exists(winners)

    # 2. Fresh process: the winner must survive the restart — resolve_tuning
    # returns it (hit counter moves) and a real field runs at the tuned
    # shape, matching the scalar oracle.
    proc = subprocess.run(
        [sys.executable, "-c", _CHILD],
        capture_output=True, text=True, timeout=300,
        env=dict(os.environ),
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    try:
        child = json.loads(proc.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        child = {"error": proc.stderr[-1500:]}
    reloaded = (
        won is not None
        and child.get("hits", 0) > 0
        and child.get("resolved", [None])[0] == won.get("batch_size")
        and child.get("field_ok") is True
    )

    # 3. Tamper the stored signature: the next resolve must refuse the
    # winner (invalidated counter) and fall back to the default batch.
    with open(winners) as f:
        table = json.load(f)
    table["detailed|b40|jax"]["signature"]["runtime"] = "jax-0.0.0-nowhere"
    with open(winners, "w") as f:
        json.dump(table, f)
    autotune.reset_for_tests()
    inv0 = AUTOTUNE_EVENTS.value(("invalidated",))
    bs, _br, _ci, _mxu, _mega = engine.resolve_tuning("detailed", 40, "jax")
    invalidated = (
        AUTOTUNE_EVENTS.value(("invalidated",)) > inv0
        and bs == engine.DEFAULT_BATCH_SIZE
    )

    ok = bool(won) and stored and reloaded and invalidated
    print(json.dumps({
        "ok": ok,
        "winner": won,
        "stored": stored,
        "reloaded": reloaded,
        "child": child,
        "invalidated": invalidated,
        "winners_file": winners,
    }), flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
