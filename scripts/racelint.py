#!/usr/bin/env python3
"""racelint CLI — thread-ownership race analysis for nice_tpu.

Checks the whole tree against the declared threading contract in
``nice_tpu/analysis/threadspec.py`` (ThreadRegistry + LockSpecs +
SHARED_STATE ownership): spawn-site coverage, multi-root unguarded
mutation, lock discipline with a static/runtime order cross-check,
blocking-under-lock, writer-actor discipline, and check-then-act
atomicity. Shares nicelint's ratchet baseline and escape grammar.

Usage:
    python scripts/racelint.py                  # report vs ratchet baseline
    python scripts/racelint.py --strict         # CI gate: also fail stale
    python scripts/racelint.py --update-baseline
    python scripts/racelint.py --json out.json  # archive the full report
    python scripts/racelint.py --rules R1,R5    # run a subset
    python scripts/racelint.py --list-roots     # dump the ThreadRegistry

Exit codes: 0 clean, 1 new violations (or stale entries under --strict),
2 usage/internal error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from nice_tpu.analysis import core, threadspec  # noqa: E402
from nice_tpu.analysis import racerules  # noqa: E402
from nice_tpu.analysis.racerules import context as racectx  # noqa: E402
from nice_tpu.utils import knobs  # noqa: E402

FAMILY = ("R1", "R2", "R3", "R4", "R5", core.DEAD_SUPPRESSION_RULE)


def _list_roots() -> int:
    for root in threadspec.THREAD_ROOTS:
        locks = f" locks={','.join(root.locks)}" if root.locks else ""
        print(f"{root.name:24s} {root.kind:11s} {root.role:12s} "
              f"{root.path}:{root.spawn_scope}"
              f"{'' if root.may_block else ' no-block'}{locks}")
    print(f"{len(threadspec.THREAD_ROOTS)} roots, "
          f"{len(threadspec.LOCK_SPECS)} lock specs, "
          f"{len(threadspec.SHARED_STATE)} shared-state declarations")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=REPO_ROOT)
    ap.add_argument("--strict", action="store_true",
                    help="fail on stale baseline entries too")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite this family's slice of the shared "
                         "baseline")
    ap.add_argument("--json", metavar="PATH",
                    help="write the full report as JSON")
    ap.add_argument("--rules", metavar="IDS",
                    default=knobs.RACELINT_RULES.get(),
                    help="comma-separated R-rule subset (e.g. R1,R5)")
    ap.add_argument("--lockorder", metavar="PATH",
                    help="runtime lock-order graph for the R2 cross-check "
                         "(default docs/lockorder.json)")
    ap.add_argument("--list-roots", action="store_true",
                    help="dump the ThreadRegistry and exit")
    args = ap.parse_args(argv)

    if args.list_roots:
        return _list_roots()

    root = os.path.abspath(args.root)
    project = core.Project(root)
    ctx = racectx.build_context(root, project,
                                lockorder_path=args.lockorder)
    print(f"racelint: {ctx.report['spawn_sites']} spawn sites, "
          f"{len(threadspec.THREAD_ROOTS)} registered roots, "
          f"{ctx.report['lock_labels']} locks, "
          f"{ctx.report['shared_write_identities']} write identities, "
          f"runtime edges {ctx.report['runtime_edges']}")

    only = [r.strip().upper() for r in args.rules.split(",")] \
        if args.rules else None
    violations, used = racerules.run_race_rules(project, ctx, only=only)

    if only is None:
        # the dead-suppression audit (S1) needs every R-rule's usage data,
        # so it only runs on full (non --rules) invocations
        rrule_ids = {r for r in FAMILY if r != core.DEAD_SUPPRESSION_RULE}
        dead, _ = core.filter_allowed(
            project, core.dead_suppressions(project, rrule_ids, used))
        violations = sorted(
            violations + dead,
            key=lambda v: (v.path, v.line, v.rule, v.detail))

    baseline = core.filter_baseline(core.load_baseline(root), FAMILY)
    if only:
        baseline = core.filter_baseline(baseline, set(only))
    new, stale = core.diff_against_baseline(violations, baseline)

    if args.update_baseline:
        old = core.load_baseline(root)
        # preserve the other families' keys — the baseline file is shared
        entries = {k: v for k, v in old.items()
                   if k not in core.filter_baseline(old, FAMILY)}
        for v in violations:
            entries[v.key] = old.get(v.key, "TODO: justify or fix")
        core.save_baseline(root, entries)
        print(f"racelint: baseline rewritten ({len(new)} new, "
              f"{len(stale)} removed; other families preserved)")
        return 0

    if args.json:
        report = {
            "violations": [v.to_json() for v in violations],
            "new": [v.to_json() for v in new],
            "stale_baseline_keys": stale,
            "baselined": len(violations) - len(new),
            "registry": {
                "roots": len(threadspec.THREAD_ROOTS),
                "lock_specs": len(threadspec.LOCK_SPECS),
                "shared_state": len(threadspec.SHARED_STATE),
            },
            "context": ctx.report,
        }
        with open(args.json, "w", encoding="utf-8") as f:  # nicelint: allow A1 (CI artifact, not state)
            json.dump(report, f, indent=1, default=str)
            f.write("\n")

    for v in new:
        print(f"{v.path}:{v.line}: {v.rule}: {v.message}")
    if stale:
        print(f"racelint: {len(stale)} stale baseline entr"
              f"{'y' if len(stale) == 1 else 'ies'} (fixed violations "
              "still listed — run --update-baseline to burn them down):")
        for key in stale:
            print(f"  stale: {key}")

    baselined = len(violations) - len(new)
    print(f"racelint: {len(new)} new, {baselined} baselined, "
          f"{len(stale)} stale")
    if new:
        return 1
    if args.strict and stale:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
