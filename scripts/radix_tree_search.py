#!/usr/bin/env python
"""Digit-by-digit backtracking search with pruning (reference
scripts/radix_tree_search.rs:13-19): build candidate n most-significant-digit
first; at each partial prefix, bound the square's and cube's shared MSD
digits and prune subtrees whose forced digits already collide.

For a prefix P of length d (of D total digits of n), every completion lies in
[P * b^(D-d), (P+1) * b^(D-d)); the MSD prefix filter applied to that interval
decides whether the subtree can contain a nice number — the same test the
range filter uses (ops/msd_filter.py), driven top-down instead of by binary
subdivision.

Usage: python scripts/radix_tree_search.py --base 20 [--leaf 250]
"""

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.core import base_range  # noqa: E402
from nice_tpu.core.types import FieldSize  # noqa: E402
from nice_tpu.ops import msd_filter, scalar  # noqa: E402


def search(base: int, leaf: int) -> tuple[list[int], int, int]:
    lo, hi = base_range.get_base_range(base)
    found: list[int] = []
    visited = pruned = 0

    def recurse(start: int, end: int) -> None:
        nonlocal visited, pruned
        start, end = max(start, lo), min(end, hi)
        if start >= end:
            return
        visited += 1
        if end - start <= leaf:
            found.extend(
                n for n in range(start, end) if scalar.get_is_nice(n, base)
            )
            return
        if msd_filter.has_duplicate_msd_prefix(FieldSize(start, end), base):
            pruned += 1
            return
        # descend one radix digit: split the interval at the next digit of n
        width = 1
        while width * base < end - start:
            width *= base
        first = (start // width) * width
        child = first
        while child < end:
            recurse(child, child + width)
            child += width

    recurse(lo, hi)
    return found, visited, pruned


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--base", type=int, default=20)
    p.add_argument("--leaf", type=int, default=250)
    args = p.parse_args()
    t0 = time.monotonic()
    found, visited, pruned = search(args.base, args.leaf)
    dt = time.monotonic() - t0
    for n in found:
        print(f"nice: {n}")
    print(
        f"base {args.base}: {len(found)} nice, {visited} nodes visited, "
        f"{pruned} subtrees pruned, {dt:.2f}s"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
