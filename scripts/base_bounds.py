#!/usr/bin/env python
"""Print the valid search interval for each base (reference
scripts/base_bounds.rs): the n-range where digits(n^2)+digits(n^3) == base.

Usage: python scripts/base_bounds.py [--min 4] [--max 120]
"""

import argparse
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from nice_tpu.core import base_range  # noqa: E402


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--min", type=int, default=4)
    p.add_argument("--max", type=int, default=120)
    args = p.parse_args()
    print(f"{'base':>5} {'range_start':>28} {'range_end':>28} {'size':>14}")
    for base in range(args.min, args.max + 1):
        r = base_range.get_base_range(base)
        if r is None:
            continue
        print(f"{base:>5} {r[0]:>28} {r[1]:>28} {r[1] - r[0]:>14.3e}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
