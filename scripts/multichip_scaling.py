"""Multi-chip scaling harness: fields/sec per chip count + feed A/B + drill.

Extends the MULTICHIP_r0*.json dryruns from {n_devices, rc, ok} to real
numbers. For each requested chip count the harness re-execs itself in a
clean subprocess with that many VIRTUAL CPU devices forced before any jax
import (utils.platform.force_virtual_cpu — XLA latches the flag at init, so
chip counts cannot share a process) and measures, on the flagship detailed
pipeline (base 40):

  * synchronous baseline: NICE_TPU_FEED_DEPTH=0 — per-batch host limb
    arithmetic runs inline on the dispatch thread (the pre-pod feed);
  * pipelined: NICE_TPU_FEED_DEPTH=2 — the double-buffered feed precomputes
    batch k+1's per-slice (starts, valids) rows while batch k runs;
  * both runs are differential-checked against the scalar oracle, and the
    engine's LAST_FEED_STATS supplies the inter-dispatch idle gap p50/p95
    that proves (or disproves) the overlap;
  * at the highest chip count, a reshard drill: the fault injector kills a
    mesh device mid-field (site mesh.dispatch), the engine must downshift
    onto the survivors, and the result must stay byte-identical to the
    oracle with NO whole-field jnp/scalar downgrade.

Prints ONE JSON report line (prefixed MULTICHIP_SCALING) and optionally
writes it to --out. Usage:

    python scripts/multichip_scaling.py [--chips 1,2,4,8] [--out report.json]
    python scripts/multichip_scaling.py --worker 8   # internal: one count
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

BASE = 40  # the extra-large benchmark base (full u32x3 limb pipeline)
FIELD_SIZE = 24_576
BATCH_SIZE = 256  # per-device lanes; total lanes scale with the mesh
WORKER_TIMEOUT = 900


def _timed_field(rng, feed_depth: int) -> dict:
    """One detailed scan of rng at the given feed depth -> result + stats."""
    from nice_tpu.ops import engine

    os.environ["NICE_TPU_FEED_DEPTH"] = str(feed_depth)
    t0 = time.monotonic()
    results = engine.process_range_detailed(
        rng, BASE, backend="jax", batch_size=BATCH_SIZE
    )
    elapsed = time.monotonic() - t0
    stats = dict(engine.LAST_FEED_STATS)
    return {
        "elapsed_secs": round(elapsed, 4),
        "numbers_per_sec": round(rng.size() / elapsed, 1),
        "fields_per_sec": round(1.0 / elapsed, 4),
        "dispatches": stats.get("dispatches", 0),
        "idle_p50_us": round(1e6 * stats.get("idle_p50", 0.0), 1),
        "idle_p95_us": round(1e6 * stats.get("idle_p95", 0.0), 1),
        "idle_total_secs": round(stats.get("idle_total", 0.0), 4),
        "feed_depth": stats.get("feed_depth", feed_depth),
        "_results": results,
    }


def _reshard_drill(rng, want) -> dict:
    """Kill a device mid-field; the run must downshift and stay exact."""
    from nice_tpu import faults
    from nice_tpu.ops import engine
    from nice_tpu.parallel import mesh as pmesh

    os.environ["NICE_TPU_FEED_DEPTH"] = "2"
    try:
        faults.configure("mesh.dispatch:dead@3")
        t0 = time.monotonic()
        results = engine.process_range_detailed(
            rng, BASE, backend="jax", batch_size=BATCH_SIZE
        )
        elapsed = time.monotonic() - t0
    finally:
        faults.reset()
        pmesh.heal_devices()
    stats = dict(engine.LAST_FEED_STATS)
    return {
        "elapsed_secs": round(elapsed, 4),
        "reshards": stats.get("reshards", 0),
        "reshard_secs": round(stats.get("reshard_secs", 0.0), 4),
        "n_dev_start": stats.get("n_dev_start", 0),
        "n_dev_end": stats.get("n_dev_end", 0),
        "byte_identical": (
            results.distribution == want.distribution
            and results.nice_numbers == want.nice_numbers
        ),
        "downgrades": list(results.backend_downgrades),
        "ok": (
            results.distribution == want.distribution
            and results.nice_numbers == want.nice_numbers
            and not results.backend_downgrades
            and stats.get("reshards", 0) >= 1
        ),
    }


def measure(n_devices: int, drill: bool = True) -> dict:
    """Measure one chip count in THIS process (n_devices must be visible)."""
    import jax

    from nice_tpu.core import base_range
    from nice_tpu.core.types import FieldSize
    from nice_tpu.ops import engine, scalar

    visible = len(jax.devices())
    assert visible >= n_devices, f"need {n_devices} devices, have {visible}"
    lo, hi = base_range.get_base_range(BASE)
    rng = FieldSize(lo, min(lo + FIELD_SIZE, hi))
    want = scalar.process_range_detailed(rng, BASE)

    # Compile outside the timed windows; both depths share the executables.
    # warm_detailed covers the per-batch steps, the untimed full pass the
    # rest (fold, rare-scan survivors) — so the sync-vs-pipelined A/B
    # measures feed overlap, not whoever-went-first paying Mosaic/XLA.
    engine.warm_detailed(BASE, batch_size=BATCH_SIZE, backend="jax")
    _timed_field(rng, feed_depth=0)

    sync = _timed_field(rng, feed_depth=0)
    pipelined = _timed_field(rng, feed_depth=2)
    out = {
        "n_devices": n_devices,
        "base": BASE,
        "field_size": rng.size(),
        "batch_size": BATCH_SIZE,
        "oracle_match": all(
            r["_results"].distribution == want.distribution
            and r["_results"].nice_numbers == want.nice_numbers
            for r in (sync, pipelined)
        ),
    }
    for r in (sync, pipelined):
        del r["_results"]
    out["sync"] = sync
    out["pipelined"] = pipelined
    # Only a real mesh (>1 device) has an inter-dispatch feed to drill.
    if drill and n_devices > 1:
        out["reshard_drill"] = _reshard_drill(rng, want)
    return out


def _run_worker(n: int) -> dict:
    """Re-exec this script for one chip count under a forced virtual mesh."""
    from nice_tpu.utils.platform import force_virtual_cpu

    env = dict(os.environ)
    force_virtual_cpu(env, max(n, 1))
    # This harness measures the per-BATCH feed (idle gaps between
    # dispatches, sync-vs-double-buffered A/B) and the dead@3 reshard
    # drill indexes per-batch dispatches; the megaloop would collapse the
    # field below the drill index at 8 devices. Megaloop reshard coverage
    # lives in test_megaloop.py's mid-slice downshift tests.
    env["NICE_TPU_MEGALOOP"] = "0"
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--worker", str(n)],
        env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        capture_output=True,
        text=True,
        timeout=WORKER_TIMEOUT,
    )
    for line in reversed(proc.stdout.splitlines()):
        if line.startswith("MULTICHIP_WORKER "):
            return json.loads(line[len("MULTICHIP_WORKER "):])
    return {
        "n_devices": n,
        "error": f"worker rc={proc.returncode}",
        "tail": (proc.stdout + proc.stderr)[-2000:],
        "oracle_match": False,
    }


def build_report(chips: list[int]) -> dict:
    per_chip = [_run_worker(n) for n in chips]
    ok = all(c.get("oracle_match") for c in per_chip)
    baseline = next(
        (c for c in per_chip if "error" not in c and c["n_devices"] == 1), None
    )
    for c in per_chip:
        if "error" in c:
            continue
        if baseline is not None:
            c["speedup_vs_1"] = round(
                c["pipelined"]["numbers_per_sec"]
                / baseline["pipelined"]["numbers_per_sec"], 3,
            )
        drill = c.get("reshard_drill")
        if drill is not None and not drill["ok"]:
            ok = False
    return {
        "harness": "multichip_scaling",
        "base": BASE,
        "field_size": FIELD_SIZE,
        "batch_size": BATCH_SIZE,
        "chips": per_chip,
        "ok": ok,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--chips", default="1,2,4,8",
                   help="comma-separated virtual chip counts")
    p.add_argument("--out", default="", help="also write the report here")
    p.add_argument("--worker", type=int, default=0,
                   help=argparse.SUPPRESS)  # internal: measure one count
    args = p.parse_args(argv)

    if args.worker:
        import jax

        jax.config.update("jax_platforms", "cpu")
        data = measure(args.worker)
        print("MULTICHIP_WORKER " + json.dumps(data))
        return 0

    chips = sorted({int(c) for c in args.chips.split(",") if c.strip()})
    report = build_report(chips)
    line = json.dumps(report, indent=2)
    print("MULTICHIP_SCALING " + json.dumps(report))
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
